"""Paged KV cache: fixed-size HBM blocks + per-request block tables.

Dense per-request KV caches fragment HBM under heterogeneous sequence
lengths: a (B, kvH, Tmax, D) cache reserves Tmax positions for every
row, so a 32-token request pins the same memory as a 2048-token one and
the batch dimension must be rebuilt (recompile + realloc) whenever the
request mix changes. The paged layout (vLLM's PagedAttention scheme)
pools ALL cache memory into ``num_blocks`` fixed-size blocks of
``block_size`` token positions each, per layer:

    k_pages, v_pages : (num_blocks, kvH, block_size, D)

and gives each request a BLOCK TABLE — logical block ``i`` of its
sequence lives at physical page ``table[i]``. Requests allocate blocks
one at a time as they grow and return them on completion/eviction, so
the only unusable memory is the tail of each request's last block
(< block_size tokens): internal fragmentation is bounded and external
fragmentation is zero by construction. The attention side
(``nn.Attention.decode_paged``) scatters new K/V through the table and
attends over the gathered logical view.

Block 0 is the reserved NULL block: unallocated table entries and the
padded slots of a partially-filled decode bucket all point there, so a
padded row's writes land in garbage space that no real row ever reads.

Block SHARING (ISSUE 12): every allocated block carries a reference
count. A block referenced once is private (its owner may write it); a
block referenced more than once — adopted into several requests' tables
by the prefix cache (``prefix_cache.PrefixCache``), or pinned by the
cache itself — is READ-ONLY: the ledger's copy-on-write primitive
(:meth:`fork_blocks`) gives an owner a private device copy before its
first divergent write. ``free``/``defrag``/eviction are all
refcount-aware — a physical page returns to the free list only when its
LAST referent lets go, and defrag moves a shared page ONCE, rewriting
every owner's table plus the prefix-cache index (remap listeners). That
is what stores a shared 4k-token system prompt once per replica instead
of once per request.

Accounting is exported live (``serve/kv_*`` gauges/counters — see
docs/OBSERVABILITY.md) and the block ledger is the engine's admission
authority: a request is only admitted when its worst-case block need
(prompt + max_new_tokens + speculative overshoot, MINUS the blocks a
prefix hit adopts, PLUS the copy-on-write forks its warm plan will
take) fits the free list, so a decode step can never fail mid-flight on
cache exhaustion.

GEMM M-class note (the continuous-batching bitwise gate): XLA CPU
lowers total-row-count-1 matmuls to a gemv kernel whose accumulation
differs in the last ulp from the gemm used for >= 2 rows; all >= 2-row
shapes agree bitwise row-for-row (measured, tests/test_serving_lm.py).
The decode scheduler therefore never dispatches a 1-row program — the
active-row bucket floor is 2 — which is what makes a request's tokens
bitwise-identical whether it decodes alone or mid-swarm.
"""
from __future__ import annotations

import hashlib
import queue
import threading
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..observability import health as _health
from ..parallel import chaos as _chaos
from ..parallel.failure import TransientDeviceError


class KVCacheOOM(RuntimeError):
    """The free list cannot cover a requested allocation. Typed so the
    scheduler's admission control can defer (keep the request queued)
    rather than fail it."""


class HostPoolOOM(RuntimeError):
    """The host block pool cannot cover a spill reservation. Typed so
    spill call sites DEGRADE — drop the coldest spilled chains, or skip
    the spill entirely (eviction then discards pages exactly like the
    pre-tier behavior) — instead of failing the admission path over an
    optimization."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // int(block_size))


class PagedKVCache:
    """Pooled block storage + the host-side block ledger for one model.

    Pages are functional jax arrays: the compiled decode step takes the
    current pages as inputs and returns updated ones; the scheduler
    stores the new handles back via :meth:`set_pages`. The ledger
    (free list, per-owner block lists, per-block refcounts) is plain
    host state guarded by a lock — allocation never touches the device.

    Sharing contract: a block with refcount 1 belongs to exactly one
    referent and may be written; refcount >= 2 means the page is shared
    (prefix-cache entries and/or several owners' tables point at it)
    and is read-only — callers must :meth:`fork_blocks` before writing.
    """

    def __init__(self, model, *, num_blocks: int, block_size: int = 16,
                 max_blocks_per_seq: int, dtype=jnp.float32,
                 metric_prefix: str = "serve/kv",
                 sharding=None):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (block 0 is the "
                             f"reserved null block), got {num_blocks}")
        if block_size < 2 or (block_size & (block_size - 1)):
            # power of two keeps the prompt-bucket math exact (prompt
            # buckets are pow2 >= block_size, so padded prefill always
            # fills whole blocks) and the //, % in the scatter cheap
            raise ValueError(f"block_size must be a power of two >= 2, "
                             f"got {block_size}")
        if max_blocks_per_seq < 1:
            raise ValueError("max_blocks_per_seq must be >= 1")
        attn = model.blocks[0].attn
        # the gauge/counter namespace — a second cache in one engine
        # (the speculative draft's) must not overwrite the target's
        # ledger telemetry
        self.metric_prefix = metric_prefix
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.max_seq_len = self.max_blocks_per_seq * self.block_size
        kvh = attn._kvh()
        d = model.hidden_size // attn.num_heads
        # page geometry, exported so a cross-process KV handoff can be
        # validated against the RECEIVING pool before any page lands
        # (serving/fleet.py refuses a mismatched handoff typed)
        self.kv_heads = int(kvh)
        self.head_dim = int(d)
        self.n_layers = len(model.blocks)
        self.page_dtype = jnp.dtype(dtype)

        def _zeros():
            z = jnp.zeros((num_blocks, kvh, block_size, d), dtype)
            # mesh-sharded serving: the pooled pages live on the mesh
            # (kvH split over the model axis when it divides — the
            # decode-path HBM lever under tensor parallelism); the
            # compiled step's functional update keeps the placement
            return z if sharding is None else jax.device_put(z, sharding)
        self._pages = [(_zeros(), _zeros()) for _ in model.blocks]
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._owned: Dict[object, List[int]] = {}
        self._refs: Dict[int, int] = {}   # physical id -> reference count
        self._high_water = 0
        self._lock = threading.Lock()
        self._remap_listeners: List[Callable[[dict], None]] = []
        self._set_gauges()

    # -- device pages ----------------------------------------------------

    def pages(self):
        """The per-layer [(k_pages, v_pages), ...] pytree the compiled
        decode step reads AND replaces (functional update)."""
        return self._pages

    def set_pages(self, new_pages):
        self._pages = new_pages

    # -- ledger ----------------------------------------------------------

    def blocks_free(self) -> int:
        with self._lock:
            return len(self._free)

    def blocks_in_use(self) -> int:
        """UNIQUE physical blocks with at least one referent — a block
        shared by ten tables (and/or the prefix cache) counts once:
        that is the stored-once-per-replica accounting."""
        with self._lock:
            return self.num_blocks - 1 - len(self._free)

    def shared_blocks(self) -> int:
        """Blocks with refcount >= 2 (prefix-cache sharing in effect)."""
        with self._lock:
            return sum(1 for r in self._refs.values() if r >= 2)

    def owned(self, owner) -> int:
        """Blocks currently in ``owner``'s table (0 when unknown)."""
        with self._lock:
            return len(self._owned.get(owner, ()))

    def block_refs(self, block: int) -> int:
        """Current refcount of a physical block (0 = free/unknown)."""
        with self._lock:
            return self._refs.get(int(block), 0)

    def can_allocate(self, n_blocks: int) -> bool:
        with self._lock:
            return n_blocks <= len(self._free)

    def ensure_capacity(self, owner, upto_tokens: int):
        """Grow ``owner``'s allocation so positions ``0..upto_tokens-1``
        fit. Raises :class:`KVCacheOOM` (allocating NOTHING) when the
        free list can't cover the growth, and ``ValueError`` past the
        table width — admission control checks both up front. Blocks an
        owner ADOPTED from the prefix cache count toward its capacity:
        only the private tail is newly allocated."""
        need = blocks_for_tokens(upto_tokens, self.block_size)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"{upto_tokens} tokens need {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq} "
                f"(max_seq_len {self.max_seq_len})")
        with self._lock:
            have = self._owned.setdefault(owner, [])
            grow = need - len(have)
            if grow <= 0:
                return
            if grow > len(self._free):
                if not have:    # don't leave an empty ledger entry behind
                    self._owned.pop(owner, None)
                in_use = self.num_blocks - 1 - len(self._free)
                raise KVCacheOOM(
                    f"need {grow} blocks, {len(self._free)} free "
                    f"(in use {in_use}/{self.num_blocks - 1})")
            for _ in range(grow):
                b = self._free.pop()
                self._refs[b] = 1
                have.append(b)
            in_use = self.num_blocks - 1 - len(self._free)
            self._high_water = max(self._high_water, in_use)
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_allocs").inc(grow)
        self._set_gauges()

    def adopt(self, owner, blocks: Sequence[int]):
        """Prefix-cache hit: append already-resident SHARED blocks to
        ``owner``'s table (refcount +1 each — the pages are not copied,
        that is the point). The adopted prefix must land before any
        private growth: adoption is refused once the owner holds
        blocks."""
        blocks = [int(b) for b in blocks]
        with self._lock:
            have = self._owned.setdefault(owner, [])
            if have:
                raise ValueError(
                    f"adopt() must precede private allocation — owner "
                    f"{owner!r} already holds {len(have)} blocks")
            for b in blocks:
                if self._refs.get(b, 0) < 1:
                    raise ValueError(f"block {b} is not live — a prefix "
                                     "entry outlived its page")
                self._refs[b] += 1
            have.extend(blocks)
        self._set_gauges()

    def retain(self, blocks: Sequence[int]):
        """Ownerless references (the prefix cache pinning its entries'
        pages): refcount +1 each, no table. All-or-nothing: a dead
        block in the list refuses the WHOLE retain before any count
        moves (a partial retain would pin the earlier blocks forever —
        nobody holds a handle to release them)."""
        with self._lock:
            ids = [int(b) for b in blocks]
            for b in ids:
                if self._refs.get(b, 0) < 1:
                    raise ValueError(f"cannot retain free block {b}")
            for b in ids:
                self._refs[b] += 1
        self._set_gauges()

    def release(self, blocks: Sequence[int]) -> int:
        """Drop ownerless references. A release past refcount zero is
        REFUSED (raises ``ValueError``) — the double-free would hand one
        physical page to two future owners. Returns how many blocks hit
        refcount 0 and went back to the free list."""
        freed = 0
        with self._lock:
            for b in blocks:
                b = int(b)
                r = self._refs.get(b, 0)
                if r < 1:
                    raise ValueError(
                        f"double-free refused: block {b} has no live "
                        "references")
                if r == 1:
                    del self._refs[b]
                    self._free.append(b)
                    freed += 1
                else:
                    self._refs[b] = r - 1
        if freed and obs.enabled():
            obs.counter(f"{self.metric_prefix}_frees").inc(freed)
        self._set_gauges()
        return freed

    def free(self, owner) -> int:
        """Drop every reference ``owner``'s table holds (the completion/
        eviction path). Private blocks return to the free list; shared
        blocks just lose one referent and live on (the prefix cache or
        another request still reads them). Returns the number of table
        entries released; unknown owners free 0 (idempotent —
        double-eviction is a no-op)."""
        returned = 0
        with self._lock:
            blocks = self._owned.pop(owner, [])
            # LIFO reuse keeps the hot end of the pool dense
            for b in reversed(blocks):
                r = self._refs.get(b, 0)
                if r <= 1:
                    self._refs.pop(b, None)
                    self._free.append(b)
                    returned += 1
                else:
                    self._refs[b] = r - 1
        if returned and obs.enabled():
            obs.counter(f"{self.metric_prefix}_frees").inc(returned)
        self._set_gauges()
        return len(blocks)

    def truncate(self, owner, keep_tokens: int) -> int:
        """Drop the TAIL of ``owner``'s table past ``keep_tokens``
        positions — the ledger half of a per-row rollback. The batched
        speculative path rolls a row back POSITIONALLY (the host-side
        position counter retreats to the accepted length and the next
        round's writes overwrite the rejected pages — no device work),
        keeping its worst-case reservation intact so later rounds can
        never OOM mid-flight; ``truncate`` is the complementary
        primitive for callers that want the overshoot capacity BACK
        (e.g. shrinking a finished-early row before handing its slot
        over). Refcount-aware like :meth:`free`: private tail blocks
        return to the free list, shared ones just lose this owner's
        reference. Returns the number of table entries dropped;
        idempotent past the current allocation."""
        keep = (blocks_for_tokens(keep_tokens, self.block_size)
                if keep_tokens > 0 else 0)
        returned = 0
        with self._lock:
            have = self._owned.get(owner)
            if have is None or len(have) <= keep:
                return 0
            tail = have[keep:]
            del have[keep:]
            for b in reversed(tail):
                r = self._refs.get(b, 0)
                if r <= 1:
                    self._refs.pop(b, None)
                    self._free.append(b)
                    returned += 1
                else:
                    self._refs[b] = r - 1
        if returned and obs.enabled():
            obs.counter(f"{self.metric_prefix}_frees").inc(returned)
        self._set_gauges()
        return len(tail)

    def fork_blocks(self, owner, idxs: Sequence[int]) -> List[int]:
        """COPY-ON-WRITE: replace the given logical indices of
        ``owner``'s table with private copies wherever the current
        physical block is shared (refcount >= 2). One device dispatch
        per layer copies all forked pages at once. Already-private
        indices are left alone. Returns the logical indices actually
        forked. Raises :class:`KVCacheOOM` when the free list cannot
        cover the forks — admission control reserves fork headroom
        up front precisely so this never fires mid-flight."""
        _chaos.maybe_fire("kv/cow_fork")
        moves = []                     # (src_physical, dst_physical)
        forked: List[int] = []
        with self._lock:
            have = self._owned.get(owner)
            if have is None:
                raise ValueError(f"unknown owner {owner!r}")
            want = [i for i in idxs
                    if i < len(have) and self._refs.get(have[i], 0) >= 2]
            if not want:
                return []
            if len(want) > len(self._free):
                raise KVCacheOOM(
                    f"copy-on-write fork needs {len(want)} blocks, "
                    f"{len(self._free)} free")
            for i in want:
                src = have[i]
                dst = self._free.pop()
                self._refs[dst] = 1
                self._refs[src] -= 1
                have[i] = dst
                moves.append((src, dst))
                forked.append(i)
            srcs = jnp.asarray([s for s, _ in moves], jnp.int32)
            dsts = jnp.asarray([d for _, d in moves], jnp.int32)
            self._pages = [
                (k.at[dsts].set(k[srcs]), v.at[dsts].set(v[srcs]))
                for k, v in self._pages]
            in_use = self.num_blocks - 1 - len(self._free)
            self._high_water = max(self._high_water, in_use)
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_cow_forks").inc(len(moves))
        self._set_gauges()
        return forked

    # -- cross-process handoff (ISSUE 15) --------------------------------

    def geometry(self) -> dict:
        """The page-shape contract two pools must agree on before a
        handoff: per-layer pages are ``(n, kv_heads, block_size,
        head_dim)`` of ``page_dtype`` across ``n_layers`` layers."""
        return {"n_layers": self.n_layers, "kv_heads": self.kv_heads,
                "block_size": self.block_size, "head_dim": self.head_dim,
                "dtype": np.dtype(self.page_dtype).str}

    def export_blocks(self, owner=None, blocks=None):
        """Host-fetch the K/V pages behind ``owner``'s table (or an
        explicit physical-block list — the prefix cache's chain) for a
        cross-process handoff: per layer one ``(n, kvH, bs, D)`` pair of
        numpy arrays, in logical order. The ids and page HANDLES are
        captured together under the ledger lock, so a concurrent defrag
        (which swaps in new page handles after moving data) cannot tear
        the view — the captured handles still hold every byte the
        captured ids name. The device fetch itself happens outside the
        lock; exporting dead blocks is refused. Returns
        ``(block_ids, [(k_np, v_np), ...])``."""
        with self._lock:
            if blocks is None:
                if owner is None:
                    raise ValueError("export_blocks needs owner= or "
                                     "blocks=")
                ids = list(self._owned.get(owner, ()))
            else:
                ids = [int(b) for b in blocks]
            for b in ids:
                if self._refs.get(b, 0) < 1:
                    raise ValueError(
                        f"cannot export dead block {b} — the handle "
                        "outlived its page")
            pages = list(self._pages)
        if not ids:
            return [], []
        idx = jnp.asarray(ids, jnp.int32)
        out = []
        for k, v in pages:
            # deliberate host fetch: the handoff's one data-plane hop —
            # raw page bytes, no per-element serialization
            out.append((np.asarray(jax.device_get(k[idx])),
                        np.asarray(jax.device_get(v[idx]))))
        return ids, out

    def snapshot_blocks(self, blocks):
        """The deferred-fetch half of :meth:`export_blocks`: capture
        ``(ids, page_handles)`` atomically under the ledger lock and
        return WITHOUT fetching. Pages are functional arrays, so the
        captured handles keep holding every byte the captured ids name
        even after the blocks are freed and rewritten by later decode
        steps — the same no-tear argument export_blocks makes against a
        concurrent defrag. This is what lets the swap tier free device
        blocks at the boundary where the spill is DECIDED while the
        staging thread performs the actual host fetch at leisure: the
        compiled step never waits on a swap-out."""
        with self._lock:
            ids = [int(b) for b in blocks]
            for b in ids:
                if self._refs.get(b, 0) < 1:
                    raise ValueError(
                        f"cannot snapshot dead block {b} — spill must be "
                        "decided while the pages are still referenced")
            return ids, list(self._pages)

    def adopt_serialized(self, owner, layers, *, stage=None) -> List[int]:
        """The receiving half of a handoff: allocate fresh private
        blocks for ``owner`` and write the transferred pages into them
        (one scatter dispatch per layer). ``layers`` is
        ``export_blocks``'s ``[(k_np, v_np), ...]``; geometry is
        validated against THIS pool before any ledger mutation, and the
        allocation is all-or-nothing (:class:`KVCacheOOM` leaves the
        ledger untouched) — admission-grade discipline for pages that
        arrived over a wire. Returns the new physical ids, in logical
        order, refcounted to ``owner`` (hand them to
        ``PrefixCache.insert`` to make the prefix adoptable, then
        ``free(owner)`` — exactly the post-prefill registration flow).

        ``stage`` optionally replaces the default host→device placement
        (``jnp.asarray`` per layer) with a caller-provided
        ``f(k_np, v_np) -> (k_dev, v_dev)`` — the swap tier routes the
        transfer through ``native.HostStagingRing``'s reusable staging
        buffers so a refill-heavy workload doesn't pay a fresh pinned
        allocation per swap-in."""
        geo = self.geometry()
        if len(layers) != geo["n_layers"]:
            raise ValueError(
                f"handoff geometry mismatch: {len(layers)} layers vs "
                f"this pool's {geo['n_layers']}")
        n = None
        want = (geo["kv_heads"], geo["block_size"], geo["head_dim"])
        for li, (k, v) in enumerate(layers):
            k, v = np.asarray(k), np.asarray(v)
            if k.shape != v.shape or k.ndim != 4 or k.shape[1:] != want:
                raise ValueError(
                    f"handoff geometry mismatch at layer {li}: "
                    f"k{k.shape}/v{v.shape} vs (n, {want[0]}, {want[1]}, "
                    f"{want[2]})")
            if n is None:
                n = int(k.shape[0])
            elif int(k.shape[0]) != n:
                raise ValueError("handoff layers disagree on block count")
        if not n:
            return []
        # pad the transfer AND the scatter to the next power-of-two
        # bucket (padding rows scatter into the reserved garbage block
        # 0, like a padded decode slot's writes): refill/handoff sizes
        # vary per boundary, and the scatter compiles per distinct row
        # count — ON THE SCHEDULER THREAD, stalling every active decode
        # for the compile. Bucketed, O(log pool) shapes exist total and
        # KVSwapManager.warmup() pre-pays them.
        npdt = np.dtype(self.page_dtype)
        pad = _gather_bucket(n) - n
        if pad:
            zrow = np.zeros((pad,) + want, npdt)
            layers = [(np.concatenate([np.asarray(lk, npdt), zrow]),
                       np.concatenate([np.asarray(lv, npdt), zrow]))
                      for lk, lv in layers]
        # host→device transfer OUTSIDE the ledger lock (the symmetric
        # discipline to export_blocks' fetch): a multi-MB handoff must
        # not stall every concurrent admission/alloc/free on this
        # replica for the transfer's duration. Only the free-list pop
        # and the page-handle swap run in-lock.
        if stage is None:
            dev = [(jnp.asarray(lk, self.page_dtype),
                    jnp.asarray(lv, self.page_dtype)) for lk, lv in layers]
        else:
            dev = [stage(np.asarray(lk, npdt), np.asarray(lv, npdt))
                   for lk, lv in layers]
        with self._lock:
            if self._owned.get(owner):
                raise ValueError(f"adopt_serialized owner {owner!r} "
                                 "already holds blocks")
            if n > len(self._free):
                raise KVCacheOOM(
                    f"handoff needs {n} blocks, {len(self._free)} free")
            ids = [self._free.pop() for _ in range(n)]
            for b in ids:
                self._refs[b] = 1
            self._owned[owner] = list(ids)
            dst = jnp.asarray(ids + [0] * pad, jnp.int32)
            self._pages = [
                (k.at[dst].set(dk), v.at[dst].set(dv))
                for (k, v), (dk, dv) in zip(self._pages, dev)]
            in_use = self.num_blocks - 1 - len(self._free)
            self._high_water = max(self._high_water, in_use)
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_allocs").inc(n)
        self._set_gauges()
        return ids

    def block_table(self, owner) -> np.ndarray:
        """``owner``'s (max_blocks_per_seq,) int32 physical-block table,
        null-block(0)-padded past its allocation."""
        out = np.zeros((self.max_blocks_per_seq,), np.int32)
        with self._lock:
            blocks = self._owned.get(owner, ())
            out[:len(blocks)] = blocks
        return out

    def owner_blocks(self, owner) -> List[int]:
        """``owner``'s physical block list (a copy)."""
        with self._lock:
            return list(self._owned.get(owner, ()))

    def null_table(self) -> np.ndarray:
        """The all-null table a padded decode slot carries: every write
        lands in the reserved garbage block."""
        return np.zeros((self.max_blocks_per_seq,), np.int32)

    # -- defrag ----------------------------------------------------------

    def add_remap_listener(self, fn: Callable[[dict], None]):
        """Register a ``{old_physical: new_physical}`` callback fired
        by :meth:`defrag` right AFTER the table rewrite, on the
        defragging thread but OUTSIDE the ledger lock (listeners take
        their own locks and may query refcounts — nesting both orders
        would deadlock). There is therefore a window where owner
        tables are rewritten and a listener's index is not yet: defrag
        runs at a decode-step boundary on the scheduler thread, which
        is also the only thread that consumes listener-held block ids,
        so nothing can adopt through a stale mapping — a listener that
        serves OTHER threads by block id must tolerate staleness. The
        prefix cache re-keys its entry->block index through this, so
        sharing survives a repack."""
        self._remap_listeners.append(fn)

    def frag_blocks(self) -> int:
        """Address-space spread: the number of free holes below the
        highest allocated physical id — 0 when the allocation is
        perfectly packed at the low end of the pool (ids are 1-based;
        packed = ids 1..n). After enough churn the live blocks scatter
        across the pool; :meth:`defrag` repacks them."""
        with self._lock:
            ids = list(self._refs)
            if not ids:
                return 0
            return max(ids) - len(ids)

    def defrag(self) -> int:
        """Repack live blocks into the lowest physical ids: device-copy
        each out-of-place block's K/V pages down and rewrite the owning
        tables — a SHARED page moves once and every owner's table plus
        the prefix-cache index (remap listeners) follows it, refcount
        untouched. Returns the number of blocks moved (``serve/kv_
        defrag_moves``). Run at a step boundary — tables handed to an
        in-flight dispatch must not be rewritten under it.

        The ``kv/page_copy`` chaos site fires BEFORE the ledger lock:
        an injected fault aborts the repack with the ledger untouched
        (the scheduler skips the round and retries on the next
        request)."""
        _chaos.maybe_fire("kv/page_copy")
        with self._lock:
            live = sorted(self._refs)
            n = len(live)
            targets = set(range(1, n + 1))
            moves = []          # (src, dst) pairs
            free_targets = sorted(targets - set(live))
            for src in sorted(b for b in live if b > n):
                moves.append((src, free_targets.pop(0)))
            if not moves:
                return 0
            remap = dict(moves)
            srcs = jnp.asarray([s for s, _ in moves], jnp.int32)
            dsts = jnp.asarray([d for _, d in moves], jnp.int32)
            self._pages = [
                (k.at[dsts].set(k[srcs]), v.at[dsts].set(v[srcs]))
                for k, v in self._pages]
            for blocks in self._owned.values():
                for i, b in enumerate(blocks):
                    blocks[i] = remap.get(b, b)
            self._refs = {remap.get(b, b): r
                          for b, r in self._refs.items()}
            self._free = list(range(self.num_blocks - 1, n, -1))
        # outside the ledger lock (listeners take their own locks — the
        # prefix cache also queries refcounts, and nesting the two
        # orders both ways would deadlock); defrag runs at a step
        # boundary on the scheduler thread, so nothing adopts through
        # the index between the table rewrite and this re-key
        for fn in self._remap_listeners:
            fn(remap)
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_defrag_moves").inc(len(moves))
        self._set_gauges()
        return len(moves)

    # -- auditor ---------------------------------------------------------

    def audit(self, prefix_pins: Optional[Dict[int, int]] = None) -> dict:
        """Ledger invariant checker (ISSUE 13). Pure host work over ONE
        consistent snapshot of the ledger (taken under the lock, checked
        outside it); NEVER raises on a violation — the caller decides
        whether to quarantine (the scheduler does) or crash. Returns
        ``{"ok", "violations": [str, ...], "blocks": n, "owners": n}``.

        Invariants:

        * **partition** — every physical id 1..num_blocks-1 is on the
          free list XOR referenced, exactly once; block 0 (the reserved
          null block) is neither.
        * **refcount vs owner tables** — a block's table references
          never exceed its refcount (an excess table entry is aliasing:
          two owners writing one page without the sharing contract),
          every table entry points at a live block, and no owner's
          table references the same physical block twice.
        * **ownerless pins** (with ``prefix_pins``, the prefix cache's
          ``pinned_blocks()`` map) — refcount minus table references
          equals EXACTLY the cache's pins per block, and every pinned
          block is live: a prefix entry whose page was freed under it
          would hand garbage KV to the next adopter. Pass ``{}`` for a
          cache with no ownerless pinner (the speculative draft pool);
          ``None`` skips the exactness check (refcount may exceed table
          references by unknown pins).
        """
        with self._lock:
            free = list(self._free)
            refs = dict(self._refs)
            owned = {o: list(b) for o, b in self._owned.items()}
        v: List[str] = []
        freeset = set(free)
        if len(freeset) != len(free):
            dup = sorted(b for b, c in Counter(free).items() if c > 1)
            v.append(f"free list holds duplicate block ids {dup[:8]}")
        if 0 in freeset:
            v.append("reserved null block 0 is on the free list")
        if 0 in refs:
            v.append("reserved null block 0 carries a refcount")
        both = sorted(freeset & set(refs))
        if both:
            v.append(f"blocks both free and referenced: {both[:8]}")
        lost = sorted(set(range(1, self.num_blocks)) - freeset - set(refs))
        if lost:
            v.append(f"blocks neither free nor referenced (leaked): "
                     f"{lost[:8]}")
        table_refs: Counter = Counter()
        for owner, blocks in owned.items():
            dup = sorted(b for b, c in Counter(blocks).items() if c > 1)
            if dup:
                v.append(f"owner {owner!r} table aliases block(s) "
                         f"{dup[:8]}")
            for b in blocks:
                table_refs[b] += 1
                if b not in refs:
                    v.append(f"owner {owner!r} references dead block {b}")
        for b in sorted(refs):
            r = refs[b]
            t = table_refs.get(b, 0)
            if r < 1:
                v.append(f"block {b} has non-positive refcount {r}")
            if t > r:
                v.append(f"block {b} aliased: {t} table references "
                         f"exceed refcount {r}")
            elif prefix_pins is not None and r - t != prefix_pins.get(b, 0):
                v.append(f"block {b} refcount {r} != {t} table refs + "
                         f"{prefix_pins.get(b, 0)} prefix pins")
        if prefix_pins:
            dead = sorted(b for b in prefix_pins if b not in refs)
            if dead:
                v.append(f"prefix entries pin dead block(s) {dead[:8]}")
        return {"ok": not v, "violations": v,
                "blocks": self.num_blocks - 1, "owners": len(owned)}

    # -- telemetry -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            in_use = self.num_blocks - 1 - len(self._free)
            return {
                "blocks_total": self.num_blocks - 1,  # null excluded
                "blocks_in_use": in_use,
                "blocks_free": len(self._free),
                "shared_blocks": sum(1 for r in self._refs.values()
                                     if r >= 2),
                "owners": len(self._owned),
                "high_water": self._high_water,
                "block_size": self.block_size,
                "max_blocks_per_seq": self.max_blocks_per_seq,
            }

    def _set_gauges(self):
        if not obs.enabled():
            return
        s = self.stats()
        pre = self.metric_prefix
        obs.gauge(f"{pre}_blocks_total").set(s["blocks_total"])
        obs.gauge(f"{pre}_blocks_in_use").set(s["blocks_in_use"])
        obs.gauge(f"{pre}_blocks_free").set(s["blocks_free"])
        obs.gauge(f"{pre}_shared_blocks").set(s["shared_blocks"])
        obs.gauge(f"{pre}_high_water").set(s["high_water"])
        obs.gauge(f"{pre}_frag_blocks").set(self.frag_blocks())


# -- host-RAM paging tier (ISSUE 18) -------------------------------------

#: HostKVHandle lifecycle. PENDING means the staging fetch is still in
#: flight on the swap thread; READY means the page bytes are resident in
#: host RAM; FAILED means the fetch died (consumers recompute); FREED
#: means the reservation is back in the pool (refilled or dropped).
SPILL_PENDING = "pending"
SPILL_READY = "ready"
SPILL_FAILED = "failed"
SPILL_FREED = "freed"

SWAP_THREAD_NAME = "bigdl_tpu-kv-swap-stager"


class HostKVHandle:
    """One spilled segment: ``n_blocks`` pages captured from the device
    pool and staged to host RAM by the swap thread. The handle is the
    ONLY name for the host bytes — whoever holds it (a spilled prefix
    entry, a preempted request) owns the reservation and must settle it
    exactly once: a successful :meth:`KVSwapManager.refill` or a
    :meth:`KVSwapManager.discard`. State transitions are owned by
    :class:`HostKVPool` under its lock; reading ``state`` without the
    lock is a benign race (a PENDING→READY flip observed late just
    defers the refill to the next step boundary)."""

    __slots__ = ("n_blocks", "tag", "state", "layers", "digest", "nbytes")

    def __init__(self, n_blocks: int, tag=None):
        self.n_blocks = int(n_blocks)
        self.tag = tag
        self.state = SPILL_PENDING
        self.layers = None   # [(k_np, v_np), ...] per layer, once READY
        self.digest = None   # blake2b over the fetched page bytes
        self.nbytes = 0


class HostKVPool:
    """Host-RAM block accounting under the device ledger: a fixed budget
    of ``num_blocks`` spill slots (each holds one device page per layer,
    so a slot's bytes = ``n_layers * 2 * kvH * block_size * D *
    itemsize``). Same drain discipline as the device pool — every
    shutdown path must return ``blocks_in_use`` to 0, and the spill
    tests gate on it. Reservation happens at spill DECISION time (before
    the async fetch lands), so the pool can never be oversubscribed by
    in-flight stages."""

    def __init__(self, num_blocks: int,
                 metric_prefix: str = "serve/kv_host"):
        if num_blocks < 1:
            raise ValueError(f"host pool needs >= 1 block, got "
                             f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        self.metric_prefix = metric_prefix
        self._lock = threading.Lock()
        self._in_use = 0
        self._spills = 0
        self._set_gauges()

    def alloc(self, n_blocks: int, tag=None) -> HostKVHandle:
        """Reserve ``n_blocks`` spill slots. Raises :class:`HostPoolOOM`
        (ledger untouched) when the budget can't cover it — callers
        degrade, never fail."""
        n = int(n_blocks)
        if n < 1:
            raise ValueError(f"spill needs >= 1 block, got {n}")
        with self._lock:
            free = self.num_blocks - self._in_use
            if n > free:
                raise HostPoolOOM(
                    f"spill needs {n} host blocks, {free} free of "
                    f"{self.num_blocks}")
            self._in_use += n
            self._spills += 1
            h = HostKVHandle(n, tag)
        self._set_gauges()
        return h

    def store(self, handle: HostKVHandle, layers, digest) -> bool:
        """Swap-thread side: land the fetched pages. Returns False when
        the handle was freed/failed while the fetch was in flight — the
        bytes are discarded (the reservation already went back)."""
        nbytes = sum(int(k.nbytes) + int(v.nbytes) for k, v in layers)
        with self._lock:
            if handle.state != SPILL_PENDING:
                return False
            handle.layers = layers
            handle.digest = digest
            handle.nbytes = nbytes
            handle.state = SPILL_READY
        return True

    def payload(self, handle: HostKVHandle):
        """``(layers, digest)`` when READY, else None. Does NOT free —
        the device-side refill may still hit :class:`KVCacheOOM` and
        retry at a roomier boundary."""
        with self._lock:
            if handle.state != SPILL_READY:
                return None
            return handle.layers, handle.digest

    def fail(self, handle: HostKVHandle):
        """Swap-thread side: the fetch died. PENDING→FAILED, the
        reservation goes back; consumers observe FAILED and recompute."""
        with self._lock:
            if handle.state != SPILL_PENDING:
                return
            handle.state = SPILL_FAILED
            handle.layers = None
            self._in_use -= handle.n_blocks
        self._set_gauges()

    def free(self, handle: HostKVHandle) -> int:
        """Settle a handle (refilled, dropped, or its owner died) and
        return its reservation. Idempotent across every terminal state;
        returns the number of blocks actually returned."""
        with self._lock:
            if handle.state not in (SPILL_PENDING, SPILL_READY):
                return 0
            handle.state = SPILL_FREED
            handle.layers = None
            n = handle.n_blocks
            self._in_use -= n
        self._set_gauges()
        return n

    def blocks_in_use(self) -> int:
        with self._lock:
            return self._in_use

    def stats(self) -> dict:
        with self._lock:
            return {
                "host_blocks_total": self.num_blocks,
                "host_blocks_in_use": self._in_use,
                "host_blocks_free": self.num_blocks - self._in_use,
                "host_spills": self._spills,
            }

    def _set_gauges(self):
        if not obs.enabled():
            return
        with self._lock:
            in_use = self._in_use
        pre = self.metric_prefix
        obs.gauge(f"{pre}_blocks_total").set(self.num_blocks)
        obs.gauge(f"{pre}_blocks_in_use").set(in_use)
        obs.gauge(f"{pre}_blocks_free").set(self.num_blocks - in_use)


def _pages_digest(layers) -> bytes:
    """Content hash over fetched page bytes — the refill re-verifies it
    before adopting, the same end-to-end integrity argument the PR-15
    handoff makes over the wire (here the 'wire' is host RAM dwell)."""
    h = hashlib.blake2b(digest_size=16)
    for k, v in layers:
        h.update(np.ascontiguousarray(k).tobytes())
        h.update(np.ascontiguousarray(v).tobytes())
    return h.digest()


def _gather_bucket(n: int) -> int:
    """Next power-of-two at or above ``n`` — the stager's gather shapes
    are padded to these buckets so XLA compiles O(log pool) gather
    programs total instead of one per distinct eviction-sweep size."""
    return 1 << max(0, (n - 1).bit_length())


class KVSwapManager:
    """Async host-RAM staging pipeline under ONE :class:`PagedKVCache`
    (ISSUE 18).

    **Swap-out never blocks the decode loop.** The caller — always a
    step-boundary path on the scheduler thread — captures ``(ids, page
    handles)`` under the ledger lock (:meth:`PagedKVCache.snapshot_blocks`,
    the deferred-fetch half of ``export_blocks``) and hands the fetch to
    this manager's staging thread. The captured handles are immutable
    functional arrays, so the fetch stays bitwise-correct even after the
    device blocks are freed and rewritten by later decode steps — the
    same no-tear argument ``export_blocks`` makes against a concurrent
    defrag. The caller may therefore release the device blocks at the
    SAME boundary the spill is decided.

    **Swap-in runs on the scheduler thread at a step boundary** but only
    ISSUES transfers — host→device through ``native.HostStagingRing``'s
    reusable staging buffers into ``adopt_serialized``'s scatter — and
    never blocks on one (the adopt discipline; the ring's reuse fence is
    its one annotated sync, paid at most once per in-flight slot).

    **Fault semantics** (docs/RESILIENCE.md): the ``kv/swap_out`` and
    ``kv/swap_in`` chaos sites fire on the respective paths. A TRANSIENT
    fault replays once — captured handles / host bytes are immutable, so
    the retry is bitwise. Anything past that DEGRADES: the spill is
    dropped (a spilled prefix chain becomes a future cold miss; a
    preempted request recomputes from its host-resident tokens) and a
    ``kv_swap_failed`` health event lands. A swap failure never corrupts
    KV and never takes serving down."""

    def __init__(self, kv: PagedKVCache, host_blocks: int, *, tag=None):
        self.kv = kv
        self.tag = tag
        self.pool = HostKVPool(
            host_blocks, metric_prefix=f"{kv.metric_prefix}_host")
        self._q: "queue.Queue" = queue.Queue()
        self._stats_lock = threading.Lock()
        self._out_bytes = 0
        self._in_bytes = 0
        self._failures = 0
        self._ring = None
        self._ring_blocks = 0
        self._thread = threading.Thread(
            target=self._worker, name=SWAP_THREAD_NAME, daemon=True)
        self._thread.start()

    def warmup(self, max_bucket: int = 32):
        """Pre-pay every bucketed swap compile BEFORE live traffic:
        the stager's gathers (:meth:`_fetch`), the refill's staging-
        ring build + host→device transfer (:meth:`_stage`), and the
        adopt scatter — one compile per power-of-two bucket. A first-
        spill gather compile on the staging thread competes with the
        decode loop and stalls staging for a large fraction of a bursty
        workload (every spill stays PENDING exactly when second-chance
        lookups want it READY); a first-refill scatter compile runs ON
        the scheduler thread and stalls every active decode. Called
        from the scheduler's warmup; safe to call any time."""
        with self.kv._lock:
            pages = list(self.kv._pages)
        if not pages:
            return
        k, v = pages[0]
        buckets = []
        b = 1
        while b <= min(max_bucket, self.kv.num_blocks):
            buckets.append(b)
            b <<= 1
        row = tuple(k.shape[1:])
        npdt = np.dtype(self.kv.page_dtype)
        # largest first: the staging ring sizes to the largest refill
        # seen and rebuilds on growth — warming descending builds ONCE
        for b in reversed(buckets):
            idx = jnp.asarray([0] * b, jnp.int32)
            # deliberate warmup fetches/transfers — no traffic yet
            jax.device_get(k[idx])
            jax.device_get(v[idx])
            z = np.zeros((b,) + row, npdt)
            dk, dv = self._stage(z, z)
            # the scatter compile, against the real page arrays; the
            # result is dropped (all rows target the garbage block)
            jax.device_get(k.at[idx].set(dk)[0, 0, 0, 0])
            jax.device_get(v.at[idx].set(dv)[0, 0, 0, 0])

    # -- swap-out (spill) ------------------------------------------------

    def spill(self, blocks, tag=None) -> Optional[HostKVHandle]:
        """Boundary op, scheduler thread: reserve host slots and enqueue
        the async fetch of ``blocks``. Returns the PENDING handle, or
        None when the host pool can't cover it (caller degrades — drop
        the pages exactly like the pre-tier behavior). The caller may
        free/release the device blocks immediately after this returns;
        the snapshot keeps the bytes alive for the stager."""
        out = self.spill_many([blocks], tag=tag)
        return out[0] if out else None

    def spill_many(self, groups, tag=None):
        """Batched :meth:`spill`: one handle PER GROUP of blocks, but
        ONE snapshot and ONE stager job — the fetch gathers every
        group's pages in a single device read instead of one dispatch
        per group. An eviction sweep spills per-leaf (one-block groups,
        so the second-chance index keeps per-key granularity); fetching
        them one at a time would pay a device round-trip per block.
        Returns one handle (or None on host-pool exhaustion — that
        group degrades to a plain drop) per group, in order."""
        plans = []      # (handle, start, n) into the flat id list
        flat: List[int] = []
        handles: List[Optional[HostKVHandle]] = []
        for blocks in groups:
            ids = [int(b) for b in blocks]
            if not ids:
                handles.append(None)
                continue
            try:
                h = self.pool.alloc(len(ids), tag if tag is not None
                                    else self.tag)
            except HostPoolOOM:
                handles.append(None)
                continue
            plans.append((h, len(flat), len(ids)))
            flat += ids
            handles.append(h)
        if plans:
            snap_ids, pages = self.kv.snapshot_blocks(flat)
            self._q.put((plans, snap_ids, pages))
        return handles

    def _worker(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            plans, ids, pages = job
            try:
                self._fetch(plans, ids, pages)
            except Exception:  # noqa: BLE001 — a swap must never kill
                for h, _s, _n in plans:
                    self._note_failure(h, "out", "unexpected stager error")

    def _fetch(self, plans, ids, pages):
        live = [(h, s, n) for h, s, n in plans
                if h.state == SPILL_PENDING]
        if not live:
            return  # freed while queued — skip the fetch entirely
        # pad the gather to the next power-of-two bucket: eviction
        # sweeps vary in size every boundary, and a shape-specialized
        # gather compile per DISTINCT sweep size would stall the stager
        # for hundreds of ms apiece while decode traffic is live (the
        # padding rows are never read back — every plan's (start, n)
        # indexes the original prefix). warmup() pre-pays the buckets.
        take = list(ids)
        take += [take[0]] * (_gather_bucket(len(take)) - len(take))
        idx = jnp.asarray(take, jnp.int32)
        last = None
        flat = None
        for _attempt in (0, 1):
            try:
                _chaos.maybe_fire("kv/swap_out", tag=live[0][0].tag)
                # deliberate host fetch — the swap-out data hop, on the
                # staging thread so the decode loop never waits on it;
                # one gather covers every handle in the job
                flat = [(np.asarray(jax.device_get(k[idx])),
                         np.asarray(jax.device_get(v[idx])))
                        for k, v in pages]
                break
            except TransientDeviceError as e:
                last = e  # replay: immutable handles → bitwise retry
                continue
            except Exception as e:  # noqa: BLE001 — degrade, never die
                last = e
                break
        if flat is None:
            for h, _s, _n in live:
                self._note_failure(h, "out", repr(last))
            return
        stored = 0
        for h, s, n in live:
            # own copies per handle: a stored slice must not pin the
            # whole job's gather in host RAM past its siblings' frees
            layers = [(np.ascontiguousarray(k[s:s + n]),
                       np.ascontiguousarray(v[s:s + n]))
                      for k, v in flat]
            if self.pool.store(h, layers, _pages_digest(layers)):
                stored += h.nbytes
        if stored:
            with self._stats_lock:
                self._out_bytes += stored
            if obs.enabled():
                obs.counter(f"{self.kv.metric_prefix}"
                            "_swap_out_bytes").inc(stored)

    def _note_failure(self, h: HostKVHandle, direction: str, error: str):
        self.pool.fail(h)
        with self._stats_lock:
            self._failures += 1
        if obs.enabled():
            obs.counter(
                f"{self.kv.metric_prefix}_swap_failures").inc()
        _health.emit("kv_swap_failed", direction=direction,
                     blocks=h.n_blocks, tag=str(h.tag), error=error)

    # -- swap-in (refill) ------------------------------------------------

    def refill(self, owner, handle: HostKVHandle) -> Optional[List[int]]:
        """Boundary op, scheduler thread: verify and adopt a READY
        handle's pages into fresh device blocks for ``owner``. On
        success the host reservation returns to the pool and the new
        physical ids come back (refcounted to ``owner``, private).
        Returns None when the handle cannot serve — fetch still in
        flight, failed, digest mismatch, or an injected permanent fault
        — and the caller degrades (second-chance miss / recompute); in
        every None case except PENDING the handle is settled here.
        Raises :class:`KVCacheOOM` with the handle INTACT when the
        device pool can't fit: the refill retries at a roomier
        boundary."""
        got = self.pool.payload(handle)
        if got is None:
            if handle.state == SPILL_PENDING:
                return None  # stage in flight — try again next boundary
            self.pool.free(handle)  # failed/freed: settle and degrade
            return None
        layers, digest = got
        last = None
        for _attempt in (0, 1):
            try:
                _chaos.maybe_fire("kv/swap_in", tag=handle.tag)
                if _pages_digest(layers) != digest:
                    raise RuntimeError(
                        f"host page digest mismatch over "
                        f"{handle.n_blocks} blocks")
                ids = self.kv.adopt_serialized(owner, layers,
                                               stage=self._stage)
                with self._stats_lock:
                    self._in_bytes += handle.nbytes
                if obs.enabled():
                    obs.counter(f"{self.kv.metric_prefix}"
                                "_swap_in_bytes").inc(handle.nbytes)
                self.pool.free(handle)
                return ids
            except KVCacheOOM:
                raise  # handle intact — retry when blocks free up
            except TransientDeviceError as e:
                last = e  # replay: host bytes immutable → bitwise retry
                continue
            except Exception as e:  # noqa: BLE001 — degrade, never die
                last = e
                break
        self._note_failure(handle, "in", repr(last))
        self.pool.free(handle)  # fail() was a no-op on a READY handle
        return None

    def refill_many(self, owner, handles):
        """Batched :meth:`refill`: verify and adopt the longest clean
        LEADING run of READY handles in ONE adopt — one scatter dispatch
        per layer instead of one per handle. A chain refill is the hot
        case (the prefix cache spills per-leaf, so a second-chance hit
        walks N one-block handles); adopting them one at a time pays N
        functional page-array updates where the batch pays one.

        Returns ``(ids, consumed, dropped)``: ``ids`` are the new
        physical blocks covering ``handles[:consumed]`` in logical
        order (split by each handle's ``n_blocks``), and the next
        ``dropped`` handles after the run were SETTLED here (fetch
        failed, digest mismatch, injected permanent fault) — the caller
        forgets those; anything later is untouched (e.g. still staging)
        and retries at the next boundary. Raises :class:`KVCacheOOM`
        with every handle intact when even a clamped run cannot fit."""
        run, run_layers = [], []
        dropped = 0
        last = None
        for h in handles:
            got = self.pool.payload(h)
            if got is None:
                if h.state != SPILL_PENDING:
                    self.pool.free(h)  # failed/freed: settle and degrade
                    dropped = 1
                break
            layers, digest = got
            ok = False
            for _attempt in (0, 1):
                try:
                    _chaos.maybe_fire("kv/swap_in", tag=h.tag)
                    if _pages_digest(layers) != digest:
                        raise RuntimeError(
                            f"host page digest mismatch over "
                            f"{h.n_blocks} blocks")
                    ok = True
                    break
                except TransientDeviceError as e:
                    last = e  # replay: host bytes immutable → bitwise
                    continue
                except Exception as e:  # noqa: BLE001 — degrade
                    last = e
                    break
            if not ok:
                self._note_failure(h, "in", repr(last))
                self.pool.free(h)
                dropped = 1
                break
            run.append(h)
            run_layers.append(layers)
        if not run:
            return None, 0, dropped
        # clamp to what the device pool can plausibly hold so the
        # all-or-nothing adopt degrades to a PARTIAL chain refill under
        # pressure (the per-handle path's behavior) instead of deferring
        # the whole run; adopt re-checks under its own lock and still
        # raises on a lost race
        free = self.kv.blocks_free()
        while run and sum(h.n_blocks for h in run) > free:
            run.pop()
            run_layers.pop()
            dropped = 0  # the settled handle no longer borders the run;
            #              its key is swept by a later lookup's state walk
        if not run:
            raise KVCacheOOM(
                f"refill needs {handles[0].n_blocks} blocks, {free} free")
        cat = [tuple(np.concatenate([ls[li][half] for ls in run_layers])
                     for half in (0, 1))
               for li in range(len(run_layers[0]))]
        for _attempt in (0, 1):
            try:
                ids = self.kv.adopt_serialized(owner, cat,
                                               stage=self._stage)
                break
            except KVCacheOOM:
                raise  # every handle intact — retry at a roomier boundary
            except TransientDeviceError as e:
                last = e  # immutable bytes → bitwise replay
                continue
            except Exception as e:  # noqa: BLE001 — degrade, never die
                last = e
                ids = None
                break
        else:
            ids = None
        if ids is None:
            for h in run:  # the whole run degrades, later handles keep
                self._note_failure(h, "in", repr(last))
                self.pool.free(h)
            return None, 0, len(run) + dropped
        nbytes = 0
        for h in run:
            nbytes += h.nbytes
            self.pool.free(h)
        with self._stats_lock:
            self._in_bytes += nbytes
        if obs.enabled():
            obs.counter(f"{self.kv.metric_prefix}"
                        "_swap_in_bytes").inc(nbytes)
        return ids, len(run), dropped

    def _stage(self, lk: np.ndarray, lv: np.ndarray):
        """Host→device placement for adopt_serialized: route the pages
        through a reusable ``HostStagingRing`` (the input pipeline's
        pinned-buffer discipline) instead of a fresh allocation per
        refill — under churn the refill path re-lands pages every few
        boundaries, exactly the per-batch cost the ring exists to
        amortize. The ring is sized to the largest refill seen and
        rebuilt on growth."""
        from ..native import HostStagingRing
        n = int(lk.shape[0])
        if self._ring is None or n > self._ring_blocks:
            cap = max(n, self._ring_blocks)
            shape = (cap,) + tuple(lk.shape[1:])
            self._ring = HostStagingRing(shape, lk.dtype, shape, lv.dtype)
            self._ring_blocks = cap
        kb, vb = self._ring.acquire()
        kb[:n] = lk
        vb[:n] = lv
        return self._ring.to_device(kb[:n], vb[:n])

    # -- lifecycle -------------------------------------------------------

    def discard(self, handle: HostKVHandle) -> int:
        """Drop a handle without refilling (its owner gave up — request
        cancelled, chain re-inserted fresh). Idempotent; returns the
        host blocks returned."""
        return self.pool.free(handle)

    def stats(self) -> dict:
        with self._stats_lock:
            out = {
                "swap_out_bytes": self._out_bytes,
                "swap_in_bytes": self._in_bytes,
                "swap_failures": self._failures,
            }
        out.update(self.pool.stats())
        return out

    def shutdown(self, timeout: float = 10.0):
        """Stop the staging thread. Jobs still queued behind the
        sentinel fail their handles (their owners are gone by the time
        the scheduler reaches here — the drain gates check the pool hits
        0 regardless of stage completion order)."""
        self._q.put(None)
        self._thread.join(timeout)
        while True:
            try:
                job = self._q.get_nowait()
            except queue.Empty:
                break
            if job is not None:
                self.pool.fail(job[0])


def kv_swap_threads_alive() -> int:
    """Live swap-stager threads (tests gate this at 0 after shutdown)."""
    return sum(1 for t in threading.enumerate()
               if t.name == SWAP_THREAD_NAME and t.is_alive())
