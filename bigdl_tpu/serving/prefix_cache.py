"""Content-addressed prefix index over the paged KV block pool.

Production LM traffic is dominated by shared prefixes — system prompts,
few-shot templates, multi-turn histories — yet a scheduler without this
module re-prefills every prompt from position 0 and stores its KV
blocks privately: a thousand requests carrying the same 4k-token system
prompt pay a thousand identical prefills and pin a thousand copies of
the same pages. This module is the serving analog of what the
data-parallel papers did for training (Parallax's locality-aware
exchange, arXiv:1808.02621): route work to where the state already
lives instead of re-materializing it.

Index structure — a CHAIN of content-addressed entries at KV-block
granularity. Block ``i`` of a prompt is keyed by a rolling digest::

    key_i = blake2b(key_{i-1} || tokens[i*bs:(i+1)*bs] || model_version)

so the key commits to the ENTIRE token history, not just the local
chunk (two prompts sharing chunk 3 but differing in chunk 1 never
collide), and to the model version (a hot swap invalidates reuse
without touching the index — old entries simply stop matching and age
out). Each entry pins ONE physical block in the
:class:`~.kv_cache.PagedKVCache` ledger (refcount +1 held by the
cache). A lookup walks the chain until the first absent entry: the
surviving prefix is exactly the longest cached block-aligned prefix.

Lifecycle:

* **insert** — after a request's prefill completes, the scheduler
  registers every FULL prompt block (partial tail blocks are never
  shared: their pages still receive that request's decode writes).
  Existing keys are refreshed (LRU touch), new keys retain the owner's
  physical block — from that moment the page is shared and read-only.
* **hit** — a later admission adopts the matched blocks into its own
  table (refcount +1 each, zero page copies) and skips their prefill
  chunks entirely.
* **evict** — under block pressure the scheduler reclaims cache-only
  pages: LEAF-FIRST LRU over entries whose block has no live adopter
  (refcount == 1, the cache's own pin). Interior entries with present
  children are skipped — evicting mid-chain would strand descendants
  unreachable while their pages stay pinned.
* **defrag** — the cache registers a remap listener with the ledger, so
  a repack that moves a shared page updates the index in the same
  critical section as the owners' tables.

Thread-safety: one lock; the scheduler thread mutates, router threads
only :meth:`peek` (prefix-affinity probes — no LRU touch, no metrics).

Metrics (``serve/prefix_*`` — docs/OBSERVABILITY.md): ``hits``/
``misses``/``evictions``/``cow_forks`` counters and
``entries``/``shared_blocks``/``reused_tokens`` gauges/counters are
maintained by this class and the scheduler's admission path.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..parallel import chaos as _chaos
from .kv_cache import PagedKVCache


def chain_keys(token_ids, block_size: int, version: str,
               max_blocks: Optional[int] = None) -> List[bytes]:
    """The rolling content digests for every FULL ``block_size`` chunk
    of ``token_ids`` under ``version`` — ``keys[i]`` commits to tokens
    ``[0, (i+1)*block_size)`` and the model version."""
    toks = np.asarray(token_ids, np.int32).reshape(-1)
    n = toks.size // block_size
    if max_blocks is not None:
        n = min(n, max_blocks)
    keys: List[bytes] = []
    prev = version.encode() + b"\x00" + str(block_size).encode()
    for i in range(n):
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(toks[i * block_size:(i + 1) * block_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


class _Entry:
    __slots__ = ("key", "parent", "block", "depth", "children")

    def __init__(self, key: bytes, parent: Optional[bytes], block: int,
                 depth: int):
        self.key = key
        self.parent = parent
        self.block = block
        self.depth = depth          # chain position (0 = first block)
        self.children = 0           # PRESENT child entries


class PrefixCache:
    """Content-addressed block sharing over one :class:`PagedKVCache`.

    Parameters
    ----------
    kv : the block ledger whose pages this index pins (refcounts).
    max_entries : optional cap on resident entries — insert evicts
        least-recently-used unreferenced entries past it. ``None``
        bounds the cache only by the block pool itself (eviction then
        happens on admission pressure via :meth:`evict`).
    metric_prefix : the ``serve/prefix`` namespace.
    """

    def __init__(self, kv: PagedKVCache, *,
                 max_entries: Optional[int] = None,
                 metric_prefix: str = "serve/prefix"):
        self.kv = kv
        self.block_size = kv.block_size
        self.max_entries = max_entries
        self.metric_prefix = metric_prefix
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self._evictions = 0
        kv.add_remap_listener(self._on_remap)

    # -- lookup ----------------------------------------------------------

    def _walk(self, token_ids, version: str, touch: bool) -> List[int]:
        """Digest the chain INCREMENTALLY, stopping at the first absent
        entry — a probe that misses at the root costs one blake2b, not
        one per prompt block (the router fans N of these out per
        dispatch, and misses dominate on every replica but the
        holder)."""
        toks = np.asarray(token_ids, np.int32).reshape(-1)
        n = toks.size // self.block_size
        prev = version.encode() + b"\x00" + str(self.block_size).encode()
        blocks: List[int] = []
        with self._lock:
            for i in range(n):
                h = hashlib.blake2b(prev, digest_size=16)
                h.update(toks[i * self.block_size:
                              (i + 1) * self.block_size].tobytes())
                prev = h.digest()
                e = self._entries.get(prev)
                if e is None:
                    break
                if touch:
                    self._entries.move_to_end(prev)
                blocks.append(e.block)
        return blocks

    def lookup(self, token_ids, version: str) -> List[int]:
        """Longest cached chain for this prompt: the physical block ids
        of every consecutive present entry from the root (possibly
        empty). Touches the matched entries (LRU recency) — this is the
        admission path."""
        return self._walk(token_ids, version, touch=True)

    def peek(self, token_ids, version: str) -> int:
        """Router-affinity probe: cached prefix length in TOKENS for
        this prompt, without touching recency or metrics."""
        return len(self._walk(token_ids, version, touch=False)) \
            * self.block_size

    # -- insert ----------------------------------------------------------

    def insert(self, token_ids, version: str,
               owner_blocks: Sequence[int]) -> int:
        """Register a prefilled prompt's FULL blocks: ``owner_blocks``
        are the owner's physical ids for chain positions 0..len-1 (the
        scheduler passes its table's head). Entries already present are
        refreshed; new entries retain the owner's page (it becomes
        shared and read-only). Returns the number of NEW entries.

        The ``prefix/insert`` chaos site fires before any index
        mutation: an injected fault costs the cache one entry, never
        its consistency (the scheduler degrades to skipping the
        registration)."""
        _chaos.maybe_fire("prefix/insert")
        keys = chain_keys(token_ids, self.block_size, version,
                          max_blocks=len(owner_blocks))
        new = 0
        with self._lock:
            parent: Optional[bytes] = None
            for i, k in enumerate(keys):
                e = self._entries.get(k)
                if e is not None:
                    self._entries.move_to_end(k)
                    parent = k
                    continue
                # chains register root-first, so the parent entry must
                # be RESIDENT by the time its child inserts — an orphan
                # would be unreachable by the lookup walk while still
                # pinning its page
                assert parent is None or parent in self._entries
                self.kv.retain([owner_blocks[i]])
                e = _Entry(k, parent, int(owner_blocks[i]), i)
                self._entries[k] = e
                if parent is not None:
                    self._entries[parent].children += 1
                parent = k
                new += 1
            over = (len(self._entries) - self.max_entries
                    if self.max_entries is not None else 0)
        if over > 0:
            self.evict(over)
        if new:
            self._set_gauges()
        return new

    # -- evict -----------------------------------------------------------

    def evict(self, n_blocks: int) -> int:
        """Reclaim up to ``n_blocks`` pages from UNREFERENCED entries
        (block refcount 1 — only the cache pins it), least recently
        used first, leaves before parents. Entries some live request
        still adopts (refcount >= 2) are never touched. Returns the
        number of pages actually returned to the free list."""
        _chaos.maybe_fire("prefix/evict")
        freed = 0
        # batched passes: each pass sweeps the LRU order ONCE and takes
        # every currently-eligible leaf (a per-victim restart would be
        # O(freed x entries) on the admission hot path); freeing a leaf
        # can make its parent eligible, so passes repeat until the
        # budget is met or a sweep finds nothing — bounded by the
        # longest chain, not by the entry count
        while freed < n_blocks:
            victims = []
            with self._lock:
                for e in self._entries.values():   # OrderedDict = LRU order
                    if freed + len(victims) >= n_blocks:
                        break
                    if e.children == 0 and self.kv.block_refs(e.block) == 1:
                        victims.append(e)
                for e in victims:
                    del self._entries[e.key]
                    if e.parent is not None:
                        p = self._entries.get(e.parent)
                        if p is not None:
                            p.children -= 1
            if not victims:
                break
            self.kv.release([e.block for e in victims])
            freed += len(victims)
            self._evictions += len(victims)
            if obs.enabled():
                obs.counter(f"{self.metric_prefix}_evictions").inc(
                    len(victims))
        if freed:
            self._set_gauges()
        return freed

    def clear(self) -> int:
        """Release every entry's page (shutdown: the leak gate demands
        ``kv_blocks_in_use`` drain to zero once the last owner freed).
        Returns the entry count dropped."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            self.kv.release([e.block])
        self._set_gauges()
        return len(entries)

    # -- internals -------------------------------------------------------

    def _on_remap(self, remap: dict):
        """Ledger defrag moved pages: follow them (called right after
        the table rewrite, outside the ledger lock — index-only work)."""
        with self._lock:
            for e in self._entries.values():
                e.block = remap.get(e.block, e.block)

    def pinned_blocks(self) -> dict:
        """``{physical_block: pin_count}`` for every resident entry —
        the ownerless references this cache holds in the ledger, handed
        to :meth:`PagedKVCache.audit` so the auditor can demand EXACT
        refcount accounting (refcount == table refs + these pins)."""
        with self._lock:
            out: dict = {}
            for e in self._entries.values():
                out[e.block] = out.get(e.block, 0) + 1
            return out

    def stats(self) -> dict:
        with self._lock:
            n = len(self._entries)
            depth = max((e.depth + 1 for e in self._entries.values()),
                        default=0)
        return {
            "entries": n,
            "max_chain_blocks": depth,
            "evictions": self._evictions,
            "shared_blocks": self.kv.shared_blocks(),
        }

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def _set_gauges(self):
        if not obs.enabled():
            return
        pre = self.metric_prefix
        obs.gauge(f"{pre}_entries").set(len(self))
        obs.gauge(f"{pre}_shared_blocks").set(self.kv.shared_blocks())
