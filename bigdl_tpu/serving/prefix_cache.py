"""Content-addressed prefix index over the paged KV block pool.

Production LM traffic is dominated by shared prefixes — system prompts,
few-shot templates, multi-turn histories — yet a scheduler without this
module re-prefills every prompt from position 0 and stores its KV
blocks privately: a thousand requests carrying the same 4k-token system
prompt pay a thousand identical prefills and pin a thousand copies of
the same pages. This module is the serving analog of what the
data-parallel papers did for training (Parallax's locality-aware
exchange, arXiv:1808.02621): route work to where the state already
lives instead of re-materializing it.

Index structure — a CHAIN of content-addressed entries at KV-block
granularity. Block ``i`` of a prompt is keyed by a rolling digest::

    key_i = blake2b(key_{i-1} || tokens[i*bs:(i+1)*bs] || model_version)

so the key commits to the ENTIRE token history, not just the local
chunk (two prompts sharing chunk 3 but differing in chunk 1 never
collide), and to the model version (a hot swap invalidates reuse
without touching the index — old entries simply stop matching and age
out). Each entry pins ONE physical block in the
:class:`~.kv_cache.PagedKVCache` ledger (refcount +1 held by the
cache). A lookup walks the chain until the first absent entry: the
surviving prefix is exactly the longest cached block-aligned prefix.

Lifecycle:

* **insert** — after a request's prefill completes, the scheduler
  registers every FULL prompt block (partial tail blocks are never
  shared: their pages still receive that request's decode writes).
  Existing keys are refreshed (LRU touch), new keys retain the owner's
  physical block — from that moment the page is shared and read-only.
* **hit** — a later admission adopts the matched blocks into its own
  table (refcount +1 each, zero page copies) and skips their prefill
  chunks entirely.
* **evict** — under block pressure the scheduler reclaims cache-only
  pages: LEAF-FIRST LRU over entries whose block has no live adopter
  (refcount == 1, the cache's own pin). Interior entries with present
  children are skipped — evicting mid-chain would strand descendants
  unreachable while their pages stay pinned.
* **spill / second chance** (ISSUE 18) — with a
  :class:`~.kv_cache.KVSwapManager` attached, eviction is no longer a
  KV funeral: each victim's page spills to the host tier (async — the
  decision and the device-block release happen at the boundary, the
  fetch on the swap thread) and its key moves to a SECOND-CHANCE index.
  A later lookup that walks off the resident chain into spilled keys
  refills them (host→device adopt, content-digest-verified — the PR-15
  handoff argument, so the hit stays bitwise) and takes the ordinary
  warm-hit path. A spill that cannot stage degrades to exactly the
  pre-tier drop.
* **defrag** — the cache registers a remap listener with the ledger, so
  a repack that moves a shared page updates the index in the same
  critical section as the owners' tables.

Thread-safety: one lock; the scheduler thread mutates, router threads
only :meth:`peek` (prefix-affinity probes — no LRU touch, no metrics).

Metrics (``serve/prefix_*`` — docs/OBSERVABILITY.md): ``hits``/
``misses``/``evictions``/``cow_forks`` counters and
``entries``/``shared_blocks``/``reused_tokens`` gauges/counters are
maintained by this class and the scheduler's admission path.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..parallel import chaos as _chaos
from .kv_cache import (SPILL_FAILED, SPILL_FREED, SPILL_PENDING,
                       SPILL_READY, KVCacheOOM, PagedKVCache,
                       TransientDeviceError)


def chain_keys(token_ids, block_size: int, version: str,
               max_blocks: Optional[int] = None) -> List[bytes]:
    """The rolling content digests for every FULL ``block_size`` chunk
    of ``token_ids`` under ``version`` — ``keys[i]`` commits to tokens
    ``[0, (i+1)*block_size)`` and the model version."""
    toks = np.asarray(token_ids, np.int32).reshape(-1)
    n = toks.size // block_size
    if max_blocks is not None:
        n = min(n, max_blocks)
    keys: List[bytes] = []
    prev = version.encode() + b"\x00" + str(block_size).encode()
    for i in range(n):
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(toks[i * block_size:(i + 1) * block_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


class _Entry:
    __slots__ = ("key", "parent", "block", "depth", "children")

    def __init__(self, key: bytes, parent: Optional[bytes], block: int,
                 depth: int):
        self.key = key
        self.parent = parent
        self.block = block
        self.depth = depth          # chain position (0 = first block)
        self.children = 0           # PRESENT child entries


class PrefixCache:
    """Content-addressed block sharing over one :class:`PagedKVCache`.

    Parameters
    ----------
    kv : the block ledger whose pages this index pins (refcounts).
    max_entries : optional cap on resident entries — insert evicts
        least-recently-used unreferenced entries past it. ``None``
        bounds the cache only by the block pool itself (eviction then
        happens on admission pressure via :meth:`evict`).
    metric_prefix : the ``serve/prefix`` namespace.
    swap : optional :class:`~.kv_cache.KVSwapManager` — arms the
        host-RAM second chance: evicted chains spill instead of
        dropping, spilled keys refill on the next lookup. ``None``
        keeps the exact pre-tier behavior.
    """

    def __init__(self, kv: PagedKVCache, *,
                 max_entries: Optional[int] = None,
                 metric_prefix: str = "serve/prefix",
                 swap=None):
        self.kv = kv
        self.block_size = kv.block_size
        self.max_entries = max_entries
        self.metric_prefix = metric_prefix
        self.swap = swap
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        # second-chance index: key -> (HostKVHandle, depth), insertion
        # order = spill recency (drop_spilled reclaims from the front)
        self._spilled: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        self._evictions = 0
        self._spills = 0
        self._refills = 0
        self._hits_after_spill = 0
        kv.add_remap_listener(self._on_remap)

    # -- lookup ----------------------------------------------------------

    def _walk(self, token_ids, version: str, touch: bool):
        """Digest the chain INCREMENTALLY, stopping at the first absent
        entry — a probe that misses at the root costs one blake2b, not
        one per prompt block (the router fans N of these out per
        dispatch, and misses dominate on every replica but the
        holder). Returns ``(blocks, parent_key, prev_digest, stop_i,
        n)`` — the digest state at the stop point lets the second-chance
        continuation keep hashing without rewalking."""
        toks = np.asarray(token_ids, np.int32).reshape(-1)
        n = toks.size // self.block_size
        prev = version.encode() + b"\x00" + str(self.block_size).encode()
        parent: Optional[bytes] = None
        blocks: List[int] = []
        i = 0
        with self._lock:
            while i < n:
                h = hashlib.blake2b(prev, digest_size=16)
                h.update(toks[i * self.block_size:
                              (i + 1) * self.block_size].tobytes())
                key = h.digest()
                e = self._entries.get(key)
                if e is None:
                    break
                if touch:
                    self._entries.move_to_end(key)
                blocks.append(e.block)
                parent = key
                prev = key
                i += 1
        return blocks, parent, prev, i, toks

    def _next_key(self, prev: bytes, toks: np.ndarray, i: int) -> bytes:
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(toks[i * self.block_size:
                      (i + 1) * self.block_size].tobytes())
        return h.digest()

    def lookup(self, token_ids, version: str) -> List[int]:
        """Longest cached chain for this prompt: the physical block ids
        of every consecutive present entry from the root (possibly
        empty). Touches the matched entries (LRU recency) — this is the
        admission path.

        With a swap manager attached the walk continues past the
        resident chain into the SECOND-CHANCE index: each consecutive
        spilled key whose stage is READY refills (digest-verified adopt
        into a fresh block, re-pinned by the cache) and extends the hit
        — the caller sees an ordinary warm hit. A key still staging
        defers to the next lookup (cold path this time, never a block);
        a failed stage degrades to a miss and drops the key."""
        blocks, parent, prev, i, toks = self._walk(
            token_ids, version, touch=True)
        if self.swap is None:
            return blocks
        n = toks.size // self.block_size
        run = []                       # consecutive READY (key, handle, depth)
        while i < n:
            key = self._next_key(prev, toks, i)
            with self._lock:
                got = self._spilled.get(key)
            if got is None:
                break
            handle, depth = got
            state = handle.state   # benign race: PENDING seen late just
            if state == SPILL_PENDING:   # defers to the next lookup
                break
            if state in (SPILL_FAILED, SPILL_FREED):
                with self._lock:
                    self._spilled.pop(key, None)
                self.swap.discard(handle)
                break
            run.append((key, handle, depth))
            prev = key
            i += 1
        if run:
            blocks += self._refill_run(run, parent, blocks)
        return blocks

    def _refill_run(self, run, parent: Optional[bytes],
                    protect: Sequence[int]) -> List[int]:
        """Land a consecutive run of spilled pages back in the device
        pool with ONE batched adopt (``KVSwapManager.refill_many`` —
        one scatter per layer for the whole chain, not one per block)
        and re-insert their entries (cache-pinned, shared/read-only —
        exactly the state eviction took them from). Returns the
        refilled physical blocks, possibly a leading partial run when
        the device pool is tight (deferred tail handles stay spilled)
        or empty when the refill must fully defer or degrade.

        Under block pressure the refill makes its own room: the
        COLDEST unreferenced resident entries are evicted (spilling to
        host — a straight swap of cold pages for the warm chain being
        revisited). ``protect`` — the resident head this run extends —
        is pinned for the duration so the trade can never cannibalize
        the chain it serves."""
        need = sum(h.n_blocks for _, h, _ in run)
        short = need - self.kv.blocks_free()
        if short > 0:
            self.kv.retain(protect)
            try:
                self.evict(short)
            except (KVCacheOOM, TransientDeviceError):
                pass       # injected evict fault: refill defers below
            finally:
                self.kv.release(protect)
        tmp = ("prefix-refill", run[0][0])
        try:
            ids, consumed, dropped = self.swap.refill_many(
                tmp, [h for _, h, _ in run])
        except KVCacheOOM:
            return []          # handles intact — retry at a roomier boundary
        for key, _h, _d in run[consumed:consumed + dropped]:
            with self._lock:   # settled by the manager: forget the keys
                self._spilled.pop(key, None)
        if not consumed:
            return []
        # convert the refill owner's table refs into the cache's
        # ownerless pins (retain-then-free — the insert flow's discipline)
        self.kv.retain(ids)
        self.kv.free(tmp)
        with self._lock:
            for (key, _h, depth), block in zip(run[:consumed], ids):
                self._spilled.pop(key, None)
                e = _Entry(key, parent, int(block), depth)
                self._entries[key] = e
                if parent is not None:
                    p = self._entries.get(parent)
                    if p is not None:
                        p.children += 1
                self._refills += 1
                parent = key
            self._hits_after_spill += 1
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_hits_after_spill").inc()
            obs.counter(f"{self.metric_prefix}_refills").inc(consumed)
        self._set_gauges()
        return [int(b) for b in ids]

    def peek(self, token_ids, version: str) -> int:
        """Router-affinity probe: cached prefix length in TOKENS for
        this prompt, without touching recency or metrics. Counts the
        resident chain PLUS consecutive spilled keys already staged
        READY — a refillable chain is as routable as a resident one."""
        blocks, _parent, prev, i, toks = self._walk(
            token_ids, version, touch=False)
        hit = len(blocks)
        if self.swap is not None:
            n = toks.size // self.block_size
            while i < n:
                key = self._next_key(prev, toks, i)
                with self._lock:
                    got = self._spilled.get(key)
                if got is None or got[0].state != SPILL_READY:
                    break
                hit += 1
                prev = key
                i += 1
        return hit * self.block_size

    # -- insert ----------------------------------------------------------

    def insert(self, token_ids, version: str,
               owner_blocks: Sequence[int]) -> int:
        """Register a prefilled prompt's FULL blocks: ``owner_blocks``
        are the owner's physical ids for chain positions 0..len-1 (the
        scheduler passes its table's head). Entries already present are
        refreshed; new entries retain the owner's page (it becomes
        shared and read-only). Returns the number of NEW entries.

        The ``prefix/insert`` chaos site fires before any index
        mutation: an injected fault costs the cache one entry, never
        its consistency (the scheduler degrades to skipping the
        registration)."""
        _chaos.maybe_fire("prefix/insert")
        keys = chain_keys(token_ids, self.block_size, version,
                          max_blocks=len(owner_blocks))
        new = 0
        stale = []
        with self._lock:
            parent: Optional[bytes] = None
            for i, k in enumerate(keys):
                e = self._entries.get(k)
                if e is not None:
                    self._entries.move_to_end(k)
                    parent = k
                    continue
                # chains register root-first, so the parent entry must
                # be RESIDENT by the time its child inserts — an orphan
                # would be unreachable by the lookup walk while still
                # pinning its page
                assert parent is None or parent in self._entries
                self.kv.retain([owner_blocks[i]])
                e = _Entry(k, parent, int(owner_blocks[i]), i)
                self._entries[k] = e
                if parent is not None:
                    self._entries[parent].children += 1
                parent = k
                new += 1
                # a fresh resident copy supersedes any spilled one
                # (same key = same content, so nothing is lost — the
                # host reservation just comes back)
                old = self._spilled.pop(k, None)
                if old is not None:
                    stale.append(old[0])
            over = (len(self._entries) - self.max_entries
                    if self.max_entries is not None else 0)
        for h in stale:
            self.swap.discard(h)
        if over > 0:
            self.evict(over)
        if new:
            self._set_gauges()
        return new

    # -- evict -----------------------------------------------------------

    def evict(self, n_blocks: int) -> int:
        """Reclaim up to ``n_blocks`` pages from UNREFERENCED entries
        (block refcount 1 — only the cache pins it), least recently
        used first, leaves before parents. Entries some live request
        still adopts (refcount >= 2) are never touched. Returns the
        number of pages actually returned to the free list."""
        _chaos.maybe_fire("prefix/evict")
        freed = 0
        # batched passes: each pass sweeps the LRU order ONCE and takes
        # every currently-eligible leaf (a per-victim restart would be
        # O(freed x entries) on the admission hot path); freeing a leaf
        # can make its parent eligible, so passes repeat until the
        # budget is met or a sweep finds nothing — bounded by the
        # longest chain, not by the entry count
        while freed < n_blocks:
            victims = []
            with self._lock:
                for e in self._entries.values():   # OrderedDict = LRU order
                    if freed + len(victims) >= n_blocks:
                        break
                    if e.children == 0 and self.kv.block_refs(e.block) == 1:
                        victims.append(e)
                for e in victims:
                    del self._entries[e.key]
                    if e.parent is not None:
                        p = self._entries.get(e.parent)
                        if p is not None:
                            p.children -= 1
            if not victims:
                break
            # second chance (ISSUE 18): spill each victim's page to the
            # host tier BEFORE releasing the device block — the spill
            # snapshots (ids, page handles) and the release is then
            # safe, the functional handles keep the bytes alive for the
            # stager. Host-pool pressure drops the COLDEST spilled keys
            # first; if the pool still can't cover it the victim is
            # dropped exactly like the pre-tier behavior.
            if self.swap is not None:
                spilled = 0
                # one spill_many per sweep: per-victim handles (the
                # second-chance index stays per-key) but ONE snapshot
                # and ONE stager fetch for the whole pass — spilling a
                # chain must not pay a device round-trip per block
                hs = self.swap.spill_many([[e.block] for e in victims],
                                          tag="prefix")
                short = [i for i, h in enumerate(hs) if h is None]
                if short and self.drop_spilled(len(short)):
                    again = self.swap.spill_many(
                        [[victims[i].block] for i in short], tag="prefix")
                    for i, h in zip(short, again):
                        hs[i] = h
                for e, h in zip(victims, hs):
                    if h is None:
                        continue
                    old = None
                    with self._lock:
                        old = self._spilled.pop(e.key, None)
                        self._spilled[e.key] = (h, e.depth)
                    if old is not None:
                        self.swap.discard(old[0])
                    spilled += 1
                if spilled:
                    self._spills += spilled
                    if obs.enabled():
                        obs.counter(f"{self.metric_prefix}"
                                    "_spills").inc(spilled)
            self.kv.release([e.block for e in victims])
            freed += len(victims)
            self._evictions += len(victims)
            if obs.enabled():
                obs.counter(f"{self.metric_prefix}_evictions").inc(
                    len(victims))
        if freed:
            self._set_gauges()
        return freed

    def drop_spilled(self, n_blocks: int) -> int:
        """Reclaim host-pool reservations from the COLDEST spilled keys
        (front of the second-chance index = oldest spill). Returns the
        host blocks actually returned. Called under host-pool pressure
        — by eviction's own spill path and by the scheduler's
        preemption — so the freshest spills survive longest."""
        dropped = []
        got = 0
        with self._lock:
            while got < n_blocks and self._spilled:
                _key, (h, _depth) = self._spilled.popitem(last=False)
                dropped.append(h)
                got += h.n_blocks
        freed = 0
        for h in dropped:
            freed += self.swap.discard(h)
        if dropped:
            self._set_gauges()
        return freed

    def clear(self) -> int:
        """Release every entry's page (shutdown: the leak gate demands
        ``kv_blocks_in_use`` drain to zero once the last owner freed)
        and settle every spilled handle (the HOST pool drains too).
        Returns the entry count dropped."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            spilled = [h for h, _d in self._spilled.values()]
            self._spilled.clear()
        for e in entries:
            self.kv.release([e.block])
        for h in spilled:
            self.swap.discard(h)
        self._set_gauges()
        return len(entries)

    # -- internals -------------------------------------------------------

    def _on_remap(self, remap: dict):
        """Ledger defrag moved pages: follow them (called right after
        the table rewrite, outside the ledger lock — index-only work)."""
        with self._lock:
            for e in self._entries.values():
                e.block = remap.get(e.block, e.block)

    def pinned_blocks(self) -> dict:
        """``{physical_block: pin_count}`` for every resident entry —
        the ownerless references this cache holds in the ledger, handed
        to :meth:`PagedKVCache.audit` so the auditor can demand EXACT
        refcount accounting (refcount == table refs + these pins)."""
        with self._lock:
            out: dict = {}
            for e in self._entries.values():
                out[e.block] = out.get(e.block, 0) + 1
            return out

    def stats(self) -> dict:
        with self._lock:
            n = len(self._entries)
            depth = max((e.depth + 1 for e in self._entries.values()),
                        default=0)
            spilled = len(self._spilled)
        return {
            "entries": n,
            "max_chain_blocks": depth,
            "evictions": self._evictions,
            "shared_blocks": self.kv.shared_blocks(),
            "spilled_entries": spilled,
            "spills": self._spills,
            "refills": self._refills,
            "hits_after_spill": self._hits_after_spill,
        }

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def _set_gauges(self):
        if not obs.enabled():
            return
        pre = self.metric_prefix
        obs.gauge(f"{pre}_entries").set(len(self))
        obs.gauge(f"{pre}_shared_blocks").set(self.kv.shared_blocks())
        with self._lock:
            spilled = len(self._spilled)
        obs.gauge(f"{pre}_spilled_entries").set(spilled)
