"""Versioned model registry with atomic hot swap.

The serving engine never holds params directly — it reads
``registry.current()`` ONCE per micro-batch, so a swap lands exactly on
a batch boundary: every request in a batch is answered by one version,
the old version keeps serving the batches already cut against it until
they drain, and no batch ever mixes versions. ``publish()`` does the
expensive part (host→device placement of the new params) on the CALLER's
thread — the batcher keeps dispatching against the active version while
the new one loads — and ``activate()`` is a pointer write under a lock.

Mesh placement (r10): a registry constructed with a ``Mesh`` + param
PartitionSpecs does the SHARDED load in ``publish()`` — every leaf
lands on the mesh with its spec (TP column/row shards, FSDP 1/N
slices), on the publishing thread, so a model that doesn't fit one
chip hot-swaps exactly like a single-device one: load sharded in the
background, ``activate()`` flips the pointer, the next dispatch serves
the new placement atomically. ``param_specs`` may be a spec tree or a
callable ``params -> spec tree`` (re-resolved per publish, so versions
with fresh leaf structure still place correctly).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp


class ModelVersion:
    """Immutable (version id, device-resident params/state) snapshot."""

    __slots__ = ("version", "params", "state")

    def __init__(self, version: str, params, state):
        self.version = version
        self.params = params
        self.state = state

    def __repr__(self):
        return f"ModelVersion({self.version!r})"


def _place(tree):
    """Host→device placement of a params/state pytree (no-op leaves that
    are already device arrays)."""
    if tree is None:
        return None
    return jax.tree_util.tree_map(jnp.asarray, tree)


class ModelRegistry:
    """Thread-safe version store: ``publish`` loads, ``activate`` swaps.

    Old versions stay resident until :meth:`retire` — instant rollback is
    ``activate(previous)``. Retiring the active version is refused (it
    may be mid-batch).

    With ``mesh`` + ``param_specs`` given, every publish is a SHARDED
    load: leaves land on the mesh per their PartitionSpec
    (``parallel.sharding.place_with_specs``); ``state_specs`` defaults
    to fully replicated. Swap semantics are unchanged — the placement
    cost rides the publishing thread, activation stays a pointer write."""

    def __init__(self, mesh=None, param_specs=None, state_specs=None):
        self._versions: Dict[str, ModelVersion] = {}
        self._order: List[str] = []
        self._active: Optional[str] = None
        self._counter = 0
        self._used: set = set()  # every id EVER published — retire must
        self._lock = threading.Lock()  # not let an id be re-minted
        self.mesh = mesh
        self._param_specs = param_specs
        self._state_specs = state_specs

    def _place_tree(self, tree, specs):
        """Mesh-aware placement of one pytree: sharded when the registry
        has a mesh (specs resolved per publish when callable, replicated
        when no specs were given), plain device load otherwise."""
        if tree is None:
            return None
        if self.mesh is None:
            return _place(tree)
        from ..parallel.sharding import place_with_specs
        from jax.sharding import PartitionSpec as P
        specs = specs(tree) if callable(specs) else specs
        if specs is None:
            specs = jax.tree_util.tree_map(lambda _: P(), tree)
        return place_with_specs(tree, self.mesh, specs)

    def publish(self, params, state=None, version: Optional[str] = None,
                activate: bool = False, transform=None) -> str:
        """Load a new version (device placement happens HERE, on the
        calling thread — the background-load half of a hot swap; sharded
        onto the registry's mesh when it has one) and optionally
        activate it. Returns the version id (auto-assigned ``v<n>`` when
        not given).

        ``transform`` — optional ``params -> params`` callable run
        exactly ONCE, here on the publishing thread, BEFORE placement:
        a declared derivation (``quantization.lm.quantize_lm_params``
        for a weight-only int8/int4 serving version, a dtype cast, a
        LoRA merge) becomes registry policy instead of a convention
        every publishing call site must remember. The stored version
        holds the TRANSFORMED params; swap semantics are unchanged
        (activation stays a pointer flip, in-flight batches keep the
        version they pinned)."""
        if transform is not None:
            params = transform(params)
        placed = ModelVersion("", self._place_tree(params, self._param_specs),
                              self._place_tree(state, self._state_specs))
        with self._lock:
            if version is None:
                # skip ids ever taken (explicit publishes AND retired
                # versions) — re-minting an id would let one version
                # string name two different models in the audit trail
                while f"v{self._counter}" in self._used:
                    self._counter += 1
                version = f"v{self._counter}"
                self._counter += 1
            elif version in self._used:
                raise ValueError(f"version {version!r} already published "
                                 "(versions are immutable — pick a new id)")
            self._used.add(version)
            placed.version = version
            self._versions[version] = placed
            self._order.append(version)
            if activate or self._active is None:
                self._active = version
        return version

    def activate(self, version: str):
        """Atomic swap: the next ``current()`` read — i.e. the next
        micro-batch — serves this version."""
        with self._lock:
            if version not in self._versions:
                raise KeyError(f"unknown version {version!r}; published: "
                               f"{self._order}")
            self._active = version

    def current(self) -> Optional[ModelVersion]:
        with self._lock:
            return (self._versions[self._active]
                    if self._active is not None else None)

    def get(self, version: str) -> ModelVersion:
        with self._lock:
            return self._versions[version]

    def versions(self) -> List[str]:
        with self._lock:
            return list(self._order)

    @property
    def active_version(self) -> Optional[str]:
        with self._lock:
            return self._active

    def retire(self, version: str):
        """Drop a drained version's device memory. The active version is
        protected — activate a replacement first."""
        with self._lock:
            if version == self._active:
                raise ValueError(f"version {version!r} is active — "
                                 "activate a replacement before retiring")
            self._versions.pop(version, None)
            if version in self._order:
                self._order.remove(version)
