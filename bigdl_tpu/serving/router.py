"""SLO-aware router: N serving replicas behind priority-class queues.

One :class:`~.engine.ServingEngine` (or
:class:`~.decode_scheduler.DecodeScheduler`) is one queue with one
latency profile. Production traffic is not one profile: interactive
requests carry tight deadlines, bulk/batch requests carry loose ones,
and a single FIFO queue makes the tight ones wait behind the loose ones
exactly when load is high — the moment the SLO matters. The reference
BigDL's PredictionService load-balanced complete model replicas
round-robin with no deadline awareness at all; this router is the
TPU-native upgrade of that tier:

* **Priority classes with weighted-fair queuing** — each
  :class:`PriorityClass` owns a bounded queue and a weight;
  the dispatch loop runs deficit round-robin over the classes, so an
  8:1 interactive:bulk weighting serves ~8 interactive requests per
  bulk one under contention while an idle class costs nothing (work
  conservation: whoever has traffic gets the capacity).
* **Deadline-aware dispatch** — a request with a deadline is placed on
  the LEAST-LOADED healthy replica (it cannot afford to queue behind a
  deep one); deadline-less requests round-robin. A request whose
  deadline is already unmeetable at ``submit()`` — expired, or under
  the class's observed service-time EWMA — **fails fast at admission**
  (typed :class:`DeadlineExceeded`, ``serve/router_doomed``) instead of
  burning replica capacity on an answer nobody will wait for.
* **Per-replica health integration** — every replica engine registers
  a NAMED stall-watchdog beacon (``ServingEngine(name=...)``); the
  router listens for that beacon's ``health/stall`` event, DRAINS the
  replica (no new traffic), and re-dispatches its in-flight requests
  onto the survivors — requests complete on survivors, none are lost.
  The replica rejoins on ``health/stall_recovered``. ``EngineStopped``
  from a replica mid-flight takes the same failover path.
* **Prefix-affinity dispatch** — KV-cache-aware routing (ISSUE 12):
  for scheduler replicas with a prefix cache, dispatch probes each
  healthy replica's cached-prefix summary for the prompt and prefers
  the one already holding the longest prefix (its admission skips that
  prefill entirely); a holder deeper than the least-loaded replica by
  more than ``affinity_slack`` in-flight requests is bypassed, so
  affinity never starves the WFQ/deadline machinery.
* **Hot swap across the fleet** — :meth:`Router.swap` publishes the
  new version to every replica (each load sharded per that replica's
  mesh placement, on this thread) and activates per replica
  atomically; every response still names the exact version that
  answered it, and no response mixes versions.

Replicas are engine objects (mesh-placed or single-device — the router
does not care: a TP-placed engine over 4 chips and a small whole-model
replica are both just ``submit()`` targets), so the two serving axes
compose: model-parallel placement inside a replica, replica-parallel
routing across them. Metrics ride the ``serve/router_*`` namespace and
feed the PR-7 cluster aggregation like every other serving metric
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import itertools
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..observability import cluster as _cluster
from ..observability import flight as _flight
from ..observability import health as _health
from ..parallel import chaos as _chaos
from ..parallel.failure import TRANSIENT, classify_failure
from .batching import (DeadlineExceeded, EngineStopped, QueueFull,
                       ServeFuture)

THREAD_NAME = "bigdl_tpu-serving-router"

_STAT_KEYS = ("submitted", "completed", "rejected", "doomed", "dispatches",
              "failovers", "drains", "rejoins", "deadline_misses",
              "replica_full", "affinity_hits", "affinity_bypassed",
              "kv_recoveries", "dispatch_retries", "joins", "retires")

#: per-request cap on transient-classified submit failures: a transport
#: that keeps presenting as transient is not transient — past this the
#: request fails typed instead of park-and-retrying forever
_MAX_DISPATCH_RETRIES = 32


def _metric_cls(name: str) -> str:
    """Class name → metric-name fragment (prometheus-safe)."""
    return re.sub(r"\W", "_", name)


class PriorityClass:
    """One latency tier: a bounded queue with a weighted-fair share.

    weight : deficit-round-robin share under contention (an idle class
        consumes nothing — work-conserving).
    default_deadline_ms : applied when ``submit`` passes none; None
        means requests of this class run deadline-less (routed
        round-robin, never doomed).
    max_queue : router-side admission bound for this class (typed
        :class:`QueueFull` past it) — one class flooding cannot starve
        another's admission.
    depth_limit : max outstanding requests of THIS class per replica
        (None = bounded only by the replica's own queue). The
        head-of-line lever for mixed tiers: a deep bulk backlog
        dispatched freely would stuff every replica's FIFO ahead of
        each arriving tight request — capping bulk at a shallow depth
        (2 keeps replicas pipelined) leaves the replica queues nearly
        empty for the tight tier, which is what bounds tight latency
        to ~2 batch cycles under full bulk overload.
    replica_tags : class→replica affinity for HETEROGENEOUS fleets
        (ISSUE 15 satellite, the direction-4b stepping stone): when
        set, requests of this class dispatch ONLY to replicas whose
        ``tags`` (``DecodeScheduler(tags=...)`` /
        ``ServingEngine(tags=...)`` / a fleet member's membership tags)
        intersect this set — e.g. bulk traffic pinned to
        int8-published replicas while tight traffic rides the f32
        fleet. Composes with least-loaded/deadline placement,
        prefix-affinity, and ``depth_limit`` (all operate on the
        tag-filtered candidate set); ``None`` keeps the class
        fleet-wide. The router validates at construction that at least
        one replica carries each demanded tag set.
    """

    def __init__(self, name: str, weight: int = 1,
                 default_deadline_ms: Optional[float] = None,
                 max_queue: int = 1024,
                 depth_limit: Optional[int] = None,
                 replica_tags: Optional[Sequence[str]] = None):
        if weight < 1:
            raise ValueError(f"weight must be >= 1, got {weight}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if depth_limit is not None and depth_limit < 1:
            raise ValueError(f"depth_limit must be >= 1, got {depth_limit}")
        if replica_tags is not None and not replica_tags:
            raise ValueError("replica_tags must name at least one tag "
                             "(None means any replica)")
        self.name = name
        self.weight = int(weight)
        self.default_deadline_ms = default_deadline_ms
        self.max_queue = int(max_queue)
        self.depth_limit = depth_limit
        self.replica_tags = (frozenset(replica_tags)
                             if replica_tags is not None else None)

    def __repr__(self):
        return (f"PriorityClass({self.name!r}, weight={self.weight}, "
                f"deadline={self.default_deadline_ms})")


class _ClassQueue:
    __slots__ = ("cls", "q", "deficit", "ewma_ms")

    def __init__(self, cls: PriorityClass):
        self.cls = cls
        self.q: deque = deque()
        self.deficit = 0.0
        # the ADMISSION estimate the doomed check reads: the best
        # (minimum) per-replica service-time EWMA across currently
        # HEALTHY replicas — kept per replica (``_Replica.ewma_ms``)
        # and re-derived on drain/rejoin, so a recovered replica's
        # pre-stall latencies can never doom tight requests (ISSUE 13)
        self.ewma_ms: Optional[float] = None


class _RouterRequest:
    __slots__ = ("payload", "kw", "klass", "future", "rid", "deadline",
                 "t_enqueue", "t_enqueue_ns", "t_dispatch_ns", "failovers",
                 "epoch", "recovered", "dispatch_retries")

    def __init__(self, payload, kw, klass, rid,
                 deadline_s: Optional[float]):
        self.payload = payload
        self.kw = kw
        # tokens a dying replica already decoded for this request
        # (KV-preserving failover splices them into the payload and the
        # final result — see Router._recover_decode)
        self.recovered: Optional[np.ndarray] = None
        self.klass = klass
        self.future = ServeFuture()
        self.future.rid = rid
        self.rid = rid
        self.t_enqueue = time.monotonic()
        self.t_enqueue_ns = time.perf_counter_ns()
        self.t_dispatch_ns = None
        self.deadline = (self.t_enqueue + deadline_s
                         if deadline_s is not None else None)
        self.failovers = 0
        self.dispatch_retries = 0
        # dispatch epoch: bumped on every failover so a LATE resolution
        # of an abandoned inner future (a drained replica finishing or
        # dying after its work was re-routed) is recognizably stale and
        # cannot fail the request over a second time
        self.epoch = 0

    def remaining_ms(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline is None:
            return None
        return (self.deadline - (now or time.monotonic())) * 1000.0

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.monotonic()) > self.deadline)


class _Replica:
    __slots__ = ("engine", "name", "healthy", "dead", "inflight",
                 "by_class", "ewma_ms", "tags")

    def __init__(self, engine, name: str):
        self.engine = engine
        self.name = name
        self.tags = frozenset(getattr(engine, "tags", ()) or ())
        self.healthy = True
        self.dead = False            # EngineStopped — no rejoin possible
        self.inflight: set = set()   # _RouterRequest currently submitted
        self.by_class: Dict[str, int] = {}   # outstanding per class
        self.ewma_ms: Dict[str, float] = {}  # per-class service time

    @property
    def beacon_name(self) -> str:
        return getattr(self.engine, "beacon_name", "")


class Router:
    """Deadline- and health-aware dispatch over N engine replicas.

    Parameters
    ----------
    replicas : engine objects (``ServingEngine`` / ``DecodeScheduler`` /
        anything with ``submit(payload, deadline_ms=..., **kw)`` →
        future plus ``start/shutdown/swap``). Give each a distinct
        ``name=`` at construction — that names its watchdog beacon,
        which is what the router's per-replica health integration keys
        on.
    classes : :class:`PriorityClass` list (default: one ``"default"``
        class, weight 1 — plain least-loaded/round-robin routing).
    max_failovers : re-dispatch budget per request (a request bouncing
        across dying replicas must eventually fail, not loop).
    fail_fast_factor : a deadline-carrying request is DOOMED at
        admission when its remaining budget is under ``factor`` × the
        class's observed service-time EWMA (0 disables the estimate —
        only already-expired deadlines fail fast).
    manage_replicas : ``start()``/``shutdown()`` cascade to the
        replicas (the common ownership); False when the caller runs
        their lifecycle.
    prefix_affinity : KV-cache-aware placement (on by default; a no-op
        unless a replica exposes ``cached_prefix_tokens`` — i.e. a
        :class:`~.decode_scheduler.DecodeScheduler` with its prefix
        cache enabled). Dispatch probes each healthy replica's
        prefix-cache summary for the prompt and prefers the replica
        already holding the LONGEST cached prefix: the hit skips that
        prefix's prefill there, where any other placement re-pays it.
        Affinity is bounded by ``affinity_slack``: a cache-holder whose
        in-flight depth exceeds the least-loaded healthy replica's by
        more than the slack is bypassed (counted), so affinity never
        starves the deadline/least-loaded machinery — a hot prefix
        cannot capsize one replica while others idle.
    affinity_slack : max extra in-flight requests a prefix-affine
        replica may carry over the least-loaded one before affinity
        yields to load balance.
    """

    def __init__(self, replicas: Sequence, *,
                 classes: Optional[Sequence[PriorityClass]] = None,
                 max_failovers: int = 2,
                 fail_fast_factor: float = 0.5,
                 manage_replicas: bool = True,
                 name: str = "router",
                 prefix_affinity: bool = True,
                 affinity_slack: int = 4,
                 stall_deadline_s: Optional[float] = None):
        if not replicas:
            raise ValueError("need at least one replica")
        self._replicas: List[_Replica] = []
        seen = set()
        for i, eng in enumerate(replicas):
            rname = getattr(eng, "name", None) or f"replica{i}"
            if rname in seen:
                raise ValueError(f"duplicate replica name {rname!r} — "
                                 "construct each engine with a distinct "
                                 "name= so health events are attributable")
            seen.add(rname)
            self._replicas.append(_Replica(eng, rname))
        classes = list(classes) if classes else [PriorityClass("default")]
        self._classes: Dict[str, _ClassQueue] = {}
        for c in classes:
            if c.name in self._classes:
                raise ValueError(f"duplicate class {c.name!r}")
            if c.replica_tags is not None and not any(
                    r.tags & c.replica_tags for r in self._replicas):
                raise ValueError(
                    f"class {c.name!r} demands replica_tags "
                    f"{sorted(c.replica_tags)} but no replica carries "
                    f"any of them (replica tags: "
                    f"{ {r.name: sorted(r.tags) for r in self._replicas} })")
            self._classes[c.name] = _ClassQueue(c)
        self.max_failovers = int(max_failovers)
        self.fail_fast_factor = float(fail_fast_factor)
        self.manage_replicas = bool(manage_replicas)
        self.prefix_affinity = bool(prefix_affinity)
        self.affinity_slack = int(affinity_slack)
        # capability probe once: affinity costs nothing on fleets whose
        # engines expose no prefix summary (plain ServingEngines)
        self._any_prefix = any(
            callable(getattr(r.engine, "cached_prefix_tokens", None))
            for r in self._replicas)
        self.name = name
        self.beacon_name = f"serving/router[{name}]"
        self.stall_deadline_s = stall_deadline_s
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._stop = threading.Event()
        self._pending = 0
        self._rids = itertools.count()
        self._rr = 0
        self._stats = dict.fromkeys(_STAT_KEYS, 0)
        self._stats_lock = threading.Lock()
        self._beacon = _health.NULL_BEACON
        self._snap_writer = _cluster.default_writer()
        self._by_beacon = {}
        for r in self._replicas:
            if not r.beacon_name:
                continue
            if r.beacon_name in self._by_beacon and len(self._replicas) > 1:
                # two engines sharing a beacon name would make a stall
                # un-attributable — the drain could take out the WRONG
                # replica while traffic keeps flowing to the stalled one
                raise ValueError(
                    f"replicas share the beacon name {r.beacon_name!r} — "
                    "construct each engine with a distinct name= so "
                    "health events are attributable per replica")
            self._by_beacon[r.beacon_name] = r

    # -- lifecycle -------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        if self._closed:
            raise EngineStopped("router was shut down; build a new one")
        if self.manage_replicas:
            for r in self._replicas:
                r.engine.start()
        _health.listeners.append(self._on_health_event)
        self._beacon = _health.beacon(self.beacon_name,
                                      deadline_s=self.stall_deadline_s)
        self._thread = threading.Thread(target=self._run, name=THREAD_NAME,
                                        daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every admitted request resolved (True) or the
        timeout passed (False)."""
        with self._cond:
            return self._cond.wait_for(lambda: self._pending == 0, timeout)

    def shutdown(self, drain: bool = True, timeout: float = 60.0):
        """Graceful by default: stop admitting, route everything queued,
        wait for in-flight work, then (when ``manage_replicas``) drain
        the replicas. ``drain=False`` abandons queued work typed."""
        with self._lock:
            self._closed = True
        if not drain:
            self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                import logging
                logging.getLogger(__name__).warning(
                    "router loop did not join within %.0fs", timeout)
        try:
            _health.listeners.remove(self._on_health_event)
        except ValueError:
            pass
        self._beacon.close()
        if self.manage_replicas:
            for r in self._replicas:
                try:
                    r.engine.shutdown(drain=drain)
                except Exception:
                    pass
        # anything still queued fails typed rather than hanging a client
        leftovers = []
        with self._lock:
            for cq in self._classes.values():
                leftovers.extend(cq.q)
                cq.q.clear()
        for req in leftovers:
            if not req.future.done():
                try:
                    req.future.set_exception(EngineStopped(
                        "router shut down before dispatch"))
                except Exception:
                    pass

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False

    # -- client surface --------------------------------------------------

    def submit(self, payload, klass: str = "default",
               deadline_ms: Optional[float] = None, **kw) -> ServeFuture:
        """Enqueue one request under a priority class. ``payload`` and
        ``**kw`` flow through to the replica's ``submit`` (a
        ``DecodeScheduler`` fleet takes ``max_new_tokens=`` etc.).

        Admission control is typed: :class:`QueueFull` past the class
        queue bound, :class:`EngineStopped` after shutdown began, and —
        the deadline-aware part — :class:`DeadlineExceeded` for a
        DOOMED request: its deadline is already unmeetable (expired, or
        under ``fail_fast_factor`` × the class's observed service-time
        EWMA), so failing in microseconds beats failing after burning a
        replica dispatch on it."""
        try:
            cq = self._classes[klass]
        except KeyError:
            raise ValueError(
                f"unknown priority class {klass!r}; configured: "
                f"{list(self._classes)}") from None
        ms = (deadline_ms if deadline_ms is not None
              else cq.cls.default_deadline_ms)
        if ms is not None:
            est = cq.ewma_ms
            if ms <= 0 or (self.fail_fast_factor > 0 and est is not None
                           and ms < self.fail_fast_factor * est):
                self._bump("doomed")
                if obs.enabled():
                    obs.counter("serve/router_doomed").inc()
                raise DeadlineExceeded(
                    f"deadline {ms:.1f}ms is unmeetable (class "
                    f"{klass!r} service estimate "
                    f"{est if est is None else round(est, 1)}ms) — "
                    "doomed requests fail at admission")
        req = _RouterRequest(payload, kw, klass, next(self._rids),
                             ms / 1000.0 if ms is not None else None)
        with self._lock:
            if self._closed:
                raise EngineStopped("router is shutting down")
            if len(cq.q) >= cq.cls.max_queue:
                self._bump("rejected")
                if obs.enabled():
                    obs.counter("serve/router_rejected").inc()
                raise QueueFull(
                    f"class {klass!r} queue at capacity "
                    f"({cq.cls.max_queue}) — shed or retry with backoff")
            cq.q.append(req)
            self._pending += 1
        req.future.add_done_callback(lambda f: self._on_done(f))
        self._bump("submitted")
        if obs.enabled():
            obs.gauge(
                f"serve/router_queue_depth_{_metric_cls(klass)}").set(
                    len(cq.q))
        self._wake.set()
        return req.future

    def predict(self, payload, timeout: Optional[float] = None, **kw):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        if self._thread is None:
            raise RuntimeError("router not started — call start() or use "
                               "it as a context manager")
        return self.submit(payload, **kw).result(timeout)

    def swap(self, params, state=None, version: Optional[str] = None) -> str:
        """Fleet-wide hot swap, TWO-PHASE so the fleet never splits:
        phase 1 publishes the new version on EVERY replica (each
        registry does its own — possibly sharded — load on THIS
        thread; traffic keeps flowing on the old version); only when
        every publish landed does phase 2 activate everywhere
        (activation after a successful publish is a pointer write that
        cannot fail). A publish failure mid-fleet retires the copies
        already loaded and re-raises — all replicas stay on the OLD
        version rather than serving two answers for one request
        depending on placement. ``state=None`` inherits each replica's
        active state (the params-only swap contract). Each replica
        still flips at its own batch boundary, so every response is
        old-or-new, never mixed."""
        v = version or f"rv{next(self._rids)}"
        published = []
        try:
            for r in self._replicas:
                st = state
                if st is None:
                    cur = r.engine.registry.current()
                    st = cur.state if cur is not None else \
                        r.engine.model.state
                r.engine.registry.publish(params, st, version=v,
                                          activate=False)
                published.append(r)
        except BaseException:
            for r in published:
                try:
                    r.engine.registry.retire(v)
                except Exception:
                    pass
            raise
        for r in self._replicas:
            r.engine.registry.activate(v)
            r.engine._bump("swaps")
            if obs.enabled():
                obs.instant("serve/swap", version=v, replica=r.name)
        if obs.enabled():
            obs.instant("serve/router_swap", version=v)
        return v

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        with self._lock:
            out["pending"] = self._pending
            out["queue_depth"] = {k: len(cq.q)
                                  for k, cq in self._classes.items()}
            out["replicas"] = {
                r.name: {"healthy": r.healthy,
                         "inflight": len(r.inflight)}
                for r in self._replicas}
        # per-replica prefix summary (the affinity signal, surfaced
        # next to the load signal): resident entry/shared-block counts
        # from each scheduler's prefix cache
        for r in self._replicas:
            pc = getattr(r.engine, "prefix", None)
            if pc is not None:
                out["replicas"][r.name]["prefix"] = pc.stats()
        return out

    def healthy_replicas(self) -> List[str]:
        with self._lock:
            return [r.name for r in self._replicas if r.healthy]

    # -- dynamic membership (ISSUE 19) -----------------------------------

    def add_replica(self, engine, name: Optional[str] = None) -> str:
        """Register one more replica on a RUNNING router (the elastic
        scale-up path). Same invariants as construction — distinct
        name, distinct beacon — enforced under the lock; the membership
        list is REPLACED rather than mutated in place so `swap`'s
        lock-free iteration sees either the old fleet or the new one,
        never a half-grown list. When ``manage_replicas``, a router
        that is already started starts the engine too. Returns the
        registered replica name."""
        rname = name or getattr(engine, "name", None)
        rep = _Replica(engine, rname or "")
        with self._lock:
            if self._closed:
                raise EngineStopped("router is shutting down")
            if not rep.name:
                rep.name = f"replica{len(self._replicas)}"
            if any(r.name == rep.name for r in self._replicas):
                raise ValueError(f"duplicate replica name {rep.name!r}")
            bn = rep.beacon_name
            if bn and bn in self._by_beacon:
                raise ValueError(
                    f"replica {rep.name!r} shares the beacon name {bn!r} "
                    "with an existing replica — health events would be "
                    "un-attributable")
            running = self._thread is not None
        if self.manage_replicas and running:
            engine.start()
        err: Optional[Exception] = None
        with self._lock:
            # re-validate: the lock was dropped around engine.start(),
            # so the router may have closed — or a concurrent add may
            # have taken the name/beacon — in between
            if self._closed:
                err = EngineStopped("router is shutting down")
            elif any(r.name == rep.name for r in self._replicas):
                err = ValueError(f"duplicate replica name {rep.name!r}")
            elif bn and bn in self._by_beacon:
                err = ValueError(
                    f"replica {rep.name!r} shares the beacon name {bn!r} "
                    "with an existing replica — health events would be "
                    "un-attributable")
            else:
                self._replicas = self._replicas + [rep]
                if bn:
                    self._by_beacon[bn] = rep
                self._any_prefix = self._any_prefix or callable(
                    getattr(engine, "cached_prefix_tokens", None))
                for k in self._classes:
                    self._reseed_ewma_locked(k)
        if err is not None:
            # undo the start — the engine never entered rotation
            if self.manage_replicas and running:
                try:
                    engine.shutdown(drain=True)
                except Exception:  # noqa: BLE001 — undo is best-effort
                    pass
            raise err
        self._bump("joins")
        if obs.enabled():
            obs.counter("serve/router_joins").inc()
            obs.instant("serve/router_join", replica=rep.name)
        self._wake.set()
        return rep.name

    def remove_replica(self, name: str):
        """Deregister a replica (the elastic scale-DOWN path): drain it
        through the existing drain machinery — its in-flight requests
        fail over to survivors, no client loses a request — then drop
        it from rotation. The engine is NOT shut down here even under
        ``manage_replicas``: retirement sequencing (drain the agent,
        wait for its queues, then stop it) belongs to the caller, who
        gets the engine back. Refuses to remove the last replica or to
        strand a tag-demanding class with zero matching replicas."""
        with self._lock:
            rep = next((r for r in self._replicas if r.name == name), None)
            if rep is None:
                raise ValueError(f"no replica named {name!r}")
            if len(self._replicas) == 1:
                raise ValueError(
                    "cannot remove the last replica — shut the router "
                    "down instead")
            rest = [r for r in self._replicas if r is not rep]
            for cq in self._classes.values():
                tags = cq.cls.replica_tags
                if tags is not None and not any(r.tags & tags
                                                for r in rest):
                    raise ValueError(
                        f"removing {name!r} would leave class "
                        f"{cq.cls.name!r} (replica_tags {sorted(tags)}) "
                        "with no eligible replica")
        # out of rotation first (re-routes its in-flight requests onto
        # the survivors), THEN deregister — the drain path needs the
        # replica still resolvable while it strands/fails-over
        self._drain_replica(rep, reason="retired")
        with self._lock:
            rep.dead = True   # a retired replica must never rejoin
            self._replicas = [r for r in self._replicas if r is not rep]
            bn = rep.beacon_name
            if bn and self._by_beacon.get(bn) is rep:
                del self._by_beacon[bn]
            self._any_prefix = any(
                callable(getattr(r.engine, "cached_prefix_tokens", None))
                for r in self._replicas)
            for k in self._classes:
                self._reseed_ewma_locked(k)
        self._bump("retires")
        if obs.enabled():
            obs.counter("serve/router_retires").inc()
            obs.instant("serve/router_retire", replica=name)
        self._wake.set()
        return rep.engine

    # -- routing loop ----------------------------------------------------

    def _run(self):
        try:
            self._route_loop()
        except BaseException as e:  # noqa: BLE001 — post-mortem, then die
            if obs.enabled():
                _flight.dump_crash_bundle(error=e, context={
                    "component": "serving/router",
                    "stats": {k: v for k, v in self.stats().items()
                              if k not in ("replicas", "queue_depth")}})
            raise

    def _route_loop(self):
        """The dispatch loop: one deficit-round-robin pass over the
        class queues per wakeup. Everything here is host bookkeeping —
        the device work happens inside the replicas' own batcher
        threads, so a slow dispatch never blocks routing."""
        while not self._stop.is_set():
            self._beacon.pulse()
            if obs.enabled():
                self._snap_writer.maybe_write()
            did = self._drr_round()
            with self._lock:
                idle = all(not cq.q for cq in self._classes.values())
                inflight = sum(len(r.inflight) for r in self._replicas)
                if self._closed and idle and inflight == 0:
                    break
            if not did:
                self._wake.wait(0.02)
                self._wake.clear()

    def _drr_round(self) -> bool:
        """Deficit round-robin: each backlogged class earns its weight
        in credits per pass and dispatches that many requests; an empty
        class forfeits its deficit (work conservation — no class banks
        credit while idle)."""
        did = False
        for cq in self._classes.values():
            with self._lock:
                backlogged = bool(cq.q)
            if not backlogged:
                cq.deficit = 0.0
                continue
            cq.deficit += cq.cls.weight
            while cq.deficit >= 1.0:
                with self._lock:
                    req = cq.q.popleft() if cq.q else None
                if req is None:
                    break
                cq.deficit -= 1.0
                if not self._dispatch_one(cq, req):
                    # THIS class is parked (depth_limit reached / its
                    # eligible replicas full) — move on to the next
                    # class rather than ending the round: a stuck bulk
                    # head must never block the tight queue behind it
                    break
                did = True
            if obs.enabled():
                obs.gauge("serve/router_queue_depth_"
                          f"{_metric_cls(cq.cls.name)}").set(len(cq.q))
        return did

    def _dispatch_one(self, cq: _ClassQueue, req: _RouterRequest) -> bool:
        """Route ONE request: deadline requests to the least-loaded
        healthy replica (they cannot afford a deep queue), deadline-less
        round-robin. Returns False when the request was PARKED (pushed
        back, nothing routable right now)."""
        if req.future.cancelled():
            return True
        now = time.monotonic()
        if req.expired(now):
            self._miss(req, cq, "deadline passed while queued at router")
            return True
        limit = cq.cls.depth_limit
        tags = cq.cls.replica_tags
        with self._lock:
            # class→replica affinity first: a tagged class only ever
            # sees its tag-matching replicas — least-loaded, deadline,
            # depth_limit and prefix-affinity all compose on the
            # filtered set
            eligible = (self._replicas if tags is None else
                        [r for r in self._replicas if r.tags & tags])
            healthy = [r for r in eligible if r.healthy]
            if limit is not None:
                healthy = [r for r in healthy
                           if r.by_class.get(req.klass, 0) < limit]
        if not healthy:
            with self._lock:
                all_dead = all(r.dead for r in eligible)
            if self._stop.is_set() or all_dead:
                # a drained replica may rejoin (park and wait); a DEAD
                # fleet never will — parking would hang every client
                self._fail(req, EngineStopped("no replicas left"))
                return True
            with self._lock:
                cq.q.appendleft(req)
            return False
        if req.deadline is not None:
            order = sorted(healthy, key=lambda r: len(r.inflight))
        else:
            self._rr += 1
            order = healthy[self._rr % len(healthy):] \
                + healthy[:self._rr % len(healthy)]
        aff = self._affinity_pick(req, healthy)
        if aff is not None:
            order = [aff] + [r for r in order if r is not aff]
        rem = req.remaining_ms(now)
        for rep in order:
            try:
                _chaos.maybe_fire("router/dispatch", tag=rep.name)
                inner = rep.engine.submit(req.payload, deadline_ms=rem,
                                          **req.kw)
            except QueueFull:
                self._bump("replica_full")
                if obs.enabled():
                    obs.counter("serve/router_replica_full").inc()
                continue
            except EngineStopped:
                self._mark_unhealthy(rep, "engine_stopped")
                continue
            except BaseException as e:  # noqa: BLE001 — fail THIS request
                if classify_failure(e) == TRANSIENT \
                        and req.dispatch_retries < _MAX_DISPATCH_RETRIES:
                    # a transient dispatch-path failure (flaky replica
                    # transport, injected fault) is worth the NEXT
                    # replica, not this request's life — bounded per
                    # request: a transport that NEVER stops presenting
                    # transient eventually fails the request typed
                    # instead of park-and-retrying forever
                    req.dispatch_retries += 1
                    self._bump("dispatch_retries")
                    if obs.enabled():
                        obs.counter("serve/router_dispatch_retries").inc()
                    continue
                self._fail(req, e)
                return True
            with self._lock:
                if not rep.healthy:
                    # drained between submit and registration: the
                    # drain's stranded snapshot could not have seen this
                    # request, so route it to the next replica ourselves
                    # (the orphaned inner future resolves into the void —
                    # the outer future is set exactly once)
                    continue
                rep.inflight.add(req)
                rep.by_class[req.klass] = \
                    rep.by_class.get(req.klass, 0) + 1
                # capture INSIDE the lock: a drain interleaving after
                # registration bumps the epoch under this same lock, so
                # the callback's epoch is guaranteed to describe THIS
                # dispatch, keeping the staleness guard sound
                req.t_dispatch_ns = time.perf_counter_ns()
                epoch = req.epoch
            self._bump("dispatches")
            if obs.enabled():
                obs.counter("serve/router_dispatches").inc()
                obs.gauge(f"serve/router_inflight_{rep.name}").set(
                    len(rep.inflight))
                obs.histogram(
                    "serve/router_queue_wait_ms_"
                    f"{_metric_cls(cq.cls.name)}", unit="ms").observe(
                        (time.perf_counter_ns() - req.t_enqueue_ns) / 1e6)
            inner.add_done_callback(
                lambda f, r=req, rp=rep, ep=epoch:
                self._on_inner_done(r, rp, f, ep))
            return True
        # every healthy replica's queue is full: park and retry — the
        # router's own bounded class queues are the real backpressure
        with self._lock:
            cq.q.appendleft(req)
        return False

    def _affinity_pick(self, req: _RouterRequest,
                       healthy: List[_Replica]) -> Optional[_Replica]:
        """Prefix-affinity placement: the healthy replica whose prefix
        cache reports the LONGEST resident prefix for this prompt (each
        replica's ``cached_prefix_tokens`` probe — a host-side digest
        walk, no device work), or None when nothing is cached, only one
        candidate exists, or the cache-holder is more than
        ``affinity_slack`` in-flight requests deeper than the
        least-loaded replica (affinity yields to load — the
        starvation guard)."""
        if not self.prefix_affinity or not self._any_prefix \
                or len(healthy) < 2:
            return None
        best, best_tokens = None, 0
        for rep in healthy:
            probe = getattr(rep.engine, "cached_prefix_tokens", None)
            if not callable(probe):
                continue
            try:
                n = int(probe(req.payload))
            except Exception:
                continue   # malformed payload for this engine — no bias
            if n > best_tokens:
                best, best_tokens = rep, n
        if best is None:
            return None
        min_load = min(len(r.inflight) for r in healthy)
        if len(best.inflight) - min_load > self.affinity_slack:
            self._bump("affinity_bypassed")
            if obs.enabled():
                obs.counter("serve/router_affinity_bypassed").inc()
            return None
        self._bump("affinity_hits")
        if obs.enabled():
            obs.counter("serve/router_affinity_hits").inc()
        return best

    def _on_inner_done(self, req: _RouterRequest, rep: _Replica, inner,
                       epoch: int = 0):
        """Resolve the client future from the replica's future — or
        FAIL OVER: a replica that died mid-request (EngineStopped, or
        drained by its stall beacon before answering) sends the request
        back through the queue to complete on a survivor."""
        with self._lock:
            if req in rep.inflight:
                rep.inflight.discard(req)
                rep.by_class[req.klass] = \
                    max(0, rep.by_class.get(req.klass, 1) - 1)
            stale = epoch != req.epoch
        # a replica slot freed: parked depth-limited classes can route
        self._wake.set()
        if obs.enabled():
            obs.gauge(f"serve/router_inflight_{rep.name}").set(
                len(rep.inflight))
        if stale:
            # an ABANDONED inner future resolving late (its request was
            # already failed over by a drain): the live copy owns the
            # outcome — acting here would requeue/dispatch it twice
            return
        if req.future.done():
            return  # failover already resolved it elsewhere
        if inner.cancelled():
            req.future.cancel()
            return
        exc = inner.exception()
        if exc is None:
            lat_ms = (time.perf_counter_ns() - req.t_enqueue_ns) / 1e6
            # the doomed-at-admission estimate is SERVICE time (dispatch
            # -> done), not end-to-end latency: a backlog inflates queue
            # wait transiently, and folding that into the estimate would
            # keep dooming tight requests long after replicas went idle
            svc_ms = ((time.perf_counter_ns() - req.t_dispatch_ns) / 1e6
                      if req.t_dispatch_ns is not None else lat_ms)
            with self._lock:
                prev = rep.ewma_ms.get(req.klass)
                rep.ewma_ms[req.klass] = (svc_ms if prev is None
                                          else 0.8 * prev + 0.2 * svc_ms)
                self._reseed_ewma_locked(req.klass)
            res = inner.result()
            if req.recovered is not None:
                # KV-preserving failover: the survivor only decoded
                # the CONTINUATION — the client gets the dead replica's
                # tokens followed by the survivor's, which is bitwise
                # the uninterrupted stream. A splice that fails (a
                # result that is not a token vector) must FAIL the
                # future, never strand it.
                try:
                    res = np.concatenate([
                        req.recovered,
                        np.asarray(res, np.int32).reshape(-1)])
                except Exception as e:  # noqa: BLE001 — typed, not stuck
                    self._fail(req, e)
                    return
            self._complete(req, res, replica=rep.name,
                           base_trace=getattr(inner, "trace", None),
                           version=getattr(inner, "version", None))
            return
        if isinstance(exc, DeadlineExceeded):
            # _miss splices req.recovered ahead of the survivor's
            # continuation partial (_carry_recovered) — one splice
            # point for every terminal path
            self._miss(req, self._classes[req.klass], str(exc), exc=exc)
            return
        if isinstance(exc, (EngineStopped, QueueFull)) \
                and not self._stop.is_set() \
                and req.failovers < self.max_failovers:
            if self._recover_decode(req, exc):
                return  # the partial already completed the request
            self._failover(req, rep, reason=type(exc).__name__)
            return
        self._fail(req, exc)

    def _recover_decode(self, req: _RouterRequest, exc) -> bool:
        """KV-preserving decode recovery (ISSUE 13). A dying
        :class:`~.decode_scheduler.DecodeScheduler` fails its in-flight
        requests typed with the tokens it already generated on
        ``exc.partial``; instead of re-running the whole generation
        from scratch on a survivor, splice that progress into the
        request before the failover re-queues it:

        * payload becomes ``prompt + partial`` — the survivor prefills
          the full token history (a PREFIX HIT where its cache already
          holds the prompt: the re-prefill collapses to the partial's
          tail chunks);
        * ``max_new_tokens`` shrinks by the tokens already produced;
        * the final result is ``partial + continuation``.

        Greedy decode — and seeded sampling, whose keys derive from
        (seed, absolute position) in-program — is a pure function of
        the token history, so the recovered stream is BITWISE the
        uninterrupted run (the `make chaos-smoke` gate). Host-only
        bookkeeping — never a device touch. Returns True when the
        partial already exhausted the budget (the request is resolved
        here, nothing left to re-dispatch); False falls through to the
        plain whole-prompt failover."""
        partial = getattr(exc, "partial", None)
        if partial is None:
            return False
        partial = np.asarray(partial, np.int32).reshape(-1)
        if partial.size == 0:
            return False
        mnt = req.kw.get("max_new_tokens")
        if mnt is None:
            return False  # not a decode-shaped request
        try:
            payload = np.asarray(req.payload, np.int32).reshape(-1)
        except (TypeError, ValueError):
            return False
        self._bump("kv_recoveries")
        if obs.enabled():
            obs.counter("serve/router_kv_recoveries").inc()
        _health.emit("router_kv_recovery", rid=req.rid,
                     tokens=int(partial.size))
        req.recovered = (partial if req.recovered is None
                         else np.concatenate([req.recovered, partial]))
        req.payload = np.concatenate([payload, partial])
        req.kw = dict(req.kw)
        req.kw["max_new_tokens"] = int(mnt) - int(partial.size)
        if req.kw["max_new_tokens"] <= 0:
            # the dead replica had already produced the full budget —
            # its answer is complete; resolve instead of re-dispatching
            # a zero-token request (replica=None in the trace: no
            # survivor served a continuation)
            self._complete(req, req.recovered, replica=None,
                           base_trace={"rid": req.rid},
                           version=getattr(exc, "version", None))
            return True
        return False

    def _complete(self, req: _RouterRequest, res, *,
                  replica: Optional[str], base_trace=None, version=None):
        """The ONE completion path: attach version + the router trace
        (with recovery provenance), record the completion metrics, and
        resolve the future — shared by the normal inner-done success
        and the full-budget recovery resolve so the provenance surface
        cannot drift between them."""
        lat_ms = (time.perf_counter_ns() - req.t_enqueue_ns) / 1e6
        trace = dict(base_trace or {})
        trace["router"] = {"class": req.klass, "replica": replica,
                           "failovers": req.failovers,
                           "latency_ms": round(lat_ms, 3)}
        if req.recovered is not None:
            trace["router"]["recovered_tokens"] = int(req.recovered.size)
        req.future.version = version
        req.future.trace = trace
        self._bump("completed")
        if obs.enabled():
            obs.counter("serve/router_completed").inc()
            obs.histogram(
                f"serve/router_latency_ms_{_metric_cls(req.klass)}",
                unit="ms").observe(lat_ms)
        try:
            req.future.set_result(res)
        except Exception:
            pass

    # -- health / failover -----------------------------------------------

    def _reseed_ewma_locked(self, klass: str):
        """Re-derive one class's admission estimate from the healthy
        replicas' per-replica EWMAs (min — doom a deadline only when
        even the BEST live replica can't meet it). Caller holds
        ``self._lock``."""
        cq = self._classes[klass]
        est = [r.ewma_ms[klass] for r in self._replicas
               if r.healthy and klass in r.ewma_ms]
        cq.ewma_ms = min(est) if est else None

    def _on_health_event(self, event: dict):
        """health-listener hook (runs on the watchdog thread): a
        replica's stall beacon drains it, its recovery rejoins it."""
        comp = event.get("component")
        rep = self._by_beacon.get(comp)
        if rep is None:
            return
        kind = event.get("kind")
        if kind == "health/stall":
            self._drain_replica(rep, reason="stall")
        elif kind == "health/stall_recovered":
            self._rejoin_replica(rep)

    def _drain_replica(self, rep: _Replica, reason: str):
        """Take a replica out of rotation and re-route its in-flight
        requests onto the survivors. The stalled replica's own futures
        are left pending — if it revives and answers first, the outer
        future is already resolved and the late answer is dropped
        (set-once), so no client ever sees two answers or none."""
        with self._lock:
            if not rep.healthy:
                return
            rep.healthy = False
            stranded = list(rep.inflight)
            rep.inflight.clear()
            rep.by_class.clear()
            # the drained replica's service times leave the admission
            # estimate with it — the fleet's doomed check must describe
            # the replicas that can actually serve
            for k in self._classes:
                self._reseed_ewma_locked(k)
        self._bump("drains")
        if obs.enabled():
            obs.counter("serve/router_drains").inc()
            obs.instant("serve/router_drain", replica=rep.name,
                        reason=reason, stranded=len(stranded))
            _flight.record("serve/router_drain", replica=rep.name,
                           reason=reason, stranded=len(stranded))
        for req in stranded:
            if not req.future.done():
                self._failover(req, rep, reason=reason)

    def _rejoin_replica(self, rep: _Replica):
        with self._lock:
            if rep.healthy or rep.dead:
                return
            rep.healthy = True
            # stale-EWMA dooming fix (ISSUE 13): the pre-stall service
            # times this replica measured are the latencies of a
            # machine that just wedged — re-seed from FRESH completions
            # so a recovered replica cannot doom tight-deadline
            # requests off its old numbers
            rep.ewma_ms.clear()
            for k in self._classes:
                self._reseed_ewma_locked(k)
        self._bump("rejoins")
        if obs.enabled():
            obs.counter("serve/router_rejoins").inc()
            obs.instant("serve/router_rejoin", replica=rep.name)
        self._wake.set()

    def _failover(self, req: _RouterRequest, rep: _Replica, reason: str):
        """Send a request back through the class queue (head — it has
        already waited) to complete on a surviving replica. The
        ``max_failovers`` budget is enforced HERE — the one choke point
        both the inner-error path and the stall-drain path go through —
        so a request ping-ponging between flapping replicas eventually
        fails typed instead of looping forever."""
        if req.failovers >= self.max_failovers:
            self._fail(req, EngineStopped(
                f"request {req.rid} failed over {req.failovers}x "
                f"(budget {self.max_failovers}) — last replica "
                f"{rep.name}: {reason}"))
            return
        req.failovers += 1
        self._bump("failovers")
        if obs.enabled():
            obs.counter("serve/router_failovers").inc()
            _health.emit("router_failover", rid=req.rid, replica=rep.name,
                         reason=reason, attempt=req.failovers)
        with self._lock:
            # bump under the SAME lock the dispatch path captures its
            # epoch under — the abandoned inner's resolution is now
            # recognizably stale, with no interleaving window
            req.epoch += 1
            self._classes[req.klass].q.appendleft(req)
        self._wake.set()

    def _mark_unhealthy(self, rep: _Replica, reason: str):
        with self._lock:
            was = rep.healthy
            rep.healthy = False
            if reason == "engine_stopped":
                rep.dead = True
            for k in self._classes:
                self._reseed_ewma_locked(k)
        if was:
            self._bump("drains")
            if obs.enabled():
                obs.counter("serve/router_drains").inc()
                obs.instant("serve/router_drain", replica=rep.name,
                            reason=reason, stranded=0)

    # -- internals -------------------------------------------------------

    def _miss(self, req: _RouterRequest, cq: _ClassQueue, msg: str,
              exc: Optional[BaseException] = None):
        self._bump("deadline_misses")
        if obs.enabled():
            obs.counter("serve/router_timeouts").inc()
            obs.counter("serve/router_deadline_miss_"
                        f"{_metric_cls(cq.cls.name)}").inc()
        if exc is None:
            exc = DeadlineExceeded(msg)
        try:
            req.future.set_exception(self._carry_recovered(req, exc))
        except Exception:
            pass

    def _carry_recovered(self, req: _RouterRequest,
                         exc: BaseException) -> BaseException:
        """Terminal failures must not silently drop tokens a dead
        replica already produced: whatever path fails the request —
        deadline at the router, exhausted failover budget, a dead
        fleet — the client's ``exc.partial`` carries the WHOLE stream:
        the recovered prefix followed by whatever continuation the
        last replica's own partial holds (an exception without one, or
        with an empty one, still keeps the prefix). The one splice
        point for every terminal path — matching the contract the
        scheduler upholds on its own failure paths."""
        if req.recovered is not None:
            tail = getattr(exc, "partial", None)
            tail = (np.zeros((0,), np.int32) if tail is None
                    else np.asarray(tail, np.int32).reshape(-1))
            exc.partial = np.concatenate([req.recovered, tail])
        return exc

    def _fail(self, req: _RouterRequest, exc: BaseException):
        try:
            req.future.set_exception(self._carry_recovered(req, exc))
        except Exception:
            pass

    def _on_done(self, future):
        with self._cond:
            self._pending -= 1
            self._cond.notify_all()

    def _bump(self, key: str, n: int = 1):
        with self._stats_lock:
            self._stats[key] += n


def router_threads_alive() -> int:
    """Live router loops (tests assert 0 after shutdown)."""
    return sum(1 for t in threading.enumerate()
               if t.name == THREAD_NAME and t.is_alive())
