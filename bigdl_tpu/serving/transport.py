"""Framed binary transport for the cross-process serving fleet.

"RPC Considered Harmful" (arXiv:1805.08430) measures where a serving
fabric actually loses its time: not in the control decisions but in the
per-message serialization tax — payloads copied through a generic
object encoder once per hop. The fleet tier (``serving/fleet.py``)
therefore splits its two planes:

* **control** rides the existing snapshot/membership FILES (one atomic
  JSON rewrite per beat — see ``observability/cluster.py`` and
  ``parallel/failure.FileHeartbeat``), and
* **data** rides THIS module: one length-prefixed frame per message
  over a local socket, with every tensor payload (token vectors, KV
  pages, published param leaves) sent as its RAW little-endian bytes —
  ``sendall(memoryview(...))`` out, ``np.frombuffer`` in. A 4 MB KV
  handoff costs one header json plus one pass over the bytes, never a
  per-element encode.

Frame layout (all integers little-endian)::

    b"BTF1" | u32 header_len | header (utf-8 json) |
    u32 nbufs | nbufs x (u64 buf_len | raw bytes)

The header names the operation (requests) or the request it answers
(replies) plus dtype/shape descriptors for the buffers; the buffers are
opaque bytes. Messages are correlated by ``mid`` so one connection
carries MANY in-flight requests (a decode generation is seconds long —
a blocking request/response socket would serialize the whole replica
behind its slowest client) and replies may land out of order.

:class:`TransportServer` accepts connections and hands each request to
a handler together with a one-shot ``reply`` callable — the handler may
answer immediately (stats) or stash the callable and answer when a
future resolves (submit). :class:`TransportClient` demultiplexes
replies onto per-request futures on a single receiver thread. A lost
connection fails every in-flight request with the typed
:class:`TransportClosed` — the fleet layer maps that onto the router's
replica-failover path.

The ``fleet/transport`` chaos site fires on every client send (tag =
the peer name), so a campaign can present a flaky fabric to the
router's transient-retry machinery without touching a socket.

Import discipline: stdlib + numpy only — no jax (the router process of
a bench parent must be able to drive a fleet without initializing a
backend).
"""
from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_LOG = logging.getLogger("bigdl_tpu.serving.transport")

MAGIC = b"BTF1"
THREAD_PREFIX = "bigdl_tpu-fleet-transport"

#: sanity bound on one frame's header (a corrupt length prefix must not
#: make the reader try to allocate gigabytes)
_MAX_HEADER = 16 * 1024 * 1024

#: sanity bound on one payload buffer. Big transfers are legitimate —
#: a published param leaf or a long prefix's KV pages run to hundreds
#: of MB — but a garbage u64 from a desynchronized stream is
#: astronomically large with overwhelming probability; refusing past
#: 8 GB turns it into the same typed TransportClosed the header bound
#: gives, instead of an allocation death spiral
_MAX_BUF = 8 * 1024 * 1024 * 1024


class TransportClosed(ConnectionError):
    """The peer's connection is gone (process death, socket teardown).
    The fleet layer converts this into the replica-dead signal the
    router's failover machinery already understands."""


# -- array / pytree codecs -------------------------------------------------

def pack_arrays(arrays: Sequence[np.ndarray]) -> Tuple[List[dict], List]:
    """(descriptors, buffers) for a list of numpy arrays. Buffers are
    zero-copy views of the (C-contiguous) array bytes."""
    descr, bufs = [], []
    for a in arrays:
        a = np.ascontiguousarray(a)
        descr.append({"dtype": a.dtype.str, "shape": list(a.shape)})
        bufs.append(memoryview(a).cast("B"))
    return descr, bufs


def unpack_arrays(descr: Sequence[dict], bufs: Sequence[bytes]) \
        -> List[np.ndarray]:
    if len(descr) != len(bufs):
        raise ValueError(f"array descriptor/buffer count mismatch: "
                         f"{len(descr)} vs {len(bufs)}")
    out = []
    for d, b in zip(descr, bufs):
        a = np.frombuffer(b, dtype=np.dtype(d["dtype"]))
        out.append(a.reshape(d["shape"]))
    return out


def encode_tree(tree, bufs: List[np.ndarray]):
    """JSON-able spec for a params/state pytree (nested dict/list/tuple
    of arrays and scalars); array leaves are appended to ``bufs`` and
    referenced by index — the publish path ships a whole version as one
    frame whose buffers are the raw leaf bytes."""
    if tree is None:
        return {"t": "n"}
    if isinstance(tree, dict):
        return {"t": "d", "k": {str(k): encode_tree(v, bufs)
                                for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        return {"t": "l" if isinstance(tree, list) else "u",
                "v": [encode_tree(v, bufs) for v in tree]}
    if isinstance(tree, (bool, int, float, str)):
        return {"t": "s", "v": tree}
    a = np.asarray(tree)
    idx = len(bufs)
    bufs.append(a)
    return {"t": "a", "i": idx}


def decode_tree(spec, arrays: Sequence[np.ndarray]):
    t = spec["t"]
    if t == "n":
        return None
    if t == "d":
        return {k: decode_tree(v, arrays) for k, v in spec["k"].items()}
    if t in ("l", "u"):
        out = [decode_tree(v, arrays) for v in spec["v"]]
        return out if t == "l" else tuple(out)
    if t == "s":
        return spec["v"]
    return arrays[spec["i"]]


# -- framing ---------------------------------------------------------------

def _read_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise :class:`TransportClosed`."""
    chunks = []
    while n > 0:
        try:
            b = sock.recv(min(n, 1 << 20))
        except OSError as e:
            raise TransportClosed(f"connection lost mid-frame: {e}") from e
        if not b:
            raise TransportClosed("peer closed the connection")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _send_frame(sock: socket.socket, lock: threading.Lock, header: dict,
                bufs: Sequence = ()):
    """One frame out: header json + raw buffers, under the connection's
    send lock (frames from concurrent repliers must not interleave).
    Raises :class:`TransportClosed` on a dead socket."""
    h = json.dumps(header).encode()
    try:
        with lock:
            sock.sendall(b"".join([MAGIC, struct.pack("<I", len(h)), h,
                                   struct.pack("<I", len(bufs))]))
            for b in bufs:
                mv = memoryview(b).cast("B")
                sock.sendall(struct.pack("<Q", len(mv)))
                sock.sendall(mv)
    except OSError as e:
        raise TransportClosed(f"send failed: {e}") from e


def _recv_frame(sock: socket.socket) -> Tuple[dict, List[bytes]]:
    magic = _read_exact(sock, 4)
    if magic != MAGIC:
        raise TransportClosed(f"bad frame magic {magic!r}")
    (hlen,) = struct.unpack("<I", _read_exact(sock, 4))
    if hlen > _MAX_HEADER:
        raise TransportClosed(f"header length {hlen} exceeds bound")
    header = json.loads(_read_exact(sock, hlen).decode())
    (nbufs,) = struct.unpack("<I", _read_exact(sock, 4))
    bufs = []
    for _ in range(nbufs):
        (blen,) = struct.unpack("<Q", _read_exact(sock, 8))
        if blen > _MAX_BUF:
            raise TransportClosed(f"buffer length {blen} exceeds bound")
        bufs.append(_read_exact(sock, blen))
    return header, bufs


# -- server ----------------------------------------------------------------

class _Conn:
    """One accepted connection: the ``reply`` factory the handler gets."""

    __slots__ = ("sock", "send_lock", "peer")

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.send_lock = threading.Lock()
        self.peer = peer

    def reply(self, mid: int, meta: Optional[dict] = None,
              arrays: Sequence[np.ndarray] = (),
              error: Optional[dict] = None):
        """Answer request ``mid`` (success meta or a typed error dict).
        Safe from any thread; a reply onto a connection the client
        already dropped is swallowed — the client is gone either way."""
        descr, bufs = pack_arrays(arrays)
        header = {"reply_to": mid, "ok": error is None,
                  "meta": meta or {}, "arrays": descr}
        if error is not None:
            header["error"] = error
        try:
            _send_frame(self.sock, self.send_lock, header, bufs)
        except TransportClosed:
            pass


#: handler signature: (reply_fn, op, meta, arrays) where reply_fn is a
#: one-shot ``(meta=None, arrays=(), error=None)`` callable
Handler = Callable[[Callable, str, dict, List[np.ndarray]], None]


class TransportServer:
    """Accept loop + per-connection reader threads over a local socket.

    The handler runs ON the connection's reader thread — it must either
    answer fast (stats, probes) or capture ``reply`` and answer later
    from another thread (the submit path answers from the engine's
    future callback). A handler exception answers the request with a
    typed error frame instead of killing the connection."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1",
                 port: int = 0, name: str = ""):
        self.handler = handler
        self.name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[_Conn] = []
        self._lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "TransportServer":
        self._sock.listen(16)
        t = threading.Thread(target=self._accept_loop,
                             name=f"{THREAD_PREFIX}-accept[{self.name}]",
                             daemon=True)
        self._accept_thread = t
        t.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return  # closed
            if self._stop.is_set():   # the close() wake-up poke
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, f"{addr[0]}:{addr[1]}")
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._conn_loop, args=(conn,),
                name=f"{THREAD_PREFIX}-conn[{self.name}]", daemon=True)
            with self._lock:
                self._threads.append(t)
            t.start()

    def _conn_loop(self, conn: _Conn):
        try:
            while not self._stop.is_set():
                header, bufs = _recv_frame(conn.sock)
                mid = header.get("mid")
                op = header.get("op", "")
                try:
                    arrays = unpack_arrays(header.get("arrays", ()), bufs)
                    done = []

                    def reply(meta=None, arrays=(), error=None,
                              _mid=mid, _done=done):
                        if _done:
                            raise RuntimeError("reply() called twice")
                        _done.append(True)
                        conn.reply(_mid, meta, arrays, error)

                    self.handler(reply, op, header.get("meta", {}), arrays)
                except TransportClosed:
                    raise
                except BaseException as e:  # noqa: BLE001 — answer typed
                    conn.reply(mid, error={"type": type(e).__name__,
                                           "msg": str(e)})
        except TransportClosed:
            pass
        finally:
            try:
                conn.sock.close()
            except OSError:
                pass
            # prune this connection's bookkeeping — a long-lived agent
            # whose peers reconnect (failover drills, monitor rejoins)
            # must not accumulate dead _Conn/Thread objects forever
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
                try:
                    self._threads.remove(threading.current_thread())
                except ValueError:
                    pass

    def close(self):
        self._stop.set()
        # closing a listening socket does not reliably wake a thread
        # blocked in accept() — poke it with a throwaway connection
        # first, then close (the loop checks _stop before accepting)
        try:
            poke = socket.create_connection((self.host, self.port),
                                            timeout=1.0)
            poke.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # shutdown BEFORE close: closing an fd another thread is
            # blocked recv()ing on does not reliably wake it — the
            # half-close does, and it sends the FIN the peer's demux
            # needs to fail its in-flight futures
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass
        me = threading.current_thread()
        for t in list(self._threads):
            if t is not me:   # a handler may close its own server
                t.join(5.0)
        if self._accept_thread is not None and self._accept_thread is not me:
            self._accept_thread.join(5.0)


# -- client ----------------------------------------------------------------

class TransportClient:
    """One connection to a fleet peer, many in-flight requests.

    ``request_async`` SENDS on the calling thread (so an injected
    ``fleet/transport`` fault or a dead socket raises typed into the
    caller — the router's dispatch loop converts that into
    try-the-next-replica) and resolves the returned future from the
    single receiver thread when the peer answers. A connection loss
    fails every in-flight future with :class:`TransportClosed`."""

    def __init__(self, host: str, port: int, name: str = "",
                 connect_timeout_s: float = 10.0):
        self.host, self.port = host, int(port)
        self.name = name
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._plock = threading.Lock()
        self._mid = 0
        self._recv_thread: Optional[threading.Thread] = None
        self._closed = False
        self._connect_timeout_s = connect_timeout_s

    @property
    def closed(self) -> bool:
        return self._closed

    def connect(self) -> "TransportClient":
        if self._sock is not None:
            return self
        s = socket.create_connection((self.host, self.port),
                                     timeout=self._connect_timeout_s)
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._recv_thread = threading.Thread(
            target=self._recv_loop,
            name=f"{THREAD_PREFIX}-client[{self.name}]", daemon=True)
        self._recv_thread.start()
        return self

    def request_async(self, op: str, meta: Optional[dict] = None,
                      arrays: Sequence[np.ndarray] = ()) -> Future:
        """Send one request; the future resolves to ``(meta, arrays)``
        or raises the peer's typed error / :class:`TransportClosed`.
        The send itself happens HERE, synchronously — a transport fault
        surfaces on the caller, not inside a callback."""
        _chaos_fire("fleet/transport", tag=self.name)
        if self._closed or self._sock is None:
            raise TransportClosed(
                f"transport to {self.name or self.host} is closed")
        fut: Future = Future()
        with self._plock:
            self._mid += 1
            mid = self._mid
            self._pending[mid] = fut
        descr, bufs = pack_arrays(arrays)
        header = {"mid": mid, "op": op, "meta": meta or {},
                  "arrays": descr}
        try:
            _send_frame(self._sock, self._send_lock, header, bufs)
        except TransportClosed:
            with self._plock:
                self._pending.pop(mid, None)
            self._fail_all("send failed")
            raise
        return fut

    def request(self, op: str, meta: Optional[dict] = None,
                arrays: Sequence[np.ndarray] = (),
                timeout: Optional[float] = None):
        """Synchronous convenience: ``(meta, arrays)`` or the typed
        error."""
        return self.request_async(op, meta, arrays).result(timeout)

    def _recv_loop(self):
        try:
            while not self._closed:
                header, bufs = _recv_frame(self._sock)
                mid = header.get("reply_to")
                with self._plock:
                    fut = self._pending.pop(mid, None)
                if fut is None:
                    continue  # peer answered a request we gave up on
                if header.get("ok", False):
                    try:
                        arrays = unpack_arrays(header.get("arrays", ()),
                                               bufs)
                        fut.set_result((header.get("meta", {}), arrays))
                    except Exception as e:  # noqa: BLE001 — typed fail
                        fut.set_exception(e)
                else:
                    err = header.get("error", {})
                    try:
                        arrays = unpack_arrays(header.get("arrays", ()),
                                               bufs)
                    except Exception:  # noqa: BLE001
                        arrays = []
                    fut.set_exception(RemoteError(
                        err.get("type", "RuntimeError"),
                        err.get("msg", "remote failure"), arrays,
                        meta=header.get("meta", {})))
        except TransportClosed as e:
            self._fail_all(str(e))
        except Exception as e:  # noqa: BLE001 — fabric bug, fail typed
            self._fail_all(f"{type(e).__name__}: {e}")

    def _fail_all(self, why: str):
        self._closed = True
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        exc = TransportClosed(
            f"transport to {self.name or self.host}:{self.port} lost "
            f"({why})")
        for fut in pending:
            try:
                fut.set_exception(exc)
            except Exception:  # noqa: BLE001 — already resolved
                pass

    def close(self):
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        self._fail_all("closed by caller")
        t = self._recv_thread
        if t is not None and t is not threading.current_thread():
            t.join(5.0)


class RemoteError(RuntimeError):
    """A typed error frame from the peer: carries the remote exception's
    class name, message, and any attached arrays (a dying scheduler's
    ``partial`` token vector rides array 0). The fleet layer re-raises
    it as the matching LOCAL serving exception type so the router's
    isinstance-based failover/recovery logic is process-transparent."""

    def __init__(self, type_name: str, msg: str,
                 arrays: Sequence[np.ndarray] = (), meta=None):
        super().__init__(msg)
        self.type_name = type_name
        self.arrays = list(arrays)
        self.meta = dict(meta or {})


def transport_threads_alive() -> int:
    """Live transport threads (tests assert 0 after close)."""
    return sum(1 for t in threading.enumerate()
               if t.name.startswith(THREAD_PREFIX) and t.is_alive())


def _chaos_fire(site: str, tag: Optional[str] = None):
    """The ``fleet/transport`` chaos seam. Lazy import keeps this module
    stdlib+numpy-only for jax-free parents; disarmed cost is the one
    module-global read inside ``chaos.maybe_fire`` plus one cached
    module attribute here."""
    global _chaos
    if _chaos is None:
        try:
            from ..parallel import chaos as _c
        except Exception:  # noqa: BLE001 — jax-free parent: no chaos
            _c = False
        _chaos = _c
    if _chaos:
        _chaos.maybe_fire(site, tag=tag)


_chaos = None


def wait_for_port(host: str, port: int, timeout_s: float = 30.0) -> bool:
    """Poll until a peer listens (spawned agent startup)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            s = socket.create_connection((host, port), timeout=1.0)
            s.close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def pick_advertise_host(bind_host: str = "0.0.0.0",
                        probe: str = "10.255.255.255") -> str:
    """The address peers should DIAL for a server bound to
    ``bind_host``. A concrete bind address is already reachable and is
    returned as-is; a wildcard bind (``0.0.0.0`` / ``::`` / empty) needs
    the host's outbound interface address — resolved with the classic
    connected-UDP-socket trick (no packet is sent; the kernel just picks
    the route to ``probe`` and reports the source address it would use).
    Falls back to ``127.0.0.1`` on boxes with no route at all, which
    keeps single-host fleets working offline."""
    if bind_host not in ("", "0.0.0.0", "::"):
        return bind_host
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((probe, 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"
