from . import vision
