from . import vision
from .image_frame import ImageFrame, LocalImageFrame, MTImageFeatureToBatch
