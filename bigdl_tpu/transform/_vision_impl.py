"""Vision feature transformers.

Parity: reference ``transform/vision/image/augmentation/*.scala`` (Resize,
Crop variants, Flip, channel ops, ColorJitter, Expand, Filler, Lighting,
PixelNormalizer) + ``MatToTensor``. The reference runs these per-sample on
OpenCV Mats inside Spark tasks; here they are host-side numpy ops feeding the
device pipeline (augmentation is IO-bound, the TPU never waits on it when the
prefetcher overlaps). Images are HWC float32 unless noted; ``MatToTensor``
produces the CHW tensor the models consume.

Each transformer is a ``dataset.Transformer`` over ``Sample``-like dicts or
raw arrays, composable with ``|``.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..dataset.transformer import Transformer


class ImageFeature(dict):
    """Loose parity with transform/vision/image/ImageFeature.scala: a dict
    carrying 'image' (HWC float), 'label', and arbitrary metadata."""

    @property
    def image(self):
        return self["image"]

    @image.setter
    def image(self, v):
        self["image"] = v


class FeatureTransformer(Transformer):
    """Base per-image transformer (transform/vision/image/
    FeatureTransformer.scala)."""

    def transform_image(self, img: np.ndarray, rng: np.random.RandomState
                        ) -> np.ndarray:
        return img

    def __init__(self, seed: int = 17):
        self.rng = np.random.RandomState(seed)

    def apply(self, it):
        for item in it:
            if isinstance(item, dict):
                item = dict(item)
                item["image"] = self.transform_image(
                    np.asarray(item["image"], np.float32), self.rng)
                yield item
            else:
                yield self.transform_image(np.asarray(item, np.float32),
                                           self.rng)


def _resize_bilinear(img, oh, ow):
    h, w = img.shape[:2]
    if (h, w) == (oh, ow):
        return img
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img if img.ndim == 3 else img[..., None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(np.float32)


class Resize(FeatureTransformer):
    """augmentation/Resize.scala."""

    def __init__(self, resize_h: int, resize_w: int, **kw):
        super().__init__(**kw)
        self.resize_h, self.resize_w = resize_h, resize_w

    def transform_image(self, img, rng):
        return _resize_bilinear(img, self.resize_h, self.resize_w)


class AspectScale(FeatureTransformer):
    """augmentation/AspectScale.scala — short side → scale."""

    def __init__(self, scale: int = 256, max_size: int = 1000, **kw):
        super().__init__(**kw)
        self.scale, self.max_size = scale, max_size

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        ratio = self.scale / short
        if long * ratio > self.max_size:
            ratio = self.max_size / long
        return _resize_bilinear(img, int(round(h * ratio)),
                                int(round(w * ratio)))


class CenterCrop(FeatureTransformer):
    """augmentation/Crop.scala CenterCrop."""

    def __init__(self, crop_width: int, crop_height: int, **kw):
        super().__init__(**kw)
        self.cw, self.ch = crop_width, crop_height

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        y = max((h - self.ch) // 2, 0)
        x = max((w - self.cw) // 2, 0)
        return img[y:y + self.ch, x:x + self.cw]


class RandomCrop(FeatureTransformer):
    """augmentation/Crop.scala RandomCrop."""

    def __init__(self, crop_width: int, crop_height: int, **kw):
        super().__init__(**kw)
        self.cw, self.ch = crop_width, crop_height

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        y = rng.randint(0, max(h - self.ch, 0) + 1)
        x = rng.randint(0, max(w - self.cw, 0) + 1)
        return img[y:y + self.ch, x:x + self.cw]


class RandomResizedCrop(FeatureTransformer):
    """models/inception RandomAlterAspect / torch-style random area+aspect
    crop then resize."""

    def __init__(self, size: int, area_range=(0.08, 1.0),
                 aspect_range=(3 / 4, 4 / 3), **kw):
        super().__init__(**kw)
        self.size = size
        self.area_range, self.aspect_range = area_range, aspect_range

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = rng.uniform(*self.area_range) * area
            aspect = np.exp(rng.uniform(np.log(self.aspect_range[0]),
                                        np.log(self.aspect_range[1])))
            cw = int(round(np.sqrt(target * aspect)))
            ch = int(round(np.sqrt(target / aspect)))
            if cw <= w and ch <= h:
                y = rng.randint(0, h - ch + 1)
                x = rng.randint(0, w - cw + 1)
                return _resize_bilinear(img[y:y + ch, x:x + cw],
                                        self.size, self.size)
        return _resize_bilinear(img, self.size, self.size)


class HFlip(FeatureTransformer):
    """augmentation/HFlip.scala (unconditional)."""

    def transform_image(self, img, rng):
        return img[:, ::-1].copy()


class RandomTransformer(FeatureTransformer):
    """augmentation/RandomTransformer.scala — apply inner with prob p."""

    def __init__(self, inner: FeatureTransformer, prob: float = 0.5, **kw):
        super().__init__(**kw)
        self.inner, self.prob = inner, prob

    def transform_image(self, img, rng):
        if rng.rand() < self.prob:
            return self.inner.transform_image(img, rng)
        return img


def RandomFlip(prob=0.5):
    return RandomTransformer(HFlip(), prob)


class ChannelNormalize(FeatureTransformer):
    """augmentation/ChannelNormalize.scala — (x - mean) / std per channel."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0,
                 std_b=1.0, **kw):
        super().__init__(**kw)
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def transform_image(self, img, rng):
        return (img - self.mean) / self.std


class ChannelScaledNormalizer(FeatureTransformer):
    """augmentation/ChannelScaledNormalizer.scala."""

    def __init__(self, mean_r, mean_g, mean_b, scale: float, **kw):
        super().__init__(**kw)
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.scale = scale

    def transform_image(self, img, rng):
        return (img - self.mean) * self.scale


class PixelNormalizer(FeatureTransformer):
    """augmentation/PixelNormalizer.scala — subtract per-pixel mean image."""

    def __init__(self, means: np.ndarray, **kw):
        super().__init__(**kw)
        self.means = np.asarray(means, np.float32)

    def transform_image(self, img, rng):
        return img - self.means.reshape(img.shape)


class Brightness(FeatureTransformer):
    """augmentation/Brightness.scala — add delta in [lo, hi]."""

    def __init__(self, delta_low: float, delta_high: float, **kw):
        super().__init__(**kw)
        self.lo, self.hi = delta_low, delta_high

    def transform_image(self, img, rng):
        return img + rng.uniform(self.lo, self.hi)


class Contrast(FeatureTransformer):
    """augmentation/Contrast.scala — scale around mean."""

    def __init__(self, delta_low: float, delta_high: float, **kw):
        super().__init__(**kw)
        self.lo, self.hi = delta_low, delta_high

    def transform_image(self, img, rng):
        f = rng.uniform(self.lo, self.hi)
        return img * f


class Saturation(FeatureTransformer):
    """augmentation/Saturation.scala — blend with grayscale."""

    def __init__(self, delta_low: float, delta_high: float, **kw):
        super().__init__(**kw)
        self.lo, self.hi = delta_low, delta_high

    def transform_image(self, img, rng):
        f = rng.uniform(self.lo, self.hi)
        gray = img.mean(axis=-1, keepdims=True)
        return gray + (img - gray) * f


class Hue(FeatureTransformer):
    """augmentation/Hue.scala — rotate hue (approximate RGB-space rotation)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 **kw):
        super().__init__(**kw)
        self.lo, self.hi = delta_low, delta_high

    def transform_image(self, img, rng):
        theta = np.deg2rad(rng.uniform(self.lo, self.hi))
        c, s = np.cos(theta), np.sin(theta)
        # YIQ hue rotation matrix
        t_yiq = np.array([[0.299, 0.587, 0.114],
                          [0.596, -0.274, -0.322],
                          [0.211, -0.523, 0.312]], np.float32)
        rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
        m = np.linalg.inv(t_yiq) @ rot @ t_yiq
        return img @ m.T


class ColorJitter(FeatureTransformer):
    """augmentation/ColorJitter.scala — random order B/C/S."""

    def __init__(self, brightness=32.0, contrast=0.5, saturation=0.5, **kw):
        super().__init__(**kw)
        self.ops = [Brightness(-brightness, brightness),
                    Contrast(1 - contrast, 1 + contrast),
                    Saturation(1 - saturation, 1 + saturation)]

    def transform_image(self, img, rng):
        order = rng.permutation(len(self.ops))
        for i in order:
            img = self.ops[i].transform_image(img, rng)
        return img


class Expand(FeatureTransformer):
    """augmentation/Expand.scala — place image on a larger mean canvas."""

    def __init__(self, means=(123, 117, 104), max_expand_ratio: float = 4.0,
                 **kw):
        super().__init__(**kw)
        self.means = np.array(means, np.float32)
        self.max_ratio = max_expand_ratio

    def transform_image(self, img, rng):
        ratio = rng.uniform(1.0, self.max_ratio)
        h, w = img.shape[:2]
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.tile(self.means, (nh, nw, 1)).astype(np.float32)
        y = rng.randint(0, nh - h + 1)
        x = rng.randint(0, nw - w + 1)
        canvas[y:y + h, x:x + w] = img
        return canvas


class Filler(FeatureTransformer):
    """augmentation/Filler.scala — fill a normalized sub-rect with a value."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0, **kw):
        super().__init__(**kw)
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img = img.copy()
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        return img


class Lighting(FeatureTransformer):
    """augmentation/Lighting.scala — AlexNet PCA noise (ImageNet eigen
    values/vectors)."""

    _eigval = np.array([0.2175, 0.0188, 0.0045], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alphastd: float = 0.1, **kw):
        super().__init__(**kw)
        self.alphastd = alphastd

    def transform_image(self, img, rng):
        alpha = rng.normal(0, self.alphastd, 3).astype(np.float32)
        noise = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return img + noise


class MatToTensor(FeatureTransformer):
    """transform/vision/image/MatToTensor.scala — HWC → CHW float tensor."""

    def transform_image(self, img, rng):
        if img.ndim == 2:
            img = img[..., None]
        return np.ascontiguousarray(img.transpose(2, 0, 1))


class ImageFrameToSample(Transformer):
    """transform/vision/image/ImageFrameToSample.scala."""

    def apply(self, it):
        from ..dataset.sample import Sample
        for item in it:
            if isinstance(item, dict):
                yield Sample(item["image"], item.get("label"))
            else:
                yield Sample(item)


class ChannelOrder(FeatureTransformer):
    """augmentation/ChannelOrder.scala — swap RGB<->BGR (the reference
    flips the OpenCV BGR order to the RGB order nets trained on).
    No-op on grayscale (HW) images — the last axis there is width."""

    def transform_image(self, img, rng):
        return img[..., ::-1] if img.ndim == 3 else img


class Crop(FeatureTransformer):
    """augmentation/Crop.scala base — crop by an explicit roi
    (x1, y1, x2, y2), normalized coords by default, clipped to bounds."""

    def __init__(self, roi, normalized: bool = True, is_clip: bool = True,
                 **kw):
        super().__init__(**kw)
        self.roi, self.normalized, self.is_clip = tuple(roi), normalized, \
            is_clip

    def generate_roi(self, img, rng):
        return self.roi

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.generate_roi(img, rng)
        if self.normalized:
            x1, y1, x2, y2 = x1 * w, y1 * h, x2 * w, y2 * h
        if self.is_clip:
            x1, x2 = max(0, x1), min(w, x2)
            y1, y2 = max(0, y1), min(h, y2)
        elif not (0 <= x1 < x2 <= w and 0 <= y1 < y2 <= h):
            # without clipping an out-of-bounds roi cannot be represented
            # by a numpy view (negative indices would WRAP); fail loudly
            raise ValueError(
                f"crop roi ({x1},{y1},{x2},{y2}) outside {w}x{h} image "
                "(set is_clip=True to clamp)")
        return img[int(y1):int(y2), int(x1):int(x2)]


class RandomCropper(FeatureTransformer):
    """augmentation/RandomCropper.scala — crop to (cropWidth, cropHeight)
    at a random (or center) position, with optional random mirror."""

    def __init__(self, crop_width: int, crop_height: int,
                 mirror: bool = True, cropper_method: str = "random", **kw):
        super().__init__(**kw)
        assert cropper_method in ("random", "center"), cropper_method
        # one source of truth for the offset math: delegate to the
        # existing crop transformers
        self._crop = (RandomCrop if cropper_method == "random"
                      else CenterCrop)(crop_width, crop_height)
        self.mirror = mirror
        self.cropper_method = cropper_method

    def transform_image(self, img, rng):
        out = self._crop.transform_image(img, rng)
        if self.mirror and rng.rand() < 0.5:
            out = out[:, ::-1]
        return out


class RandomResize(FeatureTransformer):
    """augmentation/RandomResize.scala — resize so the SHORTER side is a
    uniform random size in [min_size, max_size], keeping aspect."""

    def __init__(self, min_size: int, max_size: int, **kw):
        super().__init__(**kw)
        self.min_size, self.max_size = min_size, max_size

    def transform_image(self, img, rng):
        h, w = img.shape[:2]
        short = rng.randint(self.min_size, self.max_size + 1)
        if h < w:
            oh, ow = short, int(round(w / h * short))
        else:
            oh, ow = int(round(h / w * short)), short
        return _resize_bilinear(img, oh, ow)


# reference name for the inception-style scale/aspect crop
RandomAlterAspect = RandomResizedCrop
