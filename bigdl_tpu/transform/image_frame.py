"""ImageFrame: the vision-frame carrier the reference's detection examples
pipeline through.

Parity: ``transform/vision/image/ImageFrame.scala`` (LocalImageFrame — an
array of ImageFeatures with ``transform``/``read`` — the DistributedImageFrame
RDD variant is Spark-only and designed out; data parallelism here is the
device mesh, not an RDD) and ``MTImageFeatureToBatch.scala`` (ImageFeature
iterator → fixed-size MiniBatch; the reference's "MT" multi-thread pooling is
host-side prefetching here — see ``native/`` — so the class keeps the name
for API parity but is a plain batcher).

ImageFeature keys follow ``ImageFeature.scala``: ``uri``, ``bytes``,
``image`` (decoded HWC float, the ``floats``/``mat`` analog), ``label``,
``boundingBox``, ``predict``, ``originalSize``.
"""
from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence

import numpy as np

from ._vision_impl import FeatureTransformer, ImageFeature, MatToTensor
from ..dataset.minibatch import MiniBatch


class ImageFrame:
    """Factory namespace (ImageFrame.scala object): ``ImageFrame.read`` /
    ``ImageFrame.array`` produce a :class:`LocalImageFrame`."""

    @staticmethod
    def array(images: Sequence, labels: Optional[Sequence] = None
              ) -> "LocalImageFrame":
        """Build from decoded arrays (HWC) or ready ImageFeatures."""
        feats = []
        for i, im in enumerate(images):
            if isinstance(im, ImageFeature):
                f = im
            else:
                f = ImageFeature(image=np.asarray(im, np.float32))
            if labels is not None:
                f["label"] = labels[i]
            f.setdefault("originalSize",
                         tuple(np.asarray(f["image"]).shape)
                         if "image" in f else None)
            feats.append(f)
        return LocalImageFrame(feats)

    @staticmethod
    def read(path: str, with_label: bool = False) -> "LocalImageFrame":
        """Read a file / folder of JPEGs (ImageFrame.read local mode).
        ``with_label=True`` treats immediate subfolders as class labels
        (1-based, sorted — the ImageNet folder convention). Decoding uses
        the native libjpeg path with a PIL/torchvision-free fallback
        (dataset/imagenet.py's decoder)."""
        from ..dataset.imagenet import _decoder, scan_folder
        decode = _decoder()
        feats = []
        if os.path.isfile(path):
            entries = [(path, None)]
        elif with_label:
            # folder/<class>/<image> layout: one listing implementation
            # (dataset/imagenet.py) owns the extension set and ordering
            paths, labels, _ = scan_folder(path)
            entries = list(zip(paths, labels))
        else:
            entries = [(os.path.join(path, f), None)
                       for f in sorted(os.listdir(path))
                       if f.lower().endswith((".jpg", ".jpeg", ".png",
                                              ".bmp"))]
        for p, label in entries:
            img = decode(p)
            f = ImageFeature(image=np.asarray(img, np.float32), uri=p,
                             originalSize=tuple(np.asarray(img).shape))
            if label is not None:
                f["label"] = label
            feats.append(f)
        return LocalImageFrame(feats)


class LocalImageFrame:
    """An in-memory sequence of ImageFeatures (LocalImageFrame in
    ImageFrame.scala), transformable by FeatureTransformers."""

    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    def __len__(self):
        return len(self.features)

    def __iter__(self):
        return iter(self.features)

    def transform(self, transformer) -> "LocalImageFrame":
        """Apply a (composed) FeatureTransformer; returns a NEW frame (the
        reference mutates its array in place — a functional copy is safer
        and the arrays are shared when a transformer passes them through)."""
        out = list(transformer(iter(self.features)))
        feats = [f if isinstance(f, ImageFeature)
                 else ImageFeature(f) if isinstance(f, dict)
                 else ImageFeature(image=f)
                 for f in out]
        return LocalImageFrame(feats)

    # `frame -> transformer` composes in the reference; `|` would collide
    # with dict union on ImageFeature, so transform() is the one spelling.

    def to_distributed(self):
        raise NotImplementedError(
            "DistributedImageFrame is Spark-only in the reference; here "
            "distribution happens at the mesh level (DistriOptimizer / "
            "sharded DataSet), not the frame level")


class MTImageFeatureToBatch:
    """ImageFeature iterator → MiniBatch stream
    (MTImageFeatureToBatch.scala). Center-crops/pads every image to
    (height, width), stacks CHW floats, attaches labels when present;
    ``with_bbox=True`` also carries per-image bounding boxes (the SSD/
    Faster-RCNN path) as a list aligned with the batch."""

    def __init__(self, width: int, height: int, batch_size: int,
                 transformer: Optional[FeatureTransformer] = None,
                 to_rgb: bool = False, with_bbox: bool = False):
        self.width, self.height = width, height
        self.batch_size = batch_size
        self.transformer = transformer
        self.to_rgb = to_rgb
        self.with_bbox = with_bbox

    def _fit(self, img: np.ndarray) -> np.ndarray:
        h, w = img.shape[:2]
        if img.ndim == 2:
            img = img[:, :, None]
        # center-crop then zero-pad to the exact target (the reference
        # assumes the transformer already resized; this is the safety net)
        y0 = max((h - self.height) // 2, 0)
        x0 = max((w - self.width) // 2, 0)
        img = img[y0:y0 + self.height, x0:x0 + self.width]
        ph, pw = self.height - img.shape[0], self.width - img.shape[1]
        if ph or pw:
            img = np.pad(img, ((0, ph), (0, pw), (0, 0)))
        return img

    def __call__(self, features: Iterable[ImageFeature]):
        mat = MatToTensor()
        batch_imgs, batch_labels, batch_boxes = [], [], []
        it = iter(features)
        if self.transformer is not None:
            it = self.transformer(it)
        for f in it:
            if not isinstance(f, (dict, ImageFeature)):
                f = ImageFeature(image=f)
            img = self._fit(np.asarray(f["image"], np.float32))
            if self.to_rgb:
                img = img[:, :, ::-1]
            batch_imgs.append(mat.transform_image(img, None))
            if "label" in f:
                batch_labels.append(np.asarray(f["label"], np.float32))
            if batch_labels and len(batch_labels) != len(batch_imgs):
                raise ValueError(
                    "MTImageFeatureToBatch: mixed labeled/unlabeled "
                    "ImageFeatures in one stream — labels would misalign "
                    "with images (give every feature a 'label' or none)")
            if self.with_bbox:
                batch_boxes.append(np.asarray(f.get("boundingBox",
                                                    np.zeros((0, 4)))))
            if len(batch_imgs) == self.batch_size:
                yield self._emit(batch_imgs, batch_labels, batch_boxes)
                batch_imgs, batch_labels, batch_boxes = [], [], []
        if batch_imgs:
            yield self._emit(batch_imgs, batch_labels, batch_boxes)

    def _emit(self, imgs, labels, boxes):
        inp = np.stack(imgs)
        tgt = np.stack(labels) if labels else None
        mb = MiniBatch(inp, tgt)
        if self.with_bbox:
            mb.bboxes = boxes
        return mb
