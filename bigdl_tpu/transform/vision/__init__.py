"""``bigdl_tpu.transform.vision`` — pyspark-parity package path
(reference ``bigdl/transform/vision/``); the implementation lives in
``transform/_vision_impl.py``."""
from .. import _vision_impl as _impl

from bigdl_tpu.util._parity import public_names as _public_names

__all__ = _public_names(_impl)
globals().update({n: getattr(_impl, n) for n in __all__})
