"""``bigdl_tpu.transform.vision`` — pyspark-parity package path
(reference ``bigdl/transform/vision/``); the implementation lives in
``transform/_vision_impl.py``."""
import inspect as _inspect

from .. import _vision_impl as _impl

__all__ = [n for n in dir(_impl)
           if not n.startswith("_")
           and not _inspect.ismodule(getattr(_impl, n))
           and getattr(getattr(_impl, n), "__module__",
                       "").startswith("bigdl_tpu")]
globals().update({n: getattr(_impl, n) for n in __all__})
