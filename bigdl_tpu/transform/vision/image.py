"""``bigdl_tpu.transform.vision.image`` — the reference's module path for
every vision transform (``from bigdl.transform.vision.image import
Resize, ...`` ports with just the package rename)."""
from . import __all__                   # noqa: F401
from . import *                         # noqa: F401,F403
