"""``bigdl_tpu.util`` — pyspark-parity spelling of the util package.

The reference's Python API lives under ``bigdl.util`` (singular); this
package mirrors that module path so user scripts port with only the
top-level package rename. The TPU-native utilities themselves live in
``bigdl_tpu.utils`` (plural).
"""
from . import common  # noqa: F401
