"""Shared helper for the pyspark-parity re-export shims."""
from __future__ import annotations

import inspect


def public_names(mod):
    """Names a parity shim should re-export from ``mod``: public,
    non-module, defined inside this package (so star imports bind layer
    classes — never np/jax or submodule objects)."""
    out = []
    for n in dir(mod):
        if n.startswith("_"):
            continue
        obj = getattr(mod, n)
        if inspect.ismodule(obj):
            continue
        owner = getattr(obj, "__module__", "") or ""
        if owner == "bigdl_tpu" or owner.startswith("bigdl_tpu."):
            out.append(n)
    return out
