"""Drop-in spelling of ``bigdl.util.common`` (reference
``pyspark/bigdl/util/common.py``) — the helpers every reference user
script imports, re-grounded on the TPU runtime.

Design deltas (deliberate, documented): there is no JVM and no Spark
here, so the py4j plumbing (``JavaValue``, ``callBigDlFunc``, gateways)
does not exist; ``init_engine`` initialises the XLA engine instead of a
JVM; the Spark-context helpers raise with a pointer to the mesh-based
equivalent rather than silently half-working (README "Design deltas").
"""
from __future__ import annotations

import numpy as np

from ..dataset.sample import Sample  # noqa: F401  (parity re-export)
from ..utils import engine


def get_dtype(bigdl_type="float"):
    """Numeric dtype for the reference's ``bigdl_type`` tag."""
    return np.float64 if bigdl_type == "double" else np.float32


class JTensor:
    """ndarray carrier (parity: ``bigdl.util.common.JTensor``).

    The reference uses it to marshal tensors across py4j; here it is a
    plain host-side (storage, shape[, indices]) triple with the same
    constructor/round-trip surface, so ported code keeps working.
    ``indices`` present means a sparse (COO) tensor.
    """

    def __init__(self, storage, shape, bigdl_type="float", indices=None):
        dt = get_dtype(bigdl_type)
        if isinstance(storage, bytes) and isinstance(shape, bytes):
            self.storage = np.frombuffer(storage, dtype=dt)
            self.shape = np.frombuffer(shape, dtype=np.int32)
        else:
            self.storage = np.array(storage, dtype=dt)
            self.shape = np.array(shape, dtype=np.int32)
        if indices is None:
            self.indices = None
        elif isinstance(indices, bytes):
            self.indices = np.frombuffer(indices, dtype=np.int32)
        else:
            self.indices = np.array(indices, dtype=np.int32)
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, a_ndarray, bigdl_type="float"):
        a = np.asarray(a_ndarray)
        return cls(a.reshape(-1), np.array(a.shape, np.int32), bigdl_type)

    @classmethod
    def sparse(cls, a_ndarray, i_ndarray, shape, bigdl_type="float"):
        """COO sparse: values + (ndim, nnz) indices + dense shape."""
        return cls(np.asarray(a_ndarray).reshape(-1),
                   np.array(shape, np.int32), bigdl_type,
                   indices=np.asarray(i_ndarray).reshape(-1))

    def to_ndarray(self):
        assert self.indices is None, \
            "sparse JTensor: use bigdl_tpu.nn.SparseTensor for compute"
        return self.storage.reshape(tuple(int(s) for s in self.shape))

    def __repr__(self):
        kind = "Sparse" if self.indices is not None else "Dense"
        return f"JTensor[{kind}]{tuple(int(s) for s in self.shape)}"


class RNG:
    """Seeded tensor generator (parity: ``bigdl.util.common.RNG``)."""

    def __init__(self, bigdl_type="float"):
        self.bigdl_type = bigdl_type
        self._rng = np.random.RandomState()

    def set_seed(self, seed):
        self._rng = np.random.RandomState(seed)
        engine.set_seed(seed)

    def uniform(self, a, b, size):
        return self._rng.uniform(a, b, size).astype(
            get_dtype(self.bigdl_type))


def init_engine(bigdl_type="float"):
    """Initialise the execution engine (reference: spins up the JVM +
    BigDL engine; here: the XLA engine/default mesh)."""
    if not engine.is_initialized():
        engine.init()


def get_node_and_core_number(bigdl_type="float"):
    if not engine.is_initialized():
        init_engine()        # lazy-init like engine.get_mesh(): never the
        # placeholder (1, 1) of an uninitialised engine
    return engine.node_number(), engine.core_number()


def to_list(a):
    if isinstance(a, list):
        return a
    return [a]


def to_sample_rdd(x, y, numSlices=None):
    """Reference: parallelises (x, y) into an RDD[Sample]. Here: the
    local list of Samples the optimizers' dataset protocol accepts
    (XLA owns the device-level split; see docs/DISTRIBUTED.md)."""
    x = np.asarray(x)
    y = np.asarray(y)
    return [Sample.from_ndarray(xi, yi) for xi, yi in zip(x, y)]


_log_handlers = {}


def redire_spark_logs(bigdl_type="float", log_path=None):
    """No Spark logs exist here; route the framework logger to a file
    instead so ported scripts keep their logging side effect. Default
    path matches the reference (``./bigdl.log``); repeated calls for the
    same path reuse one handler instead of multiplying log lines."""
    import logging
    import os
    log_path = log_path or os.path.join(os.getcwd(), "bigdl.log")
    key = os.path.abspath(log_path)
    if key not in _log_handlers:
        _log_handlers[key] = logging.FileHandler(log_path)
        logging.getLogger("bigdl_tpu").addHandler(_log_handlers[key])


def show_bigdl_info_logs(bigdl_type="float"):
    import logging
    logging.getLogger("bigdl_tpu").setLevel(logging.INFO)


def _no_spark(name):
    raise NotImplementedError(
        f"{name}: there is no Spark runtime in bigdl_tpu — distribution "
        "is mesh-based (jax.sharding). See docs/DISTRIBUTED.md; "
        "DistriOptimizer replaces the Spark execution path.")


def create_spark_conf():
    _no_spark("create_spark_conf")


def get_spark_context(conf=None):
    _no_spark("get_spark_context")


def get_spark_sql_context(sc=None):
    _no_spark("get_spark_sql_context")
