"""``bigdl_tpu.util.tf_utils`` — pyspark-parity module path (reference
``bigdl/util/tf_utils.py``).

The reference's helpers marshal a live tf.Session's graph into its own
dump format for the Scala TF loader. Here TensorFlow interop is first
class in ``bigdl_tpu.loaders`` (GraphDef loader/saver + TFSession), so
these are thin spellings over that machinery; helpers that only existed
to feed the JVM byte order raise with a pointer to the native path.
"""
from __future__ import annotations

__all__ = ["get_path", "convert", "dump_model"]


def get_path(output_name, sess=None):
    """Reference: writes the session's frozen GraphDef to a temp dir and
    returns the path. Requires real TensorFlow (same gating as the
    loaders' cross-validation tests). Like the reference, a missing
    ``sess`` falls back to a fresh initialized Session over the default
    graph."""
    import os
    import tempfile

    import tensorflow as tf
    tf1 = tf.compat.v1
    owned = False
    if sess is None:
        sess = tf1.get_default_session()
    if sess is None:
        sess = tf1.Session()
        sess.run(tf1.global_variables_initializer())
        owned = True
    try:
        graph_def = tf1.graph_util.convert_variables_to_constants(
            sess, sess.graph_def, [_node_name(output_name)])
    finally:
        if owned:
            sess.close()
    d = tempfile.mkdtemp()
    path = os.path.join(d, "model.pb")
    with open(path, "wb") as f:
        f.write(graph_def.SerializeToString())
    return path


def convert(input_ops, output_ops, byte_order="little_endian",
            bigdl_type="float", graph_def=None, sess=None):
    """Convert a TF graph into a native model (reference: py4j call into
    the Scala TF loader; here: ``loaders.load_tf_graph``)."""
    from ..loaders import load_tf_graph
    if graph_def is None:
        path = get_path(output_ops[0] if isinstance(output_ops, (list,
                                                                 tuple))
                        else output_ops, sess)
        return load_tf_graph(
            path,
            inputs=[_node_name(o) for o in (input_ops or [])] or None,
            outputs=[_node_name(o) for o in (output_ops or [])] or None)
    if sess is not None and hasattr(graph_def, "node"):
        # a session means there may be live Variables: freeze them so the
        # loader (constants-only) sees their values
        import tensorflow as tf
        outs = [_node_name(o) for o in (output_ops or [])]
        graph_def = tf.compat.v1.graph_util.convert_variables_to_constants(
            sess, graph_def, outs)
    if hasattr(graph_def, "SerializeToString"):
        graph_def = graph_def.SerializeToString()
    return load_tf_graph(
        graph_def,
        inputs=[_node_name(o) for o in (input_ops or [])] or None,
        outputs=[_node_name(o) for o in (output_ops or [])] or None)


def _node_name(op_or_name):
    """'x:0' tensor names and tf op objects → loader node names."""
    name = getattr(op_or_name, "name", op_or_name)
    return name.split(":")[0]


def dump_model(path, graph=None, sess=None, ckpt_file=None,
               bigdl_type="float"):
    raise NotImplementedError(
        "dump_model wrote the reference's JVM-endian dump format; the "
        "native path is loaders.load_tf_graph / save_tf_graph (GraphDef "
        "in, GraphDef out) — see docs/MIGRATION.md")
