from .table import Table, T
from .shape import Shape, SingleShape, MultiShape
from . import engine
