from .table import Table, T
from .shape import Shape, SingleShape, MultiShape
from . import engine
from .directed_graph import DirectedGraph, Node as GraphNode, Edge
from .misc import (File, ThreadPool, crc32, string_hash,
                   redirect_spark_info_logs, profile_trace,
                   device_memory_stats)
