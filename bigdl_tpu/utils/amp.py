"""Mixed-precision helpers — the TPU training recipe in one place.

The bf16 recipe every bench/example uses: keep f32 MASTER params (the
optimizer update stays f32), cast to bf16 inside the jitted step so all
MXU contractions run at bf16 throughput, compute losses in f32. These
helpers are the one shared spelling of the cast (previously copy-pasted
across the benches/tools).

Reference analog: the mkldnn backend's f32↔bf16 reorder layers; here a
pytree cast that XLA folds into the step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["bf16_params"]


def bf16_params(tree):
    """Cast every f32 leaf to bf16 (non-f32 leaves — int8 quantized
    weights, int tables, already-bf16 — pass through untouched)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if getattr(a, "dtype", None) == jnp.float32 else a, tree)
