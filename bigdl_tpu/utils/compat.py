"""JAX version compatibility shims.

The codebase targets the modern ``jax.shard_map`` API (``check_vma=``);
older installs (< 0.5) only ship ``jax.experimental.shard_map`` with the
``check_rep=`` spelling. Import ``shard_map`` from here instead of from
``jax`` so both work.
"""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _NEW_API = True
except ImportError:  # jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEW_API = False


def pvary(x, axes):
    """Mark a value as varying over named axes (strict-VMA shard_map).
    Pre-VMA jax (< 0.6) has neither ``pcast`` nor ``pvary`` — and no
    varying-manual-axes checking either, so identity is correct there."""
    from jax import lax
    if hasattr(lax, "pcast"):
        try:
            return lax.pcast(x, axes, to="varying")
        except TypeError:  # pcast exists but predates the to= keyword
            pass
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def axis_size(axis_name) -> int:
    """``lax.axis_size`` (new API) with a pre-0.5 fallback that reads the
    size from the innermost binding frame of the named axis."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax._src import core as _core
    return _core.get_axis_env().axis_size(axis_name)


def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
              check_vma=True, **kw):
    """``jax.shard_map`` with ``check_vma`` mapped to the installed
    API's keyword (``check_rep`` pre-0.5). Supports the same optional
    decorator usage (``f=None`` returns a partial).

    On the pre-VMA API the replication checker is disabled outright:
    it is a static check only, and it has no rules for pallas_call and
    other primitives these code paths rely on."""
    if _NEW_API:
        kw["check_vma"] = check_vma
    else:
        kw["check_rep"] = False
    if f is None:
        def wrap(g):
            return _shard_map(g, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
        return wrap
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
