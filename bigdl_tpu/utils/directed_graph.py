"""Directed graph utility.

Parity: reference ``utils/DirectedGraph.scala`` + ``utils/Node.scala`` /
``Edge`` — generic DAG with topological sort, BFS, DFS, reverse. Used by the
serialization/IR tooling (the nn Graph container keeps its own lean node
type for trace-time speed).
"""
from __future__ import annotations

from collections import deque
from typing import Any, List, Optional


class Edge:
    def __init__(self, from_index: Optional[int] = None):
        self.from_index = from_index

    def __repr__(self):
        return f"Edge({self.from_index})"


class Node:
    def __init__(self, element: Any):
        self.element = element
        self.nexts: List[tuple] = []  # (node, edge)
        self.prevs: List[tuple] = []

    def add(self, node: "Node", edge: Optional[Edge] = None):
        e = edge or Edge()
        self.nexts.append((node, e))
        node.prevs.append((self, e))
        return node

    def delete(self, node: "Node"):
        self.nexts = [(n, e) for n, e in self.nexts if n is not node]
        node.prevs = [(n, e) for n, e in node.prevs if n is not self]
        return self

    def remove_prev_edges(self):
        for p, e in list(self.prevs):
            p.nexts = [(n, ee) for n, ee in p.nexts if n is not self]
        self.prevs = []
        return self

    def __repr__(self):
        return f"Node({self.element})"


class DirectedGraph:
    def __init__(self, source: Node, reverse: bool = False):
        self.source = source
        self.reverse = reverse

    def _neighbors(self, node: Node):
        pairs = node.prevs if self.reverse else node.nexts
        return [n for n, _ in pairs]

    def bfs(self):
        seen = {id(self.source)}
        q = deque([self.source])
        while q:
            n = q.popleft()
            yield n
            for nb in self._neighbors(n):
                if id(nb) not in seen:
                    seen.add(id(nb))
                    q.append(nb)

    def dfs(self):
        seen = set()
        stack = [self.source]
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            yield n
            for nb in reversed(self._neighbors(n)):
                if id(nb) not in seen:
                    stack.append(nb)

    def topology_sort(self) -> List[Node]:
        # iterative post-order DFS: no recursion limit on deep chains
        order, temp, perm = [], set(), set()
        stack = [(self.source, False)]
        while stack:
            n, children_done = stack.pop()
            if id(n) in perm:
                continue
            if children_done:
                temp.discard(id(n))
                perm.add(id(n))
                order.append(n)
                continue
            if id(n) in temp:
                raise ValueError("graph contains a cycle")
            temp.add(id(n))
            stack.append((n, True))
            for nb in reversed(self._neighbors(n)):
                if id(nb) in temp and id(nb) not in perm:
                    raise ValueError("graph contains a cycle")
                if id(nb) not in perm:
                    stack.append((nb, False))
        return list(reversed(order))

    def size(self):
        return sum(1 for _ in self.bfs())

    def edges(self):
        return sum(len(self._neighbors(n)) for n in self.bfs())
