"""Runtime engine: device discovery, mesh construction, seeds.

Parity: reference ``utils/Engine.scala`` — there it configures Spark executor
cores/nodes and the MKL thread pools. On TPU the analog is device/mesh
management: how many chips, what logical mesh axes (data/model/seq), and the
host-side PRNG. XLA owns intra-chip parallelism, so there is no thread-pool
knob to tune; ``Engine.init`` instead fixes the mesh every distributed
component uses.
"""
from __future__ import annotations

import logging
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

logger = logging.getLogger("bigdl_tpu")

_state = {
    "initialized": False,
    "mesh": None,
    "seed": None,
    "rng_key": None,
    "node_number": 1,
    "core_number": 1,
    "engine_type": "xla",
    "compile_cache_dir": None,
    "cache_listener": False,
}


def init(node_number: int = 1,
         core_number: Optional[int] = None,
         mesh_shape: Optional[Sequence[int]] = None,
         mesh_axes: Sequence[str] = ("data",),
         seed: int = 42,
         devices=None):
    """Initialise the engine (parity: Engine.init, utils/Engine.scala:106).

    ``mesh_shape``/``mesh_axes`` define the logical device mesh. Default is a
    1-D ``data`` mesh over every visible device. Multi-host initialisation
    (jax.distributed) must happen before calling this.
    """
    devices = list(devices if devices is not None else jax.devices())
    if core_number is None:
        core_number = len(devices)
    if mesh_shape is None:
        mesh_shape = (len(devices),)
    dev_arr = np.array(devices[: int(np.prod(mesh_shape))]).reshape(mesh_shape)
    mesh = jax.sharding.Mesh(dev_arr, tuple(mesh_axes))
    _state.update(initialized=True, mesh=mesh, seed=seed,
                  rng_key=jax.random.PRNGKey(seed),
                  node_number=node_number, core_number=core_number)
    maybe_enable_compilation_cache()
    return mesh


def is_initialized() -> bool:
    return _state["initialized"]


def get_mesh() -> jax.sharding.Mesh:
    if _state["mesh"] is None:
        init()
    return _state["mesh"]


def set_seed(seed: int):
    _state["seed"] = seed
    _state["rng_key"] = jax.random.PRNGKey(seed)


def get_seed():
    return _state["seed"]


def next_rng_key():
    """Split and return a fresh PRNG key from the global stream."""
    if _state["rng_key"] is None:
        set_seed(42 if _state["seed"] is None else _state["seed"])
    _state["rng_key"], sub = jax.random.split(_state["rng_key"])
    return sub


def _split_many(key, k):
    """k chained splits in one compiled program; returns (chain, [k] subs)."""
    return jax.lax.scan(lambda c, _: tuple(jax.random.split(c)), key,
                        None, length=k)


_split_many_jit = None


def next_rng_keys(k: int):
    """``k`` fresh keys from the global stream, stacked ``[k, ...]``, in
    ONE dispatch — bitwise the keys ``k`` successive :func:`next_rng_key`
    calls would return (each split depends only on its input key, so the
    scanned chain reproduces the sequential chain exactly). The superstep
    loop uses this so per-dispatch host work stays O(1) in K."""
    if _state["rng_key"] is None:
        set_seed(42 if _state["seed"] is None else _state["seed"])
    global _split_many_jit
    if _split_many_jit is None:
        _split_many_jit = jax.jit(_split_many, static_argnums=1)
    _state["rng_key"], subs = _split_many_jit(_state["rng_key"], int(k))
    return subs


def node_number() -> int:
    return _state["node_number"]


def core_number() -> int:
    return _state["core_number"]


def engine_type() -> str:
    return _state["engine_type"]


def device_count() -> int:
    return len(jax.devices())


def default_dtype():
    return np.float32


def enable_compilation_cache(cache_dir: Optional[str] = None,
                             min_compile_time_secs: float = 2.0):
    """Turn on JAX's persistent compilation cache.

    On TPU the first compile of a training step is tens of seconds; over
    a remote-device tunnel a connection flap mid-compile loses all of it.
    With the cache, a restarted process (or a bench retry) skips straight
    to execution. Safe to call more than once; honors an explicit
    ``JAX_COMPILATION_CACHE_DIR`` already in the environment.
    """
    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or os.path.join(os.path.expanduser("~"),
                                 ".cache", "bigdl_tpu", "xla"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_secs))
    _state["compile_cache_dir"] = cache_dir
    _register_cache_events()
    return cache_dir


def maybe_enable_compilation_cache():
    """Idempotent, env-gated cache enable — the lazy entry point every
    compile site (``Optimizer._build_step``, ``Evaluator``/``Predictor``
    forward builds, ``bench.py`` children) calls before jitting, so a
    restarted process or a later bench run skips straight to execution.
    ``BIGDL_TPU_COMPILE_CACHE=0`` opts out; an explicit
    ``JAX_COMPILATION_CACHE_DIR`` is honored as the location."""
    if _state["compile_cache_dir"]:
        return _state["compile_cache_dir"]
    if os.environ.get("BIGDL_TPU_COMPILE_CACHE", "1").lower() in (
            "0", "false", "off"):
        return None
    try:
        return enable_compilation_cache()
    except (OSError, ValueError) as e:  # unwritable dir must not stop training
        logger.warning("persistent compilation cache unavailable: %s", e)
        return None


def compilation_cache_dir():
    """The active persistent-cache directory, or None when disabled."""
    return _state["compile_cache_dir"]


def compilation_cache_stats() -> dict:
    """One-call provenance snapshot of the persistent compile cache —
    what the perf-introspection reports embed next to each program's
    hit/miss deltas."""
    from .. import observability as obs
    reg = obs.registry()
    return {
        "dir": _state["compile_cache_dir"],
        "entries": compilation_cache_entries(),
        "hits": int(reg.counter("engine/compile_cache_hits").value),
        "misses": int(reg.counter("engine/compile_cache_misses").value),
    }


def compilation_cache_entries() -> int:
    """Number of compiled executables in the persistent cache (0 when
    disabled) — exported as the ``engine/compile_cache_entries`` gauge."""
    d = _state["compile_cache_dir"]
    if not d or not os.path.isdir(d):
        return 0
    try:
        return sum(1 for f in os.listdir(d) if not f.startswith("."))
    except OSError:
        return 0


def _register_cache_events():
    """Bridge jax's compilation-cache monitoring events into the
    observability registry: ``engine/compile_cache_hits`` /
    ``engine/compile_cache_misses`` counters (a hit means a ``jit``
    skipped XLA compilation entirely — the cross-process win the
    persistent cache exists for)."""
    if _state["cache_listener"]:
        return
    try:
        from jax import monitoring
    except ImportError:  # very old jax: no event stream, gauge-only mode
        return
    from .. import observability as obs
    names = {
        "/jax/compilation_cache/cache_hits": "engine/compile_cache_hits",
        "/jax/compilation_cache/cache_misses": "engine/compile_cache_misses",
    }

    def _on_event(event, **kw):
        name = names.get(event)
        if name is not None and obs.enabled():
            obs.counter(name).inc()

    monitoring.register_event_listener(_on_event)
    _state["cache_listener"] = True


class RandomGenerator:
    """Parity: utils/RandomGenerator.scala — thin facade over the engine PRNG."""

    @staticmethod
    def set_seed(seed):
        set_seed(seed)
        np.random.seed(seed & 0x7FFFFFFF)

    @staticmethod
    def uniform(lo, hi, shape=()):
        return jax.random.uniform(next_rng_key(), shape, minval=lo, maxval=hi)

    @staticmethod
    def normal(mean, std, shape=()):
        return mean + std * jax.random.normal(next_rng_key(), shape)
