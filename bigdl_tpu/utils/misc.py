"""Runtime utilities.

Parity: reference ``utils/LoggerFilter.scala`` (log redirection/quieting),
``utils/File.scala`` (save/load), ``utils/Crc32.scala`` + ``HashFunc``,
``utils/ThreadPool.scala`` (host-side executor — device parallelism belongs
to XLA), and profiling hooks (reference ``optim/Metrics`` + jax.profiler).
"""
from __future__ import annotations

import concurrent.futures
import contextlib
import hashlib
import logging
import os
import pickle
import zlib


# ---------------------------------------------------------------------------
# LoggerFilter (utils/LoggerFilter.scala)
# ---------------------------------------------------------------------------
def redirect_spark_info_logs(log_file: str = "bigdl.log",
                             quiet_loggers=("jax", "absl")):
    """Quiet noisy third-party loggers to a file, keep bigdl_tpu on console
    (parity: LoggerFilter.redirectSparkInfoLogs)."""
    handler = logging.FileHandler(log_file)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s %(message)s"))
    for name in quiet_loggers:
        lg = logging.getLogger(name)
        lg.handlers = [handler]
        lg.propagate = False
        # capture INFO into the file (otherwise the logger inherits the
        # root's WARNING level and INFO records are dropped, not redirected)
        lg.setLevel(logging.INFO)
    logging.getLogger("bigdl_tpu").setLevel(logging.INFO)


# ---------------------------------------------------------------------------
# File (utils/File.scala)
# ---------------------------------------------------------------------------
class File:
    @staticmethod
    def save(obj, path: str, overwrite: bool = True):
        if not overwrite and os.path.exists(path):
            raise IOError(f"{path} exists; overwrite=False")
        with open(path, "wb") as f:
            pickle.dump(obj, f)

    @staticmethod
    def load(path: str):
        with open(path, "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------------------
# Crc32 / HashFunc (utils/Crc32.scala, utils/HashFunc.scala)
# ---------------------------------------------------------------------------
def crc32(data: bytes, seed: int = 0) -> int:
    return zlib.crc32(data, seed) & 0xFFFFFFFF


def string_hash(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


# ---------------------------------------------------------------------------
# ThreadPool (utils/ThreadPool.scala) — host-side only
# ---------------------------------------------------------------------------
class ThreadPool:
    """Host-side executor for IO/augmentation. The reference used this to
    parallelise layer compute across Xeon cores; on TPU that role belongs to
    XLA, so this only serves the input pipeline."""

    def __init__(self, pool_size: int):
        self.pool_size = pool_size
        self._ex = concurrent.futures.ThreadPoolExecutor(pool_size)

    def invoke(self, fns):
        return [self._ex.submit(fn) for fn in fns]

    def invoke_and_wait(self, fns):
        return [f.result() for f in self.invoke(fns)]

    def shutdown(self):
        self._ex.shutdown()


# ---------------------------------------------------------------------------
# Profiling (jax.profiler integration + device memory stats)
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def profile_trace(logdir: str):
    """Capture an XLA profile viewable in TensorBoard/perfetto."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def device_memory_stats():
    """Per-device memory stats (HBM usage) where the backend reports them."""
    import jax
    out = {}
    for d in jax.devices():
        try:
            out[str(d)] = d.memory_stats()
        except Exception:
            out[str(d)] = None
    return out
