"""Shape descriptors for the keras-style API.

Parity: reference ``utils/Shape.scala`` (SingleShape / MultiShape).
"""
from __future__ import annotations


class Shape:
    @staticmethod
    def of(*dims):
        if len(dims) == 1 and isinstance(dims[0], (list, tuple)):
            return SingleShape(list(dims[0]))
        if len(dims) and isinstance(dims[0], Shape):
            return MultiShape(list(dims))
        return SingleShape(list(dims))


class SingleShape(Shape):
    def __init__(self, dims):
        self.dims = list(dims)

    def to_single(self):
        return self.dims

    def copy_and_update(self, idx, value):
        d = list(self.dims)
        d[idx] = value
        return SingleShape(d)

    def __eq__(self, other):
        return isinstance(other, SingleShape) and self.dims == other.dims

    def __repr__(self):
        return f"SingleShape({self.dims})"


class MultiShape(Shape):
    def __init__(self, shapes):
        self.shapes = list(shapes)

    def to_multi(self):
        return self.shapes

    def __eq__(self, other):
        return isinstance(other, MultiShape) and self.shapes == other.shapes

    def __repr__(self):
        return f"MultiShape({self.shapes})"
