"""Torch-style heterogeneous activity container.

Parity: reference ``utils/Table.scala`` — a 1-indexed map used wherever a module
consumes/produces multiple activities. Here a ``Table`` is a thin list-like pytree
node so it can flow through ``jax.jit``/``jax.vjp`` unchanged.
"""
from __future__ import annotations

import jax


class Table:
    """1-indexed heterogeneous container (reference utils/Table.scala:37)."""

    def __init__(self, *items):
        if len(items) == 1 and isinstance(items[0], (list, tuple)):
            items = tuple(items[0])
        self._items = list(items)
        self._named = {}      # string-keyed entries (reference Table is an
        #                       arbitrary-keyed map; RowTransformer uses it)

    # -- torch-style 1-indexed access ------------------------------------
    def __getitem__(self, key):
        if isinstance(key, int):
            if key < 1:
                raise IndexError("Table is 1-indexed (torch convention)")
            return self._items[key - 1]
        if isinstance(key, str):
            return self._named[key]
        raise TypeError(f"Table index must be int or str, got {type(key)}")

    def __setitem__(self, key, value):
        if isinstance(key, str):
            self._named[key] = value
            return
        if key < 1:
            raise IndexError("Table is 1-indexed")
        while len(self._items) < key:
            self._items.append(None)
        self._items[key - 1] = value

    def __contains__(self, key):
        if isinstance(key, str):
            return key in self._named
        return isinstance(key, int) and 1 <= key <= len(self._items)

    def keys(self):
        """Named keys (string-keyed entries only)."""
        return self._named.keys()

    def update(self, key, value):
        """Reference ``table.update(key, value)`` alias."""
        self[key] = value
        return self

    def insert(self, value):
        self._items.append(value)
        return self

    def length(self):
        return len(self._items)

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def to_list(self):
        return list(self._items)

    def __repr__(self):
        parts = [repr(i) for i in self._items]
        parts += [f"{k}={v!r}" for k, v in self._named.items()]
        return "Table{" + ", ".join(parts) + "}"

    def __eq__(self, other):
        if isinstance(other, Table):
            return (self._items == other._items
                    and self._named == other._named)
        return NotImplemented

    def __hash__(self):
        return id(self)


def _table_flatten(t: Table):
    named_keys = tuple(t._named.keys())
    children = t._items + [t._named[k] for k in named_keys]
    return children, (len(t._items), named_keys)


def _table_unflatten(aux, items):
    if aux is None:         # flattened by a pre-r4 treedef
        return Table(*items)
    n, named_keys = aux
    items = list(items)
    t = Table(*items[:n])
    for k, v in zip(named_keys, items[n:]):
        t._named[k] = v
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)


def T(*items):
    """Shorthand constructor, parity with reference ``T(...)``."""
    return Table(*items)
