"""Torch-style heterogeneous activity container.

Parity: reference ``utils/Table.scala`` — a 1-indexed map used wherever a module
consumes/produces multiple activities. Here a ``Table`` is a thin list-like pytree
node so it can flow through ``jax.jit``/``jax.vjp`` unchanged.
"""
from __future__ import annotations

import jax


class Table:
    """1-indexed heterogeneous container (reference utils/Table.scala:37)."""

    def __init__(self, *items):
        if len(items) == 1 and isinstance(items[0], (list, tuple)):
            items = tuple(items[0])
        self._items = list(items)

    # -- torch-style 1-indexed access ------------------------------------
    def __getitem__(self, key):
        if isinstance(key, int):
            if key < 1:
                raise IndexError("Table is 1-indexed (torch convention)")
            return self._items[key - 1]
        raise TypeError(f"Table index must be int, got {type(key)}")

    def __setitem__(self, key, value):
        if key < 1:
            raise IndexError("Table is 1-indexed")
        while len(self._items) < key:
            self._items.append(None)
        self._items[key - 1] = value

    def insert(self, value):
        self._items.append(value)
        return self

    def length(self):
        return len(self._items)

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def to_list(self):
        return list(self._items)

    def __repr__(self):
        return "Table{" + ", ".join(repr(i) for i in self._items) + "}"

    def __eq__(self, other):
        if isinstance(other, Table):
            return self._items == other._items
        return NotImplemented

    def __hash__(self):
        return id(self)


def _table_flatten(t: Table):
    return t._items, None


def _table_unflatten(aux, items):
    return Table(*items)


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)


def T(*items):
    """Shorthand constructor, parity with reference ``T(...)``."""
    return Table(*items)
