from .summary import TrainSummary, ValidationSummary, Summary
