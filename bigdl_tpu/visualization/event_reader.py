"""TensorBoard event-file reader (no TF dependency).

Parity: reference ``visualization/Summary.scala:77`` ``readScalar`` →
``visualization/tensorboard/FileReader.scala``, which scans the TFRecord
event files on disk (CRC-checked) and filters scalar summaries by tag — so
a *restarted* process, or one pointed at another run's log directory, can
recover training history. The writer side is ``event_writer.EventWriter``;
this module is its inverse and shares the masked-crc32c implementation.

Corrupt or truncated tails (a crashed writer mid-record) end the scan of
that file cleanly at the last valid record, matching TFRecord reader
semantics.
"""
from __future__ import annotations

import os
import struct
from typing import Iterator, List, Tuple

from ..loaders.wire import iter_fields
from .event_writer import _masked_crc


def _scan_records(f) -> Iterator[Tuple[bytes, int]]:
    """Yield (payload, end_offset) for each valid TFRecord frame from the
    file object's current position. Frame layout: u64 length,
    masked-crc32c(length), payload, masked-crc32c(payload). A CRC
    mismatch or short read (truncated tail) stops iteration —
    ``end_offset`` of the last yielded frame is the resume point."""
    while True:
        hdr = f.read(8)
        lcrc = f.read(4)
        if len(hdr) < 8 or len(lcrc) < 4:
            return
        if _masked_crc(hdr) != struct.unpack("<I", lcrc)[0]:
            return
        n = struct.unpack("<Q", hdr)[0]
        data = f.read(n)
        dcrc = f.read(4)
        if len(data) < n or len(dcrc) < 4:
            return
        if _masked_crc(data) != struct.unpack("<I", dcrc)[0]:
            return
        yield data, f.tell()


def iter_records(path: str) -> Iterator[bytes]:
    """Yield the payload of each valid TFRecord frame in ``path``."""
    with open(path, "rb") as f:
        for data, _ in _scan_records(f):
            yield data


def _event_scalars(record: bytes) -> Tuple[int, float, List]:
    """Decode one Event proto → (step, wall_time, [(tag, value), ...])."""
    step, wall, vals = 0, 0.0, []
    for f, w, v in iter_fields(record):
        if f == 2 and w == 0:        # Event.step
            step = v
        elif f == 1 and w == 1:      # Event.wall_time
            wall = struct.unpack("<d", v)[0]
        elif f == 5 and w == 2:      # Event.summary
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 2:          # Summary.value
                    tag, sv = None, None
                    for f3, w3, v3 in iter_fields(v2):
                        if f3 == 1 and w3 == 2:  # Value.tag
                            tag = v3.decode("utf-8")
                        elif f3 == 2 and w3 == 5:  # Value.simple_value
                            sv = struct.unpack("<f", v3)[0]
                    if tag is not None and sv is not None:
                        vals.append((tag, sv))
    return step, wall, vals


class ScalarCache:
    """Incremental event-file scalar reader for one log directory.

    A fresh ``read_scalar`` re-parses every file from byte 0 (with two
    pure-Python CRC loops per record) — quadratic when polled during
    training. This cache remembers each file's resume offset and parsed
    rows, rescanning only appended bytes; a shrunk or replaced file
    (size below the stored offset) resets that file's entry."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._files = {}   # path -> [offset, [(wall, step, tag, value)]]

    def _refresh(self):
        try:
            names = sorted(os.listdir(self.log_dir))
        except (FileNotFoundError, NotADirectoryError):
            return
        for name in names:
            if "tfevents" not in name:
                continue
            path = os.path.join(self.log_dir, name)
            offset, rows = self._files.setdefault(path, [0, []])
            try:
                if os.path.getsize(path) < offset:   # truncated/replaced
                    offset, rows = 0, []
                    self._files[path] = [offset, rows]
                if os.path.getsize(path) == offset:
                    continue
                with open(path, "rb") as f:
                    f.seek(offset)
                    for rec, end in _scan_records(f):
                        step, wall, vals = _event_scalars(rec)
                        rows.extend((wall, step, t, v) for t, v in vals)
                        self._files[path][0] = end
            except OSError:
                continue

    def read(self, tag: str) -> List[Tuple[int, float]]:
        self._refresh()
        rows = [(wall, step, v)
                for _, (_, rs) in sorted(self._files.items())
                for wall, step, t, v in rs if t == tag]
        rows.sort(key=lambda r: (r[0], r[1]))
        return [(step, v) for _, step, v in rows]


def read_scalar(log_dir: str, tag: str) -> List[Tuple[int, float]]:
    """All (step, value) pairs for ``tag`` across every event file in
    ``log_dir``, ordered by (wall_time, step) — FileReader.readScalar
    parity. One-shot form; pollers should hold a :class:`ScalarCache`."""
    return ScalarCache(log_dir).read(tag)
