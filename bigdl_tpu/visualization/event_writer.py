"""Native TensorBoard event-file writer (no TF dependency).

Parity: reference ``visualization/tensorboard`` writers (there backed by the
tensorflow jar). Implements just enough protobuf wire encoding for Event /
Summary scalar + histogram records, framed in TFRecord format with masked
crc32c checksums.
"""
from __future__ import annotations

import os
import struct
import time

import numpy as np

# ---------------------------------------------------------------------------
# crc32c (software, table-driven)
# ---------------------------------------------------------------------------
_CRC_TABLE = []


def _make_table():
    poly = 0x82F63B78
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_make_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    # TFRecord/event-file masking: rotate right 15 THEN add kMaskDelta
    # (0xa282ead8). Omitting the delta produces files that are
    # self-consistent but rejected by real TensorFlow/TensorBoard
    # ("corrupted record at 0") — caught by cross-checking against
    # tf.data.TFRecordDataset in tests/test_native.py.
    crc = crc32c(data)
    rot = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# minimal protobuf encoding
# ---------------------------------------------------------------------------
def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _f_double(num: int, v: float) -> bytes:
    return _field(num, 1) + struct.pack("<d", v)


def _f_float(num: int, v: float) -> bytes:
    return _field(num, 5) + struct.pack("<f", v)


def _f_int64(num: int, v: int) -> bytes:
    return _field(num, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _f_bytes(num: int, v: bytes) -> bytes:
    return _field(num, 2) + _varint(len(v)) + v


def _f_string(num: int, v: str) -> bytes:
    return _f_bytes(num, v.encode("utf-8"))


def _f_packed_double(num: int, vals) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return _f_bytes(num, payload)


def encode_scalar_summary(tag: str, value: float) -> bytes:
    val = _f_string(1, tag) + _f_float(2, float(value))
    return _f_bytes(1, val)  # Summary.value


def encode_histogram_summary(tag: str, values: np.ndarray) -> bytes:
    v = np.asarray(values, np.float64).reshape(-1)
    if v.size == 0:
        v = np.zeros(1)
    counts, edges = np.histogram(v, bins=30)
    histo = (_f_double(1, float(v.min())) + _f_double(2, float(v.max())) +
             _f_double(3, float(v.size)) + _f_double(4, float(v.sum())) +
             _f_double(5, float(np.sum(v * v))) +
             _f_packed_double(6, edges[1:]) +
             _f_packed_double(7, counts))
    val = _f_string(1, tag) + _f_bytes(5, histo)  # Value.histo = 5
    return _f_bytes(1, val)


def encode_event(step: int, summary_value: bytes,
                 wall_time: float = None) -> bytes:
    wt = time.time() if wall_time is None else wall_time
    return (_f_double(1, wt) + _f_int64(2, step) +
            _f_bytes(5, summary_value))  # Event.summary = 5


def encode_file_version() -> bytes:
    return _f_double(1, time.time()) + _f_string(3, "brain.Event:2")


class EventWriter:
    """Append-only TFRecord event file, readable by TensorBoard."""

    def __init__(self, logdir: str, suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.bigdl_tpu{suffix}"
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._write_record(encode_file_version())

    def _write_record(self, data: bytes):
        length = struct.pack("<Q", len(data))
        self._f.write(length)
        self._f.write(struct.pack("<I", _masked_crc(length)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        self._write_record(encode_event(step, encode_scalar_summary(tag,
                                                                    value)))

    def add_histogram(self, tag: str, values, step: int):
        self._write_record(
            encode_event(step, encode_histogram_summary(tag, values)))

    def close(self):
        self._f.close()
