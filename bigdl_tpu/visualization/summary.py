"""Training summaries.

Parity: reference ``visualization/TrainSummary.scala`` /
``visualization/ValidationSummary.scala`` — scalar (and histogram) logging
to TensorBoard event files, plus readback (``read_scalar``) that parses the
event files on disk (``visualization/tensorboard/FileReader.scala``
parity), so history survives a restart and other runs' logs are readable.
"""
from __future__ import annotations

import os

from .event_reader import ScalarCache
from .event_writer import EventWriter


class Summary:
    def __init__(self, log_dir: str, app_name: str, sub_dir: str):
        self.log_dir = os.path.join(log_dir, app_name, sub_dir)
        self.writer = EventWriter(self.log_dir)
        self._reader = ScalarCache(self.log_dir)
        self._triggers = {}

    def add_scalar(self, tag: str, value: float, step: int):
        self.writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int):
        self.writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str):
        """Return [(step, value), ...] parsed from the event files on disk
        (parity: Summary.readScalar → tensorboard/FileReader.scala) — a
        restarted process recovers the full history, not just this
        instance's writes. Incremental: repeated polls rescan only the
        bytes appended since the last call."""
        return self._reader.read(tag)

    def set_summary_trigger(self, name: str, trigger):
        """Gate when the named tag is recorded (parity:
        TrainSummary.setSummaryTrigger); consulted by the optimizers via
        :meth:`should_record` — tags without a trigger record every step."""
        self._triggers[name] = trigger
        return self

    def should_record(self, name: str, state) -> bool:
        trig = self._triggers.get(name)
        return True if trig is None else bool(trig(state))

    def close(self):
        self.writer.close()


class TrainSummary(Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")


class ValidationSummary(Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
