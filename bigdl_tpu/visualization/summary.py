"""Training summaries.

Parity: reference ``visualization/TrainSummary.scala`` /
``visualization/ValidationSummary.scala`` — scalar (and histogram) logging to
TensorBoard event files, plus in-memory readback (``read_scalar``) used by
tests and notebooks.
"""
from __future__ import annotations

import os
from typing import Dict, List, Tuple

from .event_writer import EventWriter


class Summary:
    def __init__(self, log_dir: str, app_name: str, sub_dir: str):
        self.log_dir = os.path.join(log_dir, app_name, sub_dir)
        self.writer = EventWriter(self.log_dir)
        self._scalars: Dict[str, List[Tuple[int, float]]] = {}
        self._triggers = {}

    def add_scalar(self, tag: str, value: float, step: int):
        self._scalars.setdefault(tag, []).append((step, float(value)))
        self.writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int):
        self.writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str):
        """Return [(step, value), ...] (parity: Summary.readScalar)."""
        return list(self._scalars.get(tag, []))

    def set_summary_trigger(self, name: str, trigger):
        """Gate when the named tag is recorded (parity:
        TrainSummary.setSummaryTrigger); consulted by the optimizers via
        :meth:`should_record` — tags without a trigger record every step."""
        self._triggers[name] = trigger
        return self

    def should_record(self, name: str, state) -> bool:
        trig = self._triggers.get(name)
        return True if trig is None else bool(trig(state))

    def close(self):
        self.writer.close()


class TrainSummary(Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")


class ValidationSummary(Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
