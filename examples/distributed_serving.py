"""Multi-chip serving tour: TP decode, FSDP params, sharded KV cache,
and the production path — mesh-placed engines behind the serving queue.

Beyond-the-reference ways to put a mesh behind inference (the
reference's PredictionService is data-parallel over complete model
replicas only):

1. TENSOR-PARALLEL decode — `transformer_tp_specs` places the LM's
   matmul weights Megatron-style; `jax.jit(generate)` over that
   placement decodes with XLA-inserted per-layer psums,
   token-identical to single-device.
2. FSDP/ZeRO-3 placement — `fsdp_specs` stores every big leaf at 1/N
   per device; the SAME jitted generate serves from the sharded copy.
3. SEQUENCE-SHARDED KV cache — `make_seq_sharded_decoder` shards the
   cache itself along time (the 100k-token-conversation regime where
   the cache, not the weights, outgrows a chip).
4. THE ENGINE PATH (r10) — sections 1-2 call `jax.jit(generate)`
   directly, bypassing every serving guarantee. `DecodeScheduler(mesh=,
   placement=)` serves the SAME placements through the real queue:
   continuous batching, paged KV on the mesh (kv heads split over the
   model axis), per-request version pinning for hot swap — and a
   `Router` can put N such mesh-placed replicas behind priority-class
   queues (docs/SERVING.md "Router").

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     PYTHONPATH=. python examples/distributed_serving.py
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models import TransformerLM
from bigdl_tpu.parallel import (transformer_tp_specs, fsdp_specs,
                                make_seq_sharded_decoder)
from bigdl_tpu.serving import DecodeScheduler


def main():
    n = len(jax.devices())
    assert n >= 8, f"want an 8-device mesh (XLA_FLAGS), got {n}"
    model = TransformerLM(vocab_size=211, hidden_size=64, num_heads=8,
                          filter_size=128, num_layers=2, max_len=64)
    params, _ = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(1).randint(1, 211, (2, 8)),
                         jnp.int32)
    want = np.asarray(model.generate(params, prompt, max_new_tokens=12))
    gen = jax.jit(lambda p, x: model.generate(p, x, max_new_tokens=12))

    # 1. tensor-parallel decode
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("model",))
    tp = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        params, transformer_tp_specs(params))
    assert (np.asarray(gen(tp, prompt)) == want).all()
    print("1. TP decode == single-device (per-layer psums from placement)")

    # 2. FSDP-placed params serve through the same jitted generate
    dmesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    fp = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(dmesh, s)),
        params, fsdp_specs(params, dmesh, min_elems=1024))
    shard = fp["embed"].addressable_shards[0].data
    assert shard.size == fp["embed"].size // 8
    assert (np.asarray(gen(fp, prompt)) == want).all()
    print(f"2. FSDP decode == single-device (embed stored "
          f"{shard.shape} of {tuple(fp['embed'].shape)} per device)")

    # 3. sequence-sharded KV cache, decoded step by step
    smesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("seq",))
    dec = jax.jit(make_seq_sharded_decoder(smesh, "seq"),
                  donate_argnums=(3, 4))
    B, kvH, D, Tmax = 1, 2, 16, 32
    sh = NamedSharding(smesh, P(None, None, "seq", None))
    kc = jax.device_put(jnp.zeros((B, kvH, Tmax, D), jnp.float32), sh)
    vc = jax.device_put(jnp.zeros((B, kvH, Tmax, D), jnp.float32), sh)
    rng = np.random.RandomState(2)
    ks = np.zeros((B, kvH, Tmax, D), np.float32)
    vs = np.zeros_like(ks)
    for pos in range(12):
        q = jnp.asarray(rng.randn(B, 4, 1, D), jnp.float32)
        kt = jnp.asarray(rng.randn(B, kvH, 1, D), jnp.float32)
        vt = jnp.asarray(rng.randn(B, kvH, 1, D), jnp.float32)
        o, kc, vc = dec(q, kt, vt, kc, vc, jnp.int32(pos))
        ks[:, :, pos], vs[:, :, pos] = kt[:, :, 0], vt[:, :, 0]
        ke, ve = np.repeat(ks, 2, 1), np.repeat(vs, 2, 1)
        s = np.einsum("bhqd,bhtd->bhqt", np.asarray(q), ke) / math.sqrt(D)
        s[..., pos + 1:] = -1e30
        w = np.exp(s - s.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        ref = np.einsum("bhqt,bhtd->bhqd", w, ve)
        assert np.abs(np.asarray(o) - ref).max() < 1e-4
    assert kc.addressable_shards[0].data.shape[2] == Tmax // 8
    print("3. sequence-sharded cache: 12 steps across shard boundaries "
          "== dense oracle; each device stores Tmax/8 positions")

    # 4. the engine path: the SAME TP and FSDP placements served
    # through the DecodeScheduler queue (continuous batching, paged KV
    # on the mesh, hot-swap-ready) instead of a raw jax.jit(generate)
    sm = TransformerLM(vocab_size=211, hidden_size=64, num_heads=8,
                       filter_size=128, num_layers=2, max_len=128,
                       num_kv_heads=4)
    sm.ensure_initialized()
    prompts = [np.random.RandomState(s).randint(1, 211, (n,))
               .astype(np.int32) for s, n in ((3, 9), (4, 5))]

    def serve(**kw):
        sched = DecodeScheduler(sm, max_slots=4, block_size=8,
                                max_seq_len=96, prefill_chunk=8, **kw)
        with sched:  # start() precompiles every dispatchable shape
            futs = [sched.submit(p, 10) for p in prompts]
            return [np.asarray(f.result(timeout=120)) for f in futs]

    want_q = serve()  # single-device reference through the same queue
    tp_mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    got_tp = serve(mesh=tp_mesh, placement="tp", name="tp")
    assert all((a == b).all() for a, b in zip(want_q, got_tp))
    fs_mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    got_fs = serve(mesh=fs_mesh, placement="fsdp", name="fsdp")
    assert all((a == b).all() for a, b in zip(want_q, got_fs))
    print("4. engine path: TP(4) and FSDP(8) placements served through "
          "the DecodeScheduler queue, tokens == single-device — the "
          "model-parallel half of the ISSUE-10 serving tier (the "
          "replica-parallel half is serving.Router)")
    print("distributed serving tour OK")


if __name__ == "__main__":
    main()
