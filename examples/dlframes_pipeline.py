"""DataFrame ML-pipeline workflow (reference: example/MLPipeline +
pyspark dlframes — DLClassifier.fit on a DataFrame, transform appends a
prediction column).

Spark-free: the dlframes analog consumes pandas DataFrames (or plain dict
of arrays). Includes the image path: DLImageReader -> DLImageTransformer ->
DLModel.transform, as in the reference's imageframe examples.

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/dlframes_pipeline.py
"""
import numpy as np
import pandas as pd

from bigdl_tpu import nn
from bigdl_tpu.dlframes import DLClassifier


def main():
    rng = np.random.RandomState(0)
    x = rng.randn(300, 4).astype(np.float32)
    y = (x[:, 0] - x[:, 2] > 0).astype(np.float32) + 1  # classes 1/2
    df = pd.DataFrame({"features": list(x), "label": y})

    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    clf = DLClassifier(model, nn.ClassNLLCriterion(), [4]) \
        .set_batch_size(32).set_max_epoch(20).set_learning_rate(5e-2)
    fitted = clf.fit(df)

    out = fitted.transform(df)
    acc = float((out["prediction"] == out["label"]).mean())
    print(f"pipeline accuracy on train set: {acc:.3f}")
    assert acc > 0.9, acc

    # Raw tabular frame → RowTransformer (dataset/datamining/
    # RowTransformer.scala analog) → the same estimator: keyed column
    # schemas assemble the "features"/"label" matrices from loose columns.
    from bigdl_tpu.dataset import RowTransformer
    raw = pd.DataFrame({
        "income": x[:, 0], "debt": x[:, 1],
        "spend": x[:, 2], "age_norm": x[:, 3], "label": y,
    })
    rt = RowTransformer.numeric({
        "features": ["income", "debt", "spend", "age_norm"],
        "label": ["label"],
    })
    cols = rt.transform_frame(raw)
    clf2 = DLClassifier(
        nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2),
                      nn.LogSoftMax()),
        nn.ClassNLLCriterion(), [4]) \
        .set_batch_size(32).set_max_epoch(20).set_learning_rate(5e-2)
    fitted2 = clf2.fit({"features": cols["features"],
                        "label": cols["label"].reshape(-1)})
    out2 = fitted2.transform({"features": cols["features"]})
    acc2 = float((out2["prediction"] == y).mean())
    print(f"RowTransformer pipeline accuracy: {acc2:.3f}")
    assert acc2 > 0.9, acc2
    print("OK")


if __name__ == "__main__":
    main()
