"""ResNet-50 training from a folder of JPEGs — the reference's flagship
ImageNet path (models/resnet/TrainImageNet.scala), TPU edition.

The whole input pipeline runs on the C++ decode workers
(``native.JpegFolderPrefetcher``): libjpeg decode with fractional-DCT
downscale, Inception-style RandomResizedCrop + horizontal flip, bilinear
resize, normalization — emitted as accelerator-ready bf16 NHWC batches so
the host path is decode → ``device_put``. Compute is the bench recipe:
NHWC ResNet-50 with the layout-preserving fused bottleneck restructure
(``fused="xla"``), f32 master params, bf16 MXU compute, momentum SGD.

Usage:
  python examples/imagenet_folder_train.py --data-dir /path/to/imagenet
      [--batch 256 --steps 500]
  python examples/imagenet_folder_train.py            # synthetic 2-class
      folder written via the native JPEG encoder (zero-egress default)

With no --data-dir a tiny synthetic folder (two separable classes) is
generated and the script asserts the loss actually falls — the example is
its own smoke test (tests/test_examples.py runs it).
"""
import argparse
import os
import tempfile

import numpy as np


def make_synthetic_folder(root, n_per_class=24, size=96):
    """Two visually separable classes (dark vs bright blobs) written as
    real JPEG files via the native encoder, folder/<class>/<img> layout."""
    from bigdl_tpu.native import encode_jpeg
    rng = np.random.RandomState(0)
    for ci, (name, base) in enumerate((("dark", 60), ("bright", 190))):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            img = np.clip(base + rng.randn(size, size, 3) * 25, 0,
                          255).astype(np.uint8)
            with open(os.path.join(d, f"{i:03d}.jpg"), "wb") as f:
                f.write(encode_jpeg(img, quality=90))
    return root


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--size", type=int, default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from bigdl_tpu.dataset.imagenet import scan_folder
    from bigdl_tpu.models import ResNet
    from bigdl_tpu.native import JpegFolderPrefetcher
    from bigdl_tpu.nn import CrossEntropyCriterion
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.utils.amp import bf16_params

    synthetic = args.data_dir is None
    if synthetic:
        args.data_dir = make_synthetic_folder(
            tempfile.mkdtemp(prefix="bigdl_tpu_imagenet_"))
    paths, labels, classes = scan_folder(args.data_dir)
    n_class = max(len(classes), 2)
    batch = args.batch or (16 if synthetic else 256)
    steps = args.steps or (12 if synthetic else 500)
    size = args.size or (64 if synthetic else 224)
    print(f"{len(paths)} images / {len(classes)} classes from "
          f"{args.data_dir}")

    pf = JpegFolderPrefetcher(
        paths, labels, size, size, mean=(124.0, 117.0, 104.0),
        std=(59.0, 57.0, 57.0), batch_size=batch,
        n_workers=min(16, max(4, os.cpu_count() or 1)), queue_capacity=4,
        out="bf16_nhwc", augment=True)

    model = ResNet(class_num=n_class, depth=50, format="NHWC", fused="xla")
    params, mstate = model.init(jax.random.PRNGKey(0))
    crit = CrossEntropyCriterion()
    lr = 0.05
    optim = SGD(learningrate=lr, momentum=0.9)
    opt_state = optim.init_state(params)

    @jax.jit
    def train_step(params, opt_state, mstate, x, y):
        def loss_fn(p):
            out, ns = model.apply(bf16_params(p), mstate, x, training=True,
                                  rng=jax.random.PRNGKey(1))
            return crit._forward(out.astype(jnp.float32), y), ns
        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        p2, o2 = optim.update(grads, params, opt_state, jnp.float32(lr))
        return loss, p2, o2, ns

    losses = []
    k = 0
    # loop mode drops each epoch's partial batch: epochs must be computed
    # from USABLE batches, and zero usable batches is a config error
    batches_per_epoch = len(paths) // batch
    if batches_per_epoch == 0:
        raise SystemExit(f"{len(paths)} images < batch {batch}: every "
                         "epoch would be a dropped partial batch — lower "
                         "--batch or add data")
    epochs_needed = steps // batches_per_epoch + 2
    for mb in pf.data(train=True, loop_epochs=min(epochs_needed, 1000)):
        x = jnp.asarray(np.asarray(mb.input))          # bf16 NHWC
        y = jnp.asarray(np.asarray(mb.target), jnp.int32)
        loss, params, opt_state, mstate = train_step(params, opt_state,
                                                     mstate, x, y)
        losses.append(float(loss))
        if k % 5 == 0:
            print(f"step {k:4d}  loss {losses[-1]:.4f}")
        k += 1
        if k >= steps:
            break

    assert all(np.isfinite(losses)), "non-finite loss"
    if synthetic:
        head, tail = np.mean(losses[:3]), np.mean(losses[-3:])
        assert tail < head, (head, tail)
        print(f"OK: loss fell {head:.3f} -> {tail:.3f} over {k} augmented "
              "bf16-NHWC steps")


if __name__ == "__main__":
    main()
