"""Inception-v1: build from a Caffe deploy prototxt (+ weights when given)
and run int8-quantized inference — the reference's Caffe-load + DL-Boost
flow (example/loadmodel + quantization), on TPU int8.

Usage:
  python examples/inception_caffe.py [--prototxt P --caffemodel M] [--int8]
Without files, builds the in-tree Inception_v1 graph instead.
"""
import argparse
import time

import numpy as np

from bigdl_tpu.models import Inception_v1
from bigdl_tpu.loaders import load_caffe
from bigdl_tpu.quantization import quantize


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--prototxt", default=None)
    ap.add_argument("--caffemodel", default=None)
    ap.add_argument("--int8", action="store_true")
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()

    if args.prototxt:
        model = load_caffe(args.prototxt, args.caffemodel)
        print(f"loaded caffe net: {len(model.modules)} layers")
    else:
        model = Inception_v1(1000)
        print("built in-tree Inception_v1")
    model.evaluate()
    model.ensure_initialized()

    if args.int8:
        model = quantize(model)
        print("quantized to int8")

    x = np.random.randn(args.batch, 3, 224, 224).astype(np.float32)
    out = model.forward(x)  # compile
    t0 = time.time()
    for _ in range(5):
        out = model.forward(x)
    float(np.asarray(out).sum())
    dt = (time.time() - t0) / 5
    print(f"output {out.shape}; {args.batch / dt:.1f} img/s inference")


if __name__ == "__main__":
    main()
