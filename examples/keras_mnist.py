"""Keras-API LeNet on (synthetic) MNIST (reference: pyspark/bigdl/examples/
keras + models/lenet — the keras-1.2 Sequential workflow).

Demonstrates the full keras front-end: Sequential -> compile(optimizer,
loss, metrics) -> fit -> evaluate -> predict_classes, plus round-tripping
the architecture through ``model_from_json`` (keras/converter.py).

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/keras_mnist.py
"""
import numpy as np

from bigdl_tpu.keras import Sequential
from bigdl_tpu.keras.layers import (Convolution2D, MaxPooling2D, Flatten,
                                    Dense, Dropout, Activation)
from bigdl_tpu.dataset import mnist


def build():
    model = Sequential()
    model.add(Convolution2D(6, 5, 5, activation="tanh",
                            input_shape=(1, 28, 28)))
    model.add(MaxPooling2D())
    model.add(Convolution2D(12, 5, 5, activation="tanh"))
    model.add(MaxPooling2D())
    model.add(Flatten())
    model.add(Dense(100, activation="tanh"))
    model.add(Dropout(0.1))
    model.add(Dense(10))
    model.add(Activation("softmax"))
    return model


def main():
    imgs, labels = mnist.load(n_synthetic=512)
    x = (imgs.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0)
    y = np.eye(10, dtype=np.float32)[labels.astype(int) % 10]  # one-hot

    model = build()
    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=64, nb_epoch=3)
    loss, acc = model.evaluate(x, y, batch_size=64)
    print(f"train-set loss {loss:.4f}  acc {acc:.3f}")
    preds = model.predict_classes(x[:8])
    print("first predictions:", preds.tolist())
    assert np.isfinite(loss)
    print("OK")


if __name__ == "__main__":
    main()
