"""LeNet-5 on MNIST (parity: reference models/lenet/Train.scala and
pyspark/bigdl/models/lenet/lenet5.py).

Usage: python examples/lenet_mnist.py [--data-dir DIR] [--epochs N]
Falls back to synthetic MNIST when no data dir is given (zero-egress envs).
"""
import argparse

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.optim import (Optimizer, SGD, Top1Accuracy, Top5Accuracy,
                             Loss, max_epoch, every_epoch)
from bigdl_tpu.visualization import TrainSummary, ValidationSummary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()

    train_x, train_y = mnist.load(args.data_dir, train=True,
                                  n_synthetic=2048)
    test_x, test_y = mnist.load(args.data_dir, train=False, n_synthetic=512)
    train_ds = DataSet.array(mnist.to_samples(train_x, train_y, train=True))
    test_ds = DataSet.array(mnist.to_samples(test_x, test_y, train=False))

    model = LeNet5(class_num=10)
    opt = Optimizer(model=model, training_set=train_ds,
                    criterion=nn.ClassNLLCriterion(),
                    optim_method=SGD(learningrate=args.lr,
                                     learningrate_decay=0.0002),
                    end_trigger=max_epoch(args.epochs),
                    batch_size=args.batch_size)
    opt.set_validation(every_epoch(), test_ds,
                       [Top1Accuracy(), Top5Accuracy(), Loss()],
                       args.batch_size)
    if args.log_dir:
        opt.set_train_summary(TrainSummary(args.log_dir, "lenet"))
        opt.set_val_summary(ValidationSummary(args.log_dir, "lenet"))
    trained = opt.optimize()

    results = trained.evaluate_dataset(test_ds, [Top1Accuracy()],
                                       args.batch_size)
    print(f"final: {results[0]}")


if __name__ == "__main__":
    main()
