"""Train a tiny character LM and generate text with the KV cache.

Beyond the reference (its ``nn/Transformer.scala`` is training-only):
``Transformer.generate`` runs a prefill pass then one ``lax.scan``-fused
decode step per token over per-block K/V caches — the standard TPU
autoregressive-inference shape. This example memorises a short corpus and
checks greedy generation reproduces it.

Run: JAX_PLATFORMS=cpu PYTHONPATH=. python examples/lm_generate.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu.models import TransformerLM, lm_loss_chunked
from bigdl_tpu.optim import Adam

TEXT = "the quick brown fox jumps over the lazy dog. " * 4
chars = sorted(set(TEXT))
stoi = {c: i + 1 for i, c in enumerate(chars)}  # 0 = pad
itos = {i: c for c, i in stoi.items()}
V = len(chars) + 1


def main():
    seq = np.array([stoi[c] for c in TEXT], np.int32)
    T = 64
    # stride = the 45-char sentence period: every window is the same
    # periodic text at the same positions, so the continuation the
    # assertion checks is unambiguously memorisable
    starts = np.arange(0, len(seq) - T - 1, 45)
    x = np.stack([seq[s:s + T] for s in starts])
    y = np.stack([seq[s + 1:s + T + 1] for s in starts])

    model = TransformerLM(vocab_size=V, hidden_size=64, num_heads=4,
                          filter_size=128, num_layers=2, max_len=128)
    params, _ = model.init(jax.random.PRNGKey(0))
    optim = Adam(learningrate=3e-3)
    opt_state = optim.init_state(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            h = model.hidden_states(p, x, training=True,
                                    rng=jax.random.PRNGKey(1))
            return lm_loss_chunked(h, p["embed"], y, chunk=32)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = optim.update(grads, params, opt_state,
                                         jnp.float32(3e-3))
        return loss, params, opt_state

    xb, yb = jnp.asarray(x), jnp.asarray(y)
    first = None
    for i in range(400):
        loss, params, opt_state = step(params, opt_state, xb, yb)
        if first is None:
            first = float(loss)
    final = float(loss)
    print(f"loss {first:.3f} -> {final:.3f}")
    assert final < 0.35, final  # memorised

    # prompt with a full sentence of context, and keep prompt+generation
    # inside the 64 trained positions (absolute PE rows beyond the
    # training window length are untrained)
    prompt_txt = TEXT[:45]
    prompt = jnp.asarray([[stoi[c] for c in prompt_txt]], jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=16)
    text = "".join(itos.get(int(t), "?") for t in np.asarray(out)[0])
    print("generated:", repr(text[45:]))
    assert text.startswith(prompt_txt)
    # greedy continuation reproduces the memorised corpus
    assert text[45:61] == TEXT[45:61], (text[45:61], TEXT[45:61])
    print("lm_generate OK")


if __name__ == "__main__":
    main()
