"""Speculative decoding: a small draft accelerates a larger target LM.

Beyond the reference (its ``nn/Transformer.scala`` is training-only):
both models memorise the same corpus, then ``nn.speculative_generate``
lets the 1-layer draft propose k tokens per round while the 4-layer
target verifies them in ONE chunked cached forward
(``Transformer.decode_chunk``). Greedy speculative decoding is exactly
output-preserving — this example checks the speculative continuation is
token-identical to dense ``generate`` AND that the trained draft's
proposals are overwhelmingly accepted, so each target weight-stream
emits ~k+1 tokens instead of 1 (decode is weight-bandwidth bound:
docs/MFU_ROOFLINE.md).

Run: JAX_PLATFORMS=cpu PYTHONPATH=. python examples/lm_speculative.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu.models import TransformerLM, lm_loss_chunked
from bigdl_tpu.nn import speculative_generate
from bigdl_tpu.optim import Adam

TEXT = "the quick brown fox jumps over the lazy dog. " * 4
chars = sorted(set(TEXT))
stoi = {c: i + 1 for i, c in enumerate(chars)}  # 0 = pad
V = len(chars) + 1


def train(model, steps, lr=3e-3, seed=0):
    seq = np.array([stoi[c] for c in TEXT], np.int32)
    T = 64
    starts = np.arange(0, len(seq) - T - 1, 45)
    x = jnp.asarray(np.stack([seq[s:s + T] for s in starts]))
    y = jnp.asarray(np.stack([seq[s + 1:s + T + 1] for s in starts]))
    params, _ = model.init(jax.random.PRNGKey(seed))
    optim = Adam(learningrate=lr)
    opt_state = optim.init_state(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            h = model.hidden_states(p, x, training=True,
                                    rng=jax.random.PRNGKey(1))
            return lm_loss_chunked(h, p["embed"], y, chunk=32)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = optim.update(grads, params, opt_state,
                                         jnp.float32(lr))
        return loss, params, opt_state

    for _ in range(steps):
        loss, params, opt_state = step(params, opt_state)
    return params, float(loss)


def main():
    target = TransformerLM(vocab_size=V, hidden_size=64, num_heads=4,
                           filter_size=128, num_layers=4, max_len=128)
    draft = TransformerLM(vocab_size=V, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=1, max_len=128)
    tparams, tloss = train(target, 400)
    dparams, dloss = train(draft, 400, seed=7)
    print(f"target loss {tloss:.3f}  draft loss {dloss:.3f}")

    prompt = jnp.asarray([[stoi[c] for c in "the quick"]], jnp.int32)
    dense = target.generate(tparams, prompt, max_new_tokens=40)
    spec, stats = speculative_generate(target, tparams, draft, dparams,
                                       prompt, max_new_tokens=40, k=4,
                                       return_stats=True)
    assert (np.asarray(spec) == np.asarray(dense)).all(), \
        "speculative output must equal dense greedy exactly"
    rounds, drafted, accepted = (int(stats.rounds), int(stats.drafted),
                                 int(stats.accepted))
    rate = accepted / max(drafted, 1)
    per_round = 40 / max(rounds, 1)
    print(f"rounds {rounds} accepted {accepted}/{drafted} "
          f"({rate:.0%}), {per_round:.2f} tokens per target stream "
          f"(dense = 1.00)")
    # both models memorised the same periodic corpus: proposals should
    # overwhelmingly agree, so each round emits well over 1 token
    assert rate > 0.6, rate
    assert per_round > 2.0, per_round
    text = "".join({i: c for c, i in stoi.items()}.get(int(t), "?")
                   for t in np.asarray(spec)[0])
    print("speculative:", text)


if __name__ == "__main__":
    main()
