"""Foreign-format model interop (reference: example/loadmodel — load a
Caffe / Torch-t7 / TF model and predict).

Round-trips a LeNet through all three formats PLUS the native format, and
checks every reloaded model predicts identically to the original:

  native save/load        (Module.save / Module.load)
  Caffe  save -> load     (loaders/caffe_persister.py -> loaders/caffe.py)
  t7     save -> load     (loaders/torchfile.py both directions)
  TF     save -> load     (loaders/tf_saver.py -> loaders/tensorflow.py)

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/loadmodel_interop.py
"""
import os
import tempfile

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.loaders import (load_caffe, load_torch, load_tf_graph,
                               save_caffe, save_torch, save_tf_graph)


def main():
    model = LeNet5(10)
    model.ensure_initialized()
    model.evaluate()
    x = np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32)
    ref = np.asarray(model.forward(x))
    tmp = tempfile.mkdtemp()

    # native
    npath = os.path.join(tmp, "lenet.bigdl")
    model.save(npath)
    out = np.asarray(nn.Module.load(npath).evaluate().forward(x))
    assert np.allclose(out, ref, atol=1e-5), "native round-trip mismatch"
    print("native  save/load OK")

    # caffe
    proto, cmodel = os.path.join(tmp, "lenet.prototxt"), \
        os.path.join(tmp, "lenet.caffemodel")
    save_caffe(model, proto, cmodel, input_shape=(1, 28, 28))
    out = np.asarray(load_caffe(proto, cmodel).evaluate().forward(x))
    assert np.allclose(out, ref, atol=1e-4), "caffe round-trip mismatch"
    print("caffe   save/load OK")

    # torch t7
    tpath = os.path.join(tmp, "lenet.t7")
    save_torch(model, tpath)
    out = np.asarray(load_torch(tpath).evaluate().forward(x))
    assert np.allclose(out, ref, atol=1e-4), "t7 round-trip mismatch"
    print("t7      save/load OK")

    # tensorflow GraphDef
    gpath = os.path.join(tmp, "lenet.pb")
    save_tf_graph(model, (1, 28, 28), gpath)
    out = np.asarray(load_tf_graph(gpath).evaluate().forward(x))
    assert np.allclose(out, ref, atol=1e-4), "tf round-trip mismatch"
    print("tf      save/load OK")
    print("OK")


if __name__ == "__main__":
    main()
