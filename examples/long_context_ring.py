"""Long-context causal LM training with sequence parallelism.

The full recipe: token/position embedding, N transformer blocks whose
self-attention is ring-flash over the ``seq`` mesh axis, loss, and the
jitted train step — all inside ONE ``shard_map``, with the sequence dim
sharded end to end. Each device touches T/n tokens; attention memory is
O(T/n) per device in forward AND backward (parallel/ring_flash.py), so
the trainable context grows linearly with the mesh.

Positions are GLOBAL: each shard offsets its position encoding by
``axis_index * T_local`` — the one detail that differs from single-device
code.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     PYTHONPATH=. python examples/long_context_ring.py
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.nn.attention import position_encoding
from bigdl_tpu.parallel.ring_flash import ring_flash_attention

VOCAB, D, HEADS, LAYERS = 64, 32, 8, 2   # 8 heads: a2a needs heads % mesh == 0
T, B = 1024, 2          # 128 tokens per device on the 8-device mesh
N_DEV = 8


def init_params(rng):
    ks = jax.random.split(rng, 2 + 6 * LAYERS)
    g = lambda k, s: jax.random.normal(k, s) * (1.0 / np.sqrt(s[0]))
    p = {"emb": jax.random.normal(ks[0], (VOCAB, D)) * 0.02,
         "out": g(ks[1], (D, VOCAB)), "blocks": []}
    for i in range(LAYERS):
        k = ks[2 + 6 * i: 8 + 6 * i]
        p["blocks"].append({
            "wq": g(k[0], (D, D)), "wk": g(k[1], (D, D)),
            "wv": g(k[2], (D, D)), "wo": g(k[3], (D, D)),
            "w1": g(k[4], (D, 4 * D)),
            "w2": jax.random.normal(k[5], (4 * D, D)) * 0.02})
    return p


def forward(params, ids):
    """ids: (B, T_local) inside shard_map over 'seq'."""
    tb = ids.shape[1]
    offset = lax.axis_index("seq") * tb          # global positions
    pos = lax.dynamic_slice_in_dim(
        position_encoding(T, D), offset * 1, tb, axis=0)
    def rms(z):
        return z * jax.lax.rsqrt(jnp.mean(z * z, -1, keepdims=True) + 1e-6)

    h = params["emb"][ids] + pos[None]
    for blk in params["blocks"]:
        n = rms(h)
        q = (n @ blk["wq"]).reshape(B, tb, HEADS, -1).transpose(0, 2, 1, 3)
        k = (n @ blk["wk"]).reshape(B, tb, HEADS, -1).transpose(0, 2, 1, 3)
        v = (n @ blk["wv"]).reshape(B, tb, HEADS, -1).transpose(0, 2, 1, 3)
        a = ring_flash_attention(q, k, v, axis="seq", causal=True)
        a = a.transpose(0, 2, 1, 3).reshape(B, tb, D)
        h = h + a @ blk["wo"]
        h = h + jax.nn.relu(rms(h) @ blk["w1"]) @ blk["w2"]
    return rms(h) @ params["out"]


def loss_fn(params, ids, targets):
    logits = forward(params, ids)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1).mean()
    return lax.pmean(nll, "seq")


def main():
    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("seq",))
    rng = np.random.RandomState(0)
    # synthetic corpus with local structure the LM can learn
    ids = np.cumsum(rng.randint(0, 3, (B, T + 1)), axis=1) % VOCAB
    x = jnp.asarray(ids[:, :-1], jnp.int32)
    y = jnp.asarray(ids[:, 1:], jnp.int32)

    params = init_params(jax.random.PRNGKey(0))
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    sspec = P(None, "seq")

    step = jax.jit(shard_map(
        jax.value_and_grad(loss_fn), mesh=mesh,
        in_specs=(pspec, sspec, sspec),
        out_specs=(P(), pspec)))

    first = last = None
    for it in range(60):
        loss, grads = step(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.3 * g,
                                        params, grads)
        if first is None:
            first = float(loss)
        last = float(loss)
        if it % 20 == 0:
            print(f"iter {it:2d}  nll {float(loss):.4f}")
    print(f"nll {first:.4f} -> {last:.4f} over T={T} on {N_DEV} shards")
    # infra demo, not a convergence benchmark: plain SGD on a tiny LM —
    # the point is that gradients flow correctly through the sharded ring
    assert last < first * 0.9, "no learning"

    # the all-to-all scheme computes the SAME attention (2 collectives
    # instead of n-1 ring hops; heads must divide the axis) — swap it in
    # and check the sharded forward agrees with the ring form
    from bigdl_tpu.parallel.seq_all_to_all import a2a_attention

    def forward_a2a(params, ids):
        import bigdl_tpu.parallel.ring_flash as _rf
        orig = globals()["ring_flash_attention"]
        globals()["ring_flash_attention"] = (
            lambda q, k, v, axis, causal: a2a_attention(
                q, k, v, axis=axis, causal=causal, use_flash=False))
        try:
            return forward(params, ids)
        finally:
            globals()["ring_flash_attention"] = orig

    f_ring = jax.jit(shard_map(forward, mesh=mesh, in_specs=(pspec, sspec),
                               out_specs=P(None, "seq")))
    f_a2a = jax.jit(shard_map(forward_a2a, mesh=mesh,
                              in_specs=(pspec, sspec),
                              out_specs=P(None, "seq")))
    o_ring = np.asarray(f_ring(params, x))
    o_a2a = np.asarray(f_a2a(params, x))
    np.testing.assert_allclose(o_a2a, o_ring, atol=2e-4)
    print(f"a2a == ring sharded forward (max |d| "
          f"{np.abs(o_a2a - o_ring).max():.2e})")
    print("OK")


if __name__ == "__main__":
    main()
