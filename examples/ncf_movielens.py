"""Neural Collaborative Filtering on MovieLens with HitRatio/NDCG evaluation
(parity: the reference's HitRatio/NDCG ValidationMethods,
optim/ValidationMethod.scala:279,346, and pyspark/bigdl/dataset/movielens.py).

Usage: python examples/ncf_movielens.py [--data-dir DIR] [--model ncf|wd]
Falls back to synthetic ratings when no data dir is given (zero-egress envs).
"""
import argparse

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample, movielens
from bigdl_tpu.models import NeuralCF, WideAndDeep
from bigdl_tpu.optim import LocalOptimizer, Adam, Trigger
from bigdl_tpu.optim.validation import HitRatio, NDCG


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--model", default="ncf", choices=["ncf", "wd"])
    ap.add_argument("--iterations", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    data = movielens.read_data_sets(args.data_dir, n_synthetic=8000)
    n_users, n_items = int(data[:, 0].max()), int(data[:, 1].max())
    print(f"ratings={len(data)} users={n_users} items={n_items}")
    train, labels, ev_users, ev_items = \
        movielens.train_test_split_leave_one_out(data)

    if args.model == "ncf":
        model = NeuralCF(n_users + 1, n_items + 1, mf_dim=8, mlp_dim=16,
                         hidden_layers=(32, 16, 8))
    else:
        model = WideAndDeep(n_users + 1, n_items + 1, embed_dim=16)

    samples = [Sample(train[i].astype(np.float32),
                      labels[i].astype(np.float32))
               for i in range(len(labels))]
    opt = LocalOptimizer(model, DataSet.array(samples), nn.BCECriterion(),
                         Adam(learningrate=args.lr),
                         Trigger.max_iteration(args.iterations),
                         batch_size=args.batch_size)
    opt.optimize()

    hr, ndcg = HitRatio(k=10, neg_num=ev_items.shape[1] - 1), \
        NDCG(k=10, neg_num=ev_items.shape[1] - 1)
    hr_res = ndcg_res = None
    model.evaluate()
    for u, items in zip(ev_users, ev_items):
        pairs = np.stack([np.full(len(items), u), items], 1).astype(np.float32)
        scores = np.asarray(model.forward(pairs))
        target = np.zeros(len(items), np.float32)
        target[0] = 1
        a, b = hr(scores, target), ndcg(scores, target)
        hr_res = a if hr_res is None else hr_res + a
        ndcg_res = b if ndcg_res is None else ndcg_res + b
    print(f"HitRatio@10 = {hr_res.result()[0]:.4f}  "
          f"NDCG@10 = {ndcg_res.result()[0]:.4f}")


if __name__ == "__main__":
    main()
