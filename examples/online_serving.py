"""Online serving tour: micro-batching, backpressure, deadlines, hot swap.

The engine in bigdl_tpu/serving/ coalesces concurrent single-sample
requests into padded shape-bucket batches over the ONE compiled forward
Predictor uses, under a latency window — the serving regime the
training-side pipelining PRs never touched. This example drives every
robustness feature end-to-end on CPU with LeNet.

Run: JAX_PLATFORMS=cpu PYTHONPATH=. python examples/online_serving.py
"""
import threading

import numpy as np
import jax

from bigdl_tpu import observability as obs
from bigdl_tpu.models import LeNet5
from bigdl_tpu.serving import DeadlineExceeded, QueueFull, ServingEngine


def main():
    obs.enable()
    model = LeNet5()
    model.ensure_initialized()
    engine = ServingEngine(model, input_shape=(784,), max_batch=8,
                           max_wait_ms=3.0, max_queue=64,
                           default_deadline_ms=1000.0)
    rng = np.random.RandomState(0)
    with engine:  # start(): warmup-compiles buckets 1,2,4,8
        # 1. concurrent clients coalesce into micro-batches
        outs = [None] * 16

        def client(i):
            x = rng.randn(784).astype(np.float32)
            for _ in range(4):
                outs[i] = engine.submit(x).result(timeout=10)
        ts = [threading.Thread(target=client, args=(i,)) for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        st = engine.stats()
        print(f"1. {st['completed']} requests served in {st['batches']} "
              f"micro-batches (occupancy "
              f"{obs.registry().get('serve/batch_occupancy').mean:.2f})")

        # 2. hot swap mid-traffic: zeroed params answer with exact zeros,
        # each future stamped with the version that served it
        f_old = engine.submit(np.zeros(784, np.float32))
        v1 = engine.swap(jax.tree_util.tree_map(lambda a: a * 0,
                                                model.params), model.state)
        f_new = engine.submit(np.zeros(784, np.float32))
        f_old.result(10), f_new.result(10)
        print(f"2. hot swap to {v1}: {f_old.version} answered the in-flight "
              f"request, {f_new.version} the next — never mixed")
        engine.registry.activate("v0")  # instant rollback

        # 3. typed failure modes: deadline + admission control
        try:
            engine.submit(np.zeros(784, np.float32),
                          deadline_ms=0.0).result(10)
        except DeadlineExceeded:
            print("3. expired request failed typed (DeadlineExceeded), "
                  "not served stale")
        try:
            for _ in range(1000):
                engine.submit(np.zeros(784, np.float32))
        except QueueFull:
            print(f"   queue bounded at {engine.max_queue}: QueueFull "
                  "backpressure instead of unbounded buffering")
        engine.drain(timeout=30)
    lat = obs.registry().get("serve/latency_ms")
    print(f"serving tour OK (p50 {lat.quantile(0.5):.1f}ms, "
          f"p99 {lat.quantile(0.99):.1f}ms)")


if __name__ == "__main__":
    main()
