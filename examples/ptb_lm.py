"""PTB LSTM language model (parity: reference models/rnn/Train.scala +
example/languagemodel)."""
import argparse

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.models import PTBModel
from bigdl_tpu.dataset import DataSet, text
from bigdl_tpu.optim import Optimizer, Adam, max_epoch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--hidden", type=int, default=128)
    args = ap.parse_args()

    sents = text.ptb_synthetic(n_sentences=512, vocab=args.vocab,
                               max_len=args.seq_len)
    d = text.Dictionary(sents)
    pipeline = text.TextToLabeledSentence(d) | \
        text.LabeledSentenceToSample(fixed_length=args.seq_len)
    samples = list(pipeline(sents))
    ds = DataSet.array(samples)

    model = PTBModel(input_size=d.vocab_size() + 1, hidden_size=args.hidden,
                     output_size=d.vocab_size() + 1, num_layers=2)
    crit = nn.TimeDistributedMaskCriterion(nn.ClassNLLCriterion(),
                                           padding_value=0)
    opt = Optimizer(model=model, training_set=ds, criterion=crit,
                    optim_method=Adam(learningrate=2e-3),
                    end_trigger=max_epoch(args.epochs), batch_size=32)
    opt.optimize()
    ppl = float(np.exp(min(opt.optim_method.state["loss"], 20.0)))
    print(f"final train loss {opt.optim_method.state['loss']:.3f} "
          f"(ppl ~{ppl:.1f})")


if __name__ == "__main__":
    main()
