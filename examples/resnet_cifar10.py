"""ResNet-20 on CIFAR-10 (parity: reference models/resnet/TrainCIFAR10.scala).

Demonstrates the reference's recipe: momentum SGD + weight decay + the
epoch-decay schedule, with the vision augmentation pipeline.
"""
import argparse

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.models import ResNetCifar
from bigdl_tpu.dataset import DataSet, Sample, cifar
from bigdl_tpu.optim import (Optimizer, SGD, EpochStep, Top1Accuracy,
                             max_epoch, every_epoch)
from bigdl_tpu.transform import vision


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--depth", type=int, default=20)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    args = ap.parse_args()

    imgs, labels = cifar.load(args.data_dir, train=True, n_synthetic=1024)
    # augmentation: pad-crop + flip on HWC, then normalize + CHW
    pipeline = vision.RandomCrop(32, 32) | vision.RandomFlip(0.5) | \
        vision.ChannelNormalize(*cifar.TRAIN_MEAN, *cifar.TRAIN_STD) | \
        vision.MatToTensor()
    hwc = [np.pad(i.transpose(1, 2, 0).astype(np.float32),
                  ((4, 4), (4, 4), (0, 0))) for i in imgs]
    feats = list(pipeline(hwc))
    samples = [Sample(feats[i], np.int64(labels[i]))
               for i in range(len(labels))]
    train_ds = DataSet.array(samples)

    model = ResNetCifar(10, depth=args.depth)
    opt = Optimizer(model=model, training_set=train_ds,
                    criterion=nn.CrossEntropyCriterion(),
                    optim_method=SGD(learningrate=0.1, momentum=0.9,
                                     weightdecay=1e-4, nesterov=True,
                                     learningrate_schedule=EpochStep(80, 0.1)),
                    end_trigger=max_epoch(args.epochs),
                    batch_size=args.batch_size)
    opt.set_validation(every_epoch(), train_ds, [Top1Accuracy()],
                       args.batch_size)
    opt.optimize()
    print("metrics:", opt.metrics.summary())


if __name__ == "__main__":
    main()
