"""SSD-style detection through the ImageFrame pipeline.

The reference's detection examples pipe images through
``ImageFrame.read → transform(...) → MTImageFeatureToBatch → model``
(transform/vision/image/ImageFrame.scala + MTImageFeatureToBatch.scala);
this example runs the same call stack end-to-end: a folder of real JPEGs
(written with the native libjpeg encoder), vision transforms, the frame
batcher with bbox carriage, a tiny conv backbone with PriorBox heads, and
``DetectionOutputSSD`` post-processing (decode + per-class NMS).

Self-asserting (exits nonzero on failure) like every example here.
Run: JAX_PLATFORMS=cpu PYTHONPATH=. python examples/ssd_image_frame.py
"""
import os
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.transform import ImageFrame, MTImageFeatureToBatch, vision
from bigdl_tpu.utils.table import Table

SIZE = 64


def make_jpeg_folder(root, n=6):
    from bigdl_tpu.native import encode_jpeg, jpeg_available
    if not jpeg_available():
        raise SystemExit(0)  # no libjpeg in this environment — skip cleanly
    rng = np.random.RandomState(0)
    os.makedirs(root, exist_ok=True)
    for i in range(n):
        img = (rng.rand(72, 80, 3) * 255).astype(np.uint8)
        img[20:40, 30:60] = (255, 0, 0)  # a red "object"
        with open(os.path.join(root, f"{i}.jpg"), "wb") as f:
            f.write(encode_jpeg(img))


def main():
    # fresh per-run dir: a fixed shared path could hold stale files from
    # edited runs and break the exact-count assert below
    root = tempfile.mkdtemp(prefix="ssd_frame_demo_")
    make_jpeg_folder(root)

    # 1) frame pipeline: read -> transform -> batches
    frame = ImageFrame.read(root)
    t = vision.Resize(SIZE, SIZE) | \
        vision.ChannelNormalize(127.0, 127.0, 127.0, 128.0, 128.0, 128.0)
    frame = frame.transform(t)
    assert len(frame) == 6
    batches = list(MTImageFeatureToBatch(SIZE, SIZE, batch_size=3,
                                         with_bbox=True)(frame))
    assert [b.input.shape for b in batches] == [(3, 3, SIZE, SIZE)] * 2

    # 2) a tiny SSD-ish head: conv backbone -> loc + conf maps + priors
    n_classes, feat = 3, SIZE // 8
    backbone = nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 2, 2, 1, 1), nn.ReLU(),
        nn.SpatialConvolution(16, 16, 3, 3, 2, 2, 1, 1), nn.ReLU(),
        nn.SpatialConvolution(16, 16, 3, 3, 2, 2, 1, 1), nn.ReLU())
    prior = nn.PriorBox(min_sizes=[16.0], max_sizes=[32.0],
                        aspect_ratios=[2.0], is_flip=True, is_clip=True,
                        img_size=SIZE, step=8.0,
                        variances=(0.1, 0.1, 0.2, 0.2))
    n_anchor = prior.num_priors
    loc_head = nn.SpatialConvolution(16, n_anchor * 4, 3, 3, 1, 1, 1, 1)
    conf_head = nn.SpatialConvolution(16, n_anchor * n_classes, 3, 3, 1, 1,
                                      1, 1)
    out_head = nn.DetectionOutputSSD(n_classes=n_classes, keep_topk=10,
                                     conf_thresh=0.01).evaluate()

    x = jnp.asarray(batches[0].input)
    fmap = backbone.forward(x)
    assert fmap.shape == (3, 16, feat, feat)
    priors = prior.forward(fmap)                       # (1, 2, nPriors*4)
    loc = loc_head.forward(fmap).transpose(0, 2, 3, 1).reshape(3, -1)
    conf = conf_head.forward(fmap).transpose(0, 2, 3, 1).reshape(3, -1)
    n_priors = priors.shape[2] // 4
    assert loc.shape[1] == n_priors * 4

    # 3) SSD post-processing: decode + NMS -> [label, score, box] rows
    dets = np.asarray(out_head.forward(Table(loc, conf, priors)))
    assert dets.shape == (3, 1 + 10 * 6)
    counts = dets[:, 0].astype(int)
    assert (counts >= 0).all() and (counts <= 10).all()
    for b in range(3):
        rows = dets[b, 1:1 + counts[b] * 6].reshape(-1, 6)
        if len(rows):
            labels, scores = rows[:, 0], rows[:, 1]
            assert ((labels >= 1) & (labels < n_classes)).all()
            assert ((scores > 0) & (scores <= 1.0001)).all()
    print(f"ssd_image_frame OK: {counts.sum()} detections over "
          f"{len(counts)} images (untrained net — counts are arbitrary, "
          f"the pipeline shape/range contracts are what is asserted)")


if __name__ == "__main__":
    main()
