"""TextClassifier on 20-Newsgroups (parity: reference
example/textclassification/TextClassifier.scala and
pyspark/bigdl/models/textclassifier/textclassifier.py).

Usage: python examples/textclassifier_news20.py [--data-dir DIR]
       [--encoder cnn|lstm|gru]
Falls back to a synthetic topic corpus when no data dir is given.
"""
import argparse

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample, news20
from bigdl_tpu.models import TextClassifier
from bigdl_tpu.models.textclassifier import tokenize_to_glove_sequences
from bigdl_tpu.optim import (LocalOptimizer, Adam, Trigger, Top1Accuracy,
                             every_epoch)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--encoder", default="cnn",
                    choices=["cnn", "lstm", "gru"])
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--embedding-dim", type=int, default=50)
    args = ap.parse_args()

    texts = news20.get_news20(args.data_dir, n_per_class=30)
    feats, labels = tokenize_to_glove_sequences(
        texts, sequence_length=args.seq_len,
        embedding_dim=args.embedding_dim)
    rng = np.random.RandomState(0)
    idx = rng.permutation(len(labels))
    split = int(0.8 * len(idx))
    tr, va = idx[:split], idx[split:]

    model = TextClassifier(news20.CLASS_NUM,
                           embedding_dim=args.embedding_dim,
                           sequence_length=args.seq_len,
                           encoder=args.encoder)
    train = [Sample(feats[i], labels[i]) for i in tr]
    val = [Sample(feats[i], labels[i]) for i in va]
    opt = LocalOptimizer(model, DataSet.array(train), nn.ClassNLLCriterion(),
                         Adam(learningrate=0.01),
                         Trigger.max_epoch(args.epochs),
                         batch_size=args.batch_size)
    opt.set_validation(every_epoch(), DataSet.array(val),
                       [Top1Accuracy()], batch_size=args.batch_size)
    opt.optimize()

    model.evaluate()
    pred = np.asarray(model.forward(feats[va])).argmax(1) + 1
    print(f"val accuracy = {(pred == labels[va]).mean():.4f}")


if __name__ == "__main__":
    main()
