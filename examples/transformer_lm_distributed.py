"""TransformerLM with mesh data parallelism + ZeRO-1 sharded optimizer —
the TPU-native distributed training showcase (replaces the reference's
DistriOptimizer-on-Spark examples).

Run on CPU with 8 virtual devices:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/transformer_lm_distributed.py
"""
import argparse

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.models import TransformerLM
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.optim import DistriOptimizer, Adam, max_iteration
from bigdl_tpu.parallel import data_parallel_mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    import jax
    mesh = data_parallel_mesh()
    print(f"mesh: {mesh}")

    rng = np.random.RandomState(0)
    seqs = rng.randint(1, args.vocab - 1, size=(512, args.seq_len + 1))
    samples = [Sample(seqs[i, :-1].astype(np.float32),
                      seqs[i, 1:].astype(np.float32))
               for i in range(len(seqs))]
    ds = DataSet.array(samples)

    model = TransformerLM(vocab_size=args.vocab, hidden_size=128,
                          num_heads=4, filter_size=256, num_layers=2)
    # LMCriterion: the 0-based token-id head (logits column j == token j,
    # the tied embedding's indexing) — models trained with it decode
    # directly via Transformer.generate (the 1-based torch-parity criteria
    # would train a permuted head)
    crit = nn.LMCriterion(padding_value=0)
    opt = DistriOptimizer(model, ds, crit, Adam(learningrate=3e-4),
                          max_iteration(args.iters),
                          batch_size=8 * mesh.shape["data"], mesh=mesh,
                          parameter_mode="zero1", compress="bf16")
    opt.optimize()
    print(f"final loss {opt.optim_method.state['loss']:.3f}; "
          f"step time {opt.metrics.mean('step_time') * 1e3:.1f} ms")


if __name__ == "__main__":
    main()
