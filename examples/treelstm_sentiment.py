"""TreeLSTM sentiment classification (reference: example/treeLSTMSentiment).

Synthetic stand-in for the SST task (zero-egress sandbox): random word
vectors arranged into random binary parse trees; the label is the sign of
the summed leaf embeddings' first component. A BinaryTreeLSTM encodes each
tree bottom-up (level-synchronous lax.scan sweep — nn/tree_lstm.py), the
ROOT hidden state feeds a linear softmax head, and the whole train step is
one jit. TreeNNAccuracy (root-node accuracy) is the validation metric, as
in the reference.

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/treelstm_sentiment.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.optim import SGD

EMB, HID, N_LEAVES, BATCH = 8, 16, 4, 32
N_NODES = 2 * N_LEAVES - 1  # full binary tree


def random_tree(rng):
    """Left-branching random binary tree over N_LEAVES leaves in the
    (left, right, leaf_idx) node-table format of nn/tree_lstm.py
    (1-based children; 0 = leaf; -1 row = padding)."""
    tree = np.zeros((N_NODES, 3), np.float32)
    # internal nodes 1..N_LEAVES-1 (node 1 is root, 1-based)
    # chain: node i has children (node i+1, leaf) — a left spine
    order = rng.permutation(N_LEAVES)
    for i in range(N_LEAVES - 1):
        left = i + 2 if i < N_LEAVES - 2 else N_LEAVES + i  # next internal or a leaf slot
        right = N_LEAVES + i if i < N_LEAVES - 2 else N_LEAVES + i + 1
        tree[i] = [left, right, 0]
    # leaf slots N_LEAVES..2*N_LEAVES-1 (1-based) hold word indices
    for j in range(N_LEAVES):
        row = N_LEAVES - 1 + j
        tree[row] = [0, 0, order[j] + 1]  # 1-based word index
    return tree


def make_batch(rng, n):
    words = rng.randn(n, N_LEAVES, EMB).astype(np.float32) * 0.5
    trees = np.stack([random_tree(rng) for _ in range(n)])
    labels = (words[:, :, 0].sum(axis=1) > 0).astype(np.int32) + 1  # 1/2
    return words, trees, labels


def main():
    rng = np.random.RandomState(0)
    tree_lstm = nn.BinaryTreeLSTM(EMB, HID)
    head = nn.Sequential(nn.Linear(HID, 2), nn.LogSoftMax())
    crit = nn.ClassNLLCriterion()

    p1, s1 = tree_lstm.init(jax.random.PRNGKey(0))
    p2, s2 = head.init(jax.random.PRNGKey(1))
    optim = SGD(learningrate=0.5, momentum=0.9)
    params = {"tree": p1, "head": p2}
    opt_state = optim.init_state(params)

    def loss_fn(params, words, trees, y):
        nodes, _ = tree_lstm.apply(params["tree"], s1, (words, trees),
                                   training=True, rng=None)
        root = nodes[:, 0]  # root is node index 0 (1-based node 1)
        logp, _ = head.apply(params["head"], s2, root, training=True,
                             rng=None)
        return crit._forward(logp, y), logp

    @jax.jit
    def step(params, opt_state, words, trees, y):
        (loss, logp), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, words, trees, y)
        params, opt_state = optim.update(g, params, opt_state,
                                         jnp.float32(0.5))
        acc = jnp.mean((jnp.argmax(logp, -1) + 1) == y)
        return params, opt_state, loss, acc

    first = last = None
    for it in range(60):
        words, trees, labels = make_batch(rng, BATCH)
        params, opt_state, loss, acc = step(
            params, opt_state, jnp.asarray(words), jnp.asarray(trees),
            jnp.asarray(labels))
        if it == 0:
            first = float(loss)
        last, last_acc = float(loss), float(acc)
        if it % 15 == 0:
            print(f"iter {it:3d}  nll {float(loss):.4f}  acc {float(acc):.2f}")

    print(f"nll {first:.4f} -> {last:.4f}; final batch acc {last_acc:.2f}")
    assert last < first and last_acc > 0.8, "tree LSTM failed to learn"
    print("OK")


if __name__ == "__main__":
    main()
