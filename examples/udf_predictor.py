"""Batch-serving a trained TextClassifier as a column UDF.

Parity: reference ``example/udfpredictor`` (Scala) — there a trained text
classifier is registered as a Spark SQL UDF and applied to a DataFrame's
text column. The bigdl_tpu analog: wrap ``PredictionService`` (the
thread-safe serving facade) in a vectorized UDF over a pandas DataFrame —
one jit-compiled forward serves every row batch.

Usage: python examples/udf_predictor.py [--epochs N]
Self-contained: trains on a small synthetic topic corpus first.
"""
import argparse

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.models import TextClassifier
from bigdl_tpu.models.textclassifier import tokenize_to_glove_sequences
from bigdl_tpu.optim import (LocalOptimizer, Adam, PredictionService,
                             Trigger)

# a deterministic 3-topic corpus (sports / tech / cooking)
_TOPICS = {
    1: ["the team won the match with a late goal",
        "players train hard before the championship game",
        "the coach praised the defense after the tournament",
        "fans cheered as the striker scored twice"],
    2: ["the new processor doubles compute throughput",
        "software update improves the neural network compiler",
        "engineers benchmark the accelerator memory bandwidth",
        "the chip integrates fast matrix units"],
    3: ["simmer the sauce with garlic and fresh basil",
        "knead the dough and bake until golden",
        "season the roasted vegetables with olive oil",
        "whisk the eggs into the warm butter slowly"],
}


def make_predict_udf(service, seq_len, embedding_dim):
    """Return a UDF: list/Series of raw texts -> np.ndarray of 1-based
    class labels. The reference registers the same shape of function as a
    Spark SQL UDF (example/udfpredictor Utils.scala)."""
    def udf(texts):
        texts = list(texts)
        feats, _ = tokenize_to_glove_sequences(
            [(t, 1) for t in texts], sequence_length=seq_len,
            embedding_dim=embedding_dim)
        return service.predict_class(feats, batch_size=32)
    return udf


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=40)
    ap.add_argument("--seq-len", type=int, default=12)
    ap.add_argument("--embedding-dim", type=int, default=50)
    args = ap.parse_args()

    corpus = [(t, lbl) for lbl, ts in _TOPICS.items() for t in ts]
    feats, labels = tokenize_to_glove_sequences(
        corpus, sequence_length=args.seq_len,
        embedding_dim=args.embedding_dim)

    model = TextClassifier(len(_TOPICS), embedding_dim=args.embedding_dim,
                           sequence_length=args.seq_len, encoder="cnn")
    samples = [Sample(f, l) for f, l in zip(feats, labels)]
    LocalOptimizer(model, DataSet.array(samples), nn.ClassNLLCriterion(),
                   Adam(learningrate=0.01),
                   Trigger.max_epoch(args.epochs),
                   batch_size=6).optimize()

    # ---- serving: the trained model behind a PredictionService UDF ----
    model.evaluate()
    service = PredictionService(model)
    udf = make_predict_udf(service, args.seq_len, args.embedding_dim)

    try:
        import pandas as pd
        df = pd.DataFrame({"text": [t for t, _ in corpus],
                           "label": labels})
        df["pred"] = udf(df["text"])
        acc = float((df["pred"] == df["label"]).mean())
    except ImportError:  # pandas-free fallback: plain lists
        preds = udf([t for t, _ in corpus])
        acc = float((preds == labels).mean())
    print(f"udf serving accuracy on the training corpus = {acc:.3f}")
    assert acc >= 0.75, acc

    # unseen rows flow through the same UDF (with real GloVe vectors the
    # labels would also generalize; the offline fallback embeddings only
    # guarantee mechanics, not semantics)
    probe = udf(["the goalkeeper made a great save",
                 "the gpu runs the model faster",
                 "stir the soup and add pepper"])
    print("probe predictions:", probe.tolist())
    assert probe.shape == (3,)
    print("OK")


if __name__ == "__main__":
    main()
