"""Wide & Deep with a REAL sparse wide arm (COO + segment_sum).

Demonstrates the sparse subsystem end-to-end (parity targets:
nn/SparseLinear.scala, nn/LookupTableSparse.scala, nn/SparseJoinTable.scala
serving the reference's wide-and-deep recommendation use case):

  * wide arm: two multi-hot categorical feature blocks as SparseTensors →
    SparseJoinTable → SparseLinear (gather + segment_sum, no densification)
  * deep arm: variable-length id bags → LookupTableSparse (mean combiner)
    → MLP
  * joint training with one jitted step.

Run: JAX_PLATFORMS=cpu PYTHONPATH=. python examples/wide_deep_sparse.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn import (LookupTableSparse, SparseJoinTable, SparseLinear,
                          SparseTensor)
from bigdl_tpu.utils.table import Table


def synthetic_batch(rng, batch, wide1, wide2, vocab, bag, w_true):
    """Multi-hot wide features + id bags; a fixed planted linear rule over
    the wide features plus one "magic" vocab id decides the label."""
    d1 = (rng.rand(batch, wide1) < 0.05).astype(np.float32)
    d2 = (rng.rand(batch, wide2) < 0.05).astype(np.float32)
    ids = np.zeros((batch, bag), np.float32)
    for b in range(batch):
        k = rng.randint(1, bag + 1)
        ids[b, :k] = rng.randint(1, vocab + 1, k)
    logits = np.concatenate([d1, d2], 1) @ w_true + 2.0 * (ids == 7).any(1)
    y = (logits + 0.3 * rng.randn(batch) > 0).astype(np.float32)
    # fixed nnz budgets -> stable COO shapes -> one compile for the run
    s1 = SparseTensor.from_dense(d1, nnz=int(batch * wide1 * 0.1))
    s2 = SparseTensor.from_dense(d2, nnz=int(batch * wide2 * 0.1))
    sp_ids = SparseTensor.from_dense(ids, nnz=batch * bag)
    return s1, s2, sp_ids, y[:, None]


def main():
    rng = np.random.RandomState(0)
    B, W1, W2, V, BAG, E = 256, 400, 300, 1000, 8, 16

    wide = SparseLinear(W1 + W2, 1)
    join = SparseJoinTable(2)
    embed = LookupTableSparse(V, E, combiner="mean")
    deep = nn.Sequential(nn.Linear(E, 32), nn.ReLU(), nn.Linear(32, 1))
    for m in (wide, embed, deep):
        m.ensure_initialized()
    crit = nn.BCECriterion()

    def loss_fn(pw, pe, pd, s_joined, sp_ids, y):
        ow, _ = wide.apply(pw, wide.state, s_joined)
        vecs, _ = embed.apply(pe, embed.state, sp_ids)
        od, _ = deep.apply(pd, deep.state, vecs)
        pred = jax.nn.sigmoid(ow + od)
        return crit._forward(pred, y)

    step = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
    pw, pe, pd = wide.params, embed.params, deep.params
    lr = 0.5
    w_true = rng.randn(W1 + W2) * (rng.rand(W1 + W2) < 0.2) * 3.0
    first = last = None
    for it in range(60):
        s1, s2, sp_ids, y = synthetic_batch(rng, B, W1, W2, V, BAG, w_true)
        joined = join.forward(Table(s1, s2))
        loss, (gw, ge, gd) = step(pw, pe, pd, joined, sp_ids,
                                  jnp.asarray(y))
        pw, pe, pd = (jax.tree_util.tree_map(lambda p, g: p - lr * g, P, G)
                      for P, G in ((pw, gw), (pe, ge), (pd, gd)))
        if first is None:
            first = float(loss)
        last = float(loss)
        if it % 20 == 0:
            print(f"iter {it:3d} loss {float(loss):.4f}")
    print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first * 0.9, "no learning"
    print("OK")


if __name__ == "__main__":
    main()
