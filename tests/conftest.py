"""Test config: force CPU backend with 8 virtual devices so distributed
(mesh/shard_map) paths are exercised without TPU hardware.

Note: the axon sitecustomize pins jax_platforms to the TPU backend at
interpreter start, so the env var alone is not enough — we must override via
jax.config before any backend is initialised.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on CPU"

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy full-size checks (big-model forwards, real-TF "
        "cross-validation). Skipped by default to keep `make test` inside "
        "the verification budget; run with BIGDL_TPU_SLOW=1 or -m slow. "
        "Every component keeps an unmarked smoke-size test.")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("BIGDL_TPU_SLOW") == "1":
        return
    if "slow" in (config.getoption("-m") or ""):
        return
    skip = pytest.mark.skip(
        reason="slow: opt in with BIGDL_TPU_SLOW=1 or -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    from bigdl_tpu.utils import engine
    engine.set_seed(42)
    yield
