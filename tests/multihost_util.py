"""Shared pieces of the multi-controller tests (driver script text +
port helper) — imported by test_multihost*.py, which are separate
files so pytest-xdist loadfile sharding overlaps them."""
import socket


_DRIVER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
dp = 8 // n  # devices per process: 8-device global mesh regardless of n
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={dp}"
import jax
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=pid)
import numpy as np
import jax.numpy as jnp
from bigdl_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == dp

# 1) coordinator-level allgather (heartbeat path)
seen = multihost_utils.process_allgather(jnp.asarray([float(pid)]))
assert sorted(np.asarray(seen).reshape(-1).tolist()) == [float(i) for i in
                                                         range(n)], seen

# 2) cross-process psum over the global mesh
mesh = Mesh(np.array(jax.devices()), ("data",))
sharding = NamedSharding(mesh, P("data"))
local = np.full((dp,), float(pid + 1), np.float32)  # dp per process
garr = jax.make_array_from_process_local_data(sharding, local)
out = jax.jit(shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                        in_specs=P("data"), out_specs=P()),
              out_shardings=NamedSharding(mesh, P()))(garr)
# psum of per-device values: dp devices carry (pid+1) for each pid
expect = float(sum((i + 1) * dp for i in range(n)))
total = float(np.asarray(jax.device_get(
    out.addressable_shards[0].data)).reshape(-1)[0])
assert total == expect, (total, expect)

# 3) hybrid DCN x ICI mesh in a real 2-process topology
from bigdl_tpu.parallel.mesh import make_hybrid_mesh
hmesh = make_hybrid_mesh(ici_shape=(1, dp), dcn_shape=(n, 1),
                         axes=("data", "model"))
assert hmesh.devices.shape == (n, dp)
# the ICI (model) axis must stay inside one process
for row in hmesh.devices:
    assert len({d.process_index for d in row}) == 1, hmesh.devices

# 4) full DistriOptimizer training across processes: each process feeds its
# LOCAL data split (the reference's per-partition reads); gradients psum
# over the global 'data' axis spanning both processes
from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import DistriOptimizer, SGD, MaxIteration
from bigdl_tpu.dataset import DataSet, mnist

dmesh = Mesh(np.array(jax.devices()), ("data",))
imgs, labels = mnist.load(n_synthetic=64)
# per-process split: each controller feeds a DIFFERENT slice of the data
per = 64 // n
imgs = imgs[pid * per:(pid + 1) * per]
labels = labels[pid * per:(pid + 1) * per]
ds = DataSet.array(mnist.to_samples(imgs, labels))
opt = DistriOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                      SGD(learningrate=0.01), MaxIteration(2),
                      batch_size=8, mesh=dmesh)
opt.optimize()
loss = float(opt.optim_method.state["loss"])
assert np.isfinite(loss), loss
# every process must agree on the replicated loss/params
agreed = multihost_utils.process_allgather(jnp.asarray([loss]))
assert np.allclose(np.asarray(agreed).reshape(-1), loss), agreed

# 5) ZeRO-1 sharded-optimizer variant over the same 2-process mesh
ds2 = DataSet.array(mnist.to_samples(imgs, labels))
opt2 = DistriOptimizer(LeNet5(10), ds2, nn.ClassNLLCriterion(),
                       SGD(learningrate=0.01), MaxIteration(2),
                       batch_size=8, mesh=dmesh,
                       parameter_mode="zero1", compress="bf16")
opt2.optimize()
assert np.isfinite(float(opt2.optim_method.state["loss"]))

print(f"MULTIHOST_OK_{pid}")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Some jaxlib CPU builds cannot run cross-process collectives at all —
# every multi-process driver dies on its FIRST process_allgather with
# this INVALID_ARGUMENT. That is an environment capability gap, not a
# code regression: skip (the tests run for real on multihost-capable
# CPU builds and on TPU pods).
BACKEND_UNSUPPORTED = "Multiprocess computations aren't implemented"


def skip_if_backend_unsupported(outs):
    """``outs``: [(pid, rc, stdout, stderr), ...] from the driver procs.
    Skips the calling test when the backend provably lacks multiprocess
    support; returns otherwise so normal assertions run."""
    import pytest
    if any(rc != 0 and BACKEND_UNSUPPORTED in (err or "")
           for _, rc, _, err in outs):
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")


