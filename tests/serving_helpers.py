"""Shared LM-serving test helpers: the solo-decode oracle and the
sharing-aware KV leak gate, imported by test_serving_lm.py and
test_prefix_cache.py so both suites enforce ONE correctness bar (a
chunking or leak-gate change that lands in only one copy would make
the two files silently gate different things)."""
import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.serving import prefill_schedule


def solo_oracle(model, params, prompt, max_new, chunk=8, maxlen=256,
                eos_id=None):
    """The same request decoded ALONE through dense ``decode_chunk``
    (greedy), duplicated to 2 rows (the scheduler's gemm M-class) with
    the scheduler's own prefill chunking."""
    prompt = np.asarray(prompt, np.int32)
    caches = model.init_cache(2, maxlen, jnp.float32)
    step = jax.jit(lambda toks, pos, c: model.decode_chunk(
        params, toks, pos, c))
    tok = None
    for s, real, padded in prefill_schedule(prompt.size, chunk):
        toks = np.zeros((2, padded), np.int32)
        toks[:, :real] = prompt[s:s + real]
        lg, caches = step(jnp.asarray(toks), jnp.int32(s), caches)
        if s + real == prompt.size:
            tok = int(np.asarray(lg)[0, real - 1].argmax())
    out = [tok]
    pos = int(prompt.size)
    while len(out) < max_new and (eos_id is None or out[-1] != eos_id):
        lg, caches = step(jnp.asarray([[tok], [tok]], np.int32),
                          jnp.int32(pos), caches)
        tok = int(np.asarray(lg)[0, 0].argmax())
        out.append(tok)
        pos += 1
    return np.asarray(out, np.int32)


def no_leaked_blocks(st):
    """The sharing-aware leak gate: mid-run, every resident page is
    pinned by the prefix cache (registered prefixes waiting for their
    next hit) — no block survives the request that owned it. After
    shutdown the cache is cleared too and this reduces to the old
    ``blocks_in_use == 0``."""
    cache_resident = (st.get("prefix") or {}).get("entries", 0)
    assert st["kv"]["blocks_in_use"] == cache_resident
