"""bench.py orchestration logic (pure parent-side python — the cache
ladder is the driver's evidence path, so its behaviors are pinned here:
real-TPU lines get cached with timestamps, stale lines expire, per-config
prefixes route correctly, env knobs validate loudly)."""
import importlib
import json
import os
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    import bench
    importlib.reload(bench)
    # point the cache at a temp file so tests never touch the real one
    monkeypatch.setattr(bench, "_TPU_CACHE", str(tmp_path / "cache.json"))
    return bench


def test_cache_roundtrip_and_merge(bench_mod):
    b = bench_mod
    b._cache_tpu_lines([{"metric": "resnet50_x", "value": 1.0,
                         "backend": "tpu"},
                        {"metric": "cpu_line", "value": 9, "backend": "cpu"}])
    b._cache_tpu_lines([{"metric": "lenet_y", "value": 2.0,
                         "backend": "axon"}])
    cached = json.load(open(b._TPU_CACHE))
    by = {l["metric"]: l for l in cached}
    # only TPU-class lines are cached; both writes merged; stamped
    assert set(by) == {"resnet50_x", "lenet_y"}
    assert all("measured_at" in l for l in cached)
    # updating a metric overwrites, not duplicates
    b._cache_tpu_lines([{"metric": "resnet50_x", "value": 3.0,
                         "backend": "tpu"}])
    cached = json.load(open(b._TPU_CACHE))
    assert len([l for l in cached if l["metric"] == "resnet50_x"]) == 1
    assert [l for l in cached
            if l["metric"] == "resnet50_x"][0]["value"] == 3.0


def test_cached_lines_filter_by_config_and_age(bench_mod):
    b = bench_mod
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    old = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                        time.gmtime(time.time() - 30 * 86400))
    json.dump([
        {"metric": "resnet50_train_images_per_sec_per_chip", "value": 1,
         "backend": "tpu", "measured_at": now},
        {"metric": "lenet_mnist_train_images_per_sec", "value": 2,
         "backend": "tpu", "measured_at": now},
        {"metric": "transformer_lm_train_tokens_per_sec", "value": 3,
         "backend": "tpu", "measured_at": old},
    ], open(b._TPU_CACHE, "w"))
    # headline picks only resnet50_*
    got = b._cached_tpu_lines("headline")
    assert [l["metric"] for l in got] == \
        ["resnet50_train_images_per_sec_per_chip"]
    assert got[0]["cached"] is True
    # per-config prefix routing
    got = b._cached_tpu_lines("secondary:lenet")
    assert [l["metric"] for l in got] == \
        ["lenet_mnist_train_images_per_sec"]
    # stale lines (>14 days) are dropped, not served
    assert b._cached_tpu_lines("secondary:transformer") == []


def test_cached_lines_provenance_on_reuse(bench_mod):
    """A cache hit must not impersonate a fresh measurement: the
    timestamp moves to `cache_from` and any error text a previous serve
    attached is dropped (BENCH_r05 re-emitted a stale tunnel_error)."""
    b = bench_mod
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    json.dump([
        {"metric": "resnet50_train_images_per_sec_per_chip", "value": 5,
         "backend": "tpu", "measured_at": now,
         "tunnel_error": "old outage text", "error": "stale"},
    ], open(b._TPU_CACHE, "w"))
    got = b._cached_tpu_lines("headline")
    assert len(got) == 1
    line = got[0]
    assert line["cached"] is True
    assert line["stale_cache"] is True
    assert line["cache_from"] == now
    assert "measured_at" not in line
    assert "tunnel_error" not in line and "error" not in line

    # and re-caching a served line never persists serve-time fields
    b._cache_tpu_lines([dict(line, backend="tpu",
                             tunnel_error="current outage")])
    stored = json.load(open(b._TPU_CACHE))[0]
    assert "tunnel_error" not in stored and "cached" not in stored
    assert "cache_from" not in stored and "stale_cache" not in stored
    assert "measured_at" in stored


def test_contaminated_cache_never_reemits_stale_error(bench_mod,
                                                      monkeypatch):
    """The BENCH_r05 regression, end to end: a cache FILE contaminated
    with serve-time fields (written by an older bench.py, or by hand)
    must serve clean — the emitted ``cached: true`` line carries only
    THIS run's outage text, never the baked-in one — and the next
    re-cache scrubs the contamination off disk."""
    b = bench_mod
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    json.dump([
        {"metric": "resnet50_train_images_per_sec_per_chip", "value": 2436.9,
         "unit": "images/sec/chip", "backend": "tpu", "measured_at": now,
         # the contamination: a previous serve's provenance baked in
         "cached": True, "cache_from": "2026-01-01T00:00:00Z",
         "tunnel_error": "STALE OUTAGE TEXT", "error": "STALE ERROR"},
    ], open(b._TPU_CACHE, "w"))

    # this run's tunnel is down: every attempt times out, probe dead
    monkeypatch.setattr(b, "_run_child",
                        lambda which, env, timeout: (None, "timeout"))

    def fake_alive(timeout=90.0, force=False):
        b._TUNNEL_STATE.update(probed=True, alive=False)
        return False

    monkeypatch.setattr(b, "_tunnel_alive", fake_alive)
    monkeypatch.setattr(b.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_WAIT_S", "0")
    lines = b._orchestrate("headline")
    assert len(lines) == 1
    line = lines[0]
    assert line["cached"] is True and line["value"] == 2436.9
    # the serve attaches THIS run's ladder text, not the stale one
    assert "STALE" not in line.get("tunnel_error", "")
    assert "timeout" in line["tunnel_error"]
    assert "error" not in line
    assert line["cache_from"] == now and "measured_at" not in line

    # a later successful measurement merges against the contaminated
    # file: the scrub must also clean the entries it does NOT overwrite
    b._cache_tpu_lines([{"metric": "lenet_mnist_train_images_per_sec",
                         "value": 5.0, "backend": "tpu"}])
    stored = {l["metric"]: l for l in json.load(open(b._TPU_CACHE))}
    resnet = stored["resnet50_train_images_per_sec_per_chip"]
    for field in ("cached", "cache_from", "tunnel_error", "error"):
        assert field not in resnet, (field, resnet)
    assert resnet["measured_at"] == now


def test_recache_strips_error_field(bench_mod):
    """Re-caching a line that carries bench-child ``error`` text keeps
    the measurement but drops the text (serve-time provenance)."""
    b = bench_mod
    b._cache_tpu_lines([{"metric": "resnet50_x", "value": 1.0,
                         "backend": "tpu", "error": "transient init fail",
                         "tunnel_error": "old ladder"}])
    stored = json.load(open(b._TPU_CACHE))[0]
    assert stored["value"] == 1.0
    assert "error" not in stored and "tunnel_error" not in stored


def test_corrupt_cache_resets_instead_of_blocking(bench_mod):
    b = bench_mod
    with open(b._TPU_CACHE, "w") as f:
        f.write("{not json")
    assert b._cached_tpu_lines("headline") == []
    b._cache_tpu_lines([{"metric": "resnet50_z", "backend": "tpu"}])
    assert json.load(open(b._TPU_CACHE))[0]["metric"] == "resnet50_z"


def test_variant_parser_validates(bench_mod, monkeypatch):
    b = bench_mod
    monkeypatch.delenv("BENCH_FUSED", raising=False)
    monkeypatch.delenv("BENCH_POOL_GRAD", raising=False)
    monkeypatch.delenv("BENCH_STEM", raising=False)
    assert b.resnet_bench_variant() == ("xla", "exact", "conv7")
    monkeypatch.setenv("BENCH_FUSED", "1")
    monkeypatch.setenv("BENCH_POOL_GRAD", "fast")
    monkeypatch.setenv("BENCH_STEM", "s2d")
    assert b.resnet_bench_variant() == ("pallas", "fast", "s2d")
    monkeypatch.setenv("BENCH_FUSED", "typo")
    with pytest.raises(SystemExit, match="BENCH_FUSED"):
        b.resnet_bench_variant()


def test_json_lines_parser_ignores_noise(bench_mod):
    b = bench_mod
    out = ("INFO: some log line\n"
           '{"metric": "m1", "value": 1}\n'
           "{broken json\n"
           '{"no_metric_key": true}\n'
           '{"metric": "m2", "value": 2}\n')
    assert [l["metric"] for l in b._json_lines(out)] == ["m1", "m2"]


def test_cpu_env_strips_axon(bench_mod, monkeypatch):
    b = bench_mod
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "1.2.3.4")
    env = b._cpu_env()
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert env["JAX_PLATFORMS"] == "cpu"


def test_wait_ladder_retries_when_tunnel_returns(bench_mod, monkeypatch):
    """BENCH_WAIT_S (r4): a capture that starts during an outage keeps
    probing and measures live when the tunnel comes back inside budget."""
    b = bench_mod
    calls = {"run": 0, "probe": 0}

    def fake_run_child(which, env, timeout):
        calls["run"] += 1
        if calls["run"] <= 1:          # first attempt: tunnel down
            return None, "timeout"
        return [{"metric": "resnet50_train_images_per_sec_per_chip",
                 "value": 42.0, "backend": "tpu"}], None

    def fake_alive(timeout=90.0, force=False):
        calls["probe"] += 1
        alive = calls["probe"] >= 2    # dead on first probe, back on next
        b._TUNNEL_STATE.update(probed=True, alive=alive)
        return alive

    monkeypatch.setattr(b, "_run_child", fake_run_child)
    monkeypatch.setattr(b, "_tunnel_alive", fake_alive)
    monkeypatch.setattr(b.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_WAIT_S", "300")
    lines = b._orchestrate("headline")
    assert lines[0]["value"] == 42.0
    assert not lines[0].get("cached")
    assert calls["run"] == 2 and calls["probe"] >= 2


def test_wait_ladder_budget_zero_serves_cache(bench_mod, monkeypatch):
    b = bench_mod
    b._cache_tpu_lines([{
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 7.0, "backend": "tpu"}])

    monkeypatch.setattr(b, "_run_child",
                        lambda which, env, timeout: (None, "timeout"))

    def fake_alive(timeout=90.0, force=False):
        b._TUNNEL_STATE.update(probed=True, alive=False)
        return False

    monkeypatch.setattr(b, "_tunnel_alive", fake_alive)
    monkeypatch.setattr(b.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_WAIT_S", "0")
    lines = b._orchestrate("headline")
    assert lines[0]["cached"] and lines[0]["value"] == 7.0


def test_cached_serve_marks_stale_and_warns_loudly(bench_mod, monkeypatch,
                                                   capsys):
    """ROADMAP direction 1, named explicitly: a tunnel outage must never
    silently re-issue the cached r03 number as a new round. Every served
    line carries ``stale_cache: true`` + ``cache_from``, a loud warning
    names the measurement date, and the metrics dump built from those
    lines carries the mark too."""
    b = bench_mod
    b._cache_tpu_lines([{
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 4.49, "unit": "images/sec/chip", "backend": "tpu"}])
    measured_at = json.load(open(b._TPU_CACHE))[0]["measured_at"]

    monkeypatch.setattr(b, "_run_child",
                        lambda which, env, timeout: (None, "timeout"))

    def fake_alive(timeout=90.0, force=False):
        b._TUNNEL_STATE.update(probed=True, alive=False)
        return False

    monkeypatch.setattr(b, "_tunnel_alive", fake_alive)
    monkeypatch.setattr(b.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_WAIT_S", "0")
    lines = b._orchestrate("headline")
    assert len(lines) == 1
    line = lines[0]
    # the explicit mark: a round file holding this line is visibly a
    # re-serve, never a fresh measurement
    assert line["stale_cache"] is True and line["cached"] is True
    assert line["cache_from"] == measured_at
    err = capsys.readouterr().err
    assert "WARNING" in err and "stale_cache" in err
    assert measured_at in err and "NOT a fresh round" in err

    # the BENCH_METRICS dump carries the mark as a sibling gauge
    from bigdl_tpu import observability as obs
    reg = obs.MetricsRegistry()
    obs.record_bench_line(line, reg)
    by = {l["metric"]: l for l in obs.metrics_dump(reg)}
    assert by["bench/resnet50_train_images_per_sec_per_chip"
              "/stale_cache"]["value"] == 1.0


def test_metrics_dump_written_from_lines(bench_mod, tmp_path, monkeypatch):
    b = bench_mod
    out = tmp_path / "BENCH_METRICS.json"
    monkeypatch.setenv("BENCH_METRICS_OUT", str(out))
    b._write_metrics_dump([
        {"metric": "resnet50_train_images_per_sec_per_chip", "value": 2436.9,
         "unit": "images/sec/chip", "vs_baseline": 40.6, "backend": "tpu"},
        {"metric": "bench_failed", "value": 0, "unit": "error"},
    ])
    dump = json.load(open(out))
    by = {l["metric"]: l for l in dump}
    assert by["bench/resnet50_train_images_per_sec_per_chip"]["value"] == \
        2436.9
    assert by["bench/resnet50_train_images_per_sec_per_chip"]["unit"] == \
        "images/sec/chip"
    assert by[
        "bench/resnet50_train_images_per_sec_per_chip/vs_baseline"
    ]["value"] == 40.6
    # every line speaks the bench schema
    assert all({"metric", "value", "unit"} <= set(l) for l in dump)


def test_metrics_dump_opt_out_and_never_raises(bench_mod, monkeypatch):
    b = bench_mod
    monkeypatch.setenv("BENCH_METRICS_OUT", "")
    b._write_metrics_dump([{"metric": "x", "value": 1, "unit": "u"}])  # no-op
    # unwritable path must not raise (the dump never fails the bench)
    monkeypatch.setenv("BENCH_METRICS_OUT", "/nonexistent_dir/x.json")
    b._write_metrics_dump([{"metric": "x", "value": 1, "unit": "u"}])
