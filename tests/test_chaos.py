"""Chaos-hardened serving (ISSUE 13).

The gates: the fault-injection plane is deterministic and zero-cost
disarmed; transient faults injected at the scheduler's dispatch seams
are absorbed by the step-replay tier with tokens BITWISE the fault-free
run — over the dense AND the Pallas paged-attention path (trace spies
assert which one served); a PERMANENT fault kills the loop with a
triaged crash bundle and typed in-flight failures carrying the
generated prefix; the Router recovers those failures KV-preservingly
(``prompt + partial`` re-dispatch, recovered streams bitwise, none
lost); the ledger auditor quarantines injected corruption with a
structured event instead of crashing the loop; and the ServingEngine's
batch retry now rides the same FaultPolicy surface as everything else.
"""
import os
import time

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.models.transformer_lm import TransformerLM
from bigdl_tpu.observability import health as _health
from bigdl_tpu.parallel import chaos
from bigdl_tpu.parallel.chaos import ChaosError, ChaosPlan, Rule
from bigdl_tpu.parallel.failure import (FaultPolicy, Heartbeat,
                                        TransientDeviceError, TRANSIENT,
                                        PERMANENT, classify_failure)
from bigdl_tpu.serving import (DecodeScheduler, EngineStopped,
                               PagedKVCache, Router, ServingEngine,
                               decode_scheduler_threads_alive)
from serving_helpers import no_leaked_blocks, solo_oracle as _oracle

V, H = 48, 32
MAXLEN = 256
CHUNK = 8


def _model(**kw):
    cfg = dict(vocab_size=V, hidden_size=H, num_heads=4, filter_size=64,
               num_layers=2, max_len=MAXLEN)
    cfg.update(kw)
    m = TransformerLM(**cfg)
    m.ensure_initialized()
    return m


_shared = {}


def shared_model():
    if "m" not in _shared:
        _shared["m"] = _model(pos_encoding="rope", num_kv_heads=2)
    return _shared["m"]


def solo_oracle(model, prompt, max_new, eos_id=None):
    return _oracle(model, model.params, prompt, max_new, chunk=CHUNK,
                   maxlen=MAXLEN, eos_id=eos_id)


def _sched(model, **kw):
    cfg = dict(max_slots=4, block_size=4, max_seq_len=96,
               prefill_chunk=CHUNK)
    cfg.update(kw)
    return DecodeScheduler(model, **cfg)


@pytest.fixture(autouse=True)
def _clean():
    yield
    chaos.disarm()
    _health.reset()
    obs.registry().reset()
    obs.disable()


@pytest.fixture(params=["dense",
                        pytest.param("kernel", marks=pytest.mark.slow)])
def paged_path(request, monkeypatch):
    """The kernel-agnostic matrix (ISSUE 13 satellite): every
    fault-recovery gate must hold whether decode runs the dense gather
    or the Pallas paged-attention kernel."""
    if request.param == "kernel":
        monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "interpret")
    else:
        monkeypatch.delenv("BIGDL_TPU_PAGED_ATTN", raising=False)
    return request.param


def _spy_guard(paged_path):
    from bigdl_tpu.kernels import paged_attention as pk
    before = pk.trace_count()

    def check():
        if paged_path == "kernel":
            assert pk.trace_count() > before, \
                "kernel arm served without tracing the Pallas path"
        else:
            assert pk.trace_count() == before
    return check


# ---------------------------------------------------------------------------
# the injection plane itself
# ---------------------------------------------------------------------------

def test_disarmed_is_noop_and_stats_empty():
    chaos.disarm()
    assert not chaos.armed()
    chaos.maybe_fire("serving/scheduler_step")   # must not raise
    assert chaos.stats() == {} and chaos.fires() == []


def test_rule_schedules_nth_every_max_fires_tag():
    chaos.arm({"sites": {
        "a": [{"kind": "transient", "nth": 2}],
        "b": [{"kind": "transient", "every": 2, "max_fires": 2}],
        "c": [{"kind": "transient", "nth": 1, "tag": "r1"}],
    }})
    fired = []
    for i in range(4):
        try:
            chaos.maybe_fire("a")
        except TransientDeviceError:
            fired.append(i)
    assert fired == [1], "nth=2 fires exactly on the second call"
    fired = []
    for i in range(8):
        try:
            chaos.maybe_fire("b")
        except TransientDeviceError:
            fired.append(i)
    assert fired == [1, 3], "every=2 fires twice then hits max_fires"
    chaos.maybe_fire("c", tag="r0")          # wrong tag: no match
    with pytest.raises(TransientDeviceError):
        chaos.maybe_fire("c", tag="r1")      # r1's FIRST matching call
    st = chaos.stats()
    assert st["fires"] == 4
    assert st["by_site"] == {"a": 1, "b": 2, "c": 1}
    assert chaos.sites_fired() == ["a", "b", "c"]


def test_rule_kinds_classify_and_wedge_sleeps():
    assert classify_failure(ChaosError("chaos: x")) == PERMANENT
    assert classify_failure(TransientDeviceError("x")) == TRANSIENT
    chaos.arm({"sites": {
        "p": [{"kind": "permanent", "nth": 1}],
        "w": [{"kind": "wedge", "nth": 1, "wedge_s": 0.08}],
    }})
    with pytest.raises(ChaosError):
        chaos.maybe_fire("p")
    t0 = time.monotonic()
    chaos.maybe_fire("w")                    # sleeps, never raises
    assert time.monotonic() - t0 >= 0.07


def test_prob_schedule_is_seeded_deterministic():
    def pattern(seed):
        chaos.arm({"seed": seed, "sites": {
            "s": [{"kind": "transient", "prob": 0.5}]}})
        out = []
        for i in range(32):
            try:
                chaos.maybe_fire("s")
            except TransientDeviceError:
                out.append(i)
        return out

    a, b, c = pattern(11), pattern(11), pattern(12)
    assert a == b, "same seed, same schedule"
    assert 0 < len(a) < 32
    assert a != c


def test_rule_validation():
    with pytest.raises(ValueError, match="kind"):
        Rule(kind="sideways", nth=1)
    with pytest.raises(ValueError, match="exactly one"):
        Rule(nth=1, every=2)
    with pytest.raises(ValueError, match="exactly one"):
        Rule()
    with pytest.raises(ValueError, match="prob"):
        Rule(prob=1.5)
    with pytest.raises(ValueError, match="wedge_s"):
        Rule(kind="wedge", nth=1)
    with pytest.raises(ValueError, match="unknown rule keys"):
        Rule.from_dict({"kind": "transient", "nth": 1, "bogus": 3})
    with pytest.raises(TypeError):
        chaos.arm(42)


def test_arm_from_env_plan_file(tmp_path, monkeypatch):
    plan = tmp_path / "plan.json"
    plan.write_text('{"seed": 3, "sites": {"heartbeat/beat": '
                    '[{"kind": "transient", "nth": 1}]}}')
    monkeypatch.setenv("BIGDL_TPU_CHAOS", str(plan))
    assert chaos.arm_from_env() is not None
    assert chaos.armed()
    with pytest.raises(TransientDeviceError):
        chaos.maybe_fire("heartbeat/beat")
    chaos.disarm()
    # malformed plans stay DISARMED, loudly — never take the process down
    plan.write_text("{not json")
    assert chaos.arm_from_env() is None
    assert not chaos.armed()


def test_heartbeat_and_checkpoint_sites(tmp_path):
    from bigdl_tpu.parallel.failure import HeartbeatLost
    chaos.arm({"sites": {
        "heartbeat/beat": [{"kind": "transient", "nth": 1}],
        "checkpoint/write": [{"kind": "transient", "nth": 1}],
    }})
    # an injected heartbeat fault surfaces the way a REAL exchange
    # failure does — typed HeartbeatLost, which is what the trainer's
    # remediation tier handles (a raw transport error would crash the
    # loop around the remediation instead of through it)
    with pytest.raises(HeartbeatLost, match="injected heartbeat fault"):
        Heartbeat().beat()
    from bigdl_tpu.optim.optimizer import _atomic_pickle
    ck = tmp_path / "ck.bin"
    with pytest.raises(TransientDeviceError):
        _atomic_pickle(str(ck), {"x": 1})
    assert not ck.exists(), "a failed write must leave no file"
    _atomic_pickle(str(ck), {"x": 1})        # rule exhausted: succeeds
    assert ck.exists()


# ---------------------------------------------------------------------------
# transient step replay (the Tier-2 analog for decode)
# ---------------------------------------------------------------------------

def test_transient_step_replay_bitwise(paged_path):
    """Faults injected at the decode-step AND prefill seams are
    absorbed by replay; every request's tokens stay bitwise the solo
    oracle — on the dense and the Pallas kernel path alike."""
    m = shared_model()
    rng = np.random.RandomState(31)
    plans = [(rng.randint(1, V, size=n).astype(np.int32), mn)
             for n, mn in ((5, 8), (11, 6), (17, 7))]
    chaos.arm({"sites": {
        "serving/scheduler_step": [
            {"kind": "transient", "every": 3, "max_fires": 3}],
        "serving/prefill": [{"kind": "transient", "nth": 2}],
    }})
    spy = _spy_guard(paged_path)
    with _sched(m, fault_policy=FaultPolicy(max_restarts=2,
                                            backoff_base_s=0.0)) as sched:
        futs = [sched.submit(p, mn) for p, mn in plans]
        got = [np.asarray(f.result(timeout=120)) for f in futs]
        st = sched.stats()
    spy()
    assert st["step_replays"] >= 2, f"faults not absorbed: {st}"
    for i, (p, mn) in enumerate(plans):
        assert np.array_equal(got[i], solo_oracle(m, p, mn)), \
            f"request {i} diverged under replay"
    no_leaked_blocks(st)
    assert sched.audit()["ok"]
    assert decode_scheduler_threads_alive() == 0


@pytest.mark.slow
def test_spec_round_replay_bitwise():
    """The speculative fast path replays as ONE unit: a transient
    mid-round rolls both pools back and the round reruns bitwise."""
    m = _model()   # sinusoidal/MHA variant, target as its own draft
    rng = np.random.RandomState(32)
    pr = rng.randint(1, V, size=9).astype(np.int32)
    want = solo_oracle(m, pr, 10)
    chaos.arm({"sites": {
        "serving/spec_round": [{"kind": "transient", "nth": 2}]}})
    with _sched(m, draft_model=m, spec_k=3) as sched:
        got = np.asarray(sched.submit(pr, 10).result(timeout=120))
        st = sched.stats()
    assert np.array_equal(got, want)
    assert st["step_replays"] >= 1 and st["spec_rounds"] > 0
    no_leaked_blocks(st)


def test_admission_transient_defers_then_serves_bitwise():
    """A transient fault inside the admission transaction (the CoW
    fork of a fully-cached prompt) unwinds the transaction and defers
    the request — the next boundary retries and the warm tokens stay
    bitwise."""
    m = shared_model()
    rng = np.random.RandomState(33)
    pr = rng.randint(1, V, size=16).astype(np.int32)   # hit_align-ed
    want = solo_oracle(m, pr, 8)
    chaos.arm({"sites": {
        "kv/cow_fork": [{"kind": "transient", "nth": 1}]}})
    with _sched(m) as sched:
        first = np.asarray(sched.submit(pr, 8).result(timeout=120))
        warm = np.asarray(sched.submit(pr, 8).result(timeout=120))
        st = sched.stats()
    assert np.array_equal(first, want) and np.array_equal(warm, want)
    assert st["prefix_hits"] == 1, "the warm request must still hit"
    assert st["prefix_cow_forks"] >= 1, "the retried fork must land"
    assert chaos.stats()["by_site"].get("kv/cow_fork") == 1
    no_leaked_blocks(st)


def test_replay_budget_exhausted_dies_with_triaged_bundle(
        tmp_path, monkeypatch):
    """A persistent 'transient' exhausts the budget: the loop dies, a
    crash bundle with per-request triage lands, and the in-flight
    future fails typed EngineStopped carrying the generated prefix —
    bitwise the oracle's — on ``.partial``."""
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", str(tmp_path))
    obs.enable()
    m = shared_model()
    rng = np.random.RandomState(34)
    pr = rng.randint(1, V, size=6).astype(np.int32)
    want = solo_oracle(m, pr, 20)
    chaos.arm({"sites": {
        "serving/scheduler_step": [
            {"kind": "transient", "every": 1}]}})   # never stops
    sched = _sched(m, fault_policy=FaultPolicy(max_restarts=1,
                                               backoff_base_s=0.0))
    sched.start(warmup=False)
    fut = sched.submit(pr, 20)
    exc = fut.exception(timeout=120)
    assert isinstance(exc, EngineStopped)
    partial = np.asarray(exc.partial, np.int32)
    assert partial.size >= 1, "the prefill token was already emitted"
    assert np.array_equal(partial, want[:partial.size]), \
        "the partial must be a bitwise prefix of the solo decode"
    sched.shutdown()
    st = sched.stats()
    assert st["kv"]["blocks_in_use"] == 0
    assert sched.audit()["ok"]
    # the bundle carries the triage table and flight_report renders it
    bundles = sorted(p for p in os.listdir(tmp_path)
                     if p.startswith("flight_"))
    assert bundles, "no crash bundle landed"
    import json
    with open(tmp_path / bundles[-1]) as f:
        bundle = json.load(f)
    reqs = bundle["context"]["requests"]
    assert any(r["stage"] == "decode" and r["tokens"] >= 1
               and r["kv_blocks"] >= 1 for r in reqs), reqs
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import flight_report
    text = flight_report.render(bundle)
    assert "in-flight requests at loop death" in text
    assert "stage=decode" in text


def test_permanent_fault_never_retries():
    m = shared_model()
    chaos.arm({"sites": {
        "serving/scheduler_step": [{"kind": "permanent", "nth": 1}]}})
    sched = _sched(m).start(warmup=False)
    fut = sched.submit(np.arange(1, 8, dtype=np.int32), 10)
    assert isinstance(fut.exception(timeout=120), EngineStopped)
    sched.shutdown()
    assert sched.stats()["step_replays"] == 0, \
        "PERMANENT must not burn the replay budget"
    assert decode_scheduler_threads_alive() == 0


# ---------------------------------------------------------------------------
# the KV ledger auditor
# ---------------------------------------------------------------------------

def test_audit_clean_on_legit_ledger():
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=17, block_size=4,
                      max_blocks_per_seq=4)
    kv.ensure_capacity("a", 16)
    kv.ensure_capacity("b", 8)
    shared = kv.owner_blocks("a")[:2]
    kv.retain(shared)                       # cache-style pins
    kv.adopt("c", shared)                   # a second table referent
    rep = kv.audit(prefix_pins={shared[0]: 1, shared[1]: 1})
    assert rep["ok"], rep["violations"]
    assert rep["owners"] == 3
    kv.free("c"), kv.free("b"), kv.free("a")
    kv.release(shared)
    rep = kv.audit(prefix_pins={})
    assert rep["ok"] and kv.blocks_in_use() == 0


def test_audit_flags_every_violation_class():
    m = shared_model()

    def fresh():
        kv = PagedKVCache(m, num_blocks=9, block_size=4,
                          max_blocks_per_seq=4)
        kv.ensure_capacity("a", 8)
        return kv

    kv = fresh()                             # free-list duplicate
    with kv._lock:
        kv._free.append(kv._free[-1])
    assert any("duplicate" in v for v in kv.audit()["violations"])

    kv = fresh()                             # free AND referenced
    with kv._lock:
        kv._refs[kv._free[0]] = 1
    assert any("both free and referenced" in v
               for v in kv.audit()["violations"])

    kv = fresh()                             # leaked: in neither set
    with kv._lock:
        b = kv._free.pop()
    assert any("leaked" in v for v in kv.audit()["violations"])

    kv = fresh()                             # aliasing: tables > refcount
    with kv._lock:
        kv._owned["z"] = [kv._owned["a"][0]]
    assert any("aliased" in v for v in kv.audit()["violations"])

    kv = fresh()                             # dup within one table
    with kv._lock:
        kv._owned["a"].append(kv._owned["a"][0])
    assert any("table aliases" in v for v in kv.audit()["violations"])

    kv = fresh()                             # dead prefix pin
    assert any("dead block" in v
               for v in kv.audit(prefix_pins={7: 1})["violations"])

    kv = fresh()                             # pin-count mismatch
    assert any("prefix pins" in v
               for v in kv.audit(
                   prefix_pins={kv.owner_blocks("a")[0]: 1})["violations"])


def test_scheduler_quarantines_corruption_and_keeps_serving(
        tmp_path, monkeypatch):
    """The observe→act loop for the ledger: injected corruption fires
    ``health/kv_corruption`` + a bundle ONCE, quarantines (prefix
    adoption and the affinity probe go dark) — and the loop keeps
    serving, bitwise."""
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", str(tmp_path))
    obs.enable()
    m = shared_model()
    rng = np.random.RandomState(35)
    pr = rng.randint(1, V, size=16).astype(np.int32)
    want = solo_oracle(m, pr, 8)
    events = []
    sched = _sched(m, audit_every=2).start(warmup=False)
    with _health.listen(lambda e: events.append(e)):
        assert np.array_equal(
            np.asarray(sched.submit(pr, 8).result(timeout=120)), want)
        assert sched.cached_prefix_tokens(pr) >= 16
        with sched.kv._lock:                 # corrupt under the loop
            phantom = sched.kv._free[0]
            sched.kv._refs[phantom] = 1
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline \
                and not sched.stats()["quarantined"]:
            time.sleep(0.05)
        st = sched.stats()
        assert st["quarantined"] and st["kv_corruptions"] >= 1
        corr = [e for e in events if e["kind"] == "health/kv_corruption"]
        assert corr and corr[0]["n_violations"] >= 1
        # alive + correct, but no NEW shared state out of a corrupt pool
        f = sched.submit(pr, 8)
        assert np.array_equal(np.asarray(f.result(timeout=120)), want)
        assert f.trace["prefix_hit_tokens"] == 0
        assert sched.cached_prefix_tokens(pr) == 0
        with sched.kv._lock:                 # repair, then clean drain
            sched.kv._refs.pop(phantom, None)
    assert any(p.startswith("flight_") for p in os.listdir(tmp_path)), \
        "the corruption must land a bundle"
    sched.shutdown()
    assert sched.stats()["kv"]["blocks_in_use"] == 0
    assert decode_scheduler_threads_alive() == 0


# ---------------------------------------------------------------------------
# engine FaultPolicy (the upgraded one-shot retry)
# ---------------------------------------------------------------------------

def _engine(model, **kw):
    kw.setdefault("input_shape", (4,))
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 1.0)
    return ServingEngine(model, **kw)


def test_engine_fault_policy_absorbs_consecutive_transients():
    from bigdl_tpu.nn import Linear
    m = Linear(4, 3)
    m.ensure_initialized()
    sleeps = []
    pol = FaultPolicy(max_restarts=3, backoff_base_s=0.01,
                      sleep=sleeps.append)
    chaos.arm({"sites": {"serving/engine_dispatch": [
        {"kind": "transient", "every": 1, "max_fires": 2}]}})
    with _engine(m, fault_policy=pol) as eng:
        out = eng.predict(np.ones((4,), np.float32), timeout=30)
        st = eng.stats()
    assert out is not None and out.shape == (3,)
    assert st["transient_retries"] == 2, st
    assert sleeps == [0.01, 0.02], "exponential backoff, injectable"
    assert st["batch_errors"] == 0


def test_engine_fault_policy_budget_exhausts_typed():
    from bigdl_tpu.nn import Linear
    m = Linear(4, 3)
    m.ensure_initialized()
    chaos.arm({"sites": {"serving/engine_dispatch": [
        {"kind": "transient", "every": 1}]}})
    with _engine(m, fault_policy=FaultPolicy(max_restarts=1,
                                             backoff_base_s=0.0)) as eng:
        fut = eng.submit(np.ones((4,), np.float32))
        assert isinstance(fut.exception(timeout=30),
                          TransientDeviceError)
        # the next batch is a FRESH dispatch unit: the exhausted
        # budget reset with the failed batch, so a single isolated
        # flake is still absorbed (one exhausted batch must not
        # disable the safety net for every batch after it)
        chaos.arm({"sites": {"serving/engine_dispatch": [
            {"kind": "transient", "nth": 1}]}})
        assert eng.predict(np.ones((4,), np.float32),
                           timeout=30) is not None
        chaos.disarm()                     # the batcher must have lived
        assert eng.predict(np.ones((4,), np.float32),
                           timeout=30) is not None
        assert eng.stats()["transient_retries"] >= 2


# ---------------------------------------------------------------------------
# KV-preserving failover through the router
# ---------------------------------------------------------------------------

def _lm_replicas(model, n=2):
    return [_sched(model, name=f"lm{i}") for i in range(n)]


def test_router_kv_preserving_failover_bitwise(paged_path):
    """An injected PERMANENT fault kills replica lm0 mid-decode; its
    in-flight requests re-dispatch carrying prompt + generated tokens
    and complete on lm1 — every stream bitwise the uninterrupted run,
    none lost, none double-answered, both ledgers drained."""
    m = shared_model()
    rng = np.random.RandomState(36)
    plans = [(rng.randint(1, V, size=sz).astype(np.int32), 10, {})
             for sz in (7, 12, 9, 15)]
    plans.append((rng.randint(1, V, size=8).astype(np.int32), 10,
                  dict(temperature=0.8, top_p=0.9, seed=55)))
    want = []
    with _sched(m) as ref:
        for p, mn, kw in plans:
            want.append(np.asarray(
                ref.submit(p, mn, **kw).result(timeout=120)))
    chaos.arm({"sites": {"serving/scheduler_step": [
        {"kind": "permanent", "nth": 2, "tag": "lm0"}]}})
    spy = _spy_guard(paged_path)
    replicas = _lm_replicas(m)
    for r in replicas:
        r.start(warmup=False)
    with Router(replicas) as router:
        futs = [router.submit(p, max_new_tokens=mn, **kw)
                for p, mn, kw in plans]
        got = [np.asarray(f.result(timeout=180)) for f in futs]
        st = router.stats()
    spy()
    for i, w in enumerate(want):
        assert np.array_equal(got[i], w), \
            f"request {i}: failover broke the stream " \
            f"(want {w}, got {got[i]})"
    assert st["completed"] == len(plans), f"lost requests: {st}"
    assert st["kv_recoveries"] >= 1, \
        f"no KV-preserving recovery exercised: {st}"
    recovered = [f for f in futs
                 if f.trace.get("router", {}).get("recovered_tokens")]
    assert recovered, "at least one future must carry recovery provenance"
    for r in replicas:
        assert r.stats()["kv"]["blocks_in_use"] == 0
        assert r.audit()["ok"]
    assert decode_scheduler_threads_alive() == 0


def test_recover_decode_full_budget_resolves_without_redispatch():
    """When the dead replica had already produced the whole budget, the
    recovery resolves the client from the partial alone — re-dispatching
    a zero-token request would be a wasted prefill AND a validation
    error."""
    m = shared_model()
    router = Router(_lm_replicas(m), manage_replicas=False)
    fut = router.submit(np.arange(1, 9, dtype=np.int32),
                        max_new_tokens=4)
    req = router._classes["default"].q[0]
    exc = EngineStopped("replica died")
    exc.partial = np.asarray([5, 6, 7, 8], np.int32)
    assert router._recover_decode(req, exc) is True
    assert np.array_equal(fut.result(timeout=5),
                          np.asarray([5, 6, 7, 8], np.int32))
    assert router.stats()["kv_recoveries"] == 1
    # requests without a partial (or an empty one) fall through to the
    # plain whole-prompt failover untouched
    exc2 = EngineStopped("x")
    router.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
    req3 = router._classes["default"].q[-1]
    assert router._recover_decode(req3, exc2) is False   # no partial
    exc2.partial = np.zeros((0,), np.int32)
    assert router._recover_decode(req3, exc2) is False   # empty partial
    router.shutdown(drain=False)
