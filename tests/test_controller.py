"""Elastic fleet control plane (ISSUE 19).

The gates: the controller scales 1→3 replicas and back under an
injected load ramp with ZERO lost requests and tokens bitwise the
static-fleet oracle; decode→prefill promotion relieves an injected
prefill backlog and demotes on relief; cross-host staleness is judged
by beat-counter progress against the OBSERVER's monotonic clock (a
member file stamped hours off wall-clock is not false-killed); an
agent restarted on a NEW advertised host:port rejoins through the
monitor re-dial path with zero lost requests, three times over; and a
controller death mid-reconcile leaves the fleet serving, with a
respawned controller ADOPTING the existing members instead of
respawning them.

Everything here runs in-process agents (sockets + files, one jax
runtime) — the subprocess flavor of these drills lives in
`make fleet-smoke`/`make chaos-smoke`.
"""
import itertools
import time

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability import health as _health
from bigdl_tpu.models.transformer_lm import TransformerLM
from bigdl_tpu.parallel import chaos
from bigdl_tpu.parallel.failure import FileHeartbeat
from bigdl_tpu.serving import (DecodeScheduler, DisaggregatedFleet,
                               FleetController, FleetMonitor,
                               RemoteReplica, ReplicaAgent, Router,
                               ScalePolicy, controller_threads_alive,
                               wait_for_members)
from bigdl_tpu.serving.fleet import fleet_threads_alive, read_member
from bigdl_tpu.serving.transport import pick_advertise_host

V, H = 48, 32
SCHED = dict(max_slots=4, block_size=4, max_seq_len=96, prefill_chunk=8)
MODEL = dict(vocab_size=V, hidden_size=H, num_heads=4, filter_size=64,
             num_layers=2, max_len=256)


@pytest.fixture(autouse=True)
def _clean():
    yield
    chaos.disarm()
    _health.reset()
    obs.registry().reset()
    obs.disable()


def _model():
    m = TransformerLM(**MODEL)
    m.ensure_initialized()
    return m


def _prompts(rng, sizes):
    return [rng.randint(1, V, size=n).astype(np.int32) for n in sizes]


def _crash(ag):
    """Ungraceful agent death: no final beat, no drain — the member
    file is left mid-beat, exactly what a kill -9 leaves behind."""
    ag._stop.set()
    if ag._beat_thread is not None:
        ag._beat_thread.join(10)
    if ag.server is not None:
        ag.server.close()
    ag.engine.shutdown(drain=False)


# -- cross-host discovery ---------------------------------------------------

def test_pick_advertise_host_and_wildcard_bind_member_doc(tmp_path):
    # a concrete bind address is already dialable — passed through
    assert pick_advertise_host("10.1.2.3") == "10.1.2.3"
    assert pick_advertise_host("127.0.0.1") == "127.0.0.1"
    # a wildcard bind must never be advertised as-is: peers on other
    # hosts cannot dial 0.0.0.0
    got = pick_advertise_host("0.0.0.0")
    assert got not in ("", "0.0.0.0", "::")
    # an agent bound to the wildcard advertises the resolved address
    fd = str(tmp_path)
    m = _model()
    ag = ReplicaAgent(DecodeScheduler(m, name="adv", **SCHED),
                      fleet_dir=fd, name="adv", host="0.0.0.0",
                      beat_s=0.1).start()
    try:
        doc, = wait_for_members(fd, ["adv"], timeout_s=60)
        assert doc["host"] == got != "0.0.0.0"
        # ...and an explicit advertise_host (NAT/multi-homed) wins
        assert ReplicaAgent(
            DecodeScheduler(m, name="adv2", **SCHED), fleet_dir=fd,
            name="adv2", host="0.0.0.0",
            advertise_host="203.0.113.9").advertise_host == "203.0.113.9"
        # the advertised address is actually dialable on this box
        # (boxes whose outbound interface refuses hairpin connects just
        # skip the dial — the doc contract above is the real gate)
        try:
            rep = RemoteReplica(doc, fleet_dir=fd).start()
        except OSError:
            rep = None
        if rep is not None:
            assert rep.stats()["queue_depth"] == 0
    finally:
        ag.shutdown()
    assert fleet_threads_alive() == 0


def test_set_role_flips_member_doc_and_rejects_unknown(tmp_path):
    fd = str(tmp_path)
    ag = ReplicaAgent(DecodeScheduler(_model(), name="rf", **SCHED),
                      fleet_dir=fd, name="rf", role="decode",
                      beat_s=0.05).start()
    try:
        doc, = wait_for_members(fd, ["rf"], timeout_s=60)
        rep = RemoteReplica(doc, fleet_dir=fd).start()
        out = rep.set_role("prefill", tags=["pf"])
        assert out == {"role": "prefill", "was": "decode"}
        assert rep.role == "prefill"
        deadline = time.time() + 10
        while time.time() < deadline:
            d = read_member(fd, "rf")
            if d and d.get("role") == "prefill":
                break
            time.sleep(0.02)
        assert d["role"] == "prefill" and d["tags"] == ["pf"], \
            "the role flip must land in the member file immediately"
        with pytest.raises(ValueError, match="role"):
            rep.set_role("bogus")
        assert rep.role == "prefill"
    finally:
        ag.shutdown()
    assert fleet_threads_alive() == 0


# -- cross-host-safe staleness (satellite: skewed-stamp regression) ---------

def test_staleness_is_beat_progress_not_wallclock(tmp_path):
    """The monitor judges staleness by beat-COUNTER progress against
    its own monotonic clock; the member file's wall-clock stamp is
    never compared, so hours of cross-host clock skew cannot
    false-kill a beating agent."""
    fd = str(tmp_path)
    mon = FleetMonitor([], fleet_dir=fd, stale_s=1.0)
    # a doc stamped two hours in the past is FRESH while its counter
    # moves — under wall-clock staleness this would read age 7200s
    skew = time.time() - 7200.0
    assert mon._progress_age_s("x", {"beat": 1, "written_at": skew},
                               now=100.0) == 0.0
    assert FileHeartbeat.age_s({"written_at": skew}) > 7000.0
    # frozen counter: age accrues on the OBSERVER's clock
    assert mon._progress_age_s("x", {"beat": 1, "written_at": skew},
                               now=100.4) == pytest.approx(0.4)
    # counter moved → fresh again (stamp still hours off)
    assert mon._progress_age_s("x", {"beat": 2, "written_at": skew},
                               now=100.5) == 0.0
    # counter went BACKWARDS → a restarted incarnation, not silence
    assert mon._progress_age_s("x", {"beat": 1, "written_at": skew},
                               now=100.6) == 0.0
    # missing/typeless docs are infinitely stale
    assert mon._progress_age_s("x", None, 101.0) == float("inf")
    assert mon._progress_age_s("x", {"written_at": skew},
                               101.0) == float("inf")


def test_staleness_clock_survives_transient_read_miss(tmp_path):
    """One unreadable beat (the member file mid-rewrite) must not reset
    a frozen member's staleness clock: the next successful read
    continues the age from when the counter last ADVANCED, so a wedged
    agent cannot have its stall detection deferred by transient read
    misses."""
    mon = FleetMonitor([], fleet_dir=str(tmp_path), stale_s=1.0)
    assert mon._progress_age_s("m", {"beat": 7}, now=50.0) == 0.0
    assert mon._progress_age_s("m", {"beat": 7},
                               now=50.4) == pytest.approx(0.4)
    # transient miss: unknown for the instant, but the entry survives
    assert mon._progress_age_s("m", None, now=50.5) == float("inf")
    assert mon._progress_age_s("m", {"beat": 7},
                               now=51.2) == pytest.approx(1.2)
    # real counter progress still resets the clock
    assert mon._progress_age_s("m", {"beat": 8}, now=51.3) == 0.0
    # unwatch is what forgets the member for good
    mon.unwatch("m")
    assert "m" not in mon._progress


def test_skewed_wallclock_member_not_false_killed(tmp_path):
    """End-to-end: an agent whose member-file stamps are rewritten two
    hours into the past (a skewed cross-host clock) keeps serving under
    a monitor with a sub-second staleness threshold — no stall is ever
    emitted for it while it beats."""
    fd = str(tmp_path)
    m = _model()
    ag = ReplicaAgent(DecodeScheduler(m, name="skew", **SCHED),
                      fleet_dir=fd, name="skew", beat_s=0.1)

    class _SkewedHB(FileHeartbeat):
        def beat(self, payload=None, *, final=False):
            doc = dict(payload or {})
            out = super().beat(doc, final=final)
            # rewrite atomically with the stamp hours off, like a host
            # whose wall clock drifted — the beat counter still moves
            import json, os
            skewed = dict(out, written_at=out["written_at"] - 7200.0)
            tmp = f"{self.path}.skew"
            with open(tmp, "w") as f:
                json.dump(skewed, f, default=str)
            os.replace(tmp, self.path)
            return skewed

    ag._hb = _SkewedHB(ag._hb.path)
    ag.start()
    events = []
    _health.listeners.append(lambda e: events.append(e))
    mon = None
    try:
        doc, = wait_for_members(fd, ["skew"], timeout_s=60)
        assert doc["written_at"] < time.time() - 7000
        rep = RemoteReplica(doc, fleet_dir=fd).start()
        mon = FleetMonitor([rep], fleet_dir=fd, every_s=0.05,
                           stale_s=0.6).start()
        rng = np.random.RandomState(4)
        prompt = rng.randint(1, V, size=9).astype(np.int32)
        first = rep.submit(prompt, max_new_tokens=4).result(timeout=120)
        time.sleep(1.5)   # many monitor ticks past stale_s of wall skew
        again = rep.submit(prompt, max_new_tokens=4).result(timeout=120)
        assert np.array_equal(first, again)
        stalls = [e for e in events if e.get("kind") == "health/stall"]
        assert not stalls, f"skewed stamp false-killed the agent: {stalls}"
    finally:
        if mon is not None:
            mon.stop()
        ag.shutdown()
    assert fleet_threads_alive() == 0


# -- reconnect churn (satellite) --------------------------------------------

def test_reconnect_churn_new_ports_zero_lost_3x(tmp_path):
    """Agent restart churn, three rounds: each incarnation crashes
    (no final beat) and a replacement registers under the SAME member
    name on a NEW port; the monitor re-dials from the fresh doc and
    every post-rejoin submit completes, tokens bitwise round one's."""
    fd = str(tmp_path)
    m = _model()
    ag = ReplicaAgent(DecodeScheduler(m, name="rc0", **SCHED),
                      fleet_dir=fd, name="rc", beat_s=0.1).start()
    mon = None
    crashed = []
    try:
        doc, = wait_for_members(fd, ["rc"], timeout_s=60)
        rep = RemoteReplica(doc, fleet_dir=fd).start()
        mon = FleetMonitor([rep], fleet_dir=fd, every_s=0.05,
                           stale_s=8.0).start()
        rng = np.random.RandomState(11)
        prompt = rng.randint(1, V, size=9).astype(np.int32)
        want = rep.submit(prompt, max_new_tokens=6).result(timeout=120)
        ports = {rep.port}
        for i in range(1, 4):
            old_port = rep.port
            _crash(ag)
            crashed.append(ag)
            ag = ReplicaAgent(
                DecodeScheduler(m, name=f"rc{i}", **SCHED),
                fleet_dir=fd, name="rc", beat_s=0.1).start()
            deadline = time.time() + 60
            while time.time() < deadline:
                if not rep._client.closed and rep.port != old_port:
                    break
                time.sleep(0.05)
            assert rep.port != old_port, \
                f"round {i}: monitor never re-dialed the new port"
            ports.add(rep.port)
            got = rep.submit(prompt, max_new_tokens=6).result(timeout=120)
            assert np.array_equal(want, got), f"round {i}: tokens differ"
        assert len(ports) == 4, f"every round must land a new port: {ports}"
    finally:
        if mon is not None:
            mon.stop()
        ag.shutdown()
        for c in crashed:
            c.engine.shutdown(drain=False)
    assert fleet_threads_alive() == 0


# -- the elastic drill ------------------------------------------------------

def test_elastic_scale_up_and_down_zero_lost_bitwise(tmp_path):
    """The acceptance drill: under an injected load ramp the controller
    scales 1→3 replicas (spawn + prefix-warm + router join) and back
    down to 1 (drain-retire, never kill) with ZERO lost requests and
    every token bitwise the static oracle. The spawn-latency histogram
    records each launch."""
    fd = str(tmp_path)
    m = _model()
    obs.enable()
    local = DecodeScheduler(m, name="ctl_oracle", **SCHED).start()
    agents = {}

    def spawn(name):
        ag = ReplicaAgent(DecodeScheduler(m, name=name, **SCHED),
                          fleet_dir=fd, name=name, beat_s=0.1).start()
        agents[name] = ag
        doc, = wait_for_members(fd, [name], timeout_s=60)
        return RemoteReplica(doc, fleet_dir=fd).start()

    r0 = spawn("r0")
    router = Router([r0], max_failovers=4).start()
    mon = FleetMonitor([r0], fleet_dir=fd, every_s=0.1,
                       stale_s=10.0).start()
    rng = np.random.RandomState(7)
    prompts = _prompts(rng, [9 + (i % 13) for i in range(32)])
    pol = ScalePolicy(min_replicas=1, max_replicas=3, queue_high=2.0,
                      queue_low=0.5, up_ticks=1, down_ticks=2,
                      cooldown_s=0.0, warm_limit=2)
    ctl = FleetController(router, mon, fleet_dir=fd, spawn=spawn,
                          policy=pol, warm_prompts=lambda: prompts[:2])
    try:
        want = [local.generate(p, 24) for p in prompts]
        futs = []  # (prompt_index, future) — every request ever sent
        nxt = itertools.count()

        def top_up(n):
            for _ in range(n):
                i = next(nxt) % len(prompts)
                futs.append((i, router.submit(prompts[i],
                                              max_new_tokens=24)))

        # ramp: tick (deterministically, no thread) until the fleet
        # grows to the max budget — the load must be SUSTAINED, so the
        # queue is topped back up whenever the fleet starts catching
        # up (a one-shot burst drains before the second spawn lands
        # and the controller correctly never scales past 2)
        top_up(24)
        deadline = time.time() + 240
        while len(router.stats()["replicas"]) < 3 \
                and time.time() < deadline:
            if sum(router.stats()["queue_depth"].values()) < 8 \
                    and len(futs) < 400:
                top_up(8)
            ctl.tick()
            time.sleep(0.05)
        assert len(router.stats()["replicas"]) == 3, \
            f"never scaled to 3: {ctl.stats()} / {router.stats()}"
        for i, f in futs:
            assert np.array_equal(want[i], f.result(timeout=300)), \
                "elastic-fleet tokens must be bitwise the static oracle"
        st = router.stats()
        assert st["completed"] == len(futs), f"lost requests: {st}"
        # drain of load → scale back down to min, retiring the
        # controller-spawned replicas first; the seed replica survives
        deadline = time.time() + 240
        while len(router.stats()["replicas"]) > 1 \
                and time.time() < deadline:
            ctl.tick()
            time.sleep(0.05)
        assert router.healthy_replicas() == ["r0"], router.stats()
        cs = ctl.stats()
        assert cs["scale_ups"] >= 2 and cs["scale_downs"] >= 2, cs
        assert cs["warm_prompts"] >= 1, \
            f"joiners must pre-warm from a peer: {cs}"
        st = router.stats()
        assert st["joins"] == cs["scale_ups"] \
            and st["retires"] == cs["scale_downs"], (st, cs)
        # post-retire traffic still serves, still bitwise
        tail = router.submit(prompts[0],
                             max_new_tokens=24).result(timeout=120)
        assert np.array_equal(want[0], tail)
        assert router.stats()["completed"] == len(futs) + 1
        h = obs.registry().get("serve/fleet_spawn_ms")
        assert h is not None and h.count == cs["scale_ups"], \
            "every spawn must record its launch latency"
        router.shutdown()
    finally:
        for ag in agents.values():
            ag.shutdown()
        mon.stop()
    local.shutdown()
    assert fleet_threads_alive() == 0
    assert controller_threads_alive() == 0


# -- prefill promotion ------------------------------------------------------

def test_prefill_promotion_relieves_backlog_then_demotes(tmp_path):
    """An injected prefill backlog promotes one decode replica to
    prefill duty (role flip lands in its member file, pools move, its
    in-flight decode work fails over — zero lost); the handoff path
    keeps landing through the grown pool; backlog relief demotes it
    back to decode rotation."""
    fd = str(tmp_path)
    m = _model()
    local = DecodeScheduler(m, name="promo_oracle", **SCHED).start()
    ags = [ReplicaAgent(DecodeScheduler(m, name=n, **SCHED),
                        fleet_dir=fd, name=n, role=r,
                        beat_s=0.05).start()
           for n, r in (("pp", "prefill"), ("pd0", "decode"),
                        ("pd1", "decode"))]
    mon = None
    try:
        dpf, dd0, dd1 = wait_for_members(fd, ["pp", "pd0", "pd1"],
                                         timeout_s=120)
        rpf = RemoteReplica(dpf, fleet_dir=fd).start()
        rd0 = RemoteReplica(dd0, fleet_dir=fd)
        rd1 = RemoteReplica(dd1, fleet_dir=fd)
        router = Router([rd0, rd1], max_failovers=4).start()
        mon = FleetMonitor([rpf, rd0, rd1], fleet_dir=fd, every_s=0.1,
                           stale_s=10.0).start()
        dis = DisaggregatedFleet(router, [rpf], [rd0, rd1])
        pol = ScalePolicy(min_replicas=2, max_replicas=2, up_ticks=99,
                          down_ticks=99, cooldown_s=0.0,
                          prefill_backlog_high=3, prefill_backlog_low=0)
        ctl = FleetController(
            router, mon, fleet_dir=fd,
            spawn=lambda n: pytest.fail("promotion must not spawn"),
            policy=pol, disagg=dis)
        # the promotion version gate must read the FRESH member docs,
        # not these handle caches — an adopted or idle handle's cache
        # is seeded at construction and can stay None/stale forever,
        # which would block promotion on phantom skew. Poison the
        # caches to prove the gate no longer consults them.
        rpf._active_version = "vSTALE-pool"
        rd0._active_version = "vSTALE-promotee"
        rng = np.random.RandomState(13)
        # backlog: pile slow work straight onto the prefill specialist
        load = [rpf.submit(p, max_new_tokens=24)
                for p in _prompts(rng, (12,) * 8)]
        deadline = time.time() + 30
        while time.time() < deadline:
            s = (rpf.member() or {}).get("serving", {})
            if (s.get("queue_depth", 0) or 0) \
                    + (s.get("pending", 0) or 0) > 3:
                break
            time.sleep(0.05)
        ctl.tick()
        cs = ctl.stats()
        assert cs["promotions"] == 1 and cs["promoted"] == ["pd0"], cs
        assert [p.name for p in dis.prefill] == ["pp", "pd0"]
        assert router.healthy_replicas() == ["pd1"]
        deadline = time.time() + 10
        while time.time() < deadline:
            d = read_member(fd, "pd0")
            if d and d.get("role") == "prefill":
                break
            time.sleep(0.02)
        assert d["role"] == "prefill"
        # the handoff path keeps landing with the promoted pool, and
        # tokens stay bitwise the monolithic oracle
        long_p = rng.randint(1, V, size=40).astype(np.int32)
        want = local.generate(long_p, 8)
        got = dis.submit(long_p, max_new_tokens=8).result(timeout=240)
        assert np.array_equal(want, got)
        assert dis.stats()["handoffs"] >= 1, dis.stats()
        # relief: drain the injected backlog, demote on the next tick
        for f in load:
            f.result(timeout=300)
        deadline = time.time() + 30
        while time.time() < deadline:
            s = (rpf.member() or {}).get("serving", {})
            if not (s.get("queue_depth", 0) or s.get("pending", 0)):
                break
            time.sleep(0.05)
        ctl.tick()
        cs = ctl.stats()
        assert cs["demotions"] == 1 and cs["promoted"] == [], cs
        assert [p.name for p in dis.prefill] == ["pp"]
        assert sorted(router.healthy_replicas()) == ["pd0", "pd1"]
        # the demoted replica takes decode traffic again
        p = rng.randint(1, V, size=9).astype(np.int32)
        outs = [router.submit(p, max_new_tokens=4).result(timeout=120)
                for _ in range(4)]
        assert all(np.array_equal(outs[0], o) for o in outs)
        router.shutdown()
    finally:
        if mon is not None:
            mon.stop()
        for ag in ags:
            ag.shutdown()
    local.shutdown()
    assert fleet_threads_alive() == 0


# -- controller death + adoption --------------------------------------------

def test_controller_death_keeps_serving_and_respawn_adopts(tmp_path):
    """`fleet/controller_tick` chaos kills the controller thread
    mid-reconcile. The fleet KEEPS SERVING (the router/monitor own the
    data path); a respawned controller finds the members in the fleet
    directory and ADOPTS them — including one that joined while no
    controller was alive — instead of respawning anything."""
    fd = str(tmp_path)
    m = _model()
    local = DecodeScheduler(m, name="adopt_oracle", **SCHED).start()
    ags = {n: ReplicaAgent(DecodeScheduler(m, name=n, **SCHED),
                           fleet_dir=fd, name=n, beat_s=0.1).start()
           for n in ("c0", "c1")}
    mon = None
    ctl = ctl2 = None
    try:
        d0, _ = wait_for_members(fd, ["c0", "c1"], timeout_s=120)
        r0 = RemoteReplica(d0, fleet_dir=fd)
        router = Router([r0], max_failovers=4).start()
        mon = FleetMonitor([r0], fleet_dir=fd, every_s=0.1,
                           stale_s=10.0).start()
        pol = ScalePolicy(up_ticks=99, down_ticks=99)
        boom = lambda n: pytest.fail("adoption must not spawn")  # noqa: E731
        chaos.arm({"sites": {"fleet/controller_tick": [
            {"kind": "permanent", "nth": 3}]}})
        ctl = FleetController(router, mon, fleet_dir=fd, spawn=boom,
                              policy=pol, every_s=0.02)
        ctl.start()
        # start() adopted the member the router didn't know about
        assert ctl.stats()["adopted"] == 1
        assert sorted(router.healthy_replicas()) == ["c0", "c1"]
        deadline = time.time() + 30
        while not ctl.dead and time.time() < deadline:
            time.sleep(0.02)
        assert ctl.dead, "the armed permanent tick fault must kill it"
        assert len(chaos.fires()) >= 1
        # controller death is NOT a fleet death: traffic still serves,
        # bitwise, across both members
        rng = np.random.RandomState(17)
        prompts = _prompts(rng, (7, 12, 15, 20))
        want = [local.generate(p, 8) for p in prompts]
        futs = [router.submit(p, max_new_tokens=8) for p in prompts]
        for w, f in zip(want, futs):
            assert np.array_equal(w, f.result(timeout=240))
        assert router.stats()["completed"] == len(prompts)
        # a member joins while NO controller is alive...
        ags["c2"] = ReplicaAgent(
            DecodeScheduler(m, name="c2", **SCHED), fleet_dir=fd,
            name="c2", beat_s=0.1).start()
        wait_for_members(fd, ["c2"], timeout_s=120)
        # ...and the respawned controller adopts it from the directory
        chaos.disarm()
        ctl2 = FleetController(router, mon, fleet_dir=fd, spawn=boom,
                               policy=pol)
        assert ctl2.adopt() == 1
        assert ctl2.stats()["adopted"] == 1
        assert sorted(router.healthy_replicas()) == ["c0", "c1", "c2"]
        got = router.submit(prompts[0],
                            max_new_tokens=8).result(timeout=240)
        assert np.array_equal(want[0], got)
        router.shutdown()
    finally:
        if ctl is not None:
            ctl.stop()
        if ctl2 is not None:
            ctl2.stop()
        if mon is not None:
            mon.stop()
        for ag in ags.values():
            ag.shutdown()
    local.shutdown()
    assert controller_threads_alive() == 0
    assert fleet_threads_alive() == 0


def test_restart_spawn_names_never_collide_with_adopted(tmp_path):
    """A successor controller's spawn-id counter restarts at 0; its
    first scale-up must NOT reuse the name of a predecessor-spawned
    replica it adopted — the new agent would clobber the live replica's
    member file, be drained as a duplicate, and its final beat would
    falsely retire the healthy original. Names with a member file still
    in the directory (live OR final) are skipped too."""
    fd = str(tmp_path)
    m = _model()
    agents = {}

    def spawn(name):
        ag = ReplicaAgent(DecodeScheduler(m, name=name, **SCHED),
                          fleet_dir=fd, name=name, beat_s=0.1).start()
        agents[name] = ag
        doc, = wait_for_members(fd, [name], timeout_s=60)
        return RemoteReplica(doc, fleet_dir=fd).start()

    # a PREDECESSOR controller spawned auto0 (still live) and auto1
    # (retired cleanly — its FINAL member file remains), then died
    r0 = spawn("auto0")
    ag1 = ReplicaAgent(DecodeScheduler(m, name="auto1", **SCHED),
                       fleet_dir=fd, name="auto1", beat_s=0.1).start()
    wait_for_members(fd, ["auto1"], timeout_s=60)
    ag1.shutdown()
    assert read_member(fd, "auto1").get("final")
    router = Router([r0], max_failovers=4).start()
    mon = FleetMonitor([r0], fleet_dir=fd, every_s=0.1,
                       stale_s=10.0).start()
    pol = ScalePolicy(min_replicas=1, max_replicas=3, up_ticks=99,
                      down_ticks=99, cooldown_s=0.0)
    ctl = FleetController(router, mon, fleet_dir=fd, spawn=spawn,
                          policy=pol)
    try:
        assert ctl.adopt() == 0   # auto0 already routed; auto1 is final
        ctl._scale_up()
        cs = ctl.stats()
        assert cs["scale_ups"] == 1 and cs["spawn_failed"] == 0, cs
        assert "auto2" in agents, \
            f"spawn must skip taken names auto0/auto1: {sorted(agents)}"
        assert sorted(router.healthy_replicas()) == ["auto0", "auto2"]
        # the predecessor replica's member file was never clobbered
        d0 = read_member(fd, "auto0")
        assert d0 and not d0.get("dead") and not d0.get("final")
        assert int(d0["port"]) == r0.port
        # both serve, bitwise alike
        rng = np.random.RandomState(23)
        p = rng.randint(1, V, size=9).astype(np.int32)
        outs = [router.submit(p, max_new_tokens=6).result(timeout=120)
                for _ in range(4)]
        assert all(np.array_equal(outs[0], o) for o in outs)
        router.shutdown()
    finally:
        mon.stop()
        for ag in agents.values():
            ag.shutdown()
    assert fleet_threads_alive() == 0


def test_retired_victim_is_not_readopted_mid_drain(tmp_path):
    """The retiring agent acks its shutdown op BEFORE writing the final
    member beat; an adopt() landing in that window must not re-register
    the victim — its name is held out of adoption until its member doc
    goes terminal."""
    fd = str(tmp_path)
    m = _model()
    agents = {}

    def spawn(name):
        ag = ReplicaAgent(DecodeScheduler(m, name=name, **SCHED),
                          fleet_dir=fd, name=name, beat_s=0.05).start()
        agents[name] = ag
        doc, = wait_for_members(fd, [name], timeout_s=60)
        return RemoteReplica(doc, fleet_dir=fd).start()

    seed = spawn("seed0")
    auto = spawn("auto0")
    router = Router([seed, auto], max_failovers=4).start()
    mon = FleetMonitor([seed, auto], fleet_dir=fd, every_s=0.1,
                       stale_s=10.0).start()
    pol = ScalePolicy(up_ticks=99, down_ticks=99, cooldown_s=0.0)
    ctl = FleetController(router, mon, fleet_dir=fd, spawn=spawn,
                          policy=pol)
    try:
        ctl._scale_down()   # prefers the controller-prefixed auto0
        assert router.healthy_replicas() == ["seed0"]
        assert ctl.stats()["scale_downs"] == 1
        # hammer adoption through the ack→final-beat window
        deadline = time.time() + 60
        while time.time() < deadline:
            assert ctl.adopt() == 0, \
                "retiring member must not be re-adopted"
            d = read_member(fd, "auto0")
            if d and (d.get("final") or d.get("dead")):
                break
            time.sleep(0.002)
        else:
            pytest.fail("auto0 never reached a terminal beat")
        assert ctl.adopt() == 0   # the terminal doc clears the ledger
        assert "auto0" not in ctl._retired
        assert ctl.stats()["adopted"] == 0
        assert router.healthy_replicas() == ["seed0"]
        router.shutdown()
    finally:
        mon.stop()
        for ag in agents.values():
            ag.shutdown()
    assert fleet_threads_alive() == 0


def test_spawn_failure_mid_reconcile_changes_nothing(tmp_path):
    """`fleet/spawn` chaos: a spawn that dies mid-launch is a counted,
    cooldown-gated retry — the router's membership is untouched, no
    request is lost, and the NEXT eligible spawn succeeds."""
    fd = str(tmp_path)
    m = _model()
    agents = {}

    def spawn(name):
        ag = ReplicaAgent(DecodeScheduler(m, name=name, **SCHED),
                          fleet_dir=fd, name=name, beat_s=0.1).start()
        agents[name] = ag
        doc, = wait_for_members(fd, [name], timeout_s=60)
        return RemoteReplica(doc, fleet_dir=fd).start()

    r0 = spawn("s0")
    router = Router([r0], max_failovers=4).start()
    mon = FleetMonitor([r0], fleet_dir=fd, every_s=0.1,
                       stale_s=10.0).start()
    pol = ScalePolicy(min_replicas=1, max_replicas=2, queue_high=1.0,
                      up_ticks=1, down_ticks=99, cooldown_s=0.0)
    ctl = FleetController(router, mon, fleet_dir=fd, spawn=spawn,
                          policy=pol)
    try:
        # the first spawn attempt dies on the chaos seam
        chaos.arm({"sites": {"fleet/spawn": [
            {"kind": "transient", "nth": 1}]}})
        rng = np.random.RandomState(19)
        futs = [router.submit(p, max_new_tokens=12)
                for p in _prompts(rng, (9,) * 12)]
        deadline = time.time() + 120
        while ctl.stats()["spawn_failed"] < 1 \
                and time.time() < deadline:
            ctl.tick()
            time.sleep(0.02)
        cs = ctl.stats()
        assert cs["spawn_failed"] == 1 and cs["scale_ups"] == 0, cs
        assert router.healthy_replicas() == ["s0"], \
            "a failed spawn must change NOTHING"
        assert len(chaos.fires()) == 1
        # the retry (chaos exhausted) lands the replica — the load must
        # stay pressed, or the burst drains and the controller rightly
        # stops wanting a second replica
        deadline = time.time() + 240
        while len(router.stats()["replicas"]) < 2 \
                and time.time() < deadline:
            if sum(router.stats()["queue_depth"].values()) < 4 \
                    and len(futs) < 200:
                futs.extend(router.submit(p, max_new_tokens=12)
                            for p in _prompts(rng, (9,) * 4))
            ctl.tick()
            time.sleep(0.05)
        assert len(router.stats()["replicas"]) == 2
        for f in futs:
            f.result(timeout=300)
        assert router.stats()["completed"] == len(futs), router.stats()
        router.shutdown()
    finally:
        chaos.disarm()
        mon.stop()
        for ag in agents.values():
            ag.shutdown()
    assert fleet_threads_alive() == 0
