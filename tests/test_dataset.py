"""Dataset / transformer / vision / text pipeline tests (modeled on the
reference's dataset + transform specs)."""
import numpy as np
import pytest

from bigdl_tpu.dataset import (DataSet, Sample, MiniBatch, PaddingParam,
                               SampleToMiniBatch, mnist, cifar, text)
from bigdl_tpu.transform import vision
from bigdl_tpu.utils.table import Table


def test_sample_to_minibatch():
    samples = [Sample(np.ones((3, 4)) * i, np.int64(i)) for i in range(10)]
    batches = list(SampleToMiniBatch(4)(samples))
    assert len(batches) == 3
    assert batches[0].size() == 4
    assert batches[2].size() == 2
    assert batches[0].get_input().shape == (4, 3, 4)
    assert batches[0].get_target().shape == (4,)
    sliced = batches[0].slice(2, 2)
    assert sliced.size() == 2
    assert np.allclose(sliced.get_input()[0], 1.0)


def test_minibatch_padding():
    samples = [Sample(np.ones((t,)) * t, np.int64(t)) for t in (3, 5, 2)]
    pad = PaddingParam(padding_value=-1.0)
    mb = MiniBatch.from_samples(samples, feature_padding=pad)
    assert mb.get_input().shape == (3, 5)
    assert mb.get_input()[0, 3] == -1.0
    pad_fixed = PaddingParam(padding_value=0.0, fixed_length=8)
    mb = MiniBatch.from_samples(samples, feature_padding=pad_fixed)
    assert mb.get_input().shape == (3, 8)


def test_dataset_shuffle_iterate():
    """One authoritative shuffle: the epoch order is a pure function of
    (seed, epoch) — data() alone never reshuffles, shuffle() advances."""
    ds = DataSet.array(list(range(100)))
    a = list(ds.data(train=True))
    b = list(ds.data(train=True))
    assert sorted(a) == list(range(100))
    assert a == b  # no hidden second shuffle inside data()
    ds.shuffle()
    c = list(ds.data(train=True))
    assert sorted(c) == list(range(100))
    assert c != a  # shuffle() is what advances the order

    # reproducible per seed: a fresh dataset replays the same epochs
    ds2 = DataSet.array(list(range(100)))
    assert list(ds2.data(train=True)) == a
    ds2.shuffle()
    assert list(ds2.data(train=True)) == c
    # eval order is insertion order, untouched by shuffles
    assert list(ds2.data(train=False)) == list(range(100))


def test_multi_feature_samples():
    samples = [Sample([np.ones(3), np.zeros(2)], np.int64(1))
               for _ in range(4)]
    mb = MiniBatch.from_samples(samples)
    assert isinstance(mb.get_input(), Table)
    assert mb.get_input()[1].shape == (4, 3)
    assert mb.get_input()[2].shape == (4, 2)


def test_mnist_cifar_loaders():
    imgs, labels = mnist.load(n_synthetic=64)
    assert imgs.shape == (64, 28, 28) and imgs.dtype == np.uint8
    assert labels.min() >= 1 and labels.max() <= 10
    x = mnist.normalize(imgs)
    assert abs(float(x.mean())) < 1.5

    ci, cl = cifar.load(n_synthetic=32)
    assert ci.shape == (32, 3, 32, 32)
    s = cifar.to_samples(ci, cl)
    assert s[0].feature().shape == (3, 32, 32)


def test_cifar_binary_roundtrip(tmp_path):
    imgs, labels = cifar.synthetic(16)
    rec = np.concatenate([labels[:, None].astype(np.uint8),
                          imgs.reshape(16, -1)], axis=1)
    path = tmp_path / "data_batch_1.bin"
    rec.tofile(str(path))
    i2, l2 = cifar.load(str(tmp_path), train=True)
    assert np.array_equal(i2, imgs)
    assert np.array_equal(l2, labels + 1)


def test_text_pipeline():
    corpus = ["the cat sat on the mat. the dog ran.",
              "a cat and a dog."]
    sents = list(text.SentenceSplitter()(corpus))
    assert len(sents) == 3
    toks = list(text.SentenceTokenizer()(sents))
    assert toks[0][0] == "the"
    d = text.Dictionary(toks)
    assert d.get_index("the") > 0
    assert d.get_index("zebra") == 0  # unk
    labeled = list(text.TextToLabeledSentence(d)(toks))
    assert len(labeled[0].data) == len(labeled[0].label)
    samples = list(text.LabeledSentenceToSample(fixed_length=8)(labeled))
    assert samples[0].feature().shape == (8,)


def test_vision_transforms():
    img = np.random.rand(20, 24, 3).astype(np.float32) * 255
    out = vision.Resize(10, 12).transform_image(img, np.random.RandomState(0))
    assert out.shape == (10, 12, 3)
    out = vision.CenterCrop(8, 6).transform_image(img,
                                                  np.random.RandomState(0))
    assert out.shape == (6, 8, 3)
    out = vision.RandomCrop(8, 6).transform_image(img,
                                                  np.random.RandomState(0))
    assert out.shape == (6, 8, 3)
    out = vision.HFlip().transform_image(img, np.random.RandomState(0))
    assert np.allclose(out[:, ::-1], img)
    out = vision.ChannelNormalize(10, 20, 30, 2, 2, 2).transform_image(
        img, np.random.RandomState(0))
    assert np.allclose(out, (img - [10, 20, 30]) / 2.0, atol=1e-5)
    out = vision.MatToTensor().transform_image(img, np.random.RandomState(0))
    assert out.shape == (3, 20, 24)
    out = vision.RandomResizedCrop(16).transform_image(
        img, np.random.RandomState(0))
    assert out.shape == (16, 16, 3)
    out = vision.Lighting().transform_image(img / 255.0,
                                            np.random.RandomState(0))
    assert out.shape == img.shape
    out = vision.ColorJitter().transform_image(img, np.random.RandomState(0))
    assert out.shape == img.shape
    out = vision.Expand(max_expand_ratio=2.0).transform_image(
        img, np.random.RandomState(1))
    assert out.shape[0] >= 20 and out.shape[1] >= 24


def test_vision_pipeline_compose():
    imgs = [np.random.rand(28, 28, 3).astype(np.float32) * 255
            for _ in range(4)]
    pipeline = vision.Resize(16, 16) | vision.RandomFlip(0.5) | \
        vision.ChannelNormalize(127, 127, 127, 50, 50, 50) | \
        vision.MatToTensor()
    out = list(pipeline(imgs))
    assert len(out) == 4
    assert out[0].shape == (3, 16, 16)


def test_ptb_synthetic_markov():
    sents = text.ptb_synthetic(n_sentences=10, vocab=50)
    assert len(sents) == 10
    assert all(t.startswith("w") for t in sents[0])


# ---- COCO segmentation (poly/RLE) ------------------------------------------

class TestSegmentation:
    def test_rle_roundtrip(self):
        from bigdl_tpu.dataset import segmentation as S
        rng = np.random.RandomState(3)
        for _ in range(5):
            mask = (rng.rand(13, 17) > 0.6).astype(np.uint8)
            counts = S.rle_encode(mask)
            assert sum(counts) == mask.size
            back = S.rle_decode(counts, 13, 17)
            assert np.array_equal(back, mask)

    def test_rle_counts_convention(self):
        from bigdl_tpu.dataset import segmentation as S
        # 2x3 mask, column-major: col0=[1,0], col1=[0,0], col2=[1,1]
        mask = np.array([[1, 0, 1], [0, 0, 1]], np.uint8)
        assert S.rle_encode(mask) == [0, 1, 3, 2]

    def test_rle_string_roundtrip(self):
        from bigdl_tpu.dataset import segmentation as S
        rng = np.random.RandomState(7)
        for _ in range(10):
            mask = (rng.rand(20, 20) > 0.5).astype(np.uint8)
            counts = S.rle_encode(mask)
            s = S.rle_to_string(counts)
            assert s.isascii()
            assert S.rle_from_string(s) == counts

    def test_rle_string_known_value(self):
        from bigdl_tpu.dataset import segmentation as S
        # delta coding: [6, 1, 40, 4, 5] encodes like pycocotools
        counts = [6, 1, 40, 4, 5]
        assert S.rle_from_string(S.rle_to_string(counts)) == counts

    def test_area_bbox(self):
        from bigdl_tpu.dataset import segmentation as S
        mask = np.zeros((10, 12), np.uint8)
        mask[2:5, 3:8] = 1  # y 2..4, x 3..7
        counts = S.rle_encode(mask)
        assert S.rle_area(counts) == 15
        assert np.array_equal(S.rle_to_bbox(counts, 10, 12), [3, 2, 5, 3])

    def test_merge_iou(self):
        from bigdl_tpu.dataset import segmentation as S
        a = np.zeros((8, 8), np.uint8); a[:4] = 1
        b = np.zeros((8, 8), np.uint8); b[2:6] = 1
        ca, cb = S.rle_encode(a), S.rle_encode(b)
        union = S.rle_decode(S.rle_merge([ca, cb], 8, 8), 8, 8)
        inter = S.rle_decode(S.rle_merge([ca, cb], 8, 8, intersect=True), 8, 8)
        assert union.sum() == 6 * 8 and inter.sum() == 2 * 8
        assert abs(S.rle_iou(ca, cb, 8, 8) - (16 / 48)) < 1e-9

    def test_polygon_rasterize_square(self):
        from bigdl_tpu.dataset import segmentation as S
        # axis-aligned square covering pixel centers x,y in [2,6)
        ring = [2, 2, 6, 2, 6, 6, 2, 6]
        mask = S.rasterize_polygon(np.array(ring, float), 9, 9)
        expect = np.zeros((9, 9), np.uint8)
        expect[2:6, 2:6] = 1
        assert np.array_equal(mask, expect)

    def test_polygon_triangle_area(self):
        from bigdl_tpu.dataset import segmentation as S
        ring = [0, 0, 20, 0, 0, 20]  # right triangle, area 200
        mask = S.rasterize_polygon(np.array(ring, float), 24, 24)
        assert abs(int(mask.sum()) - 200) <= 12  # boundary rounding

    def test_poly_masks_api(self):
        from bigdl_tpu.dataset import PolyMasks, RLEMasks
        pm = PolyMasks([[[1, 1, 5, 1, 5, 5, 1, 5]],
                        [[0, 0, 3, 0, 3, 3], [4, 4, 7, 4, 7, 7]]], 8, 8)
        assert len(pm) == 2
        rle = pm.to_rle()
        assert isinstance(rle, RLEMasks) and len(rle) == 2
        dense = pm.decode()
        assert dense.shape == (2, 8, 8)
        assert dense[0].sum() == 16  # 4x4 interior
        strs = rle.to_strings()
        back = RLEMasks.from_strings(strs, 8, 8)
        assert np.array_equal(back.decode(), dense)
        assert np.array_equal(back.area(), rle.area())

    def test_rle_masks_empty_and_full(self):
        from bigdl_tpu.dataset import segmentation as S
        zero = np.zeros((5, 5), np.uint8)
        full = np.ones((5, 5), np.uint8)
        assert S.rle_encode(zero) == [25]
        assert S.rle_encode(full) == [0, 25]
        assert np.array_equal(S.rle_to_bbox(S.rle_encode(zero), 5, 5),
                              np.zeros(4))
        assert np.array_equal(S.rle_to_bbox(S.rle_encode(full), 5, 5),
                              [0, 0, 5, 5])


# ---------------------------------------------------------------------------
# TFRecord + tf.Example (nn/tf/ParsingOps.scala parity)
# ---------------------------------------------------------------------------


def test_tfrecord_example_roundtrip(tmp_path):
    from bigdl_tpu.dataset.tfrecord import (make_example, parse_example,
                                            read_tfrecords, write_tfrecords)
    rng = np.random.RandomState(0)
    feats = rng.randn(12).astype(np.float32)
    recs = [make_example({"features": feats, "label": np.int64(3),
                          "name": b"row0"})]
    path = str(tmp_path / "data.tfrecord")
    write_tfrecords(path, recs)
    got = [parse_example(r) for r in read_tfrecords(path)]
    assert len(got) == 1
    assert np.allclose(got[0]["features"], feats, atol=1e-6)
    assert got[0]["label"][0] == 3
    assert got[0]["name"][0] == b"row0"


def test_tfrecord_crc_detects_corruption(tmp_path):
    import pytest
    from bigdl_tpu.dataset.tfrecord import (make_example, read_tfrecords,
                                            write_tfrecords)
    path = str(tmp_path / "bad.tfrecord")
    write_tfrecords(path, [make_example({"x": np.float32(1.0)})])
    raw = bytearray(open(path, "rb").read())
    raw[-6] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(IOError):
        list(read_tfrecords(path))


def test_tfrecord_dataset_trains(tmp_path):
    """TFRecord → Samples → one epoch of LeNet-ish training."""
    from bigdl_tpu.dataset.tfrecord import (load_tfrecord_dataset,
                                            make_example, write_tfrecords)
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu import nn
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.optim.trigger import max_epoch
    rng = np.random.RandomState(1)
    recs = []
    for i in range(32):
        x = rng.randn(1 * 8 * 8).astype(np.float32)
        recs.append(make_example({"features": x,
                                  "label": np.int64(i % 2 + 1)}))
    path = str(tmp_path / "train.tfrecord")
    write_tfrecords(path, recs)
    samples = load_tfrecord_dataset(path, feature_shape=(1, 8, 8))
    assert len(samples) == 32
    model = nn.Sequential(nn.View(64), nn.Linear(64, 2), nn.LogSoftMax())
    Optimizer(model=model, training_set=DataSet.array(samples),
              criterion=nn.ClassNLLCriterion(),
              optim_method=SGD(learningrate=0.1),
              end_trigger=max_epoch(1), batch_size=16).optimize()


def test_tfrecord_negative_ints_and_truncation(tmp_path):
    import pytest
    from bigdl_tpu.dataset.tfrecord import (make_example, parse_example,
                                            read_tfrecords, write_tfrecords)
    ex = parse_example(make_example({"label": np.int64(-5),
                                     "ids": np.array([-1, 2, -3])}))
    assert ex["label"][0] == -5
    assert np.array_equal(ex["ids"], [-1, 2, -3])
    # truncated payload raises even with verify_crc=False
    path = str(tmp_path / "trunc.tfrecord")
    write_tfrecords(path, [make_example({"x": np.float32(1.0)})])
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-8])
    with pytest.raises(IOError):
        list(read_tfrecords(path, verify_crc=False))


def test_vision_transform_longtail():
    """ChannelOrder/Crop/RandomCropper/RandomResize/RandomAlterAspect
    (augmentation/*.scala parity additions)."""
    from bigdl_tpu.transform.vision import (ChannelOrder, Crop,
                                            RandomCropper, RandomResize,
                                            RandomAlterAspect)
    img = np.arange(8 * 10 * 3, dtype=np.float32).reshape(8, 10, 3)
    rng = np.random.RandomState(0)

    out = ChannelOrder().transform_image(img, rng)
    assert np.allclose(out[..., 0], img[..., 2])

    out = Crop((0.25, 0.25, 0.75, 0.75)).transform_image(img, rng)
    assert out.shape == (4, 5, 3)
    out = Crop((1, 2, 7, 6), normalized=False).transform_image(img, rng)
    assert out.shape == (4, 6, 3)

    out = RandomCropper(4, 4, mirror=True).transform_image(img, rng)
    assert out.shape == (4, 4, 3)
    out = RandomCropper(4, 4, cropper_method="center",
                        mirror=False).transform_image(img, rng)
    assert np.allclose(out, img[2:6, 3:7])

    out = RandomResize(4, 6).transform_image(img, rng)
    assert min(out.shape[:2]) in (4, 5, 6)

    out = RandomAlterAspect(size=5).transform_image(img, rng)
    assert out.shape[:2] == (5, 5)


def test_tfrecord_legacy_crc_detected(tmp_path):
    """Files written by pre-round-2 builds (rotate-only CRC, no kMaskDelta)
    raise an actionable 'legacy' error, not generic corruption."""
    import struct
    from bigdl_tpu.visualization.event_writer import crc32c
    from bigdl_tpu.dataset.tfrecord import read_tfrecords

    def legacy_crc(data):
        crc = crc32c(data)
        return ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF

    p = tmp_path / "legacy.tfrecord"
    data = b"payload"
    head = struct.pack("<Q", len(data))
    with open(p, "wb") as f:
        f.write(head + struct.pack("<I", legacy_crc(head)))
        f.write(data + struct.pack("<I", legacy_crc(data)))
    with pytest.raises(IOError, match="legacy"):
        list(read_tfrecords(str(p), use_native=False))
    # verify_crc=False reads it fine (the documented escape hatch)
    assert list(read_tfrecords(str(p), verify_crc=False,
                               use_native=False)) == [data]


def test_image_frame_pipeline():
    """ImageFrame carrier: array -> transform -> MTImageFeatureToBatch
    (VERDICT r2 missing #4; reference transform/vision/image/
    ImageFrame.scala + MTImageFeatureToBatch.scala)."""
    from bigdl_tpu.transform import (ImageFrame, LocalImageFrame,
                                     MTImageFeatureToBatch)
    rng = np.random.RandomState(0)
    imgs = [rng.rand(40 + i, 36, 3).astype(np.float32) for i in range(7)]
    frame = ImageFrame.array(imgs, labels=[i % 3 + 1 for i in range(7)])
    assert isinstance(frame, LocalImageFrame) and len(frame) == 7
    assert frame.features[0]["originalSize"] == (40, 36, 3)

    t = vision.Resize(32, 32) | vision.ChannelNormalize(0.5, 0.5, 0.5)
    frame2 = frame.transform(t)
    assert len(frame2) == 7
    assert frame2.features[0]["image"].shape == (32, 32, 3)
    assert frame.features[0]["image"].shape == (40, 36, 3)  # original kept

    batches = list(MTImageFeatureToBatch(32, 32, batch_size=4)(frame2))
    assert [b.input.shape for b in batches] == [(4, 3, 32, 32),
                                                (3, 3, 32, 32)]
    assert batches[0].target.shape == (4,)

    # bbox-carrying path (the SSD/FRCNN pipeline shape)
    for f in frame2.features:
        f["boundingBox"] = np.array([[1.0, 2.0, 10.0, 12.0]])
    wb = list(MTImageFeatureToBatch(32, 32, batch_size=4,
                                    with_bbox=True)(frame2))
    assert len(wb[0].bboxes) == 4 and wb[0].bboxes[0].shape == (1, 4)


def test_image_frame_read_folder(tmp_path):
    """ImageFrame.read over a labeled folder (ImageNet convention) using
    whatever decoder the environment has; falls back to synthetic skip if
    no JPEG encode path exists to build the fixture."""
    from bigdl_tpu.transform import ImageFrame
    try:
        from bigdl_tpu.native import jpeg_available
        if not jpeg_available():
            pytest.skip("no native libjpeg in this environment")
        import bigdl_tpu.native as native
        if not hasattr(native, "encode_jpeg"):
            pytest.skip("native lib has no JPEG encoder")
    except ImportError:
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(0)
    for cls in ("a", "b"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(2):
            img = (rng.rand(24, 24, 3) * 255).astype(np.uint8)
            (d / f"{i}.jpg").write_bytes(native.encode_jpeg(img))
    frame = ImageFrame.read(str(tmp_path), with_label=True)
    assert len(frame) == 4
    labels = sorted(f["label"] for f in frame)
    assert labels == [1, 1, 2, 2]
    assert frame.features[0]["image"].shape == (24, 24, 3)


# ---------------------------------------------------------------------------
# datamining RowTransformer (r4) + SentenceBiPadding
# ---------------------------------------------------------------------------


def test_row_transformer_atomic_and_numeric():
    from bigdl_tpu.dataset import RowTransformer, ColToTensor, ColsToNumeric
    rows = [{"age": 30, "height": 1.8, "name": "ann", "vip": True},
            {"age": 40, "height": 1.6, "name": "bob", "vip": False}]
    rt = RowTransformer.atomic(["age", "name", "vip"])
    tables = list(rt(rows))
    assert len(tables) == 2
    t = tables[0]
    assert t["age"].tolist() == [30.0]
    assert t["name"].tolist() == ["ann"]
    assert t["vip"].tolist() == [1.0]        # bool -> 0/1
    # numeric(): all columns -> one vector under "all" (numeric rows only)
    num_rows = [[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]
    nt = list(RowTransformer.numeric()(num_rows))
    np.testing.assert_allclose(nt[1]["all"], [4.0, 5.0, 6.0])
    # numeric map: schema_key -> selected fields
    rt2 = RowTransformer.numeric({"phys": ["height", "age"]})
    t2 = next(iter(rt2(rows)))
    np.testing.assert_allclose(t2["phys"], [1.8, 30.0])
    # mixed
    rt3 = RowTransformer.atomic_with_numeric(
        ["name"], {"feat": ["age", "height"]})
    t3 = next(iter(rt3(rows)))
    assert t3["name"].tolist() == ["ann"]
    np.testing.assert_allclose(t3["feat"], [30.0, 1.8])


def test_row_transformer_index_selection_and_errors():
    import pytest as _pytest
    from bigdl_tpu.dataset import RowTransformer, ColToTensor, ColsToNumeric
    # index-addressed plain sequences
    rt = RowTransformer([ColsToNumeric("sel", indices=[2, 0])])
    t = next(iter(rt([[7.0, 8.0, 9.0]])))
    np.testing.assert_allclose(t["sel"], [9.0, 7.0])
    # duplicate keys rejected
    with _pytest.raises(ValueError, match="replicated"):
        RowTransformer([ColToTensor("k", 0), ColToTensor("k", 1)])
    # out-of-bound indices rejected when row_size given
    with _pytest.raises(ValueError, match="out of bound"):
        RowTransformer([ColsToNumeric("s", indices=[5])], row_size=3)
    # field-name selection on a nameless row fails clearly
    rt2 = RowTransformer([ColsToNumeric("s", field_names=["a"])])
    with _pytest.raises(ValueError, match="field name"):
        next(iter(rt2([[1.0]])))


def test_row_transformer_pandas_to_dlframes():
    """transform_frame feeds dlframes: the keyed example end-to-end."""
    import pandas as pd
    from bigdl_tpu.dataset import RowTransformer
    rng = np.random.RandomState(0)
    df = pd.DataFrame({
        "a": rng.randn(64).astype(np.float32),
        "b": rng.randn(64).astype(np.float32),
        "label": rng.randint(0, 2, 64) + 1.0,
    })
    rt = RowTransformer.numeric({"features": ["a", "b"],
                                 "label": ["label"]})
    cols = rt.transform_frame(df)
    assert cols["features"].shape == (64, 2)
    assert cols["label"].shape == (64, 1)
    from bigdl_tpu.dlframes import DLClassifier
    from bigdl_tpu import nn
    est = DLClassifier(nn.Sequential(nn.Linear(2, 8), nn.ReLU(),
                                     nn.Linear(8, 2), nn.LogSoftMax()),
                       nn.ClassNLLCriterion(), [2])
    est.set_batch_size(16).set_max_epoch(3).set_learning_rate(1e-2)
    model = est.fit(cols)
    out = model.transform({"features": cols["features"]})
    assert len(out["prediction"]) == 64


def test_sentence_bipadding():
    from bigdl_tpu.dataset.text import SentenceBiPadding
    out = list(SentenceBiPadding()(["hello world", "bye"]))
    assert out == ["SENTENCESTART hello world SENTENCEEND",
                   "SENTENCESTART bye SENTENCEEND"]
    out2 = list(SentenceBiPadding("<s>", "</s>")(["x"]))
    assert out2 == ["<s> x </s>"]
    # matches the pyspark-parity free function
    from bigdl_tpu.dataset.sentence import sentences_bipadding
    assert out[0] == sentences_bipadding("hello world")


def test_table_named_keys_pytree():
    """string-keyed Table entries flow through jax pytree ops."""
    import jax
    from bigdl_tpu.utils.table import Table
    t = Table(np.ones((2,)))
    t["x"] = np.zeros((3,))
    leaves = jax.tree_util.tree_leaves(t)
    assert len(leaves) == 2
    t2 = jax.tree_util.tree_map(lambda a: a + 1, t)
    np.testing.assert_allclose(t2["x"], np.ones((3,)))
    np.testing.assert_allclose(t2[1], 2 * np.ones((2,)))
    assert "x" in t2 and list(t2.keys()) == ["x"]
