"""Dataset / transformer / vision / text pipeline tests (modeled on the
reference's dataset + transform specs)."""
import numpy as np

from bigdl_tpu.dataset import (DataSet, Sample, MiniBatch, PaddingParam,
                               SampleToMiniBatch, mnist, cifar, text)
from bigdl_tpu.transform import vision
from bigdl_tpu.utils.table import Table


def test_sample_to_minibatch():
    samples = [Sample(np.ones((3, 4)) * i, np.int64(i)) for i in range(10)]
    batches = list(SampleToMiniBatch(4)(samples))
    assert len(batches) == 3
    assert batches[0].size() == 4
    assert batches[2].size() == 2
    assert batches[0].get_input().shape == (4, 3, 4)
    assert batches[0].get_target().shape == (4,)
    sliced = batches[0].slice(2, 2)
    assert sliced.size() == 2
    assert np.allclose(sliced.get_input()[0], 1.0)


def test_minibatch_padding():
    samples = [Sample(np.ones((t,)) * t, np.int64(t)) for t in (3, 5, 2)]
    pad = PaddingParam(padding_value=-1.0)
    mb = MiniBatch.from_samples(samples, feature_padding=pad)
    assert mb.get_input().shape == (3, 5)
    assert mb.get_input()[0, 3] == -1.0
    pad_fixed = PaddingParam(padding_value=0.0, fixed_length=8)
    mb = MiniBatch.from_samples(samples, feature_padding=pad_fixed)
    assert mb.get_input().shape == (3, 8)


def test_dataset_shuffle_iterate():
    ds = DataSet.array(list(range(100)))
    a = list(ds.data(train=True))
    b = list(ds.data(train=True))
    assert sorted(a) == list(range(100))
    assert a != b  # shuffled differently


def test_multi_feature_samples():
    samples = [Sample([np.ones(3), np.zeros(2)], np.int64(1))
               for _ in range(4)]
    mb = MiniBatch.from_samples(samples)
    assert isinstance(mb.get_input(), Table)
    assert mb.get_input()[1].shape == (4, 3)
    assert mb.get_input()[2].shape == (4, 2)


def test_mnist_cifar_loaders():
    imgs, labels = mnist.load(n_synthetic=64)
    assert imgs.shape == (64, 28, 28) and imgs.dtype == np.uint8
    assert labels.min() >= 1 and labels.max() <= 10
    x = mnist.normalize(imgs)
    assert abs(float(x.mean())) < 1.5

    ci, cl = cifar.load(n_synthetic=32)
    assert ci.shape == (32, 3, 32, 32)
    s = cifar.to_samples(ci, cl)
    assert s[0].feature().shape == (3, 32, 32)


def test_cifar_binary_roundtrip(tmp_path):
    imgs, labels = cifar.synthetic(16)
    rec = np.concatenate([labels[:, None].astype(np.uint8),
                          imgs.reshape(16, -1)], axis=1)
    path = tmp_path / "data_batch_1.bin"
    rec.tofile(str(path))
    i2, l2 = cifar.load(str(tmp_path), train=True)
    assert np.array_equal(i2, imgs)
    assert np.array_equal(l2, labels + 1)


def test_text_pipeline():
    corpus = ["the cat sat on the mat. the dog ran.",
              "a cat and a dog."]
    sents = list(text.SentenceSplitter()(corpus))
    assert len(sents) == 3
    toks = list(text.SentenceTokenizer()(sents))
    assert toks[0][0] == "the"
    d = text.Dictionary(toks)
    assert d.get_index("the") > 0
    assert d.get_index("zebra") == 0  # unk
    labeled = list(text.TextToLabeledSentence(d)(toks))
    assert len(labeled[0].data) == len(labeled[0].label)
    samples = list(text.LabeledSentenceToSample(fixed_length=8)(labeled))
    assert samples[0].feature().shape == (8,)


def test_vision_transforms():
    img = np.random.rand(20, 24, 3).astype(np.float32) * 255
    out = vision.Resize(10, 12).transform_image(img, np.random.RandomState(0))
    assert out.shape == (10, 12, 3)
    out = vision.CenterCrop(8, 6).transform_image(img,
                                                  np.random.RandomState(0))
    assert out.shape == (6, 8, 3)
    out = vision.RandomCrop(8, 6).transform_image(img,
                                                  np.random.RandomState(0))
    assert out.shape == (6, 8, 3)
    out = vision.HFlip().transform_image(img, np.random.RandomState(0))
    assert np.allclose(out[:, ::-1], img)
    out = vision.ChannelNormalize(10, 20, 30, 2, 2, 2).transform_image(
        img, np.random.RandomState(0))
    assert np.allclose(out, (img - [10, 20, 30]) / 2.0, atol=1e-5)
    out = vision.MatToTensor().transform_image(img, np.random.RandomState(0))
    assert out.shape == (3, 20, 24)
    out = vision.RandomResizedCrop(16).transform_image(
        img, np.random.RandomState(0))
    assert out.shape == (16, 16, 3)
    out = vision.Lighting().transform_image(img / 255.0,
                                            np.random.RandomState(0))
    assert out.shape == img.shape
    out = vision.ColorJitter().transform_image(img, np.random.RandomState(0))
    assert out.shape == img.shape
    out = vision.Expand(max_expand_ratio=2.0).transform_image(
        img, np.random.RandomState(1))
    assert out.shape[0] >= 20 and out.shape[1] >= 24


def test_vision_pipeline_compose():
    imgs = [np.random.rand(28, 28, 3).astype(np.float32) * 255
            for _ in range(4)]
    pipeline = vision.Resize(16, 16) | vision.RandomFlip(0.5) | \
        vision.ChannelNormalize(127, 127, 127, 50, 50, 50) | \
        vision.MatToTensor()
    out = list(pipeline(imgs))
    assert len(out) == 4
    assert out[0].shape == (3, 16, 16)


def test_ptb_synthetic_markov():
    sents = text.ptb_synthetic(n_sentences=10, vocab=50)
    assert len(sents) == 10
    assert all(t.startswith("w") for t in sents[0])
