"""Detection heads: anchors, NMS, PriorBox, Proposal, DetectionOutput*, RoiAlign.

Oracles are independent numpy re-implementations of the reference semantics
(nn/Nms.scala, nn/Anchor.scala, BboxUtil.scala), so the jax kernels are
checked against straight-line scalar code, not against themselves.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.table import Table


# ---------------------------------------------------------------- oracles --

def np_iou(a, b, normalized=False):
    off = 0.0 if normalized else 1.0
    iw = min(a[2], b[2]) - max(a[0], b[0]) + off
    ih = min(a[3], b[3]) - max(a[1], b[1]) + off
    if iw <= 0 or ih <= 0:
        return 0.0
    inter = iw * ih
    area_a = (a[2] - a[0] + off) * (a[3] - a[1] + off)
    area_b = (b[2] - b[0] + off) * (b[3] - b[1] + off)
    return inter / (area_a + area_b - inter)


def np_greedy_nms(scores, boxes, thresh, normalized=False):
    order = np.argsort(-scores, kind="stable")
    keep, suppressed = [], np.zeros(len(scores), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if not suppressed[j] and j != i and \
                    np_iou(boxes[i], boxes[j], normalized) > thresh:
                suppressed[j] = True
    return np.array(keep, np.int64)


def random_boxes(n, seed, size=100.0):
    rng = np.random.RandomState(seed)
    x1 = rng.uniform(0, size, n)
    y1 = rng.uniform(0, size, n)
    w = rng.uniform(5, 40, n)
    h = rng.uniform(5, 40, n)
    boxes = np.stack([x1, y1, x1 + w, y1 + h], 1).astype(np.float32)
    scores = rng.uniform(0.01, 1.0, n).astype(np.float32)
    return boxes, scores


# ------------------------------------------------------------------- tests --

def test_nms_matches_numpy_oracle():
    for seed in range(5):
        boxes, scores = random_boxes(40, seed)
        got = nn.Nms().nms(scores, boxes, 0.5)
        want = np_greedy_nms(scores, boxes, 0.5)
        assert np.array_equal(np.sort(got), np.sort(want))


def test_nms_fast_score_thresh_and_topk():
    boxes, scores = random_boxes(50, 7)
    got = nn.Nms().nms_fast(scores, boxes, 0.5, score_thresh=0.4, topk=10,
                            normalized=True)
    # every kept score passes the threshold
    assert np.all(scores[got] >= 0.4)
    # keeping among the top-10 candidates only
    top10 = set(np.argsort(-scores, kind="stable")[:10])
    assert set(got.tolist()) <= top10
    # oracle on the surviving candidate set
    cand = sorted(top10, key=lambda i: -scores[i])
    keep, supp = [], set()
    for i in cand:
        if i in supp or scores[i] < 0.4:
            continue
        keep.append(i)
        for j in cand:
            if j not in supp and j != i and \
                    np_iou(boxes[i], boxes[j], True) > 0.5:
                supp.add(j)
    assert sorted(got.tolist()) == sorted(keep)


def test_nms_mask_is_jittable():
    boxes, scores = random_boxes(16, 3)
    f = jax.jit(lambda b, s: nn.nms_mask(b, s, iou_thresh=0.5))
    order, keep = f(boxes, scores)
    got = np.asarray(order)[np.asarray(keep)]
    want = np_greedy_nms(scores, boxes, 0.5)
    assert np.array_equal(np.sort(got), np.sort(want))


def test_basic_anchors_faster_rcnn_values():
    # canonical py-faster-rcnn anchors for ratios 0.5,1,2 scales 8,16,32
    a = nn.generate_basic_anchors([0.5, 1.0, 2.0], [8.0, 16.0, 32.0])
    want = np.array([
        [-84., -40., 99., 55.],
        [-176., -88., 191., 103.],
        [-360., -184., 375., 199.],
        [-56., -56., 71., 71.],
        [-120., -120., 135., 135.],
        [-248., -248., 263., 263.],
        [-36., -80., 51., 95.],
        [-80., -168., 95., 183.],
        [-168., -344., 183., 359.]], np.float32)
    assert np.allclose(a, want)


def test_anchor_grid_shift_order():
    anc = nn.Anchor([1.0], [1.0])
    all_a = anc.generate_anchors(width=3, height=2, feat_stride=16.0)
    assert all_a.shape == (6, 4)
    base = all_a[0]
    # x varies fastest
    assert np.allclose(all_a[1], base + [16, 0, 16, 0])
    assert np.allclose(all_a[3], base + [0, 16, 0, 16])


def test_bbox_transform_inv_and_clip():
    boxes = np.array([[0., 0., 9., 19.]], np.float32)  # w=10 h=20
    deltas = np.array([[0.1, -0.2, np.log(2.0), 0.0]], np.float32)
    out = np.asarray(nn.bbox_transform_inv(boxes, deltas))
    cx, cy = 0 + 10 / 2 + 0.1 * 10, 0 + 20 / 2 - 0.2 * 20
    assert np.allclose(out[0], [cx - 10, cy - 10, cx + 10, cy + 10], atol=1e-5)
    clipped = np.asarray(nn.clip_boxes(out, 15.0, 12.0))
    assert clipped[0, 0] >= 0 and clipped[0, 2] <= 11 and clipped[0, 3] <= 14


def test_decode_boxes_variance():
    priors = np.array([[0.1, 0.1, 0.3, 0.3]], np.float32)
    var = np.array([[0.1, 0.1, 0.2, 0.2]], np.float32)
    deltas = np.array([[1.0, 0.5, 0.0, 0.0]], np.float32)
    out = np.asarray(nn.decode_boxes(priors, var, deltas))
    pw = ph = 0.2
    cx = 0.2 + 0.1 * 1.0 * pw
    cy = 0.2 + 0.1 * 0.5 * ph
    assert np.allclose(out[0], [cx - pw / 2, cy - ph / 2,
                                cx + pw / 2, cy + ph / 2], atol=1e-6)


def test_priorbox_shape_and_values():
    pb = nn.PriorBox([30.0], max_sizes=[60.0], aspect_ratios=[2.0],
                     is_flip=True, is_clip=False,
                     variances=[0.1, 0.1, 0.2, 0.2], img_h=300, img_w=300)
    feat = jnp.zeros((1, 3, 2, 2))
    out = np.asarray(pb.forward(feat))
    # priors per cell: 1 (min) + 1 (max) + 2 (ar 2, 1/2) = 4
    assert out.shape == (1, 2, 2 * 2 * 4 * 4)
    boxes = out[0, 0].reshape(-1, 4)
    # first cell centre = (0.5*150, 0.5*150) = (75, 75); first prior min_size 30
    assert np.allclose(boxes[0] * 300.0, [60., 60., 90., 90.], atol=1e-4)
    # second prior: sqrt(30*60)
    s = np.sqrt(30.0 * 60.0) / 2
    assert np.allclose(boxes[1] * 300.0, [75 - s, 75 - s, 75 + s, 75 + s],
                       atol=1e-4)
    var = out[0, 1].reshape(-1, 4)
    assert np.allclose(var[5], [0.1, 0.1, 0.2, 0.2])


def test_proposal_outputs_valid_rois():
    rng = np.random.RandomState(0)
    A, H, W = 3, 4, 5
    # fg/bg scores are softmax outputs in the reference → positive
    scores = rng.rand(1, 2 * A, H, W).astype(np.float32)
    deltas = (rng.randn(1, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 80.0, 1.0, 1.0]], np.float32)
    prop = nn.Proposal(pre_nms_topn=50, post_nms_topn=10,
                       ratios=[0.5, 1.0, 2.0], scales=[1.0])
    prop.evaluate()
    out = np.asarray(prop.forward(Table(jnp.asarray(scores),
                                        jnp.asarray(deltas),
                                        jnp.asarray(im_info))))
    assert out.ndim == 2 and out.shape[1] == 5 and out.shape[0] <= 10
    assert np.all(out[:, 0] == 0)
    assert np.all(out[:, 1] >= 0) and np.all(out[:, 3] <= 79)
    assert np.all(out[:, 2] >= 0) and np.all(out[:, 4] <= 63)
    # proposals wide/tall enough survive the min-size filter
    assert np.all(out[:, 3] - out[:, 1] + 1 >= 16)
    assert np.all(out[:, 4] - out[:, 2] + 1 >= 16)


def test_detection_output_ssd():
    n_priors, n_classes = 8, 3
    rng = np.random.RandomState(1)
    priors = np.zeros((1, 2, n_priors * 4), np.float32)
    grid = np.linspace(0.05, 0.7, n_priors, dtype=np.float32)
    pb = np.stack([grid, grid, grid + 0.2, grid + 0.2], 1)
    priors[0, 0] = pb.reshape(-1)
    priors[0, 1] = np.tile([0.1, 0.1, 0.2, 0.2], n_priors)
    loc = np.zeros((2, n_priors * 4), np.float32)  # deltas 0 → boxes = priors
    conf = rng.randn(2, n_priors * n_classes).astype(np.float32)
    det = nn.DetectionOutputSSD(n_classes=n_classes, nms_thresh=0.45,
                                conf_thresh=0.01, keep_topk=5)
    det.evaluate()
    out = np.asarray(det.forward(Table(jnp.asarray(loc), jnp.asarray(conf),
                                       jnp.asarray(priors))))
    assert out.shape[0] == 2
    for i in range(2):
        num = int(out[i, 0])
        assert 0 <= num <= 5
        dets = out[i, 1:1 + num * 6].reshape(num, 6)
        assert np.all(dets[:, 0] >= 1)  # no background label
        assert np.all((dets[:, 1] > 0) & (dets[:, 1] <= 1))
        # boxes are decoded priors
        for d in dets:
            assert np.any(np.all(np.isclose(pb, d[2:6], atol=1e-5), axis=1))


def test_detection_output_ssd_training_passthrough():
    det = nn.DetectionOutputSSD(n_classes=3)
    det.training()
    t = Table(jnp.zeros((1, 4)), jnp.zeros((1, 3)), jnp.zeros((1, 2, 4)))
    assert det.forward(t) is t


def test_detection_output_frcnn():
    n, n_classes = 6, 3
    rng = np.random.RandomState(2)
    rois = np.concatenate([np.zeros((n, 1), np.float32),
                           random_boxes(n, 3, 50.0)[0]], axis=1)
    deltas = (rng.randn(n, 4 * n_classes) * 0.05).astype(np.float32)
    scores = np.abs(rng.rand(n, n_classes)).astype(np.float32)
    scores /= scores.sum(1, keepdims=True)
    im_info = np.array([[100.0, 100.0, 1.0, 1.0]], np.float32)
    det = nn.DetectionOutputFrcnn(n_classes=n_classes, thresh=0.05)
    det.evaluate()
    out = np.asarray(det.forward(Table(
        jnp.asarray(im_info), jnp.asarray(rois), jnp.asarray(deltas),
        jnp.asarray(scores))))
    num = int(out[0, 0])
    assert out.shape == (1, 1 + num * 6)
    dets = out[0, 1:].reshape(num, 6)
    assert np.all(dets[:, 0] >= 1)
    assert np.all(dets[:, 1] > 0.05)


def test_bbox_vote_weighted_average():
    nms_boxes = np.array([[0., 0., 10., 10.]], np.float32)
    all_boxes = np.array([[0., 0., 10., 10.], [1., 1., 11., 11.],
                          [50., 50., 60., 60.]], np.float32)
    all_scores = np.array([0.8, 0.4, 0.9], np.float32)
    s, b = nn.bbox_vote(np.array([0.8], np.float32), nms_boxes,
                        all_scores, all_boxes)
    want = (0.8 * all_boxes[0] + 0.4 * all_boxes[1]) / 1.2
    assert np.allclose(b[0], want, atol=1e-5)


def test_roi_align_constant_map():
    # constant feature map → every pooled value equals that constant
    feats = jnp.full((1, 2, 8, 8), 3.5)
    rois = jnp.asarray([[0, 1.0, 1.0, 6.0, 6.0]], jnp.float32)
    ra = nn.RoiAlign(pooled_w=3, pooled_h=3, spatial_scale=1.0)
    out = np.asarray(ra.forward(Table(feats, rois)))
    assert out.shape == (1, 2, 3, 3)
    assert np.allclose(out, 3.5, atol=1e-6)


def test_roi_align_linear_gradient_map():
    # f(y, x) = x → pooled values should increase along x, constant along y
    H = W = 16
    fm = jnp.broadcast_to(jnp.arange(W, dtype=jnp.float32)[None, :], (H, W))
    feats = fm[None, None]
    rois = jnp.asarray([[0, 2.0, 2.0, 13.0, 13.0]], jnp.float32)
    ra = nn.RoiAlign(pooled_w=4, pooled_h=4, sampling_ratio=2)
    out = np.asarray(ra.forward(Table(feats, rois)))[0, 0]
    assert np.all(np.diff(out, axis=1) > 0)
    assert np.allclose(out[0], out[-1], atol=1e-5)


def test_roi_align_jit_and_grad():
    feats = jnp.asarray(np.random.RandomState(0).rand(1, 1, 8, 8),
                        jnp.float32)
    rois = jnp.asarray([[0, 1.0, 1.0, 6.0, 6.0]], jnp.float32)
    ra = nn.RoiAlign(pooled_w=2, pooled_h=2)

    def loss(f):
        out, _ = ra.apply({}, {}, Table(f, rois))
        return jnp.sum(out ** 2)

    g = jax.jit(jax.grad(loss))(feats)
    assert g.shape == feats.shape and np.isfinite(np.asarray(g)).all()
