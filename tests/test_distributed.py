"""Distributed-path tests on the 8-virtual-CPU-device mesh (modeled on the
reference's DistriOptimizerSpec / AllReduceParameterSpec)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.optim import (LocalOptimizer, DistriOptimizer, SGD, Adam,
                             max_iteration, Top1Accuracy)
from bigdl_tpu.parallel import (make_mesh, data_parallel_mesh, ring_attention,
                                AllReduceParameter)
from bigdl_tpu.parallel.ring_attention import make_ring_attention
from utils import allclose


def _mnist_ds(n=256):
    imgs, labels = mnist.load(n_synthetic=n)
    return DataSet.array(mnist.to_samples(imgs, labels))


def test_eight_devices_present():
    assert len(jax.devices()) == 8


def _train(optimizer_cls, seed=7, iters=8, **kw):
    from bigdl_tpu.utils import engine
    engine.set_seed(seed)
    np.random.seed(seed)
    model = LeNet5(10)
    ds = _mnist_ds()
    opt = optimizer_cls(model, ds, nn.ClassNLLCriterion(),
                        SGD(learningrate=0.05), max_iteration(iters),
                        batch_size=64, **kw)
    opt.optimize()
    return model, opt


def test_distri_matches_local():
    """Same seed/data → DistriOptimizer must match LocalOptimizer numerics
    (the all-reduce of shard gradients == full-batch gradient)."""
    m_local, _ = _train(LocalOptimizer)
    mesh = data_parallel_mesh(8)
    m_dist, _ = _train(DistriOptimizer, mesh=mesh)
    for a, b in zip(jax.tree_util.tree_leaves(m_local.params),
                    jax.tree_util.tree_leaves(m_dist.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
            np.abs(np.asarray(a) - np.asarray(b)).max()


def test_zero1_matches_replicated():
    mesh = data_parallel_mesh(8)
    m_rep, _ = _train(DistriOptimizer, mesh=mesh)
    m_z1, _ = _train(DistriOptimizer, mesh=mesh, parameter_mode="zero1")
    for a, b in zip(jax.tree_util.tree_leaves(m_rep.params),
                    jax.tree_util.tree_leaves(m_z1.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_zero1_adam_trains():
    mesh = data_parallel_mesh(8)
    from bigdl_tpu.utils import engine
    engine.set_seed(3)
    model = LeNet5(10)
    ds = _mnist_ds()
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          Adam(learningrate=0.01), max_iteration(15),
                          batch_size=64, mesh=mesh, parameter_mode="zero1")
    opt.optimize()
    res = model.evaluate_dataset(ds, [Top1Accuracy()], 64)
    acc, _ = res[0].result()
    assert acc > 0.5, acc


def test_zero1_bf16_compression():
    mesh = data_parallel_mesh(8)
    model, opt = _train(DistriOptimizer, mesh=mesh, parameter_mode="zero1",
                        compress="bf16")
    assert np.isfinite(opt.optim_method.state["loss"])


def test_wire_dtype_fp32_master_accumulation_oracle():
    """The ulp-equivalence harness for the wire_dtype knob: the sharded
    all_to_all wire (compressed slices, owner sums in f32) must compute
    EXACTLY bf16-round → f32 sum over shards → /n → f32 update. The
    oracle runs the same math unsharded; SGD is elementwise, so the
    slice-wise sharded update and the full-vector oracle agree bitwise
    when the wire math does."""
    from bigdl_tpu.parallel.allreduce import AllReduceParameter
    from bigdl_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = data_parallel_mesh(8)
    n = 8
    rng = np.random.RandomState(5)
    params = {"w": jnp.asarray(rng.randn(37, 5).astype(np.float32)),
              "b": jnp.asarray(rng.randn(11).astype(np.float32))}
    arp = AllReduceParameter(SGD(learningrate=0.1), mesh,
                             wire_dtype="bf16")
    flat_w, opt_state = arp.prepare(params)
    per_dev = rng.randn(n, arp.flat.padded_size).astype(np.float32)

    def step(g_local, w_full, st):
        return arp.update(g_local[0], w_full, st, 0.1)

    new_full, _ = shard_map(
        step, mesh=mesh,
        in_specs=(P("data"), P(), arp.state_specs()),
        out_specs=(P(), arp.state_specs()), check_vma=False)(
        jnp.asarray(per_dev), flat_w, opt_state)

    # oracle: round the wire once, accumulate in f32, update in f32
    g_wire = jnp.asarray(per_dev).astype(jnp.bfloat16)
    g_mean = jnp.sum(g_wire.astype(jnp.float32), axis=0) / n
    want = flat_w - 0.1 * g_mean
    assert np.array_equal(np.asarray(new_full), np.asarray(want)), \
        np.abs(np.asarray(new_full) - np.asarray(want)).max()
    # and the rounding is REAL (the knob is not a no-op): an f32-wire
    # oracle differs
    f32_mean = jnp.sum(jnp.asarray(per_dev), axis=0) / n
    assert not np.array_equal(np.asarray(new_full),
                              np.asarray(flat_w - 0.1 * f32_mean))


def test_wire_dtype_trains_and_halves_gradient_wire_bytes():
    """End to end: wire_dtype='bf16' trains (close to the f32-wire run)
    and the per-dispatch byte accounting shows the gradient leg at HALF
    the f32 wire — the FP16CompressedTensor claim, measured."""
    from bigdl_tpu import observability as obs
    obs.enable()
    try:
        mesh = data_parallel_mesh(8)
        model, opt = _train(DistriOptimizer, mesh=mesh, iters=6,
                            parameter_mode="zero1", wire_dtype="bf16")
        assert np.isfinite(opt.optim_method.state["loss"])
        reg = obs.registry()
        wire = reg.get("collective/grad_wire_traced_bytes").value
        padded = reg.get("allreduce/param_elems")  # gauge exists
        assert padded is not None
        assert wire > 0 and wire % 2 == 0
        # bytes_per_step gauge prices the bf16 gradient leg + f32 gather
        per_step = reg.get("allreduce/bytes_per_step").value
        n_elems = opt._arp.flat.padded_size
        assert per_step == n_elems * (2 + 4)
        # the traced wire is exactly 2 bytes/elem per traced step — half
        # the 4 bytes/elem an f32 psum_scatter ships
        assert wire % (2 * n_elems) == 0
    finally:
        obs.disable()
    m_f32, _ = _train(DistriOptimizer, mesh=data_parallel_mesh(8), iters=6,
                      parameter_mode="zero1")
    for a, b in zip(jax.tree_util.tree_leaves(m_f32.params),
                    jax.tree_util.tree_leaves(model.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=2e-2)


def test_wire_dtype_and_compress_mutually_exclusive():
    from bigdl_tpu.parallel.allreduce import AllReduceParameter
    mesh = data_parallel_mesh(8)
    with pytest.raises(ValueError, match="wire_dtype"):
        AllReduceParameter(SGD(), mesh, compress="bf16", wire_dtype="bf16")
    with pytest.raises(ValueError, match="wire_dtype"):
        AllReduceParameter(SGD(), mesh, wire_dtype="int8")


def test_ring_attention_matches_full():
    mesh = make_mesh((8,), ("seq",))
    B, H, T, D = 2, 4, 64, 16
    rng = np.random.RandomState(0)
    q = rng.randn(B, H, T, D).astype(np.float32)
    k = rng.randn(B, H, T, D).astype(np.float32)
    v = rng.randn(B, H, T, D).astype(np.float32)
    from bigdl_tpu.nn.attention import dot_product_attention
    full = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v))
    ring = make_ring_attention(mesh, "seq", causal=False)(q, k, v)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-4)


def test_ring_attention_causal_matches_full():
    mesh = make_mesh((8,), ("seq",))
    B, H, T, D = 1, 2, 64, 8
    rng = np.random.RandomState(1)
    q, k, v = [rng.randn(B, H, T, D).astype(np.float32) for _ in range(3)]
    from bigdl_tpu.nn.attention import dot_product_attention, causal_mask
    full = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), causal_mask(T))
    ring = make_ring_attention(mesh, "seq", causal=True)(q, k, v)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-4)


def test_collectives():
    from bigdl_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from bigdl_tpu.parallel import collective as C
    mesh = data_parallel_mesh(8)
    x = np.arange(8, dtype=np.float32)

    def f(xs):
        return C.psum(xs, "data")
    out = shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)
    assert np.allclose(np.asarray(out), np.full(8, x.sum()))

    def g(xs):
        return C.ppermute_ring(xs, "data", 1)
    out = shard_map(g, mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)
    assert np.allclose(np.asarray(out), np.roll(x, 1))


def test_tp_sharding_linear():
    """Tensor-parallel Linear pair via sharding constraints compiles and
    matches the unsharded result."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh((8,), ("model",))
    rng = np.random.RandomState(0)
    x = rng.randn(4, 16).astype(np.float32)
    w1 = rng.randn(32, 16).astype(np.float32)
    w2 = rng.randn(16, 32).astype(np.float32)

    def f(x, w1, w2):
        h = jax.nn.relu(x @ w1.T)
        return h @ w2.T

    expect = f(x, w1, w2)
    xs = jax.device_put(x, NamedSharding(mesh, P()))
    w1s = jax.device_put(w1, NamedSharding(mesh, P("model", None)))
    w2s = jax.device_put(w2, NamedSharding(mesh, P(None, "model")))
    got = jax.jit(f)(xs, w1s, w2s)
    assert np.allclose(np.asarray(got), np.asarray(expect), atol=1e-4)


# ---- failure detection & straggler metrics ---------------------------------

def test_probe_mesh_healthy():
    from bigdl_tpu.parallel import probe_mesh
    from bigdl_tpu.parallel.mesh import make_mesh
    mesh = make_mesh((8,), ("data",))
    r = probe_mesh(mesh, timeout_s=120.0)
    assert r.ok, r
    assert r.n_devices == 8


def test_probe_mesh_2d():
    from bigdl_tpu.parallel import probe_mesh
    from bigdl_tpu.parallel.mesh import make_mesh
    mesh = make_mesh((4, 2), ("data", "model"))
    r = probe_mesh(mesh, timeout_s=120.0)
    assert r.ok and r.n_devices == 8


def test_heartbeat_single_process():
    from bigdl_tpu.parallel import Heartbeat
    hb = Heartbeat(stale_after=2)
    for _ in range(4):
        assert hb.beat() == []


def test_straggler_monitor_analysis():
    from bigdl_tpu.parallel import StragglerMonitor
    rep = StragglerMonitor.analyze(np.array([0.10, 0.11, 0.09, 0.35]),
                                   threshold=1.5)
    assert rep["stragglers"] == [3]
    assert rep["imbalance"] > 3.0
    m = StragglerMonitor()
    for t in (0.1, 0.12, 0.11):
        m.record(t)
    rep = m.report()
    assert rep["stragglers"] == []
    assert abs(rep["median_s"] - rep["per_host_mean_s"][0]) < 1e-9


def test_nan_guard_keeps_params():
    # a poisoned batch must not corrupt parameters ('skip' policy)
    from bigdl_tpu import nn
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
    from bigdl_tpu.dataset import DataSet, Sample
    model = nn.Sequential(nn.Linear(4, 2))
    xs = np.random.randn(8, 4).astype(np.float32)
    xs[4] = np.nan  # poisoned sample
    samples = [Sample(xs[i], np.float32(i % 2 + 1)) for i in range(8)]
    opt = LocalOptimizer(model, DataSet.array(samples),
                         nn.CrossEntropyCriterion(), SGD(learningrate=0.1),
                         max_iteration(4), batch_size=2)
    opt.set_nan_policy("skip")
    opt.optimize()
    w = np.asarray(model.params["0"]["weight"])
    assert np.isfinite(w).all()
    assert opt.metrics.values.get("nan_skips")


def test_nan_resume_policy(tmp_path):
    from bigdl_tpu import nn
    from bigdl_tpu.optim import (LocalOptimizer, SGD, max_iteration,
                                 several_iteration)
    from bigdl_tpu.dataset import DataSet, Sample
    model = nn.Sequential(nn.Linear(4, 2))
    xs = np.random.randn(12, 4).astype(np.float32)
    xs[9] = np.inf
    samples = [Sample(xs[i], np.float32(i % 2 + 1)) for i in range(12)]
    opt = LocalOptimizer(model, DataSet.array(samples),
                         nn.CrossEntropyCriterion(), SGD(learningrate=0.1),
                         max_iteration(6), batch_size=2)
    opt.set_checkpoint(several_iteration(1), str(tmp_path))
    opt.set_nan_policy("resume")
    opt.optimize()
    assert np.isfinite(np.asarray(model.params["0"]["weight"])).all()
    assert opt.metrics.values.get("nan_resumes")


def test_zero1_nan_resume_and_checkpoint_layout(tmp_path):
    from bigdl_tpu import nn
    from bigdl_tpu.optim import (DistriOptimizer, SGD, max_iteration,
                                 several_iteration)
    from bigdl_tpu.dataset import DataSet, Sample
    from bigdl_tpu.parallel.mesh import make_mesh
    import pickle, os
    mesh = make_mesh((8,), ("data",))
    xs = np.random.randn(32, 6).astype(np.float32)
    # poison a sample that lands in the LAST batch of epoch 1 (the shuffle
    # is deterministic per (seed, epoch)), so checkpoints exist before the
    # NaN step and 'resume' has a snapshot to replay
    probe = DataSet.array(list(range(32)))
    probe.shuffle()
    bad = list(probe.data(train=True))[-1]
    xs[bad] = np.nan
    samples = [Sample(xs[i], np.float32(i % 3 + 1)) for i in range(32)]
    model = nn.Sequential(nn.Linear(6, 3))
    opt = DistriOptimizer(model, DataSet.array(samples),
                          nn.CrossEntropyCriterion(), SGD(learningrate=0.1),
                          max_iteration(4), batch_size=8, mesh=mesh,
                          parameter_mode="zero1")
    opt.set_checkpoint(several_iteration(1), str(tmp_path))
    opt.set_nan_policy("resume")
    opt.optimize()
    w = np.asarray(model.params["0"]["weight"])
    assert w.shape == (3, 6) and np.isfinite(w).all()
    # checkpoint stores the UNFLATTENED tree (cross-mode resumable)
    snap = [f for f in os.listdir(tmp_path) if f.endswith(".bigdl")][0]
    payload = pickle.load(open(os.path.join(tmp_path, snap), "rb"))
    assert payload["params"]["0"]["weight"].shape == (3, 6)


def test_make_mesh_topology_aware_and_hybrid():
    """make_mesh uses the physical-topology layout when covering all
    devices; make_hybrid_mesh builds the ICI x DCN split (single-host: DCN
    axes of size 1)."""
    from bigdl_tpu.parallel import make_mesh, make_hybrid_mesh
    m = make_mesh((4, 2), ("data", "model"))
    assert dict(m.shape) == {"data": 4, "model": 2}
    assert len({d.id for d in m.devices.flat}) == 8
    h = make_hybrid_mesh(ici_shape=(1, 8), dcn_shape=(1, 1),
                         axes=("data", "model"))
    assert dict(h.shape) == {"data": 1, "model": 8}


def test_distri_validation_and_summary_during_training(tmp_path):
    """set_validation + train/val summaries fire during DistriOptimizer
    training (zero1) and the event files are readable back."""
    from bigdl_tpu import nn
    from bigdl_tpu.optim.optimizer import DistriOptimizer
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.trigger import max_epoch, several_iteration
    from bigdl_tpu.optim.validation import Top1Accuracy, Loss
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.visualization import TrainSummary, ValidationSummary

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 6).astype(np.float32)
    ys = (xs[:, 0] > 0).astype(np.int32) + 1
    xs[ys == 2] += 1.5
    samples = [Sample(x, np.float32(y)) for x, y in zip(xs, ys)]
    ds = DataSet.array(samples)

    model = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2),
                          nn.LogSoftMax())
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          SGD(learningrate=0.2), max_epoch(3),
                          batch_size=32, parameter_mode="zero1")
    opt.set_validation(several_iteration(2), ds,
                       [Top1Accuracy(), Loss(nn.ClassNLLCriterion())], 32)
    ts = TrainSummary(str(tmp_path), "run1")
    vs = ValidationSummary(str(tmp_path), "run1")
    opt.set_train_summary(ts)
    opt.set_val_summary(vs)
    opt.optimize()

    scalars = ts.read_scalar("Loss")
    assert len(scalars) >= 3
    acc = vs.read_scalar("Top1Accuracy")
    assert acc, "validation summary empty"
    assert acc[-1][1] > 0.6, acc[-1]


def test_sparse_embedding_grad_allreduce_matches_dense_psum():
    """Parallax-style (ids, rows) exchange == dense psum of per-device
    scatter-added embedding gradients, including duplicate ids within
    and across shards."""
    from functools import partial
    from bigdl_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from bigdl_tpu.parallel import sparse_embedding_grad_allreduce

    V, H, B = 50, 8, 16            # 16 tokens per device, 8 devices
    rng = np.random.RandomState(0)
    ids = rng.randint(0, V, size=(8 * B,)).astype(np.int32)
    ids[:8] = ids[8]               # force duplicates across shards
    rows = rng.randn(8 * B, H).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))

    f = shard_map(partial(sparse_embedding_grad_allreduce, vocab_size=V,
                          axis="dp"),
                  mesh=mesh, in_specs=(P("dp"), P("dp", None)),
                  out_specs=P(), check_vma=False)
    out = np.asarray(jax.jit(f)(ids, rows))

    dense = np.zeros((V, H), np.float32)
    np.add.at(dense, ids, rows)
    np.testing.assert_allclose(out, dense / 8, atol=1e-5)

    def dense_psum_path(i, r):
        local = jnp.zeros((V, H), r.dtype).at[i].add(r)
        return jax.lax.psum(local, "dp") / 8

    g = shard_map(dense_psum_path, mesh=mesh,
                  in_specs=(P("dp"), P("dp", None)), out_specs=P(),
                  check_vma=False)
    np.testing.assert_allclose(out, np.asarray(jax.jit(g)(ids, rows)),
                               atol=1e-5)


def test_tensor_parallel_decode_matches_single_device():
    """Multi-chip serving path: generate (prefill + cached decode scan)
    jitted over TP-sharded params on a 1x8 'model' mesh emits exactly
    the single-device tokens — XLA inserts the per-layer psums from the
    transformer_tp_specs placement alone."""
    from jax.sharding import Mesh, NamedSharding
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.parallel import transformer_tp_specs

    model = TransformerLM(vocab_size=67, hidden_size=32, num_heads=8,
                          filter_size=64, num_layers=2, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(1).randint(1, 67, (2, 6)),
                      jnp.int32)
    want = np.asarray(model.generate(params, ids, max_new_tokens=8))

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("model",))
    specs = transformer_tp_specs(params)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    got = np.asarray(jax.jit(lambda p, x: model.generate(
        p, x, max_new_tokens=8))(sharded, ids))
    assert (got == want).all()


def test_fsdp_sharded_training_matches_replicated():
    """ZeRO-3/FSDP: params placed with fsdp_specs (each big leaf split
    over 'data', small leaves replicated) train step-for-step
    identically to replicated DP — XLA derives the all-gather /
    reduce-scatter schedule from placement; optimizer state created
    under jit inherits the sharded layout."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer_lm import lm_loss_chunked
    from bigdl_tpu.parallel import fsdp_specs
    from bigdl_tpu.optim import SGD

    model = TransformerLM(vocab_size=64, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=16)
    params, _ = model.init(jax.random.PRNGKey(0))
    optim = SGD(learningrate=0.1, momentum=0.9)
    x = jnp.asarray(np.random.RandomState(0).randint(1, 64, (8, 12)),
                    jnp.int32)
    y = jnp.asarray(np.random.RandomState(1).randint(1, 64, (8, 12)),
                    jnp.int32)

    def step(p, s, xb, yb):
        def loss_fn(q):
            h = model.hidden_states(q, xb, training=False)
            return lm_loss_chunked(h, q["embed"], yb, chunk=4)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        p, s = optim.update(grads, p, s, jnp.float32(0.1))
        return loss, p, s

    # replicated oracle (two steps)
    step_j = jax.jit(step)
    s0 = optim.init_state(params)
    l1, p_r, s_r = step_j(params, s0, x, y)
    l2, p_r, _ = step_j(p_r, s_r, x, y)

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    specs = fsdp_specs(params, mesh, min_elems=256)
    # at least one big leaf actually got split
    assert any(s != P() for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda v: isinstance(v, P)))
    fp = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        params, specs)
    xb = jax.device_put(x, NamedSharding(mesh, P("data")))
    yb = jax.device_put(y, NamedSharding(mesh, P("data")))
    sf = optim.init_state(fp)
    f1, p_f, s_f = step_j(fp, sf, xb, yb)
    f2, p_f, _ = step_j(p_f, s_f, xb, yb)

    np.testing.assert_allclose(float(l1), float(f1), rtol=1e-5)
    np.testing.assert_allclose(float(l2), float(f2), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_r),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_seq_sharded_decode_matches_single_device():
    """Long-context distributed serving: decode over a TIME-sharded KV
    cache (each device owns Tmax/8 positions) == the single-device
    cached path, across a multi-step generation loop that crosses
    shard boundaries — MHA and compact-GQA caches."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bigdl_tpu.parallel import make_seq_sharded_decoder
    import math as _math

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
    dec = make_seq_sharded_decoder(mesh, "seq")
    B, D, Tmax = 2, 16, 32                      # 4 positions per device
    rng = np.random.RandomState(0)

    for nH, kvH in [(4, 4), (4, 2)]:
        k_cache = jnp.zeros((B, kvH, Tmax, D), jnp.float32)
        v_cache = jnp.zeros((B, kvH, Tmax, D), jnp.float32)
        kc = jax.device_put(k_cache, NamedSharding(
            mesh, P(None, None, "seq", None)))
        vc = jax.device_put(v_cache, NamedSharding(
            mesh, P(None, None, "seq", None)))
        ks, vs = k_cache, v_cache               # single-device oracle
        step = jax.jit(dec)
        outs, oracle = [], []
        for pos in range(7):                    # crosses a shard edge
            q = jnp.asarray(rng.randn(B, nH, 1, D), jnp.float32)
            kt = jnp.asarray(rng.randn(B, kvH, 1, D), jnp.float32)
            vt = jnp.asarray(rng.randn(B, kvH, 1, D), jnp.float32)
            o, kc, vc = step(q, kt, vt, kc, vc, jnp.int32(pos))
            outs.append(np.asarray(o))

            ks = ks.at[:, :, pos].set(kt[:, :, 0])
            vs = vs.at[:, :, pos].set(vt[:, :, 0])
            g = nH // kvH
            ke = jnp.repeat(ks, g, 1) if g > 1 else ks
            ve = jnp.repeat(vs, g, 1) if g > 1 else vs
            s = jnp.einsum("bhqd,bhtd->bhqt", q, ke) / _math.sqrt(D)
            s = jnp.where(jnp.arange(Tmax)[None, None, None] <= pos,
                          s, -1e30)
            w = jax.nn.softmax(s, axis=-1)
            oracle.append(np.asarray(
                jnp.einsum("bhqt,bhtd->bhqd", w, ve)))
        np.testing.assert_allclose(np.concatenate(outs),
                                   np.concatenate(oracle),
                                   rtol=2e-5, atol=2e-5)
        # the cache really lives sharded: each device holds Tmax/8 slots
        assert kc.addressable_shards[0].data.shape[2] == Tmax // 8


def test_embedding_grad_rows_masks_duplicates():
    """The (ids, rows) extraction ships each id's summed local
    contribution exactly ONCE — duplicates after the first occurrence
    mask to zero, so the cross-shard scatter-add never double-counts."""
    from bigdl_tpu.nn.sparse import embedding_grad_rows
    V, H = 10, 4
    ids = jnp.asarray([3, 7, 3, 3], jnp.int32)
    g = jnp.zeros((V, H)).at[3].set(2.0).at[7].set(5.0)
    rows = np.asarray(embedding_grad_rows(g, ids))
    np.testing.assert_allclose(rows[0], 2.0)          # first occurrence
    np.testing.assert_allclose(rows[1], 5.0)
    np.testing.assert_allclose(rows[2:], 0.0)         # later ones masked
    dense = np.zeros((V, H), np.float32)
    np.add.at(dense, np.asarray(ids), rows)
    np.testing.assert_allclose(dense, np.asarray(g))


def test_distri_sparse_embedding_per_layer_selection():
    """ISSUE 12 satellite: DistriOptimizer(sparse_embedding=True)
    plumbs nn.sparse.sparse_embedding_grad_allreduce into a per-layer
    gradient-wire selection — the leading LookupTable ships (indices,
    value rows), every other layer the dense pmean — and the
    byte-accounting counters prove the sparse wire beats the dense
    all-reduce for the embedding while training matches the dense-
    exchange run."""
    from bigdl_tpu import observability as obs
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.utils import engine

    V, H, T, C, B = 512, 16, 4, 5, 64

    def make_model():
        m = nn.Sequential()
        m.add(nn.LookupTable(V, H))
        m.add(nn.TemporalMaxPooling(T))
        m.add(nn.Squeeze(2))
        m.add(nn.Linear(H, C))
        m.add(nn.LogSoftMax())
        return m

    rng = np.random.RandomState(3)
    x = rng.randint(1, V + 1, size=(256, T)).astype(np.float32)
    y = (rng.randint(0, C, size=(256,)) + 1).astype(np.float32)

    def train(sparse):
        engine.set_seed(7)
        np.random.seed(7)
        m = make_model()
        ds = DataSet.from_arrays(x, y)
        opt = DistriOptimizer(m, ds, nn.ClassNLLCriterion(),
                              SGD(learningrate=0.05), max_iteration(6),
                              batch_size=B, mesh=data_parallel_mesh(8),
                              sparse_embedding=sparse)
        opt.optimize()
        return m

    obs.enable()
    try:
        obs.registry().reset()
        m_sparse = train(True)
        reg = obs.registry()
        sparse_bytes = reg.get(
            "collective/sparse_grad_wire_traced_bytes").value
        dense_bytes = reg.get("collective/grad_dense_traced_bytes").value
        assert reg.get("collective/sparse_layers_selected").value == 1
    finally:
        obs.disable()
    m_dense = train(False)
    for a, b in zip(jax.tree_util.tree_leaves(m_dense.params),
                    jax.tree_util.tree_leaves(m_sparse.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
            np.abs(np.asarray(a) - np.asarray(b)).max()
    # the accounting gate: per dispatch the embedding ships
    # B_local*T*(H+1) elements instead of vocab*H — an order of
    # magnitude under the dense wire it replaced (and the OTHER layers'
    # dense legs stay tiny next to it)
    emb_dense_bytes = V * H * 4
    b_local = B // 8
    assert sparse_bytes == b_local * T * (H + 1) * 4
    assert sparse_bytes < emb_dense_bytes / 10
    assert dense_bytes < emb_dense_bytes                # non-embedding legs


def test_sparse_embedding_rejects_zero1_and_unembedded_models():
    with pytest.raises(ValueError, match="per-LAYER"):
        DistriOptimizer(LeNet5(10), _mnist_ds(), nn.ClassNLLCriterion(),
                        SGD(), max_iteration(1), batch_size=64,
                        mesh=data_parallel_mesh(8), parameter_mode="zero1",
                        sparse_embedding=True)
    opt = DistriOptimizer(LeNet5(10), _mnist_ds(), nn.ClassNLLCriterion(),
                          SGD(), max_iteration(1), batch_size=64,
                          mesh=data_parallel_mesh(8), sparse_embedding=True)
    with pytest.raises(ValueError, match="LookupTable"):
        opt._sparse_embedding_path()
    # a w_regularizer'd embedding is refused: weight decay's gradient
    # is DENSE over the vocab, which the (indices, values) wire can't
    # carry — silently dropping it would train different weights
    from bigdl_tpu.optim.regularizer import L2Regularizer
    reg_model = nn.Sequential()
    reg_model.add(nn.LookupTable(64, 8, w_regularizer=L2Regularizer(1e-4)))
    reg_model.add(nn.Squeeze(2))
    opt = DistriOptimizer(reg_model, _mnist_ds(), nn.ClassNLLCriterion(),
                          SGD(), max_iteration(1), batch_size=64,
                          mesh=data_parallel_mesh(8), sparse_embedding=True)
    with pytest.raises(ValueError, match="regulariz"):
        opt._sparse_embedding_path()


def test_sparse_embedding_auto_selection_and_escape_hatch():
    """ISSUE 18 satellite: the default ``sparse_embedding="auto"``
    selects the per-layer wire by itself exactly when the explicit
    opt-in would be accepted — and silently rides the dense path (no
    typed refusal) when the model has no leading LookupTable, the
    embedding is regularized, or the run is ZeRO-1. ``False`` is the
    explicit-off escape hatch."""
    from bigdl_tpu.optim.regularizer import L2Regularizer

    def mk(model, **kw):
        return DistriOptimizer(model, _mnist_ds(), nn.ClassNLLCriterion(),
                               SGD(), max_iteration(1), batch_size=64,
                               mesh=data_parallel_mesh(8), **kw)

    emb_model = nn.Sequential()
    emb_model.add(nn.LookupTable(64, 8))
    emb_model.add(nn.Squeeze(2))
    opt = mk(emb_model)
    assert opt.sparse_embedding == "auto"
    assert opt._sparse_embedding_enabled(), \
        "auto must select the wire for a clean leading-LookupTable model"
    assert not mk(emb_model,
                  sparse_embedding=False)._sparse_embedding_enabled()
    # not applicable -> auto degrades silently where True refuses typed
    assert not mk(LeNet5(10))._sparse_embedding_enabled()
    reg_model = nn.Sequential()
    reg_model.add(nn.LookupTable(64, 8, w_regularizer=L2Regularizer(1e-4)))
    reg_model.add(nn.Squeeze(2))
    assert not mk(reg_model)._sparse_embedding_enabled()
    # zero1 under auto: the ctor accepts and the dense flat wire runs
    # (only the EXPLICIT True is the per-layer-seam contract violation)
    opt = mk(emb_model, parameter_mode="zero1")
    assert not opt._sparse_embedding_enabled()
