"""Distributed-path tests on the 8-virtual-CPU-device mesh (modeled on the
reference's DistriOptimizerSpec / AllReduceParameterSpec)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.optim import (LocalOptimizer, DistriOptimizer, SGD, Adam,
                             max_iteration, Top1Accuracy)
from bigdl_tpu.parallel import (make_mesh, data_parallel_mesh, ring_attention,
                                AllReduceParameter)
from bigdl_tpu.parallel.ring_attention import make_ring_attention
from utils import allclose


def _mnist_ds(n=256):
    imgs, labels = mnist.load(n_synthetic=n)
    return DataSet.array(mnist.to_samples(imgs, labels))


def test_eight_devices_present():
    assert len(jax.devices()) == 8


def _train(optimizer_cls, seed=7, iters=8, **kw):
    from bigdl_tpu.utils import engine
    engine.set_seed(seed)
    np.random.seed(seed)
    model = LeNet5(10)
    ds = _mnist_ds()
    opt = optimizer_cls(model, ds, nn.ClassNLLCriterion(),
                        SGD(learningrate=0.05), max_iteration(iters),
                        batch_size=64, **kw)
    opt.optimize()
    return model, opt


def test_distri_matches_local():
    """Same seed/data → DistriOptimizer must match LocalOptimizer numerics
    (the all-reduce of shard gradients == full-batch gradient)."""
    m_local, _ = _train(LocalOptimizer)
    mesh = data_parallel_mesh(8)
    m_dist, _ = _train(DistriOptimizer, mesh=mesh)
    for a, b in zip(jax.tree_util.tree_leaves(m_local.params),
                    jax.tree_util.tree_leaves(m_dist.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
            np.abs(np.asarray(a) - np.asarray(b)).max()


def test_zero1_matches_replicated():
    mesh = data_parallel_mesh(8)
    m_rep, _ = _train(DistriOptimizer, mesh=mesh)
    m_z1, _ = _train(DistriOptimizer, mesh=mesh, parameter_mode="zero1")
    for a, b in zip(jax.tree_util.tree_leaves(m_rep.params),
                    jax.tree_util.tree_leaves(m_z1.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_zero1_adam_trains():
    mesh = data_parallel_mesh(8)
    from bigdl_tpu.utils import engine
    engine.set_seed(3)
    model = LeNet5(10)
    ds = _mnist_ds()
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          Adam(learningrate=0.01), max_iteration(15),
                          batch_size=64, mesh=mesh, parameter_mode="zero1")
    opt.optimize()
    res = model.evaluate_dataset(ds, [Top1Accuracy()], 64)
    acc, _ = res[0].result()
    assert acc > 0.5, acc


def test_zero1_bf16_compression():
    mesh = data_parallel_mesh(8)
    model, opt = _train(DistriOptimizer, mesh=mesh, parameter_mode="zero1",
                        compress="bf16")
    assert np.isfinite(opt.optim_method.state["loss"])


def test_ring_attention_matches_full():
    mesh = make_mesh((8,), ("seq",))
    B, H, T, D = 2, 4, 64, 16
    rng = np.random.RandomState(0)
    q = rng.randn(B, H, T, D).astype(np.float32)
    k = rng.randn(B, H, T, D).astype(np.float32)
    v = rng.randn(B, H, T, D).astype(np.float32)
    from bigdl_tpu.nn.attention import dot_product_attention
    full = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v))
    ring = make_ring_attention(mesh, "seq", causal=False)(q, k, v)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-4)


def test_ring_attention_causal_matches_full():
    mesh = make_mesh((8,), ("seq",))
    B, H, T, D = 1, 2, 64, 8
    rng = np.random.RandomState(1)
    q, k, v = [rng.randn(B, H, T, D).astype(np.float32) for _ in range(3)]
    from bigdl_tpu.nn.attention import dot_product_attention, causal_mask
    full = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), causal_mask(T))
    ring = make_ring_attention(mesh, "seq", causal=True)(q, k, v)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-4)


def test_collectives():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from bigdl_tpu.parallel import collective as C
    mesh = data_parallel_mesh(8)
    x = np.arange(8, dtype=np.float32)

    def f(xs):
        return C.psum(xs, "data")
    out = shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)
    assert np.allclose(np.asarray(out), np.full(8, x.sum()))

    def g(xs):
        return C.ppermute_ring(xs, "data", 1)
    out = shard_map(g, mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)
    assert np.allclose(np.asarray(out), np.roll(x, 1))


def test_tp_sharding_linear():
    """Tensor-parallel Linear pair via sharding constraints compiles and
    matches the unsharded result."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh((8,), ("model",))
    rng = np.random.RandomState(0)
    x = rng.randn(4, 16).astype(np.float32)
    w1 = rng.randn(32, 16).astype(np.float32)
    w2 = rng.randn(16, 32).astype(np.float32)

    def f(x, w1, w2):
        h = jax.nn.relu(x @ w1.T)
        return h @ w2.T

    expect = f(x, w1, w2)
    xs = jax.device_put(x, NamedSharding(mesh, P()))
    w1s = jax.device_put(w1, NamedSharding(mesh, P("model", None)))
    w2s = jax.device_put(w2, NamedSharding(mesh, P(None, "model")))
    got = jax.jit(f)(xs, w1s, w2s)
    assert np.allclose(np.asarray(got), np.asarray(expect), atol=1e-4)
