"""dlframes pipeline-stage tests (modeled on reference DLEstimatorSpec /
DLClassifierSpec)."""
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dlframes import DLClassifier, DLEstimator


def _toy_classification(n=200, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32) + 1  # classes 1/2
    return x, y


def test_dlclassifier_fit_transform():
    x, y = _toy_classification()
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    est = DLClassifier(model, nn.ClassNLLCriterion(), [4])
    est.set_batch_size(32).set_max_epoch(15).set_learning_rate(1e-2)
    df = {"features": x, "label": y}
    fitted = est.fit(df)
    out = fitted.transform({"features": x})
    pred = out["prediction"]
    acc = float(np.mean(pred == y))
    assert acc > 0.85, acc


def test_dlestimator_regression():
    rng = np.random.RandomState(1)
    x = rng.randn(200, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32))[:, None]
    est = DLEstimator(nn.Linear(3, 1), nn.MSECriterion(), [3], [1])
    est.set_max_epoch(30).set_learning_rate(5e-2)
    model = est.fit({"features": x, "label": y})
    out = model.transform({"features": x})
    mse = float(np.mean((out["prediction"] - y) ** 2))
    assert mse < 0.1, mse


def test_dlframes_with_pandas():
    pd = __import__("pandas")
    x, y = _toy_classification(100)
    df = pd.DataFrame({"features": list(x), "label": y})
    est = DLClassifier(
        nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                      nn.LogSoftMax()),
        nn.ClassNLLCriterion(), [4]).set_max_epoch(10)
    fitted = est.fit(df)
    out = fitted.transform(df)
    assert "prediction" in out.columns
    assert len(out) == 100
