"""dlframes pipeline-stage tests (modeled on reference DLEstimatorSpec /
DLClassifierSpec)."""
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dlframes import DLClassifier, DLEstimator


def _toy_classification(n=200, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32) + 1  # classes 1/2
    return x, y


def test_dlclassifier_fit_transform():
    x, y = _toy_classification()
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    est = DLClassifier(model, nn.ClassNLLCriterion(), [4])
    est.set_batch_size(32).set_max_epoch(15).set_learning_rate(1e-2)
    df = {"features": x, "label": y}
    fitted = est.fit(df)
    out = fitted.transform({"features": x})
    pred = out["prediction"]
    acc = float(np.mean(pred == y))
    assert acc > 0.85, acc


def test_dlestimator_regression():
    rng = np.random.RandomState(1)
    x = rng.randn(200, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32))[:, None]
    est = DLEstimator(nn.Linear(3, 1), nn.MSECriterion(), [3], [1])
    est.set_max_epoch(30).set_learning_rate(5e-2)
    model = est.fit({"features": x, "label": y})
    out = model.transform({"features": x})
    mse = float(np.mean((out["prediction"] - y) ** 2))
    assert mse < 0.1, mse


def test_dlframes_with_pandas():
    pd = __import__("pandas")
    x, y = _toy_classification(100)
    df = pd.DataFrame({"features": list(x), "label": y})
    est = DLClassifier(
        nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                      nn.LogSoftMax()),
        nn.ClassNLLCriterion(), [4]).set_max_epoch(10)
    fitted = est.fit(df)
    out = fitted.transform(df)
    assert "prediction" in out.columns
    assert len(out) == 100


def test_dl_image_reader_and_transformer(tmp_path):
    """DLImageReader.read_images + DLImageTransformer parity (pandas-based
    image schema)."""
    from PIL import Image
    import numpy as np
    from bigdl_tpu.dlframes.dl_image_reader import (DLImageReader,
                                                    DLImageTransformer)
    from bigdl_tpu.transform.vision import Resize, ChannelNormalize
    rng = np.random.RandomState(0)
    d = tmp_path / "imgs"
    d.mkdir()
    for i in range(3):
        arr = rng.randint(0, 255, (12 + i, 10, 3), dtype=np.uint8)
        Image.fromarray(arr).save(str(d / f"im{i}.png"))
    (d / "notes.txt").write_text("not an image")

    df = DLImageReader.read_images(str(d))
    assert len(df) == 3
    row = df["image"][0]
    assert row["nChannels"] == 3 and row["data"].shape[2] == 3

    t = DLImageTransformer(Resize(8, 8) | ChannelNormalize(
        0.0, 0.0, 0.0, 255.0, 255.0, 255.0))
    out = t.transform(df)
    res = out["output"][0]
    assert res["height"] == 8 and res["width"] == 8
    assert float(np.asarray(res["data"]).max()) <= 1.0


def test_keras_training_config_compiles(tmp_path):
    """Full-model HDF5 with training_config compiles the converted model
    (OptimConverter parity) and fit runs."""
    import json as _json
    import numpy as np
    import h5py
    from bigdl_tpu.keras import load_keras
    from bigdl_tpu.optim import RMSprop
    spec = {"class_name": "Sequential", "config": [
        {"class_name": "Dense", "config": {
            "name": "d", "output_dim": 3, "activation": "softmax",
            "batch_input_shape": [None, 4]}}]}
    rng = np.random.RandomState(0)
    w, b = rng.randn(4, 3).astype(np.float32), np.zeros(3, np.float32)
    path = str(tmp_path / "full.h5")
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = _json.dumps(spec).encode()
        f.attrs["training_config"] = _json.dumps({
            "optimizer": {"class_name": "RMSprop",
                          "config": {"lr": 0.003, "rho": 0.8}},
            "loss": "categorical_crossentropy",
            "metrics": ["accuracy"]}).encode()
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = [b"d"]
        g = mw.create_group("d")
        g.attrs["weight_names"] = [b"d_W", b"d_b"]
        g.create_dataset("d_W", data=w)
        g.create_dataset("d_b", data=b)
    model = load_keras(hdf5_path=path)
    assert isinstance(model.optim_method, RMSprop)
    assert abs(model.optim_method.learningrate - 0.003) < 1e-9
    x = rng.randn(32, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    model.fit(x, y, batch_size=16, nb_epoch=1)
