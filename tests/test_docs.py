"""Docs consistency: README perf tables must match the committed bench
cache (VERDICT r4 weak #4 — hand-edited numbers drifted for two rounds;
tools/gen_readme_perf.py makes them mechanical, this test makes drift a
CI failure)."""
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_readme_perf_tables_match_bench_cache():
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "gen_readme_perf.py"),
         "--check"], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr or proc.stdout
