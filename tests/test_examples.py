"""Opt-in smoke runs of every example script (each is self-asserting).

    BIGDL_TPU_EXAMPLES=1 python -m pytest tests/test_examples.py -q

Off by default: the examples run real (small) training loops and add
minutes; CI-style suites exercise the same code paths through the unit
tests. Each example must exit 0 — they all end in hard asserts.
"""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_REPO, "examples"))
    if f.endswith(".py"))


# the cheapest example always runs (a default-suite canary so an example
# regression fails CI — VERDICT r2 weak #7); the rest stay opt-in
_DEFAULT_EXAMPLES = {"lenet_mnist.py"}


@pytest.mark.parametrize("script", _EXAMPLES)
def test_example_runs(script):
    if (os.environ.get("BIGDL_TPU_EXAMPLES") != "1"
            and script not in _DEFAULT_EXAMPLES):
        pytest.skip("example smoke runs are opt-in (BIGDL_TPU_EXAMPLES=1); "
                    "only the lenet_mnist canary runs by default")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # examples must not need the chip
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if script in ("long_context_ring.py", "transformer_lm_distributed.py",
                  "wide_deep_sparse.py", "distributed_serving.py"):
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run([sys.executable,
                           os.path.join(_REPO, "examples", script)],
                          env=env, capture_output=True, text=True,
                          timeout=1200, cwd=_REPO)
    assert proc.returncode == 0, \
        f"{script} failed:\n{proc.stderr[-3000:]}"
