"""Cross-process fleet serving (ISSUE 15).

The gates: a replica in ANOTHER process serves tokens bitwise-identical
to the in-process scheduler (greedy and seeded-sampled); the two-phase
fleet swap extends over the process boundary without mixing versions;
an agent process dying mid-decode loses ZERO requests (its typed
partials splice through the router's KV-preserving failover, bitwise
the uninterrupted stream); a prefill-specialist → decode-specialist KV
handoff produces tokens bitwise the monolithic scheduler; and a corrupt
or version-skewed handoff is REFUSED typed before any page lands.

Process discipline follows tests/multihost_util.py: agents spawn as
real subprocesses (their own jax runtimes — no cross-process
collectives needed, only sockets + files); a box whose environment
cannot spawn/run them SKIPS rather than fails.
"""
import json
import os
import pickle
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import jax

from bigdl_tpu import observability as obs
from bigdl_tpu.observability import health as _health
from bigdl_tpu.models.transformer_lm import TransformerLM
from bigdl_tpu.serving import (DecodeScheduler, DisaggregatedFleet,
                               EngineStopped, FleetMonitor, KVCacheOOM,
                               KVHandoffError, PriorityClass,
                               RemoteReplica, ReplicaAgent, Router,
                               TransportClient, TransportServer,
                               transport_threads_alive, wait_for_members)
from bigdl_tpu.serving.fleet import (fleet_threads_alive, read_member,
                                     warm_replica)
from bigdl_tpu.serving.kv_cache import SPILL_PENDING
from bigdl_tpu.serving.transport import (RemoteError, decode_tree,
                                         encode_tree)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, H = 48, 32
SCHED = dict(max_slots=4, block_size=4, max_seq_len=96, prefill_chunk=8)
MODEL = dict(vocab_size=V, hidden_size=H, num_heads=4, filter_size=64,
             num_layers=2, max_len=256)


@pytest.fixture(autouse=True)
def _clean_health():
    yield
    _health.reset()
    obs.registry().reset()
    obs.disable()


def _model():
    m = TransformerLM(**MODEL)
    m.ensure_initialized()
    return m


def _prompts(rng, sizes):
    return [rng.randint(1, V, size=n).astype(np.int32) for n in sizes]


# -- subprocess plumbing ----------------------------------------------------

def _save_params(model, fleet_dir):
    path = os.path.join(fleet_dir, "params.pkl")
    with open(path, "wb") as f:
        pickle.dump(jax.tree_util.tree_map(np.asarray, model.params), f)
    return path


def _spawn_agent(fleet_dir, name, params_path, *, role="replica",
                 tags=(), chaos=None, idx=1, sched=None):
    cfg = {"fleet_dir": fleet_dir, "name": name, "role": role,
           "tags": list(tags), "beat_s": 0.15, "process_index": idx,
           "model": MODEL, "params_path": params_path,
           "scheduler": dict(SCHED, **(sched or {}))}
    if chaos:
        cfg["chaos"] = chaos
    cfg_path = os.path.join(fleet_dir, f"cfg_{name}.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    env.pop("BIGDL_TPU_CHAOS", None)
    # log FILES, not pipes: nothing drains a pipe mid-test, so a chatty
    # agent (jax warnings, death tracebacks) would block on the ~64 KB
    # pipe buffer and wedge the drill
    log = open(os.path.join(fleet_dir, f"agent_{name}.log"), "w")
    p = subprocess.Popen(
        [sys.executable, "-m", "bigdl_tpu.serving.fleet", cfg_path],
        stdout=log, stderr=subprocess.STDOUT, cwd=REPO, env=env)
    p._bigdl_log = os.path.join(fleet_dir, f"agent_{name}.log")
    return p


def _members_or_skip(fleet_dir, names, procs, timeout_s=240.0):
    """Wait for the spawned agents' membership files; SKIP (not fail)
    when the box provably cannot run agent subprocesses at all."""
    try:
        return wait_for_members(fleet_dir, names, timeout_s=timeout_s)
    except TimeoutError as e:
        def tail(p):
            try:
                with open(p._bigdl_log) as f:
                    return f.read()[-800:]
            except OSError:
                return "<unreadable>"
        dead = [(p.poll(), tail(p)) for p in procs
                if p.poll() is not None]
        for p in procs:
            if p.poll() is None:
                p.kill()
        if dead:
            pytest.skip(f"agent subprocess unusable on this box: {dead}")
        raise e


def _reap(procs, timeout=60):
    """Wait for clean agent exits; escalate to kill only on a hang."""
    codes = []
    for p in procs:
        try:
            codes.append(p.wait(timeout=timeout))
        except subprocess.TimeoutExpired:
            p.kill()
            codes.append(None)
    return codes


def _end(procs, grace=60):
    """finally-block cleanup: give agents their grace to exit on their
    own (the shutdown RPC reply races their process exit), then force."""
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                p.terminate()


# -- transport (in-process) -------------------------------------------------

def test_transport_roundtrip_arrays_errors_and_pytree_codec():
    got = {}

    def handler(reply, op, meta, arrays):
        if op == "echo":
            reply(meta={"sum": float(sum(a.sum() for a in arrays)),
                        "meta": meta}, arrays=arrays)
        elif op == "boom":
            err_arrays = [np.arange(3, dtype=np.int32)]
            reply(error={"type": "EngineStopped", "msg": "dead"},
                  meta={"has_partial": True}, arrays=err_arrays)
        else:
            raise ValueError(f"nope: {op}")

    srv = TransportServer(handler, name="t").start()
    cli = TransportClient("127.0.0.1", srv.port, name="t").connect()
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.arange(5, dtype=np.int32)
    meta, arrays = cli.request("echo", {"k": 1}, [a, b], timeout=10)
    assert meta["sum"] == float(a.sum() + b.sum())
    assert np.array_equal(arrays[0], a) and np.array_equal(arrays[1], b)
    assert arrays[0].dtype == a.dtype

    with pytest.raises(RemoteError) as ei:
        cli.request("boom", timeout=10)
    assert ei.value.type_name == "EngineStopped"
    assert np.array_equal(ei.value.arrays[0], np.arange(3))
    # a handler exception answers typed instead of killing the conn
    with pytest.raises(RemoteError, match="nope"):
        cli.request("wat", timeout=10)
    meta, _ = cli.request("echo", {}, [], timeout=10)  # conn survives

    # pytree codec round-trip (the publish wire format)
    tree = {"w": np.ones((2, 3), np.float32),
            "inner": {"b": np.zeros((4,), np.int32), "lr": 0.5,
                      "t": (np.full((1,), 7.0), None)},
            "l": [np.arange(2)]}
    bufs = []
    spec = encode_tree(tree, bufs)
    back = decode_tree(json.loads(json.dumps(spec)), bufs)
    assert back["inner"]["lr"] == 0.5 and back["inner"]["t"][1] is None
    assert isinstance(back["inner"]["t"], tuple)
    assert np.array_equal(back["w"], tree["w"])
    assert np.array_equal(back["l"][0], tree["l"][0])

    cli.close()
    srv.close()
    assert transport_threads_alive() == 0, got


# -- KV handoff primitives + typed refusals (in-process) --------------------

def test_kv_export_adopt_primitives_and_geometry_refusal():
    m = _model()
    a = DecodeScheduler(m, name="exp", **SCHED)
    b = DecodeScheduler(m, name="imp", **SCHED)
    a.kv.ensure_capacity("o1", 16)
    ids = a.kv.owner_blocks("o1")
    ids2, layers = a.kv.export_blocks(owner="o1")
    assert ids2 == ids and len(layers) == a.kv.n_layers
    assert layers[0][0].shape[0] == len(ids)
    new = b.kv.adopt_serialized("x", layers)
    assert len(new) == len(ids) and b.kv.blocks_in_use() == len(ids)
    b.kv.free("x")
    assert b.kv.blocks_in_use() == 0
    # geometry refusal: wrong head_dim
    bad = [(np.zeros((2, layers[0][0].shape[1], SCHED["block_size"], 3),
            np.float32),) * 2 for _ in range(a.kv.n_layers)]
    with pytest.raises(ValueError, match="geometry"):
        b.kv.adopt_serialized("y", bad)
    # all-or-nothing under OOM
    big = [(np.zeros((1000,) + layers[0][0].shape[1:], np.float32),) * 2
           for _ in range(a.kv.n_layers)]
    with pytest.raises(KVCacheOOM):
        b.kv.adopt_serialized("z", big)
    assert b.kv.blocks_in_use() == 0
    # exporting a dead block refused
    a.kv.free("o1")
    with pytest.raises(ValueError, match="dead block"):
        a.kv.export_blocks(blocks=ids)


def test_corrupt_and_version_skewed_handoff_refused_typed():
    """The acceptance-criterion refusal matrix, over the REAL agent
    handlers (in-process agents — sockets, two schedulers): tampered
    tokens (chain-hash mismatch), tampered pages (digest mismatch), and
    a version-skewed receiver all refuse typed KVHandoffError with
    ZERO pages adopted; the untampered handoff then lands."""
    m = _model()
    fd = tempfile.mkdtemp(prefix="fleet_refuse_")
    pf = ReplicaAgent(DecodeScheduler(m, name="pf", **SCHED),
                      fleet_dir=fd, name="pf", role="prefill").start()
    dc = ReplicaAgent(DecodeScheduler(m, name="dc", **SCHED),
                      fleet_dir=fd, name="dc", role="decode").start()
    try:
        dpf, ddc = wait_for_members(fd, ["pf", "dc"], timeout_s=20)
        rpf = RemoteReplica(dpf, fleet_dir=fd).start()
        rdc = RemoteReplica(ddc, fleet_dir=fd).start()
        rng = np.random.RandomState(3)
        prompt = rng.randint(1, V, size=35).astype(np.int32)
        meta, arrays = rpf.prefill_export(prompt, timeout=120)
        assert meta["tokens"] == 32  # hit_align(8)-aligned prefix
        hand = {"version": meta["version"], "keys": meta["keys"],
                "geometry": meta["geometry"], "digest": meta["digest"]}

        # (a) corrupt TOKENS → chain-hash mismatch, refused typed
        bad_tok = [arrays[0].copy()] + arrays[1:]
        bad_tok[0][3] ^= 1
        with pytest.raises(KVHandoffError, match="chain-hash"):
            rdc.adopt_prefix(hand, bad_tok, timeout=60)
        # (b) corrupt PAGE BYTES → digest mismatch, refused typed
        bad_pg = [arrays[0]] + [a.copy() for a in arrays[1:]]
        bad_pg[1].reshape(-1)[0] += 1.0
        with pytest.raises(KVHandoffError, match="digest"):
            rdc.adopt_prefix(hand, bad_pg, timeout=60)
        # (c) version skew: decode replica swapped past the export
        p2 = jax.tree_util.tree_map(lambda x: x * 1.01, m.params)
        rdc.registry.publish(p2, version="v-new")
        rdc.registry.activate("v-new")
        with pytest.raises(KVHandoffError, match="version skew"):
            rdc.adopt_prefix(hand, arrays, timeout=60)
        st = rdc.stats()
        assert st["kv"]["blocks_in_use"] == 0, \
            "refused handoffs must adopt ZERO pages"
        # (d) the clean handoff under the matching version lands
        rdc.registry.activate(meta["version"])
        out = rdc.adopt_prefix(hand, arrays, timeout=60)
        assert out[0]["adopted_blocks"] == 32 // SCHED["block_size"]
        assert rdc.stats()["kv"]["blocks_in_use"] == \
            out[0]["adopted_blocks"]
    finally:
        pf.shutdown()
        dc.shutdown()
    assert fleet_threads_alive() == 0


def test_warm_replica_refills_spilled_chains_from_source():
    """``fleet.warm_replica``: a joining replica adopts a peer's prefix
    chains — INCLUDING chains the peer evicted to its host tier (ISSUE
    18). The export's lookup takes the second-chance refill instead of
    re-running the prefill, and the warmed replica's first submit of a
    warmed prompt is an ordinary warm hit, bitwise the solo decode."""
    m = _model()
    fd = tempfile.mkdtemp(prefix="fleet_warm_")
    src_sched = DecodeScheduler(m, name="ws", host_blocks=32, **SCHED)
    tgt_sched = DecodeScheduler(m, name="wt", **SCHED)
    src = ReplicaAgent(src_sched, fleet_dir=fd, name="ws").start()
    tgt = ReplicaAgent(tgt_sched, fleet_dir=fd, name="wt").start()
    solo = DecodeScheduler(m, name="wsolo", **SCHED).start()
    try:
        ds, dt = wait_for_members(fd, ["ws", "wt"], timeout_s=20)
        rsrc = RemoteReplica(ds, fleet_dir=fd).start()
        rtgt = RemoteReplica(dt, fleet_dir=fd).start()
        rng = np.random.RandomState(21)
        prompts = [rng.randint(1, V, size=16).astype(np.int32)
                   for _ in range(4)]
        for p in prompts:
            rsrc.submit(p, max_new_tokens=8).result(timeout=120)
        # push every chain's leaf into the host tier, then wait for the
        # stager to land the spills (in-process agent: the scheduler is
        # THIS object) — the warm exports must find settled handles
        src_sched.prefix.evict(4)
        st = rsrc.stats()
        assert st["prefix"]["spills"] == 4 and \
            st["prefix"]["spilled_entries"] == 4
        deadline = time.time() + 10
        while time.time() < deadline:
            with src_sched.prefix._lock:
                pend = [h for h, _ in src_sched.prefix._spilled.values()
                        if h.state == SPILL_PENDING]
            if not pend:
                break
            time.sleep(0.01)
        assert not pend, "spill stage never settled"

        out = warm_replica(rsrc, rtgt, prompts, timeout_s=120)
        assert out["warmed"] == 4 and out["failed"] == 0, out
        st = rsrc.stats()
        assert st["prefix"]["hits_after_spill"] >= 1, \
            f"warm exports must refill, not recompute: {st['prefix']}"
        assert rtgt.stats()["prefix"]["entries"] > 0

        # the warmed replica serves the FIRST ask of a warmed prompt
        # as a warm hit, bitwise the solo decode
        want = solo.generate(prompts[0], 8)
        got = rtgt.submit(prompts[0], max_new_tokens=8).result(timeout=120)
        assert np.array_equal(want, got), \
            "warmed-replica tokens must be bitwise the solo decode"
        assert rtgt.stats()["prefix_hits"] >= 1, \
            "the warmed chain never produced a hit"
    finally:
        src.shutdown()
        tgt.shutdown()
        solo.shutdown()
    assert src_sched.stats()["host"]["host_blocks_in_use"] == 0, \
        "the source's host pool must drain at shutdown"
    assert fleet_threads_alive() == 0


def test_monitor_redials_torn_connection():
    """One torn connection must not remove a healthy, still-beating
    agent from the fleet forever: the FleetMonitor sees fresh beats
    behind a closed client and re-dials, so the drain/rejoin
    round-trips and later submits serve normally."""
    m = _model()
    fd = tempfile.mkdtemp(prefix="fleet_reconn_")
    ag = ReplicaAgent(DecodeScheduler(m, name="rc", **SCHED),
                      fleet_dir=fd, name="rc", beat_s=0.1).start()
    mon = None
    try:
        doc, = wait_for_members(fd, ["rc"], timeout_s=20)
        rep = RemoteReplica(doc, fleet_dir=fd).start()
        mon = FleetMonitor([rep], fleet_dir=fd, every_s=0.05,
                           stale_s=5.0).start()
        rng = np.random.RandomState(9)
        prompt = rng.randint(1, V, size=9).astype(np.int32)
        first = rep.submit(prompt, max_new_tokens=4).result(timeout=60)
        rep._client.close()          # torn connection; agent alive
        deadline = time.time() + 10
        while rep._client.closed and time.time() < deadline:
            time.sleep(0.05)
        assert not rep._client.closed, \
            "the monitor must re-dial a fresh member behind a torn conn"
        again = rep.submit(prompt, max_new_tokens=4).result(timeout=60)
        assert np.array_equal(first, again)
    finally:
        if mon is not None:
            mon.stop()
        ag.shutdown()
    assert fleet_threads_alive() == 0


def test_disaggregated_swap_covers_prefill_pool():
    """``DisaggregatedFleet.swap`` lands ONE version on BOTH pools.
    ``Router.swap`` alone leaves prefill specialists behind, and every
    later handoff is version-skew-refused (safe but useless — found
    driving the API end-to-end); after dis.swap the handoff ADOPTS and
    tokens are the new version's, bitwise the monolithic scheduler."""
    m = _model()
    fd = tempfile.mkdtemp(prefix="fleet_disswap_")
    pf = ReplicaAgent(DecodeScheduler(m, name="pf2", **SCHED),
                      fleet_dir=fd, name="pf2", role="prefill").start()
    dc = ReplicaAgent(DecodeScheduler(m, name="dc2", **SCHED),
                      fleet_dir=fd, name="dc2", role="decode").start()
    local = DecodeScheduler(m, name="mono2", **SCHED).start()
    try:
        dpf, ddc = wait_for_members(fd, ["pf2", "dc2"], timeout_s=20)
        rpf = RemoteReplica(dpf, fleet_dir=fd).start()
        rd0 = RemoteReplica(ddc, fleet_dir=fd)
        router = Router([rd0]).start()
        dis = DisaggregatedFleet(router, [rpf], [rd0])
        p2 = jax.tree_util.tree_map(lambda a: a * 1.01, m.params)
        v = dis.swap(p2)
        local.swap(p2, version=v)
        rng = np.random.RandomState(5)
        prompt = rng.randint(1, V, size=37).astype(np.int32)
        want = local.generate(prompt, 8)
        got = dis.submit(prompt, max_new_tokens=8).result(timeout=120)
        assert np.array_equal(want, got), \
            "post-swap disaggregated tokens must be the new version's"
        st = dis.stats()
        assert st["handoffs"] == 1 and st["handoff_refused"] == 0, \
            f"the pool swap must keep handoffs landing: {st}"
        rpf.shutdown()
        router.shutdown()
    finally:
        pf.shutdown()
        dc.shutdown()
        local.shutdown()
    assert fleet_threads_alive() == 0


# -- cross-process: bitwise + fleet swap ------------------------------------

def test_remote_tokens_bitwise_and_fleet_swap_never_mixes(tmp_path):
    fd = str(tmp_path)
    m = _model()
    params_path = _save_params(m, fd)
    local = DecodeScheduler(m, name="oracle", **SCHED).start()
    proc = _spawn_agent(fd, "r0", params_path)
    try:
        docs = _members_or_skip(fd, ["r0"], [proc])
        rr = RemoteReplica(docs[0], fleet_dir=fd)
        router = Router([rr]).start()
        rng = np.random.RandomState(0)
        prompts = _prompts(rng, (5, 17, 26, 33))
        want = [local.generate(p, 12) for p in prompts]
        futs = [router.submit(p, max_new_tokens=12) for p in prompts]
        got = [f.result(timeout=120) for f in futs]
        for w, g in zip(want, got):
            assert np.array_equal(w, g), \
                "remote tokens must be bitwise the in-process replica's"
        assert all(f.version == "v0" for f in futs)

        # seeded sampling is (seed, position)-keyed: bitwise across the
        # process boundary too
        ws = local.generate(prompts[1], 10, temperature=0.7, top_p=0.9,
                            seed=11)
        gs = router.submit(prompts[1], max_new_tokens=10,
                           temperature=0.7, top_p=0.9,
                           seed=11).result(timeout=120)
        assert np.array_equal(ws, gs)

        # two-phase fleet swap over the wire: publish ships the tree,
        # activate flips — later admissions serve the new version and
        # answer with ITS tokens (no response mixes versions)
        p2 = jax.tree_util.tree_map(lambda a: a * 1.01, m.params)
        v2 = router.swap(p2)
        local.swap(p2, version=v2)
        futs2 = [router.submit(p, max_new_tokens=12) for p in prompts]
        got2 = [f.result(timeout=120) for f in futs2]
        want2 = [local.generate(p, 12) for p in prompts]
        for f, w, g in zip(futs2, want2, got2):
            assert f.version == v2
            assert np.array_equal(w, g), \
                "post-swap tokens must be the NEW version's, bitwise"
        assert not np.array_equal(want[0], want2[0]), \
            "the perturbed params must actually change tokens"

        # clean drain: the shutdown reply reports the remote ledger
        # empty (kv_blocks_in_use -> 0 in the agent process)
        meta, _ = rr._request("shutdown", {"drain": True}, timeout=120)
        assert meta["kv_blocks_in_use"] == 0
        router.shutdown()
    finally:
        _end([proc])
    assert _reap([proc]) == [0]
    local.shutdown()
    doc = read_member(fd, "r0")
    assert doc and doc.get("final") and not doc.get("dead")


# -- cross-process: agent death, KV-preserving failover ---------------------

@pytest.mark.slow  # ~23s of subprocess spawns; `make fleet-smoke`
# (tier-1) runs the same kill-mid-decode drill with exit-code asserts
# every run — this is the standalone, assert-rich version
def test_agent_death_mid_decode_zero_lost_partials_spliced(tmp_path):
    """Kill one replica process mid-decode (a PERMANENT chaos fault in
    its scheduler step — the deterministic process-death drill: the
    dying scheduler fails its in-flight typed-with-partial, the agent
    converts that into whole-process death). Every request completes on
    the survivor, recovered streams are BITWISE the uninterrupted run,
    and the dead process exits with the death code."""
    fd = str(tmp_path)
    m = _model()
    params_path = _save_params(m, fd)
    local = DecodeScheduler(m, name="oracle2", **SCHED).start()
    # r0 spawns with its death PRE-ARMED: a permanent fault at its 6th
    # decode-group dispatch — deterministically mid-decode for 24-token
    # generations (warmup drives the jit directly, not the chaos seam,
    # so only live traffic counts)
    procs = [_spawn_agent(fd, "r0", params_path, idx=1,
                          chaos={"sites": {"serving/scheduler_step": [
                              {"kind": "permanent", "nth": 6}]}}),
             _spawn_agent(fd, "r1", params_path, idx=2)]
    monitor = None
    try:
        docs = _members_or_skip(fd, ["r0", "r1"], procs)
        reps = [RemoteReplica(d, fleet_dir=fd) for d in docs]
        router = Router(reps, max_failovers=4).start()
        monitor = FleetMonitor(reps, fleet_dir=fd, every_s=0.1,
                               stale_s=10.0).start()
        rng = np.random.RandomState(1)
        prompts = _prompts(rng, (6, 9, 14, 21))
        want = [local.generate(p, 24) for p in prompts]
        futs = [router.submit(p, max_new_tokens=24) for p in prompts]
        got = [f.result(timeout=240) for f in futs]
        for w, g in zip(want, got):
            assert np.array_equal(w, g), \
                "recovered streams must be bitwise the uninterrupted run"
        st = router.stats()
        assert st["completed"] == len(prompts), f"lost requests: {st}"
        # the deadline-less round-robin put ~half the requests on r0;
        # its death at dispatch 6 left them mid-generation, so their
        # partials spliced through _recover_decode on r1
        assert st["kv_recoveries"] >= 1, st
        served = {f.trace["router"]["replica"] for f in futs}
        assert "r1" in served
        router.shutdown()
    finally:
        if monitor is not None:
            monitor.stop()
        _end(procs)
    codes = _reap(procs)
    assert codes == [86, 0], codes
    local.shutdown()


# -- cross-process: disaggregated prefill/decode ----------------------------

@pytest.mark.slow  # ~23s of subprocess spawns; `make fleet-smoke`
# (tier-1) asserts the handoff-bitwise gate against the monolithic
# oracle every run — this is the standalone greedy+sampled version
def test_prefill_decode_handoff_bitwise_greedy_and_sampled(tmp_path):
    """The ambitious end state: a prefill-specialist process runs the
    chunked prefill, its KV pages hand off in one framed binary hop,
    the decode-specialist adopts them (content-key-verified) and
    decodes — tokens BITWISE the monolithic single-process scheduler,
    greedy and seeded-sampled; the router's prefix affinity steers the
    request to the adopting replica."""
    fd = str(tmp_path)
    m = _model()
    params_path = _save_params(m, fd)
    local = DecodeScheduler(m, name="mono", **SCHED).start()
    procs = [_spawn_agent(fd, "pf", params_path, role="prefill", idx=1),
             _spawn_agent(fd, "d0", params_path, role="decode", idx=2)]
    try:
        dpf, dd0 = _members_or_skip(fd, ["pf", "d0"], procs)
        rpf = RemoteReplica(dpf, fleet_dir=fd)
        rd0 = RemoteReplica(dd0, fleet_dir=fd)
        router = Router([rd0]).start()
        rpf.start()
        dis = DisaggregatedFleet(router, [rpf], [rd0])
        rng = np.random.RandomState(2)
        long_prompts = _prompts(rng, (33, 40, 52))
        want = [local.generate(p, 10) for p in long_prompts]
        got = [dis.submit(p, max_new_tokens=10).result(timeout=240)
               for p in long_prompts]
        for w, g in zip(want, got):
            assert np.array_equal(w, g), \
                "disaggregated tokens must be bitwise the monolithic run"
        # seeded-sampled through the same handoff path
        ws = local.generate(long_prompts[0], 8, temperature=0.8,
                            top_p=0.85, seed=23)
        gs = dis.submit(long_prompts[0], max_new_tokens=8,
                        temperature=0.8, top_p=0.85,
                        seed=23).result(timeout=240)
        assert np.array_equal(ws, gs)
        st = dis.stats()
        assert st["handoffs"] == 4 and st["handoff_failed"] == 0, st
        # the decode specialist actually SKIPPED the handed-off prefill
        sd = rd0.stats()
        assert sd["prefix_hits"] >= 3
        assert sd["prefix_reused_tokens"] >= 3 * 32
        rpf.shutdown()
        router.shutdown()
    finally:
        _end(procs)
    assert _reap(procs) == [0, 0]
    local.shutdown()
