"""Autoregressive decoding tests (KV-cache generate/translate/beam) —
their own file so pytest-xdist loadfile sharding overlaps them with
the model forwards (suite wall time = slowest file)."""
import numpy as np
import jax
import jax.numpy as jnp


def test_transformer_lm_generate_matches_naive():
    """KV-cache generate() == the naive re-forward-everything loop
    (greedy), and the sampled path stays in-vocab and jit-compiles."""
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=61, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        1, 61, size=(2, 5)), jnp.int32)

    out = model.generate(params, prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    assert np.array_equal(np.asarray(out[:, :5]), np.asarray(prompt))

    # naive: re-run the full forward each step, argmax the last position
    ids = prompt
    for _ in range(6):
        logits, _ = model.apply(params, {}, ids, training=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    assert np.array_equal(np.asarray(out), np.asarray(ids)), \
        (np.asarray(out), np.asarray(ids))

    # sampling path, jitted end to end
    sampled = jax.jit(lambda p, x: model.generate(
        p, x, max_new_tokens=4, temperature=0.8, top_k=5,
        rng=jax.random.PRNGKey(1)))(params, prompt)
    assert sampled.shape == (2, 9)
    s = np.asarray(sampled[:, 5:])
    assert ((s >= 0) & (s < 61)).all()


def test_lm_criterion_matches_chunked_head():
    """nn.LMCriterion == models.lm_loss_chunked (the 0-based LM head) in
    value and gradient; generate edge cases (max_new_tokens=0, top_k >
    vocab) behave."""
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM, lm_loss_chunked
    from bigdl_tpu.nn import LMCriterion
    rng = np.random.RandomState(3)
    B, T, H, V = 2, 16, 8, 23
    h = jnp.asarray(rng.randn(B, T, H).astype(np.float32))
    emb = jnp.asarray(0.2 * rng.randn(V, H).astype(np.float32))
    y = rng.randint(1, V, size=(B, T)).astype(np.int32)
    y[1, :3] = 0
    y = jnp.asarray(y)
    crit = LMCriterion(padding_value=0)
    l1, g1 = jax.value_and_grad(lambda h: crit._forward(h @ emb.T, y))(h)
    l2, g2 = jax.value_and_grad(
        lambda h: lm_loss_chunked(h, emb, y, chunk=8))(h)
    assert np.allclose(float(l1), float(l2), rtol=1e-6)
    assert np.allclose(np.asarray(g1), np.asarray(g2), atol=1e-6)

    model = TransformerLM(vocab_size=V, hidden_size=16, num_heads=2,
                          filter_size=32, num_layers=1, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(rng.randint(1, V, (1, 4)), jnp.int32)
    out0 = model.generate(params, prompt, max_new_tokens=0)
    assert out0.shape == (1, 4)  # contract: Tp + 0
    outk = model.generate(params, prompt, max_new_tokens=3,
                          temperature=1.0, top_k=1000)  # > vocab: clipped
    assert outk.shape == (1, 7)


def test_generate_prefill_kernel_path(monkeypatch):
    """generate() with the Pallas prefill (interpret mode) == einsum."""
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=37, hidden_size=16, num_heads=2,
                          filter_size=32, num_layers=2, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(1).randint(1, 37, (2, 6)),
                         jnp.int32)
    monkeypatch.setenv("BIGDL_TPU_FLASH", "off")
    out_e = model.generate(params, prompt, max_new_tokens=5)
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")
    out_k = model.generate(params, prompt, max_new_tokens=5)
    assert np.array_equal(np.asarray(out_e), np.asarray(out_k))


def test_moe_lm_generate_matches_naive():
    """MoE LM cached generate() == the naive re-forward loop (greedy):
    token-level routing behaves identically under cached decode."""
    import jax.numpy as jnp
    from bigdl_tpu.models import MoETransformerLM
    model = MoETransformerLM(vocab_size=41, hidden_size=32, num_heads=2,
                             filter_size=64, num_layers=2, n_experts=2,
                             max_len=32)
    params, state = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(2).randint(1, 41, (2, 5)),
                         jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=5)
    ids = prompt
    for _ in range(5):
        logits, _ = model.apply(params, state, ids, training=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    assert np.array_equal(np.asarray(out), np.asarray(ids))


def test_lm_generate_eos_masking():
    """generate(eos_id=...): after a row emits eos, later positions are 0;
    rows that never emit eos are unaffected (vs the eos-free output).

    The eos is the first token row 0 generates, and each row is checked
    against its OWN free-run behavior — greedy continuations differ
    across jax/XLA versions (tie-breaks, fused-rounding), so the test
    must not assume a particular token appears in one row but not the
    other (the old deterministic-pick assert flaked per-environment)."""
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=19, hidden_size=16, num_heads=2,
                          filter_size=32, num_layers=1, max_len=24)
    params, _ = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 19, (2, 4)),
                         jnp.int32)
    free = np.asarray(model.generate(params, prompt, 8))
    eos = int(free[0, 4])  # row 0 emits it at its first generated slot
    out = np.asarray(model.generate(params, prompt, 8, eos_id=eos))
    masked_rows = 0
    for r in range(free.shape[0]):
        hits = np.where(free[r, 4:] == eos)[0]
        if hits.size:  # this row emits eos: masked from first hit on
            pos = int(hits[0]) + 4
            assert out[r, pos] == eos, (r, out[r], free[r])
            assert (out[r, pos + 1:] == 0).all(), (r, out[r])
            # the prefix through eos is the free continuation unchanged
            assert np.array_equal(out[r, :pos + 1], free[r, :pos + 1])
            masked_rows += 1
        else:  # never emits eos: identical to the free run
            assert np.array_equal(out[r], free[r]), (r, out[r], free[r])
    assert masked_rows >= 1  # row 0 guarantees non-vacuity


def test_gqa_lm_generate_matches_naive():
    """Grouped-query attention (num_kv_heads < num_heads): caches are
    kvH-sized and greedy decode through the grouped cache path matches
    re-running the full forward at every step."""
    from bigdl_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=61, hidden_size=32, num_heads=4,
                      filter_size=64, num_layers=2, max_len=48,
                      use_flash=False, num_kv_heads=2)
    params, _ = m.init(jax.random.PRNGKey(7))
    prompt = np.array([[5, 9, 2], [11, 3, 7]], np.int32)
    out = m.generate(params, prompt, max_new_tokens=6)
    assert out.shape == (2, 9)

    # caches really are kv-head sized
    caches = m.init_cache(2, 16)
    assert caches[0][0].shape == (2, 2, 16, 8)

    # naive: argmax over full forward each step
    ids = prompt.copy()
    for _ in range(6):
        logits, _ = m.apply(params, {}, jnp.asarray(ids.astype(np.float32)),
                            training=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), ids)


def test_gqa_forward_matches_expanded_mha():
    """A GQA attention layer == an MHA layer whose wk/wv are the grouped
    weights tiled across each group (same math, bigger projections)."""
    from bigdl_tpu import nn
    H, heads, kvh = 24, 6, 2
    g = heads // kvh
    d = H // heads
    gqa = nn.Attention(H, heads, use_flash=False, num_kv_heads=kvh)
    params, _ = gqa.init(jax.random.PRNGKey(0))

    mha = nn.Attention(H, heads, use_flash=False)
    wk = np.asarray(params["wk"]).reshape(H, kvh, d)
    wv = np.asarray(params["wv"]).reshape(H, kvh, d)
    mp = {"wq": params["wq"],
          "wk": jnp.asarray(np.repeat(wk, g, axis=1).reshape(H, H)),
          "wv": jnp.asarray(np.repeat(wv, g, axis=1).reshape(H, H)),
          "wo": params["wo"]}
    x = jnp.asarray(np.random.RandomState(1).randn(2, 10, H)
                    .astype(np.float32))
    o1, _ = gqa.apply(params, {}, x, training=False)
    o2, _ = mha.apply(mp, {}, x, training=False)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


def test_gqa_head_divisibility_rejected():
    from bigdl_tpu import nn
    import pytest as _pytest
    with _pytest.raises(ValueError, match="divide"):
        nn.Attention(32, 4, num_kv_heads=3)


def test_rope_lm_generate_matches_naive():
    """RoPE LM: decode-with-rotated-cache matches re-running the full
    forward each step (the positional bookkeeping is consistent between
    prefill, cache, and per-step rotation)."""
    from bigdl_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=53, hidden_size=32, num_heads=4,
                      filter_size=64, num_layers=2, max_len=48,
                      use_flash=False, pos_encoding="rope")
    params, _ = m.init(jax.random.PRNGKey(11))
    prompt = np.array([[4, 8, 15], [16, 23, 42]], np.int32)
    out = m.generate(params, prompt, max_new_tokens=6)
    ids = prompt.copy()
    for _ in range(6):
        logits, _ = m.apply(params, {}, jnp.asarray(ids.astype(np.float32)),
                            training=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), ids)


def test_rope_relative_position_invariance():
    """RoPE's defining property: attention logits depend only on RELATIVE
    distance — shifting all positions by a constant leaves q·k' scores
    unchanged."""
    from bigdl_tpu.nn.attention import rotary_embedding
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 2, 6, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 6, 16).astype(np.float32))

    def scores(shift):
        pos = jnp.arange(6) + shift
        qr = rotary_embedding(q, pos)
        kr = rotary_embedding(k, pos)
        return np.asarray(jnp.einsum("bhqd,bhkd->bhqk", qr, kr))

    np.testing.assert_allclose(scores(0), scores(17), atol=1e-4)


def test_rope_gqa_compose():
    """RoPE + GQA together: generate matches naive."""
    from bigdl_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=37, hidden_size=32, num_heads=4,
                      filter_size=64, num_layers=1, max_len=32,
                      use_flash=False, pos_encoding="rope", num_kv_heads=2)
    params, _ = m.init(jax.random.PRNGKey(3))
    prompt = np.array([[7, 2]], np.int32)
    out = m.generate(params, prompt, max_new_tokens=5)
    ids = prompt.copy()
    for _ in range(5):
        logits, _ = m.apply(params, {}, jnp.asarray(ids.astype(np.float32)),
                            training=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), ids)


def test_moe_lm_rope_gqa_generate_matches_naive():
    """MoE LM composes RoPE + GQA through the shared decode machinery."""
    from bigdl_tpu.models import MoETransformerLM
    m = MoETransformerLM(vocab_size=41, hidden_size=32, num_heads=4,
                         filter_size=64, num_layers=2, n_experts=2,
                         capacity_factor=2.0, max_len=32, use_flash=False,
                         num_kv_heads=2, pos_encoding="rope")
    params = m._init_params(jax.random.PRNGKey(5))
    prompt = np.array([[3, 9]], np.int32)
    out = m.generate(params, prompt, max_new_tokens=5)
    ids = prompt.copy()
    for _ in range(5):
        logits, _ = m.apply(params, m._init_state(),
                            jnp.asarray(ids.astype(np.float32)),
                            training=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), ids)


def test_top_p_sampling_masks_tail():
    """top_p keeps the nucleus: with a sharply peaked distribution and
    small p, sampling always returns the argmax; top_p=1 leaves the
    distribution unchanged (all tokens reachable over many draws)."""
    from bigdl_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=29, hidden_size=16, num_heads=2,
                      filter_size=32, num_layers=1, max_len=16,
                      use_flash=False)
    params, _ = m.init(jax.random.PRNGKey(0))
    prompt = np.array([[5]], np.int32)
    greedy = np.asarray(m.generate(params, prompt, max_new_tokens=4))
    # tiny p → nucleus collapses to the single top token → greedy
    nuc = np.asarray(m.generate(params, prompt, max_new_tokens=4,
                                temperature=0.7, top_p=1e-6,
                                rng=jax.random.PRNGKey(9)))
    np.testing.assert_array_equal(nuc, greedy)
    # generous p still yields valid ids
    samp = np.asarray(m.generate(params, prompt, max_new_tokens=4,
                                 temperature=1.0, top_p=0.9,
                                 rng=jax.random.PRNGKey(10)))
    assert samp.shape == greedy.shape and (samp >= 0).all() \
        and (samp < 29).all()


def test_lm_generate_beam_width1_is_greedy():
    """generate_beam(beam_size=1) == greedy generate, token for token,
    incl. eos masking."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=53, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=48)
    params, _ = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(1, 53, (2, 6)),
                      jnp.int32)
    greedy = np.asarray(model.generate(params, ids, max_new_tokens=8))
    beam1 = np.asarray(jax.jit(lambda p, x: model.generate_beam(
        p, x, max_new_tokens=8, beam_size=1))(params, ids))
    assert (beam1 == greedy).all()

    eos = int(greedy[0, 8])  # force an early stop on row 0's path
    g = np.asarray(model.generate(params, ids, max_new_tokens=8,
                                  eos_id=eos))
    b = np.asarray(model.generate_beam(params, ids, max_new_tokens=8,
                                       beam_size=1, eos_id=eos))
    assert (b == g).all()


def test_lm_generate_beam_score_monotone_in_width():
    """Wider beams usually improve the model's own sequence log-prob.
    NOT a theorem — beam search can prune the greedy prefix mid-way and
    finish worse — so this is a pinned-seed regression guard (mirroring
    test_translate_beam_score_monotone_in_width) on seeds where the
    typical behavior holds; the exactness property is the beam_size=1
    test above."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=31, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(3))
    ids = jnp.asarray(np.random.RandomState(5).randint(1, 31, (2, 4)),
                      jnp.int32)

    def seq_logprob(full):
        full = jnp.asarray(full)
        lg, _ = model.apply(params, {}, full[:, :-1], training=False)
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        cont = full[:, 1:]
        tot = jnp.take_along_axis(lp, cont[..., None], -1)[..., 0]
        return np.asarray(tot[:, 3:].sum(axis=1))  # continuation only

    s1 = seq_logprob(model.generate_beam(params, ids, 6, beam_size=1))
    s3 = seq_logprob(model.generate_beam(params, ids, 6, beam_size=3))
    assert (s3 >= s1 - 1e-4).all(), (s1, s3)


def test_prefill_chunked_matches_prefill():
    """Chunked prefill == one-shot prefill: same last-position logits,
    and decode continues identically from either cache (incl. a ragged
    tail chunk and GQA+RoPE)."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM

    for kv, pos_enc in [(None, "sinusoidal"), (1, "rope")]:
        model = TransformerLM(vocab_size=47, hidden_size=32, num_heads=2,
                              filter_size=64, num_layers=2, max_len=32,
                              num_kv_heads=kv, pos_encoding=pos_enc)
        params, _ = model.init(jax.random.PRNGKey(1))
        ids = jnp.asarray(np.random.RandomState(2).randint(1, 47, (2, 11)),
                          jnp.int32)  # 11 = 4 + 4 + ragged 3
        lg_a, ca = model.prefill(params, ids, 16)
        lg_b, cb = model.prefill_chunked(params, ids, 16, chunk=4)
        np.testing.assert_allclose(np.asarray(lg_a), np.asarray(lg_b),
                                   rtol=2e-4, atol=2e-4)
        nxt = jnp.argmax(lg_a, -1).astype(jnp.int32)
        oa, _ = model.decode_one(params, nxt, 11, ca)
        ob, _ = model.decode_one(params, nxt, 11, cb)
        np.testing.assert_allclose(np.asarray(oa), np.asarray(ob),
                                   rtol=2e-4, atol=2e-4)
