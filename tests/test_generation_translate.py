"""Translation-model generation tests (encoder-decoder greedy/beam) —
split from test_generation.py for xdist loadfile balance."""
import numpy as np
import jax



def test_transformer_translate_matches_naive():
    """translate() (cached encoder-decoder greedy decode) == the naive
    re-forward loop through mode='translation' apply."""
    import jax.numpy as jnp
    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.utils.table import Table
    model = Transformer(vocab_size=31, hidden_size=16, num_heads=2,
                        filter_size=32, num_hidden_layers=2,
                        mode="translation", max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    src = jnp.asarray(np.random.RandomState(0).randint(1, 31, (2, 7)),
                      jnp.int32)
    src = src.at[1, 5:].set(0)  # padded source
    out = model.translate(params, src, max_new_tokens=6, bos_id=1)
    assert out.shape == (2, 6)

    tgt = jnp.full((2, 1), 1, jnp.int32)  # BOS
    for _ in range(6):
        logits, _ = model.apply(params, {}, Table(src, tgt), training=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        tgt = jnp.concatenate([tgt, nxt[:, None]], axis=1)
    assert np.array_equal(np.asarray(out), np.asarray(tgt[:, 1:]))


def test_transformer_translate_eos_masking():
    """Tokens after the first eos are emitted as 0 (padding)."""
    import jax.numpy as jnp
    from bigdl_tpu.nn import Transformer
    model = Transformer(vocab_size=13, hidden_size=8, num_heads=2,
                        filter_size=16, num_hidden_layers=1,
                        mode="translation", max_len=16)
    params, _ = model.init(jax.random.PRNGKey(1))
    src = jnp.asarray(np.random.RandomState(1).randint(1, 13, (1, 5)),
                      jnp.int32)
    out_free = np.asarray(model.translate(params, src, 8, bos_id=1))
    # force every token to be "eos": all emissions after the first must be 0
    eos = int(out_free[0, 0])
    out = np.asarray(model.translate(params, src, 8, bos_id=1, eos_id=eos))
    assert out[0, 0] == eos
    assert (out[0, 1:] == 0).all(), out


def test_transformer_translate_beam():
    """beam_size=1 beam search == greedy translate; wider beams return
    in-vocab sequences with a no-worse model score than greedy."""
    import jax.numpy as jnp
    from bigdl_tpu.nn import Transformer
    model = Transformer(vocab_size=29, hidden_size=16, num_heads=2,
                        filter_size=32, num_hidden_layers=2,
                        mode="translation", max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    src = jnp.asarray(np.random.RandomState(0).randint(1, 29, (3, 6)),
                      jnp.int32)
    greedy = model.translate(params, src, max_new_tokens=5, bos_id=1)
    beam1 = model.translate_beam(params, src, max_new_tokens=5,
                                 beam_size=1, bos_id=1)
    assert np.array_equal(np.asarray(greedy), np.asarray(beam1))

    beam4 = model.translate_beam(params, src, max_new_tokens=5,
                                 beam_size=4, bos_id=1)
    assert beam4.shape == (3, 5)
    b = np.asarray(beam4)
    assert ((b >= 0) & (b < 29)).all()

    def seq_logprob(tgt):
        from bigdl_tpu.utils.table import Table
        full = jnp.concatenate([jnp.full((3, 1), 1, jnp.int32), tgt], 1)
        logits, _ = model.apply(params, {}, Table(src, full[:, :-1]),
                                training=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        gold = jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32),
                                   -1)[..., 0]
        return np.asarray(jnp.sum(gold, axis=1))

    sg = seq_logprob(jnp.asarray(greedy))
    sb = seq_logprob(beam4)
    assert (sb >= sg - 1e-4).all(), (sb, sg)  # beam never worse than greedy


def test_translate_beam_score_monotone_in_width():
    """The best final model score is non-decreasing in beam width (a
    classic beam-search implementation property)."""
    import jax.numpy as jnp
    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.utils.table import Table
    model = Transformer(vocab_size=17, hidden_size=12, num_heads=2,
                        filter_size=24, num_hidden_layers=1,
                        mode="translation", max_len=16)
    params, _ = model.init(jax.random.PRNGKey(2))
    src = jnp.asarray(np.random.RandomState(3).randint(1, 17, (2, 5)),
                      jnp.int32)

    def score(tgt):
        full = jnp.concatenate([jnp.full((2, 1), 1, jnp.int32), tgt], 1)
        logits, _ = model.apply(params, {}, Table(src, full[:, :-1]),
                                training=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        gold = jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32),
                                   -1)[..., 0]
        return np.asarray(jnp.sum(gold, axis=1))

    prev = None
    for k in (1, 2, 4, 8):
        s = score(model.translate_beam(params, src, 4, beam_size=k,
                                       bos_id=1))
        if prev is not None:
            assert (s >= prev - 1e-4).all(), (k, s, prev)
        prev = s
