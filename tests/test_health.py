"""Health-layer tests: stall watchdog (injected stager stall detected
within its deadline), anomaly detectors (spike/plateau/NaN-streak with
step provenance), device-memory telemetry degradation, profiler
windows, flight-recorder crash bundles (written on an injected step
failure and parseable by ``tools/flight_report.py``), per-request
serving stage traces (request id in all three stage spans), gauge
``set_fn`` hardening, the folded-stack trace report, and the
disabled-mode zero-new-events guarantee."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu import observability as obs
from bigdl_tpu.observability import flight, health
from bigdl_tpu.observability.metrics import MetricsRegistry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts disabled with empty tracer/registry/flight ring
    and no live beacons, and cannot leak state into unrelated tests."""
    obs.disable()
    obs.reset()
    obs.registry().reset()
    flight.reset()
    health.reset()
    yield
    obs.disable()
    obs.reset()
    obs.registry().reset()
    flight.reset()
    health.reset()
    t_end = time.monotonic() + 5.0
    while health.watchdog_threads_alive() and time.monotonic() < t_end:
        time.sleep(0.02)
    assert health.watchdog_threads_alive() == 0


def _mlp():
    return nn.Sequential().add(nn.Linear(16, 8)).add(nn.ReLU()) \
                          .add(nn.Linear(8, 1))


def _train(steps=4, batch=8, model=None, end_trigger=None, **opt_kw):
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    rng = np.random.RandomState(0)
    x = rng.rand(batch * steps, 16).astype(np.float32)
    y = rng.rand(batch * steps, 1).astype(np.float32)
    opt = LocalOptimizer(model or _mlp(), (x, y), nn.MSECriterion(),
                         optim_method=SGD(learningrate=0.01),
                         end_trigger=end_trigger or max_iteration(steps),
                         batch_size=batch)
    for k, v in opt_kw.items():
        setattr(opt, k, v)
    opt.optimize()
    return opt


# ---------------------------------------------------------------- watchdog

def test_watchdog_detects_injected_stager_stall():
    """A stager whose source hangs mid-epoch must fire ``health/stall``
    before 2x its deadline (the ISSUE acceptance bound)."""
    from bigdl_tpu.optim.staging import BatchStager
    obs.enable()
    release = threading.Event()
    fired = threading.Event()
    events = []

    def listener(ev):
        if ev["kind"] == "health/stall" and \
                ev.get("component", "").startswith("stager/"):
            events.append(ev)
            fired.set()
    health.listeners.append(listener)

    def source():
        yield 1
        yield 2
        release.wait(10.0)  # injected stall: the source wedges here
        yield 3

    deadline = 0.25
    stager = BatchStager(source(), lambda v: v, depth=2,
                         name="stall_test", stall_deadline_s=deadline)
    try:
        assert next(stager) == 1
        assert next(stager) == 2
        t0 = time.monotonic()
        assert fired.wait(2 * deadline + 1.0), "stall never detected"
        detect_s = time.monotonic() - t0
        assert detect_s <= 2 * deadline + 0.5, \
            f"stall detected after {detect_s:.2f}s (deadline {deadline}s)"
        ev = events[0]
        assert ev["component"] == "stager/stall_test"
        assert ev["deadline_s"] == deadline
        assert ev["age_s"] > deadline
        # structured sinks: counter + instant span + flight entry
        assert obs.registry().get("health/stall").value >= 1.0
        assert any(e.name == "health/stall"
                   for e in obs.get_tracer().events())
        assert any(e["kind"] == "health/stall"
                   for e in flight.recorder().events())
    finally:
        release.set()
        stager.close()


def test_group_mode_stager_pulses_per_item_not_per_group():
    """Superstep stacking: the worker emits one element per K source
    items, but the beacon must pulse per ITEM — a healthy-but-slow
    producer under K>1 must not page as a stall."""
    from bigdl_tpu.optim.staging import BatchStager
    obs.enable()

    def source():
        for i in range(8):
            time.sleep(0.08)  # per-item < deadline, per-GROUP(4) > deadline
            yield i

    stager = BatchStager(source(), lambda v: v, depth=2, name="group_test",
                         group=4, group_fn=lambda items: list(items),
                         stall_deadline_s=0.2)
    try:
        assert next(stager) == [0, 1, 2, 3]
        assert next(stager) == [4, 5, 6, 7]
    finally:
        stager.close()
    assert obs.registry().get("health/stall") is None, \
        "healthy K-grouped producer paged as a stall"


def test_stall_deadline_zero_disables_watchdog(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_STALL_S", "0")
    obs.enable()
    b = health.beacon("t/disabled")
    assert b is health.NULL_BEACON  # off-switch, not a ValueError
    b.pulse()
    b.close()
    assert health.watchdog().beacons() == []
    # an explicit per-call deadline of 0 disables that beacon too
    assert health.beacon("t/x", deadline_s=0) is health.NULL_BEACON
    monkeypatch.setenv("BIGDL_TPU_STALL_S", "not-a-number")
    assert health.default_stall_deadline() == 600.0  # parse fallback


def test_watchdog_stall_recovers_and_rearms():
    obs.enable()
    b = health.beacon("t/loop", deadline_s=0.1)
    try:
        time.sleep(0.3)
        assert b.stalled
        b.pulse()  # progress resumes
        assert not b.stalled
        assert obs.registry().get("health/stall_recovered").value == 1.0
        time.sleep(0.3)  # goes quiet again -> a SECOND stall fires
        assert obs.registry().get("health/stall").value == 2.0
    finally:
        b.close()


def test_watchdog_on_stall_callback():
    obs.enable()
    hits = []
    b = health.beacon("t/cb", deadline_s=0.1,
                      on_stall=lambda beacon, age: hits.append(
                          (beacon.name, age)))
    try:
        time.sleep(0.3)
        assert hits and hits[0][0] == "t/cb" and hits[0][1] > 0.1
    finally:
        b.close()


def test_optimizer_run_registers_and_clears_step_beacon():
    obs.enable()
    _train(steps=2, stall_deadline_s=30.0)
    # run finished: no beacon left registered, watchdog winds down
    assert health.watchdog().beacons() == []
    assert obs.registry().get("health/stall") is None


# -------------------------------------------------------- anomaly detectors

def test_series_monitor_spike_with_provenance():
    m = health.SeriesMonitor("loss", window=16, min_points=4,
                             spike_sigma=3.0)
    evs = []
    for i, v in enumerate([1.0, 0.98, 0.96, 0.94, 0.92, 0.9]):
        evs += m.observe(v, i)
    assert evs == []
    evs = m.observe(100.0, 6)
    assert len(evs) == 1
    ev = evs[0]
    assert ev["kind"] == "health/loss_spike"
    assert ev["step"] == 6 and ev["value"] == 100.0
    assert ev["sigma"] >= 3.0


def test_series_monitor_plateau_and_rearm():
    m = health.SeriesMonitor("loss", plateau_window=5, plateau_rel=1e-3,
                             min_points=1000)  # spikes off
    evs = []
    for i in range(20):
        evs += m.observe(0.5, i)
    kinds = [e["kind"] for e in evs]
    # recurring: once per FULL stale window (5, 10, 15) — never per
    # step, but a flat run keeps reporting so plateau COUNTS (repeated
    # LR cuts, early_stop_plateaus) can grow without an improvement
    assert kinds == ["health/plateau"] * 3
    assert evs[0]["best_step"] == 0 and evs[0]["step"] == 5
    assert [e["step"] for e in evs] == [5, 10, 15]
    assert evs[-1]["stale_steps"] == 15
    # a new best re-arms the detector
    evs = m.observe(0.1, 30)
    assert evs == []
    evs = []
    for i in range(31, 40):
        evs += m.observe(0.1, i)
    assert [e["kind"] for e in evs] == ["health/plateau"]


def test_series_monitor_nan_streak_fires_once_at_threshold():
    m = health.SeriesMonitor("loss", nan_streak=3)
    evs = m.observe(0.5, 1)
    evs += m.observe(float("nan"), 2)
    evs += m.observe(float("inf"), 3)
    assert evs == []
    evs = m.observe(float("nan"), 4)
    assert [e["kind"] for e in evs] == ["health/nan_streak"]
    assert evs[0]["step"] == 4 and evs[0]["streak"] == 3
    assert m.observe(float("nan"), 5) == []  # no re-fire mid-streak
    m.observe(0.4, 6)  # finite value re-arms
    for step in (7, 8):
        assert m.observe(float("nan"), step) == []
    assert [e["kind"] for e in m.observe(float("nan"), 9)] == \
        ["health/nan_streak"]


def test_training_nan_streak_event_from_skip_policy():
    """The detector rides the losses the loop already syncs: a training
    run whose data turns to NaN emits health/nan_streak with step
    provenance and zero extra readbacks."""
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    obs.enable()
    x = np.full((32, 16), np.nan, np.float32)
    y = np.ones((32, 1), np.float32)
    opt = LocalOptimizer(_mlp(), (x, y), nn.MSECriterion(),
                         optim_method=SGD(learningrate=0.01),
                         end_trigger=max_iteration(4), batch_size=8)
    opt.set_nan_policy("skip")
    opt.set_anomaly_detection(nan_streak=3)
    opt.optimize()
    c = obs.registry().get("health/nan_streak")
    assert c is not None and c.value == 1.0
    ev = [e for e in flight.recorder().events()
          if e["kind"] == "health/nan_streak"]
    assert ev and ev[0]["streak"] == 3


def test_training_loss_spike_detected_in_superstep_vector():
    """Superstep-vector aware: the host replay of the batched [K]
    readback feeds the detector per microstep."""
    m = health.SeriesMonitor("loss", window=32, min_points=4,
                             spike_sigma=3.0)
    # simulate two supersteps of K=4 resolved vectors
    for i, v in enumerate([0.5, 0.49, 0.5, 0.51]):
        m.observe(v, i + 1)
    evs = []
    for i, v in enumerate([0.5, 30.0, 0.49, 0.5]):
        evs += m.observe(v, 5 + i)
    assert [e["kind"] for e in evs] == ["health/loss_spike"]
    assert evs[0]["step"] == 6


# ------------------------------------------------- memory + profiler window

def test_memory_telemetry_degrades_gracefully():
    obs.enable()
    ok = health.ensure_memory_telemetry()
    live = obs.registry().get("mem/device_live_bytes")
    if ok:
        assert live is not None and live.value >= 0
        assert obs.registry().get("mem/device_peak_bytes").value >= \
            live.value * 0  # readable
        assert health.sample_device_memory()["devices"] >= 1
    else:
        # backends without memory_stats register NOTHING (no dead rows)
        assert live is None
        assert health.sample_device_memory() is None


def test_profiler_window_env_parse_and_ticks(monkeypatch, tmp_path):
    obs.enable()
    calls = []
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    w = health.profiler_window_from_env(
        {"BIGDL_TPU_PROFILE": "2:4",
         "BIGDL_TPU_PROFILE_DIR": str(tmp_path)})
    assert w is not None and w.start_step == 2 and w.stop_step == 4
    for step in range(6):
        w.maybe_tick(step)
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert w.done and not w.active
    names = [e.name for e in obs.get_tracer().events()]
    assert "health/profile_start" in names
    assert "health/profile_stop" in names
    # malformed/unset specs never raise
    assert health.profiler_window_from_env({}) is None
    assert health.profiler_window_from_env(
        {"BIGDL_TPU_PROFILE": "garbage"}) is None


# ----------------------------------------------------------- crash bundles

class _DetonateAt:
    """End-trigger that raises at iteration n: a deterministic injected
    mid-run step failure."""

    def __init__(self, n):
        self.n = n

    def __call__(self, state):
        if state.get("neval", 0) >= self.n:
            raise RuntimeError("injected step failure")
        return False


def test_crash_bundle_on_injected_step_failure(tmp_path, monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", str(tmp_path))
    obs.enable()
    steps = 40
    with pytest.raises(RuntimeError, match="injected step failure"):
        _train(steps=steps, end_trigger=_DetonateAt(steps))
    bundles = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    assert len(bundles) == 1
    with open(tmp_path / bundles[0]) as f:
        bundle = json.load(f)
    # schema + error + context provenance
    assert bundle["schema"] == flight.SCHEMA
    assert bundle["error"]["type"] == "RuntimeError"
    assert "injected step failure" in bundle["error"]["traceback"]
    ctx = bundle["context"]
    assert ctx["component"] == "optimizer"
    assert ctx["neval"] == steps and ctx["seed"] == 42
    # the ring holds the last >= 32 events with correct step/batch
    # provenance (ISSUE acceptance)
    ev_steps = [e for e in bundle["events"] if e["kind"] == "step"]
    assert len(ev_steps) >= 32
    nevals = [e["neval"] for e in ev_steps]
    assert nevals == list(range(1, steps + 1))
    assert all(e["epoch"] == 1 for e in ev_steps)
    assert all(np.isfinite(e["loss"]) for e in ev_steps)
    # metrics + span tail rode along
    assert "optim/steps" in bundle["metrics"]
    assert bundle["metrics"]["optim/steps"]["value"] == steps
    assert any(sp["name"] == "step/dispatch" for sp in bundle["spans"])


def test_crash_bundle_parseable_by_flight_report(tmp_path, monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", str(tmp_path))
    obs.enable()
    with pytest.raises(RuntimeError):
        _train(steps=6, end_trigger=_DetonateAt(6))
    bundle = [f for f in os.listdir(tmp_path) if f.endswith(".json")][0]
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "flight_report.py"),
         str(tmp_path / bundle), "--spans"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "RuntimeError: injected step failure" in out
    assert "component=optimizer" in out
    assert "optim/steps" in out
    assert "traceback:" in out
    # unreadable input is a clean nonzero, not a traceback
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "flight_report.py"),
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1


def test_crash_bundle_from_nan_abort(tmp_path, monkeypatch):
    """The nan_policy='error' abort is an unhandled failure too — the
    bundle names FloatingPointError and the nan event precedes it."""
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", str(tmp_path))
    obs.enable()
    x = np.full((16, 16), np.nan, np.float32)
    y = np.ones((16, 1), np.float32)
    opt = LocalOptimizer(_mlp(), (x, y), nn.MSECriterion(),
                         optim_method=SGD(learningrate=0.01),
                         end_trigger=max_iteration(2), batch_size=8)
    with pytest.raises(FloatingPointError):
        opt.optimize()
    bundle = [f for f in os.listdir(tmp_path) if f.endswith(".json")][0]
    with open(tmp_path / bundle) as f:
        doc = json.load(f)
    assert doc["error"]["type"] == "FloatingPointError"
    assert doc["context"]["nan_policy"] == "error"
    assert any(e["kind"] == "nan" for e in doc["events"])


def test_window_policy_flight_provenance_names_the_producing_step():
    """Under window:K the resolved loss is up to K-1 dispatches old —
    flight/anomaly events must attribute it to the step that PRODUCED
    it, not the step that read it."""
    from bigdl_tpu.utils import engine
    steps = 6
    obs.enable()
    engine.set_seed(42)  # identical init/rng for both arms
    _train(steps=steps)  # sync baseline: per-step ground-truth losses
    truth = {e["neval"]: e["loss"] for e in flight.recorder().events()
             if e["kind"] == "step"}
    assert len(truth) == steps
    flight.reset()
    obs.reset()
    engine.set_seed(42)
    opt = _train(steps=steps, sync_policy="window:3")
    lagged = [(e["neval"], e["loss"]) for e in flight.recorder().events()
              if e["kind"] == "step"]
    # K-1 tail losses drain after the loop (no flight record) — the
    # observed ones must carry their ORIGINAL step numbers and values
    assert [n for n, _ in lagged] == list(range(1, steps - 2 + 1))
    for neval, loss in lagged:
        assert loss == truth[neval], (neval, loss, truth[neval])
    assert opt._resolved_step == steps - 2


def test_crash_bundle_is_strict_json_despite_nan_events(tmp_path):
    """A NaN post-mortem must be valid STRICT json — jq/JSON.parse on
    the remote-fetched bundle is the documented workflow."""
    obs.enable()
    flight.record("nan", neval=3, loss=float("nan"))
    flight.record("spike", value=float("inf"), floor=float("-inf"))
    p = flight.dump_crash_bundle(
        error=FloatingPointError("non-finite loss nan"),
        path=str(tmp_path / "b.json"))
    text = open(p).read()

    def no_const(name):  # strict parsers reject NaN/Infinity tokens
        raise AssertionError(f"bare {name} token in bundle")
    doc = json.loads(text, parse_constant=no_const)
    evs = {e["kind"]: e for e in doc["events"]}
    assert evs["nan"]["loss"] == "NaN"
    assert evs["spike"]["value"] == "Infinity"
    assert evs["spike"]["floor"] == "-Infinity"


def test_profiler_window_jumped_over_reports_skip(monkeypatch):
    """Superstep ticks arrive at K-step stride: a window narrower than
    the stride is reported (warning + health/profile_skipped), never
    silently lost."""
    obs.enable()
    calls = []
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"))
    w = health.ProfilerWindow(2, 3, "/tmp/nope")
    w.maybe_tick(0)
    w.maybe_tick(4)  # jumped clean over [2, 3)
    assert w.done and not w.active and calls == []
    assert obs.registry().get("health/profile_skipped").value == 1.0
    w.maybe_tick(8)  # done: no re-fire
    assert obs.registry().get("health/profile_skipped").value == 1.0


def test_flight_ring_is_bounded():
    obs.enable()
    rec = flight.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("step", neval=i)
    evs = rec.events()
    assert len(evs) == 8
    assert [e["neval"] for e in evs] == list(range(12, 20))
    assert rec.total_recorded == 20


# ------------------------------------------- per-request serving traces

def test_serving_request_id_in_all_three_stage_spans():
    from bigdl_tpu.serving import ServingEngine
    obs.enable()
    model = _mlp()
    engine = ServingEngine(model, input_shape=(16,), max_batch=4,
                           max_wait_ms=1.0, warmup=False)
    with engine:
        futs = [engine.submit(np.zeros(16, np.float32)) for _ in range(3)]
        outs = [f.result(timeout=30.0) for f in futs]
    assert all(o.shape == (1,) for o in outs)
    rids = [f.rid for f in futs]
    assert sorted(rids) == [0, 1, 2]  # minted at submit, in order
    spans = obs.get_tracer().events()
    by_name = {}
    for sp in spans:
        by_name.setdefault(sp.name, []).append(sp)
    # every request id appears in all three stage spans
    qw = by_name["serve/queue_wait"]
    # overlapping retro waits each ride their own virtual lane —
    # containment tooling (trace_report) must not fake-nest them
    assert len({sp.tid for sp in qw}) == len(qw)
    assert all(sp.tid < 0 for sp in qw)
    qw_rids = {sp.args["rid"] for sp in qw}
    asm_rids = {r for sp in by_name["serve/assemble"]
                for r in sp.args["rids"]}
    dsp_rids = {r for sp in by_name["serve/dispatch"]
                for r in sp.args["rids"]}
    for rid in rids:
        assert rid in qw_rids and rid in asm_rids and rid in dsp_rids
    # stage histograms observed and decomposable
    for h in ("serve/queue_wait_ms", "serve/assemble_ms",
              "serve/dispatch_ms"):
        hist = obs.registry().get(h)
        assert hist is not None and hist.count >= 1, h
    # each future carries its trace with consistent ids
    for f in futs:
        tr = f.trace
        assert tr is not None and tr["rid"] == f.rid
        assert tr["queue_wait_ms"] >= 0.0
        assert tr["dispatch_ms"] > 0.0
        assert tr["version"] == f.version


def test_serving_trace_attached_even_when_disabled():
    """The trace dict is provenance, not telemetry: it rides the future
    regardless of the observability flag (host floats, no spans)."""
    from bigdl_tpu.serving import ServingEngine
    assert not obs.enabled()
    engine = ServingEngine(_mlp(), input_shape=(16,), max_batch=2,
                           max_wait_ms=1.0, warmup=False)
    with engine:
        fut = engine.submit(np.zeros(16, np.float32))
        fut.result(timeout=30.0)
    assert fut.trace is not None and fut.trace["rid"] == fut.rid == 0
    assert obs.get_tracer().events() == []


# -------------------------------------------------- gauge set_fn hardening

def test_raising_gauge_fn_does_not_break_exports():
    reg = MetricsRegistry()
    reg.gauge("t/good").set(1.5)

    def boom():
        raise RuntimeError("dead callback")
    reg.gauge("t/bad").set_fn(boom)
    reg.counter("t/count").inc(2)

    snap = reg.snapshot()  # must not raise
    assert snap["t/good"]["value"] == 1.5
    assert np.isnan(snap["t/bad"]["value"])
    assert snap["t/count"]["value"] == 2.0

    from bigdl_tpu.observability.exporters import prometheus_text
    text = prometheus_text(reg)  # must not raise either
    assert "bigdl_t_good 1.5" in text
    assert "bigdl_t_bad NaN" in text
    # failures are counted in the default registry
    errs = obs.registry().get("obs/gauge_fn_errors")
    assert errs is not None and errs.value == 2.0  # snapshot + prom read


# --------------------------------------------------- folded-stack report

def test_trace_report_collapsed_output(tmp_path):
    obs.enable()
    for _ in range(2):
        with obs.span("step"):
            with obs.span("step/dispatch"):
                time.sleep(0.002)
            with obs.span("step/data_fetch"):
                pass
    trace = obs.write_chrome_trace(str(tmp_path / "t.json"))
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         trace, "--collapsed"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    lines = dict(l.rsplit(" ", 1) for l in proc.stdout.strip().splitlines())
    assert "step;step/dispatch" in lines
    assert "step;step/data_fetch" in lines
    assert int(lines["step;step/dispatch"]) >= 4000  # 2 x 2ms in µs
    # parent line carries SELF time only (children subtracted)
    if "step" in lines:
        assert int(lines["step"]) < int(lines["step;step/dispatch"])


# -------------------------------------------------- disabled-mode overhead

def test_disabled_mode_records_zero_new_events():
    """The whole health layer compiles away when observability is off:
    a full training run plus a health-API exercise leaves the tracer,
    registry, flight ring and watchdog all empty."""
    assert not obs.enabled()
    opt = _train(steps=2, stall_deadline_s=5.0)
    assert opt._step_beacon is health.NULL_BEACON
    b = health.beacon("t/should_be_null", deadline_s=0.01)
    assert b is health.NULL_BEACON
    b.pulse()
    b.close()
    flight.record("step", neval=1)
    health.emit("stall", component="nope")  # listeners-only when disabled
    time.sleep(0.05)
    assert obs.get_tracer().events() == []
    assert obs.registry().names() == []
    assert flight.recorder().events() == []
    assert health.watchdog().beacons() == []
    assert health.watchdog_threads_alive() == 0
