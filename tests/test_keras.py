"""Keras API tests (modeled on reference nn/keras specs +
pyspark/test keras tests)."""
import numpy as np
import pytest

from bigdl_tpu import keras as K
from bigdl_tpu.dataset import mnist


def test_sequential_shape_inference():
    model = K.Sequential()
    model.add(K.Convolution2D(8, 3, 3, activation="relu",
                              input_shape=(1, 28, 28)))
    model.add(K.MaxPooling2D((2, 2)))
    model.add(K.Flatten())
    model.add(K.Dense(32, activation="relu"))
    model.add(K.Dense(10, activation="softmax"))
    assert model.output_shape == (10,)
    assert model.shapes[0] == (8, 26, 26)
    assert model.shapes[1] == (8, 13, 13)
    assert model.shapes[2] == (8 * 13 * 13,)
    x = np.random.randn(4, 1, 28, 28).astype(np.float32)
    out = model._module().evaluate().forward(x)
    assert out.shape == (4, 10)
    assert np.allclose(np.asarray(out).sum(-1), 1.0, atol=1e-4)


def test_sequential_fit_mnist():
    imgs, labels = mnist.load(n_synthetic=256)
    x = mnist.normalize(imgs)[:, None]
    y = labels - 1  # keras 0-based labels
    model = K.Sequential()
    model.add(K.Flatten(input_shape=(1, 28, 28)))
    model.add(K.Dense(64, activation="relu"))
    model.add(K.Dense(10))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=32, nb_epoch=3)
    loss, acc = model.evaluate(x, y)  # keras order: [loss, *metrics]
    assert acc > 0.8, acc
    pred = model.predict_classes(x[:16])
    assert pred.shape == (16,)
    assert pred.max() <= 9


def test_functional_model_with_merge():
    inp = K.Input(shape=(16,))
    a = K.Dense(8, activation="relu")(inp)
    b = K.Dense(8, activation="tanh")(inp)
    merged = K.Merge(mode="concat")([a, b])
    out = K.Dense(2)(merged)
    model = K.Model(inp, out)
    assert out.shape == (2,)
    assert merged.shape == (16,)
    x = np.random.randn(5, 16).astype(np.float32)
    y = model._module().forward(x)
    assert y.shape == (5, 2)


def test_lstm_layers():
    model = K.Sequential()
    model.add(K.Embedding(100, 16, input_length=12))
    model.add(K.LSTM(24, return_sequences=True))
    model.add(K.LSTM(8))
    model.add(K.Dense(2, activation="softmax"))
    assert model.output_shape == (2,)
    ids = np.random.randint(0, 100, size=(3, 12)).astype(np.float32)
    out = model._module().evaluate().forward(ids)
    assert out.shape == (3, 2)


def test_bidirectional():
    model = K.Sequential()
    model.add(K.Bidirectional(K.GRU(6, return_sequences=True),
                              merge_mode="concat", input_shape=(10, 4)))
    assert model.output_shape == (10, 12)
    x = np.random.randn(2, 10, 4).astype(np.float32)
    assert model._module().forward(x).shape == (2, 10, 12)


def test_misc_layers_shapes():
    m = K.Sequential()
    m.add(K.Reshape((4, 16), input_shape=(64,)))
    m.add(K.Permute((2, 1)))
    assert m.output_shape == (16, 4)
    m.add(K.Flatten())
    m.add(K.RepeatVector(3))
    assert m.output_shape == (3, 64)
    x = np.random.randn(2, 64).astype(np.float32)
    assert m._module().forward(x).shape == (2, 3, 64)


def test_batchnorm_timedistributed():
    m = K.Sequential()
    m.add(K.TimeDistributed(K.Dense(7), input_shape=(5, 3)))
    assert m.output_shape == (5, 7)
    x = np.random.randn(2, 5, 3).astype(np.float32)
    assert m._module().forward(x).shape == (2, 5, 7)

    m2 = K.Sequential()
    m2.add(K.BatchNormalization(input_shape=(4, 8, 8)))
    x = np.random.randn(2, 4, 8, 8).astype(np.float32)
    assert m2._module().forward(x).shape == (2, 4, 8, 8)


# ---- long-tail keras layer set: shape inference == actual forward shape ----

_LONGTAIL = [
    (lambda: K.SoftMax(input_shape=(6,)), (6,)),
    (lambda: K.AtrousConvolution2D(4, 3, 3, atrous_rate=(2, 2),
                                   input_shape=(2, 12, 12)), (2, 12, 12)),
    (lambda: K.AtrousConvolution1D(4, 3, atrous_rate=2,
                                   input_shape=(10, 5)), (10, 5)),
    (lambda: K.SeparableConvolution2D(6, 3, 3, border_mode="same",
                                      depth_multiplier=2,
                                      input_shape=(2, 8, 8)), (2, 8, 8)),
    (lambda: K.Deconvolution2D(3, 3, 3, subsample=(2, 2),
                               input_shape=(2, 5, 5)), (2, 5, 5)),
    (lambda: K.Convolution3D(4, 2, 3, 3, input_shape=(2, 5, 8, 8)),
     (2, 5, 8, 8)),
    (lambda: K.LocallyConnected1D(4, 3, input_shape=(9, 5)), (9, 5)),
    (lambda: K.LocallyConnected2D(4, 3, 3, input_shape=(2, 7, 7)),
     (2, 7, 7)),
    (lambda: K.Cropping1D((1, 2), input_shape=(8, 3)), (8, 3)),
    (lambda: K.Cropping3D(((1, 1), (0, 1), (1, 0)),
                          input_shape=(2, 5, 6, 6)), (2, 5, 6, 6)),
    (lambda: K.ZeroPadding1D(2, input_shape=(5, 3)), (5, 3)),
    (lambda: K.ZeroPadding3D((1, 2, 1), input_shape=(2, 3, 4, 4)),
     (2, 3, 4, 4)),
    (lambda: K.UpSampling1D(3, input_shape=(4, 2)), (4, 2)),
    (lambda: K.UpSampling3D((2, 2, 2), input_shape=(2, 3, 4, 4)),
     (2, 3, 4, 4)),
    (lambda: K.AveragePooling1D(2, input_shape=(8, 3)), (8, 3)),
    (lambda: K.AveragePooling1D(3, 2, border_mode="same",
                                input_shape=(9, 3)), (9, 3)),
    (lambda: K.MaxPooling3D((2, 2, 2), input_shape=(2, 4, 6, 6)),
     (2, 4, 6, 6)),
    (lambda: K.AveragePooling3D((2, 2, 2), input_shape=(2, 4, 6, 6)),
     (2, 4, 6, 6)),
    (lambda: K.GlobalMaxPooling1D(input_shape=(7, 4)), (7, 4)),
    (lambda: K.GlobalMaxPooling3D(input_shape=(3, 4, 5, 5)), (3, 4, 5, 5)),
    (lambda: K.GlobalAveragePooling3D(input_shape=(3, 4, 5, 5)),
     (3, 4, 5, 5)),
    (lambda: K.ConvLSTM2D(4, 3, return_sequences=True,
                          input_shape=(3, 2, 6, 6)), (3, 2, 6, 6)),
    (lambda: K.ConvLSTM2D(4, 3, input_shape=(3, 2, 6, 6)), (3, 2, 6, 6)),
    (lambda: K.MaxoutDense(6, 3, input_shape=(5,)), (5,)),
    (lambda: K.PReLU(input_shape=(4, 5)), (4, 5)),
    (lambda: K.SReLU(input_shape=(4, 5)), (4, 5)),
    (lambda: K.SpatialDropout1D(0.3, input_shape=(6, 4)), (6, 4)),
    (lambda: K.SpatialDropout3D(0.3, input_shape=(2, 3, 4, 4)),
     (2, 3, 4, 4)),
    (lambda: K.MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                            input_shape=(2, 9, 9)), (2, 9, 9)),
    (lambda: K.AveragePooling2D((2, 2), border_mode="same",
                                input_shape=(2, 7, 7)), (2, 7, 7)),
]


@pytest.mark.parametrize("make,in_shape", _LONGTAIL,
                         ids=lambda v: getattr(v, "__name__", str(v)))
def test_longtail_layer_shape(make, in_shape):
    layer = make()
    model = K.Sequential().add(layer)
    x = np.random.randn(2, *in_shape).astype(np.float32)
    out = model._module().evaluate().forward(x)
    assert tuple(out.shape) == (2,) + tuple(model.output_shape), \
        f"{type(layer).__name__}: inferred {model.output_shape}, " \
        f"got {out.shape[1:]}"


def test_longtail_softmax_values():
    model = K.Sequential().add(K.SoftMax(input_shape=(7,)))
    x = np.random.randn(3, 7).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    assert np.allclose(out.sum(-1), 1.0, atol=1e-5)


def test_longtail_cropping_values():
    model = K.Sequential().add(K.Cropping1D((1, 2), input_shape=(8, 3)))
    x = np.random.randn(2, 8, 3).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    assert np.allclose(out, x[:, 1:6])


def test_longtail_zeropadding_values():
    model = K.Sequential().add(K.ZeroPadding1D((1, 2), input_shape=(4, 3)))
    x = np.random.randn(2, 4, 3).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    assert out.shape == (2, 7, 3)
    assert np.allclose(out[:, 1:5], x)
    assert np.allclose(out[:, 0], 0) and np.allclose(out[:, 5:], 0)


def test_longtail_dense_grad_flows():
    # a deconv stack still trains end-to-end
    model = K.Sequential()
    model.add(K.Deconvolution2D(2, 3, 3, activation="relu",
                                input_shape=(1, 4, 4)))
    model.add(K.Flatten())
    model.add(K.Dense(3))
    x = np.random.randn(8, 1, 4, 4).astype(np.float32)
    y = np.random.randint(0, 3, 8)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    model.fit(x, y, batch_size=4, nb_epoch=1)


def test_longtail_avgpool1d_same_values():
    # keras 'same' average pooling excludes padding from the denominator
    model = K.Sequential().add(
        K.AveragePooling1D(3, 2, border_mode="same", input_shape=(5, 1)))
    x = np.arange(5, dtype=np.float32).reshape(1, 5, 1)
    out = np.asarray(model._module().evaluate().forward(x)).ravel()
    assert np.allclose(out, [0.5, 2.0, 3.5]), out


def test_longtail_locallyconnected2d_same_shape():
    model = K.Sequential().add(
        K.LocallyConnected2D(4, 4, 4, border_mode="same",
                             input_shape=(2, 7, 7)))
    x = np.random.randn(2, 2, 7, 7).astype(np.float32)
    out = model._module().evaluate().forward(x)
    assert tuple(out.shape) == (2,) + tuple(model.output_shape) == \
        (2, 4, 7, 7)


def test_longtail_unsupported_modes_raise():
    with pytest.raises(ValueError):
        K.AtrousConvolution2D(4, 3, 3, border_mode="same")
    with pytest.raises(ValueError):
        K.Deconvolution2D(4, 3, 3, border_mode="same")
    with pytest.raises(ValueError):
        K.ConvLSTM2D(4, 3, activation="relu")
    with pytest.raises(ValueError):
        K.ConvLSTM2D(4, 3, border_mode="valid")
