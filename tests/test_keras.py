"""Keras API tests (modeled on reference nn/keras specs +
pyspark/test keras tests)."""
import numpy as np
import pytest

from bigdl_tpu import keras as K
from bigdl_tpu.dataset import mnist


def test_sequential_shape_inference():
    model = K.Sequential()
    model.add(K.Convolution2D(8, 3, 3, activation="relu",
                              input_shape=(1, 28, 28)))
    model.add(K.MaxPooling2D((2, 2)))
    model.add(K.Flatten())
    model.add(K.Dense(32, activation="relu"))
    model.add(K.Dense(10, activation="softmax"))
    assert model.output_shape == (10,)
    assert model.shapes[0] == (8, 26, 26)
    assert model.shapes[1] == (8, 13, 13)
    assert model.shapes[2] == (8 * 13 * 13,)
    x = np.random.randn(4, 1, 28, 28).astype(np.float32)
    out = model._module().evaluate().forward(x)
    assert out.shape == (4, 10)
    assert np.allclose(np.asarray(out).sum(-1), 1.0, atol=1e-4)


def test_sequential_fit_mnist():
    imgs, labels = mnist.load(n_synthetic=256)
    x = mnist.normalize(imgs)[:, None]
    y = labels - 1  # keras 0-based labels
    model = K.Sequential()
    model.add(K.Flatten(input_shape=(1, 28, 28)))
    model.add(K.Dense(64, activation="relu"))
    model.add(K.Dense(10))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=32, nb_epoch=3)
    acc = model.evaluate(x, y)[0]
    assert acc > 0.8, acc
    pred = model.predict_classes(x[:16])
    assert pred.shape == (16,)
    assert pred.max() <= 9


def test_functional_model_with_merge():
    inp = K.Input(shape=(16,))
    a = K.Dense(8, activation="relu")(inp)
    b = K.Dense(8, activation="tanh")(inp)
    merged = K.Merge(mode="concat")([a, b])
    out = K.Dense(2)(merged)
    model = K.Model(inp, out)
    assert out.shape == (2,)
    assert merged.shape == (16,)
    x = np.random.randn(5, 16).astype(np.float32)
    y = model._module().forward(x)
    assert y.shape == (5, 2)


def test_lstm_layers():
    model = K.Sequential()
    model.add(K.Embedding(100, 16, input_length=12))
    model.add(K.LSTM(24, return_sequences=True))
    model.add(K.LSTM(8))
    model.add(K.Dense(2, activation="softmax"))
    assert model.output_shape == (2,)
    ids = np.random.randint(0, 100, size=(3, 12)).astype(np.float32)
    out = model._module().evaluate().forward(ids)
    assert out.shape == (3, 2)


def test_bidirectional():
    model = K.Sequential()
    model.add(K.Bidirectional(K.GRU(6, return_sequences=True),
                              merge_mode="concat", input_shape=(10, 4)))
    assert model.output_shape == (10, 12)
    x = np.random.randn(2, 10, 4).astype(np.float32)
    assert model._module().forward(x).shape == (2, 10, 12)


def test_misc_layers_shapes():
    m = K.Sequential()
    m.add(K.Reshape((4, 16), input_shape=(64,)))
    m.add(K.Permute((2, 1)))
    assert m.output_shape == (16, 4)
    m.add(K.Flatten())
    m.add(K.RepeatVector(3))
    assert m.output_shape == (3, 64)
    x = np.random.randn(2, 64).astype(np.float32)
    assert m._module().forward(x).shape == (2, 3, 64)


def test_batchnorm_timedistributed():
    m = K.Sequential()
    m.add(K.TimeDistributed(K.Dense(7), input_shape=(5, 3)))
    assert m.output_shape == (5, 7)
    x = np.random.randn(2, 5, 3).astype(np.float32)
    assert m._module().forward(x).shape == (2, 5, 7)

    m2 = K.Sequential()
    m2.add(K.BatchNormalization(input_shape=(4, 8, 8)))
    x = np.random.randn(2, 4, 8, 8).astype(np.float32)
    assert m2._module().forward(x).shape == (2, 4, 8, 8)
