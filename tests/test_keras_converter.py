"""Keras-1.2.2 JSON definition + HDF5 weight converter tests.

Parity target: reference ``pyspark/bigdl/keras/converter.py`` — loads real
``model.to_json()`` definitions and Keras-layout weights.
"""
import json

import numpy as np
import pytest

from bigdl_tpu.keras.converter import (load_keras, load_weights,
                                       load_weights_hdf5, model_from_json)


def _layer(cls, name, **cfg):
    cfg.setdefault("name", name)
    return {"class_name": cls, "config": cfg}


def _seq_json(layers):
    return json.dumps({"class_name": "Sequential",
                       "config": [dict(l) for l in layers]})


# ---------------------------------------------------------------------------
# definition loading
# ---------------------------------------------------------------------------


def test_lenet_json_definition_shapes():
    """A LeNet-5 Sequential definition builds with the right shapes."""
    spec = [
        _layer("Convolution2D", "conv1", nb_filter=6, nb_row=5, nb_col=5,
               activation="tanh", border_mode="valid", dim_ordering="th",
               batch_input_shape=[None, 1, 28, 28]),
        _layer("MaxPooling2D", "pool1", pool_size=[2, 2], dim_ordering="th"),
        _layer("Convolution2D", "conv2", nb_filter=12, nb_row=5, nb_col=5,
               activation="tanh", dim_ordering="th"),
        _layer("MaxPooling2D", "pool2", pool_size=[2, 2], dim_ordering="th"),
        _layer("Flatten", "flat"),
        _layer("Dense", "fc1", output_dim=100, activation="tanh"),
        _layer("Dense", "fc2", output_dim=10, activation="softmax"),
    ]
    model = model_from_json(_seq_json(spec))
    assert model.output_shape == (10,)
    x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    assert out.shape == (2, 10)
    assert np.allclose(out.sum(-1), 1.0, atol=1e-4)  # softmax head


def test_mlp_json_weights_exact():
    """Dense weights load with the keras (in, out) → (out, in) transpose."""
    spec = [
        _layer("Dense", "d1", output_dim=4, activation="relu",
               batch_input_shape=[None, 3]),
        _layer("Dropout", "drop", p=0.5),
        _layer("Dense", "d2", output_dim=2),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(1)
    w1, b1 = rng.randn(3, 4).astype(np.float32), rng.randn(4).astype(
        np.float32)
    w2, b2 = rng.randn(4, 2).astype(np.float32), rng.randn(2).astype(
        np.float32)
    load_weights(model, {"d1": [w1, b1], "drop": [], "d2": [w2, b2]})
    x = rng.randn(5, 3).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    ref = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()


def test_cnn_json_weights_exact():
    """Conv2D + BN weights (incl. running stats) match a torch oracle."""
    import torch
    import torch.nn.functional as F
    spec = [
        _layer("Convolution2D", "c1", nb_filter=4, nb_row=3, nb_col=3,
               dim_ordering="th", batch_input_shape=[None, 2, 6, 6]),
        _layer("BatchNormalization", "bn", epsilon=1e-3, momentum=0.99,
               mode=0, axis=1),
        _layer("Activation", "act", activation="relu"),
        _layer("Flatten", "flat"),
        _layer("Dense", "fc", output_dim=3),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(2)
    cw = rng.randn(4, 2, 3, 3).astype(np.float32)
    cb = rng.randn(4).astype(np.float32)
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = rng.rand(4).astype(np.float32) + 0.5
    fw = rng.randn(4 * 4 * 4, 3).astype(np.float32)
    fb = rng.randn(3).astype(np.float32)
    load_weights(model, {"c1": [cw, cb], "bn": [gamma, beta, mean, var],
                         "fc": [fw, fb]})
    x = rng.randn(2, 2, 6, 6).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    t = F.conv2d(torch.tensor(x), torch.tensor(cw), torch.tensor(cb))
    t = F.batch_norm(t, torch.tensor(mean), torch.tensor(var),
                     torch.tensor(gamma), torch.tensor(beta), False,
                     eps=1e-3)
    ref = F.relu(t).flatten(1).numpy() @ fw + fb
    assert np.allclose(out, ref, atol=1e-3), np.abs(out - ref).max()


def test_functional_model_json_with_merge():
    """Functional Model graphs (inbound_nodes + Merge) convert."""
    spec = {
        "class_name": "Model",
        "config": {
            "layers": [
                {"class_name": "InputLayer", "name": "in1",
                 "config": {"batch_input_shape": [None, 4], "name": "in1"},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "a",
                 "config": {"output_dim": 3, "name": "a"},
                 "inbound_nodes": [[["in1", 0, 0]]]},
                {"class_name": "Dense", "name": "b",
                 "config": {"output_dim": 3, "name": "b"},
                 "inbound_nodes": [[["in1", 0, 0]]]},
                {"class_name": "Merge", "name": "m",
                 "config": {"mode": "concat", "concat_axis": -1, "name":
                            "m"},
                 "inbound_nodes": [[["a", 0, 0], ["b", 0, 0]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"output_dim": 2, "name": "out"},
                 "inbound_nodes": [[["m", 0, 0]]]},
            ],
            "input_layers": [["in1", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
    }
    model = model_from_json(json.dumps(spec))
    rng = np.random.RandomState(3)
    wa, ba = rng.randn(4, 3).astype(np.float32), rng.randn(3).astype(
        np.float32)
    wb, bb = rng.randn(4, 3).astype(np.float32), rng.randn(3).astype(
        np.float32)
    wo, bo = rng.randn(6, 2).astype(np.float32), rng.randn(2).astype(
        np.float32)
    load_weights(model, {"a": [wa, ba], "b": [wb, bb], "out": [wo, bo]},
                 by_name=True)
    x = rng.randn(3, 4).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    ref = np.concatenate([x @ wa + ba, x @ wb + bb], -1) @ wo + bo
    assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()


def test_text_model_lstm_embedding_weights():
    """Embedding + LSTM (per-gate keras 1.2 triples) load and run."""
    T, V, E, H = 5, 10, 4, 3
    spec = [
        _layer("Embedding", "emb", input_dim=V, output_dim=E,
               batch_input_shape=[None, T]),
        _layer("LSTM", "lstm", output_dim=H, return_sequences=False),
        _layer("Dense", "fc", output_dim=2, activation="softmax"),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(4)
    emb = rng.randn(V, E).astype(np.float32)
    # keras 1.2 per-gate order: i, c, f, o
    gates = {}
    for gname in "icfo":
        gates[gname] = (rng.randn(E, H).astype(np.float32),
                        rng.randn(H, H).astype(np.float32),
                        rng.randn(H).astype(np.float32))
    lstm_ws = []
    for gname in "icfo":
        lstm_ws.extend(gates[gname])
    fw, fb = rng.randn(H, 2).astype(np.float32), rng.randn(2).astype(
        np.float32)
    load_weights(model, {"emb": [emb], "lstm": lstm_ws, "fc": [fw, fb]})

    ids = rng.randint(0, V, size=(2, T)).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(ids))

    # numpy oracle
    def sigm(v):
        return 1.0 / (1.0 + np.exp(-v))

    xseq = emb[ids.astype(int)]
    h = np.zeros((2, H), np.float32)
    c = np.zeros((2, H), np.float32)
    for t in range(T):
        xt = xseq[:, t]
        i = sigm(xt @ gates["i"][0] + h @ gates["i"][1] + gates["i"][2])
        f = sigm(xt @ gates["f"][0] + h @ gates["f"][1] + gates["f"][2])
        g = np.tanh(xt @ gates["c"][0] + h @ gates["c"][1] + gates["c"][2])
        o = sigm(xt @ gates["o"][0] + h @ gates["o"][1] + gates["o"][2])
        c = f * c + i * g
        h = o * np.tanh(c)
    logits = h @ fw + fb
    ref = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


def test_gru_simplernn_weights_shapes():
    """GRU (9 per-gate arrays) and SimpleRNN load without shape errors."""
    T, E, H = 4, 3, 5
    spec = [
        _layer("GRU", "gru", output_dim=H, return_sequences=True,
               batch_input_shape=[None, T, E]),
        _layer("SimpleRNN", "rnn", output_dim=2),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(5)
    gru_ws = []
    for _ in "zrh":  # keras order z, r, h
        gru_ws.extend([rng.randn(E, H).astype(np.float32),
                       rng.randn(H, H).astype(np.float32),
                       rng.randn(H).astype(np.float32)])
    rnn_ws = [rng.randn(H, 2).astype(np.float32),
              rng.randn(2, 2).astype(np.float32),
              rng.randn(2).astype(np.float32)]
    load_weights(model, {"gru": gru_ws, "rnn": rnn_ws})
    x = rng.randn(2, T, E).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    assert out.shape == (2, 2)


def test_hdf5_weight_file_roundtrip(tmp_path):
    """Keras-1.2-layout HDF5 weight files load via h5py."""
    h5py = pytest.importorskip("h5py")
    spec = [
        _layer("Dense", "dense_1", output_dim=4, activation="tanh",
               batch_input_shape=[None, 3]),
        _layer("Dense", "dense_2", output_dim=2),
    ]
    rng = np.random.RandomState(6)
    w1, b1 = rng.randn(3, 4).astype(np.float32), rng.randn(4).astype(
        np.float32)
    w2, b2 = rng.randn(4, 2).astype(np.float32), rng.randn(2).astype(
        np.float32)
    path = str(tmp_path / "weights.h5")
    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = [b"dense_1", b"dense_2"]
        g1 = f.create_group("dense_1")
        g1.attrs["weight_names"] = [b"dense_1_W", b"dense_1_b"]
        g1.create_dataset("dense_1_W", data=w1)
        g1.create_dataset("dense_1_b", data=b1)
        g2 = f.create_group("dense_2")
        g2.attrs["weight_names"] = [b"dense_2_W", b"dense_2_b"]
        g2.create_dataset("dense_2_W", data=w2)
        g2.create_dataset("dense_2_b", data=b2)

    model = model_from_json(_seq_json(spec))
    load_weights_hdf5(model, path)
    x = rng.randn(5, 3).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    ref = np.tanh(x @ w1 + b1) @ w2 + b2
    assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()


def test_full_model_hdf5_with_config(tmp_path):
    """A full-model HDF5 (model_config attr + model_weights group) loads
    with one call."""
    h5py = pytest.importorskip("h5py")
    spec = [
        _layer("Dense", "d", output_dim=2, batch_input_shape=[None, 3]),
    ]
    cfg = _seq_json(spec)
    rng = np.random.RandomState(7)
    w, b = rng.randn(3, 2).astype(np.float32), rng.randn(2).astype(
        np.float32)
    path = str(tmp_path / "model.h5")
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = cfg.encode()
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = [b"d"]
        g = mw.create_group("d")
        g.attrs["weight_names"] = [b"d_W", b"d_b"]
        g.create_dataset("d_W", data=w)
        g.create_dataset("d_b", data=b)
    model = load_keras(hdf5_path=path)
    x = rng.randn(4, 3).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    assert np.allclose(out, x @ w + b, atol=1e-5)


def test_tf_ordering_builds_channels_first():
    """A keras-1.2 'tf'-ordered conv definition converts: the model is
    built channels-first with the (H, W, C) input shape transposed (round-3
    transposed-weight pipeline; exactness vs real keras is covered by
    test_tf_ordered_conv_stack_matches_real_keras)."""
    spec = [_layer("Convolution2D", "c", nb_filter=2, nb_row=3, nb_col=3,
                   dim_ordering="tf", border_mode="same",
                   batch_input_shape=[None, 8, 8, 3])]
    model = model_from_json(_seq_json(spec))
    assert model._tf_ordered
    out = model._module().evaluate().forward(
        np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32))
    assert np.asarray(out).shape == (2, 2, 8, 8)


def test_unsupported_layer_class_rejected():
    spec = [_layer("FancyNewLayer", "x", batch_input_shape=[None, 3])]
    with pytest.raises(NotImplementedError):
        model_from_json(_seq_json(spec))


def test_sequential_with_inputlayer_first():
    """Sequential configs emitted with a leading InputLayer convert."""
    spec = [
        _layer("InputLayer", "in", batch_input_shape=[None, 3]),
        _layer("Dense", "d", output_dim=2),
    ]
    model = model_from_json(_seq_json(spec))
    x = np.random.RandomState(8).randn(4, 3).astype(np.float32)
    assert np.asarray(model._module().evaluate().forward(x)).shape == (4, 2)


def test_embedding_input_length_shape():
    """Embedding without batch_input_shape derives shape from input_length
    (not the vocab size)."""
    spec = [
        _layer("Embedding", "e", input_dim=1000, output_dim=8,
               input_length=12),
        _layer("Flatten", "f"),
        _layer("Dense", "d", output_dim=2),
    ]
    model = model_from_json(_seq_json(spec))
    ids = np.random.RandomState(9).randint(0, 1000, (2, 12)).astype(
        np.float32)
    assert np.asarray(model._module().evaluate().forward(ids)).shape == (2, 2)


def test_batchnorm_bad_axis_rejected():
    spec = [
        _layer("Convolution2D", "c", nb_filter=2, nb_row=3, nb_col=3,
               dim_ordering="th", batch_input_shape=[None, 3, 8, 8]),
        _layer("BatchNormalization", "bn", axis=-1),
    ]
    with pytest.raises(NotImplementedError):
        model_from_json(_seq_json(spec))


def test_unsupported_weighted_layer_raises_at_load():
    """A weighted layer without a weight converter refuses load_weights
    instead of silently keeping random init."""
    spec = [
        _layer("MaxoutDense", "mx", output_dim=4, nb_feature=2,
               batch_input_shape=[None, 3]),
    ]
    model = model_from_json(_seq_json(spec))
    with pytest.raises(NotImplementedError, match="mx"):
        load_weights(model, {"mx": [np.zeros((2, 3, 4), np.float32)]})


def test_batchnorm_temporal_feature_axis():
    """BN over a (T, F) input with axis=-1 normalizes features (Bottle)."""
    spec = [
        _layer("BatchNormalization", "bn", axis=-1, epsilon=1e-3,
               batch_input_shape=[None, 5, 4]),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(10)
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = rng.rand(4).astype(np.float32) + 0.5
    load_weights(model, {"bn": [gamma, beta, mean, var]})
    x = rng.randn(2, 5, 4).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    ref = (x - mean) / np.sqrt(var + 1e-3) * gamma + beta
    assert np.allclose(out, ref, atol=1e-3), np.abs(out - ref).max()


def test_recurrent_input_dim_input_length():
    """LSTM(input_dim=.., input_length=..) derives input_shape (T, F)."""
    spec = [
        _layer("LSTM", "l", output_dim=6, input_dim=3, input_length=7),
        _layer("Dense", "d", output_dim=2),
    ]
    model = model_from_json(_seq_json(spec))
    x = np.random.RandomState(11).randn(2, 7, 3).astype(np.float32)
    assert np.asarray(model._module().evaluate().forward(x)).shape == (2, 2)


def test_atrous_conv1d_weights():
    """AtrousConvolution1D weights load through the dilated-conv mapping."""
    import torch
    import torch.nn.functional as F
    T, C, OUT, K, RATE = 12, 3, 5, 3, 2
    spec = [
        _layer("AtrousConvolution1D", "ac", nb_filter=OUT, filter_length=K,
               atrous_rate=RATE, batch_input_shape=[None, T, C]),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(12)
    w = rng.randn(K, 1, C, OUT).astype(np.float32)
    b = rng.randn(OUT).astype(np.float32)
    load_weights(model, {"ac": [w, b]})
    x = rng.randn(2, T, C).astype(np.float32)
    out = np.asarray(model._module().evaluate().forward(x))
    # torch oracle: conv1d with dilation over (B, C, T)
    wt = torch.tensor(w[:, 0].transpose(2, 1, 0))  # (OUT, C, K)
    ref = F.conv1d(torch.tensor(x.transpose(0, 2, 1)), wt, torch.tensor(b),
                   dilation=RATE).numpy().transpose(0, 2, 1)
    assert out.shape == ref.shape, (out.shape, ref.shape)
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


def test_non_strict_load_skips_unsupported():
    """strict=False loads supported layers and warns for the rest."""
    import warnings as _w
    spec = [
        _layer("Dense", "d", output_dim=4, batch_input_shape=[None, 3]),
        _layer("MaxoutDense", "mx", output_dim=4, nb_feature=2),
    ]
    model = model_from_json(_seq_json(spec))
    rng = np.random.RandomState(13)
    w, b = rng.randn(3, 4).astype(np.float32), rng.randn(4).astype(
        np.float32)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        load_weights(model, {"d": [w, b]}, by_name=True, strict=False)
    assert any("mx" in str(r.message) for r in rec)
    # dense arm got its weights even though maxout was skipped (assert on
    # the root param tree, which is what forward uses)
    root = model._module()
    assert np.allclose(np.asarray(root.params["0"]["weight"]), w.T,
                       atol=1e-6)
    assert np.allclose(np.asarray(root.params["0"]["bias"]), b, atol=1e-6)


@pytest.mark.slow
def test_model_from_json_accepts_modern_tf_keras():
    """model_from_json ingests today's tf.keras ``model.to_json()``
    (keras 2/3 config spellings: units/use_bias/rate/batch_shape,
    Functional class name) — definitions only; weight HDF5 stays 1.2."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    from bigdl_tpu.keras.converter import model_from_json

    m = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dropout(0.2),
        keras.layers.Dense(2, activation="softmax"),
    ])
    ours = model_from_json(m.to_json())
    x = np.random.randn(3, 4).astype(np.float32)
    out = np.asarray(ours._module().evaluate().forward(x))
    assert out.shape == (3, 2)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)

    # functional ("Functional" class name in keras 2/3)
    inp = keras.layers.Input(shape=(6,))
    h = keras.layers.Dense(5, activation="tanh")(inp)
    out_l = keras.layers.Dense(3)(h)
    fm = keras.Model(inp, out_l)
    ours2 = model_from_json(fm.to_json())
    y = np.asarray(ours2._module().evaluate().forward(
        np.random.randn(2, 6).astype(np.float32)))
    assert y.shape == (2, 3)


@pytest.mark.slow
def test_modern_keras_edge_configs():
    """The modern-config translation is complete where it claims to be:
    1D pool sizes honored, channels_last pooling rejected loudly, dilation
    maps to the Atrous classes, LeakyReLU negative_slope honored."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    from bigdl_tpu.keras.converter import model_from_json

    m = keras.Sequential([keras.layers.Input((12, 3)),
                          keras.layers.MaxPooling1D(pool_size=4)])
    ours = model_from_json(m.to_json())
    out = ours._module().evaluate().forward(
        np.random.randn(2, 12, 3).astype(np.float32))
    assert out.shape == (2, 3, 3)

    # channels_last pooling converts via the transposed pipeline: the
    # model is built channels-first, so feed NCHW
    m2 = keras.Sequential([keras.layers.Input((6, 6, 3)),
                           keras.layers.MaxPooling2D()])
    out2 = model_from_json(m2.to_json())._module().evaluate().forward(
        np.random.randn(2, 3, 6, 6).astype(np.float32))
    assert out2.shape == (2, 3, 3, 3)

    m3 = keras.Sequential([
        keras.layers.Input((3, 8, 8)),
        keras.layers.Conv2D(4, 3, dilation_rate=2,
                            data_format="channels_first")])
    out3 = model_from_json(m3.to_json())._module().evaluate().forward(
        np.random.randn(2, 3, 8, 8).astype(np.float32))
    assert out3.shape == (2, 4, 4, 4)

    m4 = keras.Sequential([keras.layers.Input((4,)),
                           keras.layers.LeakyReLU(negative_slope=0.01)])
    y = model_from_json(m4.to_json())._module().evaluate().forward(
        -np.ones((1, 4), np.float32))
    np.testing.assert_allclose(np.asarray(y), -0.01, rtol=1e-5)


def _keras12_h5(path, keras_model, h5py):
    """Write a keras-1.2-layout weights HDF5 from a live tf.keras model
    (layer_names/weight_names attrs — the format load_weights_hdf5 reads;
    modern tf.keras save_weights uses a different container)."""
    with h5py.File(path, "w") as f:
        names = []
        for layer in keras_model.layers:
            ws = layer.get_weights()
            if not ws:
                continue
            names.append(layer.name.encode())
            g = f.create_group(layer.name)
            wnames = [f"{layer.name}_p{i}".encode() for i in range(len(ws))]
            g.attrs["weight_names"] = wnames
            for wn, w in zip(wnames, ws):
                g.create_dataset(wn.decode(), data=w)
        f.attrs["layer_names"] = names


@pytest.mark.slow
def test_tf_ordered_conv_stack_matches_real_keras(tmp_path):
    """VERDICT r2 #6: a channels_last ('tf'-ordered) conv stack — JSON +
    HDF5 weights from REAL tf.keras — converts through the transposed-weight
    pipeline and matches tf.keras outputs (incl. the Flatten→Dense row
    permutation)."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    h5py = pytest.importorskip("h5py")
    keras = tf.keras
    from bigdl_tpu.keras.converter import model_from_json, load_weights_hdf5

    rng = np.random.RandomState(0)
    m = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Conv2D(5, 3, activation="relu", padding="same"),
        keras.layers.MaxPooling2D(),
        keras.layers.Conv2D(4, 3),
        keras.layers.Flatten(),
        keras.layers.Dense(6, activation="tanh"),
        keras.layers.Dense(3),
    ])
    x = rng.randn(4, 8, 8, 3).astype(np.float32)
    ref = m.predict(x, verbose=0)

    ours = model_from_json(m.to_json())
    path = str(tmp_path / "w.h5")
    _keras12_h5(path, m, h5py)
    load_weights_hdf5(ours, path)
    out = np.asarray(ours._module().evaluate().forward(
        x.transpose(0, 3, 1, 2)))  # converted model consumes NCHW
    np.testing.assert_allclose(out, ref, atol=1e-4)


@pytest.mark.slow
def test_tf_ordered_functional_with_bn_matches_real_keras(tmp_path):
    """Functional channels_last graph with BatchNormalization(axis=-1):
    BN stats stay per-channel across the layout change."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    h5py = pytest.importorskip("h5py")
    keras = tf.keras
    from bigdl_tpu.keras.converter import model_from_json, load_weights_hdf5

    rng = np.random.RandomState(1)
    inp = keras.layers.Input((6, 6, 2))
    h = keras.layers.Conv2D(4, 3, padding="same")(inp)
    h = keras.layers.BatchNormalization(axis=-1)(h)
    h = keras.layers.Activation("relu")(h)
    h = keras.layers.Flatten()(h)
    out_l = keras.layers.Dense(2)(h)
    m = keras.Model(inp, out_l)
    # non-trivial BN stats
    bn = m.layers[2]
    bn.set_weights([rng.rand(4).astype(np.float32) + 0.5,
                    rng.randn(4).astype(np.float32),
                    rng.randn(4).astype(np.float32),
                    rng.rand(4).astype(np.float32) + 0.3])
    x = rng.randn(3, 6, 6, 2).astype(np.float32)
    ref = m.predict(x, verbose=0)

    ours = model_from_json(m.to_json())
    path = str(tmp_path / "w.h5")
    _keras12_h5(path, m, h5py)
    load_weights_hdf5(ours, path)
    out = np.asarray(ours._module().evaluate().forward(
        x.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(out, ref, atol=1e-4)


@pytest.mark.slow
def test_tf_ordered_conv3d_input_transposed():
    """Rank-4 tf-ordered input shapes (D, H, W, C) transpose to
    (C, D, H, W) — a channels_last Conv3D must not treat D as channels."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    m = keras.Sequential([
        keras.layers.Input((5, 6, 6, 2)),
        keras.layers.Conv3D(4, 3, padding="same"),
    ])
    ours = model_from_json(m.to_json())
    x = np.random.RandomState(0).randn(1, 2, 5, 6, 6).astype(np.float32)
    out = np.asarray(ours._module().evaluate().forward(x))
    assert out.shape == (1, 4, 5, 6, 6), out.shape


@pytest.mark.slow
def test_tf_ordered_flatten_bn_dense_rejected(tmp_path):
    """A per-feature-parameter layer (BatchNormalization) between Flatten
    and Dense in a tf-ordered model is refused loudly at weight-load time —
    never silently mis-permuted."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    h5py = pytest.importorskip("h5py")
    keras = tf.keras
    from bigdl_tpu.keras.converter import load_weights_hdf5
    m = keras.Sequential([
        keras.layers.Input((6, 6, 2)),
        keras.layers.Conv2D(3, 3, padding="same"),
        keras.layers.Flatten(),
        keras.layers.BatchNormalization(),
        keras.layers.Dense(2),
    ])
    ours = model_from_json(m.to_json())
    path = str(tmp_path / "w.h5")
    _keras12_h5(path, m, h5py)
    with pytest.raises(NotImplementedError, match="per-feature"):
        load_weights_hdf5(ours, path)


# ---------------------------------------------------------------------------
# with_bigdl_backend (r5 — VERDICT r4 missing #2)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_with_bigdl_backend_real_tf_keras_end_to_end():
    """Reference pyspark/bigdl/keras/backend.py headline UX: hand over a
    COMPILED live tf.keras model object; predict matches keras exactly
    (same weights) and fit on the bigdl_tpu engine reduces the loss."""
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    from bigdl_tpu.keras import with_bigdl_backend

    rng = np.random.RandomState(0)
    km = keras.Sequential([
        keras.layers.Input(shape=(6,)),
        keras.layers.Dense(10, activation="relu", name="h"),
        keras.layers.Dense(1, name="out"),
    ])
    km.compile(optimizer=keras.optimizers.SGD(learning_rate=0.05),
               loss="mse")
    bm = with_bigdl_backend(km)

    # weight transfer: our forward == keras forward on the same inputs
    x = rng.randn(32, 6).astype(np.float32)
    w = rng.randn(6, 1).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(32, 1)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(bm.predict(x)),
                               km.predict(x, verbose=0), atol=1e-5)

    # optimizer mapping: fit runs on OUR engine and learns
    loss0 = bm.evaluate(x, y)
    bm.fit(x, y, batch_size=8, nb_epoch=15)
    loss1 = bm.evaluate(x, y)
    assert loss1 < loss0 * 0.5, (loss0, loss1)


@pytest.mark.slow
def test_with_bigdl_backend_classifier_metrics():
    """Compiled metrics map (accuracy -> Top1Accuracy) and evaluate
    returns [loss, acc] keras-style."""
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    from bigdl_tpu.keras import with_bigdl_backend

    rng = np.random.RandomState(1)
    km = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(8, activation="tanh"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    km.compile(optimizer="adam", loss="categorical_crossentropy",
               metrics=["accuracy"])
    bm = with_bigdl_backend(km)
    assert bm.model.metrics == ["accuracy"]

    x = rng.randn(30, 4).astype(np.float32)
    labels = rng.randint(0, 3, size=30)
    y = np.eye(3, dtype=np.float32)[labels]
    loss, acc = bm.evaluate(x, y, batch_size=10)
    assert 0.0 <= acc <= 1.0
    assert np.asarray(bm.predict_classes(x)).shape == (30,)
