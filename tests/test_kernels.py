"""Pallas kernel tests — run the real kernel code via the interpreter on CPU.

The interpret-mode path executes the identical kernel bodies the TPU
compiles, so numerics (online softmax, causal masking, custom VJP) are
covered without hardware.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.kernels import flash_attention_fused
from bigdl_tpu.nn.attention import dot_product_attention


def _ref(q, k, v, causal):
    mask = None
    if causal:
        t = q.shape[-2]
        mask = jnp.where(np.tril(np.ones((t, t), np.bool_))[None, None],
                         0.0, -1e30)
    return dot_product_attention(q, k, v, mask)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [128, 256])
def test_flash_forward_matches_einsum(causal, t):
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(2, 3, t, 64).astype(np.float32))
               for _ in range(3)]
    out = flash_attention_fused(q, k, v, causal=causal, block_q=128,
                                block_k=128, interpret=True)
    ref = _ref(q, k, v, causal)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_flash_forward_unpadded_length():
    """T not a multiple of the block: padding + kv_len masking."""
    rng = np.random.RandomState(1)
    t = 200
    q, k, v = [jnp.asarray(rng.randn(1, 2, t, 32).astype(np.float32))
               for _ in range(3)]
    out = flash_attention_fused(q, k, v, causal=False, block_q=128,
                                block_k=128, interpret=True)
    ref = _ref(q, k, v, False)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_cross_attention_kv_longer():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    k, v = [jnp.asarray(rng.randn(1, 2, 384, 32).astype(np.float32))
            for _ in range(2)]
    out = flash_attention_fused(q, k, v, causal=False, block_q=128,
                                block_k=128, interpret=True)
    ref = _ref(q, k, v, False)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_einsum(causal):
    rng = np.random.RandomState(3)
    t = 256
    q, k, v = [jnp.asarray(rng.randn(1, 2, t, 32).astype(np.float32))
               for _ in range(3)]

    def loss_flash(q, k, v):
        o = flash_attention_fused(q, k, v, causal=causal, block_q=128,
                                  block_k=128, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref(q, k, v, causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        err = np.abs(np.asarray(a) - np.asarray(b)).max()
        assert err < 5e-4, f"d{name} err {err}"


def test_flash_bf16_runs():
    rng = np.random.RandomState(4)
    q, k, v = [jnp.asarray(rng.randn(1, 2, 128, 64)).astype(jnp.bfloat16)
               for _ in range(3)]
    out = flash_attention_fused(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _ref(q.astype(jnp.float32), k.astype(jnp.float32),
               v.astype(jnp.float32), True)
    assert np.allclose(np.asarray(out, np.float32), np.asarray(ref),
                       atol=5e-2)


def test_flash_dispatcher_interpret_env(monkeypatch):
    from bigdl_tpu.parallel import flash
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")
    rng = np.random.RandomState(5)
    q, k, v = [jnp.asarray(rng.randn(1, 1, 128, 16).astype(np.float32))
               for _ in range(3)]
    out = flash.flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_fused_matmul_forward_and_grads():
    from bigdl_tpu.kernels.fused_matmul import fused_bn_relu_matmul
    rng = np.random.RandomState(0)
    M, K, N = 160, 48, 72  # deliberately unpadded sizes
    x = jnp.asarray(rng.randn(M, K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)
    a = jnp.asarray(rng.rand(K).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(K).astype(np.float32))

    def ref(x, w, a, b):
        xh = jnp.maximum(x * a + b, 0.0)
        z = xh @ w
        return z, jnp.sum(z, 0), jnp.sum(z * z, 0)

    z, s1, s2 = fused_bn_relu_matmul(x, w, a, b, interpret=True)
    zr, s1r, s2r = ref(x, w, a, b)
    assert np.allclose(z, zr, atol=1e-4)
    assert np.allclose(s1, s1r, atol=1e-3)
    assert np.allclose(s2, s2r, atol=1e-2)

    def mk_loss(fwd):
        def loss(x, w, a, b):
            z, s1, s2 = fwd(x, w, a, b)
            mean = s1 / z.shape[0]
            var = s2 / z.shape[0] - mean ** 2
            zh = (z - mean) * jax.lax.rsqrt(var + 1e-5)
            return jnp.sum(jnp.tanh(zh * 0.3))
        return loss

    gf = jax.grad(mk_loss(lambda *aa: fused_bn_relu_matmul(
        *aa, interpret=True)), argnums=(0, 1, 2, 3))(x, w, a, b)
    gr = jax.grad(mk_loss(ref), argnums=(0, 1, 2, 3))(x, w, a, b)
    for name, f, r in zip("xwab", gf, gr):
        rel = float(jnp.abs(f - r).max()) / (float(jnp.abs(r).max()) + 1e-9)
        assert rel < 2e-4, (name, rel)


def test_fused_matmul_nhwc_forward_and_grads():
    """Layout-preserving (B,H,W,K) kernel == last-axis dot_general math —
    values, stats, and grads through the same BN-normalize loss as the
    flattened kernel's test."""
    from bigdl_tpu.kernels.fused_matmul import fused_bn_relu_matmul_nhwc
    rng = np.random.RandomState(0)
    B, H, W, K, N = 4, 6, 8, 16, 32
    x = jnp.asarray(rng.randn(B, H, W, K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)
    a = jnp.asarray(rng.rand(K).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(K).astype(np.float32))

    def ref(x, w, a, b):
        xh = jnp.maximum(x * a + b, 0.0)
        z = jax.lax.dot_general(xh, w, (((3,), (0,)), ((), ())))
        return z, jnp.sum(z, (0, 1, 2)), jnp.sum(z * z, (0, 1, 2))

    kern = lambda *aa: fused_bn_relu_matmul_nhwc(*aa, interpret=True)
    z, s1, s2 = kern(x, w, a, b)
    zr, s1r, s2r = ref(x, w, a, b)
    assert z.shape == (B, H, W, N)
    assert np.allclose(z, zr, atol=1e-4)
    assert np.allclose(s1, s1r, atol=1e-3)
    assert np.allclose(s2, s2r, atol=1e-2)

    def mk_loss(fwd):
        def loss(x, w, a, b):
            z, s1, s2 = fwd(x, w, a, b)
            m = B * H * W
            mean = s1 / m
            var = s2 / m - mean ** 2
            zh = (z - mean) * jax.lax.rsqrt(var + 1e-5)
            return jnp.sum(jnp.tanh(zh * 0.3))
        return loss

    gf = jax.grad(mk_loss(kern), argnums=(0, 1, 2, 3))(x, w, a, b)
    gr = jax.grad(mk_loss(ref), argnums=(0, 1, 2, 3))(x, w, a, b)
    for name, f, r in zip("xwab", gf, gr):
        rel = float(jnp.abs(f - r).max()) / (float(jnp.abs(r).max()) + 1e-9)
        assert rel < 2e-4, (name, rel)
    # non-dividing N falls back (caller handles None)
    wbad = jnp.asarray(rng.randn(K, 24).astype(np.float32))
    assert fused_bn_relu_matmul_nhwc(x, wbad, block_n=16,
                                     interpret=True) is None

    # genuinely multi-tile grid (nb=2, nh=2, nn=2): covers the cross-tile
    # accumulator init/finish guards (ib==0&&ih==0 / last-tile writes)
    # that the auto-fitted single-tile call above never exercises
    from bigdl_tpu.kernels.fused_matmul import _fused4
    zm, s1m, s2m = _fused4(x, w, a, b, True, True, B // 2, H // 2, N // 2,
                           True)
    assert np.allclose(zm, zr, atol=1e-4)
    assert np.allclose(s1m, s1r, atol=1e-3)
    assert np.allclose(s2m, s2r, atol=1e-2)
    gm = jax.grad(mk_loss(lambda *aa: _fused4(
        *aa, True, True, B // 2, H // 2, N // 2, True)),
        argnums=(0, 1, 2, 3))(x, w, a, b)
    for name, f, r in zip("xwab", gm, gr):
        rel = float(jnp.abs(f - r).max()) / (float(jnp.abs(r).max()) + 1e-9)
        assert rel < 2e-4, ("multi-tile", name, rel)


@pytest.mark.parametrize("B,H,W,K,N", [
    (1, 3, 5, 8, 16),     # tiny, odd spatial dims
    (2, 7, 7, 32, 8),     # stage-3-like spatial, N < K
    (3, 4, 1, 16, 32),    # W=1 (degenerate inner row)
    (5, 2, 6, 24, 48),    # B prime vs divisor search
])
def test_fused_matmul_nhwc_shape_matrix(B, H, W, K, N):
    """NHWC kernel == last-axis dot across a shape matrix (values only;
    grads covered by the dedicated test). Catches block-fit/index-map
    regressions the two fixed-shape tests can't."""
    from bigdl_tpu.kernels.fused_matmul import fused_bn_relu_matmul_nhwc
    rng = np.random.RandomState(B * 100 + N)
    x = jnp.asarray(rng.randn(B, H, W, K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)
    a = jnp.asarray(rng.rand(K).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(K).astype(np.float32))
    out = fused_bn_relu_matmul_nhwc(x, w, a, b, relu=True, stats=True,
                                    interpret=True)
    # every shape in the matrix tiles: a None here IS the fitter
    # regression this test exists to catch
    assert out is not None
    z, s1, s2 = out
    xh = jnp.maximum(x * a + b, 0.0)
    zr = jax.lax.dot_general(xh, w, (((3,), (0,)), ((), ())))
    assert np.allclose(z, zr, atol=1e-4), np.abs(z - zr).max()
    assert np.allclose(s1, jnp.sum(zr, (0, 1, 2)), atol=1e-3)
    assert np.allclose(s2, jnp.sum(zr * zr, (0, 1, 2)), atol=1e-2)


def test_fused_matmul_vmem_overflow_fallback(monkeypatch):
    """When even the smallest block size exceeds the VMEM footprint model,
    fused_bn_relu_matmul warns and computes the same math unfused (XLA) —
    values, stats, grads, dtype, and the stats=False tuple all match the
    kernel contract."""
    import warnings
    import bigdl_tpu.kernels.fused_matmul as fm
    rng = np.random.RandomState(3)
    M, K, N = 32, 16, 24
    x = jnp.asarray(rng.randn(M, K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)
    a = jnp.asarray(rng.rand(K).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(K).astype(np.float32))

    zk, s1k, s2k = fm.fused_bn_relu_matmul(x, w, a, b, interpret=True)

    def grads(fwd):
        def loss(x, w, a, b):
            z, s1, s2 = fwd(x, w, a, b)
            return (z * z).sum() + s1.sum() + (s2 * 0.1).sum()
        return jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, a, b)

    gk = grads(lambda *t: fm.fused_bn_relu_matmul(*t, interpret=True))

    monkeypatch.setattr(fm, "_VMEM_BUDGET", 1)  # force the overflow branch
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        zf, s1f, s2f = fm.fused_bn_relu_matmul(x, w, a, b)
    assert any("falling" in str(r.message) for r in rec)
    assert zf.dtype == x.dtype and s1f.dtype == jnp.float32
    assert np.allclose(zf, zk, atol=1e-4)
    assert np.allclose(s1f, s1k, atol=1e-3)
    assert np.allclose(s2f, s2k, atol=1e-2)
    gf = grads(fm.fused_bn_relu_matmul)
    for gi, gj in zip(gk, gf):
        assert np.allclose(gi, gj, atol=1e-3), np.abs(gi - gj).max()

    # stats=False keeps the (z, zeros, zeros) tuple shape
    z0, s10, s20 = fm.fused_bn_relu_matmul(x, w, a, b, stats=False)
    assert s10.shape == (N,) and not s10.any() and not s20.any()

    # bf16 compute dtype stays bf16 through the fallback (f32 scale/bias)
    zb, s1b, _ = fm.fused_bn_relu_matmul(x.astype(jnp.bfloat16), w.astype(
        jnp.bfloat16), a, b)
    assert zb.dtype == jnp.bfloat16 and s1b.dtype == jnp.float32


def test_fused_matmul_nhwc_h_split_path(monkeypatch):
    """When no whole-batch block fits the VMEM budget the fitter splits H
    — force that path with a tiny budget and check values still match."""
    import bigdl_tpu.kernels.fused_matmul as fm
    B, H, W, K, N = 2, 6, 4, 16, 32
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, H, W, K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)
    # budget EXACTLY the (bb=1, bh=2) footprint — the fitter's _fits
    # compares with <=, so the search lands there and nowhere larger
    need = fm._vmem_need(1 * 2 * W, K, N, min(512, N), 4)
    monkeypatch.setattr(fm, "_VMEM_BUDGET", need)
    out = fm.fused_bn_relu_matmul_nhwc(x, w, relu=False, stats=True,
                                       interpret=True)
    assert out is not None     # None here = the fitter regressed
    z, s1, s2 = out
    zr = jax.lax.dot_general(x, w, (((3,), (0,)), ((), ())))
    assert np.allclose(z, zr, atol=1e-4)
    assert np.allclose(s1, jnp.sum(zr, (0, 1, 2)), atol=1e-3)
    assert np.allclose(s2, jnp.sum(zr * zr, (0, 1, 2)), atol=1e-2)


def test_fused_bottleneck_matches_reference_block(monkeypatch):
    """FusedBottleneck == the Sequential bottleneck with identical weights
    (fwd train+eval, running stats), and the interpret-mode Pallas path ==
    the jnp fallback in values and grads."""
    from bigdl_tpu.models.resnet import FusedBottleneck, bottleneck
    rng = np.random.RandomState(0)
    B, H, W, C = 2, 8, 8, 16
    x = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    monkeypatch.setenv("BIGDL_TPU_FLASH", "off")  # jnp fallback path

    for stride, nmid in ((1, 8), (2, 8)):
        fb = FusedBottleneck(C, nmid, stride)
        params, state = fb.init(jax.random.PRNGKey(0))
        ref = bottleneck(C, nmid, stride, 4, "B", False, "NHWC")
        rp, rs = ref.init(jax.random.PRNGKey(1))
        main_p, sc_p = rp["0"]["0"], rp["0"]["1"]

        def oihw(hwio):
            return jnp.asarray(np.transpose(hwio, (3, 2, 0, 1)))
        main_p["0"]["weight"] = oihw(params["w1"].reshape(1, 1, C, nmid))
        main_p["3"]["weight"] = oihw(np.asarray(params["w2"]))
        main_p["6"]["weight"] = oihw(params["w3"].reshape(1, 1, nmid,
                                                          4 * nmid))
        sc_p["0"]["weight"] = oihw(params["proj_w"].reshape(1, 1, C,
                                                            4 * nmid))
        for training in (True, False):
            out_f, st_f = fb.apply(params, state, x, training=training)
            out_r, st_r = ref.apply(rp, rs, x, training=training)
            assert np.allclose(np.asarray(out_f), np.asarray(out_r),
                               atol=2e-4)
            if training:
                assert np.allclose(
                    np.asarray(st_f["bn1"]["running_mean"]),
                    np.asarray(st_r["0"]["0"]["1"]["running_mean"]),
                    atol=1e-4)

    fb = FusedBottleneck(C, 8, 1)
    params, state = fb.init(jax.random.PRNGKey(0))

    def loss(p):
        out, _ = fb.apply(p, state, x, training=True)
        return jnp.sum(out * out) * 0.01

    l_jnp, g_jnp = jax.value_and_grad(loss)(params)
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")  # real kernel
    l_krn, g_krn = jax.value_and_grad(loss)(params)
    assert abs(float(l_jnp) - float(l_krn)) < 1e-3
    for va, vb in zip(jax.tree_util.tree_leaves(g_jnp),
                      jax.tree_util.tree_leaves(g_krn)):
        assert np.allclose(np.asarray(va), np.asarray(vb), atol=1e-3)


def test_fused_chain_kernel_forward_and_grads():
    """Cross-layer junction kernel (kernels/fused_chain.py) vs the jnp
    oracle: h/z_out/stats values and all five gradients, through a loss
    touching every output (interpret mode runs the real kernel bodies)."""
    from bigdl_tpu.kernels.fused_chain import (fused_residual_matmul_nhwc,
                                               residual_chain_reference)
    rng = np.random.RandomState(0)
    B, H, W, K, N = 2, 4, 4, 48, 24
    z = jnp.asarray(rng.randn(B, H, W, K).astype(np.float32))
    r = jnp.asarray(rng.randn(B, H, W, K).astype(np.float32))
    a = jnp.asarray(rng.rand(K).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)

    h, zo, s1, s2 = fused_residual_matmul_nhwc(z, r, w, a, b,
                                               interpret=True)
    hr, zor, s1r, s2r = residual_chain_reference(z, r, a, b, w)
    assert np.allclose(h, hr, atol=1e-5)
    assert np.allclose(zo, zor, atol=1e-4)
    assert np.allclose(s1, s1r, atol=1e-3)
    assert np.allclose(s2, s2r, atol=1e-2)

    def mk_loss(fn):
        def loss(z, r, a, b, w):
            h, zo, s1, s2 = fn(z, r, a, b, w)
            m = B * H * W
            mean = s1 / m
            var = s2 / m - mean ** 2
            zh = (zo - mean) * jax.lax.rsqrt(var + 1e-5)
            return jnp.sum(jnp.tanh(zh * 0.3)) + 0.5 * jnp.sum(jnp.sin(h))
        return loss

    gk = jax.grad(mk_loss(lambda z, r, a, b, w: fused_residual_matmul_nhwc(
        z, r, w, a, b, interpret=True)), argnums=(0, 1, 2, 3, 4))(
            z, r, a, b, w)
    gr = jax.grad(mk_loss(residual_chain_reference),
                  argnums=(0, 1, 2, 3, 4))(z, r, a, b, w)
    for name, f, x in zip("zrabw", gk, gr):
        rel = float(jnp.abs(f - x).max()) / (float(jnp.abs(x).max()) + 1e-9)
        assert rel < 2e-4, (name, rel)


def test_fused_bottleneck_chain_matches_sequential_blocks(monkeypatch):
    """FusedBottleneckChain == the same FusedBottleneck blocks run
    sequentially with identical params (train+eval values, running
    stats, grads); the interpret-mode chain kernel == the jnp fallback."""
    from bigdl_tpu.models.resnet import (FusedBottleneck,
                                         FusedBottleneckChain)
    rng = np.random.RandomState(0)
    B, H, W, C, nmid = 2, 8, 8, 16, 8
    x = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    blocks = [FusedBottleneck(C, nmid, stride=2),
              FusedBottleneck(4 * nmid, nmid),
              FusedBottleneck(4 * nmid, nmid)]
    chain = FusedBottleneckChain(blocks)
    params, state = chain.init(jax.random.PRNGKey(0))

    def sequential(params, state, x, training):
        h, sts = x, {}
        for i, blk in enumerate(blocks):
            h, sts[str(i)] = blk.apply(params[str(i)], state[str(i)], h,
                                       training=training)
        return h, sts

    monkeypatch.setenv("BIGDL_TPU_FLASH", "off")   # jnp composition
    for training in (True, False):
        out_c, st_c = chain.apply(params, state, x, training=training)
        out_s, st_s = sequential(params, state, x, training)
        assert np.allclose(np.asarray(out_c), np.asarray(out_s),
                           atol=2e-4), training
        if training:
            assert np.allclose(
                np.asarray(st_c["1"]["bn1"]["running_mean"]),
                np.asarray(st_s["1"]["bn1"]["running_mean"]), atol=1e-4)

    def loss(p, training=True):
        out, _ = chain.apply(p, state, x, training=training)
        return jnp.sum(out * out) * 0.01

    l_jnp, g_jnp = jax.value_and_grad(loss)(params)
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")  # real kernels
    l_krn, g_krn = jax.value_and_grad(loss)(params)
    assert abs(float(l_jnp) - float(l_krn)) < 1e-3
    for va, vb in zip(jax.tree_util.tree_leaves(g_jnp),
                      jax.tree_util.tree_leaves(g_krn)):
        assert np.allclose(np.asarray(va), np.asarray(vb), atol=1e-3)
    # eval-mode interpret path (stats=False arm of the kernel)
    out_e, _ = chain.apply(params, state, x, training=False)
    monkeypatch.setenv("BIGDL_TPU_FLASH", "off")
    out_o, _ = chain.apply(params, state, x, training=False)
    assert np.allclose(np.asarray(out_e), np.asarray(out_o), atol=2e-4)


def test_resnet50_fused_chain_builds_and_runs(monkeypatch):
    """ResNet(fused='pallas') assembles FusedBottleneckChain stages by
    default; BIGDL_TPU_FUSED_CHAIN=0 (the ab_queue control arm) keeps
    per-block modules; BOTH run (jnp fallback) and agree with the same
    weights."""
    from bigdl_tpu.models.resnet import ResNet, FusedBottleneckChain
    monkeypatch.setenv("BIGDL_TPU_FLASH", "off")
    m = ResNet(10, 50, format="NHWC", fused="pallas")
    chains = [mod for mod in m.modules
              if isinstance(mod, FusedBottleneckChain)]
    assert len(chains) == 4 and [len(c.blocks) for c in chains] == \
        [3, 4, 6, 3]
    monkeypatch.setenv("BIGDL_TPU_FUSED_CHAIN", "0")
    m0 = ResNet(10, 50, format="NHWC", fused="pallas")
    assert not any(isinstance(mod, FusedBottleneckChain)
                   for mod in m0.modules)

    x = jnp.asarray(
        np.random.RandomState(0).randn(1, 64, 64, 3).astype(np.float32))
    params, state = m.init(jax.random.PRNGKey(0))
    # remap the chained trees (stage chains hold {j: block}) onto the
    # flat per-block Sequential of the control arm
    p0, s0, k = {}, {}, 0
    for i, mod in enumerate(m.modules):
        if isinstance(mod, FusedBottleneckChain):
            for j in range(len(mod.blocks)):
                p0[str(k)] = params[str(i)][str(j)]
                s0[str(k)] = state[str(i)][str(j)]
                k += 1
        else:
            p0[str(k)] = params[str(i)]
            s0[str(k)] = state[str(i)]
            k += 1
    assert k == len(m0.modules)
    out, _ = m.apply(params, state, x, training=False)
    out0, _ = m0.apply(p0, s0, x, training=False)
    assert out.shape == (1, 10)
    assert np.allclose(np.asarray(out), np.asarray(out0), atol=2e-4)


def test_fused_conv3x3_kernel_forward_and_grads():
    """Fused BN+ReLU+3x3-conv+stats kernel (kernels/fused_conv.py) vs the
    jnp oracle at strides 1 and 2 — values and all four gradients."""
    from bigdl_tpu.kernels.fused_conv import (fused_bn_relu_conv3x3,
                                              conv3x3_reference)
    rng = np.random.RandomState(0)
    for stride in (1, 2):
        B, H, W, K, N = 2, 8, 8, 16, 24
        x = jnp.asarray(rng.randn(B, H, W, K).astype(np.float32))
        w = jnp.asarray(rng.randn(3, 3, K, N).astype(np.float32) * 0.1)
        a = jnp.asarray(rng.rand(K).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(K).astype(np.float32))
        z, s1, s2 = fused_bn_relu_conv3x3(x, w, a, b, stride=stride,
                                          interpret=True)
        zr, s1r, s2r = conv3x3_reference(x, w, a, b, stride)
        assert np.allclose(z, zr, atol=1e-4)
        assert np.allclose(s1, s1r, atol=1e-3)
        assert np.allclose(s2, s2r, atol=1e-2)

        def mk_loss(fn):
            def loss(x, w, a, b):
                z, s1, s2 = fn(x, w, a, b)
                m = z.shape[0] * z.shape[1] * z.shape[2]
                mean = s1 / m
                var = s2 / m - mean ** 2
                zh = (z - mean) * jax.lax.rsqrt(var + 1e-5)
                return jnp.sum(jnp.tanh(zh * 0.3))
            return loss

        gk = jax.grad(mk_loss(
            lambda x, w, a, b: fused_bn_relu_conv3x3(
                x, w, a, b, stride=stride, interpret=True)),
            argnums=(0, 1, 2, 3))(x, w, a, b)
        gr = jax.grad(mk_loss(
            lambda x, w, a, b: conv3x3_reference(x, w, a, b, stride)),
            argnums=(0, 1, 2, 3))(x, w, a, b)
        for name, f, r in zip("xwab", gk, gr):
            rel = (float(jnp.abs(f - r).max())
                   / (float(jnp.abs(r).max()) + 1e-9))
            assert rel < 2e-4, (stride, name, rel)


def test_fused_bottleneck_conv2_arm_matches(monkeypatch):
    """BIGDL_TPU_FUSED_CONV2=1 routes conv2 through the fused kernel with
    identical results (fwd train+eval, grads) vs the default path."""
    from bigdl_tpu.models.resnet import FusedBottleneck
    from bigdl_tpu.kernels.fused_conv import fused_bn_relu_conv3x3
    rng = np.random.RandomState(0)
    B, H, W, C, nmid = 2, 8, 8, 16, 8
    x = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    # guard against vacuous pass: the kernel must actually ENGAGE at the
    # bottleneck's z1 shape (a VMEM-fitter regression returning None
    # would silently compare the default path with itself)
    probe = fused_bn_relu_conv3x3(
        jnp.zeros((B, H, W, nmid), jnp.float32),
        jnp.zeros((3, 3, nmid, nmid), jnp.float32),
        jnp.ones((nmid,), jnp.float32), jnp.zeros((nmid,), jnp.float32),
        stride=1, interpret=True)
    assert probe is not None
    for stride in (1, 2):
        fb = FusedBottleneck(C, nmid, stride)
        params, state = fb.init(jax.random.PRNGKey(0))
        monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")
        monkeypatch.delenv("BIGDL_TPU_FUSED_CONV2", raising=False)

        def loss(p):
            out, _ = fb.apply(p, state, x, training=True)
            return jnp.sum(out * out) * 0.01

        out_d, st_d = fb.apply(params, state, x, training=True)
        l_d, g_d = jax.value_and_grad(loss)(params)
        monkeypatch.setenv("BIGDL_TPU_FUSED_CONV2", "1")
        out_f, st_f = fb.apply(params, state, x, training=True)
        l_f, g_f = jax.value_and_grad(loss)(params)
        assert np.allclose(np.asarray(out_d), np.asarray(out_f),
                           atol=2e-4)
        assert np.allclose(
            np.asarray(st_d["bn2"]["running_mean"]),
            np.asarray(st_f["bn2"]["running_mean"]), atol=1e-4)
        assert abs(float(l_d) - float(l_f)) < 1e-3
        for va, vb in zip(jax.tree_util.tree_leaves(g_d),
                          jax.tree_util.tree_leaves(g_f)):
            assert np.allclose(np.asarray(va), np.asarray(vb), atol=1e-3)
        # eval arm
        oe_f, _ = fb.apply(params, state, x, training=False)
        monkeypatch.delenv("BIGDL_TPU_FUSED_CONV2")
        oe_d, _ = fb.apply(params, state, x, training=False)
        assert np.allclose(np.asarray(oe_f), np.asarray(oe_d), atol=2e-4)


@pytest.mark.parametrize("q_offset,s,t", [
    (0, 128, 128),      # degenerate: plain causal self-attention
    (128, 128, 256),    # mid-cache chunk, aligned
    (100, 60, 160),     # ragged chunk and offset (padding + iota masks)
])
def test_flash_chunk_attention_matches_einsum(q_offset, s, t):
    """Rectangular-causal chunk kernel (prefill_chunked's attention):
    q rows at global positions q_offset.. over a t-long valid cache
    prefix, row r attending cols <= q_offset + r."""
    from bigdl_tpu.kernels.flash_attention import flash_chunk_attention

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 2, s, 64).astype(np.float32))
    k, v = [jnp.asarray(rng.randn(2, 2, t, 64).astype(np.float32))
            for _ in range(2)]
    out = flash_chunk_attention(q, k, v, q_offset, block_q=128,
                                block_k=128, interpret=True)
    mask = jnp.where(
        jnp.arange(t)[None, :] <= q_offset + jnp.arange(s)[:, None],
        0.0, -1e30)[None, None]
    ref = dot_product_attention(q, k, v, mask)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_prefill_chunked_uses_chunk_kernel(monkeypatch):
    """Integration: prefill_chunked through the interpret-mode Pallas
    chunk kernel equals one-shot prefill (the flash path engages at
    S >= 8 with static offsets) — and a spy proves the kernel path
    actually ran (a dispatch-guard regression falling back to einsum
    would otherwise pass silently)."""
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.parallel import flash as flash_mod

    calls = []
    real = flash_mod.flash_chunk_attention
    monkeypatch.setattr(
        flash_mod, "flash_chunk_attention",
        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1])
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")
    model = TransformerLM(vocab_size=43, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=64)
    params, _ = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(1).randint(1, 43, (2, 24)),
                      jnp.int32)
    lg_a, ca = model.prefill(params, ids, 32)
    lg_b, cb = model.prefill_chunked(params, ids, 32, chunk=8)
    np.testing.assert_allclose(np.asarray(lg_a), np.asarray(lg_b),
                               rtol=2e-4, atol=2e-4)
    nxt = jnp.argmax(lg_a, -1).astype(jnp.int32)
    oa, _ = model.decode_one(params, nxt, 24, ca)
    ob, _ = model.decode_one(params, nxt, 24, cb)
    np.testing.assert_allclose(np.asarray(oa), np.asarray(ob),
                               rtol=2e-4, atol=2e-4)
    # 24 tokens / chunk 8 = 3 chunks x 2 layers dispatched to the kernel
    assert len(calls) == 6, len(calls)
