"""Pallas kernel tests — run the real kernel code via the interpreter on CPU.

The interpret-mode path executes the identical kernel bodies the TPU
compiles, so numerics (online softmax, causal masking, custom VJP) are
covered without hardware.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.kernels import flash_attention_fused
from bigdl_tpu.nn.attention import dot_product_attention


def _ref(q, k, v, causal):
    mask = None
    if causal:
        t = q.shape[-2]
        mask = jnp.where(np.tril(np.ones((t, t), np.bool_))[None, None],
                         0.0, -1e30)
    return dot_product_attention(q, k, v, mask)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [128, 256])
def test_flash_forward_matches_einsum(causal, t):
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(2, 3, t, 64).astype(np.float32))
               for _ in range(3)]
    out = flash_attention_fused(q, k, v, causal=causal, block_q=128,
                                block_k=128, interpret=True)
    ref = _ref(q, k, v, causal)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_flash_forward_unpadded_length():
    """T not a multiple of the block: padding + kv_len masking."""
    rng = np.random.RandomState(1)
    t = 200
    q, k, v = [jnp.asarray(rng.randn(1, 2, t, 32).astype(np.float32))
               for _ in range(3)]
    out = flash_attention_fused(q, k, v, causal=False, block_q=128,
                                block_k=128, interpret=True)
    ref = _ref(q, k, v, False)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_cross_attention_kv_longer():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    k, v = [jnp.asarray(rng.randn(1, 2, 384, 32).astype(np.float32))
            for _ in range(2)]
    out = flash_attention_fused(q, k, v, causal=False, block_q=128,
                                block_k=128, interpret=True)
    ref = _ref(q, k, v, False)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_einsum(causal):
    rng = np.random.RandomState(3)
    t = 256
    q, k, v = [jnp.asarray(rng.randn(1, 2, t, 32).astype(np.float32))
               for _ in range(3)]

    def loss_flash(q, k, v):
        o = flash_attention_fused(q, k, v, causal=causal, block_q=128,
                                  block_k=128, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref(q, k, v, causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        err = np.abs(np.asarray(a) - np.asarray(b)).max()
        assert err < 5e-4, f"d{name} err {err}"


def test_flash_bf16_runs():
    rng = np.random.RandomState(4)
    q, k, v = [jnp.asarray(rng.randn(1, 2, 128, 64)).astype(jnp.bfloat16)
               for _ in range(3)]
    out = flash_attention_fused(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _ref(q.astype(jnp.float32), k.astype(jnp.float32),
               v.astype(jnp.float32), True)
    assert np.allclose(np.asarray(out, np.float32), np.asarray(ref),
                       atol=5e-2)


def test_flash_dispatcher_interpret_env(monkeypatch):
    from bigdl_tpu.parallel import flash
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")
    rng = np.random.RandomState(5)
    q, k, v = [jnp.asarray(rng.randn(1, 1, 128, 16).astype(np.float32))
               for _ in range(3)]
    out = flash.flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
