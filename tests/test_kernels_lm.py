"""LM-level kernel-integration tests (flash path in the model, remat
equivalence, chunked CE loss) — split from test_kernels.py so xdist
loadfile sharding overlaps these compile-heavy checks with the rest."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

def _tiny_lm(**kw):
    from bigdl_tpu.models import TransformerLM
    return TransformerLM(vocab_size=97, hidden_size=32, num_heads=2,
                         filter_size=64, num_layers=2, max_len=64, **kw)


def test_lm_flash_path_matches_einsum(monkeypatch):
    """LM logits with the kernel (interpret) == einsum reference path."""
    import jax
    ids = jnp.asarray(np.random.RandomState(0).randint(
        1, 97, size=(2, 64)).astype(np.int32))
    model = _tiny_lm(use_flash=True)
    params, _ = model.init(jax.random.PRNGKey(0))
    monkeypatch.setenv("BIGDL_TPU_FLASH", "interpret")
    out_kernel, _ = model.apply(params, {}, ids, training=False)
    monkeypatch.setenv("BIGDL_TPU_FLASH", "off")
    out_einsum, _ = model.apply(params, {}, ids, training=False)
    ref_model = _tiny_lm(use_flash=False)
    out_ref, _ = ref_model.apply(params, {}, ids, training=False)
    assert np.allclose(np.asarray(out_kernel), np.asarray(out_ref), atol=2e-4)
    assert np.allclose(np.asarray(out_einsum), np.asarray(out_ref), atol=1e-5)


def test_lm_remat_matches_plain():
    """remat=True changes memory, not values — fwd and grads identical."""
    import jax
    ids = jnp.asarray(np.random.RandomState(1).randint(
        1, 97, size=(2, 32)).astype(np.int32))
    plain = _tiny_lm(use_flash=False, remat=False)
    remat = _tiny_lm(use_flash=False, remat=True)
    params, _ = plain.init(jax.random.PRNGKey(0))

    def loss(m):
        def f(p):
            out, _ = m.apply(p, {}, ids, training=False)
            return jnp.sum(jnp.tanh(out * 0.01))
        return f

    l0, g0 = jax.value_and_grad(loss(plain))(params)
    l1, g1 = jax.value_and_grad(loss(remat))(params)
    assert np.allclose(float(l0), float(l1), atol=1e-6)
    flat0 = jax.tree_util.tree_leaves(g0)
    flat1 = jax.tree_util.tree_leaves(g1)
    for a, b in zip(flat0, flat1):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_moe_lm_remat_matches_plain():
    """MoE LM remat=True changes memory, not values — fwd (incl. the
    router aux loss) and grads identical through BOTH block types."""
    import jax
    from bigdl_tpu.models import MoETransformerLM
    ids = jnp.asarray(np.random.RandomState(1).randint(
        1, 67, size=(2, 16)).astype(np.int32))

    def build(remat):
        return MoETransformerLM(vocab_size=67, hidden_size=32, num_heads=2,
                                filter_size=64, num_layers=2, n_experts=4,
                                moe_every=2, capacity_factor=4.0,
                                max_len=16, use_flash=False, remat=remat)

    plain, remat = build(False), build(True)
    params, _ = plain.init(jax.random.PRNGKey(0))

    def loss(m):
        def f(p):
            h, aux = m.hidden_states(p, ids, training=False)
            return jnp.sum(jnp.tanh(h * 0.01)) + 0.1 * aux
        return f

    l0, g0 = jax.value_and_grad(loss(plain))(params)
    l1, g1 = jax.value_and_grad(loss(remat))(params)
    assert np.allclose(float(l0), float(l1), atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_lm_loss_chunked_matches_full_logits():
    """lm_loss_chunked == full-logits softmax-CE with RAW (0-based) token
    ids, values AND gradients (through a scan-of-checkpoint body). The
    0-based head is what makes argmax(logits) round-trip through
    generate(); the torch-parity criteria stay 1-based — the identity is
    chunked(y) == TimeDistributedMaskCriterion(CE)(logits, y+1)."""
    import jax
    from bigdl_tpu.models import lm_loss_chunked
    from bigdl_tpu.nn import (CrossEntropyCriterion,
                              TimeDistributedMaskCriterion)
    rng = np.random.RandomState(2)
    B, T, H, V = 2, 64, 16, 53
    h = jnp.asarray(rng.randn(B, T, H).astype(np.float32))
    emb = jnp.asarray(0.1 * rng.randn(V, H).astype(np.float32))
    y = rng.randint(1, V - 1, size=(B, T)).astype(np.int32)
    y[0, :5] = 0  # padding positions excluded
    y = jnp.asarray(y)

    def ref(h, emb):
        logits = (h @ emb.T).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, y[..., None], axis=-1)[..., 0]
        valid = (y != 0).astype(jnp.float32)
        return jnp.sum((lse - gold) * valid) / jnp.sum(valid)

    def chunked(h, emb):
        return lm_loss_chunked(h, emb, y, chunk=16)

    l_ref, g_ref = jax.value_and_grad(ref, argnums=(0, 1))(h, emb)
    l_ch, g_ch = jax.value_and_grad(chunked, argnums=(0, 1))(h, emb)
    assert np.allclose(float(l_ref), float(l_ch), rtol=1e-5)
    for a, b in zip(g_ref, g_ch):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # identity to the 1-based criterion: shift targets up by one (pad
    # positions shift to 1 — give the shifted criterion padding_value=1)
    crit = TimeDistributedMaskCriterion(CrossEntropyCriterion(),
                                        padding_value=1)
    l_crit = crit._forward(h @ emb.T, y + 1)
    assert np.allclose(float(l_crit), float(l_ch), rtol=1e-5)


# ---------------------------------------------------------------------------
# fused BN+ReLU+matmul (+stats) kernel and the FusedBottleneck built on it
# ---------------------------------------------------------------------------
