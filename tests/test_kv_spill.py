"""Host-RAM KV paging tier (ISSUE 18): async spill/refill under the
paged pool, prefix second-chance, and swap-based preemption.

Gate families:

* **Bitwise** — a prefix chain that was evicted-to-host and refilled
  serves tokens BITWISE identical to the cold solo decode, over the
  dense AND the Pallas-kernel paged-attention paths; a request
  preempted to host mid-decode resumes and finishes bitwise too (the
  refilled pages are digest-verified copies of the snapshotted
  handles).
* **Chaos drills** — the ``kv/swap_out`` / ``kv/swap_in`` seams:
  transient faults replay once and stay bitwise; permanent faults
  DEGRADE (the spill becomes a future cold miss, a ``kv_swap_failed``
  health event lands, serving stays up) and never corrupt KV.
* **Pool hygiene** — host-pool exhaustion degrades a spill to the
  pre-tier drop; refill under device-block pressure trades the coldest
  resident entries for the warm chain without cannibalizing the chain
  it serves; the host pool drains to ZERO at every shutdown path.
"""
import threading
import time

import numpy as np
import pytest

from bigdl_tpu.observability import health as _health
from bigdl_tpu.parallel import chaos
from bigdl_tpu.parallel.chaos import ChaosPlan, Rule
from bigdl_tpu.models.transformer_lm import TransformerLM
from bigdl_tpu.serving import (DecodeScheduler,
                               decode_scheduler_threads_alive)
from bigdl_tpu.serving.kv_cache import (SPILL_FAILED, SPILL_FREED,
                                        SPILL_PENDING, SPILL_READY)
from serving_helpers import no_leaked_blocks, solo_oracle as _oracle

V, H = 48, 32
MAXLEN = 256
CHUNK = 8
BS = 4          # block_size; hit_align = max(CHUNK, BS) = 8


def _model(**kw):
    cfg = dict(vocab_size=V, hidden_size=H, num_heads=4, filter_size=64,
               num_layers=2, max_len=MAXLEN)
    cfg.update(kw)
    m = TransformerLM(**cfg)
    m.ensure_initialized()
    return m


_shared = {}


def shared_model():
    if "m" not in _shared:
        _shared["m"] = _model(pos_encoding="rope", num_kv_heads=2)
    return _shared["m"]


def solo_oracle(model, prompt, max_new):
    return _oracle(model, model.params, prompt, max_new, chunk=CHUNK,
                   maxlen=MAXLEN)


def _sched(model, **kw):
    cfg = dict(max_slots=4, block_size=BS, max_seq_len=96,
               prefill_chunk=CHUNK, host_blocks=32)
    cfg.update(kw)
    return DecodeScheduler(model, **cfg)


@pytest.fixture(params=["dense", "kernel"])
def paged_path(request, monkeypatch):
    if request.param == "kernel":
        monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "interpret")
    else:
        monkeypatch.delenv("BIGDL_TPU_PAGED_ATTN", raising=False)
    return request.param


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    chaos.disarm()


def _settle(sched, deadline_s=30.0):
    """Spills are async: poll until no spilled handle is PENDING (a
    PENDING handle is a deliberate lookup miss, never a wait)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        with sched.prefix._lock:
            pend = [h for h, _ in sched.prefix._spilled.values()
                    if h.state == SPILL_PENDING]
        if not pend:
            return
        time.sleep(0.005)
    raise AssertionError("spill stage never settled")


def _drained_host(st):
    assert st["host"]["host_blocks_in_use"] == 0, \
        f"host pool leaked: {st['host']}"


def _prefix_plus(rng, prefix, n):
    return np.concatenate([prefix, rng.randint(1, V, size=n).astype(
        np.int32)])


# -- second-chance bitwise --------------------------------------------------

def test_hit_after_spill_bitwise(paged_path):
    """The tier's core gate: evict a registered chain (pages spill to
    host), revisit — the lookup refills the spilled chain through the
    ordinary warm-hit path and the tokens stay BITWISE the cold solo
    decode's, dense and kernel paths both."""
    m = shared_model()
    rng = np.random.RandomState(31)
    prefix = rng.randint(1, V, size=16).astype(np.int32)   # 4-block chain
    p1 = _prefix_plus(rng, prefix, 5)
    p2 = _prefix_plus(rng, prefix, 3)
    with _sched(m) as sched:
        r1 = sched.submit(p1, 6).result(timeout=120)
        n_entries = sched.stats()["prefix"]["entries"]
        sched.prefix.evict(n_entries)          # whole chain → host tier
        st = sched.stats()
        assert st["prefix"]["spilled_entries"] == n_entries
        assert st["prefix"]["entries"] == 0
        _settle(sched)
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r1, solo_oracle(m, p1, 6))
    assert np.array_equal(r2, solo_oracle(m, p2, 6))
    # the revisit was a REFILL, not a re-prefill: second-chance hit,
    # real bytes both directions, no failures
    assert st["prefix"]["hits_after_spill"] == 1
    assert st["prefix"]["refills"] >= 4        # the shared 16-token chain
    assert st["prefix_hits"] == 1
    assert st["host"]["swap_out_bytes"] > 0
    assert st["host"]["swap_in_bytes"] > 0
    assert st["host"]["swap_failures"] == 0
    no_leaked_blocks(st)
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


def test_pending_spill_defers_to_cold_path():
    """A lookup that races its own chain's stage treats PENDING as a
    MISS (never a wait): the revisit re-prefills, re-registers, and the
    superseded handle is discarded — the host pool gets its blocks
    back."""
    m = shared_model()
    rng = np.random.RandomState(32)
    prefix = rng.randint(1, V, size=16).astype(np.int32)
    with _sched(m) as sched:
        sched.submit(_prefix_plus(rng, prefix, 5), 4).result(timeout=120)
        # wedge the stager INSIDE the job — the worker is already parked
        # in q.get(), so the gate has to sit on the fetch it runs next
        gate = threading.Event()
        orig_fetch = sched.kv_swap._fetch

        def gated_fetch(plans, ids, pages):
            gate.wait(30.0)
            return orig_fetch(plans, ids, pages)
        sched.kv_swap._fetch = gated_fetch
        try:
            n = sched.stats()["prefix"]["entries"]
            sched.prefix.evict(n)
            st = sched.stats()
            assert st["prefix"]["spilled_entries"] == n
            r2 = sched.submit(_prefix_plus(rng, prefix, 3), 4).result(
                timeout=120)
            st = sched.stats()
            assert st["prefix"]["hits_after_spill"] == 0   # PENDING = miss
            assert st["prefix_misses"] == 2
        finally:
            gate.set()
            sched.kv_swap._fetch = orig_fetch
    assert r2.size == 4  # tokens gated bitwise in test_hit_after_spill
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


# -- swap-based preemption --------------------------------------------------

def test_preempt_then_resume_bitwise(paged_path):
    """Admission block pressure swaps the lower-priority decoding
    request out to host; it resumes from the exact interrupted position
    and BOTH streams finish bitwise the solo decode's — the refilled
    pages are digest-verified copies of the snapshot."""
    m = shared_model()
    rng = np.random.RandomState(33)
    pa = rng.randint(1, V, size=24).astype(np.int32)
    pb = rng.randint(1, V, size=24).astype(np.int32)
    # pool fits ONE request's worst case (9 blocks) + slack, not two
    with _sched(m, num_blocks=14, prefix_cache=False) as sched:
        fa = sched.submit(pa, 24, priority=0)
        t0 = time.monotonic()
        while sched.stats()["active"] == 0:    # A decoding, pages owned
            assert time.monotonic() - t0 < 60
            time.sleep(0.002)
        fb = sched.submit(pb, 8, priority=1)
        rb = fb.result(timeout=120)
        ra = fa.result(timeout=120)
        st = sched.stats()
    assert np.array_equal(ra, solo_oracle(m, pa, 24))
    assert np.array_equal(rb, solo_oracle(m, pb, 8))
    assert st["preemptions"] >= 1
    assert st["resumes"] + st["resume_recomputes"] >= 1
    assert st["host"]["swap_failures"] == 0
    assert st["kv"]["blocks_in_use"] == 0
    _drained_host(st)
    assert decode_scheduler_threads_alive() == 0


def test_preempt_swap_out_fault_degrades_to_recompute():
    """A PERMANENT swap-out fault on the preempted victim's stage: the
    resume path degrades to re-prefilling the host-resident tokens
    (``resume_recomputes``), the stream still finishes BITWISE, and a
    ``kv_swap_failed`` health event lands — a swap failure never
    corrupts KV and never takes serving down."""
    m = shared_model()
    rng = np.random.RandomState(34)
    pa = rng.randint(1, V, size=24).astype(np.int32)
    pb = rng.randint(1, V, size=24).astype(np.int32)
    events = []
    chaos.arm(ChaosPlan({"kv/swap_out": [Rule(kind="permanent", nth=1,
                                              tag="preempt")]}))
    try:
        with _health.listen(events.append), \
                _sched(m, num_blocks=14, prefix_cache=False) as sched:
            fa = sched.submit(pa, 24, priority=0)
            t0 = time.monotonic()
            while sched.stats()["active"] == 0:
                assert time.monotonic() - t0 < 60
                time.sleep(0.002)
            fb = sched.submit(pb, 8, priority=1)
            rb = fb.result(timeout=120)
            ra = fa.result(timeout=120)
            st = sched.stats()
    finally:
        chaos.disarm()
    assert np.array_equal(ra, solo_oracle(m, pa, 24))
    assert np.array_equal(rb, solo_oracle(m, pb, 8))
    assert st["preemptions"] >= 1
    assert st["resume_recomputes"] >= 1
    assert st["host"]["swap_failures"] >= 1
    assert any(e["kind"] == "health/kv_swap_failed"
               and e.get("direction") == "out" for e in events)
    _drained_host(st)
    assert decode_scheduler_threads_alive() == 0


# -- chaos drills on the prefix second-chance path --------------------------

def test_swap_out_transient_replays_bitwise():
    """A transient fault inside the stager's fetch replays once off the
    immutable snapshot — the stage lands, the revisit refills, tokens
    bitwise, zero failures counted."""
    m = shared_model()
    rng = np.random.RandomState(35)
    prefix = rng.randint(1, V, size=16).astype(np.int32)
    p2 = _prefix_plus(rng, prefix, 3)
    chaos.arm(ChaosPlan({"kv/swap_out": [Rule(kind="transient", nth=1)]}))
    with _sched(m) as sched:
        sched.submit(_prefix_plus(rng, prefix, 5), 4).result(timeout=120)
        sched.prefix.evict(sched.stats()["prefix"]["entries"])
        _settle(sched)
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r2, solo_oracle(m, p2, 6))
    assert st["prefix"]["hits_after_spill"] == 1
    assert st["host"]["swap_failures"] == 0
    assert chaos.stats()["fires"] >= 1
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


def test_swap_out_permanent_degrades_to_cold_miss():
    """A permanent stage failure drops the spill: the handle settles
    FAILED, its host blocks come back, the revisit is an ordinary cold
    miss (correct tokens, one more prefill) and serving stays up."""
    m = shared_model()
    rng = np.random.RandomState(36)
    prefix = rng.randint(1, V, size=16).astype(np.int32)
    p2 = _prefix_plus(rng, prefix, 3)
    # every=1: eviction stages leaf-first, one job per pass — fail ALL
    # of them so the whole chain degrades, not just the leaf
    chaos.arm(ChaosPlan({"kv/swap_out": [Rule(kind="permanent",
                                              every=1)]}))
    with _sched(m) as sched:
        sched.submit(_prefix_plus(rng, prefix, 5), 4).result(timeout=120)
        n = sched.stats()["prefix"]["entries"]
        sched.prefix.evict(n)
        t0 = time.monotonic()
        while True:       # FAILED is a settled state — wait for it
            with sched.prefix._lock:
                states = [h.state for h, _ in
                          sched.prefix._spilled.values()]
            if all(s != SPILL_PENDING for s in states):
                break
            assert time.monotonic() - t0 < 30
            time.sleep(0.005)
        assert SPILL_FAILED in states
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r2, solo_oracle(m, p2, 6))
    assert st["prefix"]["hits_after_spill"] == 0
    assert st["prefix_misses"] == 2            # the revisit went cold
    assert st["host"]["swap_failures"] >= 1
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


def test_swap_in_transient_replays_bitwise():
    """A transient fault on the refill path replays once against the
    immutable host bytes — the second-chance hit still lands,
    bitwise."""
    m = shared_model()
    rng = np.random.RandomState(37)
    prefix = rng.randint(1, V, size=16).astype(np.int32)
    p2 = _prefix_plus(rng, prefix, 3)
    chaos.arm(ChaosPlan({"kv/swap_in": [Rule(kind="transient", nth=1)]}))
    with _sched(m) as sched:
        sched.submit(_prefix_plus(rng, prefix, 5), 4).result(timeout=120)
        sched.prefix.evict(sched.stats()["prefix"]["entries"])
        _settle(sched)
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r2, solo_oracle(m, p2, 6))
    assert st["prefix"]["hits_after_spill"] == 1
    assert st["host"]["swap_failures"] == 0
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


def test_swap_in_permanent_degrades_to_cold_miss():
    """A hard refill failure frees the handle and the lookup degrades
    to a cold miss — correct tokens, a counted failure, serving up."""
    m = shared_model()
    rng = np.random.RandomState(38)
    prefix = rng.randint(1, V, size=16).astype(np.int32)
    p2 = _prefix_plus(rng, prefix, 3)
    chaos.arm(ChaosPlan({"kv/swap_in": [Rule(kind="permanent", nth=1)]}))
    with _sched(m) as sched:
        sched.submit(_prefix_plus(rng, prefix, 5), 4).result(timeout=120)
        sched.prefix.evict(sched.stats()["prefix"]["entries"])
        _settle(sched)
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r2, solo_oracle(m, p2, 6))
    assert st["prefix"]["hits_after_spill"] == 0
    assert st["host"]["swap_failures"] >= 1
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


# -- pool hygiene -----------------------------------------------------------

def test_host_pool_exhaustion_degrades_spill_to_drop():
    """With the host pool too small for the chain, the overflow
    victims degrade to the pre-tier drop (spill returns None) — the
    eviction still frees the device blocks, nothing crashes, and what
    DID spill stays refillable."""
    m = shared_model()
    rng = np.random.RandomState(39)
    prefix = rng.randint(1, V, size=16).astype(np.int32)   # 4 blocks
    with _sched(m, host_blocks=2) as sched:
        sched.submit(_prefix_plus(rng, prefix, 5), 4).result(timeout=120)
        n = sched.stats()["prefix"]["entries"]
        freed = sched.prefix.evict(n)
        st = sched.stats()
        assert freed == n                      # device blocks all freed
        assert 0 < st["prefix"]["spilled_entries"] <= 2
        _settle(sched)
        r2 = sched.submit(_prefix_plus(rng, prefix, 3), 6).result(
            timeout=120)
        st = sched.stats()
    assert r2.size == 6
    no_leaked_blocks(st)
    _drained_host(sched.stats())
    assert decode_scheduler_threads_alive() == 0


def test_refill_pressure_trades_cold_residents_for_warm_chain():
    """The second-chance swap under device pressure: refilling a READY
    spilled tail evicts the COLDEST unreferenced resident entries (they
    spill to host in turn — a straight trade) while the resident head
    of the chain being extended is pinned and survives untouched."""
    from bigdl_tpu.serving.kv_cache import KVSwapManager, PagedKVCache
    from bigdl_tpu.serving.prefix_cache import PrefixCache
    m = shared_model()
    rng = np.random.RandomState(40)
    tok_c = rng.randint(1, V, size=32).astype(np.int32)    # 8-block chain
    tok_a = rng.randint(1, V, size=12).astype(np.int32)    # cold bystander
    kv = PagedKVCache(m, num_blocks=9, block_size=BS, max_blocks_per_seq=16)
    swap = KVSwapManager(kv, host_blocks=32)
    pc = PrefixCache(kv, swap=swap)
    try:
        kv.ensure_capacity("c", 32)            # all 8 usable blocks
        pc.insert(tok_c, "v", kv.owner_blocks("c"))
        kv.free("c")
        assert pc.evict(4) == 4                # C's tail spills leaf-first
        kv.ensure_capacity("a", 12)
        pc.insert(tok_a, "v", kv.owner_blocks("a"))
        kv.free("a")
        t0 = time.monotonic()
        while True:
            with pc._lock:
                states = [h.state for h, _ in pc._spilled.values()]
            if all(s == SPILL_READY for s in states):
                break
            assert time.monotonic() - t0 < 30
            time.sleep(0.005)
        st = pc.stats()
        assert st["spilled_entries"] == 4 and st["entries"] == 7
        assert kv.blocks_free() == 1           # refill of 4 can't fit as-is
        blocks = pc.lookup(tok_c, "v")         # walk extends into the tail
        st = pc.stats()
        assert len(blocks) == 8                # head resident, tail refilled
        assert st["hits_after_spill"] == 1
        assert st["refills"] == 4
        # the room came from trading A's cold chain to host — spilled in
        # turn, not dropped — and the protected head C0..C3 never moved
        assert st["spilled_entries"] == 3      # A's entries, now host-side
        assert st["spills"] == 7               # C's tail (4) + A's trade (3)
        assert st["entries"] == 8              # C fully resident again
        assert kv.blocks_free() == 0
        assert pc.lookup(tok_c, "v") == blocks
    finally:
        pc.clear()
        swap.shutdown()
    assert swap.pool.stats()["host_blocks_in_use"] == 0


def test_refill_many_partial_run_and_handle_settlement():
    """Unit gates on the batched manager API: a run larger than the
    free device pool refills a leading PARTIAL run (tail handles stay
    spilled and refillable later), and freed/consumed handles settle
    idempotently."""
    from bigdl_tpu.serving.kv_cache import KVSwapManager, PagedKVCache
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=8, block_size=BS, max_blocks_per_seq=16)
    swap = KVSwapManager(kv, host_blocks=16)
    try:
        kv.ensure_capacity("seed", 6 * BS)     # 6 blocks of real pages
        blocks = kv.owner_blocks("seed")
        hs = swap.spill_many([[b] for b in blocks], tag="t")
        assert all(h is not None for h in hs)
        t0 = time.monotonic()
        while any(h.state == SPILL_PENDING for h in hs):
            assert time.monotonic() - t0 < 30
            time.sleep(0.005)
        assert all(h.state == SPILL_READY for h in hs)
        kv.free("seed")                        # pool: 7 free now
        kv.ensure_capacity("hog", 5 * BS)      # leave 2 free
        ids, consumed, dropped = swap.refill_many("re", hs)
        assert consumed == 2 and dropped == 0  # leading partial run
        assert len(ids) == 2
        assert [h.state for h in hs[:2]] == [SPILL_FREED, SPILL_FREED]
        assert all(h.state == SPILL_READY for h in hs[2:])
        kv.free("re")
        kv.free("hog")
        ids2, consumed2, dropped2 = swap.refill_many("re2", hs[2:])
        assert consumed2 == 4 and dropped2 == 0
        kv.free("re2")
        assert swap.pool.stats()["host_blocks_in_use"] == 0
    finally:
        swap.shutdown()


def test_spill_many_groups_and_host_exhaustion_per_group():
    """spill_many reserves per GROUP: groups past the pool's capacity
    degrade to None (pre-tier drop) while earlier groups stage
    normally — and a group of zero blocks is a None, not a crash."""
    from bigdl_tpu.serving.kv_cache import KVSwapManager, PagedKVCache
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=8, block_size=BS, max_blocks_per_seq=16)
    swap = KVSwapManager(kv, host_blocks=3)
    try:
        kv.ensure_capacity("seed", 6 * BS)
        blocks = kv.owner_blocks("seed")
        hs = swap.spill_many([[], [blocks[0], blocks[1]],
                              [blocks[2]], [blocks[3]]], tag="t")
        assert hs[0] is None                   # empty group
        assert hs[1] is not None and hs[1].n_blocks == 2
        assert hs[2] is not None and hs[2].n_blocks == 1
        assert hs[3] is None                   # pool exhausted (3 used)
        t0 = time.monotonic()
        live = [h for h in hs if h is not None]
        while any(h.state == SPILL_PENDING for h in live):
            assert time.monotonic() - t0 < 30
            time.sleep(0.005)
        assert all(h.state == SPILL_READY for h in live)
        for h in live:
            swap.discard(h)
        assert swap.pool.stats()["host_blocks_in_use"] == 0
        kv.free("seed")
    finally:
        swap.shutdown()
