"""Per-layer forward value/shape tests + gradient checks (modeled on the
reference's per-layer spec files in spark/dl/src/test)."""
import numpy as np
import jax.numpy as jnp
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.table import Table
from utils import check_gradient, allclose


def test_linear_matches_torch():
    m = nn.Linear(5, 3)
    m.ensure_initialized()
    x = np.random.randn(4, 5).astype(np.float32)
    out = m.forward(x)
    w = np.asarray(m.params["weight"])
    b = np.asarray(m.params["bias"])
    ref = torch.nn.functional.linear(torch.tensor(x), torch.tensor(w),
                                     torch.tensor(b)).numpy()
    assert allclose(out, ref)


def test_linear_gradcheck():
    check_gradient(nn.Linear(6, 4), np.random.randn(3, 6))


def test_spatial_convolution_matches_torch():
    m = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
    m.ensure_initialized()
    x = np.random.randn(2, 3, 9, 9).astype(np.float32)
    out = m.forward(x)
    w = torch.tensor(np.asarray(m.params["weight"]))
    b = torch.tensor(np.asarray(m.params["bias"]))
    ref = F.conv2d(torch.tensor(x), w, b, stride=2, padding=1).numpy()
    assert allclose(out, ref, tol=1e-4)
    assert out.shape == ref.shape


def test_conv_grouped():
    m = nn.SpatialConvolution(4, 8, 3, 3, n_group=2)
    m.ensure_initialized()
    x = np.random.randn(2, 4, 8, 8).astype(np.float32)
    out = m.forward(x)
    w = torch.tensor(np.asarray(m.params["weight"]))
    b = torch.tensor(np.asarray(m.params["bias"]))
    ref = F.conv2d(torch.tensor(x), w, b, groups=2).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_dilated_conv_matches_torch():
    m = nn.SpatialDilatedConvolution(2, 4, 3, 3, dilation_w=2, dilation_h=2)
    m.ensure_initialized()
    x = np.random.randn(1, 2, 10, 10).astype(np.float32)
    out = m.forward(x)
    ref = F.conv2d(torch.tensor(x),
                   torch.tensor(np.asarray(m.params["weight"])),
                   torch.tensor(np.asarray(m.params["bias"])),
                   dilation=2).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_full_convolution_matches_torch():
    m = nn.SpatialFullConvolution(3, 5, 3, 3, 2, 2, 1, 1, adj_w=1, adj_h=1)
    m.ensure_initialized()
    x = np.random.randn(2, 3, 5, 5).astype(np.float32)
    out = m.forward(x)
    w = torch.tensor(np.asarray(m.params["weight"]))
    b = torch.tensor(np.asarray(m.params["bias"]))
    ref = F.conv_transpose2d(torch.tensor(x), w, b, stride=2, padding=1,
                             output_padding=1).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_volumetric_conv_matches_torch():
    m = nn.VolumetricConvolution(2, 4, 3, 3, 3, 1, 1, 1, 1, 1, 1)
    m.ensure_initialized()
    x = np.random.randn(1, 2, 6, 6, 6).astype(np.float32)
    out = m.forward(x)
    ref = F.conv3d(torch.tensor(x),
                   torch.tensor(np.asarray(m.params["weight"])),
                   torch.tensor(np.asarray(m.params["bias"])),
                   padding=1).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_maxpool_matches_torch():
    m = nn.SpatialMaxPooling(2, 2, 2, 2)
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    assert allclose(m.forward(x),
                    F.max_pool2d(torch.tensor(x), 2).numpy())


def test_maxpool_ceil():
    m = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
    x = np.random.randn(2, 3, 7, 7).astype(np.float32)
    ref = F.max_pool2d(torch.tensor(x), 3, 2, ceil_mode=True).numpy()
    assert allclose(m.forward(x), ref)


def test_avgpool_matches_torch():
    m = nn.SpatialAveragePooling(2, 2, 2, 2)
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    assert allclose(m.forward(x), F.avg_pool2d(torch.tensor(x), 2).numpy())


def test_batchnorm_train_and_eval():
    m = nn.SpatialBatchNormalization(4)
    x = np.random.randn(8, 4, 5, 5).astype(np.float32) * 3 + 1
    m.training()
    out = m.forward(x)
    assert abs(float(np.mean(np.asarray(out)))) < 1e-4
    assert abs(float(np.std(np.asarray(out))) - 1.0) < 1e-2
    # running stats moved toward batch stats
    rm = np.asarray(m.state["running_mean"])
    assert np.all(np.abs(rm) > 0)
    m.evaluate()
    out_eval = m.forward(x)
    assert out_eval.shape == x.shape


def test_batchnorm_matches_torch_eval():
    m = nn.BatchNormalization(6)
    m.ensure_initialized()
    m.evaluate()
    x = np.random.randn(4, 6).astype(np.float32)
    out = m.forward(x)
    tb = torch.nn.BatchNorm1d(6).eval()
    with torch.no_grad():
        tb.weight.copy_(torch.tensor(np.asarray(m.params["weight"])))
        tb.bias.copy_(torch.tensor(np.asarray(m.params["bias"])))
    ref = tb(torch.tensor(x)).detach().numpy()
    assert allclose(out, ref, tol=1e-4)


def test_layernorm_matches_torch():
    m = nn.LayerNormalization(8)
    m.ensure_initialized()
    x = np.random.randn(2, 5, 8).astype(np.float32)
    out = m.forward(x)
    ref = F.layer_norm(torch.tensor(x), (8,),
                       torch.tensor(np.asarray(m.params["weight"])),
                       torch.tensor(np.asarray(m.params["bias"])),
                       eps=1e-6).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_lrn_matches_torch():
    m = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0)
    x = np.abs(np.random.randn(2, 7, 4, 4)).astype(np.float32)
    ref = torch.nn.LocalResponseNorm(5, 0.0001, 0.75, 1.0)(
        torch.tensor(x)).numpy()
    assert allclose(m.forward(x), ref, tol=1e-4)


@pytest.mark.parametrize("cls,tfn", [
    (nn.ReLU, F.relu), (nn.Tanh, torch.tanh), (nn.Sigmoid, torch.sigmoid),
    (nn.ELU, F.elu), (nn.SoftPlus, F.softplus), (nn.SoftSign, F.softsign),
    (nn.LogSigmoid, F.logsigmoid), (nn.ReLU6, F.relu6),
])
def test_activations_match_torch(cls, tfn):
    x = np.random.randn(4, 7).astype(np.float32)
    out = cls().forward(x)
    ref = tfn(torch.tensor(x)).numpy()
    assert np.allclose(out, ref, atol=1e-5)


def test_hard_sigmoid_reference_formula():
    # BigDL HardSigmoid is clip(0.2x + 0.5, 0, 1) (keras convention),
    # NOT torch's clip(x/6 + 0.5, 0, 1).
    x = np.random.randn(4, 7).astype(np.float32)
    out = nn.HardSigmoid().forward(x)
    assert allclose(out, np.clip(0.2 * x + 0.5, 0, 1))


def test_softmax_logsoftmax():
    x = np.random.randn(3, 5).astype(np.float32)
    assert allclose(nn.SoftMax().forward(x),
                    F.softmax(torch.tensor(x), dim=1).numpy())
    assert allclose(nn.LogSoftMax().forward(x),
                    F.log_softmax(torch.tensor(x), dim=1).numpy())


def test_prelu_gradcheck():
    check_gradient(nn.PReLU(3), np.random.randn(2, 3, 4, 4))


def test_dropout_train_eval():
    m = nn.Dropout(0.5)
    x = np.ones((100, 100), np.float32)
    m.training()
    out = np.asarray(m.forward(x))
    frac = np.mean(out == 0)
    assert 0.3 < frac < 0.7
    kept = out[out != 0]
    assert np.allclose(kept, 2.0)
    m.evaluate()
    assert allclose(m.forward(x), x)


def test_lookup_table():
    m = nn.LookupTable(10, 4)
    m.ensure_initialized()
    ids = np.array([[1, 2, 10]], np.float32)
    out = m.forward(ids)
    assert out.shape == (1, 3, 4)
    w = np.asarray(m.params["weight"])
    assert allclose(out[0, 0], w[0])
    assert allclose(out[0, 2], w[9])


def test_embedding_gradcheck_like_sum():
    m = nn.CMul([4])
    check_gradient(m, np.random.randn(3, 4))


def test_reshape_view_squeeze():
    x = np.random.randn(2, 3, 4).astype(np.float32)
    assert nn.Reshape([12]).forward(x).shape == (2, 12)
    assert nn.View(12).forward(x).shape == (2, 12)
    assert nn.Squeeze(2).forward(np.zeros((3, 1, 4))).shape == (3, 4)
    assert nn.Unsqueeze(2).forward(np.zeros((3, 4))).shape == (3, 1, 4)
    assert nn.Transpose([(1, 2)]).forward(x).shape == (3, 2, 4)


def test_narrow_select_index():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    out = nn.Narrow(2, 2, 2).forward(x)
    assert out.shape == (2, 2, 4)
    assert allclose(out, x[:, 1:3])
    out = nn.Select(1, 2).forward(x)
    assert allclose(out, x[1])
    out = nn.Select(1, -1).forward(x)
    assert allclose(out, x[1])


def test_padding_zeropad():
    x = np.ones((2, 2), np.float32)
    out = nn.Padding(2, 2, 2, value=7.0).forward(x)
    assert out.shape == (2, 4)
    assert np.all(np.asarray(out)[:, 2:] == 7.0)
    x4 = np.ones((1, 1, 3, 3), np.float32)
    out = nn.SpatialZeroPadding(1, 1, 1, 1).forward(x4)
    assert out.shape == (1, 1, 5, 5)
    out = nn.SpatialZeroPadding(-1, -1, -1, -1).forward(x4)
    assert out.shape == (1, 1, 1, 1)


def test_table_ops():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(3, 4).astype(np.float32)
    t = Table(a, b)
    assert allclose(nn.CAddTable().forward(t), a + b)
    assert allclose(nn.CSubTable().forward(t), a - b)
    assert allclose(nn.CMulTable().forward(t), a * b)
    assert allclose(nn.CMaxTable().forward(t), np.maximum(a, b))
    assert allclose(nn.JoinTable(2).forward(t), np.concatenate([a, b], 1))
    assert allclose(nn.DotProduct().forward(t), np.sum(a * b, -1))
    parts = nn.SplitTable(2).forward(a)
    assert len(parts) == 4
    assert allclose(parts[1], a[:, 0])
    assert allclose(nn.SelectTable(2).forward(t), b)


def test_mm_mv():
    a = np.random.randn(2, 3, 4).astype(np.float32)
    b = np.random.randn(2, 4, 5).astype(np.float32)
    assert allclose(nn.MM().forward(Table(a, b)), a @ b)
    v = np.random.randn(2, 5).astype(np.float32)
    assert allclose(nn.MV().forward(Table(b, v)),
                    np.einsum("bij,bj->bi", b, v))


def test_containers():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = np.random.randn(3, 4).astype(np.float32)
    out = seq.forward(x)
    assert out.shape == (3, 2)
    check_gradient(seq, x)

    ct = nn.ConcatTable(nn.Linear(4, 2), nn.Identity())
    out = ct.forward(x)
    assert isinstance(out, Table) and len(out) == 2

    cc = nn.Concat(2, nn.Linear(4, 2), nn.Linear(4, 3))
    assert cc.forward(x).shape == (3, 5)

    pt = nn.ParallelTable(nn.Linear(4, 2), nn.ReLU())
    out = pt.forward(Table(x, x))
    assert out[1].shape == (3, 2) and out[2].shape == (3, 4)


def test_graph():
    inp = nn.Input()
    h = nn.Linear(4, 8)(inp)
    a = nn.ReLU()(h)
    b = nn.Tanh()(h)
    merged = nn.CAddTable()(a, b)
    out = nn.Linear(8, 2)(merged)
    g = nn.Graph(inp, out)
    x = np.random.randn(5, 4).astype(np.float32)
    y = g.forward(x)
    assert y.shape == (5, 2)
    check_gradient(g, x)


def test_bottle():
    m = nn.Bottle(nn.Linear(4, 3))
    x = np.random.randn(2, 5, 4).astype(np.float32)
    assert m.forward(x).shape == (2, 5, 3)


def test_highway_maxout():
    x = np.random.randn(3, 6).astype(np.float32)
    assert nn.Highway(6).forward(x).shape == (3, 6)
    assert nn.Maxout(6, 4, 3).forward(x).shape == (3, 4)


def test_upsampling_resize():
    x = np.random.randn(1, 2, 4, 4).astype(np.float32)
    assert nn.UpSampling2D((2, 2)).forward(x).shape == (1, 2, 8, 8)
    out = nn.ResizeBilinear(8, 8).forward(x)
    ref = F.interpolate(torch.tensor(x), size=(8, 8), mode="bilinear",
                        align_corners=False).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_resize_align_corners():
    x = np.random.randn(1, 1, 4, 4).astype(np.float32)
    out = nn.ResizeBilinear(7, 7, align_corners=True).forward(x)
    ref = F.interpolate(torch.tensor(x), size=(7, 7), mode="bilinear",
                        align_corners=True).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_normalize():
    x = np.random.randn(4, 6).astype(np.float32)
    out = np.asarray(nn.Normalize(2).forward(x))
    norms = np.linalg.norm(out, axis=1)
    assert np.allclose(norms, 1.0, atol=1e-4)


def test_temporal_conv_matches_torch():
    m = nn.TemporalConvolution(6, 8, 3, 1)
    m.ensure_initialized()
    x = np.random.randn(2, 10, 6).astype(np.float32)
    out = m.forward(x)
    w = np.asarray(m.params["weight"])  # (out, in, k)
    ref = F.conv1d(torch.tensor(x).transpose(1, 2), torch.tensor(w),
                   torch.tensor(np.asarray(m.params["bias"]))
                   ).transpose(1, 2).numpy()
    assert allclose(out, ref, tol=1e-4)


def test_locally_connected_2d():
    m = nn.LocallyConnected2D(2, 6, 6, 3, 3, 3)
    x = np.random.randn(2, 2, 6, 6).astype(np.float32)
    out = m.forward(x)
    assert out.shape == (2, 3, 4, 4)
    check_gradient(m, x, tol=5e-2)


def test_separable_conv():
    m = nn.SpatialSeparableConvolution(3, 6, 2, 3, 3)
    x = np.random.randn(1, 3, 8, 8).astype(np.float32)
    assert m.forward(x).shape == (1, 6, 6, 6)


def test_conv_map():
    tbl = nn.SpatialConvolutionMap.one_to_one(3)
    m = nn.SpatialConvolutionMap(tbl, 3, 3)
    x = np.random.randn(1, 3, 6, 6).astype(np.float32)
    assert m.forward(x).shape == (1, 3, 4, 4)


def test_gradient_reversal():
    m = nn.GradientReversal(0.5)
    x = np.random.randn(3, 4).astype(np.float32)
    assert allclose(m.forward(x), x)
    g = m.backward(x, np.ones((3, 4), np.float32))
    assert allclose(g, -0.5 * np.ones((3, 4)))


def test_srelu_forward():
    m = nn.SReLU((4,))
    x = np.random.randn(3, 4).astype(np.float32)
    assert m.forward(x).shape == (3, 4)


def test_masking():
    m = nn.Masking(0.0)
    x = np.array([[[1, 2], [0, 0], [3, 0]]], np.float32)
    out = np.asarray(m.forward(x))
    assert np.all(out[0, 1] == 0)
    assert np.all(out[0, 0] == [1, 2])
    assert np.all(out[0, 2] == [3, 0])


@pytest.mark.slow
def test_inception_v2_shapes():
    from bigdl_tpu.models import Inception_v2_NoAuxClassifier, Inception_v2
    x = np.random.randn(1, 3, 224, 224).astype(np.float32)
    m = Inception_v2_NoAuxClassifier(class_num=7)
    m.evaluate()
    assert m.forward(x).shape == (1, 7)
    m2 = Inception_v2(class_num=7)
    m2.evaluate()
    assert m2.forward(x).shape == (1, 21)


def test_inception_v2_block_smoke():
    """Unmarked smoke for the v2 BN-everywhere block (the full-model
    shapes test above is @slow): one inception_layer_v2 stage forwards."""
    from bigdl_tpu.models.inception import inception_layer_v2
    blk = inception_layer_v2(64, ([16], [16, 24], [16, 24], ("avg", 24)),
                             name_prefix="smoke/")
    blk.evaluate()
    x = np.random.randn(1, 64, 14, 14).astype(np.float32)
    out = blk.forward(x)
    assert out.shape == (1, 16 + 24 + 24 + 24, 14, 14)


def test_dynamic_graph_switch_merge():
    # data-dependent branch: pred chooses between x*2 (true) and -x (false);
    # the untaken side must not execute (eager scheduler parity:
    # nn/DynamicGraph.scala + nn/ops/ControlOps.scala)
    calls = []

    class Probe(nn.Identity):
        def _apply(self, params, state, x, training, rng):
            calls.append(self.name)
            return x

    def build():
        calls.clear()
        data, pred = nn.Input(), nn.Input()
        sw = nn.Switch()(data, pred)
        f = nn.MulConstant(-1.0)(nn.SelectTable(1)(sw))
        f = Probe(name="false_branch")(f)
        t = nn.MulConstant(2.0)(nn.SelectTable(2)(sw))
        t = Probe(name="true_branch")(t)
        out = nn.Merge()(f, t)
        return nn.DynamicGraph([data, pred], out)

    x = np.arange(4, dtype=np.float32)
    g = build()
    y = g.forward(Table(x, np.bool_(True)))
    np.testing.assert_allclose(np.asarray(y), x * 2)
    assert calls == ["true_branch"]

    g2 = build()
    y = g2.forward(Table(x, np.bool_(False)))
    np.testing.assert_allclose(np.asarray(y), -x)
    assert calls == ["false_branch"]

    # StaticGraph is Graph
    assert nn.StaticGraph is nn.Graph


def test_l1_penalty():
    m = nn.L1Penalty(0.5)
    x = np.random.randn(3, 4).astype(np.float32)
    y = m.forward(x)
    np.testing.assert_allclose(np.asarray(y), x)  # identity forward

    # grad of sum(f(x)) = 1 + 0.5*sign(x)  (provide_output=True)
    import jax
    import jax.numpy as jnp
    p, st = m.init()
    gfn = jax.grad(lambda xx: jnp.sum(m.apply(p, st, xx, False, None)[0]))
    g = gfn(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), 1.0 + 0.5 * np.sign(x),
                               rtol=1e-6)

    # size_average divides by nElement; provide_output=False drops gradOutput
    m2 = nn.L1Penalty(2.0, size_average=True, provide_output=False)
    p2, st2 = m2.init()
    g2 = jax.grad(lambda xx: jnp.sum(m2.apply(p2, st2, xx, False, None)[0]))(
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g2), 2.0 / x.size * np.sign(x),
                               rtol=1e-6)


def test_layer_exception_context_notes():
    """utils/LayerException.scala parity: errors inside a model carry the
    failing layer's identity (PEP-678 notes; type/message unchanged)."""
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                      nn.Linear(9, 2, name="bad_fc"))
    with pytest.raises(Exception) as ei:
        m.forward(np.zeros((2, 4), np.float32))
    notes = getattr(ei.value, "__notes__", [])
    assert any("bad_fc" in n for n in notes), notes
    assert any("Sequential" in n for n in notes), notes


def test_batchnorm_large_mean_stable():
    """Shifted one-pass BN stats survive mean >> std (ADVICE r2: plain
    E[x^2]-E[x]^2 catastrophically cancels for un-normalized inputs).
    After one step the running mean becomes the shift, so the SECOND
    step's variance must match the two-pass reference closely."""
    import jax
    from bigdl_tpu.nn import BatchNormalization
    rng = np.random.RandomState(0)
    x = (1e4 + rng.randn(64, 8).astype(np.float32))
    bn = BatchNormalization(8, momentum=1.0)  # running stats = batch stats
    params, state = bn.init(jax.random.PRNGKey(0))
    _, state = bn.apply(params, state, jnp.asarray(x), training=True)
    # second pass: shift == true mean, cancellation-free
    _, state2 = bn.apply(params, state, jnp.asarray(x), training=True)
    ref_var = x.var(axis=0, ddof=1)
    got = np.asarray(state2["running_var"])
    assert np.allclose(got, ref_var, rtol=1e-3), (got, ref_var)
