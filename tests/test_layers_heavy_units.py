"""Compile-heavy single-layer checks split from test_layers.py so
xdist loadfile sharding overlaps them with the rest (each is ~10 s of
XLA compile on CPU)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.utils.table import Table
from utils import allclose, check_gradient


def test_binary_tree_lstm():
    """Level-synchronous sweep must equal explicit recursion
    (reference BinaryTreeLSTM recursiveForward)."""
    import jax.numpy as jnp
    np.random.seed(7)
    # 2-sample batch; sample 0: root(1)=[2,3], leaves 2,3; node 4,5 padding
    # sample 1: root(1)=[4,5], node4=[2,3] internal, leaves 2,3,5
    trees = np.zeros((2, 5, 3), np.float32)
    trees[:, :, 0] = -1
    trees[0, 0] = [2, 3, -1]
    trees[0, 1] = [0, 0, 1]
    trees[0, 2] = [0, 0, 2]
    trees[1, 0] = [4, 5, -1]
    trees[1, 3] = [2, 3, 0]
    trees[1, 1] = [0, 0, 1]
    trees[1, 2] = [0, 0, 3]
    trees[1, 4] = [0, 0, 2]
    words = np.random.randn(2, 3, 4).astype(np.float32)
    m = nn.BinaryTreeLSTM(4, 6)
    out = np.asarray(m.forward((words, trees)))
    assert out.shape == (2, 5, 6)
    p = m.params

    def leaf(w):
        return m._leaf(p, jnp.asarray(w))

    # sample 0
    c2, h2 = leaf(words[0, 0])
    c3, h3 = leaf(words[0, 1])
    _, h1 = m._compose(p, c2, h2, c3, h3)
    assert allclose(out[0, 0], h1, tol=1e-5)
    assert allclose(out[0, 1], h2, tol=1e-5)
    assert np.all(out[0, 3] == 0) and np.all(out[0, 4] == 0)
    # sample 1 (two levels deep)
    c2, h2 = leaf(words[1, 0])
    c3, h3 = leaf(words[1, 2])
    c5, h5 = leaf(words[1, 1])
    c4, h4 = m._compose(p, c2, h2, c3, h3)
    _, h1 = m._compose(p, c4, h4, c5, h5)
    assert allclose(out[1, 0], h1, tol=1e-5)
    assert allclose(out[1, 3], h4, tol=1e-5)
    # backward produces grads for inputs
    g = m.backward((words, trees), np.ones_like(out))
    assert np.asarray(g[0]).shape == words.shape
    assert np.isfinite(np.asarray(g[0])).all()
    # no-gate-output variant
    m2 = nn.BinaryTreeLSTM(4, 6, gate_output=False)
    assert m2.forward((words, trees)).shape == (2, 5, 6)


def test_recurrent_hoisted_projection_matches_step():
    # Recurrent scans step_pre when the cell offers precompute (input
    # projection hoisted out of the loop); must be numerically identical
    # to the per-step path for every hoistable cell type.
    import jax
    import jax.numpy as jnp

    cells = [
        nn.LSTM(6, 8),
        nn.GRU(6, 8),
        nn.RnnCell(6, 8),
        nn.LSTMPeephole(6, 8),
        nn.MultiRNNCell([nn.LSTM(6, 8), nn.LSTM(8, 8)]),
    ]
    x = jnp.asarray(np.random.RandomState(0).randn(3, 7, 6), np.float32)
    for cell in cells:
        rec = nn.Recurrent(cell)
        p, st = rec.init(jax.random.PRNGKey(0))
        assert cell.precompute(p["cell"], jnp.moveaxis(x, 1, 0)) is not None
        y_pre, _ = rec.apply(p, st, x, False, None)
        # oracle: explicit per-timestep python loop over cell.step
        h = cell.init_hidden(3, x.dtype)
        outs = []
        for t in range(x.shape[1]):
            out, h = cell.step(p["cell"], x[:, t], h)
            outs.append(out)
        y_step = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(y_pre), np.asarray(y_step),
                                   atol=1e-5,
                                   err_msg=type(cell).__name__)


def test_maxpool_fast_grad_mode():
    """grad_mode='fast' (shifted-max tree): identical forward; identical
    backward on tie-free inputs."""
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    for fmt, shape in (("NCHW", (2, 3, 11, 13)), ("NHWC", (2, 11, 13, 3))):
        x = jnp.asarray(rng.rand(*shape) * 10, jnp.float32)  # tie-free
        for args in ((3, 3, 2, 2, 1, 1), (2, 2, 2, 2, 0, 0),
                     (3, 2, 1, 2, 0, 1)):
            exact = nn.SpatialMaxPooling(*args, format=fmt)
            fast = nn.SpatialMaxPooling(*args, format=fmt, grad_mode="fast")
            y1 = exact.forward(np.asarray(x))
            y2 = fast.forward(np.asarray(x))
            np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                       err_msg=f"{fmt} {args}")
            p, st = exact.init()
            g1 = jax.grad(lambda xx: jnp.sum(
                exact.apply(p, st, xx, False, None)[0] ** 2))(x)
            g2 = jax.grad(lambda xx: jnp.sum(
                fast.apply(p, st, xx, False, None)[0] ** 2))(x)
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       atol=1e-5, err_msg=f"{fmt} {args}")


def test_lstm_matches_torch_lstm():
    """bigdl_tpu LSTM == torch.nn.LSTM with mapped weights: both use gate
    order (i, f, g, o); torch stores (4H, I) row-major and splits bias
    into b_ih + b_hh. Validates the whole sequence output and final
    (h, c)."""
    import torch
    I, H, T, B = 5, 7, 6, 3
    m = nn.Recurrent(nn.LSTM(I, H))
    m.ensure_initialized()
    cell_p = m.params["cell"]
    tl = torch.nn.LSTM(I, H, batch_first=True)
    with torch.no_grad():
        tl.weight_ih_l0.copy_(torch.tensor(
            np.asarray(cell_p["w_i"]).T.copy()))
        tl.weight_hh_l0.copy_(torch.tensor(
            np.asarray(cell_p["w_h"]).T.copy()))
        tl.bias_ih_l0.copy_(torch.tensor(np.asarray(cell_p["bias"])))
        tl.bias_hh_l0.zero_()
    x = np.random.RandomState(0).randn(B, T, I).astype(np.float32)
    ours = np.asarray(m.evaluate().forward(x))
    with torch.no_grad():
        theirs, _ = tl(torch.tensor(x))
    np.testing.assert_allclose(ours, theirs.numpy(), atol=1e-5)


def test_gru_matches_numpy_oracle():
    """bigdl_tpu GRU == a numpy replica of the documented equations.
    Convention note: the candidate applies the reset gate to h BEFORE the
    hidden matmul (``(r*h) @ w_hn`` — the original/Torch7-era GRU the
    reference's nn/GRU.scala follows), unlike torch.nn.GRU's cuDNN
    variant ``r * (W_hn h)`` — the two are NOT linearly weight-mappable,
    so the oracle here is the spec, not torch."""
    I, H, T, B = 4, 6, 5, 2
    m = nn.Recurrent(nn.GRU(I, H))
    m.ensure_initialized()
    p = {k: np.asarray(v) for k, v in m.params["cell"].items()}
    x = np.random.RandomState(1).randn(B, T, I).astype(np.float32)
    ours = np.asarray(m.evaluate().forward(x))

    def sig(a):
        return 1.0 / (1.0 + np.exp(-a))

    h = np.zeros((B, H), np.float32)
    outs = []
    for t in range(T):
        pre = x[:, t] @ p["w_i"] + p["bias"]
        hh = h @ p["w_h"]
        r = sig(pre[:, :H] + hh[:, :H])
        z = sig(pre[:, H:2 * H] + hh[:, H:])
        n = np.tanh(pre[:, 2 * H:] + (r * h) @ p["w_hn"])
        h = (1 - z) * n + z * h
        outs.append(h)
    np.testing.assert_allclose(ours, np.stack(outs, 1), atol=1e-5)
