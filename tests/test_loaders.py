"""Caffe / Torch loader tests (modeled on reference CaffeLoaderSpec /
TorchFileSpec). Binary fixtures are synthesized in-test with minimal
protobuf / t7 encoders."""
import struct

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.loaders import (load_caffe, parse_prototxt,
                               read_caffemodel_blobs, load_torch, load_t7)
from bigdl_tpu.visualization.event_writer import (_varint, _field, _f_bytes,
                                                  _f_string)

LENET_PROTOTXT = """
name: "TinyNet"
input: "data"
input_dim: 1
input_dim: 3
input_dim: 12
input_dim: 12
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "incept_a" type: "Convolution" bottom: "pool1" top: "incept_a"
  convolution_param { num_output: 2 kernel_size: 1 }
}
layer {
  name: "incept_b" type: "Convolution" bottom: "pool1" top: "incept_b"
  convolution_param { num_output: 3 kernel_size: 1 }
}
layer { name: "merge" type: "Concat" bottom: "incept_a" bottom: "incept_b"
        top: "merge" }
layer {
  name: "fc" type: "InnerProduct" bottom: "merge" top: "fc"
  inner_product_param { num_output: 5 }
}
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def test_parse_prototxt():
    net = parse_prototxt(LENET_PROTOTXT)
    assert net["name"] == "TinyNet"
    assert len(net["layer"]) == 8
    assert net["layer"][0]["convolution_param"]["num_output"] == 4
    assert net["layer"][5]["bottom"] == ["incept_a", "incept_b"]


def test_caffe_prototxt_to_graph():
    import tempfile, os
    with tempfile.NamedTemporaryFile("w", suffix=".prototxt",
                                     delete=False) as f:
        f.write(LENET_PROTOTXT)
        path = f.name
    try:
        # note: InnerProduct input channels come from flattened conv output:
        # merge has 5 ch at 6x6 → but caffe flattens implicitly; our loader
        # tracks channels only, so wire fc on channels*h*w via Reshape is the
        # caller's concern for spatial inputs. Use 1x1 spatial to keep exact.
        g = load_caffe(path, input_channels=3)
        assert g is not None
    finally:
        os.unlink(path)


def _encode_blob(arr):
    arr = np.asarray(arr, np.float32)
    shape_payload = b""
    for d in arr.shape:
        shape_payload += _field(1, 0) + _varint(d)
    blob = _f_bytes(7, shape_payload)
    blob += _f_bytes(5, arr.astype("<f4").tobytes())
    return blob


def _encode_layer(name, blobs):
    payload = _f_string(1, name)
    for b in blobs:
        payload += _f_bytes(7, _encode_blob(b))
    return _f_bytes(100, payload)


def test_caffemodel_binary_reader(tmp_path):
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    b = np.random.randn(4).astype(np.float32)
    data = _encode_layer("conv1", [w, b]) + \
        _encode_layer("fc", [np.random.randn(5, 20).astype(np.float32)])
    path = str(tmp_path / "model.caffemodel")
    with open(path, "wb") as f:
        f.write(data)
    blobs = read_caffemodel_blobs(path)
    assert set(blobs) == {"conv1", "fc"}
    assert np.allclose(blobs["conv1"][0], w)
    assert np.allclose(blobs["conv1"][1], b)
    assert blobs["fc"][0].shape == (5, 20)


def test_caffe_load_with_weights(tmp_path):
    proto = """
input: "data"
input_dim: 1
input_dim: 2
input_dim: 4
input_dim: 4
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 3 kernel_size: 3 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "out" }
"""
    ppath = str(tmp_path / "net.prototxt")
    with open(ppath, "w") as f:
        f.write(proto)
    w = np.random.randn(3, 2, 3, 3).astype(np.float32)
    b = np.zeros(3, np.float32)
    mpath = str(tmp_path / "net.caffemodel")
    with open(mpath, "wb") as f:
        f.write(_encode_layer("conv1", [w, b]))
    g = load_caffe(ppath, mpath, input_channels=2)
    x = np.random.randn(1, 2, 4, 4).astype(np.float32)
    out = np.asarray(g.evaluate().forward(x))
    import jax
    import torch
    import torch.nn.functional as F
    ref = F.relu(F.conv2d(torch.tensor(x), torch.tensor(w),
                          torch.tensor(b))).numpy()
    assert np.allclose(out, ref, atol=1e-4)


# ---------------------------------------------------------------------------
# t7 writer (test fixture) — inverse of loaders/torchfile.py reader
# ---------------------------------------------------------------------------
class _T7Writer:
    def __init__(self, f):
        self.f = f
        self.next_index = 1

    def w_int(self, v):
        self.f.write(struct.pack("<i", v))

    def w_long(self, v):
        self.f.write(struct.pack("<q", v))

    def w_double(self, v):
        self.f.write(struct.pack("<d", v))

    def w_string(self, s):
        b = s.encode()
        self.w_int(len(b))
        self.f.write(b)

    def write_number(self, v):
        self.w_int(1)
        self.w_double(float(v))

    def write_string_obj(self, s):
        self.w_int(2)
        self.w_string(s)

    def write_bool(self, v):
        self.w_int(5)
        self.w_int(1 if v else 0)

    def _new_index(self):
        i = self.next_index
        self.next_index += 1
        return i

    def write_table(self, d):
        self.w_int(3)
        self.w_int(self._new_index())
        self.w_int(len(d))
        for k, v in d.items():
            self.write_obj(k)
            self.write_obj(v)

    def write_tensor(self, arr):
        arr = np.ascontiguousarray(arr, np.float64)
        self.w_int(4)
        self.w_int(self._new_index())
        self.w_string("V 1")
        self.w_string("torch.DoubleTensor")
        self.w_int(arr.ndim)
        for s in arr.shape:
            self.w_long(s)
        strides = [s // arr.itemsize for s in arr.strides]
        for s in strides:
            self.w_long(s)
        self.w_long(1)  # storage offset (1-based)
        # storage
        self.w_int(4)
        self.w_int(self._new_index())
        self.w_string("V 1")
        self.w_string("torch.DoubleStorage")
        self.w_long(arr.size)
        self.f.write(arr.tobytes())

    def write_module(self, typename, table):
        self.w_int(4)
        self.w_int(self._new_index())
        self.w_string("V 1")
        self.w_string(typename)
        self.write_table(table)

    def write_obj(self, v):
        if isinstance(v, bool):
            self.write_bool(v)
        elif isinstance(v, (int, float)):
            self.write_number(v)
        elif isinstance(v, str):
            self.write_string_obj(v)
        elif isinstance(v, np.ndarray):
            self.write_tensor(v)
        elif isinstance(v, dict):
            self.write_table(v)
        elif isinstance(v, tuple) and v[0] == "module":
            self.write_module(v[1], v[2])
        else:
            raise TypeError(type(v))


def test_t7_roundtrip_linear(tmp_path):
    w = np.random.randn(3, 5)
    b = np.random.randn(3)
    path = str(tmp_path / "model.t7")
    with open(path, "wb") as f:
        wr = _T7Writer(f)
        wr.write_module("nn.Sequential", {
            "modules": {1: ("module", "nn.Linear",
                            {"weight": w, "bias": b}),
                        2: ("module", "nn.ReLU", {})}})
    m = load_torch(path)
    x = np.random.randn(4, 5).astype(np.float32)
    out = np.asarray(m.forward(x))
    ref = np.maximum(x @ w.T.astype(np.float32) + b.astype(np.float32), 0)
    assert np.allclose(out, ref, atol=1e-4)


def test_t7_raw_objects(tmp_path):
    path = str(tmp_path / "obj.t7")
    arr = np.arange(12).reshape(3, 4).astype(np.float64)
    with open(path, "wb") as f:
        wr = _T7Writer(f)
        wr.write_table({"x": arr, "n": 7, "s": "hello", "flag": True})
    obj = load_t7(path)
    assert obj["n"] == 7
    assert obj["s"] == "hello"
    assert obj["flag"] is True
    assert np.allclose(obj["x"], arr)
