"""Caffe / Torch loader tests (modeled on reference CaffeLoaderSpec /
TorchFileSpec). Binary fixtures are synthesized in-test with minimal
protobuf / t7 encoders."""
import struct

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.loaders import (load_caffe, parse_prototxt,
                               read_caffemodel_blobs, load_torch, load_t7)
from bigdl_tpu.visualization.event_writer import (_varint, _field, _f_bytes,
                                                  _f_string)

LENET_PROTOTXT = """
name: "TinyNet"
input: "data"
input_dim: 1
input_dim: 3
input_dim: 12
input_dim: 12
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "incept_a" type: "Convolution" bottom: "pool1" top: "incept_a"
  convolution_param { num_output: 2 kernel_size: 1 }
}
layer {
  name: "incept_b" type: "Convolution" bottom: "pool1" top: "incept_b"
  convolution_param { num_output: 3 kernel_size: 1 }
}
layer { name: "merge" type: "Concat" bottom: "incept_a" bottom: "incept_b"
        top: "merge" }
layer {
  name: "fc" type: "InnerProduct" bottom: "merge" top: "fc"
  inner_product_param { num_output: 5 }
}
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def test_parse_prototxt():
    net = parse_prototxt(LENET_PROTOTXT)
    assert net["name"] == "TinyNet"
    assert len(net["layer"]) == 8
    assert net["layer"][0]["convolution_param"]["num_output"] == 4
    assert net["layer"][5]["bottom"] == ["incept_a", "incept_b"]


def test_caffe_prototxt_to_graph():
    import tempfile, os
    with tempfile.NamedTemporaryFile("w", suffix=".prototxt",
                                     delete=False) as f:
        f.write(LENET_PROTOTXT)
        path = f.name
    try:
        # note: InnerProduct input channels come from flattened conv output:
        # merge has 5 ch at 6x6 → but caffe flattens implicitly; our loader
        # tracks channels only, so wire fc on channels*h*w via Reshape is the
        # caller's concern for spatial inputs. Use 1x1 spatial to keep exact.
        g = load_caffe(path, input_channels=3)
        assert g is not None
    finally:
        os.unlink(path)


def _encode_blob(arr):
    arr = np.asarray(arr, np.float32)
    shape_payload = b""
    for d in arr.shape:
        shape_payload += _field(1, 0) + _varint(d)
    blob = _f_bytes(7, shape_payload)
    blob += _f_bytes(5, arr.astype("<f4").tobytes())
    return blob


def _encode_layer(name, blobs):
    payload = _f_string(1, name)
    for b in blobs:
        payload += _f_bytes(7, _encode_blob(b))
    return _f_bytes(100, payload)


def test_caffemodel_binary_reader(tmp_path):
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    b = np.random.randn(4).astype(np.float32)
    data = _encode_layer("conv1", [w, b]) + \
        _encode_layer("fc", [np.random.randn(5, 20).astype(np.float32)])
    path = str(tmp_path / "model.caffemodel")
    with open(path, "wb") as f:
        f.write(data)
    blobs = read_caffemodel_blobs(path)
    assert set(blobs) == {"conv1", "fc"}
    assert np.allclose(blobs["conv1"][0], w)
    assert np.allclose(blobs["conv1"][1], b)
    assert blobs["fc"][0].shape == (5, 20)


def test_caffe_load_with_weights(tmp_path):
    proto = """
input: "data"
input_dim: 1
input_dim: 2
input_dim: 4
input_dim: 4
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 3 kernel_size: 3 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "out" }
"""
    ppath = str(tmp_path / "net.prototxt")
    with open(ppath, "w") as f:
        f.write(proto)
    w = np.random.randn(3, 2, 3, 3).astype(np.float32)
    b = np.zeros(3, np.float32)
    mpath = str(tmp_path / "net.caffemodel")
    with open(mpath, "wb") as f:
        f.write(_encode_layer("conv1", [w, b]))
    g = load_caffe(ppath, mpath, input_channels=2)
    x = np.random.randn(1, 2, 4, 4).astype(np.float32)
    out = np.asarray(g.evaluate().forward(x))
    import jax
    import torch
    import torch.nn.functional as F
    ref = F.relu(F.conv2d(torch.tensor(x), torch.tensor(w),
                          torch.tensor(b))).numpy()
    assert np.allclose(out, ref, atol=1e-4)


# ---------------------------------------------------------------------------
# t7 writer (test fixture) — inverse of loaders/torchfile.py reader
# ---------------------------------------------------------------------------
class _T7Writer:
    def __init__(self, f):
        self.f = f
        self.next_index = 1

    def w_int(self, v):
        self.f.write(struct.pack("<i", v))

    def w_long(self, v):
        self.f.write(struct.pack("<q", v))

    def w_double(self, v):
        self.f.write(struct.pack("<d", v))

    def w_string(self, s):
        b = s.encode()
        self.w_int(len(b))
        self.f.write(b)

    def write_number(self, v):
        self.w_int(1)
        self.w_double(float(v))

    def write_string_obj(self, s):
        self.w_int(2)
        self.w_string(s)

    def write_bool(self, v):
        self.w_int(5)
        self.w_int(1 if v else 0)

    def _new_index(self):
        i = self.next_index
        self.next_index += 1
        return i

    def write_table(self, d):
        self.w_int(3)
        self.w_int(self._new_index())
        self.w_int(len(d))
        for k, v in d.items():
            self.write_obj(k)
            self.write_obj(v)

    def write_tensor(self, arr):
        arr = np.ascontiguousarray(arr, np.float64)
        self.w_int(4)
        self.w_int(self._new_index())
        self.w_string("V 1")
        self.w_string("torch.DoubleTensor")
        self.w_int(arr.ndim)
        for s in arr.shape:
            self.w_long(s)
        strides = [s // arr.itemsize for s in arr.strides]
        for s in strides:
            self.w_long(s)
        self.w_long(1)  # storage offset (1-based)
        # storage
        self.w_int(4)
        self.w_int(self._new_index())
        self.w_string("V 1")
        self.w_string("torch.DoubleStorage")
        self.w_long(arr.size)
        self.f.write(arr.tobytes())

    def write_module(self, typename, table):
        self.w_int(4)
        self.w_int(self._new_index())
        self.w_string("V 1")
        self.w_string(typename)
        self.write_table(table)

    def write_obj(self, v):
        if isinstance(v, bool):
            self.write_bool(v)
        elif isinstance(v, (int, float)):
            self.write_number(v)
        elif isinstance(v, str):
            self.write_string_obj(v)
        elif isinstance(v, np.ndarray):
            self.write_tensor(v)
        elif isinstance(v, dict):
            self.write_table(v)
        elif isinstance(v, tuple) and v[0] == "module":
            self.write_module(v[1], v[2])
        else:
            raise TypeError(type(v))


def test_t7_roundtrip_linear(tmp_path):
    w = np.random.randn(3, 5)
    b = np.random.randn(3)
    path = str(tmp_path / "model.t7")
    with open(path, "wb") as f:
        wr = _T7Writer(f)
        wr.write_module("nn.Sequential", {
            "modules": {1: ("module", "nn.Linear",
                            {"weight": w, "bias": b}),
                        2: ("module", "nn.ReLU", {})}})
    m = load_torch(path)
    x = np.random.randn(4, 5).astype(np.float32)
    out = np.asarray(m.forward(x))
    ref = np.maximum(x @ w.T.astype(np.float32) + b.astype(np.float32), 0)
    assert np.allclose(out, ref, atol=1e-4)


def test_t7_raw_objects(tmp_path):
    path = str(tmp_path / "obj.t7")
    arr = np.arange(12).reshape(3, 4).astype(np.float64)
    with open(path, "wb") as f:
        wr = _T7Writer(f)
        wr.write_table({"x": arr, "n": 7, "s": "hello", "flag": True})
    obj = load_t7(path)
    assert obj["n"] == 7
    assert obj["s"] == "hello"
    assert obj["flag"] is True
    assert np.allclose(obj["x"], arr)


# ---- TensorFlow GraphDef loader --------------------------------------------

def _tf_attr(key, val_bytes):
    from bigdl_tpu.loaders import wire as W
    return W.field_bytes(5, W.field_string(1, key) + W.field_bytes(2, val_bytes))


def _tf_tensor(arr):
    from bigdl_tpu.loaders import wire as W
    arr = np.asarray(arr)
    shape = b"".join(W.field_bytes(2, W.field_varint(1, d)) for d in arr.shape)
    dt = 3 if arr.dtype.kind == "i" else 1
    body = W.field_varint(1, dt) + W.field_bytes(2, shape)
    if dt == 3:
        body += W.field_bytes(4, arr.astype("<i4").tobytes())
    else:
        body += W.field_bytes(4, arr.astype("<f4").tobytes())
    return W.field_bytes(8, body)


def _tf_node(name, op, inputs=(), **attrs):
    from bigdl_tpu.loaders import wire as W
    b = W.field_string(1, name) + W.field_string(2, op)
    for i in inputs:
        b += W.field_string(3, i)
    for k, vb in attrs.items():
        b += _tf_attr(k, vb)
    return W.field_bytes(1, b)


def _attr_s(s):
    from bigdl_tpu.loaders import wire as W
    return W.field_bytes(2, s.encode())


def _attr_list_i(vals):
    from bigdl_tpu.loaders import wire as W
    return W.field_bytes(1, W.field_packed_varint(3, vals))


def _attr_f(v):
    from bigdl_tpu.loaders import wire as W
    return W.field_float(4, v)


def test_tf_graphdef_parse_and_forward():
    from bigdl_tpu.loaders import load_tf_graph, parse_graphdef
    rng = np.random.RandomState(0)
    w = rng.randn(3, 3, 2, 4).astype(np.float32) * 0.3   # HWIO
    b = rng.randn(4).astype(np.float32) * 0.1
    wfc = rng.randn(4, 5).astype(np.float32) * 0.3       # (in, out)
    bfc = rng.randn(5).astype(np.float32) * 0.1

    gd = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("w", "Const", value=_tf_tensor(w)),
        _tf_node("conv", "Conv2D", ["x", "w"],
                 strides=_attr_list_i([1, 1, 1, 1]), padding=_attr_s("SAME")),
        _tf_node("b", "Const", value=_tf_tensor(b)),
        _tf_node("bias", "BiasAdd", ["conv", "b"]),
        _tf_node("relu", "Relu", ["bias"]),
        _tf_node("pool", "MaxPool", ["relu"],
                 ksize=_attr_list_i([1, 2, 2, 1]),
                 strides=_attr_list_i([1, 2, 2, 1]),
                 padding=_attr_s("VALID")),
        _tf_node("axes", "Const", value=_tf_tensor(np.array([1, 2], np.int32))),
        _tf_node("gap", "Mean", ["pool", "axes"]),
        _tf_node("wfc", "Const", value=_tf_tensor(wfc)),
        _tf_node("fc", "MatMul", ["gap", "wfc"]),
        _tf_node("bfc", "Const", value=_tf_tensor(bfc)),
        _tf_node("logits", "BiasAdd", ["fc", "bfc"]),
        _tf_node("prob", "Softmax", ["logits"]),
    ])

    nodes = parse_graphdef(gd)
    assert [n["op"] for n in nodes][:2] == ["Placeholder", "Const"]
    model = load_tf_graph(gd)
    model.evaluate()

    x = rng.randn(2, 2, 8, 8).astype(np.float32)  # NCHW
    out = np.asarray(model.forward(x))
    assert out.shape == (2, 5)
    assert np.allclose(out.sum(-1), 1.0, atol=1e-5)

    # reference computation with torch (TF semantics: SAME pad 3x3/s1 == pad 1)
    import torch
    import torch.nn.functional as F
    tx = torch.from_numpy(x)
    tw = torch.from_numpy(np.transpose(w, (3, 2, 0, 1)).copy())
    y = F.conv2d(tx, tw, torch.from_numpy(b), padding=1).relu()
    y = F.max_pool2d(y, 2)
    y = y.mean((2, 3))
    y = y @ torch.from_numpy(wfc) + torch.from_numpy(bfc)
    y = torch.softmax(y, -1).numpy()
    assert np.allclose(out, y, atol=1e-4), np.abs(out - y).max()


def test_tf_flatten_matmul_order():
    # NHWC flatten order must be preserved for MatMul weights
    from bigdl_tpu.loaders import load_tf_graph
    rng = np.random.RandomState(1)
    wfc = rng.randn(2 * 2 * 3, 4).astype(np.float32)
    gd = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("shape", "Const",
                 value=_tf_tensor(np.array([-1, 12], np.int32))),
        _tf_node("flat", "Reshape", ["x", "shape"]),
        _tf_node("wfc", "Const", value=_tf_tensor(wfc)),
        _tf_node("fc", "MatMul", ["flat", "wfc"]),
    ])
    model = load_tf_graph(gd).evaluate()
    x = rng.randn(2, 3, 2, 2).astype(np.float32)  # NCHW, C=3, H=W=2
    out = np.asarray(model.forward(x))
    x_nhwc = np.transpose(x, (0, 2, 3, 1)).reshape(2, -1)
    assert np.allclose(out, x_nhwc @ wfc, atol=1e-5)


def test_tf_unsupported_op_raises():
    from bigdl_tpu.loaders import load_tf_graph
    gd = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("y", "SomeFakeOpV9", ["x"]),
    ])
    with pytest.raises(NotImplementedError):
        load_tf_graph(gd)


# ---- bigdl.proto-compatible serializer -------------------------------------

def test_bigdl_proto_roundtrip_sequential():
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    import tempfile, os
    model = nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(4),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([4 * 4 * 4], batch_mode=True),
        nn.Linear(4 * 4 * 4, 10),
        nn.LogSoftMax()).evaluate()
    x = np.random.randn(2, 1, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.bigdl")
        save_bigdl(model, path)
        loaded = load_bigdl(path)
    out = np.asarray(loaded.forward(x))
    assert out.shape == ref.shape
    assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()


def test_bigdl_proto_moduletype_names():
    from bigdl_tpu.loaders.bigdl_proto import (save_bigdl,
                                               decode_bigdl_module)
    import tempfile, os
    model = nn.Sequential(nn.Linear(3, 2)).evaluate()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.bigdl")
        save_bigdl(model, path)
        mod = decode_bigdl_module(open(path, "rb").read())
    assert mod["moduleType"] == "com.intel.analytics.bigdl.nn.Sequential"
    sub = mod["subModules"][0]
    assert sub["moduleType"] == "com.intel.analytics.bigdl.nn.Linear"
    assert int(sub["attr"]["inputSize"]) == 3
    assert int(sub["attr"]["outputSize"]) == 2
    assert len(sub["parameters"]) == 2  # weight + bias
    assert sub["parameters"][0].shape == (2, 3)


def test_bigdl_proto_grouped_conv_layout():
    from bigdl_tpu.loaders.bigdl_proto import (save_bigdl,
                                               decode_bigdl_module,
                                               load_bigdl)
    import tempfile, os
    model = nn.Sequential(
        nn.SpatialConvolution(4, 6, 3, 3, n_group=2)).evaluate()
    x = np.random.randn(2, 4, 7, 7).astype(np.float32)
    ref = np.asarray(model.forward(x))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "g.bigdl")
        save_bigdl(model, path)
        mod = decode_bigdl_module(open(path, "rb").read())
        # reference layout: (nGroup, out/g, in/g, kh, kw)
        assert mod["subModules"][0]["parameters"][0].shape == (2, 3, 2, 3, 3)
        out = np.asarray(load_bigdl(path).forward(x))
    assert np.allclose(out, ref, atol=1e-5)


def test_bigdl_proto_legacy_weight_bias_fields():
    # legacy (pre-hasParameters) checkpoints store weight=3 / bias=4
    from bigdl_tpu.loaders.bigdl_proto import (load_bigdl, _enc_tensor,
                                               _attr_i32, _attr_bool,
                                               _attr_null_reg,
                                               _attr_null_tensor,
                                               _map_entry, _Ids)
    from bigdl_tpu.loaders import wire as W
    w = np.random.randn(2, 3).astype(np.float32)
    b = np.random.randn(2).astype(np.float32)
    ids = _Ids()
    body = W.field_string(1, "fc")
    body += W.field_bytes(3, _enc_tensor(w, ids))
    body += W.field_bytes(4, _enc_tensor(b, ids))
    body += W.field_string(7, "com.intel.analytics.bigdl.nn.Linear")
    for k, v in [("inputSize", _attr_i32(3)), ("outputSize", _attr_i32(2)),
                 ("withBias", _attr_bool(True))]:
        body += _map_entry(k, v)
    m = load_bigdl(body)
    x = np.random.randn(4, 3).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert np.allclose(out, x @ w.T + b, atol=1e-5)


def test_bigdl_proto_bn_running_stats_roundtrip():
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    import tempfile, os
    model = nn.Sequential(nn.SpatialConvolution(1, 3, 3, 3),
                          nn.SpatialBatchNormalization(3), nn.ReLU())
    model.training()
    for _ in range(3):
        model.forward(np.random.randn(4, 1, 6, 6).astype(np.float32))
    model.evaluate()
    x = np.random.randn(2, 1, 6, 6).astype(np.float32)
    ref = np.asarray(model.forward(x))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bn.bigdl")
        save_bigdl(model, path)
        out = np.asarray(load_bigdl(path).forward(x))
    assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()


def test_bigdl_proto_negative_int_attr():
    from bigdl_tpu.loaders.bigdl_proto import (save_bigdl, load_bigdl,
                                               decode_bigdl_module)
    import tempfile, os
    model = nn.Sequential(nn.Reshape([-1], batch_mode=True)).evaluate()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "r.bigdl")
        save_bigdl(model, path)
        mod = decode_bigdl_module(open(path, "rb").read())
        assert list(mod["subModules"][0]["attr"]["size"]) == [-1]
        m = load_bigdl(path)
    out = m.forward(np.random.randn(2, 3, 4).astype(np.float32))
    assert out.shape == (2, 12)


def test_tf_const_float_and_int_val_fields():
    from bigdl_tpu.loaders import wire as W
    from bigdl_tpu.loaders.tensorflow import _decode_tensor
    # float_val (field 5) scalar splat
    shape = W.field_bytes(2, W.field_varint(1, 3))
    t = W.field_varint(1, 1) + W.field_bytes(2, shape) + W.field_float(5, 2.5)
    arr = _decode_tensor(t)
    assert arr.shape == (3,) and np.allclose(arr, 2.5)
    # int_val (field 7)
    t = W.field_varint(1, 3) + W.field_bytes(2, shape) + \
        W.field_packed_varint(7, [1, 2, 3])
    arr = _decode_tensor(t)
    assert np.array_equal(arr, [1, 2, 3])


def test_tf_rank_changing_reshape_order():
    # [B,H,W,C] -> [-1, H*W, C] must preserve TF (NHWC) element order
    from bigdl_tpu.loaders import load_tf_graph
    gd = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("shape", "Const",
                 value=_tf_tensor(np.array([-1, 4, 3], np.int32))),
        _tf_node("r", "Reshape", ["x", "shape"]),
    ])
    m = load_tf_graph(gd).evaluate()
    x = np.random.randn(2, 3, 2, 2).astype(np.float32)  # NCHW C=3 H=W=2
    out = np.asarray(m.forward(x))
    expect = np.transpose(x, (0, 2, 3, 1)).reshape(2, 4, 3)
    assert np.allclose(out, expect)


# ---------------------------------------------------------------------------
# TF export (tf_saver) round-trips + extended op set
# ---------------------------------------------------------------------------


def test_tf_save_load_roundtrip_lenet():
    """save_tf_graph -> load_tf_graph reproduces LeNet-5 outputs."""
    import numpy as np
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.loaders.tf_saver import save_tf_graph
    from bigdl_tpu.loaders.tensorflow import load_tf_graph
    model = LeNet5(10)
    model.ensure_initialized()
    model.evaluate()
    data = save_tf_graph(model, input_shape=(1, 28, 28))
    loaded = load_tf_graph(data)
    x = np.random.randn(2, 28, 28).astype(np.float32)
    ref = np.asarray(model.forward(x))
    out = np.asarray(loaded.forward(x.reshape(2, 1, 28, 28)))
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


def test_tf_save_load_roundtrip_conv_bn_concat():
    """BN + LRN + Concat branches + SAME pools survive the round trip."""
    import numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.loaders.tf_saver import save_tf_graph
    from bigdl_tpu.loaders.tensorflow import load_tf_graph
    branch1 = nn.Sequential(
        nn.SpatialConvolution(4, 6, 1, 1), nn.ReLU())
    branch2 = nn.Sequential(
        nn.SpatialConvolution(4, 6, 3, 3, 1, 1, 1, 1), nn.ReLU())
    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, -1, -1),
        nn.SpatialBatchNormalization(4),
        nn.ReLU(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
        nn.Concat(2, branch1, branch2),
        nn.SpatialAveragePooling(1, 1, global_pooling=True),
        nn.View(12),
        nn.Linear(12, 5),
        nn.LogSoftMax())
    model.training()
    import numpy as _np
    for _ in range(2):  # populate BN running stats
        model.forward(_np.random.randn(4, 3, 8, 8).astype(_np.float32))
    model.evaluate()
    data = save_tf_graph(model, input_shape=(3, 8, 8))
    loaded = load_tf_graph(data)
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    out = np.asarray(loaded.forward(x))
    assert np.allclose(out, ref, atol=1e-3), np.abs(out - ref).max()


def test_tf_save_load_roundtrip_residual():
    """ConcatTable + CAddTable (residual block) exports to AddV2."""
    import numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.loaders.tf_saver import save_tf_graph
    from bigdl_tpu.loaders.tensorflow import load_tf_graph
    block = nn.Sequential(
        nn.ConcatTable(
            nn.Sequential(nn.SpatialConvolution(3, 3, 3, 3, 1, 1, 1, 1),
                          nn.ReLU()),
            nn.Identity()),
        nn.CAddTable(),
        nn.ReLU())
    block.ensure_initialized()
    block.evaluate()
    data = save_tf_graph(block, input_shape=(3, 6, 6))
    loaded = load_tf_graph(data)
    x = np.random.randn(2, 3, 6, 6).astype(np.float32)
    ref = np.asarray(block.forward(x))
    out = np.asarray(loaded.forward(x))
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


def test_tf_loader_extended_ops_and_folding():
    """Round-2 op growth: elementwise/comparison ops load, and const
    sub-DAGs (Shape->Range style) fold to Consts up front."""
    from bigdl_tpu.loaders import load_tf_graph
    from bigdl_tpu.loaders.tf_saver import _attr_tensor, _attr_type
    from bigdl_tpu.loaders import wire as W

    def _t(arr):
        from bigdl_tpu.loaders.tf_saver import _tensor_proto
        return W.field_bytes(8, _tensor_proto(np.asarray(arr)))

    gd = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("sq", "Square", ["x"]),
        _tf_node("half", "Const", value=_t(np.float32(0.5))),
        _tf_node("scaled", "Mul", ["sq", "half"]),
        _tf_node("r", "Rsqrt", ["scaled"]),
        _tf_node("out", "Neg", ["r"]),
    ])
    m = load_tf_graph(gd)
    x = np.random.RandomState(0).rand(2, 3).astype(np.float32) + 0.5
    out = np.asarray(m.forward(x))
    ref = -1.0 / np.sqrt(0.5 * x ** 2)
    assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()

    # const folding: Range(0, Rank-const, 1) style chain becomes a Const
    gd2 = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("c0", "Const", value=_t(np.int32(0))),
        _tf_node("c2", "Const", value=_t(np.int32(2))),
        _tf_node("c1", "Const", value=_t(np.int32(1))),
        _tf_node("axes", "Range", ["c0", "c2", "c1"]),
        _tf_node("s", "Sum", ["x", "axes"]),
    ])
    m2 = load_tf_graph(gd2)
    x2 = np.arange(6.0).reshape(2, 3).astype(np.float32)
    assert np.isclose(float(np.asarray(m2.forward(x2))), 15.0)


def test_tf_loader_split_multi_output():
    """Split produces a Table; consumers select outputs by :index."""
    from bigdl_tpu.loaders import load_tf_graph
    from bigdl_tpu.loaders import wire as W

    def _t(arr):
        from bigdl_tpu.loaders.tf_saver import _tensor_proto
        return W.field_bytes(8, _tensor_proto(np.asarray(arr)))

    gd = b"".join([
        _tf_node("x", "Placeholder"),
        _tf_node("axis", "Const", value=_t(np.int32(1))),
        _tf_node("split", "Split", ["axis", "x"],
                 num_split=W.field_varint(3, 2)),
        _tf_node("out", "Sub", ["split", "split:1"]),
    ])
    m = load_tf_graph(gd)
    x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert np.allclose(out, x[:, :3] - x[:, 3:], atol=1e-6)


# ---------------------------------------------------------------------------
# Caffe export (caffe_persister) round-trips
# ---------------------------------------------------------------------------


def test_caffe_save_load_roundtrip_convnet(tmp_path):
    """save_caffe -> load_caffe reproduces a conv/pool/fc net's outputs."""
    from bigdl_tpu import nn
    from bigdl_tpu.loaders.caffe_persister import save_caffe
    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialAveragePooling(1, 1, global_pooling=True),
        nn.View(4),
        nn.Linear(4, 5),
        nn.SoftMax())
    model.ensure_initialized()
    model.evaluate()
    pp = str(tmp_path / "net.prototxt")
    mp = str(tmp_path / "net.caffemodel")
    save_caffe(model, pp, mp, input_shape=(3, 8, 8))
    g = load_caffe(pp, mp).evaluate()
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    out = np.asarray(g.forward(x))
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


def test_caffe_save_load_roundtrip_inception_block(tmp_path):
    """BN(+Scale pair), LRN, Dropout and Concat branches survive the trip."""
    from bigdl_tpu import nn
    from bigdl_tpu.loaders.caffe_persister import save_caffe
    branch1 = nn.Sequential(nn.SpatialConvolution(4, 6, 1, 1), nn.ReLU())
    branch2 = nn.Sequential(
        nn.SpatialConvolution(4, 6, 3, 3, 1, 1, 1, 1), nn.ReLU())
    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(4),
        nn.ReLU(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.Concat(2, branch1, branch2),
        nn.Dropout(0.4),
        nn.SpatialAveragePooling(1, 1, global_pooling=True),
        nn.View(12),
        nn.Linear(12, 5),
        nn.LogSoftMax())
    model.training()
    for _ in range(2):  # populate BN running stats
        model.forward(np.random.randn(4, 3, 8, 8).astype(np.float32))
    model.evaluate()
    pp = str(tmp_path / "net.prototxt")
    mp = str(tmp_path / "net.caffemodel")
    save_caffe(model, pp, mp, input_shape=(3, 8, 8))
    g = load_caffe(pp, mp).evaluate()
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    out = np.asarray(g.forward(x))
    assert np.allclose(out, ref, atol=1e-3), np.abs(out - ref).max()


def test_caffe_save_load_roundtrip_residual(tmp_path):
    """ConcatTable + CAddTable (residual block) exports to Eltwise SUM."""
    from bigdl_tpu import nn
    from bigdl_tpu.loaders.caffe_persister import save_caffe
    model = nn.Sequential(
        nn.ConcatTable(
            nn.Sequential(nn.SpatialConvolution(3, 3, 3, 3, 1, 1, 1, 1),
                          nn.ReLU()),
            nn.Identity()),
        nn.CAddTable(),
        nn.ReLU(),
        nn.SpatialAveragePooling(1, 1, global_pooling=True),
        nn.View(3),
        nn.Linear(3, 2))
    model.ensure_initialized()
    model.evaluate()
    pp = str(tmp_path / "res.prototxt")
    mp = str(tmp_path / "res.caffemodel")
    save_caffe(model, pp, mp, input_shape=(3, 6, 6))
    g = load_caffe(pp, mp).evaluate()
    x = np.random.RandomState(2).randn(2, 3, 6, 6).astype(np.float32)
    ref = np.asarray(model.forward(x))
    out = np.asarray(g.forward(x))
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


# ---------------------------------------------------------------------------
# Torch t7 export (save_torch / save_t7) round-trips
# ---------------------------------------------------------------------------


def test_t7_save_load_roundtrip_convnet(tmp_path):
    """save_torch -> load_torch reproduces a conv/pool/fc net's outputs."""
    from bigdl_tpu import nn
    from bigdl_tpu.loaders.torchfile import save_torch
    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(4),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.View(4 * 4 * 4),
        nn.Linear(4 * 4 * 4, 5),
        nn.LogSoftMax())
    model.training()
    for _ in range(2):  # populate BN running stats
        model.forward(np.random.randn(4, 3, 8, 8).astype(np.float32))
    model.evaluate()
    path = str(tmp_path / "net.t7")
    save_torch(model, path)
    loaded = load_torch(path).evaluate()
    x = np.random.RandomState(3).randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    out = np.asarray(loaded.forward(x))
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


def test_t7_save_load_raw_objects(tmp_path):
    """save_t7/load_t7 round-trips tables, numbers, strings, tensors."""
    from bigdl_tpu.loaders.torchfile import save_t7
    arr = np.arange(12, dtype=np.float64).reshape(3, 4)
    ints = np.array([2, 5], dtype=np.int64)
    path = str(tmp_path / "obj.t7")
    save_t7({"x": arr, "n": 7, "s": "hello", "flag": True,
             "sub": {"ints": ints}}, path)
    obj = load_t7(path)
    assert obj["n"] == 7
    assert obj["s"] == "hello"
    assert obj["flag"] is True
    assert np.allclose(obj["x"], arr)
    assert obj["x"].dtype == np.float64
    assert np.array_equal(obj["sub"]["ints"], ints)


# ---------------------------------------------------------------------------
# TF Session training path (utils/tf/Session.scala parity)
# ---------------------------------------------------------------------------


def test_tf_session_train_and_predict(tmp_path):
    """A saved GraphDef trains through TFSession: loss drops, BN/weights
    update, predict serves the trained graph."""
    from bigdl_tpu import nn
    from bigdl_tpu.loaders import TFSession
    from bigdl_tpu.loaders.tf_saver import save_tf_graph
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.trigger import max_epoch

    src = nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        nn.SpatialAveragePooling(1, 1, global_pooling=True),
        nn.View(4), nn.Linear(4, 3), nn.LogSoftMax())
    src.ensure_initialized()
    src.evaluate()
    gd = save_tf_graph(src, input_shape=(1, 8, 8))

    rng = np.random.RandomState(0)
    # separable-by-construction task: class mean shifts
    xs = rng.randn(96, 1, 8, 8).astype(np.float32)
    ys = np.repeat(np.arange(3), 32)
    xs += ys[:, None, None, None] * 3.0
    samples = [Sample(x, np.float32(y + 1)) for x, y in zip(xs, ys)]

    sess = TFSession(gd)
    before = sess.predict([], xs[:9])
    model = sess.train([], DataSet.array(samples), SGD(learningrate=0.1),
                       nn.ClassNLLCriterion(), max_epoch(15), batch_size=32)
    after = sess.predict([], xs)
    acc = (after.argmax(-1) == ys).mean()
    assert acc > 0.8, acc
    assert not np.allclose(before, after[:9])  # training changed the graph


def test_caffe_innerproduct_spatial_input_roundtrip():
    """InnerProduct after conv/pool stacks has spatial extent >1x1; the
    loader must recover the true flattened input dim from the weight blob
    (prototxt can't express it)."""
    import tempfile, os
    import numpy as np
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.loaders import save_caffe, load_caffe

    model = LeNet5(10)
    model.ensure_initialized()
    model.evaluate()
    x = np.random.RandomState(3).randn(2, 1, 28, 28).astype(np.float32)
    ref = np.asarray(model.forward(x))
    tmp = tempfile.mkdtemp()
    proto = os.path.join(tmp, "m.prototxt")
    cm = os.path.join(tmp, "m.caffemodel")
    save_caffe(model, proto, cm, input_shape=(1, 28, 28))
    loaded = load_caffe(proto, cm).evaluate()
    out = np.asarray(loaded.forward(x))
    np.testing.assert_allclose(out, ref, atol=1e-4)


@pytest.mark.slow
def test_exported_graphdef_executes_in_real_tensorflow():
    """save_tf_graph output must not just round-trip through OUR loader —
    real TensorFlow must import AND execute it with identical outputs."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.loaders import save_tf_graph

    m = LeNet5(10)
    m.ensure_initialized()
    m.evaluate()
    x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
    ref = np.asarray(m.forward(x))
    gd_bytes = save_tf_graph(m, (1, 28, 28))

    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(gd_bytes)
    with tf.Graph().as_default() as g:
        tf.import_graph_def(gd, name="")
        inp = g.get_tensor_by_name("input:0")
        out = g.get_tensor_by_name(gd.node[-1].name + ":0")
        with tf.compat.v1.Session(graph=g) as sess:
            tf_out = sess.run(out, {inp: x.transpose(0, 2, 3, 1)})
    np.testing.assert_allclose(np.asarray(tf_out).reshape(ref.shape), ref,
                               atol=1e-5)


def test_load_graph_written_by_real_tensorflow():
    """The TF GraphDef loader must execute graphs REAL TensorFlow builds,
    not just our own exporter's output."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.loaders import load_tf_graph

    tf1 = tf.compat.v1
    g = tf.Graph()
    with g.as_default():
        rng = np.random.RandomState(0)
        x = tf1.placeholder(tf.float32, [None, 8, 8, 3], name="input")
        w = tf.constant(rng.randn(3, 3, 3, 4).astype(np.float32))
        y = tf.nn.conv2d(x, w, strides=[1, 1, 1, 1], padding="SAME")
        y = tf.nn.bias_add(y, tf.constant(rng.randn(4).astype(np.float32)))
        y = tf.nn.relu(y)
        y = tf1.reshape(y, [-1, 8 * 8 * 4])
        wd = tf.constant(rng.randn(8 * 8 * 4, 5).astype(np.float32))
        y = tf.nn.softmax(tf1.matmul(y, wd), name="probs")
    xin = np.random.RandomState(1).randn(2, 8, 8, 3).astype(np.float32)
    with tf1.Session(graph=g) as sess:
        ref = sess.run("probs:0", {"input:0": xin})

    m = load_tf_graph(g.as_graph_def().SerializeToString()).evaluate()
    ours = np.asarray(m.forward(xin.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(ours.reshape(ref.shape), ref, atol=1e-5)


def test_load_deconv_graph_written_by_real_tensorflow():
    """Conv2DBackpropInput (tf.nn.conv2d_transpose) loads and matches real
    TF, both SAME (incl. asymmetric pad) and VALID (VERDICT r2 missing #1;
    reference analog utils/tf/loaders/Conv2DBackpropInput.scala:30)."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.loaders import load_tf_graph

    tf1 = tf.compat.v1
    # (padding, stride, in_hw, out_hw): the last two are the NON-divisible
    # sizes TF permits (ceil(out/s)==in for SAME, ceil((out-k+1)/s)==in for
    # VALID) whose trailing pixels no forward window touches
    cases = (("SAME", 2, 5, 10), ("VALID", 2, 5, 11), ("SAME", 1, 5, 5),
             ("SAME", 2, 3, 5), ("VALID", 2, 2, 6))
    for padding, stride, ih, oh in cases:
        g = tf.Graph()
        with g.as_default():
            rng = np.random.RandomState(0)
            x = tf1.placeholder(tf.float32, [2, ih, ih, 4], name="input")
            w = tf.constant(rng.randn(3, 3, 6, 4).astype(np.float32))
            y = tf.nn.conv2d_transpose(
                x, w, output_shape=[2, oh, oh, 6],
                strides=[1, stride, stride, 1], padding=padding)
            y = tf.nn.relu(y, name="out")
        xin = np.random.RandomState(1).randn(2, ih, ih, 4).astype(np.float32)
        with tf1.Session(graph=g) as sess:
            ref = sess.run("out:0", {"input:0": xin})
        m = load_tf_graph(g.as_graph_def().SerializeToString()).evaluate()
        ours = np.asarray(m.forward(xin.transpose(0, 3, 1, 2)))
        np.testing.assert_allclose(
            ours.transpose(0, 2, 3, 1), ref, atol=1e-4,
            err_msg=f"{padding} stride {stride} {ih}->{oh}")


def test_load_topk_graph_written_by_real_tensorflow():
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.loaders import load_tf_graph

    tf1 = tf.compat.v1
    g = tf.Graph()
    with g.as_default():
        x = tf1.placeholder(tf.float32, [4, 10], name="input")
        vals, idx = tf.nn.top_k(x, k=3)
        tf.identity(vals, name="vals")
        tf.identity(tf.cast(idx, tf.int32), name="idx")
    xin = np.random.RandomState(2).randn(4, 10).astype(np.float32)
    with tf1.Session(graph=g) as sess:
        rv, ri = sess.run(["vals:0", "idx:0"], {"input:0": xin})
    m = load_tf_graph(g.as_graph_def().SerializeToString(),
                      outputs=["vals", "idx"]).evaluate()
    out = m.forward(xin)
    np.testing.assert_allclose(np.asarray(out[1]), rv, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(out[2]), ri)


def test_load_graph_with_in_graph_decode_via_input_cut():
    """Graphs carrying their own input pipeline (DecodeRaw/DecodeJpeg-style
    nodes) load by cutting at the decode OUTPUT (README Design-deltas:
    in-graph data ops are host-side by design; reference analog
    utils/tf/Session.scala feeding DecodeJpeg through Spark)."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.loaders import load_tf_graph

    tf1 = tf.compat.v1
    g = tf.Graph()
    with g.as_default():
        raw = tf1.placeholder(tf.string, [], name="bytes_in")
        dec = tf.io.decode_raw(raw, tf.float32)
        dec = tf1.reshape(dec, [2, 6], name="decoded")
        w = tf.constant(np.random.RandomState(0).randn(6, 3)
                        .astype(np.float32))
        tf.nn.relu(tf1.matmul(dec, w), name="out")
    xin = np.random.RandomState(1).randn(2, 6).astype(np.float32)
    with tf1.Session(graph=g) as sess:
        ref = sess.run("out:0", {"bytes_in:0": xin.tobytes()})
    # cut at the decode output: the decode/reshape subtree is replaced by a
    # dense-array Input; the unsupported string ops are never converted
    m = load_tf_graph(g.as_graph_def().SerializeToString(),
                      inputs=["decoded"], outputs=["out"]).evaluate()
    ours = np.asarray(m.forward(xin))
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_tf_random_shuffle_module():
    from bigdl_tpu.loaders.tensorflow import _TFRandomShuffle
    import jax
    m = _TFRandomShuffle()
    m.ensure_initialized()
    import jax.numpy as jnp
    x = np.arange(20.0).reshape(10, 2)
    # no rng → identity (deterministic inference)
    out, _ = m.apply({}, {}, jnp.asarray(x), False, None)
    np.testing.assert_array_equal(np.asarray(out), x)
    # with rng → a permutation of the rows
    out, _ = m.apply({}, {}, jnp.asarray(x), True, jax.random.PRNGKey(3))
    got = np.asarray(out)
    assert sorted(map(tuple, got)) == sorted(map(tuple, x))


# ---------------------------------------------------------------------------
# Caffe converter long tail (r4): Power/PReLU/Slice/Threshold/Exp/Log/
# AbsVal/ELU/Deconvolution + a VGG-16-topology caffemodel end-to-end
# ---------------------------------------------------------------------------


def test_caffe_long_tail_layers(tmp_path):
    proto = """
input: "data"
input_dim: 1
input_dim: 4
input_dim: 6
input_dim: 6
layer { name: "pw" type: "Power" bottom: "data" top: "pw"
        power_param { power: 2.0 scale: 0.5 shift: 1.0 } }
layer { name: "abs" type: "AbsVal" bottom: "pw" top: "abs" }
layer { name: "elu" type: "ELU" bottom: "abs" top: "elu"
        elu_param { alpha: 0.5 } }
layer { name: "prelu" type: "PReLU" bottom: "elu" top: "prelu" }
layer { name: "sl" type: "Slice" bottom: "prelu" top: "s1" top: "s2"
        slice_param { axis: 1 slice_point: 1 } }
layer { name: "exp" type: "Exp" bottom: "s1" top: "e1"
        exp_param { scale: 0.5 shift: 0.25 } }
layer { name: "log" type: "Log" bottom: "s2" top: "l2"
        log_param { shift: 8.0 } }
layer { name: "cat" type: "Concat" bottom: "e1" bottom: "l2" top: "cat" }
layer { name: "th" type: "Threshold" bottom: "cat" top: "th"
        threshold_param { threshold: 0.5 } }
layer { name: "dec" type: "Deconvolution" bottom: "th" top: "dec"
        convolution_param { num_output: 2 kernel_size: 2 stride: 2 } }
"""
    ppath = str(tmp_path / "tail.prototxt")
    open(ppath, "w").write(proto)
    slope = np.asarray([0.1, 0.2, 0.3, 0.4], np.float32)
    dec_w = np.random.RandomState(0).randn(4, 2, 2, 2).astype(np.float32)
    dec_b = np.asarray([0.05, -0.05], np.float32)
    data = _encode_layer("prelu", [slope]) + \
        _encode_layer("dec", [dec_w, dec_b])
    mpath = str(tmp_path / "tail.caffemodel")
    open(mpath, "wb").write(data)

    g = load_caffe(ppath, mpath, input_channels=4).evaluate()
    x = np.random.RandomState(1).randn(1, 4, 6, 6).astype(np.float32)
    out = np.asarray(g.forward(x))
    assert out.shape == (1, 2, 12, 12)

    # replicate the caffe math in numpy
    h = (1.0 + 0.5 * x) ** 2.0
    h = np.abs(h)
    h = np.where(h > 0, h, 0.5 * (np.exp(h) - 1.0))          # ELU
    h = np.where(h > 0, h, slope.reshape(1, 4, 1, 1) * h)    # PReLU
    s1, s2 = h[:, :1], h[:, 1:]
    e1 = np.exp(0.5 * s1 + 0.25)
    l2 = np.log(s2 + 8.0)
    cat = np.concatenate([e1, l2], axis=1)
    th = (cat > 0.5).astype(np.float32)
    np.testing.assert_allclose(out.sum(), _deconv_ref(th, dec_w, dec_b,
                                                      stride=2).sum(),
                               rtol=1e-4)
    np.testing.assert_allclose(out, _deconv_ref(th, dec_w, dec_b, stride=2),
                               atol=1e-4)


def _deconv_ref(x, w, b, stride):
    """Naive transposed conv, NCHW, (in, out, kh, kw) weights."""
    n, cin, hh, ww = x.shape
    _, cout, kh, kw = w.shape
    out = np.zeros((n, cout, (hh - 1) * stride + kh,
                    (ww - 1) * stride + kw), np.float32)
    for i in range(hh):
        for j in range(ww):
            patch = np.einsum("nc,cokl->nokl", x[:, :, i, j], w)
            out[:, :, i * stride:i * stride + kh,
                j * stride:j * stride + kw] += patch
    return out + b.reshape(1, -1, 1, 1)


def test_caffe_vgg16_class_model(tmp_path):
    """VGG-16 topology (13 conv + 5 pool + 3 fc, narrow channels) from a
    fixture-generated prototxt + caffemodel — the class of public model the
    r3 verdict called out. Forward shape + a loaded-weight spot check."""
    chans = [(4, 4), (4, 8), (8, 8), (8, 8), (8, 8)]  # per-block (in, out)
    convs_per_block = [2, 2, 3, 3, 3]
    lines = ["""
input: "data"
input_dim: 1
input_dim: 3
input_dim: 32
input_dim: 32
"""]
    blobs = b""
    rng = np.random.RandomState(0)
    cin = 3
    bottom = "data"
    ci = 0
    for bi, ((_, cout), reps) in enumerate(zip(chans, convs_per_block)):
        for ri in range(reps):
            name = f"conv{bi+1}_{ri+1}"
            lines.append(
                f'layer {{ name: "{name}" type: "Convolution" '
                f'bottom: "{bottom}" top: "{name}" convolution_param '
                f'{{ num_output: {cout} kernel_size: 3 pad: 1 }} }}')
            lines.append(
                f'layer {{ name: "relu{bi+1}_{ri+1}" type: "ReLU" '
                f'bottom: "{name}" top: "{name}" }}')
            w = rng.randn(cout, cin, 3, 3).astype(np.float32) * 0.2
            b = rng.randn(cout).astype(np.float32) * 0.1
            blobs += _encode_layer(name, [w, b])
            if ci == 0:
                first_w = w
            ci += 1
            bottom, cin = name, cout
        lines.append(
            f'layer {{ name: "pool{bi+1}" type: "Pooling" '
            f'bottom: "{bottom}" top: "pool{bi+1}" pooling_param '
            f'{{ pool: MAX kernel_size: 2 stride: 2 }} }}')
        bottom = f"pool{bi+1}"
    for i, nout in enumerate([32, 32, 10]):
        name = f"fc{i+6}"
        lines.append(
            f'layer {{ name: "{name}" type: "InnerProduct" '
            f'bottom: "{bottom}" top: "{name}" inner_product_param '
            f'{{ num_output: {nout} }} }}')
        if i < 2:
            lines.append(
                f'layer {{ name: "relu{name}" type: "ReLU" '
                f'bottom: "{name}" top: "{name}" }}')
        fin = cin if i == 0 else 32
        w = rng.randn(nout, fin).astype(np.float32) * 0.1
        blobs += _encode_layer(name, [w, rng.randn(nout).astype(
            np.float32) * 0.1])
        bottom, cin = name, nout
    lines.append('layer { name: "prob" type: "Softmax" bottom: "fc8" '
                 'top: "prob" }')
    ppath = str(tmp_path / "vgg.prototxt")
    open(ppath, "w").write("\n".join(lines))
    mpath = str(tmp_path / "vgg.caffemodel")
    open(mpath, "wb").write(blobs)

    g = load_caffe(ppath, mpath, input_channels=3).evaluate()
    x = np.random.RandomState(2).randn(1, 3, 32, 32).astype(np.float32)
    out = np.asarray(g.forward(x))
    assert out.shape == (1, 10)
    np.testing.assert_allclose(out.sum(), 1.0, atol=1e-5)  # softmax
    # the first conv's loaded weights are the fixture's, not random init
    conv1 = next(m for m in g.modules
                 if getattr(m, "name", "") == "conv1_1")
    idx = str(g.modules.index(conv1))
    np.testing.assert_allclose(np.asarray(g.params[idx]["weight"]),
                               first_w, atol=1e-6)


# ---------------------------------------------------------------------------
# Torch t7 long tail (r4): containers, LSTM, normalization family
# ---------------------------------------------------------------------------


def _t7_roundtrip(m, x, tmp_path, atol=1e-5):
    from bigdl_tpu.loaders.torchfile import save_torch
    m.ensure_initialized()
    m.evaluate()
    ref = np.asarray(m.forward(x))
    path = str(tmp_path / "m.t7")
    save_torch(m, path)
    m2 = load_torch(path)
    m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)), ref, atol=atol)
    return m2


@pytest.mark.parametrize("factory,shape", [
    (lambda: nn.Sequential(nn.Concat(
        2, nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.SpatialConvolution(3, 2, 3, 3, 1, 1, 1, 1))), (2, 3, 8, 8)),
    (lambda: nn.Sequential(
        nn.ConcatTable().add(nn.Linear(6, 4)).add(nn.Linear(6, 4)),
        nn.CAddTable()), (2, 6)),
    (lambda: nn.Sequential(nn.LeakyReLU(0.2), nn.Threshold(0.1, -1.0)),
     (2, 6)),
    (lambda: nn.Sequential(nn.SpatialCrossMapLRN(5, 1e-3, 0.75, 1.0),
                           nn.SpatialZeroPadding(1, 1, 1, 1)), (2, 3, 8, 8)),
    (lambda: nn.Sequential(nn.BatchNormalization(6), nn.Linear(6, 3)),
     (2, 6)),
])
def test_t7_long_tail_roundtrip(factory, shape, tmp_path):
    x = np.random.RandomState(0).randn(*shape).astype(np.float32)
    _t7_roundtrip(factory(), x, tmp_path)


def test_t7_lstm_end_to_end(tmp_path):
    """LSTM through t7 (the r3-verdict named case): save, reload, identical
    sequence outputs; weight fields use torch Linear (out, in) layout."""
    from bigdl_tpu.loaders.torchfile import save_torch, load_t7
    m = nn.Recurrent(nn.LSTM(6, 5))
    x = np.random.RandomState(1).randn(2, 7, 6).astype(np.float32)
    m2 = _t7_roundtrip(m, x, tmp_path)
    assert type(m2.cell) is nn.LSTM
    # the on-disk record is Sequencer(LSTM) with (4H, in) torch-layout mats
    obj = load_t7(str(tmp_path / "m.t7"))
    assert obj.torch_typename == "nn.Sequencer"
    lstm = obj.get("module")
    assert lstm.torch_typename == "nn.LSTM"
    assert lstm.get("i2g_weight").shape == (20, 6)
    assert lstm.get("o2g_weight").shape == (20, 5)


def test_caffe_slice_axis_ne1_with_points_clear_error(tmp_path):
    """Slice on axis != 1 with explicit slice_point: unsupported (the last
    output's extent is unknown off the channel axis) — the error must say
    so instead of a wrong slice_point-count complaint (ADVICE r4)."""
    proto = """
input: "data"
input_dim: 1
input_dim: 4
input_dim: 6
input_dim: 6
layer { name: "sl" type: "Slice" bottom: "data" top: "s1" top: "s2"
        slice_param { axis: 2 slice_point: 3 } }
"""
    ppath = str(tmp_path / "sl.prototxt")
    open(ppath, "w").write(proto)
    with pytest.raises(ValueError, match="axis != 1"):
        load_caffe(ppath, None, input_channels=4)


def test_caffe_slice_axis_ne1_fully_specified_points(tmp_path):
    """Slice on axis != 1 IS supported when slice_point gives every
    boundary (len(tops) points) — only the unknown-last-extent case errs."""
    proto = """
input: "data"
input_dim: 1
input_dim: 4
input_dim: 6
input_dim: 6
layer { name: "sl" type: "Slice" bottom: "data" top: "s1" top: "s2"
        slice_param { axis: 2 slice_point: 2 slice_point: 6 } }
layer { name: "cat" type: "Concat" bottom: "s2" bottom: "s1" top: "cat"
        concat_param { axis: 2 } }
"""
    ppath = str(tmp_path / "sl2.prototxt")
    open(ppath, "w").write(proto)
    g = load_caffe(ppath, None, input_channels=4).evaluate()
    x = np.random.RandomState(2).randn(1, 4, 6, 6).astype(np.float32)
    out = np.asarray(g.forward(x))
    assert out.shape == (1, 4, 6, 6)
    np.testing.assert_allclose(
        out, np.concatenate([x[:, :, 2:6], x[:, :, :2]], axis=2), atol=0)


def test_caffe_concat_off_axis_channel_tracking(tmp_path):
    """Concat on a non-channel axis must NOT sum channel counts — a
    following Convolution is built with the bottoms' real channel count."""
    proto = """
input: "data"
input_dim: 1
input_dim: 3
input_dim: 4
input_dim: 4
layer { name: "sl" type: "Slice" bottom: "data" top: "s1" top: "s2"
        slice_param { axis: 2 slice_point: 2 slice_point: 4 } }
layer { name: "cat" type: "Concat" bottom: "s2" bottom: "s1" top: "cat"
        concat_param { axis: 2 } }
layer { name: "conv" type: "Convolution" bottom: "cat" top: "conv"
        convolution_param { num_output: 2 kernel_size: 3 } }
"""
    ppath = str(tmp_path / "cc.prototxt")
    open(ppath, "w").write(proto)
    g = load_caffe(ppath, None, input_channels=3).evaluate()
    x = np.random.RandomState(3).randn(1, 3, 4, 4).astype(np.float32)
    assert np.asarray(g.forward(x)).shape == (1, 2, 2, 2)
