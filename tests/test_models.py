"""Model zoo forward/shape/training tests (modeled on the reference's
models/*Spec.scala)."""
import numpy as np
import jax
import pytest

from bigdl_tpu import nn
from bigdl_tpu import models
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.optim import LocalOptimizer, SGD, Adam, max_iteration, \
    Top1Accuracy


def _count_params(model):
    model.ensure_initialized()
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(model.params))


def test_lenet_param_count():
    m = models.LeNet5(10)
    # conv1 6*1*25+6, conv2 12*6*25+12, fc1 192*100+100, fc2 100*10+10
    assert _count_params(m) == (6 * 25 + 6) + (12 * 6 * 25 + 12) + \
        (192 * 100 + 100) + (100 * 10 + 10)


def test_resnet18_like_cifar_forward():
    m = models.ResNetCifar(10, depth=20)
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    out = m.forward(x)
    assert out.shape == (2, 10)


def test_resnet_param_count_matches_torch_resnet50():
    m = models.ResNet(class_num=1000, depth=50)
    n = _count_params(m)
    assert n == 25_557_032, n  # torchvision resnet50 param count


def test_ptb_model_forward():
    m = models.PTBModel(input_size=50, hidden_size=16, output_size=50,
                        num_layers=2)
    ids = np.random.randint(1, 51, size=(3, 12)).astype(np.float32)
    out = m.forward(ids)
    assert out.shape == (3, 12, 50)
    # log-probs normalize
    assert np.allclose(np.exp(np.asarray(out)).sum(-1), 1.0, atol=1e-4)


def test_simple_rnn_forward():
    m = models.SimpleRNN(20, 8, 5)
    x = np.random.randn(4, 7, 20).astype(np.float32)
    assert m.forward(x).shape == (4, 5)


def test_autoencoder_trains():
    m = models.Autoencoder(32)
    imgs, _ = mnist.load(n_synthetic=128)
    x = (imgs.astype(np.float32) / 255.0)[:, None]
    from bigdl_tpu.dataset import Sample
    samples = [Sample(x[i], x[i].reshape(-1)) for i in range(len(x))]
    ds = DataSet.array(samples)
    opt = LocalOptimizer(m, ds, nn.MSECriterion(), Adam(learningrate=1e-3),
                         max_iteration(20), batch_size=32)
    opt.optimize()
    losses = opt.optim_method.state["loss"]
    assert losses < 0.25
