"""Compile-heavy model forwards (ResNet-50, VGG, Inception) — split
from test_models.py so pytest-xdist loadfile sharding overlaps them
with the rest (each is tens of seconds of XLA compile on CPU)."""
import numpy as np
import pytest

from bigdl_tpu import models
from test_models import _count_params


@pytest.mark.slow
def test_resnet50_forward_tiny():
    m = models.ResNet(class_num=100, depth=50)
    x = np.random.randn(1, 3, 64, 64).astype(np.float32)  # small spatial
    m.evaluate()
    out = m.forward(x)
    assert out.shape == (1, 100)
    # ~25.5M params for class_num=1000; with 100 classes slightly fewer
    n = _count_params(m)
    assert 23_000_000 < n < 26_000_000, n


@pytest.mark.slow
def test_vgg_cifar_forward():
    m = models.VggForCifar10(10)
    m.evaluate()
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    assert m.forward(x).shape == (2, 10)


@pytest.mark.slow
def test_inception_v1_forward():
    m = models.Inception_v1(1000)
    m.evaluate()
    x = np.random.randn(1, 3, 224, 224).astype(np.float32)
    out = m.forward(x)
    assert out.shape == (1, 1000)


