"""Transformer-family model tests — split from test_models.py for
xdist loadfile balance."""
import numpy as np
import jax
import pytest

from bigdl_tpu import nn
from bigdl_tpu import models
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.optim import LocalOptimizer, SGD, Adam, max_iteration, \
    max_epoch
from test_models import _count_params



def test_transformer_lm_forward_and_train():
    m = models.TransformerLM(vocab_size=60, hidden_size=32, num_heads=4,
                             filter_size=64, num_layers=2)
    ids = np.random.randint(1, 60, size=(2, 16))
    out = m.forward(ids.astype(np.float32))
    assert out.shape == (2, 16, 60)

    # next-token training decreases loss
    from bigdl_tpu.dataset import Sample
    rng = np.random.RandomState(0)
    seqs = rng.randint(1, 59, size=(64, 17))
    seqs[:, 1::2] = seqs[:, 0:-1:2]  # learnable copy structure
    samples = [Sample(seqs[i, :-1].astype(np.float32),
                      seqs[i, 1:].astype(np.float32)) for i in range(64)]
    ds = DataSet.array(samples)
    crit = nn.TimeDistributedMaskCriterion(
        nn.CrossEntropyCriterion(), padding_value=0)
    opt = LocalOptimizer(m, ds, crit, Adam(learningrate=3e-3),
                         max_iteration(2), batch_size=32)
    opt.optimize()
    first = opt.optim_method.state["loss"]
    opt2 = LocalOptimizer(m, ds, crit, Adam(learningrate=3e-3),
                          max_iteration(25), batch_size=32)
    opt2.optimize()
    assert opt2.optim_method.state["loss"] < first


def test_transformer_translation_mode():
    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.utils.table import Table
    m = Transformer(vocab_size=40, hidden_size=16, num_heads=2,
                    filter_size=32, num_hidden_layers=1, mode="translation")
    src = np.random.randint(1, 40, size=(2, 10)).astype(np.float32)
    tgt = np.random.randint(1, 40, size=(2, 8)).astype(np.float32)
    out = m.forward(Table(src, tgt))
    assert out.shape == (2, 8, 40)


def test_moe_transformer_lm_trains():
    """Switch-MoE LM: forward shape, aux loss present, short training
    (lm loss + aux) decreases, gradients flow into expert weights."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.models import MoETransformerLM
    from bigdl_tpu.nn import CrossEntropyCriterion, TimeDistributedMaskCriterion
    from bigdl_tpu.optim import SGD

    model = MoETransformerLM(vocab_size=64, hidden_size=32, num_heads=4,
                             filter_size=64, num_layers=2, n_experts=4,
                             moe_every=2, max_len=16)
    params, st = model.init(jax.random.PRNGKey(0))
    crit = TimeDistributedMaskCriterion(CrossEntropyCriterion(),
                                        padding_value=0)
    optim = SGD(learningrate=0.5, momentum=0.9)
    opt_state = optim.init_state(params)
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 63, size=(8, 13)).astype(np.float32)
    x, y = jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])

    (out, new_st) = model.apply(params, st, x, training=False)[0:2]
    assert out.shape == (8, 12, 64)
    assert "aux_loss" in new_st and np.isfinite(float(new_st["aux_loss"]))

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits, stt = model.apply(p, st, x, training=True,
                                      rng=jax.random.PRNGKey(1))
            return (crit._forward(logits, y)
                    + 0.01 * stt["aux_loss"]), stt
        (l, stt), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        p2, o2 = optim.update(g, params, opt_state, jnp.float32(0.5))
        gmoe = g["block1"]["ffn"]["w1"]
        return l, p2, o2, jnp.abs(gmoe).max()

    first = None
    for i in range(25):
        l, params, opt_state, gmax = step(params, opt_state)
        if i == 0:
            first = float(l)
            assert float(gmax) > 0, "no gradient reached expert weights"
    assert float(l) < first, (first, float(l))


def test_ffn_activations_and_swiglu_lm():
    """FFN activation options: gelu/swiglu match hand-computed forms, and
    a SwiGLU+RoPE+GQA LM trains and decodes consistently."""
    import jax.numpy as jnp
    from bigdl_tpu.nn.attention import FeedForwardNetwork
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 5, 8).astype(np.float32))
    for act in ("relu", "gelu", "swiglu"):
        ffn = FeedForwardNetwork(8, 16, activation=act)
        p, _ = ffn.init(jax.random.PRNGKey(1))
        out, _ = ffn.apply(p, {}, x, training=False)
        h = np.asarray(x) @ np.asarray(p["w1"]) + np.asarray(p["b1"])
        if act == "swiglu":
            gate = np.asarray(jax.nn.silu(jnp.asarray(h)))
            ref = (gate * (np.asarray(x) @ np.asarray(p["w3"])))
            assert "w3" in p
        elif act == "gelu":
            ref = np.asarray(jax.nn.gelu(jnp.asarray(h)))
            assert "w3" not in p
        else:
            ref = np.maximum(h, 0)
        ref = ref @ np.asarray(p["w2"]) + np.asarray(p["b2"])
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    from bigdl_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=33, hidden_size=16, num_heads=4,
                      filter_size=32, num_layers=1, max_len=24,
                      use_flash=False, pos_encoding="rope",
                      num_kv_heads=2, ffn_activation="swiglu")
    params, _ = m.init(jax.random.PRNGKey(2))
    prompt = np.array([[3, 5]], np.int32)
    out = m.generate(params, prompt, max_new_tokens=4)
    ids = prompt.copy()
    for _ in range(4):
        logits, _ = m.apply(params, {}, jnp.asarray(ids.astype(np.float32)),
                            training=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), ids)
