"""True multi-process (multi-controller) distributed tests.

The rest of the suite emulates N devices inside ONE process; the reference's
distributed substrate, however, is genuinely multi-node (Spark executors +
BlockManager). This test spawns TWO separate JAX processes that rendezvous
through ``jax.distributed.initialize`` (gRPC coordinator — the DCN analog),
each owning 4 virtual CPU devices of an 8-device global mesh, and checks:

  * process_allgather sees every process (failure-detection heartbeat path)
  * a shard_mapped psum over the GLOBAL mesh reduces across process
    boundaries (the cross-host gradient all-reduce of DistriOptimizer)
  * make_hybrid_mesh builds the DCN x ICI mesh in a real multi-process
    topology (process_is_granule path)

Skipped automatically if the coordinator cannot bind (sandboxes without
localhost sockets).
"""
import os
import subprocess
import sys

import pytest

from multihost_util import _DRIVER, _free_port, skip_if_backend_unsupported


@pytest.mark.parametrize("n", [2])
def test_multi_process_distributed(n):
    try:
        port = _free_port()
    except OSError:
        pytest.skip("no localhost sockets in this sandbox")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # driver sets its own device count
    # strip the axon TPU plugin registration: a multi-process CPU
    # rendezvous must never claim the real chip (cf. bench.py _cpu_env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _DRIVER, str(pid), str(n), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n)]
    outs = []
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        outs.append((pid, proc.returncode, out, err))
    skip_if_backend_unsupported(outs)
    for pid, rc, out, err in outs:
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"MULTIHOST_OK_{pid}" in out
