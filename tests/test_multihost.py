"""True multi-process (multi-controller) distributed tests.

The rest of the suite emulates N devices inside ONE process; the reference's
distributed substrate, however, is genuinely multi-node (Spark executors +
BlockManager). This test spawns TWO separate JAX processes that rendezvous
through ``jax.distributed.initialize`` (gRPC coordinator — the DCN analog),
each owning 4 virtual CPU devices of an 8-device global mesh, and checks:

  * process_allgather sees every process (failure-detection heartbeat path)
  * a shard_mapped psum over the GLOBAL mesh reduces across process
    boundaries (the cross-host gradient all-reduce of DistriOptimizer)
  * make_hybrid_mesh builds the DCN x ICI mesh in a real multi-process
    topology (process_is_granule path)

Skipped automatically if the coordinator cannot bind (sandboxes without
localhost sockets).
"""
import os
import socket
import subprocess
import sys

import pytest

_DRIVER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

assert len(jax.devices()) == 4 * n, jax.devices()
assert len(jax.local_devices()) == 4

# 1) coordinator-level allgather (heartbeat path)
seen = multihost_utils.process_allgather(jnp.asarray([float(pid)]))
assert sorted(np.asarray(seen).reshape(-1).tolist()) == [float(i) for i in
                                                         range(n)], seen

# 2) cross-process psum over the global mesh
mesh = Mesh(np.array(jax.devices()), ("data",))
sharding = NamedSharding(mesh, P("data"))
local = np.full((4 * n // n,), float(pid + 1), np.float32)  # 4 per process
garr = jax.make_array_from_process_local_data(sharding, local)
out = jax.jit(shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                        in_specs=P("data"), out_specs=P()),
              out_shardings=NamedSharding(mesh, P()))(garr)
# psum of per-device values: 4 devices carrying 1.0 + 4 carrying 2.0 = 12
total = float(np.asarray(jax.device_get(
    out.addressable_shards[0].data)).reshape(-1)[0])
assert total == 12.0, total

# 3) hybrid DCN x ICI mesh in a real 2-process topology
from bigdl_tpu.parallel.mesh import make_hybrid_mesh
hmesh = make_hybrid_mesh(ici_shape=(1, 4), dcn_shape=(n, 1),
                         axes=("data", "model"))
assert hmesh.devices.shape == (n, 4)
# the ICI (model) axis must stay inside one process
for row in hmesh.devices:
    assert len({d.process_index for d in row}) == 1, hmesh.devices

# 4) full DistriOptimizer training across processes: each process feeds its
# LOCAL data split (the reference's per-partition reads); gradients psum
# over the global 'data' axis spanning both processes
from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import DistriOptimizer, SGD, MaxIteration
from bigdl_tpu.dataset import DataSet, mnist

dmesh = Mesh(np.array(jax.devices()), ("data",))
imgs, labels = mnist.load(n_synthetic=64)
# per-process split: each controller feeds a DIFFERENT half of the data
imgs, labels = imgs[pid * 32:(pid + 1) * 32], labels[pid * 32:(pid + 1) * 32]
ds = DataSet.array(mnist.to_samples(imgs, labels))
opt = DistriOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                      SGD(learningrate=0.01), MaxIteration(2),
                      batch_size=8, mesh=dmesh)
opt.optimize()
loss = float(opt.optim_method.state["loss"])
assert np.isfinite(loss), loss
# every process must agree on the replicated loss/params
agreed = multihost_utils.process_allgather(jnp.asarray([loss]))
assert np.allclose(np.asarray(agreed).reshape(-1), loss), agreed

# 5) ZeRO-1 sharded-optimizer variant over the same 2-process mesh
ds2 = DataSet.array(mnist.to_samples(imgs, labels))
opt2 = DistriOptimizer(LeNet5(10), ds2, nn.ClassNLLCriterion(),
                       SGD(learningrate=0.01), MaxIteration(2),
                       batch_size=8, mesh=dmesh,
                       parameter_mode="zero1", compress="bf16")
opt2.optimize()
assert np.isfinite(float(opt2.optim_method.state["loss"]))

print(f"MULTIHOST_OK_{pid}")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed():
    try:
        port = _free_port()
    except OSError:
        pytest.skip("no localhost sockets in this sandbox")
    n = 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # driver sets its own device count
    # strip the axon TPU plugin registration: a multi-process CPU
    # rendezvous must never claim the real chip (cf. bench.py _cpu_env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _DRIVER, str(pid), str(n), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n)]
    outs = []
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        outs.append((pid, proc.returncode, out, err))
    for pid, rc, out, err in outs:
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"MULTIHOST_OK_{pid}" in out
