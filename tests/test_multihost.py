"""True multi-process (multi-controller) distributed tests.

The rest of the suite emulates N devices inside ONE process; the reference's
distributed substrate, however, is genuinely multi-node (Spark executors +
BlockManager). This test spawns TWO separate JAX processes that rendezvous
through ``jax.distributed.initialize`` (gRPC coordinator — the DCN analog),
each owning 4 virtual CPU devices of an 8-device global mesh, and checks:

  * process_allgather sees every process (failure-detection heartbeat path)
  * a shard_mapped psum over the GLOBAL mesh reduces across process
    boundaries (the cross-host gradient all-reduce of DistriOptimizer)
  * make_hybrid_mesh builds the DCN x ICI mesh in a real multi-process
    topology (process_is_granule path)

Skipped automatically if the coordinator cannot bind (sandboxes without
localhost sockets).
"""
import os
import socket
import subprocess
import sys

import pytest

_DRIVER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
dp = 8 // n  # devices per process: 8-device global mesh regardless of n
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={dp}"
import jax
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == dp

# 1) coordinator-level allgather (heartbeat path)
seen = multihost_utils.process_allgather(jnp.asarray([float(pid)]))
assert sorted(np.asarray(seen).reshape(-1).tolist()) == [float(i) for i in
                                                         range(n)], seen

# 2) cross-process psum over the global mesh
mesh = Mesh(np.array(jax.devices()), ("data",))
sharding = NamedSharding(mesh, P("data"))
local = np.full((dp,), float(pid + 1), np.float32)  # dp per process
garr = jax.make_array_from_process_local_data(sharding, local)
out = jax.jit(shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                        in_specs=P("data"), out_specs=P()),
              out_shardings=NamedSharding(mesh, P()))(garr)
# psum of per-device values: dp devices carry (pid+1) for each pid
expect = float(sum((i + 1) * dp for i in range(n)))
total = float(np.asarray(jax.device_get(
    out.addressable_shards[0].data)).reshape(-1)[0])
assert total == expect, (total, expect)

# 3) hybrid DCN x ICI mesh in a real 2-process topology
from bigdl_tpu.parallel.mesh import make_hybrid_mesh
hmesh = make_hybrid_mesh(ici_shape=(1, dp), dcn_shape=(n, 1),
                         axes=("data", "model"))
assert hmesh.devices.shape == (n, dp)
# the ICI (model) axis must stay inside one process
for row in hmesh.devices:
    assert len({d.process_index for d in row}) == 1, hmesh.devices

# 4) full DistriOptimizer training across processes: each process feeds its
# LOCAL data split (the reference's per-partition reads); gradients psum
# over the global 'data' axis spanning both processes
from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import DistriOptimizer, SGD, MaxIteration
from bigdl_tpu.dataset import DataSet, mnist

dmesh = Mesh(np.array(jax.devices()), ("data",))
imgs, labels = mnist.load(n_synthetic=64)
# per-process split: each controller feeds a DIFFERENT slice of the data
per = 64 // n
imgs = imgs[pid * per:(pid + 1) * per]
labels = labels[pid * per:(pid + 1) * per]
ds = DataSet.array(mnist.to_samples(imgs, labels))
opt = DistriOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                      SGD(learningrate=0.01), MaxIteration(2),
                      batch_size=8, mesh=dmesh)
opt.optimize()
loss = float(opt.optim_method.state["loss"])
assert np.isfinite(loss), loss
# every process must agree on the replicated loss/params
agreed = multihost_utils.process_allgather(jnp.asarray([loss]))
assert np.allclose(np.asarray(agreed).reshape(-1), loss), agreed

# 5) ZeRO-1 sharded-optimizer variant over the same 2-process mesh
ds2 = DataSet.array(mnist.to_samples(imgs, labels))
opt2 = DistriOptimizer(LeNet5(10), ds2, nn.ClassNLLCriterion(),
                       SGD(learningrate=0.01), MaxIteration(2),
                       batch_size=8, mesh=dmesh,
                       parameter_mode="zero1", compress="bf16")
opt2.optimize()
assert np.isfinite(float(opt2.optim_method.state["loss"]))

print(f"MULTIHOST_OK_{pid}")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("n", [2, 4])
def test_multi_process_distributed(n):
    try:
        port = _free_port()
    except OSError:
        pytest.skip("no localhost sockets in this sandbox")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # driver sets its own device count
    # strip the axon TPU plugin registration: a multi-process CPU
    # rendezvous must never claim the real chip (cf. bench.py _cpu_env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _DRIVER, str(pid), str(n), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n)]
    outs = []
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        outs.append((pid, proc.returncode, out, err))
    for pid, rc, out, err in outs:
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"MULTIHOST_OK_{pid}" in out


_FAILURE_DRIVER = r"""
import os, sys, time
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
# heartbeat_timeout_seconds: keep the coordination service's OWN failure
# escalation (error-poll -> fatal process termination) out of the test
# window — detection must come from Heartbeat.beat's watchdog, and the
# service's async fatal would otherwise race it under heavy CI load
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=pid,
                           heartbeat_timeout_seconds=600)
from bigdl_tpu.parallel.failure import Heartbeat, HeartbeatLost

hb = Heartbeat()
for i in range(100):
    if pid == n - 1 and i == 2:
        # simulated host death: no shutdown handshake, no exit notice —
        # the peers' next heartbeat exchange must detect it
        os._exit(0)
    try:
        stale = hb.beat(timeout_s=20.0)
    except HeartbeatLost as e:
        # detection -> clean halt (the real loop would checkpoint here).
        # os._exit, not sys.exit: atexit would run jax.distributed.shutdown,
        # whose shutdown barrier can never complete with a dead peer — the
        # distributed channel is already lost, leave without the handshake
        print(f"DETECTED_{pid}: {e}", flush=True)
        os._exit(0)
    time.sleep(0.2)
raise SystemExit(f"process {pid} never detected the dead peer")
"""


def test_heartbeat_detects_killed_process():
    """Failure injection (VERDICT r2 #8): one of 4 processes dies without
    ceremony mid-run; every survivor's next Heartbeat.beat(timeout_s=...)
    raises HeartbeatLost and the process halts cleanly (rc 0) instead of
    stalling in the collective forever. Reference analog: Spark task-failure
    detection feeding DistriOptimizer's retry (optim/DistriOptimizer.scala)."""
    try:
        port = _free_port()
    except OSError:
        pytest.skip("no localhost sockets in this sandbox")
    n = 4
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _FAILURE_DRIVER, str(pid), str(n), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n)]
    outs = []
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        outs.append((pid, proc.returncode, out, err))
    for pid, rc, out, err in outs:
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        if pid < n - 1:  # survivors must have DETECTED the death
            assert f"DETECTED_{pid}" in out, \
                f"process {pid} did not detect the dead peer:\n{out}\n{err[-1500:]}"
