"""4-process variant of the multi-controller test — its own file so
pytest-xdist loadfile sharding runs it in parallel with the 2-process one
(the suite's wall time is the slowest FILE)."""


def test_four_process_distributed():
    from test_multihost import test_multi_process_distributed
    test_multi_process_distributed(4)
