"""Heartbeat failure-injection test — own file for loadfile sharding
(see tests/test_multihost.py for the 2-process rendezvous basics)."""
import os
import subprocess
import sys

import pytest

from multihost_util import _free_port


_FAILURE_DRIVER = r"""
import os, sys, time
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
# heartbeat_timeout_seconds: keep the coordination service's OWN failure
# escalation (error-poll -> fatal process termination) out of the test
# window — detection must come from Heartbeat.beat's watchdog, and the
# service's async fatal would otherwise race it under heavy CI load
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=pid,
                           heartbeat_timeout_seconds=600)
from bigdl_tpu.parallel.failure import Heartbeat, HeartbeatLost

hb = Heartbeat()
for i in range(100):
    if pid == n - 1 and i == 2:
        # simulated host death: no shutdown handshake, no exit notice —
        # the peers' next heartbeat exchange must detect it
        os._exit(0)
    try:
        stale = hb.beat(timeout_s=60.0)
    except HeartbeatLost as e:
        # detection -> clean halt (the real loop would checkpoint here).
        # os._exit, not sys.exit: atexit would run jax.distributed.shutdown,
        # whose shutdown barrier can never complete with a dead peer — the
        # distributed channel is already lost, leave without the handshake
        print(f"DETECTED_{pid}: {e}", flush=True)
        os._exit(0)
    time.sleep(0.2)
raise SystemExit(f"process {pid} never detected the dead peer")
"""


def test_heartbeat_detects_killed_process():
    """Failure injection (VERDICT r2 #8): one of 4 processes dies without
    ceremony mid-run; every survivor's next Heartbeat.beat(timeout_s=...)
    raises HeartbeatLost and the process halts cleanly (rc 0) instead of
    stalling in the collective forever. Reference analog: Spark task-failure
    detection feeding DistriOptimizer's retry (optim/DistriOptimizer.scala)."""
    try:
        port = _free_port()
    except OSError:
        pytest.skip("no localhost sockets in this sandbox")
    n = 4
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _FAILURE_DRIVER, str(pid), str(n), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n)]
    outs = []
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        outs.append((pid, proc.returncode, out, err))
    for pid, rc, out, err in outs:
        if pid < n - 1:
            # every survivor must DETECT and initiate the clean halt.
            # rc is asserted only for survivors that did NOT print the
            # marker: after detection, the FIRST exiting survivor tears
            # down the gRPC coordination service it hosts, and the jax
            # runtime's async error-poll can fatally terminate slower
            # survivors in the instants between their detection printout
            # and process exit — that post-detection race is runtime
            # noise, not a detection failure
            assert f"DETECTED_{pid}" in out, \
                f"process {pid} did not detect the dead peer " \
                f"(rc={rc}):\n{out}\n{err[-1500:]}"
        else:
            assert rc == 0, f"killed-process stand-in exited {rc}"
