"""Heartbeat failure-injection test — own file for loadfile sharding
(see tests/test_multihost.py for the 2-process rendezvous basics)."""
import os
import subprocess
import sys

import pytest

from multihost_util import _free_port


_FAILURE_DRIVER = r"""
import os, sys, time
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
# heartbeat_timeout_seconds: keep the coordination service's OWN failure
# escalation (error-poll -> fatal process termination) out of the test
# window — detection must come from Heartbeat.beat's watchdog, and the
# service's async fatal would otherwise race it under heavy CI load
try:
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                               process_id=pid,
                               heartbeat_timeout_seconds=600)
except TypeError:
    # older jax: no heartbeat_timeout_seconds kwarg — accept the default
    # escalation window (detection still must come from Heartbeat.beat)
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                               process_id=pid)
from bigdl_tpu.parallel.failure import Heartbeat, HeartbeatLost

hb = Heartbeat()
print(f"READY_{pid}", flush=True)   # rendezvous done, loop entered: the
# harness uses this to tell detection hangs from scheduling starvation
for i in range(100):
    if pid == n - 1 and i == 2:
        # simulated host death: no shutdown handshake, no exit notice —
        # the peers' next heartbeat exchange must detect it
        os._exit(0)
    try:
        stale = hb.beat(timeout_s=60.0)
    except HeartbeatLost as e:
        # detection -> clean halt (the real loop would checkpoint here).
        # os._exit, not sys.exit: atexit would run jax.distributed.shutdown,
        # whose shutdown barrier can never complete with a dead peer — the
        # distributed channel is already lost, leave without the handshake
        print(f"DETECTED_{pid}: {e}", flush=True)
        os._exit(0)
    time.sleep(0.2)
raise SystemExit(f"process {pid} never detected the dead peer")
"""


def _run_failure_injection(n):
    """One 4-process run; returns the (pid, rc, out, err) list or None on
    harness-level starvation (rendezvous/communicate timeout — on a
    saturated 1-core CI box the processes may simply never get scheduled;
    that is box noise, not a detection failure)."""
    try:
        port = _free_port()
    except OSError:
        import pytest as _pytest
        _pytest.skip("no localhost sockets in this sandbox")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    try:
        for pid in range(n):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _FAILURE_DRIVER, str(pid), str(n),
                 str(port)], env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
    except OSError:
        for p2 in procs:       # spawn failed mid-way: reap the spawned
            p2.kill()
            p2.wait()
        raise RuntimeError(f"could not spawn {n} driver processes")
    # LOAD-SCALED budget: 420s covers 4 jax.distributed processes on a
    # quiet 1.5-core box, but the same work under an oversubscribed
    # scheduler (tier-1 sharing the box with a build) legitimately takes
    # longer — scale the wait by runnable-tasks-per-core, capped at 2x,
    # so a busy box stops failing a test that passes isolated
    try:
        _load = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
    except OSError:
        _load = 0.0
    budget = 420 * min(max(_load, 1.0), 2.0)
    outs = []
    timed_out = False
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=budget)
        except subprocess.TimeoutExpired:
            timed_out = True
            break
        outs.append((pid, proc.returncode, out, err))
    if timed_out:
        # kill AND reap every child (zombies + open pipe fds would pile
        # onto an already-starved box before the retry), keeping their
        # partial stdout: READY markers discriminate a detection HANG
        # (rendezvous done, beat never raised — a product bug, fail loud)
        # from scheduling starvation (never rendezvoused — box noise)
        outs = []
        for pid, proc in enumerate(procs):
            proc.kill()
            try:
                out, err = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                out, err = "", ""
            outs.append((pid, proc.returncode, out, err))
        reaped = [(p, out) for p, _, out, _ in outs if out != ""]
        ready = sum(1 for p, out in reaped if f"READY_{p}" in out)
        # judge the hang on the evidence we HAVE: if every child whose
        # stdout we recovered had rendezvoused, this is a detection hang,
        # not starvation (a lost stdout must not reclassify it)
        if reaped and ready == len(reaped):
            pytest.fail(
                "all processes rendezvoused but none finished within the "
                "budget — Heartbeat.beat hang (detection regression), "
                f"outs: {[(p, o[-200:]) for p, _, o, _ in outs]}")
        return None
    return outs


def test_heartbeat_detects_killed_process():
    """Failure injection (VERDICT r2 #8): one of 4 processes dies without
    ceremony mid-run; every survivor's next Heartbeat.beat(timeout_s=...)
    raises HeartbeatLost and the process halts cleanly (rc 0) instead of
    stalling in the collective forever. Reference analog: Spark task-failure
    detection feeding DistriOptimizer's retry (optim/DistriOptimizer.scala).

    One retry on harness starvation: under a loaded 1-core xdist run the
    4 jax.distributed subprocesses can miss every scheduling window; the
    DETECTION assertions themselves are never retried-away (a run that
    completes but fails them fails the test immediately)."""
    n = 4
    outs = _run_failure_injection(n)
    if outs is None:
        outs = _run_failure_injection(n)
    if outs is None:
        pytest.skip("box too loaded to schedule 4 jax.distributed "
                    "processes twice (rendezvous starvation)")
    from multihost_util import skip_if_backend_unsupported
    skip_if_backend_unsupported(outs)
    # Invariants (the first detector's exit tears down the gRPC
    # coordination service it participates in, and the jax runtime's
    # async error-poll can then fatally terminate the OTHER survivors
    # before their own beat() raises — so "every survivor detects" is
    # stronger than the runtime guarantees):
    #   1. at least one survivor DETECTS and halts cleanly — the event
    #      that triggers the cluster-wide halt in the real loop;
    #   2. every process TERMINATED within the budget (communicate()
    #      returned) — nobody stalls in the collective forever;
    #   3. every survivor either detected or was torn down AFTER the
    #      detection existed (rc != 0 runtime fatal), never a silent
    #      clean exit without detection.
    survivors = [o for o in outs if o[0] < n - 1]
    detected = [o for o in survivors if f"DETECTED_{o[0]}" in o[2]]
    assert detected, "no survivor detected the dead peer:\n" + "\n".join(
        f"pid {p} rc={rc}: {out}\n{err[-800:]}"
        for p, rc, out, err in survivors)
    for pid, rc, out, err in survivors:
        if f"DETECTED_{pid}" not in out:
            assert rc != 0, \
                f"survivor {pid} exited cleanly WITHOUT detecting " \
                f"(rc=0):\n{out}\n{err[-800:]}"
    assert outs[n - 1][1] == 0, \
        f"killed-process stand-in exited {outs[n - 1][1]}"
