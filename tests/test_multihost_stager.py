"""Multihost stager coverage (ROADMAP open item #2): the BatchStager's
per-process lookahead + the ``_check_split_agreement`` guard, exercised
under (a) a mocked multi-process mesh for the uneven-split failure path
and (b) a REAL 2-process ``jax.distributed`` rendezvous training with
prefetch and superstep groups on per-process data splits.

Separate file from test_multihost*.py so pytest-xdist loadfile sharding
overlaps the subprocess rendezvous with other workers."""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import DistriOptimizer, SGD, MaxIteration
from bigdl_tpu.utils import engine

from multihost_util import _free_port, skip_if_backend_unsupported


def test_uneven_split_agreement_raises(monkeypatch):
    """Per-process batch counts that disagree must fail loudly at setup
    (the extra steps on the larger split would deadlock in the
    cross-process psum) — simulated 2-process mesh: this process reports
    4 batches/epoch, the allgather claims the peer reports 3."""
    from jax.sharding import Mesh
    from bigdl_tpu.parallel import sharding
    from jax.experimental import multihost_utils

    engine.set_seed(1)
    imgs, labels = mnist.load(n_synthetic=64)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    opt = DistriOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                          SGD(learningrate=0.01), MaxIteration(1),
                          batch_size=16, mesh=mesh)
    monkeypatch.setattr(sharding, "is_multi_process", lambda m: True)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: np.asarray([[4], [3]], np.int32))
    with pytest.raises(ValueError, match="disagree on batches/epoch"):
        opt._check_split_agreement()


def test_even_split_agreement_passes(monkeypatch):
    """Matching per-process counts pass the guard (the mocked allgather
    echoes this process's count for both peers)."""
    from jax.sharding import Mesh
    from bigdl_tpu.parallel import sharding
    from jax.experimental import multihost_utils

    engine.set_seed(1)
    imgs, labels = mnist.load(n_synthetic=64)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    opt = DistriOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                          SGD(learningrate=0.01), MaxIteration(1),
                          batch_size=16, mesh=mesh)
    n = opt._batched().batches_per_epoch()
    monkeypatch.setattr(sharding, "is_multi_process", lambda m: True)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: np.asarray([[n], [n]], np.int32))
    opt._check_split_agreement()  # no raise


_STAGER_DRIVER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
dp = 8 // n
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={dp}"
import jax
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n,
                           process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.experimental import multihost_utils

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import DistriOptimizer, SGD, MaxIteration
from bigdl_tpu.optim.staging import stager_threads_alive

mesh = Mesh(np.array(jax.devices()), ("data",))
imgs, labels = mnist.load(n_synthetic=64)
per = 64 // n   # each controller feeds a DIFFERENT slice of the data
imgs = imgs[pid * per:(pid + 1) * per]
labels = labels[pid * per:(pid + 1) * per]

# (a) per-process lookahead stager feeding cross-process training
ds = DataSet.array(mnist.to_samples(imgs, labels))
opt = DistriOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                      SGD(learningrate=0.01), MaxIteration(3),
                      batch_size=8, mesh=mesh)
opt.set_prefetch(3)
opt.optimize()
loss = float(opt.optim_method.state["loss"])
assert np.isfinite(loss), loss
agreed = multihost_utils.process_allgather(jnp.asarray([loss]))
assert np.allclose(np.asarray(agreed).reshape(-1), loss), agreed
assert stager_threads_alive() == 0

# (b) superstep groups over the same per-process splits: the stacking
# stage runs on each process's stager thread; the scanned program psums
# across the process boundary every microstep
ds2 = DataSet.array(mnist.to_samples(imgs, labels))
opt2 = DistriOptimizer(LeNet5(10), ds2, nn.ClassNLLCriterion(),
                       SGD(learningrate=0.01), MaxIteration(4),
                       batch_size=8, mesh=mesh)
opt2.set_prefetch(3).set_superstep(2)
opt2.optimize()
loss2 = float(opt2.optim_method.state["loss"])
assert np.isfinite(loss2), loss2
assert opt2.optim_method.state["neval"] == 4
agreed2 = multihost_utils.process_allgather(jnp.asarray([loss2]))
assert np.allclose(np.asarray(agreed2).reshape(-1), loss2), agreed2
assert stager_threads_alive() == 0

print(f"MULTIHOST_STAGER_OK_{pid}")
"""


@pytest.mark.parametrize("n", [2])
def test_multi_process_stager_and_superstep(n):
    try:
        port = _free_port()
    except OSError:
        pytest.skip("no localhost sockets in this sandbox")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # driver sets its own device count
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _STAGER_DRIVER, str(pid), str(n), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n)]
    outs = []
    for pid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        outs.append((pid, proc.returncode, out, err))
    skip_if_backend_unsupported(outs)
    for pid, rc, out, err in outs:
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"MULTIHOST_STAGER_OK_{pid}" in out
