"""Native C++ prefetcher tests."""
import numpy as np
import pytest

from bigdl_tpu import native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")


def test_prefetcher_batches_match_python():
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, size=(50, 3, 8, 8)).astype(np.uint8)
    labels = rng.randint(1, 11, size=(50,)).astype(np.int64)
    mean, std = [10.0, 20.0, 30.0], [2.0, 3.0, 4.0]
    pf = native.NativePrefetcher(imgs, labels, mean, std, batch_size=16,
                                 n_workers=2)
    batches = list(pf.data(train=False))
    assert sum(b.size() for b in batches) == 50
    # deterministic order for train=False: reconstruct and compare
    x0 = batches[0].get_input()
    ref = (imgs[:16].astype(np.float32) -
           np.asarray(mean, np.float32)[:, None, None]) / \
        np.asarray(std, np.float32)[:, None, None]
    assert np.allclose(x0, ref, atol=1e-5)
    assert np.allclose(batches[0].get_target(), labels[:16])


def test_prefetcher_shuffled_epoch_covers_all():
    imgs = np.arange(40, dtype=np.uint8).reshape(40, 1, 1, 1)
    labels = np.arange(1, 41, dtype=np.int64)
    pf = native.NativePrefetcher(imgs, labels, [0.0], [1.0], batch_size=8)
    seen = []
    for b in pf.data(train=True):
        seen.extend(np.asarray(b.get_target()).astype(int).tolist())
    assert sorted(seen) == list(range(1, 41))


def test_prefetcher_trains_lenet():
    from bigdl_tpu import nn
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
    from bigdl_tpu.dataset import mnist
    imgs, labels = mnist.load(n_synthetic=256)
    pf = native.NativePrefetcher(imgs[:, None], labels,
                                 [mnist.TRAIN_MEAN], [mnist.TRAIN_STD],
                                 batch_size=64)
    opt = LocalOptimizer(LeNet5(10), pf, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05), max_iteration(8), 64)
    opt.optimize()
    assert opt.optim_method.state["loss"] < 2.5
