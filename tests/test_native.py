"""Native C++ prefetcher tests."""
import numpy as np
import pytest

from bigdl_tpu import native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")


def test_prefetcher_batches_match_python():
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, size=(50, 3, 8, 8)).astype(np.uint8)
    labels = rng.randint(1, 11, size=(50,)).astype(np.int64)
    mean, std = [10.0, 20.0, 30.0], [2.0, 3.0, 4.0]
    pf = native.NativePrefetcher(imgs, labels, mean, std, batch_size=16,
                                 n_workers=2)
    batches = list(pf.data(train=False))
    assert sum(b.size() for b in batches) == 50
    # deterministic order for train=False: reconstruct and compare
    x0 = batches[0].get_input()
    ref = (imgs[:16].astype(np.float32) -
           np.asarray(mean, np.float32)[:, None, None]) / \
        np.asarray(std, np.float32)[:, None, None]
    assert np.allclose(x0, ref, atol=1e-5)
    assert np.allclose(batches[0].get_target(), labels[:16])


def test_prefetcher_shuffled_epoch_covers_all():
    imgs = np.arange(40, dtype=np.uint8).reshape(40, 1, 1, 1)
    labels = np.arange(1, 41, dtype=np.int64)
    pf = native.NativePrefetcher(imgs, labels, [0.0], [1.0], batch_size=8)
    seen = []
    for b in pf.data(train=True):
        seen.extend(np.asarray(b.get_target()).astype(int).tolist())
    assert sorted(seen) == list(range(1, 41))


def test_prefetcher_looped_epochs_cover_all_without_restart():
    """loop_epochs=k yields k full (independently permuted) epochs from ONE
    worker run — the no-queue-refill-stall path the realdata bench uses."""
    imgs = np.arange(40, dtype=np.uint8).reshape(40, 1, 1, 1)
    labels = np.arange(1, 41, dtype=np.int64)
    pf = native.NativePrefetcher(imgs, labels, [0.0], [1.0], batch_size=8)
    seen = []
    for b in pf.data(train=True, loop_epochs=3):
        seen.extend(np.asarray(b.get_target()).astype(int).tolist())
    assert len(seen) == 120
    # every epoch's worth of labels appears exactly 3 times overall
    assert sorted(seen) == sorted(list(range(1, 41)) * 3)
    # non-divisible n: each epoch drops its partial batch so no minibatch
    # spans an epoch boundary (which could repeat a sample within a batch)
    pf2 = native.NativePrefetcher(imgs, labels, [0.0], [1.0], batch_size=16)
    batches = [np.asarray(b.get_target()).astype(int)
               for b in pf2.data(train=True, loop_epochs=2)]
    assert [len(b) for b in batches] == [16, 16, 16, 16]  # 2 * (40 // 16)
    for b in batches:
        assert len(set(b.tolist())) == len(b), "duplicate sample in batch"


def test_prefetcher_trains_lenet():
    from bigdl_tpu import nn
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
    from bigdl_tpu.dataset import mnist
    imgs, labels = mnist.load(n_synthetic=256)
    pf = native.NativePrefetcher(imgs[:, None], labels,
                                 [mnist.TRAIN_MEAN], [mnist.TRAIN_STD],
                                 batch_size=64)
    opt = LocalOptimizer(LeNet5(10), pf, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05), max_iteration(8), 64)
    opt.optimize()
    assert opt.optim_method.state["loss"] < 2.5


# ---- native JPEG decode -----------------------------------------------------

def _make_jpeg(tmp_path, w=64, h=48, q=95, name="img.jpg"):
    from PIL import Image
    rng = np.random.RandomState(0)
    # smooth gradient (JPEG-friendly so decode comparison is tight)
    yy, xx = np.mgrid[0:h, 0:w]
    arr = np.stack([(xx * 255 / w), (yy * 255 / h),
                    ((xx + yy) * 127 / (w + h))], -1).astype(np.uint8)
    path = str(tmp_path / name)
    Image.fromarray(arr).save(path, quality=q)
    return path, arr


def test_native_jpeg_decode_matches_pil(tmp_path):
    from bigdl_tpu import native
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    from PIL import Image
    path, _ = _make_jpeg(tmp_path)
    ours = native.decode_jpeg(path)
    ref = np.asarray(Image.open(path).convert("RGB"))
    assert ours.shape == ref.shape
    # same bitstream, independent decoders: allow small IDCT rounding diffs
    assert np.mean(np.abs(ours.astype(int) - ref.astype(int))) < 2.0
    assert np.max(np.abs(ours.astype(int) - ref.astype(int))) <= 24


def test_native_jpeg_decode_resize_norm(tmp_path):
    from bigdl_tpu import native
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    path, _ = _make_jpeg(tmp_path, w=100, h=80)
    mean, std = [10.0, 20.0, 30.0], [2.0, 3.0, 4.0]
    out = native.decode_jpeg_resize_norm(path, 32, 32, mean, std)
    assert out.shape == (3, 32, 32)
    # un-normalize and compare against python bilinear of the full decode
    full = native.decode_jpeg(path).astype(np.float32)
    back = out * np.array(std, np.float32)[:, None, None] + \
        np.array(mean, np.float32)[:, None, None]
    assert back.min() >= -1 and back.max() <= 256
    # centers should track the gradient: monotone along x for channel 0
    row = back[0, 16]
    assert np.all(np.diff(row) > -3)


def test_native_jpeg_folder_prefetcher(tmp_path):
    from bigdl_tpu import native
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    paths, labels = [], []
    for i in range(8):
        p, _ = _make_jpeg(tmp_path, w=40 + i, h=30 + i, name=f"im{i}.jpg")
        paths.append(p)
        labels.append(i % 4 + 1)
    # n_workers=1: batches are pushed in completion order, so only a single
    # worker guarantees index order for the exact-label assertion below
    pf = native.JpegFolderPrefetcher(paths, labels, 24, 24, 0.0, 255.0,
                                     batch_size=3, n_workers=1)
    assert pf.size() == 8
    seen, ys = 0, []
    for mb in pf.data(train=False):
        assert mb.input.shape[1:] == (3, 24, 24)
        assert np.isfinite(mb.input).all()
        assert mb.input.max() <= 1.0
        seen += mb.input.shape[0]
        ys += list(mb.target)
    assert seen == 8
    assert ys == [float(l) for l in labels]  # single worker: order preserved
    assert pf.decode_failures == 0
    # multi-worker: same multiset of samples, any batch order
    pf2 = native.JpegFolderPrefetcher(paths, labels, 24, 24, 0.0, 255.0,
                                      batch_size=3, n_workers=3)
    ys2 = sorted(y for mb in pf2.data(train=False) for y in mb.target)
    assert ys2 == sorted(float(l) for l in labels)


def test_native_jpeg_prefetcher_bf16_nhwc_output(tmp_path):
    """out="bf16_nhwc" emits accelerator-ready batches: same pixels as the
    f32 CHW path within bf16 rounding, transposed to NHWC, dtype bf16.
    n_workers=1 so both instances deliver batches in cursor order."""
    import ml_dtypes
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    paths, labels = [], []
    for i in range(8):
        p, _ = _make_jpeg(tmp_path, w=48, h=48, name=f"bf{i}.jpg")
        paths.append(p)
        labels.append(i % 4 + 1)
    kw = dict(mean=(124.0, 117.0, 104.0), std=(59.0, 57.0, 57.0),
              batch_size=4, n_workers=1, queue_capacity=2)
    pf32 = native.JpegFolderPrefetcher(paths, labels, 32, 32, **kw)
    pf16 = native.JpegFolderPrefetcher(paths, labels, 32, 32,
                                       out="bf16_nhwc", **kw)
    b32 = next(pf32.data(train=False))
    b16 = next(pf16.data(train=False))
    x16 = np.asarray(b16.get_input())
    assert x16.dtype == ml_dtypes.bfloat16
    assert x16.shape == (4, 32, 32, 3)
    x32 = np.transpose(np.asarray(b32.get_input()), (0, 2, 3, 1))
    assert np.max(np.abs(x32 - x16.astype(np.float32))) < 0.02
    assert np.allclose(np.asarray(b32.get_target()),
                       np.asarray(b16.get_target()))
    # non-JPEG prefetchers reject the format rather than crash
    imgs = np.zeros((8, 1, 8, 8), np.uint8)
    pf = native.NativePrefetcher(imgs, np.arange(1, 9, dtype=np.int64),
                                 [0.0], [1.0], batch_size=4)
    assert pf.lib.pf_set_format(pf.handle, 1) != 0


def test_native_jpeg_prefetcher_augmentation(tmp_path):
    """Worker-side RandomResizedCrop + hflip: deterministic per seed,
    different across seeds, different from the un-augmented decode, and
    statistically centered (mean within the un-augmented image's range)."""
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    paths, labels = [], []
    for i in range(8):
        p, _ = _make_jpeg(tmp_path, w=64, h=48, name=f"aug{i}.jpg")
        paths.append(p)
        labels.append(i % 4 + 1)
    kw = dict(mean=(124.0, 117.0, 104.0), std=(59.0, 57.0, 57.0),
              batch_size=8, n_workers=1, queue_capacity=2)
    plain = np.asarray(next(native.JpegFolderPrefetcher(
        paths, labels, 32, 32, **kw).data(train=False)).get_input())
    a1 = np.asarray(next(native.JpegFolderPrefetcher(
        paths, labels, 32, 32, augment=True, seed=7,
        **kw).data(train=False)).get_input())
    a1b = np.asarray(next(native.JpegFolderPrefetcher(
        paths, labels, 32, 32, augment=True, seed=7,
        **kw).data(train=False)).get_input())
    a2 = np.asarray(next(native.JpegFolderPrefetcher(
        paths, labels, 32, 32, augment=True, seed=8,
        **kw).data(train=False)).get_input())
    assert np.array_equal(a1, a1b)          # same seed → same crops
    assert not np.array_equal(a1, a2)       # different seed → different
    assert not np.array_equal(a1, plain)    # augmented ≠ plain decode
    assert np.isfinite(a1).all()
    # crops sample real pixels: values stay within the plain image's
    # normalized range (bilinear cannot extrapolate)
    assert a1.min() >= plain.min() - 0.1 and a1.max() <= plain.max() + 0.1
    # non-JPEG prefetchers reject augmentation rather than crash
    imgs = np.zeros((8, 1, 8, 8), np.uint8)
    pf = native.NativePrefetcher(imgs, np.arange(1, 9, dtype=np.int64),
                                 [0.0], [1.0], batch_size=4)
    assert pf.lib.pf_set_augment(pf.handle, 1, 3) != 0


def test_native_jpeg_augmentation_worker_count_invariant(tmp_path):
    """Crops hash per (seed, epoch position), not per worker: the multiset
    of augmented images is identical for 1 vs 3 decode workers (batch
    ORDER may differ — completion order — but contents may not)."""
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    paths, labels = [], []
    for i in range(12):
        p, _ = _make_jpeg(tmp_path, w=40, h=40, name=f"wi{i}.jpg")
        paths.append(p)
        labels.append(i % 3 + 1)

    def collect(n_workers):
        pf = native.JpegFolderPrefetcher(
            paths, labels, 24, 24, mean=(124.0, 117.0, 104.0),
            std=(59.0, 57.0, 57.0), batch_size=4, n_workers=n_workers,
            queue_capacity=2, augment=True, seed=5)
        out = []
        for mb in pf.data(train=False):
            for img in np.asarray(mb.get_input()):
                out.append(img.tobytes())
        return sorted(out)

    assert collect(1) == collect(3)


def test_native_jpeg_prefetcher_counts_bad_files(tmp_path):
    from bigdl_tpu import native
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    good, _ = _make_jpeg(tmp_path, name="good.jpg")
    bad = str(tmp_path / "bad.jpg")
    with open(bad, "wb") as f:
        f.write(b"\xff\xd8 garbage that is not a jpeg")
    pf = native.JpegFolderPrefetcher([good, bad], [1, 2], 16, 16, 0.0, 255.0,
                                     batch_size=2, n_workers=1)
    batches = list(pf.data(train=False))
    assert pf.decode_failures == 1
    # the bad sample decoded to a zero image, the good one did not
    xs = np.concatenate([mb.input for mb in batches])
    zero_mask = [bool(np.all(x == 0)) for x in xs]
    assert sorted(zero_mask) == [False, True]


def test_native_jpeg_corrupt_input(tmp_path):
    from bigdl_tpu import native
    if not native.jpeg_available():
        import pytest
        pytest.skip("libjpeg not available")
    import pytest
    with pytest.raises(ValueError):
        native.decode_jpeg(b"not a jpeg at all" * 10)


def test_native_tfrecord_reader_matches_python(tmp_path):
    """C++ tfr_* reader == pure-python reader; corrupt crc raises in both."""
    from bigdl_tpu.native import read_tfrecords_native, available
    from bigdl_tpu.dataset.tfrecord import read_tfrecords, write_tfrecords
    if not available():
        import pytest
        pytest.skip("no native toolchain")

    path = str(tmp_path / "data.tfrecord")
    rng = np.random.RandomState(0)
    records = [rng.bytes(int(n)) for n in rng.randint(1, 2000, size=20)]
    records.append(b"")  # zero-length record edge case
    write_tfrecords(path, records)

    native = read_tfrecords_native(path)
    python = list(read_tfrecords(path, use_native=False))
    assert native == python == records

    # the public reader routes through the native path transparently
    assert list(read_tfrecords(path)) == records

    # corruption: flip a payload byte -> both readers raise
    blob = bytearray(open(path, "rb").read())
    blob[30] ^= 0xFF
    bad = str(tmp_path / "bad.tfrecord")
    open(bad, "wb").write(bytes(blob))
    import pytest
    with pytest.raises(IOError):
        read_tfrecords_native(bad)
    with pytest.raises(IOError):
        list(read_tfrecords(bad, use_native=False))


def test_tfrecord_interop_with_real_tensorflow(tmp_path):
    """Files we write are readable by REAL TensorFlow and vice versa (the
    masked-crc delta bug would fail this: 'corrupted record at 0')."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.dataset.tfrecord import write_tfrecords, read_tfrecords

    ours = str(tmp_path / "ours.tfrecord")
    write_tfrecords(ours, [b"hello", b"\x00" * 100, b"world"])
    got = [r.numpy() for r in tf.data.TFRecordDataset(ours)]
    assert got == [b"hello", b"\x00" * 100, b"world"]

    theirs = str(tmp_path / "theirs.tfrecord")
    with tf.io.TFRecordWriter(theirs) as w:
        w.write(b"alpha")
        w.write(b"beta")
    assert list(read_tfrecords(theirs)) == [b"alpha", b"beta"]
    assert list(read_tfrecords(theirs, use_native=False)) == \
        [b"alpha", b"beta"]


def test_native_jpeg_encode_roundtrip():
    """je_encode inverse of jd_decode (smooth image: JPEG-friendly)."""
    import pytest
    from bigdl_tpu import native
    if not native.jpeg_available():
        pytest.skip("no libjpeg")
    h, w = 24, 30
    yy, xx = np.mgrid[0:h, 0:w]
    img = np.stack([yy * 255 // h, xx * 255 // w,
                    (yy + xx) * 255 // (h + w)], axis=-1).astype(np.uint8)
    back = native.decode_jpeg(native.encode_jpeg(img, quality=95))
    assert back.shape == img.shape
    assert np.abs(back.astype(int) - img.astype(int)).mean() < 3.0
