"""Observability subsystem tests: tracer semantics (nesting, threads,
exception safety, disabled no-op cost), exporter round-trips (Chrome
trace / Prometheus / BENCH-line dump), the TensorBoard bridge, the
trace_report tool, and the end-to-end acceptance run: LeNet/MNIST
training with tracing on produces a valid Chrome trace with nested
``step/*`` spans and a Prometheus dump with step-latency quantiles."""
import json
import logging
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability.metrics import MetricsRegistry
from bigdl_tpu.observability.trace import Tracer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts disabled with empty tracer + registry and cannot
    leak state into unrelated tests."""
    obs.disable()
    obs.reset()
    obs.registry().reset()
    yield
    obs.disable()
    obs.reset()
    obs.registry().reset()


# ------------------------------------------------------------------ tracer

def test_span_nesting_depths_and_order():
    t = Tracer()
    with t.span("a"):
        with t.span("a/b"):
            with t.span("a/b/c"):
                pass
        with t.span("a/d"):
            pass
    evs = {e.name: e for e in t.events()}
    assert evs["a"].depth == 0
    assert evs["a/b"].depth == 1
    assert evs["a/b/c"].depth == 2
    assert evs["a/d"].depth == 1
    # children close before parents, and are contained in the parent
    assert evs["a"].start_ns <= evs["a/b"].start_ns
    assert evs["a/b"].end_ns <= evs["a"].end_ns


def test_span_exception_safety():
    t = Tracer()
    with pytest.raises(ValueError):
        with t.span("outer"):
            with t.span("inner"):
                raise ValueError("boom")
    evs = {e.name: e for e in t.events()}
    # both spans closed despite the raise, tagged with the error type
    assert evs["inner"].end_ns is not None
    assert evs["outer"].end_ns is not None
    assert evs["inner"].args["error"] == "ValueError"
    assert evs["outer"].args["error"] == "ValueError"
    # stack fully unwound: a fresh span sits at depth 0 again
    with t.span("after"):
        pass
    assert {e.name: e for e in t.events()}["after"].depth == 0


def test_span_threads_do_not_share_stacks():
    t = Tracer()
    err = []

    def worker():
        try:
            with t.span("worker"):
                time.sleep(0.01)
        except Exception as e:  # pragma: no cover
            err.append(e)

    with t.span("main"):
        th = threading.Thread(target=worker)
        th.start()
        th.join()
    assert not err
    evs = {e.name: e for e in t.events()}
    # the worker span is depth 0 on ITS thread, not a child of "main"
    assert evs["worker"].depth == 0
    assert evs["worker"].tid != evs["main"].tid


def test_disabled_is_noop_and_cheap():
    assert not obs.enabled()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        with obs.span("hot/loop"):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert obs.get_tracer().events() == []
    # shared no-op handle: no allocation, no clock read. 5µs/call is an
    # order of magnitude above observed (~0.1-0.3µs) but still proves
    # the path costs nothing against a >1ms training step.
    assert per_call < 5e-6, f"disabled span cost {per_call * 1e6:.2f}µs"
    obs.instant("nope")
    assert obs.get_tracer().events() == []


def test_tracer_bounds_memory():
    t = Tracer(max_events=3)
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert len(t.events()) == 3
    assert t.dropped == 2


# ----------------------------------------------------------------- metrics

def test_histogram_exact_below_reservoir_cap():
    h = obs.registry().histogram("t/h", unit="s")
    for v in range(1, 101):
        h.observe(v / 100.0)
    assert h.count == 100
    assert h.min == 0.01 and h.max == 1.0
    assert abs(h.mean - 0.505) < 1e-9
    assert abs(h.quantile(0.5) - 0.51) < 0.02
    assert abs(h.quantile(0.99) - 1.0) < 0.02


def test_histogram_reservoir_sane_above_cap():
    h = obs.registry().histogram("t/big")
    for _ in range(5000):
        h.observe(1.0)
    h.observe(100.0)  # outlier must survive in max even if not sampled
    assert h.count == 5001
    assert h.max == 100.0
    assert 0.9 <= h.quantile(0.5) <= 1.1


def test_registry_type_conflict_raises():
    obs.registry().counter("t/x")
    with pytest.raises(TypeError):
        obs.registry().gauge("t/x")


# --------------------------------------------------------------- exporters

def test_chrome_trace_round_trip(tmp_path):
    obs.enable()
    with obs.span("step", neval=7):
        with obs.span("step/dispatch"):
            time.sleep(0.001)
    path = obs.write_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_name = {e["name"]: e for e in evs}
    assert set(by_name) == {"step", "step/dispatch"}
    outer, inner = by_name["step"], by_name["step/dispatch"]
    assert outer["args"]["neval"] == 7
    # containment: child interval inside parent interval, µs timestamps
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert inner["dur"] >= 1000  # slept 1ms = 1000µs
    assert by_name["step"]["cat"] == "step"


def test_prometheus_golden():
    reg = MetricsRegistry()
    reg.counter("optim/steps").inc(3)
    reg.gauge("optim/throughput", unit="samples/s").set(1.5)
    h = reg.histogram("optim/step_time", unit="s")
    for _ in range(4):
        h.observe(0.25)
    from bigdl_tpu.observability.exporters import prometheus_text
    text = prometheus_text(reg)
    assert text == (
        "# HELP bigdl_optim_step_time optim/step_time (s)\n"
        "# TYPE bigdl_optim_step_time summary\n"
        'bigdl_optim_step_time{quantile="0.5"} 0.25\n'
        'bigdl_optim_step_time{quantile="0.9"} 0.25\n'
        'bigdl_optim_step_time{quantile="0.99"} 0.25\n'
        "bigdl_optim_step_time_sum 1.0\n"
        "bigdl_optim_step_time_count 4\n"
        "bigdl_optim_step_time_min 0.25\n"
        "bigdl_optim_step_time_max 0.25\n"
        "# HELP bigdl_optim_steps optim/steps\n"
        "# TYPE bigdl_optim_steps counter\n"
        "bigdl_optim_steps 3.0\n"
        "# HELP bigdl_optim_throughput optim/throughput (samples/s)\n"
        "# TYPE bigdl_optim_throughput gauge\n"
        "bigdl_optim_throughput 1.5\n")


def test_metrics_dump_bench_schema_round_trip(tmp_path):
    from bigdl_tpu.observability.exporters import (
        record_bench_line, metrics_dump, write_metrics_dump)
    reg = MetricsRegistry()
    line = {"metric": "resnet50_train_images_per_sec_per_chip",
            "value": 2436.91, "unit": "images/sec/chip",
            "vs_baseline": 40.6, "backend": "tpu"}
    record_bench_line(line, reg)
    dump = metrics_dump(reg)
    by_metric = {d["metric"]: d for d in dump}
    main = by_metric["bench/resnet50_train_images_per_sec_per_chip"]
    assert main["value"] == 2436.91
    assert main["unit"] == "images/sec/chip"
    assert by_metric[
        "bench/resnet50_train_images_per_sec_per_chip/vs_baseline"
    ]["value"] == 40.6
    p = write_metrics_dump(str(tmp_path / "m.json"), reg)
    with open(p) as f:
        assert json.load(f) == dump
    # the dump speaks the same schema bench.py prints: every entry has
    # the metric/value/unit triple
    assert all({"metric", "value", "unit"} <= set(d) for d in dump)


def test_summary_bridge_visible_via_read_scalar(tmp_path):
    from bigdl_tpu.visualization import TrainSummary
    reg = MetricsRegistry()
    reg.gauge("optim/throughput").set(512.0)
    h = reg.histogram("optim/step_time", unit="s")
    for _ in range(10):
        h.observe(0.125)
    summary = TrainSummary(str(tmp_path), "bridge_app")
    bridge = obs.SummaryBridge(summary, reg)
    n = bridge.flush(step=3)
    assert n == 4  # gauge + histogram mean/p50/p99
    assert summary.read_scalar("obs/optim/throughput") == [(3, 512.0)]
    [(step, mean)] = summary.read_scalar("obs/optim/step_time/mean")
    assert step == 3 and abs(mean - 0.125) < 1e-6
    [(_, p99)] = summary.read_scalar("obs/optim/step_time/p99")
    assert abs(p99 - 0.125) < 1e-6
    # selection: a name filter drops everything else
    s2 = TrainSummary(str(tmp_path), "bridge_app2")
    assert obs.SummaryBridge(s2, reg,
                             metrics=["optim/throughput"]).flush(1) == 1


# ------------------------------------------------------------ trace_report

def test_trace_report_smoke(tmp_path):
    obs.enable()
    for i in range(3):
        with obs.span("step"):
            with obs.span("step/dispatch"):
                time.sleep(0.002)
            with obs.span("step/data_fetch"):
                pass
    trace = obs.write_chrome_trace(str(tmp_path / "tiny.json"))
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         trace, "--top", "5"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "step/dispatch" in out and "step" in out
    # dispatch slept ~6ms total; the parent step's SELF time must exclude
    # it (self-time is the point of the report)
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import trace_report
        agg = trace_report.self_times(trace_report.load_events(trace))
    finally:
        sys.path.pop(0)
    assert agg["step/dispatch"][1] >= 6000  # ≥6ms total in µs
    assert agg["step"][2] < agg["step"][1]  # self < total


# -------------------------------------------------- optimizer Metrics shim

def test_optimizer_metrics_mean_unseen_raises():
    from bigdl_tpu.optim import Metrics
    m = Metrics()
    m.add("step_time", 0.5)
    assert m.mean("step_time") == 0.5
    with pytest.raises(KeyError, match="no metric named 'bogus'"):
        m.mean("bogus")


def test_optimizer_metrics_mirrors_into_registry_when_enabled():
    from bigdl_tpu.optim import Metrics
    m = Metrics()
    m.add("step_time", 1.0)  # disabled: local only
    assert obs.registry().get("optim/step_time") is None
    obs.enable()
    m.add("step_time", 3.0)
    h = obs.registry().get("optim/step_time")
    assert h is not None and h.count == 1 and h.mean == 3.0
    assert m.values["step_time"] == [1.0, 3.0]


# ---------------------------------------------------------- heartbeat/probe

def test_heartbeat_age_gauge_and_late_warning(caplog):
    from bigdl_tpu.parallel.failure import Heartbeat
    obs.enable()
    hb = Heartbeat(expected_interval_s=0.01)
    assert hb.last_beat_age_s == float("inf")
    hb.beat()
    assert hb.last_beat_age_s < 1.0
    time.sleep(0.03)
    with caplog.at_level(logging.WARNING, "bigdl_tpu.parallel.failure"):
        hb.beat()
    assert any("late heartbeat" in r.message for r in caplog.records)
    rec = [r for r in caplog.records if "late heartbeat" in r.message][0]
    assert "age_s=" in rec.getMessage()
    assert obs.registry().get("failure/late_beats").value == 1.0
    assert obs.registry().get("failure/beats").value == 2.0
    # the age gauge is LIVE: it keeps growing with no beat() writes —
    # the hung-loop case a liveness alert exists to catch
    g = obs.registry().get("failure/last_beat_age_s")
    v1 = g.value
    time.sleep(0.02)
    assert g.value > v1


def test_probe_mesh_records_latency_histogram():
    import jax
    from jax.sharding import Mesh
    from bigdl_tpu.parallel.failure import probe_mesh
    obs.enable()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    res = probe_mesh(mesh, timeout_s=120.0)
    assert res.ok, res
    h = obs.registry().get("failure/probe_latency_s")
    assert h is not None and h.count == 1


# ------------------------------------------------- end-to-end acceptance

def _train_lenet(steps=4, batch=8):
    from bigdl_tpu import nn
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    rng = np.random.RandomState(0)
    x = rng.rand(batch * steps, 28, 28).astype(np.float32)
    y = rng.randint(1, 11, size=batch * steps).astype(np.float32)
    opt = LocalOptimizer(LeNet5(10), (x, y), nn.ClassNLLCriterion(),
                         optim_method=SGD(learningrate=0.01),
                         end_trigger=max_iteration(steps),
                         batch_size=batch)
    opt.optimize()
    return opt


def test_lenet_training_traced_end_to_end(tmp_path):
    obs.enable()
    opt = _train_lenet()
    # --- Chrome trace: valid JSON, nested step/* spans -----------------
    path = obs.write_chrome_trace(str(tmp_path / "lenet_trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    steps = [e for e in evs if e["name"] == "step"]
    assert len(steps) == 4
    for phase in ("step/data_fetch", "step/dispatch", "step/loss_sync"):
        kids = [e for e in evs if e["name"] == phase]
        assert len(kids) == 4, phase
        # every phase span is contained in some step span (nesting)
        for k in kids:
            assert any(s["ts"] <= k["ts"] and
                       k["ts"] + k["dur"] <= s["ts"] + s["dur"] + 1e-3
                       for s in steps), (phase, k)
    # dataset batching shows up too
    assert any(e["name"] == "optimizer/build_step" for e in evs)
    # --- Prometheus dump: step-latency histogram with quantiles --------
    text = obs.prometheus_text()
    assert "# TYPE bigdl_optim_step_time summary" in text
    assert 'bigdl_optim_step_time{quantile="0.5"}' in text
    assert 'bigdl_optim_step_time{quantile="0.99"}' in text
    assert "bigdl_optim_step_time_count 4" in text
    # dataset batch-produce latency was collected
    assert obs.registry().get("dataset/batch_produce_s").count >= 4
    assert obs.registry().get("optim/steps").value == 4.0
    # local Metrics view still agrees (back-compat surface)
    assert len(opt.metrics.values["step_time"]) == 4


def test_lenet_training_disabled_records_nothing():
    assert not obs.enabled()
    _train_lenet(steps=2)
    assert obs.get_tracer().events() == []
    assert obs.registry().names() == []
