"""TF-style op layer tests (parity: reference nn/ops/* behaviors)."""
import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import ops
from bigdl_tpu.utils.table import Table


def _f(op, *xs):
    return np.asarray(op.forward(Table(*xs) if len(xs) > 1 else xs[0]))


def test_comparison_and_logical():
    a = jnp.asarray([1.0, 2.0, 3.0])
    b = jnp.asarray([1.0, 3.0, 2.0])
    assert _f(ops.Equal(), a, b).tolist() == [True, False, False]
    assert _f(ops.NotEqual(), a, b).tolist() == [False, True, True]
    assert _f(ops.Greater(), a, b).tolist() == [False, False, True]
    assert _f(ops.LessEqual(), a, b).tolist() == [True, True, False]
    assert _f(ops.ApproximateEqual(0.5), a, b).tolist() == [True, False, False]
    t = jnp.asarray([True, False])
    assert _f(ops.LogicalNot(), t).tolist() == [False, True]
    assert _f(ops.LogicalAnd(), t, jnp.asarray([True, True])).tolist() == \
        [True, False]


def test_reductions():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    assert _f(ops.Sum(axis=0), x).tolist() == [4.0, 6.0]
    assert _f(ops.Prod(axis=1), x).tolist() == [2.0, 12.0]
    assert _f(ops.Max(axis=1), x).tolist() == [2.0, 4.0]
    assert float(_f(ops.Mean(), x)) == 2.5
    bools = jnp.asarray([[True, False], [True, True]])
    assert _f(ops.All(axis=1), bools).tolist() == [False, True]
    assert _f(ops.Any(axis=0), bools).tolist() == [True, True]
    # axis via second input (TF style)
    assert _f(ops.Sum(), x, jnp.asarray([0])).tolist() == [4.0, 6.0]


def test_elementwise_math():
    x = jnp.asarray([0.5, 1.5, -2.5])
    assert np.allclose(_f(ops.Exp(), x), np.exp([0.5, 1.5, -2.5]))
    assert np.allclose(_f(ops.Floor(), x), [0.0, 1.0, -3.0])
    assert np.allclose(_f(ops.Sign(), x), [1.0, 1.0, -1.0])
    assert np.allclose(_f(ops.SquaredDifference(), x, jnp.zeros(3)),
                       np.square([0.5, 1.5, -2.5]))
    assert np.allclose(_f(ops.FloorDiv(), jnp.asarray([7.0]),
                          jnp.asarray([2.0])), [3.0])
    assert _f(ops.IsNan(), jnp.asarray([np.nan, 1.0])).tolist() == \
        [True, False]
    assert np.allclose(_f(ops.Erf(), jnp.asarray([0.0])), [0.0])


def test_shape_cast():
    x = jnp.zeros((2, 3, 4))
    assert _f(ops.Shape(), x).tolist() == [2, 3, 4]
    assert int(_f(ops.Rank(), x)) == 3
    y = _f(ops.Cast(jnp.int32), jnp.asarray([1.7, 2.2]))
    assert y.dtype == np.int32 and y.tolist() == [1, 2]


def test_gather_select_slice():
    p = jnp.arange(12.0).reshape(4, 3)
    idx = jnp.asarray([2, 0])
    assert np.allclose(_f(ops.Gather(), p, idx), np.asarray(p)[[2, 0]])
    cond = jnp.asarray([True, False, True])
    assert _f(ops.Select(), cond, jnp.ones(3), jnp.zeros(3)).tolist() == \
        [1.0, 0.0, 1.0]
    s = _f(ops.Slice(begin=[1, 0], size=[2, 2]), p)
    assert np.allclose(s, np.asarray(p)[1:3, :2])
    ss = _f(ops.StridedSlice([0, 0], [4, 3], [2, 1]), p)
    assert np.allclose(ss, np.asarray(p)[::2])
    shr = _f(ops.StridedSlice([1, 0], [2, 3], shrink_axis_mask=1), p)
    assert np.allclose(shr, np.asarray(p)[1])


def test_tile_onehot_topk():
    x = jnp.asarray([[1.0, 2.0]])
    assert _f(ops.Tile([2, 2]), x).shape == (2, 4)
    oh = _f(ops.OneHot(4), jnp.asarray([0, 3]))
    assert np.allclose(oh, np.eye(4)[[0, 3]])
    scores = jnp.asarray([[0.1, 0.9, 0.5], [0.8, 0.2, 0.3]])
    tk = ops.TopK(2).forward(scores)
    assert np.asarray(tk[2]).tolist() == [[1, 2], [0, 2]]
    itk = _f(ops.InTopK(1), scores, jnp.asarray([1, 2]))
    assert itk.tolist() == [True, False]
    am = _f(ops.ArgMax(axis=1), scores)
    assert am.tolist() == [1, 0]


def test_batch_matmul_segment_sum():
    a = jnp.asarray(np.random.RandomState(0).randn(2, 3, 4).astype(np.float32))
    b = jnp.asarray(np.random.RandomState(1).randn(2, 4, 5).astype(np.float32))
    out = _f(ops.BatchMatMul(), a, b)
    assert np.allclose(out, np.matmul(np.asarray(a), np.asarray(b)),
                       atol=1e-5)
    outT = _f(ops.BatchMatMul(adj_y=True), a, jnp.swapaxes(b, 1, 2))
    assert np.allclose(outT, out, atol=1e-5)
    data = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    seg = jnp.asarray([0, 0, 1])
    ss = _f(ops.SegmentSum(num_segments=2), data, seg)
    assert np.allclose(ss, [[4.0, 6.0], [5.0, 6.0]])


def test_resize_bilinear():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    out = _f(ops.ResizeBilinear(2, 2), x)
    assert out.shape == (1, 2, 2, 1)
    ac = _f(ops.ResizeBilinear(7, 7, align_corners=True), x)
    assert ac.shape == (1, 7, 7, 1)
    # align_corners keeps the corner values exactly
    assert np.isclose(ac[0, 0, 0, 0], 0.0) and np.isclose(ac[0, -1, -1, 0],
                                                          15.0)


def test_dilation2d():
    x = jnp.zeros((1, 5, 5, 1)).at[0, 2, 2, 0].set(1.0)
    filt = jnp.zeros((3, 3, 1))
    out = _f(ops.Dilation2D(strides=[1, 1, 1, 1], rates=[1, 1, 1, 1]),
             x, filt)
    assert out.shape == (1, 5, 5, 1)
    assert float(np.asarray(out)[0, 1:4, 1:4, 0].min()) == 1.0  # dilated peak


def test_losses_and_tensor_op():
    x = jnp.asarray([3.0, 4.0])
    assert float(_f(ops.L2Loss(), x)) == 12.5
    logits = jnp.asarray([[2.0, 0.0]])
    labels = jnp.asarray([[1.0, 0.0]])
    ce = float(_f(ops.CrossEntropy(), logits, labels)[0])
    assert np.isclose(ce, -np.log(np.exp(2) / (np.exp(2) + 1)), atol=1e-5)
    top = ops.TensorOp().exp().add(1.0).log()
    out = _f(top, jnp.asarray([0.0]))
    assert np.isclose(out[0], np.log(2.0), atol=1e-6)


def test_feature_columns():
    b = ops.BucketizedCol(boundaries=[0.0, 10.0, 100.0])
    assert _f(b, jnp.asarray([-5.0, 5.0, 50.0, 500.0])).tolist() == \
        [0, 1, 2, 3]
    h = ops.CategoricalColHashBucket(hash_bucket_size=16)
    out = _f(h, np.array(["a", "b", "a"], dtype=object))
    assert out[0] == out[2] and 0 <= out.min() and out.max() < 16
    v = ops.CategoricalColVocaList(["cat", "dog"], num_oov_buckets=2)
    out = _f(v, np.array(["dog", "bird", "cat"], dtype=object))
    assert out[0] == 1 and out[2] == 0 and out[1] >= 2
    c = ops.CrossCol(hash_bucket_size=32)
    out = np.asarray(c.forward(Table(np.array(["a", "b"], dtype=object),
                                     np.array(["x", "y"], dtype=object))))
    assert out.shape == (2,) and (0 <= out).all() and (out < 32).all()
    ind = ops.IndicatorCol(feat_len=4)
    out = _f(ind, jnp.asarray([[0, 2]]))
    assert np.allclose(out, [[1, 0, 1, 0]])
    kv = ops.Kv2Tensor(feat_len=4)
    out = _f(kv, np.array(["0:1.5,2:3.0", "1:2.0"], dtype=object))
    assert np.allclose(out, [[1.5, 0, 3.0, 0], [0, 2.0, 0, 0]])
    mk = ops.MkString("-")
    out = mk.forward(np.array([[1, 2], [3, 4]]))
    assert list(out) == ["1-2", "3-4"]
    sub = ops.Substr(1, 2)
    out = sub.forward(np.array(["hello", "world"], dtype=object))
    assert list(out) == ["el", "or"]


def test_random_ops():
    import jax
    r = ops.RandomUniform(minval=2.0, maxval=3.0)
    out = np.asarray(r.apply({}, {}, jnp.asarray([3, 4]), False,
                             jax.random.PRNGKey(0))[0])
    assert out.shape == (3, 4) and (out >= 2.0).all() and (out < 3.0).all()
    t = ops.TruncatedNormal(stddev=1.0)
    out = np.asarray(t.apply({}, {}, jnp.asarray([100]), False,
                             jax.random.PRNGKey(1))[0])
    assert out.shape == (100,) and np.abs(out).max() <= 2.0 + 1e-6


def test_module_to_operation():
    from bigdl_tpu import nn
    op = ops.ModuleToOperation(nn.ReLU())
    out = _f(op, jnp.asarray([-1.0, 2.0]))
    assert out.tolist() == [0.0, 2.0]


def test_range_ops():
    from bigdl_tpu.ops import RangeOps
    out = np.asarray(RangeOps().forward([np.int32(2), np.int32(14),
                                         np.int32(3)]))
    assert np.array_equal(out, np.arange(2, 14, 3))


def test_depthwise_conv2d_matches_torch():
    import torch
    import torch.nn.functional as F
    from bigdl_tpu.ops import DepthwiseConv2D
    rng = np.random.RandomState(0)
    x = rng.randn(2, 6, 10, 10).astype(np.float32)       # NCHW
    w = rng.randn(3, 3, 6, 2).astype(np.float32)         # kh,kw,in,mult
    op = DepthwiseConv2D(stride_w=1, stride_h=1, pad_w=1, pad_h=1,
                         data_format="NCHW")
    out = np.asarray(op.forward([x, w]))
    # torch depthwise: weight (in*mult, 1, kh, kw), groups=in, cin-major
    wt = torch.tensor(w.transpose(2, 3, 0, 1).reshape(12, 1, 3, 3))
    ref = F.conv2d(torch.tensor(x), wt, padding=1, groups=6).numpy()
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()
    # NHWC agrees with NCHW
    op2 = DepthwiseConv2D(pad_w=1, pad_h=1, data_format="NHWC")
    out2 = np.asarray(op2.forward([x.transpose(0, 2, 3, 1), w]))
    assert np.allclose(out2.transpose(0, 3, 1, 2), ref, atol=1e-4)


def test_tf_wrapper_ops():
    """nn/tf wrapper parity: Assert/NoOp/ControlDependency/BiasAdd/
    TensorModuleWrapper/Compare."""
    import pytest
    from bigdl_tpu import nn
    from bigdl_tpu.utils.table import Table

    x = np.ones((2, 3, 4), np.float32)
    b = np.arange(4, dtype=np.float32)
    out = np.asarray(ops.BiasAdd().forward(Table(x, b)))
    assert np.allclose(out, 1.0 + b)

    assert np.allclose(np.asarray(
        ops.NoOp().forward(x)), x)
    assert np.allclose(np.asarray(
        ops.ControlDependency().forward(x)), x)

    y = ops.Assert().forward(Table(np.bool_(True), x))
    assert np.allclose(np.asarray(y), x)
    with pytest.raises(ValueError):  # survives python -O (ADVICE r2)
        ops.Assert().forward(Table(np.bool_(False), x))

    w = ops.TensorModuleWrapper(nn.AddConstant(2.0))
    assert np.allclose(np.asarray(w.forward(x)), x + 2.0)

    class Gt(ops.Compare):
        def _cmp(self, a, b):
            return a > b
    assert bool(np.asarray(Gt().forward(Table(np.float32(3), np.float32(1)))))
