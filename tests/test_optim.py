"""Optim method / schedule / trigger / checkpoint tests (modeled on the
reference's optim/*Spec.scala)."""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.optim import (SGD, Adam, Adagrad, Adadelta, Adamax, RMSprop,
                             Ftrl, LarsSGD, LBFGS, Trigger, max_iteration,
                             max_epoch, every_epoch, several_iteration,
                             min_loss, and_, or_)
from bigdl_tpu.optim.optim_method import (Poly, Step, MultiStep, EpochStep,
                                          Exponential, NaturalExp, Warmup,
                                          SequentialSchedule, Plateau,
                                          Default)
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.models import LeNet5


def _quadratic():
    """min 0.5*||x - t||^2, t = [1, -2, 3]."""
    t = jnp.asarray([1.0, -2.0, 3.0])

    def feval(x):
        return 0.5 * jnp.sum((x["x"] - t) ** 2), {"x": x["x"] - t}
    return feval, {"x": jnp.zeros(3)}, t


@pytest.mark.parametrize("method,iters,tol", [
    (SGD(learningrate=0.5), 50, 1e-2),
    (SGD(learningrate=0.2, momentum=0.9, nesterov=True), 80, 1e-2),
    (Adam(learningrate=0.3), 200, 1e-2),
    (Adagrad(learningrate=1.0), 300, 5e-2),
    (Adadelta(decayrate=0.9, epsilon=1e-2), 500, 5e-2),
    (Adamax(learningrate=0.5), 200, 5e-2),
    (RMSprop(learningrate=0.3), 200, 5e-2),
    (Ftrl(learningrate=1.0), 300, 5e-2),
    (LarsSGD(learningrate=0.1, trust=0.5), 400, 2.0),
])
def test_method_converges_quadratic(method, iters, tol):
    feval, x, t = _quadratic()
    state = method.init_state(x)
    for i in range(iters):
        loss, g = feval(x)
        x, state = method.update(g, x, state, method.current_lr())
        method.state["neval"] += 1
    assert float(jnp.max(jnp.abs(x["x"] - t))) < tol, \
        (type(method).__name__, x["x"])


def test_lbfgs_rosenbrock():
    def feval(x):
        a, b = x[0], x[1]
        loss = (1 - a) ** 2 + 100 * (b - a * a) ** 2
        g = jnp.asarray([-2 * (1 - a) - 400 * a * (b - a * a),
                         200 * (b - a * a)])
        return loss, g
    lbfgs = LBFGS(max_iter=100, line_search=True)
    x, losses = lbfgs.optimize(feval, jnp.zeros(2))
    assert losses[-1] < 1e-4, losses[-1]
    assert np.allclose(np.asarray(x), [1.0, 1.0], atol=1e-2)


def test_schedules():
    st = {"neval": 0, "epoch": 1}
    assert Default().update_lr(0.1, st) == 0.1
    d = Default()
    d.decay = 0.1
    st["neval"] = 10
    assert abs(d.update_lr(0.1, st) - 0.1 / 2.0) < 1e-9

    assert abs(Poly(0.5, 100).update_lr(1.0, {"neval": 75, "epoch": 1}) -
               0.5) < 1e-9
    assert Poly(0.5, 100).update_lr(1.0, {"neval": 100, "epoch": 1}) == 0.0
    assert abs(Step(10, 0.5).update_lr(1.0, {"neval": 25, "epoch": 1}) -
               0.25) < 1e-9
    assert abs(MultiStep([10, 20], 0.1).update_lr(
        1.0, {"neval": 15, "epoch": 1}) - 0.1) < 1e-9
    assert abs(EpochStep(2, 0.5).update_lr(1.0, {"neval": 0, "epoch": 5}) -
               0.25) < 1e-9
    assert abs(Exponential(10, 0.5, stair_case=True).update_lr(
        1.0, {"neval": 25, "epoch": 1}) - 0.25) < 1e-9
    assert abs(NaturalExp(1, 0.1).update_lr(
        1.0, {"neval": 2, "epoch": 1}) - np.exp(-0.2)) < 1e-6
    assert abs(Warmup(0.01).update_lr(0.1, {"neval": 5, "epoch": 1}) -
               0.15) < 1e-9

    seq = SequentialSchedule(10).add(Warmup(0.01), 5).add(Default(), 100)
    assert abs(seq.update_lr(0.1, {"neval": 3, "epoch": 1}) - 0.13) < 1e-9
    assert abs(seq.update_lr(0.1, {"neval": 7, "epoch": 1}) - 0.1) < 1e-9


def test_plateau():
    p = Plateau(monitor="score", factor=0.5, patience=2, mode="max")
    lr = 1.0
    s = {"neval": 0, "epoch": 1, "score": 0.5}
    assert p.update_lr(lr, s) == 1.0
    for _ in range(3):  # no improvement for patience+1 steps
        out = p.update_lr(lr, {"neval": 0, "epoch": 1, "score": 0.4})
    assert out == 0.5


def test_triggers():
    assert max_iteration(10)({"neval": 10, "epoch": 1})
    assert not max_iteration(10)({"neval": 9, "epoch": 1})
    assert max_epoch(2)({"neval": 0, "epoch": 3})
    assert several_iteration(5)({"neval": 5, "epoch": 1})
    assert not several_iteration(5)({"neval": 6, "epoch": 1})
    assert min_loss(0.1)({"neval": 0, "epoch": 1, "loss": 0.05})
    t = and_(max_iteration(5), min_loss(1.0))
    assert t({"neval": 5, "epoch": 1, "loss": 0.5})
    assert not t({"neval": 4, "epoch": 1, "loss": 0.5})
    e = every_epoch()
    assert not e({"neval": 3, "epoch": 1, "epoch_finished": False})
    assert e({"neval": 3, "epoch": 1, "epoch_finished": True})
    assert not e({"neval": 4, "epoch": 1, "epoch_finished": True})  # same ep


def test_gradient_clipping():
    from bigdl_tpu.optim.optimizer import _clip_grads
    g = {"a": jnp.asarray([3.0, -4.0])}
    out = _clip_grads(g, clip_const=(-1.0, 1.0))
    assert np.allclose(np.asarray(out["a"]), [1.0, -1.0])
    out = _clip_grads(g, clip_norm=1.0)  # norm 5 → scale by 1/5
    assert np.allclose(np.asarray(out["a"]), [0.6, -0.8])


def test_checkpoint_resume(tmp_path):
    from bigdl_tpu.optim import LocalOptimizer
    imgs, labels = mnist.load(n_synthetic=128)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    model = LeNet5(10)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05), max_iteration(4),
                         batch_size=32)
    opt.set_checkpoint(several_iteration(2), str(tmp_path))
    opt.optimize()
    ckpt = os.path.join(str(tmp_path), "checkpoint.bigdl")
    assert os.path.exists(ckpt)

    model2 = LeNet5(10)
    opt2 = LocalOptimizer(model2, ds, nn.ClassNLLCriterion(),
                          SGD(learningrate=0.05), max_iteration(8),
                          batch_size=32)
    opt2.load_checkpoint(ckpt)
    assert opt2.optim_method.state["neval"] == 4
    opt2.optimize()
    assert opt2.optim_method.state["neval"] == 8


def test_train_summary(tmp_path):
    from bigdl_tpu.optim import LocalOptimizer
    from bigdl_tpu.visualization import TrainSummary
    imgs, labels = mnist.load(n_synthetic=64)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    summ = TrainSummary(str(tmp_path), "test_app")
    opt = LocalOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.01), max_iteration(3),
                         batch_size=32)
    opt.set_train_summary(summ)
    opt.optimize()
    scalars = summ.read_scalar("Loss")
    assert len(scalars) == 3
    assert scalars[0][0] == 1
    # event file exists and is non-trivial
    assert os.path.getsize(summ.writer.path) > 50


def test_nan_policy():
    from bigdl_tpu.optim import LocalOptimizer
    from bigdl_tpu.dataset import Sample
    x = np.random.randn(64, 4).astype(np.float32)
    samples = [Sample(x[i], x[i, :1]) for i in range(64)]
    opt = LocalOptimizer(nn.Linear(4, 1), DataSet.array(samples),
                         nn.MSECriterion(), SGD(learningrate=1e20),
                         max_iteration(5), batch_size=32)
    with pytest.raises(FloatingPointError):
        opt.optimize()


def test_regularizer_applied():
    from bigdl_tpu.optim import L2Regularizer, LocalOptimizer
    x = np.random.randn(64, 4).astype(np.float32)
    y = np.random.randn(64, 1).astype(np.float32)
    from bigdl_tpu.dataset import Sample
    samples = [Sample(x[i], y[i]) for i in range(64)]
    m_reg = nn.Linear(4, 1, w_regularizer=L2Regularizer(10.0))
    opt = LocalOptimizer(m_reg, DataSet.array(samples), nn.MSECriterion(),
                         SGD(learningrate=0.1), max_iteration(50), 32)
    opt.optimize()
    w_reg = np.linalg.norm(np.asarray(m_reg.params["weight"]))

    m_plain = nn.Linear(4, 1)
    opt = LocalOptimizer(m_plain, DataSet.array(samples), nn.MSECriterion(),
                         SGD(learningrate=0.1), max_iteration(50), 32)
    opt.optimize()
    w_plain = np.linalg.norm(np.asarray(m_plain.params["weight"]))
    assert w_reg < w_plain  # regularized weights shrink


def test_validation_during_training():
    from bigdl_tpu.optim import LocalOptimizer, Top1Accuracy
    imgs, labels = mnist.load(n_synthetic=128)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    opt = LocalOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05), max_iteration(6), 64)
    opt.set_validation(several_iteration(3), ds, [Top1Accuracy()], 64)
    opt.optimize()
    assert "score" in opt.optim_method.state


def test_treenn_accuracy():
    from bigdl_tpu.optim import TreeNNAccuracy
    m = TreeNNAccuracy()
    # (B, nodes, classes): root = node 0
    out = np.zeros((4, 3, 5), np.float32)
    out[0, 0, 2] = 1; out[1, 0, 1] = 1; out[2, 0, 4] = 1; out[3, 0, 0] = 1
    target = np.zeros((4, 3), np.float32)
    target[:, 0] = [3, 2, 1, 1]  # 1-based; three of four correct
    acc, n = m(out, target).result()
    assert n == 4 and abs(acc - 0.75) < 1e-9
    # binary head thresholds at 0.5
    outb = np.array([[[0.9]], [[0.2]]], np.float32)
    tb = np.array([[1], [0]], np.float32)
    accb, nb = m(outb, tb).result()
    assert nb == 2 and accb == 1.0


def test_freeze_unfreeze_finetuning():
    """Module.freeze keeps a layer's params fixed through training (incl.
    weight decay) and unfreeze releases them (AbstractModule.freeze
    parity)."""
    import jax, numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.optim.trigger import max_epoch
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample

    model = nn.Sequential(
        nn.Linear(4, 8, name="backbone"), nn.ReLU(),
        nn.Linear(8, 2, name="head"), nn.LogSoftMax())
    model.ensure_initialized()
    w_backbone = np.asarray(model.params["0"]["weight"]).copy()
    w_head = np.asarray(model.params["2"]["weight"]).copy()
    model.freeze("backbone")

    rng = np.random.RandomState(0)
    xs = rng.randn(32, 4).astype(np.float32)
    ys = (rng.rand(32) < 0.5).astype(np.int32) + 1
    ds = DataSet.array([Sample(x, np.float32(y)) for x, y in zip(xs, ys)])
    opt = Optimizer(model=model, training_set=ds,
                    criterion=nn.ClassNLLCriterion(),
                    optim_method=SGD(learningrate=0.1, weightdecay=1e-2),
                    end_trigger=max_epoch(3), batch_size=16)
    opt.optimize()
    assert np.allclose(np.asarray(model.params["0"]["weight"]),
                       w_backbone), "frozen backbone moved"
    assert not np.allclose(np.asarray(model.params["2"]["weight"]), w_head), \
        "head did not train"

    model.unfreeze()
    opt2 = Optimizer(model=model, training_set=ds,
                     criterion=nn.ClassNLLCriterion(),
                     optim_method=SGD(learningrate=0.1),
                     end_trigger=max_epoch(2), batch_size=16)
    opt2.optimize()
    assert not np.allclose(np.asarray(model.params["0"]["weight"]),
                           w_backbone), "unfreeze did not release backbone"


def test_module_parity_helpers():
    """quantize()/save_torch/save_tf/extra-parameter round trips exist on
    Module (AbstractModule API parity)."""
    import tempfile, os
    import numpy as np
    from bigdl_tpu import nn
    m = nn.Sequential(nn.SpatialConvolution(1, 2, 3, 3),
                      nn.SpatialBatchNormalization(2), nn.ReLU())
    m.training()
    m.forward(np.random.randn(2, 1, 6, 6).astype(np.float32))
    m.evaluate()
    q = m.quantize()
    assert type(q.modules[0]).__name__.startswith("Quantized")
    extra = m.get_extra_parameter()
    assert len(extra) > 0
    m.set_extra_parameter([np.asarray(e) for e in extra])
    with tempfile.TemporaryDirectory() as d:
        m.save_torch(os.path.join(d, "m.t7"))
        assert os.path.exists(os.path.join(d, "m.t7"))
        data = m.save_tf(input_shape=(1, 6, 6))
        assert isinstance(data, bytes) and len(data) > 0


def test_freeze_all_then_unfreeze_head():
    """freeze() marks the whole tree; unfreeze('head') releases just it."""
    import numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.optim.trigger import max_epoch
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample

    model = nn.Sequential(
        nn.Linear(4, 8, name="backbone"), nn.ReLU(),
        nn.Linear(8, 2, name="head"), nn.LogSoftMax())
    model.ensure_initialized()
    w_backbone = np.asarray(model.params["0"]["weight"]).copy()
    w_head = np.asarray(model.params["2"]["weight"]).copy()
    model.freeze()
    model.unfreeze("head")

    rng = np.random.RandomState(1)
    xs = rng.randn(32, 4).astype(np.float32)
    ys = (rng.rand(32) < 0.5).astype(np.int32) + 1
    ds = DataSet.array([Sample(x, np.float32(y)) for x, y in zip(xs, ys)])
    Optimizer(model=model, training_set=ds,
              criterion=nn.ClassNLLCriterion(),
              optim_method=SGD(learningrate=0.1),
              end_trigger=max_epoch(3), batch_size=16).optimize()
    assert np.allclose(np.asarray(model.params["0"]["weight"]), w_backbone)
    assert not np.allclose(np.asarray(model.params["2"]["weight"]), w_head)


def test_freeze_zero1_distributed():
    """Module.freeze holds through the zero1 sharded-update path."""
    import numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.optim.optimizer import DistriOptimizer
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.trigger import max_epoch
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample

    model = nn.Sequential(
        nn.Linear(4, 8, name="backbone"), nn.ReLU(),
        nn.Linear(8, 2, name="head"), nn.LogSoftMax())
    model.ensure_initialized()
    w_backbone = np.asarray(model.params["0"]["weight"]).copy()
    w_head = np.asarray(model.params["2"]["weight"]).copy()
    model.freeze("backbone")

    rng = np.random.RandomState(2)
    xs = rng.randn(64, 4).astype(np.float32)
    ys = (rng.rand(64) < 0.5).astype(np.int32) + 1
    ds = DataSet.array([Sample(x, np.float32(y)) for x, y in zip(xs, ys)])
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          SGD(learningrate=0.1, weightdecay=1e-2),
                          max_epoch(3), batch_size=32,
                          parameter_mode="zero1")
    opt.optimize()
    assert np.allclose(np.asarray(model.params["0"]["weight"]),
                       w_backbone, atol=1e-6), "frozen backbone moved (zero1)"
    assert not np.allclose(np.asarray(model.params["2"]["weight"]), w_head)


def test_set_extra_parameter_shape_check():
    import numpy as np
    import pytest as _pt
    from bigdl_tpu import nn
    m = nn.SpatialBatchNormalization(4)
    m.ensure_initialized()
    extra = m.get_extra_parameter()
    with _pt.raises(ValueError):
        m.set_extra_parameter([np.zeros(1)] * len(extra))


def test_async_sync_policy_trains_like_sync():
    """set_sync_policy('async') reaches the same solution (lagged loss
    reads only change WHEN the host observes, not what the device runs)."""
    import numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.optim import LocalOptimizer, SGD
    from bigdl_tpu.optim.trigger import max_epoch
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils import engine

    def build_and_train(policy):
        engine.set_seed(7)
        rng = np.random.RandomState(3)
        xs = rng.randn(64, 4).astype(np.float32)
        ys = (xs[:, 0] > 0).astype(np.int32) + 1
        ds = DataSet.array([Sample(x, np.float32(y))
                            for x, y in zip(xs, ys)])
        m = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
        opt = LocalOptimizer(m, ds, nn.ClassNLLCriterion(),
                             SGD(learningrate=0.2), max_epoch(4), 16)
        opt.set_sync_policy(policy)
        opt.optimize()
        return np.asarray(m.params["0"]["weight"]), \
            opt.optim_method.state["loss"]

    w_sync, l_sync = build_and_train("sync")
    w_async, l_async = build_and_train("async")
    assert np.allclose(w_sync, w_async, atol=1e-5)
    assert np.isfinite(l_async)
    assert abs(l_sync - l_async) < 1e-5  # drained final loss matches


def test_async_sync_policy_nan_detection_lags_but_fires():
    """A NaN produced mid-run is detected via the LAGGED read (step k's
    blow-up observed at step k+1), and a NaN pending on the FINAL step is
    caught by the post-loop drain — neither is swallowed."""
    import numpy as np
    import pytest as _pt
    from bigdl_tpu import nn
    from bigdl_tpu.optim import LocalOptimizer, SGD
    from bigdl_tpu.optim.trigger import max_epoch
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample

    def diverging_opt(n_samples, epochs):
        # step 1 is finite; the huge LR explodes params so step 2+ is
        # non-finite — the first NaN is only ever seen via a lagged read
        rng = np.random.RandomState(0)
        xs = (rng.randn(n_samples, 4) * 100).astype(np.float32)
        ys = rng.randn(n_samples, 1).astype(np.float32) * 100
        ds = DataSet.array([Sample(x, y) for x, y in zip(xs, ys)])
        m = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 1))
        opt = LocalOptimizer(m, ds, nn.MSECriterion(),
                             SGD(learningrate=1e12), max_epoch(epochs), 16)
        opt.set_sync_policy("async")
        return opt

    # many steps: lagged detection mid-run
    with _pt.raises(FloatingPointError):
        diverging_opt(64, 4).optimize()

    # exactly 2 steps: the NaN loss of the final step is pending when the
    # loop ends — the drain must raise, not silently return
    with _pt.raises(FloatingPointError):
        diverging_opt(32, 1).optimize()


def test_accuracy_sequence_labels_and_onehot():
    """Top1/Top5 accept (B,T,C) outputs with integer (B,T) sequence labels
    (even when T == C) AND one-hot (B,C) targets."""
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy
    rng = np.random.RandomState(0)
    # sequence labels, T == C == 10, B=3
    out = rng.randn(3, 10, 10).astype(np.float32)
    t = rng.randint(1, 11, size=(3, 10))
    r = Top1Accuracy()(out, t)
    expect = int(np.sum(np.argmax(out.reshape(-1, 10), -1) + 1
                        == t.reshape(-1)))
    assert r.correct == expect and r.count == 30
    r5 = Top5Accuracy()(out, t)
    assert r5.count == 30 and r5.correct >= r.correct
    # one-hot rows (keras categorical path)
    oh = np.eye(10, dtype=np.float32)[t.reshape(-1) - 1][:30]
    out2 = rng.randn(30, 10).astype(np.float32)
    r2 = Top1Accuracy()(out2, oh)
    expect2 = int(np.sum(np.argmax(out2, -1) + 1 == t.reshape(-1)))
    assert r2.correct == expect2 and r2.count == 30


def test_async_checkpoint_write_and_resume(tmp_path):
    """set_checkpoint(async_write=True): writes land on the background
    thread (ordered, atomic tmp+rename), optimize() flushes them, resume
    works, and writer failures surface instead of vanishing."""
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import LocalOptimizer, SGD, MaxEpoch, \
        several_iteration
    from bigdl_tpu.dataset import DataSet, mnist
    from bigdl_tpu import nn

    imgs, labels = mnist.load(n_synthetic=32)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    opt = LocalOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.01), MaxEpoch(2), batch_size=8)
    opt.set_checkpoint(several_iteration(2), str(tmp_path),
                       async_write=True)
    opt.optimize()
    snap = tmp_path / "checkpoint.bigdl"
    assert snap.exists()
    assert not (tmp_path / "checkpoint.bigdl.tmp").exists()  # atomic

    # resume restores counters/params
    opt2 = LocalOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                          SGD(learningrate=0.01), MaxEpoch(3), batch_size=8)
    opt2.load_checkpoint(str(snap))
    opt2.optimize()
    assert np.isfinite(float(opt2.optim_method.state["loss"]))

    # a failing writer surfaces at flush
    from bigdl_tpu.optim.optimizer import _AsyncCheckpointWriter
    w = _AsyncCheckpointWriter()
    w.submit(str(tmp_path / "no" / "such" / "dir" / "x.bigdl"), {"a": 1})
    with pytest.raises(RuntimeError, match="async checkpoint"):
        w.flush()


def test_adamw_decoupled_decay():
    """AdamW == Adam + lr*wd*w subtracted from the PRE-step weights (the
    decoupled form); biases/norms (ndim < 2) are excluded by default; a
    pure-decay case shrinks weights geometrically where Adam's
    L2-in-gradient would not."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.optim import Adam, AdamW
    params = {"w": jnp.asarray(np.array([[1.0, -2.0, 0.5]], np.float32)),
              "b": jnp.asarray(np.array([0.7], np.float32))}
    grads = {"w": jnp.asarray(np.array([[0.3, -0.1, 0.2]], np.float32)),
             "b": jnp.asarray(np.array([0.1], np.float32))}
    lr = jnp.float32(0.1)

    adam = Adam()
    aw = AdamW(weight_decay=0.04)
    s1 = adam.init_state(params)
    s2 = aw.init_state(params)
    p_adam, _ = adam.update(grads, params, s1, lr)
    p_aw, _ = aw.update(grads, params, s2, lr)
    np.testing.assert_allclose(
        np.asarray(p_aw["w"]),
        np.asarray(p_adam["w"]) - 0.1 * 0.04 * np.asarray(params["w"]),
        rtol=1e-6)
    # the 1-D bias does NOT decay (standard recipe excludes biases/norms)
    np.testing.assert_allclose(np.asarray(p_aw["b"]),
                               np.asarray(p_adam["b"]), rtol=1e-6)

    # zero gradients: Adam leaves weights alone, AdamW still decays the
    # matrix but not the bias
    z = {"w": jnp.zeros((1, 3)), "b": jnp.zeros((1,))}
    p2, _ = aw.update(z, params, aw.init_state(params), lr)
    np.testing.assert_allclose(np.asarray(p2["w"]),
                               np.asarray(params["w"]) * (1 - 0.1 * 0.04),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p2["b"]),
                               np.asarray(params["b"]), rtol=1e-6)
    # opt-out filter decays everything
    aw2 = AdamW(weight_decay=0.04, decay_filter=lambda w: True)
    p3, _ = aw2.update(z, params, aw2.init_state(params), lr)
    np.testing.assert_allclose(np.asarray(p3["b"]),
                               np.asarray(params["b"]) * (1 - 0.1 * 0.04),
                               rtol=1e-6)


def test_adamw_trains():
    from bigdl_tpu import nn
    from bigdl_tpu.optim import AdamW, LocalOptimizer, max_iteration
    from bigdl_tpu.dataset import DataSet
    rng = np.random.RandomState(0)
    x = rng.randn(128, 6).astype(np.float32)
    w_true = rng.randn(6, 1).astype(np.float32)
    y = x @ w_true
    opt = LocalOptimizer(nn.Linear(6, 1), DataSet.from_arrays(x, y),
                         nn.MSECriterion(),
                         AdamW(learningrate=5e-2, weight_decay=1e-4),
                         max_iteration(300), batch_size=32)
    opt.optimize()
    assert float(opt.optim_method.state["loss"]) < 0.05


def test_cosine_annealing_schedule():
    from bigdl_tpu.optim import SGD, CosineAnnealing
    opt = SGD(learningrate=1.0,
              learningrate_schedule=CosineAnnealing(100, min_lr=0.1))
    opt.state["neval"] = 0
    assert abs(opt.current_lr() - 1.0) < 1e-6        # start at lr
    opt.state["neval"] = 50
    assert abs(opt.current_lr() - 0.55) < 1e-6       # halfway: mean
    opt.state["neval"] = 100
    assert abs(opt.current_lr() - 0.1) < 1e-6        # floor at min_lr
    opt.state["neval"] = 1000
    assert abs(opt.current_lr() - 0.1) < 1e-6        # stays at floor

    # SGDR restarts: lr comes back to the peak at each cycle boundary
    opt2 = SGD(learningrate=1.0,
               learningrate_schedule=CosineAnnealing(10, restarts=True))
    opt2.state["neval"] = 10
    assert abs(opt2.current_lr() - 1.0) < 1e-6
    opt2.state["neval"] = 25
    assert abs(opt2.current_lr() - 0.5) < 1e-6
