"""Pallas paged-attention decode kernel (ISSUE 11) — interpret mode.

Kernel discipline (kernels/flash_attention.py's): the dense
``Attention._paged_gather_attend`` einsum is the ORACLE — the kernel
must match it to ulps on logits and bitwise on greedy argmax across the
serving shapes (S=1 decode, S>1 chunked prefill / speculative verify,
GQA and MHA, scattered tables, null-table padded slots). The dispatch
seam (``parallel.flash.paged_attention``) is gated by
``BIGDL_TPU_PAGED_ATTN`` with the dense path as fallback; the
trace-count spy proves which path built the program.
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.kernels import paged_attention as pk
from bigdl_tpu.parallel import flash as pf


def _dense_ref(q, kp, vp, tables, pos):
    """The gathered-view einsum, standalone (mirrors
    Attention._paged_gather_attend for arbitrary head counts)."""
    B, nH, S, D = q.shape
    kvH, bs = kp.shape[1], kp.shape[2]
    G = nH // kvH
    kg = jnp.moveaxis(kp[tables], 2, 1)
    vg = jnp.moveaxis(vp[tables], 2, 1)
    t = tables.shape[1] * bs
    kg = kg.reshape(B, kvH, t, D)
    vg = vg.reshape(B, kvH, t, D)
    pos_s = pos[:, None] + jnp.arange(S)[None, :]
    keep = (jnp.arange(t)[None, None, :] <= pos_s[:, :, None])
    if G > 1:
        qg = q.reshape(B, kvH, G, S, D)
        logits = jnp.einsum("bkgsd,bktd->bkgst", qg, kg) / math.sqrt(D)
        logits = jnp.where(keep[:, None, None], logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bkgst,bktd->bkgsd", w, vg).reshape(B, nH, S, D)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, kg) / math.sqrt(D)
    logits = jnp.where(keep[:, None], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", w, vg)


def _case(rng, B, nH, kvH, S, D, bs, nblk):
    NB = 1 + B * nblk
    kp = jnp.asarray(rng.randn(NB, kvH, bs, D).astype(np.float32))
    vp = jnp.asarray(rng.randn(NB, kvH, bs, D).astype(np.float32))
    tables = np.zeros((B, nblk), np.int32)
    for b in range(B):
        tables[b] = rng.permutation(np.arange(1, NB))[:nblk]
    pos = rng.randint(0, nblk * bs - S, size=B).astype(np.int32)
    q = jnp.asarray(rng.randn(B, nH, S, D).astype(np.float32))
    return q, kp, vp, jnp.asarray(tables), jnp.asarray(pos)


@pytest.mark.parametrize("B,nH,kvH,S,D,bs,nblk", [
    (3, 4, 2, 1, 8, 4, 6),    # GQA decode step
    (2, 4, 4, 1, 16, 8, 4),   # MHA decode step
    (2, 4, 2, 8, 8, 4, 8),    # chunked prefill (S = chunk)
    (1, 8, 2, 5, 64, 16, 4),  # speculative verify (S = k+1), wide head
])
def test_kernel_matches_dense_oracle_ulp(B, nH, kvH, S, D, bs, nblk):
    rng = np.random.RandomState(hash((B, nH, S)) % 2**31)
    q, kp, vp, tables, pos = _case(rng, B, nH, kvH, S, D, bs, nblk)
    want = _dense_ref(q, kp, vp, tables, pos)
    got = pk.paged_decode_attention(q, kp, vp, tables, pos,
                                    interpret=True)
    err = float(jnp.max(jnp.abs(want - got)))
    scale = float(jnp.max(jnp.abs(want)))
    assert err <= 4e-6 * max(scale, 1.0), (err, scale)


def test_kernel_null_table_padded_slot_no_nan():
    """A padded slot (null table, pos 0) must produce finite output —
    its rows are garbage the scheduler never reads, but a NaN would
    poison the whole batch through the shared program."""
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 4, 1, 8).astype(np.float32))
    kp = jnp.asarray(rng.randn(5, 2, 4, 8).astype(np.float32))
    vp = jnp.asarray(rng.randn(5, 2, 4, 8).astype(np.float32))
    tables = jnp.asarray(np.array([[1, 2, 0], [0, 0, 0]], np.int32))
    pos = jnp.asarray(np.array([6, 0], np.int32))
    out = pk.paged_decode_attention(q, kp, vp, tables, pos,
                                    interpret=True)
    assert bool(jnp.isfinite(out).all())
    want = _dense_ref(q, kp, vp, tables, pos)
    assert float(jnp.max(jnp.abs(out - want))) < 1e-5


def test_kernel_greedy_argmax_bitwise_through_projection():
    """The serving gate in miniature: project kernel/dense attention
    outputs through a vocab head — greedy argmax must agree exactly
    (the online-softmax ulps never flip a token)."""
    rng = np.random.RandomState(3)
    q, kp, vp, tables, pos = _case(rng, 4, 4, 2, 1, 16, 8, 6)
    wo = jnp.asarray(rng.randn(4 * 16, 48).astype(np.float32))
    dense = _dense_ref(q, kp, vp, tables, pos)
    kern = pk.paged_decode_attention(q, kp, vp, tables, pos,
                                     interpret=True)
    to_logits = lambda o: o.transpose(0, 2, 1, 3).reshape(4, 1, -1) @ wo
    assert np.array_equal(
        np.asarray(jnp.argmax(to_logits(dense), -1)),
        np.asarray(jnp.argmax(to_logits(kern), -1)))


def test_dispatch_gating_and_trace_spy(monkeypatch):
    """BIGDL_TPU_PAGED_ATTN routes the seam: off/auto-on-CPU -> dense
    (no kernel trace), interpret -> kernel (trace count bumps); a
    kernel failure falls back to the dense value, never raises."""
    rng = np.random.RandomState(1)
    q, kp, vp, tables, pos = _case(rng, 2, 4, 2, 1, 8, 4, 4)
    dense = lambda: _dense_ref(q, kp, vp, tables, pos)
    want = dense()

    monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "off")
    t0 = pk.trace_count()
    out = pf.paged_attention(q, kp, vp, tables, pos, dense)
    assert pk.trace_count() == t0
    assert np.array_equal(np.asarray(out), np.asarray(want))

    monkeypatch.delenv("BIGDL_TPU_PAGED_ATTN", raising=False)
    out = pf.paged_attention(q, kp, vp, tables, pos, dense)   # auto=dense on CPU
    assert pk.trace_count() == t0

    monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "interpret")
    out = pf.paged_attention(q, kp, vp, tables, pos, dense)
    assert pk.trace_count() == t0 + 1, "spy: the Pallas path must trace"
    assert float(jnp.max(jnp.abs(out - want))) < 1e-5

    # fallback: a kernel that raises degrades to the dense value, loudly
    def boom(*a, **kw):
        raise RuntimeError("injected kernel failure")
    monkeypatch.setattr(pk, "paged_decode_attention", boom)
    out = pf.paged_attention(q, kp, vp, tables, pos, dense)
    assert np.array_equal(np.asarray(out), np.asarray(want))


def test_dispatch_counters_exported(monkeypatch):
    from bigdl_tpu import observability as obs
    obs.enable()
    try:
        rng = np.random.RandomState(2)
        q, kp, vp, tables, pos = _case(rng, 2, 4, 2, 1, 8, 4, 4)
        dense = lambda: _dense_ref(q, kp, vp, tables, pos)
        monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "interpret")
        pf.paged_attention(q, kp, vp, tables, pos, dense)
        assert obs.registry().get("kernels/paged_attn_programs").value >= 1
        monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "off")
        pf.paged_attention(q, kp, vp, tables, pos, dense)
        assert obs.registry().get(
            "kernels/paged_attn_dense_programs").value >= 1
    finally:
        obs.disable()


def test_kernel_under_jit_compiles_once_per_shape():
    rng = np.random.RandomState(4)
    q, kp, vp, tables, pos = _case(rng, 2, 4, 2, 1, 8, 4, 4)
    f = jax.jit(lambda *a: pk.paged_decode_attention(*a, interpret=True))
    t0 = pk.trace_count()
    a = f(q, kp, vp, tables, pos)
    b = f(q, kp, vp, tables, pos + 1)   # same shapes -> no re-trace
    assert pk.trace_count() == t0 + 1
    assert a.shape == b.shape == q.shape
