"""pyspark module-path parity: every import line a reference user script
uses must work after `bigdl` -> `bigdl_tpu` (docs/MIGRATION.md's
one-line rename contract)."""
import numpy as np


def test_nn_layer_path_trains():
    from bigdl_tpu.nn.layer import Linear, Sequential, ReLU
    m = Sequential(); m.add(Linear(2, 4)); m.add(ReLU())
    assert np.asarray(m.forward(np.ones((3, 2), "float32"))).shape == (3, 4)


def test_criterion_and_optimizer_paths():
    from bigdl_tpu.nn.criterion import ClassNLLCriterion
    from bigdl_tpu.optim.optimizer import Optimizer, SGD  # noqa: F401
    assert ClassNLLCriterion is not None


def test_initialization_method_path():
    from bigdl_tpu.nn.initialization_method import (Xavier, MsraFiller,
                                                    Zeros, Ones,
                                                    RandomUniform,
                                                    RandomNormal,
                                                    ConstInitMethod,
                                                    BilinearFiller)
    from bigdl_tpu.nn.init import Xavier as X2
    assert Xavier is X2


def test_transform_vision_image_path():
    from bigdl_tpu.transform.vision import Resize
    from bigdl_tpu.transform.vision.image import (Resize as R2, RandomCrop,
                                                  ChannelNormalize, HFlip)
    assert Resize is R2
    # and the transforms still run through the parity path (pipeline
    # protocol: a transformer maps an iterable of images)
    img = (np.random.rand(10, 12, 3) * 255).astype(np.float32)
    out = next(iter(Resize(6, 8)([img])))
    assert out.shape[:2] == (6, 8)


def test_util_common_path():
    from bigdl_tpu.util.common import init_engine, JTensor, Sample  # noqa


def test_dlframes_paths():
    from bigdl_tpu.dlframes.dl_classifier import (DLEstimator, DLModel,
                                                  DLClassifier,
                                                  DLClassifierModel)  # noqa
    from bigdl_tpu.dlframes.dl_image_transformer import DLImageTransformer
    from bigdl_tpu.dlframes import DLClassifier as C2
    assert DLClassifier is C2


def test_dataset_sentence_and_base_paths(tmp_path):
    from bigdl_tpu.dataset.sentence import (read_localfile, sentences_split,
                                            sentences_bipadding,
                                            sentence_tokenizer)
    p = tmp_path / "t.txt"
    p.write_text("One line.\nTwo.\n")
    assert len(read_localfile(str(p))) == 2
    assert sentences_split("A b. C d! E?") == ["A b.", "C d!", "E?"]
    assert sentences_bipadding("x").startswith("SENTENCESTART ")
    assert sentence_tokenizer("don't stop.") == ["don't", "stop", "."]

    from bigdl_tpu.dataset.base import Progbar, maybe_download
    Progbar(10, verbose=0).update(5)
    f = tmp_path / "have.bin"
    f.write_bytes(b"x")
    assert maybe_download("have.bin", str(tmp_path), "http://x/") == str(f)
    import pytest
    with pytest.raises(FileNotFoundError, match="gated"):
        maybe_download("missing.bin", str(tmp_path), "http://x/")


def test_optimizer_reuse_and_persistence_surface(tmp_path):
    """pyspark Optimizer conveniences: create factory, set_model/
    set_criterion/set_traindata reuse, prepare_input, OptimMethod
    save/load round-trip."""
    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD, Adam, Trigger
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.optim.optim_method import OptimMethod
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample

    rng = np.random.RandomState(0)
    samples = [Sample.from_ndarray(rng.randn(4).astype(np.float32),
                                   float(rng.randint(1, 3)))
               for _ in range(16)]
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    opt = Optimizer.create(model, DataSet.array(samples),
                           nn.ClassNLLCriterion(), batch_size=16,
                           end_trigger=Trigger.max_epoch(1))
    opt.prepare_input()
    opt.optimize()

    # reuse: swap model/criterion/data and train again — progress counters
    # must reset or the second optimize() stops at the old end-trigger
    assert opt.optim_method.state["epoch"] > 1
    m2 = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    opt.set_model(m2).set_criterion(nn.ClassNLLCriterion())
    assert opt.optim_method.state == {"neval": 0, "epoch": 1}
    opt.set_traindata(DataSet.array(samples), batch_size=8)
    opt.optimize()
    assert m2.params is not None
    assert opt.optim_method.state["neval"] >= 2  # a FULL epoch retrained

    # summary triggers actually gate recording
    from bigdl_tpu.visualization import TrainSummary
    from bigdl_tpu.optim import several_iteration
    ts = TrainSummary(str(tmp_path), "app")
    ts.set_summary_trigger("LearningRate", several_iteration(1000))
    m3 = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    opt.set_model(m3)
    opt.set_train_summary(ts)
    opt.optimize()
    assert len(ts.read_scalar("Loss")) >= 2          # ungated: every step
    assert len(ts.read_scalar("LearningRate")) == 0  # gated off

    # OptimMethod persistence keeps hyper-params and step state
    a = Adam(learningrate=0.0123)
    a.state["neval"] = 7
    p = str(tmp_path / "adam.bin")
    a.save(p)
    b = OptimMethod.load(p)
    assert isinstance(b, Adam)
    assert b.learningrate == 0.0123 and b.state["neval"] == 7
    import pytest
    with pytest.raises(IOError):
        a.save(p, overwrite=False)


def test_nn_keras_paths():
    import numpy as np
    from bigdl_tpu.nn.keras.layer import Dense
    from bigdl_tpu.nn.keras.topology import Sequential
    m = Sequential()
    m.add(Dense(4, input_shape=(3,)))
    out = m.predict(np.ones((2, 3), "float32"))
    assert np.asarray(out).shape == (2, 4)


def test_util_tf_utils_path():
    """bigdl.util.tf_utils parity: convert() builds a native module from
    a real-TF GraphDef (cross-validated like the loaders)."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.util.tf_utils import convert, dump_model

    tf1 = tf.compat.v1
    g = tf1.Graph()
    with g.as_default():
        x = tf1.placeholder(tf.float32, (None, 4), name="x")
        w = tf1.constant(np.random.RandomState(0).randn(4, 3),
                         tf.float32)
        y = tf1.nn.relu(tf1.matmul(x, w), name="y")
    m = convert(["x:0"], ["y:0"], graph_def=g.as_graph_def())
    xin = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    with tf1.Session(graph=g) as sess:
        ref = sess.run(y, {x: xin})
    out = np.asarray(m.evaluate().forward(xin))
    assert np.allclose(out, ref, atol=1e-5)
    with pytest.raises(NotImplementedError, match="MIGRATION"):
        dump_model("/tmp/x")

    # variables + a session: convert() freezes their live values; op
    # objects (not just "name:0" strings) are accepted like the reference
    g2 = tf1.Graph()
    with g2.as_default():
        x2 = tf1.placeholder(tf.float32, (None, 4), name="x2")
        wv = tf1.get_variable(
            "wv", initializer=np.random.RandomState(2).randn(4, 3)
            .astype(np.float32))
        y2 = tf1.identity(tf1.matmul(x2, wv), name="y2")
        with tf1.Session(graph=g2) as sess:
            sess.run(tf1.global_variables_initializer())
            ref2 = sess.run(y2, {x2: xin})
            m2 = convert([x2.op], [y2.op], graph_def=g2.as_graph_def(),
                         sess=sess)
    out2 = np.asarray(m2.evaluate().forward(xin))
    assert np.allclose(out2, ref2, atol=1e-5)
