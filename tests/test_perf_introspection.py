"""The performance introspection plane (PR 7): compiled-program
artifacts at every compile-site kind, live MFU gauges vs the offline
bench math, cluster metric aggregation with straggler attribution, and
the perf-regression gate's exit codes."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu import observability as obs
from bigdl_tpu.observability import cluster, perf

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_perf(monkeypatch, tmp_path):
    """Isolated observability + artifact registry + flight dir; peak
    FLOPs pinned so MFU is well-defined on CPU."""
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("BIGDL_TPU_PEAK_FLOPS", "1e9")
    monkeypatch.delenv("BIGDL_TPU_METRIC_SNAP_S", raising=False)
    obs.disable()
    obs.reset()
    obs.registry().reset()
    perf.reset()
    yield
    obs.disable()
    obs.reset()
    obs.registry().reset()
    perf.reset()


def _mlp(d_in=8):
    return nn.Sequential(nn.Linear(d_in, 16), nn.ReLU(), nn.Linear(16, 1))


def _train(steps=6, superstep=1, batch=16, model=None):
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import max_iteration
    rng = np.random.RandomState(0)
    x = rng.randn(steps * batch, 8).astype(np.float32)
    y = rng.randn(steps * batch, 1).astype(np.float32)
    opt = LocalOptimizer(model or _mlp(), (x, y), nn.MSECriterion(),
                         optim_method=SGD(learningrate=0.01),
                         end_trigger=max_iteration(steps),
                         batch_size=batch)
    if superstep > 1:
        opt.set_superstep(superstep)
    opt.optimize()
    return opt


# ------------------------------------------------- artifact capture

def test_optimizer_step_records_artifact():
    obs.enable()
    _train(steps=4)
    arts = [a for a in perf.registry().artifacts()
            if a.name == "optim/step"]
    assert len(arts) == 1, arts
    a = arts[0]
    assert a.kind == "train_step" and a.steps_per_program == 1
    assert a.compile_seconds > 0
    assert a.input_shapes, a.to_dict()
    # CPU XLA exposes cost analysis: FLOPs and memory present
    assert a.flops and a.flops > 0
    assert a.resident_bytes() and a.resident_bytes() > 0
    assert a.degraded is None
    # mirrored into the metrics registry for the exporters
    assert obs.registry().counter("compile/programs").value == 1


def test_superstep_program_records_k():
    obs.enable()
    _train(steps=4, superstep=2)
    a = perf.registry().latest("optim/step")
    assert a is not None and a.steps_per_program == 2
    # the [K, batch, ...] stack is visible in the recorded shapes
    assert any(s.startswith("(2, ") for s in a.input_shapes), \
        a.input_shapes


def test_evaluator_forward_records_artifacts():
    from bigdl_tpu.optim.evaluator import Evaluator
    from bigdl_tpu.optim.validation import Loss, Top1Accuracy
    obs.enable()
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m.ensure_initialized()
    rng = np.random.RandomState(1)
    x = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(1, 5, (32,)).astype(np.int64)
    from bigdl_tpu.dataset.dataset import DataSet
    ds = DataSet.from_arrays(x, y)
    Evaluator(m).evaluate(ds, [Top1Accuracy()], batch_size=16)
    names = {a.name for a in perf.registry().artifacts()}
    assert "eval/forward_stats" in names, names


def test_predictor_and_serving_warmup_record_bucket_artifacts():
    from bigdl_tpu.serving import ServingEngine
    from bigdl_tpu.optim.predictor import shape_buckets
    obs.enable()
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    m.ensure_initialized()
    eng = ServingEngine(m, input_shape=(4,), max_batch=8, warmup=True)
    with eng:
        eng.predict(np.zeros(4, np.float32), timeout=30)
    fwd_arts = [a for a in perf.registry().artifacts()
                if a.name.startswith("predict/forward")]
    # one artifact per warmup bucket; the live request reuses bucket 1
    assert len(fwd_arts) == len(shape_buckets(8)), fwd_arts
    assert all(a.kind == "forward" for a in fwd_arts)


def test_disabled_observability_records_nothing():
    _train(steps=3)
    assert perf.registry().artifacts() == []
    assert obs.registry().get("perf/mfu") is None


def test_analyze_compiled_degrades_without_apis():
    class NoApis:
        pass

    class RaisingApis:
        def cost_analysis(self):
            raise NotImplementedError("backend says no")

        def memory_analysis(self):
            raise NotImplementedError

    assert perf.analyze_compiled(NoApis()) == {}
    assert perf.analyze_compiled(RaisingApis()) == {}
    art = perf.record_compiled("x", "forward", NoApis())
    assert art.degraded and art.flops is None


def test_instrumented_jit_falls_back_when_lowering_breaks():
    obs.enable()

    class BrokenLower:
        def __init__(self, fn):
            self._fn = jax.jit(fn)

        def __call__(self, *args):
            return self._fn(*args)

        def lower(self, *args):
            raise RuntimeError("no AOT on this backend")

    wrapped = perf.instrument_jit(BrokenLower(lambda x: x * 2),
                                  name="t/broken", kind="forward")
    out = wrapped(jnp.ones((3,)))
    assert np.allclose(np.asarray(out), 2.0)
    art = perf.registry().latest("t/broken")
    assert art is not None and art.degraded  # recorded the degradation
    # permanently broken: later calls go straight through the jit path
    assert np.allclose(np.asarray(wrapped(jnp.ones((3,)))), 2.0)
    assert len(perf.registry().artifacts()) == 1


def test_instrumented_jit_one_compile_per_shape():
    obs.enable()
    calls = {"n": 0}

    def f(x):
        calls["n"] += 1  # traced once per distinct shape
        return x + 1

    wrapped = perf.instrument_jit(jax.jit(f), name="t/shapes",
                                  kind="forward")
    for _ in range(3):
        wrapped(jnp.ones((4,)))
    wrapped(jnp.ones((8,)))
    assert wrapped.compiled_shape_count() == 2
    assert len(perf.registry().artifacts()) == 2
    assert wrapped.last_artifact is perf.registry().artifacts()[-1]


# ------------------------------------------------------- live MFU

def test_live_mfu_agrees_with_offline_bench_math():
    """The acceptance bar: perf/mfu_mean within 10% of the MFU computed
    offline the way bench.py computes it — XLA cost-analysis FLOPs of
    the SAME compiled program over the measured step wall time, against
    the same peak table."""
    obs.enable()
    opt = _train(steps=8)
    reg = obs.registry()
    live = reg.gauge("perf/mfu_mean").value
    assert live > 0

    art = perf.registry().latest("optim/step")
    # offline: bench.py's formula — flops * dispatches / wall / peak —
    # over the measured (non-compile) FULL iteration walls (fetch +
    # step: the gauge divides by the whole iteration so async sync
    # policies can't flatter it)
    walls = [d + s for d, s in zip(opt.metrics.values["data_time"][1:],
                                   opt.metrics.values["step_time"][1:])]
    offline = (art.flops * len(walls)) / sum(walls) / perf.peak_flops("")
    assert live == pytest.approx(offline, rel=0.10), (live, offline)
    # instantaneous gauge and flops throughput exist alongside
    assert reg.gauge("perf/mfu").value > 0
    assert reg.gauge("perf/model_flops_per_s").value > 0


def test_live_mfu_flops_match_independent_aot_compile():
    """The artifact's FLOPs equal an independent AOT cost analysis of
    an equivalent program — the live gauge inherits XLA's number, not a
    hand-rolled estimate."""
    obs.enable()
    _train(steps=3)
    art = perf.registry().latest("optim/step")
    assert art.flops > 0
    # independent: any second compile of the same-shape step must agree
    # to within float noise; sanity-bound against the analytic FLOPs of
    # the MLP instead of recompiling the whole step (fwd+bwd+SGD of an
    # 8->16->1 MLP at batch 16 is O(10k) flops, not O(1M))
    assert 1e3 < art.flops < 1e6


def test_phase_decomposition_fractions():
    obs.enable()
    _train(steps=6)
    reg = obs.registry()
    host = reg.gauge("perf/phase_host_frac").value
    disp = reg.gauge("perf/phase_dispatch_frac").value
    dev = reg.gauge("perf/phase_device_frac").value
    for v in (host, disp, dev):
        assert 0.0 <= v <= 1.0, (host, disp, dev)
    assert host + disp + dev == pytest.approx(1.0, abs=0.05), \
        (host, disp, dev)


def test_peak_flops_table_and_env_override(monkeypatch):
    monkeypatch.delenv("BIGDL_TPU_PEAK_FLOPS", raising=False)
    assert perf.peak_flops("TPU v5 lite") == 197.0e12
    assert perf.peak_flops("TPU v5p chip") == 459.0e12
    assert perf.peak_flops("unknown cpu") == perf.DEFAULT_PEAK_FLOPS
    monkeypatch.setenv("BIGDL_TPU_PEAK_FLOPS", "2.5e12")
    assert perf.peak_flops("TPU v5 lite") == 2.5e12


def test_step_perf_peak_unsticks_when_env_unset(monkeypatch):
    """A smoke-phase BIGDL_TPU_PEAK_FLOPS override must not survive
    unsetting the env in the same process (a cached 1e9 would read MFU
    ~200,000x high on the real chip)."""
    sp = perf._StepPerf()
    monkeypatch.setenv("BIGDL_TPU_PEAK_FLOPS", "1e9")
    assert sp.peak() == 1e9
    monkeypatch.delenv("BIGDL_TPU_PEAK_FLOPS")
    assert sp.peak() == perf.peak_flops("")  # re-resolved from the table
    monkeypatch.setenv("BIGDL_TPU_PEAK_FLOPS", "3e9")
    assert sp.peak() == 3e9  # and a CHANGED override re-resolves too


def test_clamped_superstep_artifact_records_its_own_k():
    """A checkpoint trigger firing mid-group clamps the dispatch to a
    j<K prefix, which compiles a SEPARATE program — its artifact must
    record j steps, not the configured K (flops_per_step would read
    K/j-fold low otherwise)."""
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import max_iteration
    import tempfile
    obs.enable()
    rng = np.random.RandomState(0)
    x = rng.randn(96, 8).astype(np.float32)
    y = rng.randn(96, 1).astype(np.float32)
    opt = LocalOptimizer(_mlp(), (x, y), nn.MSECriterion(),
                         optim_method=SGD(learningrate=0.01),
                         end_trigger=max_iteration(6), batch_size=16)
    opt.set_superstep(4)
    # checkpoint at every 2nd iteration: groups clamp to 2-step prefixes
    from bigdl_tpu.optim.trigger import several_iteration
    opt.set_checkpoint(several_iteration(2), tempfile.mkdtemp())
    opt.optimize()
    ks = sorted({a.steps_per_program for a in perf.registry().artifacts()
                 if a.name == "optim/step"})
    assert ks == [2], ks  # every dispatched program really ran 2 steps
    for a in perf.registry().artifacts():
        if a.name == "optim/step":
            assert any(s.startswith("(2, ") for s in a.input_shapes)


def test_bench_peak_table_is_the_shared_one():
    """bench.py's offline MFU and the live gauge read the same table."""
    sys.path.insert(0, _REPO)
    try:
        import bench
        assert bench._peak_flops("TPU v5 lite") == \
            perf.peak_flops("TPU v5 lite")
    finally:
        sys.path.remove(_REPO)


# ------------------------------------------- artifact dump + report

def test_dump_artifacts_and_xla_report_round_trip(tmp_path):
    obs.enable()
    _train(steps=3)
    obs.registry().gauge("mem/device_peak_bytes", unit="bytes").set(1e9)
    path = perf.dump_artifacts()
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["schema"] == perf.ARTIFACT_SCHEMA
    assert any(p["name"] == "optim/step" for p in doc["programs"])
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "xla_report.py"),
         path], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "optim/step" in proc.stdout
    assert "HBM headroom" in proc.stdout
    # unreadable dump: exit 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "xla_report.py"),
         str(bad)], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2


def test_crash_bundle_carries_programs():
    from bigdl_tpu.observability import flight
    obs.enable()
    _train(steps=2)
    bundle = flight.crash_bundle(error=RuntimeError("x"))
    assert any(p["name"] == "optim/step" for p in bundle["programs"])


# ------------------------------------------------- cluster metrics

def _write_snapshot(d, idx, step_time_mean, hb_age=0.5, step=100,
                    final=False):
    """A per-process snapshot file in the writer's exact schema."""
    doc = {
        "final": final,
        "schema": cluster.SNAPSHOT_SCHEMA,
        "written_at": time.time(),
        "pid": 1000 + idx,
        "process_index": idx,
        "step": step,
        "metrics": {
            "optim/step_time": {"type": "histogram", "unit": "",
                                "count": 10, "sum": step_time_mean * 10,
                                "mean": step_time_mean,
                                "min": step_time_mean,
                                "max": step_time_mean, "quantiles": {}},
            "optim/throughput": {"type": "gauge", "unit": "samples/s",
                                 "value": 16.0 / step_time_mean},
            "failure/last_beat_age_s": {"type": "gauge", "unit": "s",
                                        "value": hb_age},
        },
    }
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"metrics_p{idx:05d}.json"), "w") as f:
        json.dump(doc, f)


def test_snapshot_writer_cadence_and_atomicity(tmp_path):
    w = cluster.MetricSnapshotWriter(every_s=3600, directory=str(tmp_path),
                                     process_index=7)
    obs.registry().counter("optim/steps").inc(5)
    assert w.maybe_write(step=5)  # first call writes immediately
    assert w.maybe_write(step=6) is None  # cadence not elapsed
    assert w.writes == 1
    snaps = cluster.read_snapshots(str(tmp_path))
    assert len(snaps) == 1 and snaps[0]["process_index"] == 7
    assert snaps[0]["step"] == 5
    assert snaps[0]["metrics"]["optim/steps"]["value"] == 5
    # zero interval: disabled entirely
    w0 = cluster.MetricSnapshotWriter(every_s=0, directory=str(tmp_path))
    assert w0.maybe_write() is None and not w0.enabled


def test_rank0_aggregation_attributes_injected_straggler(tmp_path):
    d = str(tmp_path)
    _write_snapshot(d, 0, 0.010)
    _write_snapshot(d, 1, 0.011)
    _write_snapshot(d, 2, 0.033, hb_age=120.0)  # slow AND stale: dying
    # a torn write from a dying peer is skipped, not fatal
    with open(os.path.join(d, "metrics_p00003.json"), "w") as f:
        f.write('{"schema": "bigdl_tpu.metric_snapshot.v1", "wri')
    view = cluster.aggregate(d)
    assert view["n_processes"] == 3
    assert view["step_time_skew"] == pytest.approx(3.0, rel=0.01)
    assert len(view["stragglers"]) == 1
    s = view["stragglers"][0]
    assert s["process_index"] == 2 and s["suspect_dead"] is True
    assert s["heartbeat_age_s"] == 120.0

    out = cluster.write_aggregate(d, context={"elastic_attempt": 1})
    assert out and os.path.exists(out)
    saved = json.load(open(out))
    assert saved["context"]["elastic_attempt"] == 1
    assert cluster.latest_aggregate(d) == out
    # headline numbers mirrored for the local exporters
    assert obs.registry().gauge("cluster/stragglers").value == 1


def test_finished_process_not_attributed_as_suspect_dead(tmp_path):
    """ISSUE 15 satellite: a replica process that exited CLEANLY writes
    a terminal ``final: true`` snapshot — its step-time mean freezes
    and its heartbeat age grows forever, which used to read exactly
    like a wedged process. The aggregate must attribute the WEDGED
    writer (no final marker, slow, stale heartbeat) and skip the
    finished one."""
    d = str(tmp_path)
    _write_snapshot(d, 0, 0.010)
    _write_snapshot(d, 3, 0.011)
    # finished: slow-looking frozen mean + very stale heartbeat, but
    # terminal final:true — retired, not dying
    _write_snapshot(d, 1, 0.060, hb_age=500.0, final=True)
    # wedged: same signature WITHOUT the final marker — a real suspect
    _write_snapshot(d, 2, 0.060, hb_age=500.0)
    view = cluster.aggregate(d)
    assert view["n_processes"] == 4
    by_idx = {r["process_index"]: r for r in view["processes"]}
    assert by_idx[1]["final"] is True and by_idx[2]["final"] is False
    assert [s["process_index"] for s in view["stragglers"]] == [2]
    assert view["stragglers"][0]["suspect_dead"] is True

    # the writer's own terminal write carries the marker
    w = cluster.MetricSnapshotWriter(every_s=3600, directory=d,
                                     process_index=7)
    w.write(step=9, final=True)
    snaps = {s["process_index"]: s for s in cluster.read_snapshots(d)}
    assert snaps[7]["final"] is True
    assert snaps[0].get("final", False) is False


def test_snapshot_writer_extra_sections(tmp_path):
    """MetricSnapshotWriter.add_section: a registered provider's dict
    lands in every snapshot under its name (the fleet agent's
    ``serving`` section rides this); a raising provider is skipped, a
    core-field collision is refused."""
    w = cluster.MetricSnapshotWriter(every_s=3600, directory=str(tmp_path),
                                     process_index=3)
    w.add_section("serving", lambda: {"queue_depth": 4,
                                      "active_version": "v1"})
    w.add_section("broken", lambda: 1 / 0)
    with pytest.raises(ValueError, match="collides"):
        w.add_section("metrics", dict)
    w.write(step=1)
    snap = cluster.read_snapshots(str(tmp_path))[0]
    assert snap["serving"] == {"queue_depth": 4, "active_version": "v1"}
    assert "broken" not in snap


def test_cluster_report_tool_round_trip(tmp_path):
    d = str(tmp_path)
    _write_snapshot(d, 0, 0.010)
    _write_snapshot(d, 1, 0.040, hb_age=99.0)
    prom = os.path.join(d, "cluster.prom")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cluster_report.py"),
         d, "--prom", prom], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "stragglers: 1" in proc.stdout
    assert "DYING" in proc.stdout
    text = open(prom).read()
    assert 'bigdl_cluster_step_time_mean_s{process="1"} 0.04' in text
    assert "bigdl_cluster_step_time_skew" in text
    # empty dir: exit 2 (nothing to merge)
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "cluster_report.py"),
         str(empty)], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2


def test_optimizer_ticks_snapshots_under_env(monkeypatch, tmp_path):
    d = str(tmp_path / "snaps")
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", d)
    monkeypatch.setenv("BIGDL_TPU_METRIC_SNAP_S", "0.01")
    obs.enable()
    _train(steps=4)
    snaps = cluster.read_snapshots(d)
    assert len(snaps) == 1  # one process, latest-state file
    assert snaps[0]["step"] == 4  # terminal snapshot carries end state


def test_elastic_restart_writes_cluster_aggregate(monkeypatch, tmp_path):
    """ElasticRunner merges the per-process snapshots at every restart
    (one coherent timeline across the reshape)."""
    from bigdl_tpu.parallel.elastic import ElasticRunner
    from bigdl_tpu.parallel.failure import TrainingHalted
    d = str(tmp_path / "flight")
    monkeypatch.setenv("BIGDL_TPU_FLIGHT_DIR", d)
    _write_snapshot(d, 0, 0.02)

    class FakeOpt:
        def __init__(self):
            self.calls = 0

        def load_checkpoint(self, p):
            pass

        def optimize(self):
            raise TrainingHalted(cause="stall", failure_class="permanent",
                                 checkpoint_path=None, bundle_path=None,
                                 epoch=1, neval=3, lost_processes=())

    class Dev:
        process_index = 0

    runner = ElasticRunner(lambda devices, attempt: FakeOpt(),
                           checkpoint_dir=str(tmp_path / "ckpt"),
                           max_restarts=1, devices=[Dev()],
                           backoff_s=0.0)
    with pytest.raises(TrainingHalted):
        runner.run()
    assert runner.restarts == 1
    agg = cluster.latest_aggregate(d)
    assert agg is not None
    saved = json.load(open(agg))
    # both halts post-mortem: the restart (attempt 0) and the terminal
    # budget exhaustion (attempt 1) each merged a view; latest wins
    assert saved["context"]["elastic_attempt"] == 1
    assert saved["context"]["cause"] == "stall"


# ------------------------------------------------- perf gate

def _gate(args, **kw):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "perf_gate.py")]
        + args, capture_output=True, text=True, timeout=120, **kw)


def _metrics_file(tmp_path, name, rows):
    p = tmp_path / name
    p.write_text(json.dumps(rows))
    return str(p)


_ROWS = [
    {"metric": "bench/x_images_per_sec", "value": 100.0,
     "unit": "images/sec/chip", "kind": "gauge"},
    {"metric": "bench/y_p99_ms", "value": 20.0, "unit": "ms",
     "kind": "gauge"},
    {"metric": "bench/x_images_per_sec/mfu", "value": 0.30, "unit": "",
     "kind": "gauge"},
    {"metric": "bench/x_images_per_sec/vs_baseline", "value": 1.7,
     "unit": "", "kind": "gauge"},  # provenance: not gated
]


def test_perf_gate_pass_fail_exit_codes(tmp_path):
    cur = _metrics_file(tmp_path, "cur.json", _ROWS)
    base = str(tmp_path / "base.json")
    assert _gate(["--current", cur, "--baseline", base,
                  "--update"]).returncode == 0
    # identical metrics: pass
    assert _gate(["--current", cur, "--baseline", base]).returncode == 0

    # >= 20% throughput regression: fail (band is 15%)
    worse = [dict(r) for r in _ROWS]
    worse[0]["value"] = 79.0
    cur2 = _metrics_file(tmp_path, "cur2.json", worse)
    p = _gate(["--current", cur2, "--baseline", base])
    assert p.returncode == 1
    assert "bench/x_images_per_sec" in p.stderr

    # within the band: pass
    ok = [dict(r) for r in _ROWS]
    ok[0]["value"] = 90.0
    cur3 = _metrics_file(tmp_path, "cur3.json", ok)
    assert _gate(["--current", cur3, "--baseline", base]).returncode == 0


def test_perf_gate_latency_direction(tmp_path):
    cur = _metrics_file(tmp_path, "cur.json", _ROWS)
    base = str(tmp_path / "base.json")
    _gate(["--current", cur, "--baseline", base, "--update"])
    # p99 RISING 50% is a regression even though the number went up
    worse = [dict(r) for r in _ROWS]
    worse[1]["value"] = 30.0
    cur2 = _metrics_file(tmp_path, "cur2.json", worse)
    p = _gate(["--current", cur2, "--baseline", base])
    assert p.returncode == 1 and "bench/y_p99_ms" in p.stderr
    # p99 dropping is an improvement, not a failure
    better = [dict(r) for r in _ROWS]
    better[1]["value"] = 10.0
    cur3 = _metrics_file(tmp_path, "cur3.json", better)
    p = _gate(["--current", cur3, "--baseline", base])
    assert p.returncode == 0 and "IMPROVED" in p.stdout


def test_perf_gate_missing_files_pass_unless_strict(tmp_path):
    missing = str(tmp_path / "nope.json")
    assert _gate(["--current", missing]).returncode == 0
    assert _gate(["--current", missing, "--strict"]).returncode == 1
    cur = _metrics_file(tmp_path, "cur.json", _ROWS)
    nobase = str(tmp_path / "nobase.json")
    assert _gate(["--current", cur, "--baseline", nobase]).returncode == 0
    assert _gate(["--current", cur, "--baseline", nobase,
                  "--strict"]).returncode == 1


def test_perf_gate_provenance_gauges_not_gated(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(_REPO, "tools", "perf_gate.py"))
    perf_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_gate)
    picked = perf_gate.gated_metrics(_ROWS)
    assert "bench/x_images_per_sec" in picked
    assert "bench/y_p99_ms" in picked
    assert picked["bench/y_p99_ms"]["direction"] == "lower"
    assert "bench/x_images_per_sec/mfu" in picked  # MFU IS perf
    assert "bench/x_images_per_sec/vs_baseline" not in picked


def test_repo_baseline_gates_current_metrics():
    """The committed pin passes against the committed BENCH_METRICS —
    the tier-1 `make perf-gate` contract."""
    p = _gate([], cwd=_REPO)
    assert p.returncode == 0, p.stderr + p.stdout
