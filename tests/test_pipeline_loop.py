"""Pipelined training loop (PR 2): stager equivalence, windowed loss
sync, NaN semantics under lag, thread hygiene, and the data_fetch
collapse acceptance criterion."""
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import nn, observability as obs
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import (LocalOptimizer, SGD, max_iteration, max_epoch,
                             several_iteration, Top1Accuracy)
from bigdl_tpu.optim.staging import (BatchStager, staged,
                                     stager_threads_alive)
from bigdl_tpu.utils import engine


def _flat(tree):
    import jax
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


def _trees_equal(a, b):
    return all(np.array_equal(x, y) for x, y in zip(_flat(a), _flat(b)))


# ---------------------------------------------------------------------------
# equivalence: the staged loop must be bitwise-identical to the serial one
# ---------------------------------------------------------------------------

def _train_lenet(policy, depth, tmp_path, tag):
    """LeNet/MNIST run returning (params, final checkpoint payload)."""
    import pickle, os
    engine.set_seed(11)
    imgs, labels = mnist.load(n_synthetic=128)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    model = LeNet5(10)
    steps = 8
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05, momentum=0.9),
                         max_iteration(steps), batch_size=32)
    ckpt_dir = str(tmp_path / tag)
    opt.set_checkpoint(several_iteration(steps), ckpt_dir)
    opt.set_sync_policy(policy)
    opt.set_prefetch(depth)
    opt.optimize()
    with open(os.path.join(ckpt_dir, "checkpoint.bigdl"), "rb") as f:
        payload = pickle.load(f)
    return model.params, payload


def test_pipelined_loop_bitwise_equivalent(tmp_path):
    """Identical final params AND opt_state vs the serial loop across
    sync policies — the stager/window change WHEN the host observes,
    never what the device computes."""
    ref_params, ref_ckpt = _train_lenet("sync", 0, tmp_path, "serial")
    for i, (policy, depth) in enumerate([("sync", 3), ("async", 3),
                                         ("window:3", 3), ("window:1", 2)]):
        params, ckpt = _train_lenet(policy, depth, tmp_path, f"cfg{i}")
        assert _trees_equal(ref_params, params), (policy, depth)
        assert _trees_equal(ref_ckpt["params"], ckpt["params"]), (policy,
                                                                  depth)
        assert _trees_equal(ref_ckpt["opt_state"], ckpt["opt_state"]), \
            (policy, depth)
    assert stager_threads_alive() == 0


def test_window_policy_validation():
    opt = LocalOptimizer(nn.Linear(2, 1), DataSet.from_arrays(
        np.zeros((4, 2), np.float32), np.zeros((4, 1), np.float32)),
        nn.MSECriterion(), SGD(), max_iteration(1), 2)
    opt.set_sync_policy("window:4")
    assert opt._window_k() == 4
    with pytest.raises(ValueError):
        opt.set_sync_policy("window:0")
    with pytest.raises(ValueError):
        opt.set_sync_policy("window:x")
    with pytest.raises(ValueError):
        opt.set_prefetch(-1)


# ---------------------------------------------------------------------------
# NaN policy semantics under a windowed (lagged) sync
# ---------------------------------------------------------------------------

def _poisoned_dataset(n=64, dim=4, bad=1):
    """Linear-regression samples with `bad` NaN features — exactly one
    poisoned batch per epoch, every other step finite."""
    rng = np.random.RandomState(0)
    xs = rng.randn(n, dim).astype(np.float32)
    ys = (xs @ rng.randn(dim, 1)).astype(np.float32)
    xs[:bad] = np.nan
    return DataSet.array([Sample(x, y) for x, y in zip(xs, ys)])


def test_window_nan_skip_recovers():
    """nan_policy='skip' under window:4: the poisoned batch is observed
    K-1 steps late, counted as a skip, and training still converges to
    finite params (the in-step guard held them safe meanwhile)."""
    ds = _poisoned_dataset()
    m = nn.Linear(4, 1)
    opt = LocalOptimizer(m, ds, nn.MSECriterion(), SGD(learningrate=0.05),
                         max_epoch(3), batch_size=16)
    opt.set_sync_policy("window:4").set_prefetch(3)
    opt.set_nan_policy("skip")
    opt.optimize()
    assert opt.metrics.mean("nan_skips") == 1.0
    assert len(opt.metrics.values["nan_skips"]) >= 1
    assert all(np.isfinite(l).all() for l in _flat(m.params))
    assert np.isfinite(opt.optim_method.state["loss"])
    assert stager_threads_alive() == 0


def test_window_nan_resume_replays_checkpoint(tmp_path):
    """nan_policy='resume' under window:3 replays from the checkpoint
    exactly like the sync loop: in-flight window cleared, counters
    rolled back to the snapshot, run completes finite."""
    ds = _poisoned_dataset()
    m = nn.Linear(4, 1)
    opt = LocalOptimizer(m, ds, nn.MSECriterion(), SGD(learningrate=0.05),
                         max_epoch(2), batch_size=16)
    opt.set_checkpoint(several_iteration(1), str(tmp_path))
    opt.set_sync_policy("window:3").set_prefetch(2)
    opt.set_nan_policy("resume")
    opt.optimize()
    assert len(opt.metrics.values["nan_resumes"]) >= 1
    assert len(opt._loss_window) == 0  # cleared on restore and drained
    assert all(np.isfinite(l).all() for l in _flat(m.params))
    assert stager_threads_alive() == 0


def test_window_nan_on_final_steps_not_swallowed():
    """A NaN still in flight when the loop ends (window larger than the
    remaining steps) must surface in the end-of-run drain."""
    rng = np.random.RandomState(0)
    xs = (rng.randn(32, 4) * 100).astype(np.float32)
    ys = (rng.randn(32, 1) * 100).astype(np.float32)
    ds = DataSet.array([Sample(x, y) for x, y in zip(xs, ys)])
    m = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 1))
    opt = LocalOptimizer(m, ds, nn.MSECriterion(), SGD(learningrate=1e12),
                         max_epoch(1), batch_size=16)  # 2 steps, window 4
    opt.set_sync_policy("window:4").set_prefetch(2)
    with pytest.raises(FloatingPointError):
        opt.optimize()
    assert stager_threads_alive() == 0


# ---------------------------------------------------------------------------
# stager hygiene: shutdown, error transparency, order
# ---------------------------------------------------------------------------

def test_stager_no_thread_leak_on_error_paths():
    """Every optimize() exit — including a FloatingPointError mid-epoch —
    joins the stager thread (asserted over threading.enumerate())."""
    before = {t.ident for t in threading.enumerate()}
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 4).astype(np.float32)
    ds = DataSet.array([Sample(x, x[:1]) for x in xs])
    opt = LocalOptimizer(nn.Linear(4, 1), ds, nn.MSECriterion(),
                         SGD(learningrate=1e20), max_iteration(5), 32)
    opt.set_prefetch(4)
    with pytest.raises(FloatingPointError):
        opt.optimize()
    assert stager_threads_alive() == 0
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.name.startswith("bigdl_tpu")]
    assert leaked == []


def test_stager_propagates_source_errors():
    class Exploding:
        def __iter__(self):
            yield from range(3)
            raise ValueError("decode failed")

    st = BatchStager(Exploding(), lambda v: v * 2, depth=2)
    got = []
    with pytest.raises(ValueError, match="decode failed"):
        for v in st:
            got.append(v)
    assert got == [0, 2, 4]  # order preserved up to the failure
    st.close()
    assert stager_threads_alive() == 0


def test_stager_close_mid_stream_and_serial_fallback():
    st = staged(iter(range(100)), lambda v: v + 1, depth=3)
    assert next(st) == 1
    st.close()  # early shutdown: no hang, no leak
    assert stager_threads_alive() == 0
    # depth 0/1 never spawns a thread but keeps the same surface
    ser = staged(iter(range(3)), lambda v: v + 1, depth=1)
    assert list(ser) == [1, 2, 3]
    ser.close()
    assert stager_threads_alive() == 0


def test_evaluator_predictor_staged_paths():
    from bigdl_tpu.optim.evaluator import Evaluator
    from bigdl_tpu.optim.predictor import Predictor
    imgs, labels = mnist.load(n_synthetic=64)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    model = LeNet5(10)
    model.ensure_initialized()
    res = Evaluator(model, prefetch_depth=3).evaluate(
        ds, [Top1Accuracy()], batch_size=16)
    acc, n = res[0].result()
    assert n == 64
    preds = Predictor(model, prefetch_depth=3).predict(ds, batch_size=16)
    assert preds.shape[0] == 64
    assert stager_threads_alive() == 0


# ---------------------------------------------------------------------------
# acceptance: data_fetch collapses to a queue pop with the stager on
# ---------------------------------------------------------------------------

class _SlowBatches:
    """Batch-level dataset with a fixed per-batch produce delay — a
    stand-in for host-side decode (the realdata JPEG path)."""

    def __init__(self, n_batches, batch, dim, delay):
        rng = np.random.RandomState(0)
        self.xs = [rng.randn(batch, dim).astype(np.float32)
                   for _ in range(n_batches)]
        self.ys = [rng.randn(batch, dim).astype(np.float32)
                   for _ in range(n_batches)]
        self.n_batches, self.batch, self.delay = n_batches, batch, delay

    def size(self):
        return self.n_batches * self.batch

    def batches_per_epoch(self):
        return self.n_batches

    def shuffle(self):
        return self

    def data(self, train=True):
        for x, y in zip(self.xs, self.ys):
            time.sleep(self.delay)
            yield MiniBatch(x, y)


def _mean_fetch_seconds(depth):
    obs.enable()
    obs.reset()
    obs.registry().reset()
    try:
        ds = _SlowBatches(12, 256, 2048, 0.02)
        m = nn.Linear(2048, 2048)  # step compute >> produce delay
        opt = LocalOptimizer(m, ds, nn.MSECriterion(), SGD(learningrate=0.01),
                             max_epoch(1), batch_size=256)
        opt.set_prefetch(depth)
        opt.optimize()
        spans = [s for s in obs.get_tracer().events()
                 if s.name == "step/data_fetch"]
        # 12 real fetches + the exhaustion probe (StopIteration) — drop it
        assert len(spans) == 13
        spans = spans[:-1]
        return sum(s.duration_ns for s in spans) / len(spans) / 1e9
    finally:
        obs.disable()
        obs.reset()
        obs.registry().reset()


def test_stager_collapses_data_fetch_5x():
    """ISSUE 2 acceptance: with the stager (depth >= 2), mean
    step/data_fetch drops >= 5x vs the serial loop when produce time
    overlaps device compute."""
    serial = _mean_fetch_seconds(0)
    staged_t = _mean_fetch_seconds(4)
    assert serial >= 0.02  # sanity: serial pays the produce delay
    assert serial / staged_t >= 5.0, (serial, staged_t)
    assert stager_threads_alive() == 0


# ---------------------------------------------------------------------------
# persistent compile cache wiring
# ---------------------------------------------------------------------------

def test_compile_cache_env_gate_and_entries(tmp_path, monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CACHE", "0")
    prev = engine._state["compile_cache_dir"]
    engine._state["compile_cache_dir"] = None
    try:
        assert engine.maybe_enable_compilation_cache() is None
        assert engine.compilation_cache_entries() == 0
        monkeypatch.setenv("BIGDL_TPU_COMPILE_CACHE", "1")
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path))
        d = engine.maybe_enable_compilation_cache()
        assert d == str(tmp_path)
        assert engine.compilation_cache_dir() == str(tmp_path)
        # idempotent: the second call returns the same dir without re-init
        assert engine.maybe_enable_compilation_cache() == str(tmp_path)
        assert engine.compilation_cache_entries() == 0
        (tmp_path / "a_compiled_executable").write_bytes(b"x")
        assert engine.compilation_cache_entries() == 1
    finally:
        engine._state["compile_cache_dir"] = prev
