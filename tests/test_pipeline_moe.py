"""Pipeline (GPipe over 'pipe' axis) and MoE (expert parallel) tests on the
8-virtual-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from bigdl_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.pipeline import (gpipe, stack_stage_params,
                                         unstack_stage_params)
from bigdl_tpu.parallel.moe import moe_ffn, top1_routing


def _mesh(axis, n=8):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _make_stages(rng, n_stages, d):
    stages = []
    for i in range(n_stages):
        k1, k2, rng = jax.random.split(rng, 3)
        stages.append({"w": jax.random.normal(k1, (d, d)) * 0.3,
                       "b": jax.random.normal(k2, (d,)) * 0.1})
    return stages


def test_gpipe_matches_sequential():
    """Pipelined forward == applying the stages one after another."""
    n_stages, n_micro, mb, d = 8, 6, 4, 16
    rng = jax.random.PRNGKey(0)
    stages = _make_stages(rng, n_stages, d)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, d))

    mesh = _mesh("pipe")
    run = gpipe(_stage_fn, axis="pipe")
    piped = jax.jit(shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pipe"), stacked),
                  P()),
        out_specs=P()))(stacked, x)

    ref = x
    for p in stages:
        ref = jax.vmap(lambda m: _stage_fn(p, m))(ref)
    assert np.allclose(np.asarray(piped), np.asarray(ref), atol=1e-5), \
        np.abs(np.asarray(piped) - np.asarray(ref)).max()


def test_gpipe_unstack_roundtrip():
    stages = _make_stages(jax.random.PRNGKey(2), 4, 8)
    back = unstack_stage_params(stack_stage_params(stages), 4)
    for a, b in zip(stages, back):
        assert np.allclose(a["w"], b["w"])


def test_gpipe_trains():
    """jax.grad through the pipelined loss moves stage params (the backward
    schedule comes from autodiff through scan+ppermute)."""
    n_stages, n_micro, mb, d = 8, 4, 2, 8
    stages = _make_stages(jax.random.PRNGKey(3), n_stages, d)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(4), (n_micro, mb, d))
    y = jax.random.normal(jax.random.PRNGKey(5), (n_micro, mb, d))

    mesh = _mesh("pipe")
    run = gpipe(_stage_fn, axis="pipe")
    specs = jax.tree_util.tree_map(lambda _: P("pipe"), stacked)

    def loss_fn(params, x, y):
        def inner(p, xx, yy):
            out = run(p, xx)
            return jnp.mean((out - yy) ** 2) * jnp.ones((1,))
        l = shard_map(inner, mesh=mesh, in_specs=(specs, P(), P()),
                      out_specs=P())(params, x, y)
        return l.sum()

    g = jax.jit(jax.grad(loss_fn))(stacked, x, y)
    norms = [float(jnp.linalg.norm(leaf))
             for leaf in jax.tree_util.tree_leaves(g)]
    assert all(n > 0 for n in norms), norms
    # one SGD step reduces the loss
    l0 = float(jax.jit(loss_fn)(stacked, x, y))
    stepped = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, stacked, g)
    l1 = float(jax.jit(loss_fn)(stepped, x, y))
    assert l1 < l0, (l0, l1)


def test_top1_routing_shapes_and_capacity():
    logits = jnp.asarray(np.random.RandomState(0).randn(16, 4),
                         jnp.float32)
    dispatch, combine, aux = top1_routing(logits, capacity=3)
    assert dispatch.shape == (16, 4, 3)
    # no expert queue exceeds capacity
    assert float(dispatch.sum(axis=(0, 2)).max()) <= 3 + 1e-6
    # each kept token dispatched exactly once
    per_token = dispatch.sum(axis=(1, 2))
    assert set(np.asarray(per_token).round(4).tolist()) <= {0.0, 1.0}
    assert float(aux) > 0


def test_moe_matches_dense_oracle():
    """With ample capacity, expert-parallel MoE == gate * expert(x) computed
    densely on the host."""
    E, tloc, d = 8, 4, 8
    rng = np.random.RandomState(1)
    router_w = jnp.asarray(rng.randn(d, E) * 0.5, jnp.float32)
    # one expert per device: stacked params with leading expert axis
    ws = jnp.asarray(rng.randn(E, d, d) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(E * tloc, d), jnp.float32)

    def expert_fn(p, h):
        return jnp.tanh(h @ p["w"])

    mesh = _mesh("expert")
    run = moe_ffn(expert_fn, axis="expert", capacity_factor=float(E))

    def spmd(router_w, params, x):
        return run(router_w, params, x)

    y, aux = jax.jit(shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), {"w": P("expert")}, P("expert")),
        out_specs=(P("expert"), P())))(router_w, {"w": ws}, x)

    # dense oracle
    probs = jax.nn.softmax(np.asarray(x) @ np.asarray(router_w), axis=-1)
    gate = probs.max(-1)
    eidx = probs.argmax(-1)
    ref = np.stack([gate[t] * np.tanh(np.asarray(x)[t] @
                                      np.asarray(ws)[eidx[t]])
                    for t in range(x.shape[0])])
    assert np.allclose(np.asarray(y), ref, atol=1e-4), \
        np.abs(np.asarray(y) - ref).max()


def test_moe_grads_flow():
    E, tloc, d = 8, 4, 8
    rng = np.random.RandomState(2)
    router_w = jnp.asarray(rng.randn(d, E) * 0.5, jnp.float32)
    ws = jnp.asarray(rng.randn(E, d, d) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(E * tloc, d), jnp.float32)

    def expert_fn(p, h):
        return jnp.tanh(h @ p["w"])

    mesh = _mesh("expert")
    run = moe_ffn(expert_fn, axis="expert", capacity_factor=2.0)

    def loss(router_w, params, x):
        def inner(rw, p, xx):
            y, aux = run(rw, p, xx)
            val = jax.lax.pmean(jnp.mean(y ** 2), "expert") + 0.01 * aux
            return val * jnp.ones((1,))
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), {"w": P("expert")}, P("expert")),
                         out_specs=P())(router_w, params, x).sum()

    gr, gw = jax.jit(jax.grad(loss, argnums=(0, 1)))(router_w, {"w": ws}, x)
    assert float(jnp.abs(gw["w"]).sum()) > 0
    assert float(jnp.abs(gr).sum()) > 0


def test_mixture_of_experts_layer():
    """nn.MixtureOfExperts: shapes, aux loss recorded, trains by grad."""
    from bigdl_tpu.nn import MixtureOfExperts
    m = MixtureOfExperts(hidden_size=8, n_experts=4, ffn_hidden=16,
                         capacity_factor=2.0)
    m.ensure_initialized()
    x = np.random.RandomState(0).randn(2, 6, 8).astype(np.float32)
    out = m.forward(x)
    assert np.asarray(out).shape == (2, 6, 8)
    assert float(m.state["aux_loss"]) > 0

    def loss(p):
        y, st = m.apply(p, m.state, x, training=True)
        return jnp.mean(y ** 2) + 0.01 * st["aux_loss"]

    g = jax.grad(loss)(m.params)
    assert all(float(jnp.abs(l).sum()) > 0
               for l in jax.tree_util.tree_leaves(g))


def test_gpipe_composed_dp_pipe_mesh():
    """GPipe inside a COMPOSED (data x pipe) mesh: microbatches sharded
    over 'data', stages over 'pipe' — the dp+pp layout. Output must match
    the sequential stage application (strict-VMA typing regression test:
    the tick's where() mixes pipe-invariant x_stack with the pipe-varying
    ring carry)."""
    n_stages, n_micro, mb, d = 4, 4, 4, 16
    rng = jax.random.PRNGKey(0)
    stages = _make_stages(rng, n_stages, d)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, 2 * mb, d))

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "pipe"))
    run = gpipe(_stage_fn, axis="pipe")
    piped = jax.jit(shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pipe"), stacked),
                  P(None, "data")),        # micro-batch rows over data
        out_specs=P(None, "data")))(stacked, x)

    ref = x
    for p in stages:
        ref = jax.vmap(lambda m: _stage_fn(p, m))(ref)
    assert np.allclose(np.asarray(piped), np.asarray(ref), atol=1e-5), \
        np.abs(np.asarray(piped) - np.asarray(ref)).max()


def test_moe_composed_dp_expert_mesh():
    """Expert-parallel MoE inside a COMPOSED (data x expert) mesh — the
    dp+ep layout: batch rows over 'data', experts over 'expert'."""
    E, tloc, d = 4, 4, 8
    rng = np.random.RandomState(1)
    router_w = jnp.asarray(rng.randn(d, E) * 0.5, jnp.float32)
    ws = jnp.asarray(rng.randn(E, d, d) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(2 * E * tloc, d), jnp.float32)

    def expert_fn(p, h):
        return jnp.tanh(h @ p["w"])

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "expert"))
    run = moe_ffn(expert_fn, axis="expert", capacity_factor=float(E))

    def spmd(router_w, params, xx):
        y, aux = run(router_w, params, xx)
        from jax import lax
        return y, lax.pmean(aux, "data")   # scalar: average the data rows

    y, aux = jax.jit(shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), {"w": P("expert")}, P(("data", "expert"))),
        out_specs=(P(("data", "expert")), P())))(
        router_w, {"w": ws}, x)

    probs = jax.nn.softmax(np.asarray(x) @ np.asarray(router_w), axis=-1)
    gate = probs.max(-1)
    eidx = probs.argmax(-1)
    ref = np.stack([gate[t] * np.tanh(np.asarray(x)[t] @
                                      np.asarray(ws)[eidx[t]])
                    for t in range(x.shape[0])])
    assert np.allclose(np.asarray(y), ref, atol=1e-4), \
        np.abs(np.asarray(y) - ref).max()
