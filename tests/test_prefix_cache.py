"""Prefix-aware KV reuse (ISSUE 12): content-addressed block sharing.

Two gate families:

* **Ledger invariants** — per-block refcounts: double-free refused,
  adoption pins pages, copy-on-write forks leave the shared original
  intact, defrag moves a shared page ONCE and every referent (owner
  tables + prefix-cache index) follows it, eviction reclaims only
  refcount-0 (cache-only) entries leaf-first, and
  ``kv_blocks_in_use`` drains to zero at every shutdown path.
* **The bitwise matrix** — tokens produced through any mix of prefix
  hits, CoW forks, defrag-then-decode, eviction-under-sharing,
  speculative decoding and the Pallas paged-attention kernel are
  BITWISE identical to a cold solo decode (the house correctness bar):
  a warm hit adopts blocks whose pages were written by the SAME chunk
  shapes over the SAME inputs the cold schedule would use, and the
  warm suffix re-runs exactly the cold schedule's remaining chunks.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import observability as obs
from bigdl_tpu.models.transformer_lm import TransformerLM
from serving_helpers import no_leaked_blocks, solo_oracle as _oracle
from bigdl_tpu.serving import (DecodeScheduler, KVCacheOOM, PagedKVCache,
                               PrefixCache, chain_keys,
                               decode_scheduler_threads_alive,
                               prefill_schedule)

V, H, LAYERS = 48, 32, 2
MAXLEN = 256
CHUNK = 8
BS = 4          # block_size; hit_align = max(CHUNK, BS) = 8


def _model(**kw):
    cfg = dict(vocab_size=V, hidden_size=H, num_heads=4, filter_size=64,
               num_layers=LAYERS, max_len=MAXLEN)
    cfg.update(kw)
    m = TransformerLM(**cfg)
    m.ensure_initialized()
    return m


_shared = {}


def shared_model():
    if "m" not in _shared:
        _shared["m"] = _model(pos_encoding="rope", num_kv_heads=2)
    return _shared["m"]


def solo_oracle(model, params, prompt, max_new, chunk=CHUNK, eos_id=None):
    return _oracle(model, params, prompt, max_new, chunk=chunk,
                   maxlen=MAXLEN, eos_id=eos_id)


def _sched(model, **kw):
    cfg = dict(max_slots=4, block_size=BS, max_seq_len=96,
               prefill_chunk=CHUNK)
    cfg.update(kw)
    return DecodeScheduler(model, **cfg)


def _no_leaked_blocks(st):
    no_leaked_blocks(st)


@pytest.fixture(params=["dense",
                        pytest.param("kernel", marks=pytest.mark.slow)])
def paged_path(request, monkeypatch):
    if request.param == "kernel":
        monkeypatch.setenv("BIGDL_TPU_PAGED_ATTN", "interpret")
    else:
        monkeypatch.delenv("BIGDL_TPU_PAGED_ATTN", raising=False)
    return request.param


# ---------------------------------------------------------------------------
# ledger invariants: refcounts, CoW, defrag-under-sharing, eviction
# ---------------------------------------------------------------------------

def test_refcount_adopt_release_and_double_free_refused():
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=9, block_size=4, max_blocks_per_seq=4)
    kv.ensure_capacity("a", 12)                       # 3 private blocks
    blocks = kv.owner_blocks("a")
    assert [kv.block_refs(b) for b in blocks] == [1, 1, 1]
    kv.retain(blocks[:2])                             # cache pins 2
    assert [kv.block_refs(b) for b in blocks] == [2, 2, 1]
    kv.adopt("b", blocks[:2])                         # a hit adopts them
    assert kv.block_refs(blocks[0]) == 3
    # shared pages count ONCE: a(3) + b shares 2 of them
    assert kv.blocks_in_use() == 3 and kv.shared_blocks() == 2
    assert kv.free("a") == 3          # drops a's refs; only block 3 frees
    assert kv.blocks_in_use() == 2 and kv.blocks_free() == 6
    assert kv.free("b") == 2          # cache still pins both
    assert kv.blocks_in_use() == 2
    assert kv.release(blocks[:2]) == 2                # now they free
    with pytest.raises(ValueError, match="double-free"):
        kv.release(blocks[:1])
    with pytest.raises(ValueError):
        kv.retain(blocks[:1])          # can't pin a free page
    with pytest.raises(ValueError):
        kv.adopt("c", blocks[:1])      # can't adopt a free page
    assert kv.blocks_in_use() == 0 and kv.free("a") == 0  # idempotent
    # adoption must precede private growth (the table layout contract)
    kv.ensure_capacity("d", 4)
    with pytest.raises(ValueError, match="adopt"):
        kv.adopt("d", kv.owner_blocks("d"))


def test_cow_fork_copies_pages_and_leaves_original():
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=8, block_size=4, max_blocks_per_seq=4)
    kv.ensure_capacity("a", 8)                        # blocks [1, 2]
    b0, b1 = kv.owner_blocks("a")
    # stamp recognizable values into a's pages
    k0, v0 = kv.pages()[0]
    kv.set_pages([(k.at[b0].set(7.0).at[b1].set(9.0), v)
                  for k, v in kv.pages()])
    kv.retain([b0, b1])                               # now shared
    forked = kv.fork_blocks("a", [0, 1, 3])           # 3 is out of range
    assert forked == [0, 1]
    n0, n1 = kv.owner_blocks("a")
    assert {n0, n1}.isdisjoint({b0, b1})
    k, _ = kv.pages()[0]
    assert float(k[n0].reshape(-1)[0]) == 7.0         # pages copied
    assert float(k[b1].reshape(-1)[0]) == 9.0         # original intact
    assert kv.block_refs(b0) == 1 and kv.block_refs(n0) == 1
    assert kv.fork_blocks("a", [0, 1]) == []          # already private
    # fork respects the free list: pool of 7, 4 in use -> 3 free; a
    # second owner adopting + forking past that must raise typed
    kv.adopt("b", [b0, b1])
    kv.ensure_capacity("b", 16)  # grows b to 4 blocks (2 adopted + 2)
    assert kv.blocks_free() == 1
    with pytest.raises(KVCacheOOM):
        kv.fork_blocks("b", [0, 1])


def test_defrag_preserves_sharing_and_remaps_index():
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=20, block_size=4, max_blocks_per_seq=5)
    seen = []
    kv.add_remap_listener(seen.append)
    kv.ensure_capacity("hole", 12)
    kv.ensure_capacity("a", 12)
    shared = kv.owner_blocks("a")[:2]
    kv.retain(shared)
    kv.adopt("b", shared)
    kv.free("hole")                   # holes below a's ids
    assert kv.frag_blocks() > 0
    moved = kv.defrag()
    assert moved > 0 and kv.frag_blocks() == 0 and seen
    remap = seen[0]
    new_shared = [remap.get(b, b) for b in shared]
    # BOTH owners' tables follow the moved page — still the same page
    assert kv.owner_blocks("a")[:2] == new_shared
    assert kv.owner_blocks("b") == new_shared
    assert [kv.block_refs(b) for b in new_shared] == [3, 3]
    assert kv.shared_blocks() == 2


def test_prefix_cache_insert_lookup_evict_leaf_first():
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=32, block_size=4, max_blocks_per_seq=8)
    pc = PrefixCache(kv)
    toks = np.arange(1, 17, dtype=np.int32)           # 4 full blocks
    kv.ensure_capacity("a", 16)
    blocks = kv.owner_blocks("a")
    assert pc.insert(toks, "v0", blocks) == 4
    assert pc.insert(toks, "v0", blocks) == 0         # refresh, not dup
    assert pc.lookup(toks, "v0") == blocks
    assert pc.lookup(toks, "v1") == []                # version-keyed
    assert pc.peek(toks, "v0") == 16
    assert pc.peek(toks[:10], "v0") == 8              # partial chain
    assert len(chain_keys(toks, 4, "v0")) == 4
    # divergent chain shares only the common prefix
    toks2 = toks.copy()
    toks2[9] = 44
    assert pc.peek(toks2, "v0") == 8
    # owner still holds every block: nothing is evictable
    assert pc.evict(99) == 0 and len(pc) == 4
    kv.free("a")
    # now cache-only (refcount 1): evict reclaims LEAF-first
    assert pc.evict(1) == 1
    assert pc.peek(toks, "v0") == 12                  # chain shrank at tail
    assert pc.evict(99) == 3 and len(pc) == 0
    assert kv.blocks_in_use() == 0
    # stats surface
    s = pc.stats()
    assert s["evictions"] == 4 and s["entries"] == 0


def test_prefix_cache_interior_entry_pinned_by_descendant():
    """An interior entry whose child is still adopted must not be
    evicted even when its own block is unreferenced — the chain walk
    would strand the descendant unreachable while its page stays
    pinned."""
    m = shared_model()
    kv = PagedKVCache(m, num_blocks=16, block_size=4, max_blocks_per_seq=4)
    pc = PrefixCache(kv)
    toks = np.arange(1, 13, dtype=np.int32)           # 3 blocks
    kv.ensure_capacity("a", 12)
    pc.insert(toks, "v0", kv.owner_blocks("a"))
    tail = kv.owner_blocks("a")[2]
    kv.free("a")
    kv.retain([tail])                 # a live adopter of the TAIL only
    assert pc.evict(99) == 0          # parents have children; tail adopted
    assert len(pc) == 3
    kv.release([tail])
    assert pc.evict(99) == 3


# ---------------------------------------------------------------------------
# the bitwise matrix
# ---------------------------------------------------------------------------

def _prefix_plus(rng, prefix, n_extra):
    return np.concatenate([prefix,
                           rng.randint(1, V, size=n_extra).astype(np.int32)])


def test_warm_hit_bitwise_and_skips_prefill(paged_path):
    """The core gate: a request whose prompt extends a registered
    prefix adopts the cached blocks, skips their prefill chunks, and
    still emits BITWISE the cold solo decode's tokens — dense and
    Pallas-kernel paths both."""
    m = shared_model()
    rng = np.random.RandomState(20)
    prefix = rng.randint(1, V, size=16).astype(np.int32)   # 2 chunks
    p1 = _prefix_plus(rng, prefix, 5)
    p2 = _prefix_plus(rng, prefix, 3)
    with _sched(m) as sched:
        r1 = sched.submit(p1, 6).result(timeout=120)
        chunks_cold = sched.stats()["prefill_chunks"]
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r1, solo_oracle(m, m.params, p1, 6))
    assert np.array_equal(r2, solo_oracle(m, m.params, p2, 6))
    assert st["prefix_hits"] == 1 and st["prefix_misses"] == 1
    assert st["prefix_reused_tokens"] == 16
    # p2 cold would be 3 chunks (8+8+4); warm runs ONLY the tail chunk
    assert st["prefill_chunks"] - chunks_cold == 1
    assert st["prefix_cow_forks"] == 0
    _no_leaked_blocks(st)
    assert decode_scheduler_threads_alive() == 0


def test_full_aligned_hit_reruns_last_chunk_with_cow(paged_path):
    """A fully-cached, fully-aligned prompt re-runs only its LAST cold
    chunk for the first-token logits; that chunk's writes into shared
    pages take copy-on-write forks — and the tokens stay bitwise the
    cold decode's (same chunk shape, same inputs, private pages)."""
    m = shared_model()
    rng = np.random.RandomState(21)
    p = rng.randint(1, V, size=16).astype(np.int32)   # aligned to 8
    want = solo_oracle(m, m.params, p, 6)
    with _sched(m) as sched:
        a = sched.submit(p, 6).result(timeout=120)
        chunks_cold = sched.stats()["prefill_chunks"]
        b = sched.submit(p, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(a, want) and np.array_equal(b, want)
    assert st["prefix_hits"] == 1
    # honest accounting: the rerun chunk's 8 tokens are re-computed,
    # so only the first chunk's 8 count as reused
    assert st["prefix_reused_tokens"] == 8
    assert st["prefill_chunks"] - chunks_cold == 1    # rerun tail only
    # the rerun chunk spans blocks 2,3 of the adopted prefix -> 2 forks
    assert st["prefix_cow_forks"] == 2
    _no_leaked_blocks(st)


def test_warm_hit_after_defrag_bitwise(paged_path):
    """Defrag moves the SHARED prefix pages; a later hit adopts the
    moved pages through the remapped index and decodes bitwise."""
    m = shared_model()
    rng = np.random.RandomState(22)
    prefix = rng.randint(1, V, size=16).astype(np.int32)
    p1 = _prefix_plus(rng, prefix, 4)
    p2 = _prefix_plus(rng, prefix, 6)
    with _sched(m) as sched:
        sched.submit(p1, 4).result(timeout=120)
        # churn scatters ids, then repack with the cache resident
        for n in (9, 5, 12):
            sched.submit(rng.randint(1, V, size=n), 3).result(timeout=120)
        sched.defrag()
        time.sleep(0.05)              # let the step boundary run it
        r2 = sched.submit(p2, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(r2, solo_oracle(m, m.params, p2, 6))
    assert st["prefix_hits"] >= 1
    _no_leaked_blocks(st)


def test_eviction_under_sharing_and_backpressure():
    """A pool sized so the cache's resident prefixes must be partially
    evicted to admit new work: admission reclaims ONLY unreferenced
    entries, requests still serve bitwise, and the pool never leaks."""
    m = shared_model()
    rng = np.random.RandomState(23)
    prompts = [rng.randint(1, V, size=20).astype(np.int32)
               for _ in range(3)]
    # each request needs ceil(28/4)=7 blocks; pool of 11 holds ONE
    # request + part of one registered prefix at a time
    with _sched(m, num_blocks=12, max_seq_len=32) as sched:
        outs = [sched.submit(p, 8).result(timeout=120) for p in prompts]
        st = sched.stats()
    for p, r in zip(prompts, outs):
        assert np.array_equal(r, solo_oracle(m, m.params, p, 8))
    assert st["prefix"]["evictions"] > 0
    _no_leaked_blocks(st)


def test_shared_prefix_resident_once():
    """The storage gate: concurrent requests over one system prompt
    share ONE copy of its blocks (serve/prefix gauges + ledger)."""
    obs.enable()
    try:
        m = shared_model()
        rng = np.random.RandomState(24)
        prefix = rng.randint(1, V, size=24).astype(np.int32)  # 6 blocks
        with _sched(m, max_slots=4) as sched:
            sched.submit(_prefix_plus(rng, prefix, 3), 4).result(
                timeout=120)
            futs = [sched.submit(_prefix_plus(rng, prefix, 3), 12)
                    for _ in range(3)]
            # while the swarm decodes, the prefix pages must be SHARED
            peak_shared = 0
            for _ in range(200):
                peak_shared = max(peak_shared, sched.kv.shared_blocks())
                if all(f.done() for f in futs):
                    break
                time.sleep(0.005)
            [f.result(timeout=120) for f in futs]
            st = sched.stats()
        # hit_align=8: 24-token prefix -> 24 reusable tokens = 6 blocks
        assert st["prefix_hits"] == 3
        assert st["prefix_reused_tokens"] == 3 * 24
        assert peak_shared >= 6, \
            f"prefix must be resident once and SHARED (saw {peak_shared})"
        reg = obs.registry()
        assert reg.get("serve/prefix_hits").value == 3
        assert reg.get("serve/prefix_reused_tokens").value == 72
        assert reg.get("serve/prefix_shared_blocks").value >= 0
        _no_leaked_blocks(st)
    finally:
        obs.disable()


def test_no_cross_version_reuse_after_swap():
    """Reuse is keyed on (tokens, version): after a hot swap the same
    prompt MISSES (old pages describe old params) and decodes bitwise
    under the new version."""
    m = shared_model()
    m2 = _model(pos_encoding="rope", num_kv_heads=2)
    rng = np.random.RandomState(25)
    p = rng.randint(1, V, size=16).astype(np.int32)
    with _sched(m) as sched:
        a = sched.submit(p, 6).result(timeout=120)
        sched.swap(m2.params, m2.state)
        b = sched.submit(p, 6).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(a, solo_oracle(m, m.params, p, 6))
    assert np.array_equal(b, solo_oracle(m, m2.params, p, 6))
    assert st["prefix_hits"] == 0 and st["prefix_misses"] == 2
    _no_leaked_blocks(st)


def test_warm_hit_speculates_after_lazy_draft_catchup():
    """Prefix adoption composes with speculative decoding (ISSUE 14
    satellite — the PR-12 cost-only carve-out is gone): a warm HIT
    skipped the draft model's prefill along with the target's, so the
    scheduler lazily re-prefills the draft over the adopted region on
    the row's first spec round (`_draft_catchup`) instead of losing
    spec eligibility forever. Warm tokens are bitwise the cold run's,
    the warm request DOES ride spec rounds, and with a perfect draft
    its acceptance is as total as the cold run's (the catch-up rebuilt
    a correct draft cache — garbage proposals would zero it)."""
    m = _model()                      # sinusoidal/MHA variant
    rng = np.random.RandomState(26)
    p = rng.randint(1, V, size=16).astype(np.int32)
    want = solo_oracle(m, m.params, p, 10)
    with _sched(m, draft_model=m, spec_k=3) as sched:
        a = sched.submit(p, 10).result(timeout=120)
        st_cold = sched.stats()
        fut = sched.submit(p, 10)
        b = fut.result(timeout=120)
        st = sched.stats()
    assert np.array_equal(a, want) and np.array_equal(b, want)
    assert st_cold["spec_rounds"] > 0, "cold must ride the spec path"
    assert st["spec_rounds"] > st_cold["spec_rounds"], \
        "a warm hit must speculate too (lazy draft catch-up)"
    assert st["prefix_hits"] == 1
    warm_rounds = st["spec_rounds"] - st_cold["spec_rounds"]
    warm_accept = st["spec_accepted"] - st_cold["spec_accepted"]
    assert warm_accept == 3 * warm_rounds, \
        "perfect draft after catch-up must accept every proposal"
    assert fut.trace["spec_rounds"] == warm_rounds
    assert fut.trace["spec_accepted"] == warm_accept
    _no_leaked_blocks(st)


def test_prefix_disabled_is_prior_behavior():
    m = shared_model()
    rng = np.random.RandomState(27)
    p = rng.randint(1, V, size=16).astype(np.int32)
    with _sched(m, prefix_cache=False) as sched:
        a = sched.submit(p, 5).result(timeout=120)
        b = sched.submit(p, 5).result(timeout=120)
        st = sched.stats()
    assert np.array_equal(a, b)
    assert np.array_equal(a, solo_oracle(m, m.params, p, 5))
    assert st["prefix"] is None and st["prefix_hits"] == 0
    assert st["kv"]["blocks_in_use"] == 0
    assert sched.cached_prefix_tokens(p) == 0


def test_probe_and_shutdown_paths_drain_to_zero():
    from bigdl_tpu.serving import EngineStopped
    m = shared_model()
    rng = np.random.RandomState(28)
    p = rng.randint(1, V, size=16).astype(np.int32)
    # drain=True path
    sched = _sched(m).start()
    sched.submit(p, 4).result(timeout=120)
    assert sched.cached_prefix_tokens(p) == 16        # probe, no metrics
    assert sched.cached_prefix_tokens(rng.randint(1, V, size=16)) == 0
    assert sched.stats()["prefix_hits"] == 0          # peek stayed silent
    sched.shutdown(drain=True)
    assert sched.kv.stats()["blocks_in_use"] == 0
    # drain=False path with cache entries AND in-flight work
    sched = _sched(m)
    sched.submit(p, 30)
    sched.start()
    time.sleep(0.05)
    sched.shutdown(drain=False)
    assert sched.kv.stats()["blocks_in_use"] == 0
    assert decode_scheduler_threads_alive() == 0
    with pytest.raises(EngineStopped):
        sched.submit(p, 2)
