"""Universal-tier bigdl.proto round-trips of full models — the r3
verdict's named bars (Inception, LSTM, quantized LeNet, criteria).
Split from test_serialization.py for xdist loadfile balance (the full
Inception init dominates)."""
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5


def _proto_roundtrip_forward(m, x, tmp_path, atol=1e-5):
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    m.ensure_initialized()
    m.evaluate()
    ref = np.asarray(m.forward(x))
    path = str(tmp_path / "m.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)), ref, atol=atol)
    return m2


@pytest.mark.slow
def test_proto_inception_roundtrip(tmp_path):
    """FULL Inception-v1 (LRN + Concat heads) through bigdl.proto — the
    exact case the r3 verdict called out as unserializable. Structure +
    exact params/state equality. @slow since PR 7 (the full-size init
    dominated tier-1's --durations at ~21-32s); the block-level forward
    check below keeps default-tier coverage of the LRN + Concat case."""
    import jax
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
    m = Inception_v1_NoAuxClassifier(class_num=10)
    m.ensure_initialized()
    path = str(tmp_path / "i.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    assert type(m2) is type(m)
    l1, s1 = jax.tree_util.tree_flatten(m.params)
    l2, s2 = jax.tree_util.tree_flatten(m2.params)
    assert s1 == s2
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def types(mm):
        out = [type(mm).__name__]
        for c in getattr(mm, "modules", []):
            out += types(c)
        return out

    assert types(m2) == types(m)
    assert "SpatialCrossMapLRN" in types(m2)  # the named LRN case


def test_proto_inception_block_forward(tmp_path):
    """Forward equality for one inception block (Concat heads + LRN) —
    the cheap default-path check backing the structure test above."""
    from bigdl_tpu import nn
    from bigdl_tpu.models.inception import inception_block
    m = nn.Sequential(nn.SpatialCrossMapLRN(5, 1e-4, 0.75, 1.0),
                      inception_block(32, ([8], [8, 12], [8, 12], [8]),
                                      name_prefix="pb/"))
    x = np.random.RandomState(0).randn(1, 32, 14, 14).astype(np.float32)
    _proto_roundtrip_forward(m, x, tmp_path, atol=1e-5)


@pytest.mark.slow
def test_proto_inception_forward_full(tmp_path):
    """Full-model forward equality (opt-in: BIGDL_TPU_SLOW=1)."""
    from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
    m = Inception_v1_NoAuxClassifier(class_num=10)
    x = np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32)
    _proto_roundtrip_forward(m, x, tmp_path, atol=1e-4)


def test_proto_lstm_roundtrip(tmp_path):
    m = nn.Recurrent(nn.LSTM(5, 7))
    x = np.random.RandomState(1).randn(2, 6, 5).astype(np.float32)
    _proto_roundtrip_forward(m, x, tmp_path)


def test_proto_quantized_lenet_roundtrip(tmp_path):
    """quantize()d LeNet through bigdl.proto: int8 weights and scales
    survive with exact forward agreement (QuantSerializer.scala analog)."""
    import jax
    from bigdl_tpu.quantization import quantize
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    m = LeNet5(class_num=10)
    m.ensure_initialized()
    q = quantize(m)
    q.ensure_initialized()
    q.evaluate()
    x = np.random.RandomState(2).randn(2, 1, 28, 28).astype(np.float32)
    ref = np.asarray(q.forward(x))
    path = str(tmp_path / "q.bigdl")
    save_bigdl(q, path)
    q2 = load_bigdl(path)
    q2.evaluate()
    np.testing.assert_allclose(np.asarray(q2.forward(x)), ref, atol=1e-6)
    # int8 payloads really stayed int8 on the wire
    int8_leaves = [l for l in jax.tree_util.tree_leaves(q2.params)
                   if np.asarray(l).dtype == np.int8]
    assert int8_leaves, "no int8 leaves survived the round-trip"


def test_proto_criterion_roundtrip(tmp_path):
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    c = nn.TimeDistributedMaskCriterion(nn.ClassNLLCriterion())
    path = str(tmp_path / "c.bigdl")
    save_bigdl(c, path)
    c2 = load_bigdl(path)
    assert type(c2) is type(c)
    assert type(c2.critrn) is nn.ClassNLLCriterion


def test_proto_rope_gqa_lm_roundtrip(tmp_path):
    """The r4 LM options (RoPE, GQA) survive bigdl.proto: config attrs
    round-trip and the loaded model decodes identically."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl
    from bigdl_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=31, hidden_size=16, num_heads=4,
                      filter_size=32, num_layers=1, max_len=24,
                      use_flash=False, num_kv_heads=2, pos_encoding="rope")
    m.ensure_initialized()
    path = str(tmp_path / "lm.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    assert m2.pos_encoding == "rope"
    assert m2.blocks[0].attn.num_kv_heads == 2 and m2.blocks[0].attn.rope
    prompt = np.array([[3, 7]], np.int32)
    out1 = np.asarray(m.generate(m.params, prompt, max_new_tokens=4))
    out2 = np.asarray(m2.generate(m2.params, prompt, max_new_tokens=4))
    np.testing.assert_array_equal(out1, out2)
