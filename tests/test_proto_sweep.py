"""Per-layer bigdl.proto round-trip sweep — every public Module class in
``bigdl_tpu.nn`` must save→load through the protobuf serializer with its
type, config, and param/state trees intact.

Parity: the reference exercises exactly this with a reflection-default
serializer plus a per-layer SerializerSpec sweep
(``utils/serializer/ModuleSerializer.scala:199``); this is the bigdl_tpu
equivalent. Classes with required ctor args get an instance factory below;
zero-arg classes are auto-instantiated. The coverage assertion at the bottom
guarantees no newly-added class silently escapes the sweep.
"""
import inspect

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as N
from bigdl_tpu.nn.module import Module
from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl

# abstract bases / machinery that users never instantiate directly
EXEMPT = {
    "Module", "Container", "Cell", "Layer", "TableOperation",
}


def _graph(cls):
    inp = N.Input()
    h = N.Linear(6, 5)(inp)
    out = N.ReLU()(h)
    return cls(inp, out)


# instance factories for classes whose ctor has required args
SPECS = {
    "Add": lambda: N.Add(6),
    "AddConstant": lambda: N.AddConstant(1.5),
    "Attention": lambda: N.Attention(8, 2),
    "BatchNormalization": lambda: N.BatchNormalization(6),
    "BifurcateSplitTable": lambda: N.BifurcateSplitTable(1),
    "Bilinear": lambda: N.Bilinear(4, 5, 3),
    "BinaryTreeLSTM": lambda: N.BinaryTreeLSTM(6, 5),
    "Bottle": lambda: N.Bottle(N.Linear(4, 3)),
    "CAdd": lambda: N.CAdd((6,)),
    "CMul": lambda: N.CMul((6,)),
    "Clamp": lambda: N.Clamp(-1.0, 1.0),
    "Concat": lambda: N.Concat(1, N.Linear(4, 3), N.Linear(4, 2)),
    "ConvLSTMPeephole": lambda: N.ConvLSTMPeephole(3, 4),
    "ConvLSTMPeephole3D": lambda: N.ConvLSTMPeephole3D(3, 4),
    "Cosine": lambda: N.Cosine(4, 3),
    "DynamicGraph": lambda: _graph(N.DynamicGraph),
    "Euclidean": lambda: N.Euclidean(4, 3),
    "ExpandSize": lambda: N.ExpandSize([2, 6]),
    "FeedForwardNetwork": lambda: N.FeedForwardNetwork(8, 16),
    "GRU": lambda: N.GRU(6, 5),
    "GaussianDropout": lambda: N.GaussianDropout(0.3),
    "GaussianNoise": lambda: N.GaussianNoise(0.2),
    "Graph": lambda: _graph(N.Graph),
    "Highway": lambda: N.Highway(6),
    "Index": lambda: N.Index(1),
    "InferReshape": lambda: N.InferReshape([-1, 3]),
    "JoinTable": lambda: N.JoinTable(1),
    "L1Penalty": lambda: N.L1Penalty(0.01),
    "LSTM": lambda: N.LSTM(6, 5),
    "LSTMPeephole": lambda: N.LSTMPeephole(6, 5),
    "LayerNormalization": lambda: N.LayerNormalization(8),
    "Linear": lambda: N.Linear(6, 4),
    "LocallyConnected1D": lambda: N.LocallyConnected1D(8, 4, 3, 2),
    "LocallyConnected2D": lambda: N.LocallyConnected2D(2, 8, 8, 3, 3, 3),
    "LookupTable": lambda: N.LookupTable(10, 6),
    "LookupTableSparse": lambda: N.LookupTableSparse(10, 6),
    "MapTable": lambda: N.MapTable(N.Linear(4, 3)),
    "Maxout": lambda: N.Maxout(6, 4, 2),
    "MixtureOfExperts": lambda: N.MixtureOfExperts(8, 2),
    "Model": lambda: _graph(N.Model),
    "MulConstant": lambda: N.MulConstant(2.0),
    "NormalizeScale": lambda: N.NormalizeScale(size=(1, 6, 1, 1)),
    "Recurrent": lambda: N.Recurrent(N.LSTM(6, 5)),
    "BiRecurrent": lambda: N.BiRecurrent().add(N.RnnCell(6, 5)),
    "MultiRNNCell": lambda: N.MultiRNNCell([N.RnnCell(6, 6),
                                            N.RnnCell(6, 6)]),
    "Narrow": lambda: N.Narrow(1, 0, 2),
    "NarrowTable": lambda: N.NarrowTable(1, 1),
    "Pack": lambda: N.Pack(1),
    "Padding": lambda: N.Padding(1, 2, 2),
    "Power": lambda: N.Power(2.0),
    "PriorBox": lambda: N.PriorBox([16.0], aspect_ratios=[2.0],
                                   img_size=64, step=8.0),
    "Proposal": lambda: N.Proposal(100, 10, [0.5, 1.0, 2.0], [8.0]),
    "RNN": lambda: N.RNN(6, 5),
    "RecurrentDecoder": lambda: N.RecurrentDecoder(4).add(N.RnnCell(5, 5)),
    "View": lambda: N.View(2, 3),
    "Replicate": lambda: N.Replicate(3),
    "Reshape": lambda: N.Reshape([2, 3]),
    "ResizeBilinear": lambda: N.ResizeBilinear(8, 8),
    "RnnCell": lambda: N.RnnCell(6, 5),
    "RoiAlign": lambda: N.RoiAlign(3, 3),
    "RoiPooling": lambda: N.RoiPooling(3, 3),
    "SReLU": lambda: N.SReLU((6,)),
    "Scale": lambda: N.Scale((1, 6)),
    "Select": lambda: N.Select(1, 0),
    "SelectTable": lambda: N.SelectTable(1),
    "SparseLinear": lambda: N.SparseLinear(6, 4),
    "SpatialAveragePooling": lambda: N.SpatialAveragePooling(2, 2),
    "SpatialBatchNormalization": lambda: N.SpatialBatchNormalization(3),
    "SpatialConvolution": lambda: N.SpatialConvolution(3, 4, 3, 3),
    "SpatialConvolutionMap": lambda: N.SpatialConvolutionMap(
        np.array([[0, 0], [1, 1], [2, 2]], np.int32), 3, 3),
    "SpatialDilatedConvolution": lambda: N.SpatialDilatedConvolution(
        3, 4, 3, 3, dilation_w=2, dilation_h=2),
    "SpatialFullConvolution": lambda: N.SpatialFullConvolution(3, 4, 3, 3),
    "SpatialMaxPooling": lambda: N.SpatialMaxPooling(2, 2),
    "SpatialSeparableConvolution": lambda: N.SpatialSeparableConvolution(
        3, 6, 2, 3, 3),
    "SpatialShareConvolution": lambda: N.SpatialShareConvolution(3, 4, 3, 3),
    "SpatialZeroPadding": lambda: N.SpatialZeroPadding(1, 1, 1, 1),
    "SplitTable": lambda: N.SplitTable(1),
    "StaticGraph": lambda: _graph(N.StaticGraph),
    "TemporalConvolution": lambda: N.TemporalConvolution(4, 6, 3),
    "TemporalMaxPooling": lambda: N.TemporalMaxPooling(2),
    "TimeDistributed": lambda: N.TimeDistributed(N.Linear(4, 3)),
    "Transformer": lambda: N.Transformer(32, hidden_size=16, num_heads=2,
                                         filter_size=32,
                                         num_hidden_layers=1),
    "TransformerBlock": lambda: N.TransformerBlock(8, 2, 16),
    "Transpose": lambda: N.Transpose([(1, 2)]),
    "TreeLSTM": lambda: N.TreeLSTM(6, 5),
    "Unsqueeze": lambda: N.Unsqueeze(1),
    "UpSampling1D": lambda: N.UpSampling1D(2),
    "VolumetricAveragePooling": lambda: N.VolumetricAveragePooling(2, 2, 2),
    "VolumetricBatchNormalization": lambda:
        N.VolumetricBatchNormalization(3),
    "VolumetricConvolution": lambda: N.VolumetricConvolution(3, 4, 2, 3, 3),
    "VolumetricFullConvolution": lambda:
        N.VolumetricFullConvolution(3, 4, 2, 3, 3),
    "VolumetricMaxPooling": lambda: N.VolumetricMaxPooling(2, 2, 2),
}


def _public_module_classes():
    out = []
    for n in dir(N):
        c = getattr(N, n)
        if inspect.isclass(c) and issubclass(c, Module) and n not in EXEMPT:
            out.append(n)
    return out


ALL_CLASSES = _public_module_classes()


def _instance(name):
    if name in SPECS:
        return SPECS[name]()
    return getattr(N, name)()


def _tree_equal(t1, t2, name):
    l1, s1 = jax.tree_util.tree_flatten(t1)
    l2, s2 = jax.tree_util.tree_flatten(t2)
    assert s1 == s2, f"{name}: tree structure changed\n{s1}\n{s2}"
    for a, b in zip(l1, l2):
        if hasattr(a, "dtype") or hasattr(b, "dtype"):
            a, b = np.asarray(a), np.asarray(b)
            assert a.dtype == b.dtype, f"{name}: dtype {a.dtype}->{b.dtype}"
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=1e-6, err_msg=name)
        else:
            assert a == b, f"{name}: leaf {a!r} != {b!r}"


@pytest.mark.parametrize("name", ALL_CLASSES)
def test_roundtrip(name, tmp_path):
    m = _instance(name)
    m.ensure_initialized()
    path = str(tmp_path / "m.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    assert type(m2) is type(m)
    _tree_equal(m.params, m2.params, name)
    _tree_equal(m.state, m2.state, name)


class _DtypeBag(Module):
    def _init_params(self, rng):
        import ml_dtypes
        import jax.numpy as jnp
        return {
            "i32": jnp.asarray(np.array([-5, 3, -(2**31)], np.int32)),
            "i8": jnp.asarray(np.array([-128, 0, 127], np.int8)),
            "u8": jnp.asarray(np.array([0, 255], np.uint8)),
            "b": jnp.asarray(np.array([True, False])),
            "f16": jnp.asarray(np.array([1.5, -2.25], np.float16)),
            "bf16": jnp.asarray(np.array([0.5, -3.0], ml_dtypes.bfloat16)),
            "scalar": jnp.float32(2.5),
        }

    def _apply(self, params, state, x, training, rng):
        return x


class _TupleTree(Module):
    def _init_params(self, rng):
        import jax.numpy as jnp
        return {"pair": (jnp.zeros((2,)), jnp.ones((3,)))}

    def _apply(self, params, state, x, training, rng):
        return x


def test_generic_tier_dtypes_roundtrip(tmp_path):
    """Negative int32, bool, f16, bf16, int8 tensor leaves all survive the
    generic tier with exact dtype and value (user-defined Module subclass,
    exercising the out-of-package pickled-config path too)."""
    m = _DtypeBag()
    m.ensure_initialized()
    path = str(tmp_path / "d.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    _tree_equal(m.params, m2.params, "_DtypeBag")
    assert np.asarray(m2.params["scalar"]).shape == ()


def test_tuple_in_param_tree_roundtrips_via_pickle(tmp_path):
    """A tuple inside the param tree keeps its treedef (pickle fallback)."""
    m = _TupleTree()
    m.ensure_initialized()
    path = str(tmp_path / "t.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    assert isinstance(m2.params["pair"], tuple)
    _tree_equal(m.params, m2.params, "_TupleTree")


def test_sweep_covers_every_public_class():
    """A class added to bigdl_tpu.nn without a spec (when it needs one)
    fails test_roundtrip via auto-instantiation — this guards the inverse:
    specs for classes that no longer exist."""
    missing = [n for n in SPECS if n not in ALL_CLASSES]
    assert not missing, f"specs for non-existent classes: {missing}"


def test_proto_random_composition_fuzz(tmp_path):
    """Fuzz the UNIVERSAL serializer: random Sequential/ConcatTable
    compositions mixing reference-tier and generic-tier layers must
    round-trip through bigdl.proto with identical eval outputs (seeded,
    deterministic)."""
    import jax
    rng = np.random.RandomState(77)

    def rand_model(seed):
        r = np.random.RandomState(seed)
        dim = int(r.randint(3, 9))
        layers = [N.Linear(6, dim)]
        cur = dim
        for _ in range(int(r.randint(2, 6))):
            c = r.randint(0, 10)
            if c == 0:
                nxt = int(r.randint(3, 9))
                layers.append(N.Linear(cur, nxt))
                cur = nxt
            elif c == 1:
                layers.append(N.ReLU())
            elif c == 2:
                layers.append(N.PReLU(cur))          # generic tier
            elif c == 3:
                layers.append(N.BatchNormalization(cur))
            elif c == 4:
                layers.append(N.LayerNormalization(cur))  # generic tier
            elif c == 5:
                layers.append(N.Highway(cur))        # generic tier
            elif c == 6:
                layers.append(N.ELU(0.5))            # generic tier
            elif c == 7:
                layers.append(N.Sequential(
                    N.ConcatTable().add(N.Identity()).add(
                        N.Linear(cur, cur)),
                    N.CAddTable()))                  # mixed container
            elif c == 8:
                layers.append(N.Dropout(0.2))
            else:
                layers.append(N.SoftPlus())          # generic tier
        return N.Sequential(*layers)

    for i in range(8):
        m = rand_model(int(rng.randint(0, 10_000)))
        m.ensure_initialized()
        m.evaluate()
        x = np.random.RandomState(i).randn(4, 6).astype(np.float32)
        ref = np.asarray(m.forward(x))
        path = str(tmp_path / f"pf{i}.bigdl")
        save_bigdl(m, path)
        m2 = load_bigdl(path)
        m2.evaluate()
        np.testing.assert_allclose(np.asarray(m2.forward(x)), ref,
                                   atol=1e-5, err_msg=f"model {i}: {m}")
