"""Per-layer bigdl.proto round-trip sweep — every public Module class in
``bigdl_tpu.nn`` must save→load through the protobuf serializer with its
type, config, and param/state trees intact.

Parity: the reference exercises exactly this with a reflection-default
serializer plus a per-layer SerializerSpec sweep
(``utils/serializer/ModuleSerializer.scala:199``); this is the bigdl_tpu
equivalent. Classes with required ctor args get an instance factory below;
zero-arg classes are auto-instantiated. The coverage assertion at the bottom
guarantees no newly-added class silently escapes the sweep.
"""
import inspect
import os

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as N
from bigdl_tpu.nn.module import Module
from bigdl_tpu.loaders.bigdl_proto import save_bigdl, load_bigdl

# abstract bases / machinery that users never instantiate directly
EXEMPT = {
    "Module", "Container", "Cell", "Layer", "TableOperation",
}


def _graph(cls):
    inp = N.Input()
    h = N.Linear(6, 5)(inp)
    out = N.ReLU()(h)
    return cls(inp, out)


# instance factories for classes whose ctor has required args
SPECS = {
    "Add": lambda: N.Add(6),
    "AddConstant": lambda: N.AddConstant(1.5),
    "Attention": lambda: N.Attention(8, 2),
    "BatchNormalization": lambda: N.BatchNormalization(6),
    "BifurcateSplitTable": lambda: N.BifurcateSplitTable(1),
    "Bilinear": lambda: N.Bilinear(4, 5, 3),
    "BinaryTreeLSTM": lambda: N.BinaryTreeLSTM(6, 5),
    "Bottle": lambda: N.Bottle(N.Linear(4, 3)),
    "CAdd": lambda: N.CAdd((6,)),
    "CMul": lambda: N.CMul((6,)),
    "Clamp": lambda: N.Clamp(-1.0, 1.0),
    "Concat": lambda: N.Concat(1, N.Linear(4, 3), N.Linear(4, 2)),
    "ConvLSTMPeephole": lambda: N.ConvLSTMPeephole(3, 4),
    "ConvLSTMPeephole3D": lambda: N.ConvLSTMPeephole3D(3, 4),
    "Cosine": lambda: N.Cosine(4, 3),
    "DynamicGraph": lambda: _graph(N.DynamicGraph),
    "Euclidean": lambda: N.Euclidean(4, 3),
    "ExpandSize": lambda: N.ExpandSize([2, 6]),
    "FeedForwardNetwork": lambda: N.FeedForwardNetwork(8, 16),
    "GRU": lambda: N.GRU(6, 5),
    "GaussianDropout": lambda: N.GaussianDropout(0.3),
    "GaussianNoise": lambda: N.GaussianNoise(0.2),
    "Graph": lambda: _graph(N.Graph),
    "Highway": lambda: N.Highway(6),
    "Index": lambda: N.Index(1),
    "InferReshape": lambda: N.InferReshape([-1, 3]),
    "JoinTable": lambda: N.JoinTable(1),
    "L1Penalty": lambda: N.L1Penalty(0.01),
    "LSTM": lambda: N.LSTM(6, 5),
    "LSTMPeephole": lambda: N.LSTMPeephole(6, 5),
    "LayerNormalization": lambda: N.LayerNormalization(8),
    "Linear": lambda: N.Linear(6, 4),
    "LocallyConnected1D": lambda: N.LocallyConnected1D(8, 4, 3, 2),
    "LocallyConnected2D": lambda: N.LocallyConnected2D(2, 8, 8, 3, 3, 3),
    "LookupTable": lambda: N.LookupTable(10, 6),
    "LookupTableSparse": lambda: N.LookupTableSparse(10, 6),
    "MapTable": lambda: N.MapTable(N.Linear(4, 3)),
    "Maxout": lambda: N.Maxout(6, 4, 2),
    "MixtureOfExperts": lambda: N.MixtureOfExperts(8, 2),
    "Model": lambda: _graph(N.Model),
    "MulConstant": lambda: N.MulConstant(2.0),
    "NormalizeScale": lambda: N.NormalizeScale(size=(1, 6, 1, 1)),
    "Recurrent": lambda: N.Recurrent(N.LSTM(6, 5)),
    "BiRecurrent": lambda: N.BiRecurrent().add(N.RnnCell(6, 5)),
    "MultiRNNCell": lambda: N.MultiRNNCell([N.RnnCell(6, 6),
                                            N.RnnCell(6, 6)]),
    "Narrow": lambda: N.Narrow(1, 0, 2),
    "NarrowTable": lambda: N.NarrowTable(1, 1),
    "Pack": lambda: N.Pack(1),
    "Padding": lambda: N.Padding(1, 2, 2),
    "Power": lambda: N.Power(2.0),
    "PriorBox": lambda: N.PriorBox([16.0], aspect_ratios=[2.0],
                                   img_size=64, step=8.0),
    "Proposal": lambda: N.Proposal(100, 10, [0.5, 1.0, 2.0], [8.0]),
    "RNN": lambda: N.RNN(6, 5),
    "RecurrentDecoder": lambda: N.RecurrentDecoder(4).add(N.RnnCell(5, 5)),
    "View": lambda: N.View(2, 3),
    "Replicate": lambda: N.Replicate(3),
    "Reshape": lambda: N.Reshape([2, 3]),
    "ResizeBilinear": lambda: N.ResizeBilinear(8, 8),
    "RnnCell": lambda: N.RnnCell(6, 5),
    "RoiAlign": lambda: N.RoiAlign(3, 3),
    "RoiPooling": lambda: N.RoiPooling(3, 3),
    "SReLU": lambda: N.SReLU((6,)),
    "Scale": lambda: N.Scale((1, 6)),
    "Select": lambda: N.Select(1, 0),
    "SelectTable": lambda: N.SelectTable(1),
    "SparseLinear": lambda: N.SparseLinear(6, 4),
    "SpatialAveragePooling": lambda: N.SpatialAveragePooling(2, 2),
    "SpatialBatchNormalization": lambda: N.SpatialBatchNormalization(3),
    "SpatialConvolution": lambda: N.SpatialConvolution(3, 4, 3, 3),
    "SpatialConvolutionMap": lambda: N.SpatialConvolutionMap(
        np.array([[0, 0], [1, 1], [2, 2]], np.int32), 3, 3),
    "SpatialDilatedConvolution": lambda: N.SpatialDilatedConvolution(
        3, 4, 3, 3, dilation_w=2, dilation_h=2),
    "SpatialFullConvolution": lambda: N.SpatialFullConvolution(3, 4, 3, 3),
    "SpatialMaxPooling": lambda: N.SpatialMaxPooling(2, 2),
    "SpatialSeparableConvolution": lambda: N.SpatialSeparableConvolution(
        3, 6, 2, 3, 3),
    "SpatialShareConvolution": lambda: N.SpatialShareConvolution(3, 4, 3, 3),
    "SpatialZeroPadding": lambda: N.SpatialZeroPadding(1, 1, 1, 1),
    "SplitTable": lambda: N.SplitTable(1),
    "StaticGraph": lambda: _graph(N.StaticGraph),
    "TemporalConvolution": lambda: N.TemporalConvolution(4, 6, 3),
    "TemporalMaxPooling": lambda: N.TemporalMaxPooling(2),
    "TimeDistributed": lambda: N.TimeDistributed(N.Linear(4, 3)),
    "Transformer": lambda: N.Transformer(32, hidden_size=16, num_heads=2,
                                         filter_size=32,
                                         num_hidden_layers=1),
    "TransformerBlock": lambda: N.TransformerBlock(8, 2, 16),
    "Transpose": lambda: N.Transpose([(1, 2)]),
    "TreeLSTM": lambda: N.TreeLSTM(6, 5),
    "Unsqueeze": lambda: N.Unsqueeze(1),
    "UpSampling1D": lambda: N.UpSampling1D(2),
    "VolumetricAveragePooling": lambda: N.VolumetricAveragePooling(2, 2, 2),
    "VolumetricBatchNormalization": lambda:
        N.VolumetricBatchNormalization(3),
    "VolumetricConvolution": lambda: N.VolumetricConvolution(3, 4, 2, 3, 3),
    "VolumetricFullConvolution": lambda:
        N.VolumetricFullConvolution(3, 4, 2, 3, 3),
    "VolumetricMaxPooling": lambda: N.VolumetricMaxPooling(2, 2, 2),
}


def _public_module_classes():
    out = []
    for n in dir(N):
        c = getattr(N, n)
        if inspect.isclass(c) and issubclass(c, Module) and n not in EXEMPT:
            out.append(n)
    return out


ALL_CLASSES = _public_module_classes()


def _instance(name):
    if name in SPECS:
        return SPECS[name]()
    return getattr(N, name)()


def _tree_equal(t1, t2, name):
    l1, s1 = jax.tree_util.tree_flatten(t1)
    l2, s2 = jax.tree_util.tree_flatten(t2)
    assert s1 == s2, f"{name}: tree structure changed\n{s1}\n{s2}"
    for a, b in zip(l1, l2):
        if hasattr(a, "dtype") or hasattr(b, "dtype"):
            a, b = np.asarray(a), np.asarray(b)
            assert a.dtype == b.dtype, f"{name}: dtype {a.dtype}->{b.dtype}"
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=1e-6, err_msg=name)
        else:
            assert a == b, f"{name}: leaf {a!r} != {b!r}"


@pytest.mark.parametrize("name", ALL_CLASSES)
def test_roundtrip(name, tmp_path):
    m = _instance(name)
    m.ensure_initialized()
    path = str(tmp_path / "m.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    assert type(m2) is type(m)
    _tree_equal(m.params, m2.params, name)
    _tree_equal(m.state, m2.state, name)


class _DtypeBag(Module):
    def _init_params(self, rng):
        import ml_dtypes
        import jax.numpy as jnp
        return {
            "i32": jnp.asarray(np.array([-5, 3, -(2**31)], np.int32)),
            "i8": jnp.asarray(np.array([-128, 0, 127], np.int8)),
            "u8": jnp.asarray(np.array([0, 255], np.uint8)),
            "b": jnp.asarray(np.array([True, False])),
            "f16": jnp.asarray(np.array([1.5, -2.25], np.float16)),
            "bf16": jnp.asarray(np.array([0.5, -3.0], ml_dtypes.bfloat16)),
            # plain-numpy f64 leaf: the generic tier must restore it as
            # exact float64 (_NDT_F64), not the reference DOUBLE→f32 path
            "f64": np.array([1e-300, 2.5, -7.125], np.float64),
            "scalar": jnp.float32(2.5),
        }

    def _apply(self, params, state, x, training, rng):
        return x


class _TupleTree(Module):
    def _init_params(self, rng):
        import jax.numpy as jnp
        return {"pair": (jnp.zeros((2,)), jnp.ones((3,)))}

    def _apply(self, params, state, x, training, rng):
        return x


def test_generic_tier_dtypes_roundtrip(tmp_path):
    """Negative int32, bool, f16, bf16, int8 tensor leaves all survive the
    generic tier with exact dtype and value (user-defined Module subclass,
    exercising the out-of-package pickled-config path too)."""
    m = _DtypeBag()
    m.ensure_initialized()
    path = str(tmp_path / "d.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    _tree_equal(m.params, m2.params, "_DtypeBag")
    assert np.asarray(m2.params["scalar"]).shape == ()


def test_tuple_in_param_tree_roundtrips_via_pickle(tmp_path):
    """A tuple inside the param tree keeps its treedef (pickle fallback)."""
    m = _TupleTree()
    m.ensure_initialized()
    path = str(tmp_path / "t.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    assert isinstance(m2.params["pair"], tuple)
    _tree_equal(m.params, m2.params, "_TupleTree")


def test_sweep_covers_every_public_class():
    """A class added to bigdl_tpu.nn without a spec (when it needs one)
    fails test_roundtrip via auto-instantiation — this guards the inverse:
    specs for classes that no longer exist."""
    missing = [n for n in SPECS if n not in ALL_CLASSES]
    assert not missing, f"specs for non-existent classes: {missing}"


def test_proto_random_composition_fuzz(tmp_path):
    """Fuzz the UNIVERSAL serializer: random Sequential/ConcatTable
    compositions mixing reference-tier and generic-tier layers must
    round-trip through bigdl.proto with identical eval outputs (seeded,
    deterministic)."""
    import jax
    rng = np.random.RandomState(77)

    def rand_model(seed):
        r = np.random.RandomState(seed)
        dim = int(r.randint(3, 9))
        layers = [N.Linear(6, dim)]
        cur = dim
        for _ in range(int(r.randint(2, 6))):
            c = r.randint(0, 10)
            if c == 0:
                nxt = int(r.randint(3, 9))
                layers.append(N.Linear(cur, nxt))
                cur = nxt
            elif c == 1:
                layers.append(N.ReLU())
            elif c == 2:
                layers.append(N.PReLU(cur))          # generic tier
            elif c == 3:
                layers.append(N.BatchNormalization(cur))
            elif c == 4:
                layers.append(N.LayerNormalization(cur))  # generic tier
            elif c == 5:
                layers.append(N.Highway(cur))        # generic tier
            elif c == 6:
                layers.append(N.ELU(0.5))            # generic tier
            elif c == 7:
                layers.append(N.Sequential(
                    N.ConcatTable().add(N.Identity()).add(
                        N.Linear(cur, cur)),
                    N.CAddTable()))                  # mixed container
            elif c == 8:
                layers.append(N.Dropout(0.2))
            else:
                layers.append(N.SoftPlus())          # generic tier
        return N.Sequential(*layers)

    # 4 compositions by default (~10s of tier-1 budget), the full 8
    # under the slow tier — the per-class sweep above already covers
    # every layer individually; the fuzz adds composition coverage
    n = 8 if os.environ.get("BIGDL_TPU_SLOW") == "1" else 4
    for i in range(n):
        m = rand_model(int(rng.randint(0, 10_000)))
        m.ensure_initialized()
        m.evaluate()
        x = np.random.RandomState(i).randn(4, 6).astype(np.float32)
        ref = np.asarray(m.forward(x))
        path = str(tmp_path / f"pf{i}.bigdl")
        save_bigdl(m, path)
        m2 = load_bigdl(path)
        m2.evaluate()
        np.testing.assert_allclose(np.asarray(m2.forward(x)), ref,
                                   atol=1e-5, err_msg=f"model {i}: {m}")


# ---------------------------------------------------------------------------
# pickle trust model (r5 — ADVICE r4 medium finding)
# ---------------------------------------------------------------------------


class _EvilReduce:
    """Pickles to a REDUCE that would invoke os.system on load."""

    def __init__(self, path):
        self.path = path

    def __reduce__(self):
        import os
        return (os.system, (f"touch {self.path}",))


def _crafted_generic_module(attrs):
    """Minimal generic-tier BigDLModule wire bytes with the given custom
    (bytes-payload) attrs — what an attacker-controlled .bigdl file is."""
    from bigdl_tpu.loaders import bigdl_proto as BP
    from bigdl_tpu.loaders.wire import field_bytes, field_string
    out = field_string(
        7, BP._NATIVE_PREFIX + "bigdl_tpu.nn.elementwise.Identity")
    for k, blob in attrs.items():
        entry = field_string(1, k) + field_bytes(2, BP._attr_custom(blob))
        out += field_bytes(8, entry)
    return out


@pytest.mark.parametrize("attr", ["cfg_pickle", "param_pickle",
                                  "state_pickle", "cfgp:frob"])
def test_load_refuses_os_system_gadget(attr, tmp_path):
    """A crafted .bigdl file whose pickled attr REDUCEs to os.system must
    raise, not execute (default restricted unpickler)."""
    import pickle as _p
    marker = tmp_path / "pwned"
    data = _crafted_generic_module({attr: _p.dumps(_EvilReduce(marker))})
    with pytest.raises(Exception, match="refusing to unpickle"):
        load_bigdl(data)
    assert not marker.exists(), "gadget executed!"


def test_allow_pickle_false_refuses_pickled_attrs(tmp_path):
    """allow_pickle=False refuses any pickled attr with a clear error, and
    'unsafe' still loads the (benign) file."""
    m = _TupleTree()
    m.ensure_initialized()
    path = str(tmp_path / "t.bigdl")
    save_bigdl(m, path)  # tuple treedef rides the pickle fallback
    with pytest.raises(ValueError, match="allow_pickle=False"):
        load_bigdl(path, allow_pickle=False)
    m2 = load_bigdl(path, allow_pickle="unsafe")
    _tree_equal(m.params, m2.params, "_TupleTree-unsafe")


def test_allow_pickle_false_loads_reference_tier(tmp_path):
    """Reference-compatible files never carry pickle — allow_pickle=False
    must load them unchanged (the reference ModuleLoader trust model)."""
    m = N.Sequential(N.Linear(6, 5), N.ReLU())
    m.ensure_initialized()
    path = str(tmp_path / "ref.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path, allow_pickle=False)
    x = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    m.evaluate(), m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)),
                               np.asarray(m.forward(x)), atol=1e-6)


def test_restricted_unpickler_allows_user_module_subclass(tmp_path):
    """Out-of-package Module subclasses (this test module) still load under
    the default restricted policy — the generic tier's documented scope."""
    m = _TupleTree()
    m.ensure_initialized()
    path = str(tmp_path / "user.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)  # default: restricted
    assert isinstance(m2, _TupleTree)
    _tree_equal(m.params, m2.params, "_TupleTree-restricted")


def _su(s):
    """Pickle SHORT_BINUNICODE opcode for a short string."""
    b = s.encode() if isinstance(s, str) else s
    return b"\x8c" + bytes([len(b)]) + b


def _sb(b):
    """Pickle SHORT_BINBYTES / BINBYTES opcode."""
    return (b"C" + bytes([len(b)]) if len(b) < 256
            else b"B" + len(b).to_bytes(4, "little")) + b


def _stack_global_pickle(module, name, arg_pickle):
    """Hand-built protocol-4 stream: STACK_GLOBAL(module, name) REDUCEd on
    one bytes arg — the dotted-name re-export bypass shape."""
    return (b"\x80\x04" + _su(module) + _su(name) + b"\x93"
            + _sb(arg_pickle) + b"\x85R.")


def test_load_refuses_stack_global_reexport_bypass(tmp_path):
    """Protocol-4 STACK_GLOBAL with a dotted name must not reach module
    attributes of whitelisted packages (e.g. the `pickle` module imported
    inside bigdl_tpu.loaders.bigdl_proto → pickle.loads → raw unpickle)."""
    import pickle as _p
    marker = tmp_path / "pwned2"
    inner = _p.dumps(_EvilReduce(marker))
    evil = _stack_global_pickle(
        "bigdl_tpu.loaders.bigdl_proto", "pickle.loads", inner)
    data = _crafted_generic_module({"cfg_pickle": evil})
    with pytest.raises(Exception, match="refusing to unpickle"):
        load_bigdl(data)
    assert not marker.exists(), "dotted-name bypass executed!"


def test_load_refuses_numpy_exec_helper(tmp_path):
    """numpy is not an open package: its exec-style helpers
    (numpy.testing._private.utils.runstring) must be refused."""
    code = _su("import os; os.system('false')")
    evil = (b"\x80\x04" + _su("numpy.testing._private.utils")
            + _su("runstring") + b"\x93" + code + b"}\x86R.")
    data = _crafted_generic_module({"cfg_pickle": evil})
    with pytest.raises(Exception, match="refusing to unpickle"):
        load_bigdl(data)


def test_load_refuses_numpy_memmap_file_write(tmp_path):
    """numpy.memmap is a file-write primitive — the numpy-types branch must
    admit only scalar/dtype types."""
    victim = tmp_path / "victim.bin"
    victim.write_bytes(b"AAAAAAAA")
    evil = (b"\x80\x04" + _su("numpy") + _su("memmap") + b"\x93"
            + _su(str(victim)) + b"\x85R.")
    data = _crafted_generic_module({"cfg_pickle": evil})
    with pytest.raises(Exception, match="refusing to unpickle"):
        load_bigdl(data)
    assert victim.read_bytes() == b"AAAAAAAA"


def test_load_refuses_module_object_resolution():
    """Resolving a MODULE object through an open package would let BUILD
    rewrite package globals — must be refused (classes/callables only)."""
    evil = b"\x80\x04" + _su("bigdl_tpu") + _su("loaders") + b"\x93."
    data = _crafted_generic_module({"cfg_pickle": evil})
    with pytest.raises(Exception, match="refusing to unpickle"):
        load_bigdl(data)
    import bigdl_tpu.loaders
    assert bigdl_tpu.loaders.bigdl_proto is not None


def test_load_refuses_loader_reentry_laundering(tmp_path):
    """load_bigdl itself must not be REDUCE-invocable: a crafted file could
    otherwise re-enter load_bigdl(<inner bytes>, 'unsafe') and run raw
    pickle. Functions are refused wholesale from open packages."""
    import pickle as _p
    marker = tmp_path / "pwned3"
    inner = _crafted_generic_module({"cfg_pickle":
                                     _p.dumps(_EvilReduce(marker))})

    evil = (b"\x80\x04" + _su("bigdl_tpu.loaders.bigdl_proto")
            + _su("load_bigdl") + b"\x93" + _sb(inner) + _su("unsafe")
            + b"\x86R.")
    data = _crafted_generic_module({"cfg_pickle": evil})
    with pytest.raises(Exception, match="refusing to unpickle"):
        load_bigdl(data)
    assert not marker.exists(), "loader re-entry executed!"


def test_allow_pickle_rejects_ambiguous_values():
    """Falsy-but-not-False values (0, None) must not silently mean
    'restricted' — only True/False/'unsafe' are accepted."""
    for bad in (0, None, 1, "restricted"):
        with pytest.raises(ValueError, match="allow_pickle must be"):
            load_bigdl(b"", allow_pickle=bad)


def test_ufunc_config_roundtrips_under_restricted(tmp_path):
    """A config holding a numpy ufunc (TableOperation(np.add) style) must
    load under the default restricted policy — ufuncs are data-only."""
    m = N.TableOperation(np.add) if hasattr(N, "TableOperation") else None
    if m is None:
        pytest.skip("no TableOperation")
    path = str(tmp_path / "uf.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    a = np.ones((2, 3), np.float32)
    from bigdl_tpu.utils import Table
    np.testing.assert_allclose(np.asarray(m2.forward(Table(a, a))),
                               2 * a, atol=0)


class _I64Bag(Module):
    def _init_params(self, rng):
        return {"steps": np.array([2**40 + 3, -7], np.int64),
                "w64": np.array([1e-300, 2.5], np.float64)}

    def _apply(self, params, state, x, training, rng):
        return x


def test_i64_f64_leaves_roundtrip_with_zero_grads(tmp_path):
    """int64 leaves must not truncate to int32 (2**40+3 -> 3), and the
    kept-as-numpy leaves must get ZERO grad_params, not alias the param
    values."""
    m = _I64Bag()
    m.ensure_initialized()
    path = str(tmp_path / "i.bigdl")
    save_bigdl(m, path)
    m2 = load_bigdl(path)
    s = np.asarray(m2.params["steps"])
    assert s.dtype == np.int64 and s[0] == 2**40 + 3, s
    g = m2.grad_params["steps"]
    assert g is not m2.params["steps"]
    assert np.asarray(g).sum() == 0
    assert np.asarray(m2.grad_params["w64"]).sum() == 0
