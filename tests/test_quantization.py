"""Int8 quantization tests (modeled on reference
nn/quantized specs + quantization accuracy checks)."""
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.quantization import quantize, quantize_weight
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration, Top1Accuracy


def test_quantize_weight_roundtrip():
    w = np.random.randn(8, 16).astype(np.float32)
    q, s = quantize_weight(w, axis=0)
    deq = np.asarray(q, np.float32) * np.asarray(s)
    assert np.abs(deq - w).max() < np.abs(w).max() / 100


def test_quantized_linear_close_to_float():
    m = nn.Linear(32, 16)
    m.ensure_initialized()
    x = np.random.randn(8, 32).astype(np.float32)
    ref = np.asarray(m.forward(x))
    qm = quantize(m)
    out = np.asarray(qm.forward(x))
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantized_conv_close_to_float():
    m = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)
    m.ensure_initialized()
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(m.forward(x))
    qm = quantize(m)
    out = np.asarray(qm.forward(x))
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantized_lenet_accuracy():
    """Parity with the reference's int8 claim: accuracy drop ≤ 1%."""
    imgs, labels = mnist.load(n_synthetic=256)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    model = LeNet5(10)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05), max_iteration(30), 64)
    opt.optimize()
    acc_f = model.evaluate_dataset(ds, [Top1Accuracy()], 64)[0].result()[0]
    qmodel = quantize(model)
    acc_q = qmodel.evaluate_dataset(ds, [Top1Accuracy()], 64)[0].result()[0]
    assert acc_f - acc_q <= 0.01 + 1e-9, (acc_f, acc_q)


def test_quantized_graph_model():
    inp = nn.Input()
    h = nn.SpatialConvolution(1, 4, 3, 3)(inp)
    r = nn.ReLU()(h)
    g = nn.Graph(inp, r)
    g.ensure_initialized()
    x = np.random.randn(1, 1, 6, 6).astype(np.float32)
    ref = np.asarray(g.forward(x))
    qg = quantize(g)
    out = np.asarray(qg.forward(x))
    assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9) < 0.05


# ---- calibration (static int8) ---------------------------------------------

def _small_convnet():
    from bigdl_tpu import nn
    return nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3), nn.ReLU(),
        nn.Reshape([4 * 6 * 6], batch_mode=True),
        nn.Linear(4 * 6 * 6, 10))


def test_observers():
    from bigdl_tpu.quantization import (MinMaxObserver, MovingAverageObserver,
                                        PercentileObserver)
    batches = [np.full((4,), v, np.float32) for v in (1.0, 3.0, 2.0)]
    mm = MinMaxObserver()
    for b in batches:
        mm.update(b)
    assert abs(mm.absmax - 3.0) < 1e-6
    ma = MovingAverageObserver(momentum=0.5)
    for b in batches:
        ma.update(b)
    # 1 -> .5*1+.5*3=2 -> .5*2+.5*2=2
    assert abs(ma.absmax - 2.0) < 1e-6
    pc = PercentileObserver(percentile=50)
    x = np.ones(100, np.float32); x[0] = 1000.0  # outlier clipped
    pc.update(x)
    assert pc.absmax < 10


def test_calibrate_records_per_layer_scales():
    from bigdl_tpu.quantization import calibrate, quantizable_paths
    model = _small_convnet()
    batches = [np.random.randn(2, 1, 8, 8).astype(np.float32)
               for _ in range(3)]
    scales = calibrate(model, batches)
    paths = [p for p, _ in quantizable_paths(model)]
    assert set(scales) == set(paths) and len(paths) == 2
    assert all(s > 0 for s in scales.values())
    # hooks removed: forward still works and _apply restored to class impl
    for _, m in quantizable_paths(model):
        assert "_apply" not in m.__dict__


def test_calibrated_quantize_close_to_float():
    from bigdl_tpu.quantization import calibrate, quantize
    model = _small_convnet().evaluate()
    batches = [np.random.randn(4, 1, 8, 8).astype(np.float32)
               for _ in range(4)]
    scales = calibrate(model, batches)
    qmodel = quantize(model, calibration=scales)
    x = batches[0]
    ref = np.asarray(model.forward(x))
    out = np.asarray(qmodel.forward(x))
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert err < 0.1, err
    # static scale is baked into params (no dynamic max at inference)
    import jax
    flat = jax.tree_util.tree_leaves_with_path(qmodel.params)
    assert any("act_scale" in "/".join(str(k) for k in path)
               for path, _ in flat)


def test_fold_batchnorm_matches_unfused():
    from bigdl_tpu import nn
    from bigdl_tpu.quantization import fold_batchnorm
    model = nn.Sequential(
        nn.SpatialConvolution(2, 4, 3, 3),
        nn.SpatialBatchNormalization(4),
        nn.ReLU())
    # give BN non-trivial running stats by training a few batches
    model.training()
    for _ in range(3):
        model.forward(np.random.randn(4, 2, 8, 8).astype(np.float32))
    model.evaluate()
    x = np.random.randn(2, 2, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    folded = fold_batchnorm(model)
    out = np.asarray(folded.forward(x))
    assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()
    # BN slot is now an identity
    assert type(folded.modules[1]).__name__ == "Identity"


def test_fold_then_calibrated_quantize():
    from bigdl_tpu import nn
    from bigdl_tpu.quantization import calibrate, fold_batchnorm, quantize
    model = nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3),
        nn.SpatialBatchNormalization(4),
        nn.ReLU(),
        nn.Reshape([4 * 6 * 6], batch_mode=True),
        nn.Linear(4 * 6 * 6, 5))
    model.training()
    for _ in range(3):
        model.forward(np.random.randn(4, 1, 8, 8).astype(np.float32))
    model.evaluate()
    x = np.random.randn(4, 1, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    fold_batchnorm(model)
    q = quantize(model, calibration=calibrate(model, [x]))
    out = np.asarray(q.forward(x))
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert err < 0.15, err


def test_quantize_nested_containers():
    """Regression: quantize() must propagate fresh params through containers
    that are rewritten in place (Concat branches inside Sequential) — r1 lost
    every quantized-param subtree below depth 1."""
    import numpy as np
    from bigdl_tpu import nn
    from bigdl_tpu.quantization import quantize
    from bigdl_tpu.quantization.quantize import (QuantizedLinear,
                                                 QuantizedSpatialConvolution)
    from bigdl_tpu.nn.module import Container
    branch1 = nn.Sequential(nn.SpatialConvolution(2, 3, 1, 1), nn.ReLU())
    branch2 = nn.Sequential(nn.SpatialConvolution(2, 5, 3, 3, 1, 1, 1, 1),
                            nn.ReLU())
    model = nn.Sequential(
        nn.SpatialConvolution(1, 2, 3, 3, 1, 1, 1, 1),
        nn.Concat(2, branch1, branch2),
        nn.Reshape([8 * 8 * 8], batch_mode=True),
        nn.Linear(8 * 8 * 8, 4))
    model.ensure_initialized()
    model.evaluate()
    x = np.random.randn(2, 1, 8, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    q = quantize(model)

    def walk(mod, params):
        if isinstance(mod, (QuantizedLinear, QuantizedSpatialConvolution)):
            assert "qweight" in params, \
                f"{type(mod).__name__} kept float params {list(params)}"
        if isinstance(mod, Container):
            for i, ch in enumerate(mod.modules):
                walk(ch, params[str(i)])
    walk(q, q.params)
    out = np.asarray(q.forward(x))
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert err < 0.15, err


def test_quantized_dilated_conv():
    """SpatialDilatedConvolution quantizes (reference
    nn/quantized/SpatialDilatedConvolution.scala) with bounded error."""
    from bigdl_tpu import nn
    from bigdl_tpu.quantization import quantize
    m = nn.Sequential(
        nn.SpatialDilatedConvolution(3, 6, 3, 3, 1, 1, 2, 2,
                                     dilation_w=2, dilation_h=2),
        nn.ReLU())
    m.ensure_initialized()
    m.evaluate()
    x = np.random.RandomState(0).randn(2, 3, 12, 12).astype(np.float32)
    ref = np.asarray(m.forward(x))
    q = quantize(m)
    out = np.asarray(q.forward(x))
    assert out.shape == ref.shape
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.1, rel


def test_quantized_separable_conv():
    """SpatialSeparableConvolution quantizes both stages."""
    from bigdl_tpu import nn
    from bigdl_tpu.quantization import quantize
    from bigdl_tpu.quantization.quantize import \
        QuantizedSpatialSeparableConvolution
    m = nn.Sequential(
        nn.SpatialSeparableConvolution(4, 8, 2, 3, 3, 1, 1, 1, 1),
        nn.ReLU())
    m.ensure_initialized()
    m.evaluate()
    x = np.random.RandomState(1).randn(2, 4, 10, 10).astype(np.float32)
    ref = np.asarray(m.forward(x))
    q = quantize(m)
    assert isinstance(q.modules[0], QuantizedSpatialSeparableConvolution)
    out = np.asarray(q.forward(x))
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.15, rel


def test_sparse_linear_not_quantized():
    """SparseLinear keeps its float COO path through quantize()."""
    from bigdl_tpu import nn
    from bigdl_tpu.quantization import quantize
    m = nn.Sequential(nn.SparseLinear(6, 4), nn.ReLU())
    m.ensure_initialized()
    q = quantize(m)
    assert isinstance(q.modules[0], nn.SparseLinear)
    sp = nn.SparseTensor.from_dense(
        np.eye(6, dtype=np.float32)[:3])
    assert np.asarray(q.forward(sp)).shape == (3, 4)


@pytest.mark.slow
def test_quantized_resnet50_accuracy_drop():
    """Quantized ResNet-50: int8 predictions agree with float top-1 on
    random-init weights (graph-rewrite over the full bottleneck DAG)."""
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.quantization import quantize
    model = ResNet(class_num=10, depth=50)
    model.ensure_initialized()
    model.evaluate()
    x = np.random.RandomState(2).randn(2, 3, 64, 64).astype(np.float32)
    ref = np.asarray(model.forward(x))
    q = quantize(model)
    out = np.asarray(q.forward(x))
    assert out.shape == ref.shape
    # same argmax on a clear majority of rows + bounded logit error
    agree = (out.argmax(-1) == ref.argmax(-1)).mean()
    assert agree >= 0.5, agree
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.25, rel


def test_weight_only_int8_lm_generate():
    """quantize_lm_params drops into the UNCHANGED forward/generate code:
    logits stay close to float, greedy generation runs jitted, and the
    quantized weight bytes are ~4x smaller than the f32 originals."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.quantization import quantize_lm_params, lm_quantized_bytes

    model = TransformerLM(vocab_size=43, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    qparams = quantize_lm_params(params)

    ids = jnp.asarray(np.random.RandomState(0).randint(1, 43, (2, 10)),
                      jnp.int32)
    ref, _ = model.apply(params, {}, ids, training=False)
    out, _ = model.apply(qparams, {}, ids, training=False)
    rel = float(jnp.abs(out - ref).max() /
                (jnp.abs(ref).max() + 1e-9))
    assert rel < 0.08, rel  # int8 weight rounding error bound

    gen = jax.jit(lambda p, x: model.generate(p, x, max_new_tokens=4))
    toks = gen(qparams, ids[:, :4])
    assert toks.shape == (2, 8)
    assert ((np.asarray(toks) >= 0) & (np.asarray(toks) < 43)).all()

    # the quantized payload is ~4x smaller than the SAME mats in f32
    b = lm_quantized_bytes(qparams)
    orig = sum(v.nbytes
               for blk in range(2)
               for k, v in params[f"block{blk}"]["attn"].items()) \
        + sum(params[f"block{blk}"]["ffn"][k].nbytes
              for blk in range(2) for k in ("w1", "w2"))
    assert b["quantized"] < 0.3 * orig, (b, orig)


def test_quantize_weight_int4_roundtrip():
    """Group-wise int4: dequant error bounded by half a quantization step
    per element, and non-divisible K fails loudly."""
    import jax.numpy as jnp
    from bigdl_tpu.quantization import quantize_weight_int4

    w = np.random.RandomState(3).randn(256, 24).astype(np.float32)
    qw = quantize_weight_int4(w, group=128)
    assert str(qw.q.dtype) == "int4" and qw.s.shape == (2, 24)
    step = np.repeat(np.asarray(qw.s), 128, axis=0)   # (256, 24)
    err = np.abs(np.asarray(qw.dequantize()) - w)
    assert (err <= 0.5 * step + 1e-6).all(), err.max()

    x = np.random.RandomState(4).randn(5, 256).astype(np.float32)
    got = np.asarray(jnp.asarray(x) @ qw)
    ref = x @ np.asarray(qw.dequantize())
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    with pytest.raises(ValueError):
        quantize_weight_int4(w[:100], group=128)


def test_weight_only_int4_lm_generate():
    """bits=4 drops into the same unchanged forward/generate code as
    int8, with the coarser (but group-wise-scaled) error bound, and the
    packed payload beats the int8 one."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.quantization import quantize_lm_params, lm_quantized_bytes

    model = TransformerLM(vocab_size=43, hidden_size=32, num_heads=2,
                          filter_size=64, num_layers=2, max_len=32)
    params, _ = model.init(jax.random.PRNGKey(0))
    q4 = quantize_lm_params(params, bits=4, group=16)

    ids = jnp.asarray(np.random.RandomState(0).randint(1, 43, (2, 10)),
                      jnp.int32)
    ref, _ = model.apply(params, {}, ids, training=False)
    out, _ = model.apply(q4, {}, ids, training=False)
    rel = float(jnp.abs(out - ref).max() / (jnp.abs(ref).max() + 1e-9))
    assert rel < 0.2, rel  # int4 rounding error bound (group-wise)

    gen = jax.jit(lambda p, x: model.generate(p, x, max_new_tokens=4))
    toks = gen(q4, ids[:, :4])
    assert toks.shape == (2, 8)
    assert ((np.asarray(toks) >= 0) & (np.asarray(toks) < 43)).all()

    b4 = lm_quantized_bytes(q4)
    b8 = lm_quantized_bytes(quantize_lm_params(params))
    assert b4["quantized"] < 0.8 * b8["quantized"], (b4, b8)
