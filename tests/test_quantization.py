"""Int8 quantization tests (modeled on reference
nn/quantized specs + quantization accuracy checks)."""
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.models import LeNet5
from bigdl_tpu.quantization import quantize, quantize_weight
from bigdl_tpu.dataset import DataSet, mnist
from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration, Top1Accuracy


def test_quantize_weight_roundtrip():
    w = np.random.randn(8, 16).astype(np.float32)
    q, s = quantize_weight(w, axis=0)
    deq = np.asarray(q, np.float32) * np.asarray(s)
    assert np.abs(deq - w).max() < np.abs(w).max() / 100


def test_quantized_linear_close_to_float():
    m = nn.Linear(32, 16)
    m.ensure_initialized()
    x = np.random.randn(8, 32).astype(np.float32)
    ref = np.asarray(m.forward(x))
    qm = quantize(m)
    out = np.asarray(qm.forward(x))
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantized_conv_close_to_float():
    m = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)
    m.ensure_initialized()
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(m.forward(x))
    qm = quantize(m)
    out = np.asarray(qm.forward(x))
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantized_lenet_accuracy():
    """Parity with the reference's int8 claim: accuracy drop ≤ 1%."""
    imgs, labels = mnist.load(n_synthetic=256)
    ds = DataSet.array(mnist.to_samples(imgs, labels))
    model = LeNet5(10)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         SGD(learningrate=0.05), max_iteration(30), 64)
    opt.optimize()
    acc_f = model.evaluate_dataset(ds, [Top1Accuracy()], 64)[0].result()[0]
    qmodel = quantize(model)
    acc_q = qmodel.evaluate_dataset(ds, [Top1Accuracy()], 64)[0].result()[0]
    assert acc_f - acc_q <= 0.01 + 1e-9, (acc_f, acc_q)


def test_quantized_graph_model():
    inp = nn.Input()
    h = nn.SpatialConvolution(1, 4, 3, 3)(inp)
    r = nn.ReLU()(h)
    g = nn.Graph(inp, r)
    g.ensure_initialized()
    x = np.random.randn(1, 1, 6, 6).astype(np.float32)
    ref = np.asarray(g.forward(x))
    qg = quantize(g)
    out = np.asarray(qg.forward(x))
    assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9) < 0.05
